"""Model registry: hive catalog + resident component bundles.

Two reference behaviors merge here:

1. the server-driven model catalog (``GET /api/models`` cached to
   ``models.json``, swarm/initialize.py:97-116) whose per-model
   ``parameters`` drive dispatch (swarm/job_arguments.py:104-151), and
2. model loading — which the reference does per job from the HF cache
   (swarm/diffusion/diffusion_func.py:41-46). On TPU weights stay resident
   (core/compile_cache.py): loading + conversion + XLA compilation amortize
   across jobs, which is the single biggest architectural departure
   (SURVEY.md §7 "hard parts" #3).

Checkpoints live under ``<settings root>/models/<name with / -> __>`` in
HF-diffusers directory layout; ``allow_random=True`` (tests, benches)
fabricates random weights of the right family instead.
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any

from chiaswarm_tpu.models.configs import FAMILIES, ModelFamily, get_family
from chiaswarm_tpu.node.settings import load_file, settings_root
from chiaswarm_tpu.pipelines.components import Components
from chiaswarm_tpu.pipelines.diffusion import DiffusionPipeline
from chiaswarm_tpu.serving.residency import ResidencyManager, default_manager

log = logging.getLogger("chiaswarm.registry")


def model_dir(model_name: str) -> Path:
    return settings_root() / "models" / model_name.replace("/", "__")


def _mesh_cache_key(mesh) -> tuple | None:
    """Cache-key identity for a slot mesh (None -> default placement)."""
    if mesh is None:
        return None
    return (tuple(mesh.axis_names), mesh.devices.shape,
            tuple(d.id for d in mesh.devices.flatten()))


def _place_params(params, mesh, model_name: str):
    """Put a param tree where its slot executes: tensor-parallel shardings
    for >1-chip meshes, plain placement on the slot's chip otherwise."""
    if mesh is None:
        return params
    import jax

    if mesh.devices.size > 1:
        from chiaswarm_tpu.parallel import shard_params

        log.info("sharding %s params over mesh %s", model_name,
                 dict(zip(mesh.axis_names, mesh.devices.shape)))
        return shard_params(params, mesh)
    device = mesh.devices.flatten()[0]
    log.info("placing %s params on %s", model_name, device)
    return jax.device_put(params, device)


class ModelRegistry:
    def __init__(self, catalog: list[dict] | None = None,
                 allow_random: bool = False,
                 attn_impl: str = "auto",
                 residency: ResidencyManager | None = None) -> None:
        if catalog is None:
            catalog = load_file("models.json") or []
        self._catalog = {m.get("name", m.get("model_name", "")): m
                         for m in catalog}
        self.allow_random = allow_random
        self.attn_impl = attn_impl
        self._quarantined: dict[str, str] = {}
        # the HBM ledger every pipeline load routes through (ISSUE 8):
        # measured footprints, priority eviction with donation, prefetch,
        # and the degradation rungs. Process-global by default (like the
        # compile cache); tests pass private managers with tiny budgets.
        self.residency = (residency if residency is not None
                          else default_manager())

    # ---- quarantine (circuit breaker, node/resilience.py) ----

    def quarantine(self, model_name: str, reason: str = "") -> None:
        """Refuse to serve ``model_name`` until :meth:`unquarantine` — the
        worker's per-model circuit breaker trips this after K consecutive
        permanent failures so one broken checkpoint cannot poison the
        whole node (it would otherwise burn a load + compile per job)."""
        log.error("quarantining model %s%s", model_name,
                  f": {reason}" if reason else "")
        self._quarantined[model_name] = reason or "circuit breaker open"
        self.residency.note_quarantined(model_name)

    def unquarantine(self, model_name: str) -> None:
        if self._quarantined.pop(model_name, None) is not None:
            log.warning("model %s released from quarantine", model_name)
        self.residency.note_unquarantined(model_name)

    def is_quarantined(self, model_name: str) -> bool:
        return model_name in self._quarantined

    def quarantined_models(self) -> list[str]:
        return sorted(self._quarantined)

    def _check_quarantine(self, model_name: str) -> None:
        reason = self._quarantined.get(model_name)
        if reason is not None:
            raise ValueError(
                f"model {model_name!r} is quarantined on this node "
                f"({reason})"
            )

    # ---- catalog (server-driven config, job_arguments.py:104-151) ----

    def entry(self, model_name: str) -> dict[str, Any]:
        return self._catalog.get(model_name, {})

    def parameters(self, model_name: str) -> dict[str, Any]:
        return dict(self.entry(model_name).get("parameters", {}))

    def known_models(self) -> list[str]:
        return list(self._catalog)

    # ---- residency (serving/residency.py is the authority) ----

    def model_states(self) -> dict[str, str]:
        """ONE authoritative per-model state enum (ISSUE 8 satellite):
        quarantine (previously a side dict) and residency (previously
        invisible) merged — ``cold`` / ``loading`` / ``resident`` /
        ``degraded`` / ``evicted`` / ``unavailable`` / ``quarantined``.
        Served at ``/healthz`` (node/worker.py)."""
        states = {name: "cold" for name in self._catalog if name}
        states.update(self.residency.model_states())
        for model in self._quarantined:
            states[model] = "quarantined"
        return states

    def lane_resident_ok(self, model_name: str) -> bool:
        """May this model pin a resident stepper lane? A model degraded
        to load-per-job must run solo (load -> run -> release) — a lane
        would hold its over-budget params live between jobs, defeating
        the rung (node/executor.py checks this BEFORE the lane submit
        path pays a transient load)."""
        return not self.residency.would_degrade(str(model_name))

    def _priority_for(self, model_name: str) -> int:
        """Catalog-driven eviction priority (higher = evicted later);
        the hive can pin its headline families hot via a
        ``residency_priority`` entry/parameter field."""
        entry = self.entry(model_name)
        raw = entry.get("residency_priority",
                        (entry.get("parameters") or {}).get(
                            "residency_priority", 0))
        try:
            return int(raw)
        except (TypeError, ValueError):
            return 0

    def _estimate_bytes(self, model_name: str) -> int | None:
        """Pre-load reservation fallback for a model never measured:
        the family estimate at the serving weight density (1 byte/param
        under CHIASWARM_WEIGHTS=int8, else bf16's 2). Replaced by the
        measured footprint after the first load."""
        try:
            from chiaswarm_tpu.convert.quantize import bytes_per_param
            from chiaswarm_tpu.pipelines.components import (
                estimate_family_bytes,
            )

            return estimate_family_bytes(self.family_for(model_name).name,
                                         bytes_per_param())
        except Exception:  # unknown family shapes: load-then-measure
            return None

    def family_for(self, model_name: str) -> ModelFamily:
        fam = self.entry(model_name).get("family")
        if fam and fam in FAMILIES:
            return FAMILIES[fam]
        return get_family(model_name)

    def _load_components(self, model_name: str) -> Components:
        ckpt = model_dir(model_name)
        if ckpt.exists():
            log.info("loading checkpoint %s from %s", model_name, ckpt)
            return Components.from_checkpoint(
                ckpt, model_name, self.family_for(model_name)
            )
        if self.allow_random:
            log.warning("no checkpoint for %s; using random weights",
                        model_name)
            return Components.random(self.family_for(model_name),
                                     model_name=model_name)
        raise ValueError(
            f"model {model_name!r} is not available on this node "
            f"(no checkpoint at {ckpt}); run `swarm-tpu init` to fetch it"
        )

    def pipeline(self, model_name: str,
                 textual_inversion: str | None = None,
                 lora: str | None = None,
                 lora_scale: float = 1.0,
                 mesh=None):
        """Resident pipeline (components + params + compiled executables),
        one measured entry in the residency ledger (serving/residency.py):
        evicting it drops the manager's strong reference to the param
        tree, and a model whose measured footprint exceeds the budget
        degrades to load-per-job instead. The pipeline class is
        selected by the family's ``kind`` ("sd" -> DiffusionPipeline,
        "upscaler" -> LatentUpscalePipeline). A textual inversion keys a
        SEPARATE entry: the concept rows merge into that entry's private
        embedding table (convert/textual_inversion.py), never the base's.
        A LoRA adapter likewise keys its own entry under
        ``(lora, lora_scale)``: the low-rank deltas merge into that
        entry's private UNet kernels once at load time
        (convert/lora.py; the runtime side-path + scale kwarg of
        swarm/diffusion/diffusion_func.py:58-68, done ahead of time so the
        jitted program and flash attention are unchanged).

        ``mesh`` (a MeshSlot's mesh) places the params: >1 chip shards
        them — Megatron-style tensor parallel on the ``model`` axis, data
        parallel batches on ``data`` (parallel/sharding.py; the pipeline
        seeds batch sharding by placing its token inputs on the ``data``
        axis) — and a single-chip slot mesh pins them to THAT chip so
        per-device slots do not all serialize on the default device.
        """
        self._check_quarantine(model_name)
        mesh_key = _mesh_cache_key(mesh)
        if mesh_key is None:
            mesh = None

        def build():
            components = self._load_components(model_name)
            if textual_inversion is not None:
                from chiaswarm_tpu.convert.textual_inversion import (
                    apply_textual_inversion,
                    load_embeddings,
                )

                ti_dir = model_dir(textual_inversion)
                if not ti_dir.exists():
                    raise ValueError(
                        f"textual inversion {textual_inversion!r} is not "
                        f"available on this node (no file at {ti_dir})"
                    )
                apply_textual_inversion(components, load_embeddings(ti_dir))
            if lora is not None:
                from chiaswarm_tpu.convert.lora import load_lora, merge_lora

                lora_dir = model_dir(lora)
                if not lora_dir.exists():
                    raise ValueError(
                        f"LoRA {lora!r} is not available on this node "
                        f"(no file at {lora_dir})"
                    )
                n_levels = len(components.family.unet.block_out_channels)
                components.params["unet"], n_merged = merge_lora(
                    components.params["unet"], load_lora(lora_dir),
                    scale=float(lora_scale), n_levels=n_levels)
                log.info("merged LoRA %s into %s (%d projections, "
                         "scale %.3g)", lora, model_name, n_merged,
                         lora_scale)
            # int8 weight residency (convert/quantize.py, gated by
            # CHIASWARM_WEIGHTS=int8 + the forward-parity tests):
            # quantize AFTER the adapter merges (fp math) and BEFORE
            # placement; multi-chip placements decline (sharding specs
            # are fp-tree-shaped)
            from chiaswarm_tpu.convert.quantize import (
                maybe_quantize_params,
            )

            components.params = maybe_quantize_params(
                components.params, family=components.family, mesh=mesh)
            # place AFTER the embedding-table/LoRA merges so the final
            # tree gets uniform placement
            components.params = _place_params(components.params, mesh,
                                              model_name)
            if components.family.kind == "upscaler":
                from chiaswarm_tpu.pipelines.upscale import (
                    LatentUpscalePipeline,
                )

                return LatentUpscalePipeline(components,
                                             attn_impl=self.attn_impl)
            if components.family.kind == "upscaler4":
                from chiaswarm_tpu.pipelines.upscale import (
                    Upscale4xPipeline,
                )

                return Upscale4xPipeline(components,
                                         attn_impl=self.attn_impl)
            return DiffusionPipeline(components, attn_impl=self.attn_impl)

        lora_key = (lora, float(lora_scale)) if lora is not None else None
        return self.residency.acquire(
            ("pipeline", model_name, textual_inversion, lora_key, mesh_key),
            build, model=model_name,
            size_of=lambda pipe: pipe.c.param_bytes(),
            estimate=lambda: self._estimate_bytes(model_name),
            priority=self._priority_for(model_name),
        )

    def components(self, model_name: str) -> Components:
        return self.pipeline(model_name).c

    def cascade_pipeline(self, model_name: str, mesh=None):
        """Resident IF-class cascade (pipelines/cascade.py) — the
        ``DeepFloyd/`` dispatch target (swarm/job_arguments.py:39-40).

        Multi-chip ``mesh`` placement is tensor-parallel ONLY (weights on
        the ``model`` axis; the batch stays replicated across ``data``) —
        unlike DiffusionPipeline, the cascade does not seed its inputs on
        the ``data`` axis."""
        from chiaswarm_tpu.pipelines.cascade import (
            CascadeComponents,
            CascadePipeline,
            get_cascade_family,
        )

        self._check_quarantine(model_name)
        mesh_key = _mesh_cache_key(mesh)

        def build():
            ckpt = model_dir(model_name)
            family = get_cascade_family(model_name)
            if ckpt.exists():
                from chiaswarm_tpu.convert.torch_to_flax import (
                    load_cascade_checkpoint,
                )

                log.info("loading cascade %s from %s", model_name, ckpt)
                components = load_cascade_checkpoint(ckpt, model_name,
                                                     family)
            elif self.allow_random:
                log.warning("no checkpoint for cascade %s; using random "
                            "weights", model_name)
                components = CascadeComponents.random(family,
                                                      model_name=model_name)
            else:
                raise ValueError(
                    f"cascade model {model_name!r} is not available on this "
                    f"node (no checkpoint at {ckpt})"
                )
            components.params = _place_params(components.params, mesh,
                                              model_name)
            return CascadePipeline(components)

        return self.residency.acquire(
            ("cascade", model_name, mesh_key), build, model=model_name,
            size_of=lambda pipe: pipe.c.param_bytes(),
            priority=self._priority_for(model_name),
        )

    def audio_pipeline(self, model_name: str):
        """Resident AudioLDM-class txt2audio pipeline
        (swarm/audio/audioldm.py:12-36 parity, pipelines/audio.py)."""
        from chiaswarm_tpu.pipelines.audio import (
            AudioComponents,
            AudioPipeline,
            get_audio_family,
        )

        self._check_quarantine(model_name)

        def build():
            ckpt = model_dir(model_name)
            family = get_audio_family(model_name)
            if ckpt.exists():
                from chiaswarm_tpu.convert.torch_to_flax import (
                    load_audio_checkpoint,
                )

                log.info("loading audio model %s from %s", model_name, ckpt)
                return AudioPipeline(
                    load_audio_checkpoint(ckpt, model_name, family))
            if self.allow_random:
                log.warning("no checkpoint for audio model %s; using random "
                            "weights", model_name)
                return AudioPipeline(AudioComponents.random(
                    family, model_name=model_name))
            raise ValueError(
                f"audio model {model_name!r} is not available on this node "
                f"(no checkpoint at {ckpt})"
            )

        return self.residency.acquire(
            ("audio", model_name), build, model=model_name,
            size_of=lambda pipe: pipe.c.param_bytes(),
            priority=self._priority_for(model_name),
        )

    def video_pipeline(self, model_name: str, mesh=None):
        """Resident ModelScope-class txt2vid pipeline
        (swarm/video/tx2vid.py:17-57 parity, pipelines/video.py).

        Multi-chip ``mesh`` placement is tensor-parallel ONLY: temporal
        attention couples the frame axis, so frames cannot ride a
        ``data`` axis here (the frame-batched vid2vid path, which runs
        per-frame through DiffusionPipeline, does get data parallelism)."""
        from chiaswarm_tpu.pipelines.video import (
            Img2VidPipeline,
            VideoComponents,
            VideoPipeline,
            get_video_family,
        )

        self._check_quarantine(model_name)
        mesh_key = _mesh_cache_key(mesh)

        def build():
            family = get_video_family(model_name)
            pipeline_cls = (Img2VidPipeline if family.image_conditioned
                            else VideoPipeline)
            ckpt = model_dir(model_name)
            components = None
            if ckpt.exists():
                try:
                    log.info("loading video model %s from %s (strict "
                             "temporal conversion; 2D snapshots inflate "
                             "for text families only)", model_name, ckpt)
                    components = VideoComponents.from_checkpoint(
                        ckpt, model_name, family)
                except Exception as exc:
                    # truncated/partial download: fall through to the
                    # configured fallback instead of poisoning every job
                    # (same policy as tts_pipeline)
                    log.warning("video checkpoint at %s unusable (%s: %s)",
                                ckpt, type(exc).__name__, exc)
            if components is None and self.allow_random:
                log.warning("video model %s: using random weights",
                            model_name)
                components = VideoComponents.random(family,
                                                    model_name=model_name)
            if components is None:
                why = (f"checkpoint at {ckpt} is unusable"
                       if ckpt.exists() else f"no checkpoint at {ckpt}")
                raise ValueError(
                    f"video model {model_name!r} is not available on this "
                    f"node ({why})"
                )
            components.params = _place_params(components.params, mesh,
                                              model_name)
            return pipeline_cls(components, attn_impl=self.attn_impl)

        return self.residency.acquire(
            ("video", model_name, mesh_key), build, model=model_name,
            size_of=lambda pipe: pipe.c.param_bytes(),
            priority=self._priority_for(model_name),
        )

    def tts_pipeline(self, model_name: str):
        """Resident bark-class TTS pipeline (swarm/audio/bark.py:11-38
        parity, pipelines/tts.py). Checkpoints load from the torch
        BarkModel layout via convert_bark."""
        from chiaswarm_tpu.pipelines.tts import (
            TTSComponents,
            TTSPipeline,
            get_tts_family,
        )

        self._check_quarantine(model_name)

        def build():
            family = get_tts_family(model_name)
            ckpt = model_dir(model_name)
            if ckpt.exists():
                try:
                    log.info("loading tts model %s from %s", model_name,
                             ckpt)
                    return TTSPipeline(TTSComponents.from_checkpoint(
                        ckpt, model_name, family))
                except Exception as exc:
                    # empty dir, truncated download (UnpicklingError),
                    # or key mismatch: fall through to the configured
                    # fallback path instead of poisoning every job
                    log.warning("tts checkpoint at %s unusable (%s: %s)",
                                ckpt, type(exc).__name__, exc)
            if self.allow_random:
                log.warning("tts model %s: using random weights", model_name)
                return TTSPipeline(TTSComponents.random(
                    family, model_name=model_name))
            raise ValueError(
                f"tts model {model_name!r} is not available on this node "
                f"(no checkpoint at {ckpt})"
            )

        return self.residency.acquire(
            ("tts", model_name), build, model=model_name,
            size_of=lambda pipe: pipe.c.param_bytes(),
            priority=self._priority_for(model_name),
        )

    def caption_pipeline(self, model_name: str, mesh=None):
        """Resident BLIP-class captioner (the per-job torch BLIP load of
        swarm/captioning/caption_image.py:12-17, made resident + LRU'd;
        native stack in models/blip.py + pipelines/caption.py)."""
        from chiaswarm_tpu.pipelines.caption import (
            CaptionComponents,
            CaptionPipeline,
        )

        self._check_quarantine(model_name)
        mesh_key = _mesh_cache_key(mesh)

        def build():
            ckpt = model_dir(model_name)
            components = None
            if ckpt.exists():
                try:
                    log.info("loading caption model %s from %s", model_name,
                             ckpt)
                    components = CaptionComponents.from_checkpoint(
                        ckpt, model_name)
                except Exception as exc:
                    # same fallback policy as tts_pipeline: an unusable
                    # checkpoint dir must not poison every caption job
                    log.warning("caption checkpoint at %s unusable (%s: %s)",
                                ckpt, type(exc).__name__, exc)
            if components is None and self.allow_random:
                log.warning("no checkpoint for caption model %s; using "
                            "random tiny weights", model_name)
                components = CaptionComponents.random(
                    "blip_tiny", model_name=model_name)
            if components is None:
                why = (f"checkpoint at {ckpt} is unusable"
                       if ckpt.exists() else f"no checkpoint at {ckpt}")
                raise ValueError(
                    f"caption model {model_name!r} is not available on "
                    f"this node ({why})"
                )
            # a ~450M-param captioner gains nothing from weight sharding:
            # pin to the slot's lead chip so per-slot jobs do not all
            # serialize on the default device
            if mesh is not None:
                import jax

                device = mesh.devices.flatten()[0]
                log.info("placing %s params on %s", model_name, device)
                components.params = jax.device_put(components.params,
                                                   device)
            return CaptionPipeline(components)

        return self.residency.acquire(
            ("caption", model_name, mesh_key), build, model=model_name,
            size_of=lambda pipe: pipe.c.param_bytes(),
            priority=self._priority_for(model_name),
        )

    def controlnet(self, controlnet_name: str, family: ModelFamily,
                   mesh=None):
        """Resident ControlNetBundle (the per-job ControlNetModel load of
        swarm/diffusion/diffusion_func.py:29-34, made resident + LRU'd).

        ``mesh`` (the consuming slot's mesh) only gates the int8 path:
        sharded placements decline quantization exactly like the base
        pipeline's params, so a multi-chip generate program never mixes
        sharded fp weights with a single-device-committed int8 control
        tree. The quantization decision rides the cache key — a bundle
        requested from both a single-chip and a multi-chip slot keys
        two entries rather than serving whichever loaded first."""
        from chiaswarm_tpu.convert.quantize import int8_enabled
        from chiaswarm_tpu.pipelines.components import ControlNetBundle

        quantize = (int8_enabled() and family.kind == "sd"
                    and (mesh is None or mesh.devices.size <= 1))

        def load() -> ControlNetBundle:
            from chiaswarm_tpu.convert.quantize import (
                maybe_quantize_params,
            )

            ckpt = model_dir(controlnet_name)
            if ckpt.exists():
                log.info("loading controlnet %s from %s",
                         controlnet_name, ckpt)
                bundle = ControlNetBundle.from_checkpoint(
                    ckpt, controlnet_name, family)
            elif self.allow_random:
                log.warning("no checkpoint for controlnet %s; using random "
                            "weights", controlnet_name)
                bundle = ControlNetBundle.random(family,
                                                model_name=controlnet_name)
            else:
                raise ValueError(
                    f"controlnet {controlnet_name!r} is not available on "
                    f"this node (no checkpoint at {ckpt})"
                )
            # bundles are the catalog's multiplied checkpoint class —
            # the int8 path applies to them like the base families
            if quantize:
                bundle.params = maybe_quantize_params(
                    bundle.params, family=family, mesh=None)
            return bundle

        return self.residency.acquire(
            ("controlnet", controlnet_name, family.name, quantize), load,
            model=controlnet_name,
            size_of=lambda b: b.param_bytes(),
            priority=self._priority_for(controlnet_name),
        )
