"""Node bootstrap: configure hive credentials, fetch the model catalog,
prefetch + convert checkpoints, and pre-warm compiles.

Capability parity with swarm/initialize.py:19-120 (``--reset`` / ``--silent``
interactive setup, ``GET /api/models`` cached to ``models.json``, per-model
weight prefetch), plus the TPU-specific extra the reference doesn't need:
optional ahead-of-time compilation of the hot shape buckets so the first
real job doesn't pay XLA compile time.

Zero-egress environments (no hub access) skip the download step cleanly —
the registry falls back per job and `swarm-tpu smoke` still runs with
random weights.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
from typing import Any

import aiohttp

from chiaswarm_tpu.node.hive import HiveClient
from chiaswarm_tpu.node.logging_setup import setup_logging
from chiaswarm_tpu.node.registry import model_dir
from chiaswarm_tpu.node.settings import (
    Settings,
    load_settings,
    save_file,
    save_settings,
    settings_root,
)

log = logging.getLogger("chiaswarm.init")


def prompt_settings(settings: Settings) -> Settings:
    uri = input(f"hive uri [{settings.hive_uri}]: ").strip()
    token = input("hive token (blank keeps current): ").strip()
    name = input(f"worker name [{settings.worker_name}]: ").strip()
    if uri:
        settings.hive_uri = uri
    if token:
        settings.hive_token = token
    if name:
        settings.worker_name = name
    return settings


async def fetch_model_catalog(settings: Settings) -> list[dict[str, Any]]:
    hive = HiveClient(settings.hive_uri, settings.hive_token,
                      settings.worker_name)
    async with aiohttp.ClientSession() as session:
        models = await hive.get_models(session)
    save_file(models, "models.json")
    log.info("cached %d models from the hive catalog", len(models))
    return models


def prefetch_checkpoints(models: list[dict[str, Any]],
                         settings: Settings) -> int:
    """Download preloadable checkpoints into the local model store
    (reference behavior at swarm/initialize.py:62-94). Needs hub access;
    returns the number fetched."""
    try:
        from huggingface_hub import snapshot_download
    except Exception:
        log.warning("huggingface_hub unavailable; skipping prefetch")
        return 0

    fetched = 0
    for model in models:
        name = model.get("name") or model.get("model_name")
        if not name or not model.get("parameters", {}).get("can_preload",
                                                           True):
            continue
        target = model_dir(name)
        if target.exists():
            continue
        try:
            log.info("prefetching %s", name)
            snapshot_download(
                name, local_dir=str(target),
                token=settings.huggingface_token or None,
                allow_patterns=["*.safetensors", "*.json", "*.txt"],
            )
            fetched += 1
        except Exception as exc:
            log.warning("prefetch of %s failed: %s", name, exc)
    fetched += _prefetch_annotators(models, settings)
    fetched += _prefetch_safety_checker(models, settings)
    return fetched


_SAFETY_CHECKER_REPO = "CompVis/stable-diffusion-safety-checker"


def _is_sd_generation_model(model: dict[str, Any]) -> bool:
    """True for models whose outputs go through the NSFW checker —
    anything the diffusion callback serves (the reference always checks,
    swarm/diffusion/diffusion_func.py:99-111)."""
    name = str(model.get("name") or model.get("model_name") or "")
    if not name:
        return False
    from chiaswarm_tpu.pipelines.tts import is_tts_model

    if is_tts_model(name) or "audioldm" in name.lower() \
            or "blip" in name.lower():
        return False
    workflow = str((model.get("parameters") or {}).get("workflow", ""))
    return workflow not in ("txt2audio", "img2txt", "txt2vid", "vid2vid")


def _prefetch_safety_checker(models: list[dict[str, Any]],
                             settings: Settings) -> int:
    """Provision the standalone safety checker whenever the catalog lists
    any image-generating model (workloads/safety.py loads it from
    ``model_dir("CompVis/stable-diffusion-safety-checker")``; without it a
    node honestly reports ``safety_checker: "unavailable"`` but an open
    network should always check)."""
    if not any(_is_sd_generation_model(m) for m in models):
        return 0
    target = model_dir(_SAFETY_CHECKER_REPO)
    if target.exists():
        return 0
    tmp = target.with_name(target.name + ".fetching")
    try:
        from huggingface_hub import snapshot_download

        tmp.mkdir(parents=True, exist_ok=True)
        snapshot_download(
            _SAFETY_CHECKER_REPO, local_dir=str(tmp),
            token=settings.huggingface_token or None,
            allow_patterns=["*.safetensors", "*.bin", "*.json"],
        )
        tmp.rename(target)  # only a COMPLETE fetch claims the dir
        log.info("fetched safety checker weights")
        return 1
    except Exception as exc:
        log.warning("safety checker fetch failed: %s", exc)
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
        return 0


# learned preprocessor weights (models/openpose.py, models/hed.py,
# models/dpt.py, models/upernet.py, models/mlsd.py, models/lineart.py):
# local model-dir name -> (catalog hint words, hub repo, weight filename).
# openpose/hed/mlsd/lineart come from the public annotator mirror the
# reference's controlnet_aux uses; depth from the Intel DPT release. ALL
# six learned modes provision here — a fresh node must never silently
# serve a stand-in for a mode it could run natively.
_ANNOTATORS = {
    "openpose": (("openpose",), "lllyasviel/Annotators",
                 "body_pose_model.pth"),
    "hed": (("hed", "scribble", "softedge"), "lllyasviel/Annotators",
            "ControlNetHED.pth"),
    "dpt": (("depth", "normal", "normalbae"), "Intel/dpt-large",
            "model.safetensors"),
    "upernet": (("seg", "segmentation"), "openmmlab/upernet-convnext-small",
                "model.safetensors"),
    "mlsd": (("mlsd",), "lllyasviel/Annotators",
             "mlsd_large_512_fp32.pth"),
    "lineart": (("lineart",), "lllyasviel/Annotators", "sk_model.pth"),
}


def _prefetch_annotators(models: list[dict[str, Any]],
                         settings: Settings) -> int:
    """Fetch learned-preprocessor weights when any catalog model
    advertises a controlnet mode that needs them."""
    import re

    blob = " ".join(
        f"{m.get('name', '')} {m.get('parameters') or {}}".lower()
        for m in models)
    words = set(re.findall(r"[a-z0-9]+", blob))  # word-boundary matching:
    # a substring test would fire 'hed' on 'scheduler'/'cached'
    fetched = 0
    for local_name, (hints, repo, filename) in _ANNOTATORS.items():
        target = model_dir(local_name)
        if target.exists() or not any(h in words for h in hints):
            continue
        tmp = target.with_name(target.name + ".fetching")
        try:
            from huggingface_hub import hf_hub_download

            tmp.mkdir(parents=True, exist_ok=True)
            hf_hub_download(repo, filename,
                            local_dir=str(tmp),
                            token=settings.huggingface_token or None)
            tmp.rename(target)  # only a COMPLETE fetch claims the dir
            log.info("fetched %s annotator weights (%s)", local_name,
                     filename)
            fetched += 1
        except Exception as exc:
            log.warning("%s weight fetch failed: %s", local_name, exc)
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
    return fetched


def warm_compile(models: list[dict[str, Any]]) -> None:
    """Ahead-of-time compile the default shape bucket per local model.

    Warms the SAME cache entries serving will hit: the worker's default
    slot mesh keys the pipeline entry (node/registry.py), so warming
    without it would leave a dead unsharded duplicate and pay the full
    load+compile again on the first real job."""
    from chiaswarm_tpu.core.chip_pool import ChipPool
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.node.settings import load_settings
    from chiaswarm_tpu.pipelines.diffusion import GenerateRequest

    settings = load_settings()
    from chiaswarm_tpu.core.mesh import MeshSpec

    spec = (MeshSpec(dict(settings.mesh_shape))
            if settings.mesh_shape else None)
    mesh = ChipPool(n_slots=1, mesh_spec=spec).slots[0].mesh
    registry = ModelRegistry(catalog=models, allow_random=False)
    for model in models:
        name = model.get("name") or model.get("model_name")
        if not name or not model_dir(name).exists():
            continue
        try:
            workflow = str((model.get("parameters") or {})
                           .get("workflow", ""))
            # bark outranks the txt2audio workflow tag: the hive serves
            # bark UNDER txt2audio (job_args.py routing), so the name
            # gate must win or bark would warm as AudioLDM and fail
            from chiaswarm_tpu.pipelines.tts import is_tts_model

            if is_tts_model(name):
                registry.tts_pipeline(name)("warmup", duration_s=0.5)
            elif name.startswith("DeepFloyd/"):
                registry.cascade_pipeline(name, mesh=mesh)(
                    "warmup", steps=2, sr_steps=2)
            elif workflow == "txt2audio" or "audioldm" in name.lower():
                registry.audio_pipeline(name)("warmup", steps=2,
                                              duration_s=1.0)
            elif workflow == "img2txt" or "blip" in name.lower():
                import numpy as np

                registry.caption_pipeline(name, mesh=mesh)(
                    np.zeros((64, 64, 3), np.uint8))
            else:
                pipe = registry.pipeline(name, mesh=mesh)
                size = pipe.c.family.default_size
                pipe(GenerateRequest(prompt="warmup", steps=2,
                                     height=size, width=size, seed=0))
            log.info("warmed %s", name)
        except Exception as exc:
            log.warning("warm compile of %s failed: %s", name, exc)


async def init(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--reset", action="store_true",
                        help="re-prompt for hive uri/token")
    parser.add_argument("--silent", action="store_true",
                        help="no prompts; use existing/env settings")
    parser.add_argument("--no-prefetch", action="store_true")
    parser.add_argument("--warm-compile", action="store_true")
    args = parser.parse_args(argv)

    settings = load_settings()
    setup_logging(settings_root() / "logs", settings.log_filename,
                  settings.log_level)
    if args.reset or (not settings.hive_token and not args.silent):
        settings = prompt_settings(settings)
    save_settings(settings)

    try:
        models = await fetch_model_catalog(settings)
    except Exception as exc:
        log.warning("could not reach the hive (%s); using cached catalog",
                    exc)
        from chiaswarm_tpu.node.settings import load_file

        models = load_file("models.json") or []

    if not args.no_prefetch:
        prefetch_checkpoints(models, settings)
    if args.warm_compile:
        warm_compile(models)
    log.info("init complete: settings at %s", settings_root())
    return 0


def main() -> None:
    raise SystemExit(asyncio.run(init()))


if __name__ == "__main__":
    main()
