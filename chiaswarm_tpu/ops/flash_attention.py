"""Pallas TPU blockwise flash attention (forward, inference).

This is the framework's native-kernel replacement for the reference's
xformers memory-efficient attention (enabled at
swarm/diffusion/diffusion_func.py:86-87). The reference delegates to a
prebuilt CUDA wheel; here the kernel is written for the TPU memory
hierarchy directly:

- grid = (batch*heads, Q blocks, KV blocks), KV innermost ("arbitrary"
  semantics) so the running-softmax accumulator lives in VMEM scratch
  across the KV sweep while Q/KV blocks stream HBM -> VMEM.
- logits/softmax accumulate in float32 on the MXU (`preferred_element_type`)
  regardless of the bf16 input dtype; the output is cast back at the end.
- O(L) memory: no (L, S) attention matrix ever materializes in HBM. That is
  what lets SDXL 1024px self-attention (4096 tokens) and video/long-context
  shapes run without the reference's attention-slicing fallbacks
  (swarm/diffusion/diffusion_func.py:85-88).

Head dims of SD UNets (40/80/160/64/128) are zero-padded up to the 128-lane
tile; padded lanes contribute zero logits and zero values, so results are
exact. Sequence lengths pad up to the block size with -inf-masked logits.

Default blocks (2048 q x 1024 kv) come from an end-to-end sweep on v5e
(SDXL 1024px, 30 steps): 256x256 ran 6.98 s/image, XLA's fused attention
5.07 s, 2048x1024 3.98 s; 2048x2048 and 4096x1024 exceed the 16 MB VMEM
scoped limit. Large q blocks amortize the running-softmax scratch traffic;
the kernel clamps blocks to the (padded) sequence length for small inputs.

The same kernel runs in Pallas interpret mode on CPU, which is how the
hermetic test suite validates it against the einsum reference
(tests/test_ops.py) without a TPU.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU builds too; guard for safety
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

_NEG_INF = -1e30  # finite stand-in: true -inf breaks exp() on fully-masked rows


def _compiler_params(**kwargs):
    """The Mosaic params dataclass is ``TPUCompilerParams`` on the 0.4.x
    pin and ``CompilerParams`` on modern jax — resolve whichever ships.
    (The old spelling here only ever ran on TPU, so CPU CI could not
    catch the pin mismatch; ring_flash_attention shares this helper.)"""
    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams")
    return cls(**kwargs)

# block-sweep knobs (read once at import): defaults are the tuned v5e
# values. CHIASWARM_FLASH_VMEM_MB sets the kernel-scoped VMEM cap — the
# default 24 MB gives the tuned 2048x1024 blocks headroom over XLA's
# ~16 MB default cap (the SVD video program's surrounding pads push the
# same blocks to 16.4 MB scoped); the cap is a compile-time guard, not an
# allocation, so programs already under 16 MB compile identically. Raise
# further for sweeps of bigger blocks (2048x2048, 4096x1024) on other
# TPU generations; 0 = XLA's default cap.
# an env-pinned block is an EXPLICIT sweep request: EITHER knob disables
# the divisibility auto-pick on BOTH axes, so a datapoint labeled
# "4096x1024" measures exactly 4096x1024 (pinning one axis must not let
# the other silently auto-pick)
_ENV_BLOCK_Q = os.environ.get("CHIASWARM_FLASH_BLOCK_Q")
_ENV_BLOCK_KV = os.environ.get("CHIASWARM_FLASH_BLOCK_KV")
_ENV_PINNED = bool(_ENV_BLOCK_Q or _ENV_BLOCK_KV)
_DEFAULT_BLOCK_Q = int(_ENV_BLOCK_Q) if _ENV_BLOCK_Q else 2048
_DEFAULT_BLOCK_KV = int(_ENV_BLOCK_KV) if _ENV_BLOCK_KV else 1024
_VMEM_MB = int(os.environ.get("CHIASWARM_FLASH_VMEM_MB", "24"))
_LANES = 128


def online_softmax_block_update(q, k, v, m_prev, l_prev, acc_prev, *,
                                scale: float, kv_len: int, col_offset):
    """One KV block of the running-softmax recurrence, shared by the
    local flash kernel below and the fused ring kernel
    (ops/ring_flash_attention.py). All operands are plain arrays (the
    callers own the scratch refs): q (bq, d), k/v (bkv, d), m/l (bq, 1)
    running max/denominator, acc (bq, d) fp32 accumulator. ``col_offset``
    is the block's first GLOBAL kv column (masks padding past
    ``kv_len``); it may be a traced scalar in the ring kernel, where the
    hop index is a grid coordinate. Returns (m_next, l_next, acc_next)
    — bit-identical math to the pre-refactor inline version."""
    logits = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale

    # mask KV positions past the true sequence length (block padding)
    col = col_offset + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    logits = jnp.where(col < kv_len, logits, _NEG_INF)

    m_cur = jnp.max(logits, axis=-1, keepdims=True)
    m_next = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_next)           # rescale of the old partials
    p = jnp.exp(logits - m_next)               # (bq, bkv) fp32
    l_next = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
    acc_next = acc_prev * alpha + jax.lax.dot_general(
        p, v.astype(jnp.float32),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return m_next, l_next, acc_next


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                  scale: float, kv_len: int, block_kv: int):
    """One (q-block, kv-block) tile of the running-softmax recurrence."""
    j = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    m_next, l_next, acc_next = online_softmax_block_update(
        q_ref[0], k_ref[0], v_ref[0],
        m_scr[:, :1], l_scr[:, :1], acc_scr[:],
        scale=scale, kv_len=kv_len, col_offset=j * block_kv,
    )
    acc_scr[:] = acc_next
    m_scr[:] = jnp.broadcast_to(m_next, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_next, l_scr.shape)

    @pl.when(j == n_kv - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:] / l_scr[:, :1]).astype(o_ref.dtype)


def _clamp_block(length: int, block: int) -> int:
    """Shrink a block to the 8-padded sequence length (small inputs)."""
    return min(block, max(8, ((length + 7) // 8) * 8))


def _pick_block(length: int, default: int) -> int:
    """Auto block size for one attention axis: minimize the PADDED
    length — masked block padding still runs on the MXU, so a
    non-divisible tuned block wastes real time (the SVD portrait's
    9216-token level padded to 10240 with 2048-blocks; its 2304-token
    level to 4096/3072). The rule minimizes padded length over the
    FIXED candidate list (1536, 1280, 1024, 768) below the tuned
    default — large blocks only, not divisors of it. Two guards keep
    the r2 sweep's findings intact: candidates stop at 768 (the sweep
    measured small blocks ~75% slower than large ones regardless of
    padding — a 256-divisible length must not fall off that cliff),
    and a smaller block is taken only when it saves >=5% of the
    default's padded length. Power-of-two SD/SDXL
    shapes keep the tuned blocks bit-for-bit. Applied ONLY when neither
    the caller nor the CHIASWARM_FLASH_BLOCK_* env knobs pin a block —
    explicit sweep values are honored as requested."""
    length8 = max(8, ((length + 7) // 8) * 8)
    if length8 <= default:
        return length8
    pad_default = -(-length8 // default) * default
    best_key, best = (pad_default, -default), default
    for cand in (1536, 1280, 1024, 768):
        if cand >= default:
            continue
        padded = -(-length8 // cand) * cand
        if pad_default - padded < 0.05 * pad_default:
            continue  # not worth leaving the tuned block
        key = (padded, -cand)
        if key < best_key:
            best_key, best = key, cand
    return best


def _pad_to(x: jnp.ndarray, axis: int, multiple: int) -> jnp.ndarray:
    size = x.shape[axis]
    target = ((size + multiple - 1) // multiple) * multiple
    if target == size:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, target - size)
    return jnp.pad(x, pad)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "block_q", "block_kv", "interpret"),
)
def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    scale: float | None = None,
    block_q: int | None = None,
    block_kv: int | None = None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Blockwise attention over (B, L, H, D) q and (B, S, H, D) k/v."""
    if scale is None:
        scale = float(q.shape[-1]) ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    b, l, h, d = q.shape
    s = k.shape[1]
    out_dtype = q.dtype

    # (B, L, H, D) -> (B*H, L, D): heads become grid-parallel programs
    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    qf, kf, vf = fold(q), fold(k), fold(v)

    # None = auto (divisibility-aware pick, unless an env sweep pins the
    # block); an explicit caller/env value is honored, clamped only to
    # the padded sequence length. (A per-shape measured-pair override
    # was tried and REJECTED: tools/flash_sweep.py's isolated chain
    # showed 1152x2304 beating 768x768 by ~21% at 2304 tokens, but the
    # end-to-end portrait program measured ~equal-or-worse — in-program
    # these ops already run at 97 TFLOP/s with XLA overlapping them,
    # and the isolated ~40 TFLOP/s chain mispredicts that regime.)
    if block_q is None:
        block_q = (_clamp_block(l, _DEFAULT_BLOCK_Q) if _ENV_PINNED
                   else _pick_block(l, _DEFAULT_BLOCK_Q))
    else:
        block_q = _clamp_block(l, block_q)
    if block_kv is None:
        block_kv = (_clamp_block(s, _DEFAULT_BLOCK_KV) if _ENV_PINNED
                    else _pick_block(s, _DEFAULT_BLOCK_KV))
    else:
        block_kv = _clamp_block(s, block_kv)
    qf = _pad_to(qf, 1, block_q)
    kf = _pad_to(kf, 1, block_kv)
    vf = _pad_to(vf, 1, block_kv)
    qf = _pad_to(qf, 2, _LANES)
    kf = _pad_to(kf, 2, _LANES)
    vf = _pad_to(vf, 2, _LANES)
    dp = qf.shape[2]
    lp, sp = qf.shape[1], kf.shape[1]
    grid = (b * h, lp // block_q, sp // block_kv)

    kernel = functools.partial(
        _flash_kernel, scale=scale, kv_len=s, block_kv=block_kv,
    )
    scratch = [
        pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max
        pltpu.VMEM((block_q, _LANES), jnp.float32),  # running denom
        pltpu.VMEM((block_q, dp), jnp.float32),      # output accumulator
    ]
    params = {}
    if _HAS_PLTPU and not interpret:
        extra = {"vmem_limit_bytes": _VMEM_MB << 20} if _VMEM_MB else {}
        params["compiler_params"] = _compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
            **extra,
        )

    of = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, dp), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_kv, dp), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, block_kv, dp), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, dp), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, lp, dp), out_dtype),
        scratch_shapes=scratch,
        interpret=interpret,
        **params,
    )(qf, kf, vf)

    # unfold: (B*H, Lp, Dp) -> (B, L, H, D)
    of = of[:, :l, :d].reshape(b, h, l, d).transpose(0, 2, 1, 3)
    return of
