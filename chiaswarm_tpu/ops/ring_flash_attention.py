"""Fused Pallas ring-flash attention: DMA/compute overlap on the ICI ring.

`parallel/ring_attention.py` alternates phases — each hop runs the local
partial softmax, THEN `lax.ppermute` rotates the KV shard — so the MXU
idles during every rotation and the ICI idles during every compute. The
BENCH r05 roofline puts the 2304-token flash levels at 49% attainment
(9216 at 69%): attention is where the remaining chip time lives (ROADMAP
item 2). This kernel closes the gap by issuing the NEXT hop's KV transfer
as an async remote DMA (`pltpu.make_async_remote_copy`) into a
double-buffered VMEM slot while the blockwise flash inner loop — the
online-softmax recurrence shared with `ops/flash_attention.py` via
``online_softmax_block_update`` — consumes the CURRENT slot. One
`pl.pallas_call` per shard covers all n hops; no XLA collective ever
lowers for the rotation (the HLO census in tools/contracts/tiny.json
pins that).

Two drive modes, one recurrence:

- fused (TPU)     grid = (B*H, hops), hops innermost/"arbitrary"; the
                  running (m, l, acc) state lives in VMEM scratch across
                  the hop sweep exactly like the local flash kernel's KV
                  sweep. Per hop: start the RDMA of the current KV slot
                  to the right neighbor's next slot, run the flash block
                  update on the current slot, then wait both DMA
                  semaphores and flip slots. A capacity semaphore from
                  the receiver guards the slot against overwrite-while-
                  reading skew; `pltpu.get_barrier_semaphore` aligns the
                  ring before the first send.
- interpret (CPU) `lax.scan` over hops with `lax.ppermute` rotation —
                  the hermetic harness for the SAME in-kernel hop update
                  (`_hop_kernel` runs under Pallas interpret mode with
                  the carried state as inputs/outputs). This is also the
                  software fallback on TPU via CHIASWARM_RING_FLASH=scan.

Call inside `shard_map` with q/k/v sharded on the sequence axis, layout
(B, L, H, D) per shard — the same contract as
`parallel.ring_attention.ring_attention`, which remains the exactness
oracle (tests/test_ring_flash.py pins parity on seq=4/seq=8 and the
data x seq divergence-family trigger mesh).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from chiaswarm_tpu.core.compat import axis_size
from chiaswarm_tpu.obs import numerics as _numerics
from chiaswarm_tpu.ops.flash_attention import (
    _LANES,
    _NEG_INF,
    _compiler_params,
    _pad_to,
    online_softmax_block_update,
)

try:  # pltpu imports on CPU builds too; guard for safety
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False


# ---------------------------------------------------------------------------
# the per-hop kernel: the local flash KV sweep with CARRIED state
#
# Identical blockwise recurrence to ops/flash_attention.py::_flash_kernel,
# except the (m, l, acc) accumulator state enters through input refs and
# leaves through output refs instead of being -inf/zero initialized — the
# ring carries it across hops. m/l ride (bq, LANES) lane-broadcast tiles,
# the same scratch layout the local kernel uses.


def _hop_kernel(q_ref, k_ref, v_ref, m_in_ref, l_in_ref, acc_in_ref,
                m_out_ref, l_out_ref, acc_out_ref,
                m_scr, l_scr, acc_scr, *,
                scale: float, kv_len: int, block_kv: int):
    j = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(j == 0)
    def _load():
        m_scr[:] = m_in_ref[0]
        l_scr[:] = l_in_ref[0]
        acc_scr[:] = acc_in_ref[0]

    m_next, l_next, acc_next = online_softmax_block_update(
        q_ref[0], k_ref[0], v_ref[0],
        m_scr[:, :1], l_scr[:, :1], acc_scr[:],
        scale=scale, kv_len=kv_len, col_offset=j * block_kv,
    )
    acc_scr[:] = acc_next
    m_scr[:] = jnp.broadcast_to(m_next, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_next, l_scr.shape)

    @pl.when(j == n_kv - 1)
    def _store():
        m_out_ref[0] = m_scr[:]
        l_out_ref[0] = l_scr[:]
        acc_out_ref[0] = acc_scr[:]


def _hop_call(qf, kf, vf, m, l, acc, *, scale: float, kv_len: int,
              block_q: int, block_kv: int, interpret: bool):
    """One ring hop: run the flash inner loop of the local q shard over
    one KV shard, threading the running state. Shapes are the folded
    (B*H, Lp, Dp) / (B*H, Sp, Dp) layout; m/l are (B*H, Lp, LANES)."""
    bh, lp, dp = qf.shape
    sp = kf.shape[1]
    grid = (bh, lp // block_q, sp // block_kv)
    kernel = functools.partial(
        _hop_kernel, scale=scale, kv_len=kv_len, block_kv=block_kv)

    q_spec = pl.BlockSpec((1, block_q, dp), lambda b, i, j: (b, i, 0))
    kv_spec = pl.BlockSpec((1, block_kv, dp), lambda b, i, j: (b, j, 0))
    row_spec = pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0))
    acc_spec = pl.BlockSpec((1, block_q, dp), lambda b, i, j: (b, i, 0))

    params = {}
    if _HAS_PLTPU and not interpret:
        params["compiler_params"] = _compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[q_spec, kv_spec, kv_spec, row_spec, row_spec, acc_spec],
        out_specs=(row_spec, row_spec, acc_spec),
        out_shape=(
            jax.ShapeDtypeStruct((bh, lp, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((bh, lp, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((bh, lp, dp), jnp.float32),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, dp), jnp.float32),
        ] if _HAS_PLTPU else None,
        interpret=interpret,
    )(qf, kf, vf, m, l, acc)


# ---------------------------------------------------------------------------
# fused TPU kernel: all hops in one pallas_call, RDMA under the compute


def _fused_kernel(nbr_ref,  # scalar prefetch: right neighbor mesh coords
                  q_ref, k_ref, v_ref, o_ref,
                  k_buf, v_buf, m_scr, l_scr, acc_scr,
                  send_sem, recv_sem, free_sem, *,
                  scale: float, kv_len: int, n_shards: int,
                  n_mesh_axes: int):
    bh = pl.program_id(0)
    hop = pl.program_id(1)
    cur = jax.lax.rem(hop, 2)
    nxt = jax.lax.rem(hop + 1, 2)
    right = tuple(nbr_ref[0, a] for a in range(n_mesh_axes))
    left = tuple(nbr_ref[1, a] for a in range(n_mesh_axes))

    @pl.when(jnp.logical_and(bh == 0, hop == 0))
    def _ring_barrier():
        # nobody may RDMA into a neighbor that has not entered the kernel
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(
            barrier, inc=1, device_id=left,
            device_id_type=pltpu.DeviceIdType.MESH)
        pltpu.semaphore_signal(
            barrier, inc=1, device_id=right,
            device_id_type=pltpu.DeviceIdType.MESH)
        pltpu.semaphore_wait(barrier, 2)

    @pl.when(hop == 0)
    def _seed():
        # local KV shard into slot 0; grant the upstream sender slot 1
        # (its hop-0 send target). Subsequent grants are issued as each
        # slot's compute retires below.
        k_buf[0] = k_ref[0]
        v_buf[0] = v_ref[0]
        if n_shards > 1:
            pltpu.semaphore_signal(
                free_sem, inc=1, device_id=left,
                device_id_type=pltpu.DeviceIdType.MESH)

    @pl.when(jnp.logical_and(hop < n_shards - 1, n_shards > 1))
    def _send_next():
        # capacity handshake: wait for the receiver's grant on slot nxt,
        # then stream both KV halves of the current slot rightward while
        # the MXU works on the same slot below.
        pltpu.semaphore_wait(free_sem, 1)
        for buf, sems in ((k_buf, 0), (v_buf, 1)):
            pltpu.make_async_remote_copy(
                buf.at[cur], buf.at[nxt],
                send_sem.at[sems], recv_sem.at[sems],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.MESH,
            ).start()

    # ---- the blockwise flash inner loop on the CURRENT slot -------------
    m_prev = jnp.where(hop == 0, jnp.full_like(m_scr[:, :1], _NEG_INF),
                       m_scr[:, :1])
    l_prev = jnp.where(hop == 0, jnp.zeros_like(l_scr[:, :1]), l_scr[:, :1])
    acc_prev = jnp.where(hop == 0, jnp.zeros_like(acc_scr[:]), acc_scr[:])
    m_next, l_next, acc_next = online_softmax_block_update(
        q_ref[0], k_buf[cur], v_buf[cur],
        m_prev, l_prev, acc_prev,
        scale=scale, kv_len=kv_len, col_offset=0,
    )
    acc_scr[:] = acc_next
    m_scr[:] = jnp.broadcast_to(m_next, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_next, l_scr.shape)

    @pl.when(jnp.logical_and(hop < n_shards - 1, n_shards > 1))
    def _drain():
        # our outbound write landed AND the inbound next slot is full
        for sems in (0, 1):
            pltpu.make_async_remote_copy(
                k_buf.at[cur], k_buf.at[nxt],
                send_sem.at[sems], recv_sem.at[sems],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.MESH,
            ).wait()
        # slot `cur` is consumed: grant it to the upstream sender, whose
        # hop+1 send targets it — EXCEPT on the last two hops, where no
        # further send exists (the grant ledger must balance per sweep:
        # n-1 waits == 1 seed grant + n-2 retire grants).

    @pl.when(jnp.logical_and(hop < n_shards - 2, n_shards > 2))
    def _retire_grant():
        pltpu.semaphore_signal(
            free_sem, inc=1, device_id=left,
            device_id_type=pltpu.DeviceIdType.MESH)

    @pl.when(hop == n_shards - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:] / l_scr[:, :1]).astype(o_ref.dtype)


def _ring_flash_fused(q, k, v, *, axis_name: str, scale: float,
                      mesh_axis_names: tuple[str, ...]):
    """TPU path: one pallas_call per shard, hops innermost, KV slots
    double-buffered in VMEM with the RDMA issued under the compute."""
    n = axis_size(axis_name)
    b, l, h, d = q.shape
    s = k.shape[1]
    out_dtype = q.dtype

    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    qf, kf, vf = fold(q), fold(k), fold(v)
    qf = _pad_to(_pad_to(qf, 1, 8), 2, _LANES)
    kf = _pad_to(_pad_to(kf, 1, 8), 2, _LANES)
    vf = _pad_to(_pad_to(vf, 1, 8), 2, _LANES)
    bh, lp, dp = qf.shape
    sp = kf.shape[1]

    # right/left neighbor mesh coordinates (rotate ONLY the seq axis);
    # scalar-prefetched so the kernel can address the RDMA without
    # recomputing axis indices per grid step
    seq_pos = mesh_axis_names.index(axis_name)
    me = [jax.lax.axis_index(a) for a in mesh_axis_names]
    right = list(me)
    right[seq_pos] = jax.lax.rem(me[seq_pos] + 1, n)
    left = list(me)
    left[seq_pos] = jax.lax.rem(me[seq_pos] + n - 1, n)
    nbr = jnp.stack([jnp.stack(right), jnp.stack(left)]).astype(jnp.int32)

    kernel = functools.partial(
        _fused_kernel, scale=scale, kv_len=s, n_shards=n,
        n_mesh_axes=len(mesh_axis_names))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bh, n),
        in_specs=[
            pl.BlockSpec((1, lp, dp), lambda b_, hop_: (b_, 0, 0)),
            pl.BlockSpec((1, sp, dp), lambda b_, hop_: (b_, 0, 0)),
            pl.BlockSpec((1, sp, dp), lambda b_, hop_: (b_, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, lp, dp), lambda b_, hop_: (b_, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, sp, dp), jnp.float32),   # K slots
            pltpu.VMEM((2, sp, dp), jnp.float32),   # V slots
            pltpu.VMEM((lp, _LANES), jnp.float32),  # running max
            pltpu.VMEM((lp, _LANES), jnp.float32),  # running denom
            pltpu.VMEM((lp, dp), jnp.float32),      # output accumulator
            pltpu.SemaphoreType.DMA((2,)),          # send (K, V)
            pltpu.SemaphoreType.DMA((2,)),          # recv (K, V)
            pltpu.SemaphoreType.REGULAR,            # slot capacity grants
        ],
    )
    of = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((bh, lp, dp), out_dtype),
        compiler_params=_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary"),
            has_side_effects=True,
            collective_id=7,
        ),
    )(nbr, qf.astype(jnp.float32), kf.astype(jnp.float32),
      vf.astype(jnp.float32))
    return of[:, :l, :d].reshape(b, h, l, d).transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# interpret/oracle path: ppermute rotation around the SAME hop kernel


def _ring_flash_scan(q, k, v, *, axis_name: str, scale: float,
                     block_q: int | None, block_kv: int | None,
                     interpret: bool):
    n = axis_size(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    b, l, h, d = q.shape
    s = k.shape[1]

    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    qf, kf, vf = fold(q), fold(k), fold(v)
    if block_q is None:
        block_q = max(8, ((l + 7) // 8) * 8)
    if block_kv is None:
        block_kv = max(8, ((s + 7) // 8) * 8)
    qf = _pad_to(_pad_to(qf, 1, block_q), 2, _LANES)
    kf = _pad_to(_pad_to(kf, 1, block_kv), 2, _LANES)
    vf = _pad_to(_pad_to(vf, 1, block_kv), 2, _LANES)
    bh, lp, dp = qf.shape

    # zero-init carries derive from q arithmetic so they inherit the full
    # varying-axes set under multi-axis shard_map (same stance as
    # parallel/ring_attention.py); XLA folds the zero-multiplies away.
    zrow = jnp.broadcast_to(
        (qf * 0).astype(jnp.float32).sum(axis=-1, keepdims=True),
        (bh, lp, _LANES))
    m0 = zrow + _NEG_INF
    l0 = zrow
    acc0 = (qf * 0).astype(jnp.float32)

    tap_on = _numerics.enabled_for("ring_flash")

    def body(carry, hop):
        k_blk, v_blk, m, lsum, acc = carry
        m, lsum, acc = _hop_call(
            qf, k_blk, v_blk, m, lsum, acc, scale=scale, kv_len=s,
            block_q=block_q, block_kv=block_kv, interpret=interpret)
        if tap_on:
            shard = jax.lax.axis_index(axis_name)
            m = _numerics.tap("ring_flash.hop_rowmax", m,
                              step=hop, shard=shard)
            lsum = _numerics.tap("ring_flash.hop_rowsum", lsum,
                                 step=hop, shard=shard)
            acc = _numerics.tap("ring_flash.hop_acc", acc,
                                step=hop, shard=shard)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, m, lsum, acc), None

    (_, _, m, lsum, acc), _ = jax.lax.scan(
        body, (kf, vf, m0, l0, acc0),
        jnp.arange(n) if tap_on else None,
        length=None if tap_on else n,
    )
    out = acc / lsum[:, :, :1]
    out = out[:, :l, :d].reshape(b, h, l, d).transpose(0, 2, 1, 3)
    if tap_on:
        out = _numerics.tap("ring_flash.out", out,
                            shard=jax.lax.axis_index(axis_name))
    return out.astype(q.dtype)


def _mode() -> str:
    """CHIASWARM_RING_FLASH: fused (TPU default) | scan (software
    fallback / the interpret oracle, CPU default)."""
    return os.environ.get("CHIASWARM_RING_FLASH", "").strip().lower()


def ring_flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str,
    scale: float | None = None,
    mesh_axis_names: tuple[str, ...] | None = None,
    block_q: int | None = None,
    block_kv: int | None = None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Full (non-causal) ring-flash attention inside ``shard_map``.

    Per-shard layout (B, L/n, H, D), the `ring_attention` contract. On
    TPU the fused single-kernel path runs (RDMA under compute); anywhere
    else — or under CHIASWARM_RING_FLASH=scan — the ppermute scan drives
    the same hop kernel in Pallas interpret mode, which is how the
    hermetic suite pins parity against the ppermute ring oracle."""
    if scale is None:
        scale = float(q.shape[-1]) ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    fused = (_HAS_PLTPU and not interpret and _mode() != "scan"
             and mesh_axis_names is not None)
    if fused:
        return _ring_flash_fused(
            q, k, v, axis_name=axis_name, scale=scale,
            mesh_axis_names=mesh_axis_names)
    return _ring_flash_scan(
        q, k, v, axis_name=axis_name, scale=scale,
        block_q=block_q, block_kv=block_kv, interpret=interpret)
