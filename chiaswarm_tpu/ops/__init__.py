"""Compute ops: attention (XLA reference + Pallas flash kernel), fused helpers."""

from chiaswarm_tpu.ops.attention import attention, AttentionImpl

__all__ = ["attention", "AttentionImpl"]
