"""Compute ops: attention (XLA reference + Pallas flash and fused
ring-flash kernels), fused helpers."""

from chiaswarm_tpu.ops.attention import attention, AttentionImpl

__all__ = ["attention", "AttentionImpl", "ring_flash_attention"]


def __getattr__(name):
    # lazy: ring_flash_attention pulls in the Pallas modules; the hot
    # serving import path should not pay for it until a seq mesh engages
    if name == "ring_flash_attention":
        from chiaswarm_tpu.ops.ring_flash_attention import (
            ring_flash_attention,
        )

        return ring_flash_attention
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
