"""Attention dispatch — the TPU replacement for the reference's xformers
memory-efficient attention (enabled at swarm/diffusion/diffusion_func.py:86-87).

Three implementations behind one function:

- ``"xla"``      — plain einsum softmax attention; XLA fuses it well for the
                   small/medium sequence lengths of image latents. Always
                   correct; the golden reference for kernel tests.
- ``"flash"``    — Pallas blockwise flash-attention kernel (ops/flash_attention.py),
                   O(L) memory, targets the MXU; used on TPU for large token
                   counts (SDXL 1024px self-attention = 4096 tokens, video).
- ``"auto"``     — flash on TPU when shapes qualify, else xla.

All take (B, L, H, D) query / (B, S, H, D) key-value tensors and return
(B, L, H, D). Head-batched layouts keep the last dim = head_dim (128-lane
friendly) and let the kernel tile L/S onto the MXU.
"""

from __future__ import annotations

import functools
from typing import Literal

import jax
import jax.numpy as jnp

AttentionImpl = Literal["auto", "xla", "flash"]


def _xla_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   scale: float) -> jnp.ndarray:
    # (B, L, H, D) x (B, S, H, D) -> (B, H, L, S)
    logits = jnp.einsum("blhd,bshd->bhls", q, k,
                        preferred_element_type=jnp.float32)
    weights = jax.nn.softmax(logits * scale, axis=-1).astype(q.dtype)
    return jnp.einsum("bhls,bshd->blhd", weights, v)


@functools.lru_cache(maxsize=1)
def _flash_available() -> bool:
    try:
        from chiaswarm_tpu.ops import flash_attention  # noqa: F401
        return True
    except Exception:
        return False


def _on_tpu(x: jnp.ndarray) -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    scale: float | None = None,
    impl: AttentionImpl = "auto",
) -> jnp.ndarray:
    """Multi-head scaled dot-product attention, (B, L, H, D) layout."""
    if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
        raise ValueError(f"expected (B, L, H, D) tensors, got {q.shape}")
    if scale is None:
        scale = q.shape[-1] ** -0.5

    use_flash = False
    if impl == "flash":
        use_flash = True
    elif impl == "auto":
        # Block-size sweep on v5e (SDXL 1024px, 30 steps, end-to-end):
        # flash@256 blocks 6.98s < XLA fused 5.07s < flash@2048x1024
        # blocks 3.98s per image. With the tuned blocks the Pallas kernel
        # wins from 1024 tokens up; tiny KV (77-token text cross-attention)
        # and small spatial grids stay on the einsum path.
        use_flash = (
            _on_tpu(q)
            and _flash_available()
            and q.shape[1] >= 1024
            and k.shape[1] >= 1024
        )

    if use_flash:
        from chiaswarm_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, scale=scale)
    return _xla_attention(q, k, v, scale)
