"""Attention dispatch — the TPU replacement for the reference's xformers
memory-efficient attention (enabled at swarm/diffusion/diffusion_func.py:86-87).

Five implementations behind one function:

- ``"xla"``        — plain einsum softmax attention; XLA fuses it well for
                     the small/medium sequence lengths of image latents.
                     Always correct; the golden reference for kernel tests.
- ``"flash"``      — Pallas blockwise flash-attention kernel
                     (ops/flash_attention.py), O(L) memory, targets the MXU;
                     used on TPU for large token counts (SDXL 1024px
                     self-attention = 4096 tokens, video).
- ``"ring"``       — sequence-parallel ring attention
                     (parallel/ring_attention.py): tokens sharded over the
                     mesh's ``seq`` axis, KV blocks rotated with ppermute.
                     Engaged when the pipeline runs under
                     parallel.context.sequence_parallel on a seq>1 mesh —
                     self-attention only (cross-attention KV is 77 tokens).
                     The exactness oracle for the fused kernel.
- ``"ring_flash"`` — fused Pallas ring-flash kernel
                     (ops/ring_flash_attention.py): the flash inner loop
                     with the next hop's KV shard streaming in as an async
                     remote DMA under the compute. The seq-mesh default on
                     TPU; on CPU it rides Pallas interpret mode and is
                     opt-in (explicit impl or CHIASWARM_ATTENTION) so the
                     hermetic tier keeps the cheap ppermute lowering.
- ``"auto"``       — ring_flash (TPU) / ring (elsewhere) when a
                     seq-parallel mesh is active and shapes qualify, else
                     flash on TPU when shapes qualify, else xla.
                     CHIASWARM_ATTENTION=<kind> overrides the auto pick.

All take (B, L, H, D) query / (B, S, H, D) key-value tensors and return
(B, L, H, D). Head-batched layouts keep the last dim = head_dim (128-lane
friendly) and let the kernel tile L/S onto the MXU.

Low-precision activations (ISSUE 18, the PR-8 weight-path residue): with
CHIASWARM_ACTIVATIONS=int8|fp8 the q/k/v operands pass through
convert.quantize.fake_quant_activation — per-tensor dynamic-absmax
quantize + dequant-at-use inside the traced program — BEFORE the
swarmlens taps, so a bisect of a quantized-vs-fp twin pair localizes the
first attention layer whose inputs lost too much.
"""

from __future__ import annotations

import functools
from typing import Literal

import jax
import jax.numpy as jnp

from chiaswarm_tpu.obs import numerics as _numerics

AttentionImpl = Literal["auto", "xla", "flash", "ring", "ring_flash"]

_RING_MIN_TOKENS = 1024  # same bar as the flash kernel; env-overridable

_IMPLS = ("auto", "xla", "flash", "ring", "ring_flash")


def _ring_min_tokens() -> int:
    import os

    return int(os.environ.get("CHIASWARM_RING_MIN_TOKENS", _RING_MIN_TOKENS))


def _env_impl() -> str | None:
    """CHIASWARM_ATTENTION: operator override of the ``auto`` pick (the
    attainment-sweep knob — flip kinds without touching worker config).
    Explicit ``impl=`` callers are never overridden."""
    import os

    raw = os.environ.get("CHIASWARM_ATTENTION", "").strip().lower()
    return raw if raw in _IMPLS else None


def _try_ring(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, scale: float,
              impl: str) -> jnp.ndarray | None:
    """Sequence-parallel dispatch: shard tokens over the active mesh's
    ``seq`` axis and run the ring — the fused ring-flash kernel by
    default on TPU, the ppermute scan elsewhere. None = not eligible.

    The specs compose with the other parallel axes: batch rides ``data``
    and heads ride ``model`` (Megatron head sharding) whenever divisible,
    so a dp x tp x sp mesh needs no resharding beyond the ring itself.
    Per-shard attention inside the ppermute ring is the einsum
    recurrence — local sequences are L/sp, below the flash kernel's win
    threshold; the fused kernel replaces exactly that inner loop with
    the blockwise flash recurrence and overlaps the hop DMA with it."""
    from chiaswarm_tpu.parallel.context import active_seq_mesh

    mesh = active_seq_mesh()
    if mesh is None:
        return None
    b, l, h, _ = q.shape
    if k.shape[1] != l:
        return None  # cross-attention: tiny KV, the einsum path wins
    from chiaswarm_tpu.core.mesh import DATA_AXIS, MODEL_AXIS, SEQ_AXIS

    sizes = dict(mesh.shape)
    sp = sizes.get(SEQ_AXIS, 1)
    ring_kinds = ("ring", "ring_flash")
    if l % sp or (impl not in ring_kinds and l < _ring_min_tokens()):
        return None
    from functools import partial

    from jax.sharding import PartitionSpec as P

    from chiaswarm_tpu.core.compat import shard_map

    dp, tp = sizes.get(DATA_AXIS, 1), sizes.get(MODEL_AXIS, 1)
    spec = P(DATA_AXIS if dp > 1 and b % dp == 0 else None,
             SEQ_AXIS,
             MODEL_AXIS if tp > 1 and h % tp == 0 else None,
             None)

    # kind choice inside the ring family: the fused kernel is the TPU
    # default (ROADMAP item 2 — DMA under compute); on CPU meshes auto
    # keeps the ppermute scan so the hermetic tier's seq-parallel
    # programs keep their cheap ppermute lowering, and the fused path is
    # engaged explicitly (impl="ring_flash" / CHIASWARM_ATTENTION) by
    # the parity suite, the bisect probe configs and the HLO audit.
    use_fused = (impl == "ring_flash"
                 or (impl != "ring" and _on_tpu(q)))
    if use_fused:
        from chiaswarm_tpu.core.compat import shard_map_unchecked

        from chiaswarm_tpu.ops.ring_flash_attention import (
            ring_flash_attention,
        )

        body = partial(ring_flash_attention, axis_name=SEQ_AXIS,
                       scale=scale,
                       mesh_axis_names=tuple(mesh.axis_names))
        # pallas_call has no shard_map replication rule: checking off
        fn = shard_map_unchecked(body, mesh=mesh,
                                 in_specs=(spec, spec, spec),
                                 out_specs=spec)
    else:
        from chiaswarm_tpu.parallel.ring_attention import ring_attention

        body = partial(ring_attention, axis_name=SEQ_AXIS, scale=scale)
        fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec)
    return fn(q, k, v)


def _xla_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   scale: float) -> jnp.ndarray:
    # (B, L, H, D) x (B, S, H, D) -> (B, H, L, S)
    logits = jnp.einsum("blhd,bshd->bhls", q, k,
                        preferred_element_type=jnp.float32)
    weights = jax.nn.softmax(logits * scale, axis=-1).astype(q.dtype)
    return jnp.einsum("bhls,bshd->blhd", weights, v)


@functools.lru_cache(maxsize=1)
def _flash_available() -> bool:
    try:
        from chiaswarm_tpu.ops import flash_attention  # noqa: F401
        return True
    except Exception:
        return False


def _on_tpu(x: jnp.ndarray) -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    scale: float | None = None,
    impl: AttentionImpl = "auto",
) -> jnp.ndarray:
    """Multi-head scaled dot-product attention, (B, L, H, D) layout."""
    if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
        raise ValueError(f"expected (B, L, H, D) tensors, got {q.shape}")
    if scale is None:
        scale = q.shape[-1] ** -0.5
    env_forced = False
    if impl == "auto":
        env = _env_impl()
        if env is not None:
            impl, env_forced = env, True

    # low-precision activations (CHIASWARM_ACTIVATIONS, default off):
    # identity when disabled — applied BEFORE the taps so the numerics
    # streams record what the kernels actually consumed
    from chiaswarm_tpu.convert.quantize import fake_quant_activation

    q = fake_quant_activation(q, tag="attn.q")
    k = fake_quant_activation(k, tag="attn.k")
    v = fake_quant_activation(v, tag="attn.v")

    # swarmlens (ISSUE 11): per-call-site I/O probes. ``step`` carries a
    # TRACE-time call index — twin programs trace the same module
    # structure in the same order, so call N aligns across runs (the
    # bisect drill-down from "eps diverged" to "THIS attention layer,
    # and on the input or the output side"; the driver resets the
    # counter between paired runs).
    if _numerics.enabled_for("attn"):
        idx = _numerics.TAPS.trace_seq("attn")
        q = _numerics.tap("attn.q", q, step=idx)
        k = _numerics.tap("attn.k", k, step=idx)
        v = _numerics.tap("attn.v", v, step=idx)

        def _out_tap(out: jnp.ndarray) -> jnp.ndarray:
            return _numerics.tap("attn.out", out, step=idx)
    else:
        def _out_tap(out: jnp.ndarray) -> jnp.ndarray:
            return out

    # sequence-parallel dispatch is orthogonal to the LOCAL impl choice:
    # under an active seq>1 mesh even impl="xla" callers (e.g. a
    # latency_mode worker with use_flash_attention=false) ring their
    # large self-attentions — the guards inside _try_ring keep small
    # sequences on the local paths
    out = _try_ring(q, k, v, scale, impl)
    if out is not None:
        return _out_tap(out)
    if impl in ("ring", "ring_flash"):
        from chiaswarm_tpu.parallel.context import active_seq_mesh

        if active_seq_mesh() is None and not env_forced:
            # explicit impl= is a caller contract; the env knob is
            # advisory (a fleet-wide roll must not crash workers whose
            # mesh has no seq axis — they keep their local paths)
            raise ValueError(
                f"impl={impl!r} requires an active sequence-parallel mesh "
                "(parallel.context.sequence_parallel)")
        # mesh active but shape not divisible by the seq axis:
        # correctness first, fall through to the local paths
        impl = "auto"

    use_flash = False
    if impl == "flash":
        use_flash = True
    elif impl == "auto":
        # Block-size sweep on v5e (SDXL 1024px, 30 steps, end-to-end):
        # flash@256 blocks 6.98s < XLA fused 5.07s < flash@2048x1024
        # blocks 3.98s per image. With the tuned blocks the Pallas kernel
        # wins from 1024 tokens up; tiny KV (77-token text cross-attention)
        # and small spatial grids stay on the einsum path.
        use_flash = (
            _on_tpu(q)
            and _flash_available()
            and q.shape[1] >= 1024
            and k.shape[1] >= 1024
        )

    if use_flash:
        from chiaswarm_tpu.ops.flash_attention import flash_attention

        return _out_tap(flash_attention(q, k, v, scale=scale))
    return _out_tap(_xla_attention(q, k, v, scale))
