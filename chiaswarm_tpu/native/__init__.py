"""Native (C++) host runtime — ctypes bindings with pure-Python fallback.

The artifact codec (csrc/artifact_codec.cc) natively implements the host
hot path the reference runs through Python/PIL at the GPU->host boundary
(swarm/output_processor.py:46-58,121-136): PNG encoding (measured ~2x PIL
at 1024px — the piece the envelope actually routes here), box-filter
thumbnailing, plus SHA-256 and base64 kept for completeness/testing —
the stdlib versions of those are already native and faster through
ctypes-free call paths, so the envelope uses hashlib/base64 for them.

``load()`` compiles the shared object on first use with the system g++
(no pip, no network — the image bakes the toolchain) into
``~/.cache/chiaswarm_tpu/``; import never fails — callers check
``codec() is not None`` and fall back to PIL/hashlib.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from pathlib import Path

log = logging.getLogger("chiaswarm.native")

_SOURCE = Path(__file__).resolve().parents[2] / "csrc" / "artifact_codec.cc"
_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_TRIED = False


def _cache_dir() -> Path:
    root = os.environ.get("CHIASWARM_NATIVE_CACHE")
    if root:
        return Path(root)
    return Path(os.environ.get("XDG_CACHE_HOME",
                               Path.home() / ".cache")) / "chiaswarm_tpu"


def _build(source: Path, out: Path) -> None:
    out.parent.mkdir(parents=True, exist_ok=True)
    # pid-suffixed tmp: concurrent first-use builds across processes must
    # not interleave writes; os.replace keeps the install atomic
    tmp = out.with_suffix(f".so.tmp.{os.getpid()}")
    cmd = ["g++", "-O2", "-shared", "-fPIC", str(source), "-lz",
           "-o", str(tmp)]
    try:
        # one-time cold-path compile, deliberately under _LOCK: every
        # contender needs the library and must wait for the build anyway;
        # serializing here IS the double-checked init (load() re-checks
        # _LIB/_TRIED under the same lock). Never runs on the event loop.
        # swarmlens: allow-blocking-under-lock
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)
    finally:
        tmp.unlink(missing_ok=True)


def load() -> ctypes.CDLL | None:
    """The artifact-codec library, building it on first call. None when
    the source or toolchain is unavailable (callers use the PIL path)."""
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if not _SOURCE.exists():
            log.info("native codec source not found at %s", _SOURCE)
            return None
        so = _cache_dir() / "libartifact.so"
        try:
            if (not so.exists() or
                    so.stat().st_mtime < _SOURCE.stat().st_mtime):
                _build(_SOURCE, so)
            lib = ctypes.CDLL(str(so))
        except (OSError, subprocess.SubprocessError) as exc:
            log.warning("native codec unavailable (%s); using Python path",
                        exc)
            return None

        lib.sha256_hex.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                   ctypes.c_char_p]
        lib.b64_encode.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                   ctypes.c_char_p]
        lib.b64_encode.restype = ctypes.c_uint64
        lib.thumbnail_rgb.argtypes = [ctypes.c_char_p, ctypes.c_uint32,
                                      ctypes.c_uint32, ctypes.c_uint32,
                                      ctypes.c_uint32, ctypes.c_char_p]
        lib.png_encode_rgb.argtypes = [ctypes.c_char_p, ctypes.c_uint32,
                                       ctypes.c_uint32, ctypes.c_char_p,
                                       ctypes.c_uint64]
        lib.png_encode_rgb.restype = ctypes.c_uint64
        _LIB = lib
        log.info("native artifact codec loaded from %s", so)
        return _LIB


def sha256_hex(data: bytes) -> str:
    lib = load()
    if lib is None:
        import hashlib

        return hashlib.sha256(data).hexdigest()
    out = ctypes.create_string_buffer(65)
    lib.sha256_hex(data, len(data), out)
    return out.value.decode("ascii")


def b64_encode(data: bytes) -> str:
    lib = load()
    if lib is None:
        import base64

        return base64.b64encode(data).decode("ascii")
    out = ctypes.create_string_buffer(4 * ((len(data) + 2) // 3) + 1)
    n = lib.b64_encode(data, len(data), out)
    return out.raw[:n].decode("ascii")


def png_encode_rgb(arr) -> bytes | None:
    """uint8 (H, W, 3) -> PNG bytes, or None when the native path is
    unavailable (caller falls back to PIL)."""
    import numpy as np

    lib = load()
    if lib is None:
        return None
    arr = np.ascontiguousarray(arr, dtype=np.uint8)
    h, w = arr.shape[:2]
    cap = arr.nbytes + (1 << 16)
    out = ctypes.create_string_buffer(cap)
    n = lib.png_encode_rgb(arr.ctypes.data_as(ctypes.c_char_p),
                           w, h, out, cap)
    return out.raw[:n] if n else None


def thumbnail_rgb(arr, tw: int, th: int):
    """uint8 (H, W, 3) -> uint8 (th, tw, 3), or None (caller uses PIL)."""
    import numpy as np

    lib = load()
    if lib is None:
        return None
    arr = np.ascontiguousarray(arr, dtype=np.uint8)
    h, w = arr.shape[:2]
    out = np.empty((th, tw, 3), np.uint8)
    lib.thumbnail_rgb(arr.ctypes.data_as(ctypes.c_char_p), w, h, tw, th,
                      out.ctypes.data_as(ctypes.c_char_p))
    return out
