"""chiaswarm_tpu — a TPU-native distributed generative-AI worker framework.

Brand-new JAX/XLA/Flax/Pallas implementation of the capabilities of the
chiaSWARM worker node (reference: swarm/__init__.py:1, version 0.23.6):
a stateless node that polls a central "hive" job queue over HTTP, executes
generative workloads on accelerators, and uploads base64 artifact envelopes.

Layer map (TPU-first, not a port — see SURVEY.md §7):

- ``core``       — device mesh, chip pool, RNG, compiled-pipeline cache
- ``ops``        — attention (Pallas flash attention + reference), fused ops
- ``models``     — Flax modules: CLIP/OpenCLIP text encoders, UNet, VAE,
                   ControlNet (SD 1.5 / 2.x / SDXL families)
- ``schedulers`` — jittable pure-function diffusion schedulers
                   (DDPM/DDIM/Euler/DPM-Solver++ with Karras sigmas)
- ``pipelines``  — jitted end-to-end generate functions + workload registry
- ``parallel``   — sharding rules, data/tensor/sequence parallelism,
                   ring attention, multi-host initialization
- ``node``       — async worker daemon, hive protocol client, job dispatch,
                   artifact envelope, settings
- ``convert``    — torch/safetensors checkpoint -> Flax param conversion
"""

__version__ = "0.1.0"

WORKER_VERSION = __version__
