"""Pinned-version JAX compat layer — one place that knows which APIs moved.

The repo pins jax 0.4.37 (pyproject.toml). JAX churns public surface
between minors: ``shard_map`` graduated from ``jax.experimental.shard_map``
to a top-level ``jax.shard_map`` export, Pallas modules move, and
``jax.experimental.*`` carries no stability promise at all. The seed repo
already paid for this twice — ``tests/test_parallel.py`` imported
``from jax import shard_map`` (absent on 0.4.37, poisoning the whole tier-1
collection) and ``ops/attention.py`` hand-rolled its own try/except
fallback for the same symbol.

This module is the single sanctioned crossing point:

- ``COMPAT_TABLE`` is pure data (no jax import needed to read it) and
  drives the ``compat-import`` lint rule in ``chiaswarm_tpu.analysis`` —
  any module outside this file that imports a shimmed symbol directly is
  a finding.
- The shims themselves resolve lazily via module ``__getattr__`` so that
  importing this module (e.g. from the linter, or from a host-only tool)
  never drags in the jax runtime.

Usage::

    from chiaswarm_tpu.core.compat import shard_map
"""

from __future__ import annotations

import dataclasses

#: The jax version this repo is pinned to (pyproject.toml). The compat
#: table below documents API surface relative to THIS version; bump them
#: together.
PINNED_JAX = "0.4.37"


@dataclasses.dataclass(frozen=True)
class CompatEntry:
    """One symbol whose import path differs across pinned/modern jax."""

    symbol: str           # name exported by this module
    modern: str           # import path on current jax (>= 0.6)
    pinned: str           # import path on the pinned version
    note: str = ""


#: Symbols that MUST be imported from this module rather than from jax
#: directly. Keys are ``"<module>:<name>"`` import forms that the
#: ``compat-import`` rule rejects anywhere outside this file.
COMPAT_TABLE: dict[str, CompatEntry] = {
    "jax:shard_map": CompatEntry(
        symbol="shard_map",
        modern="jax.shard_map",
        pinned="jax.experimental.shard_map.shard_map",
        note="top-level export only exists on jax >= 0.6; 0.4.x raises "
             "ImportError at collection time",
    ),
    "jax.experimental.shard_map:shard_map": CompatEntry(
        symbol="shard_map",
        modern="jax.shard_map",
        pinned="jax.experimental.shard_map.shard_map",
        note="experimental path is removed once the symbol graduates; "
             "route through compat so the repo survives an upgrade",
    ),
    "jax.lax:axis_size": CompatEntry(
        symbol="axis_size",
        modern="jax.lax.axis_size",
        pinned="jax.core.axis_frame",
        note="lax.axis_size does not exist on 0.4.x; axis_frame(name) "
             "returns the static size there (ring_attention relied on the "
             "modern name and broke every seq-parallel test on the pin)",
    ),
    # jax.profiler is stable across the pin, but serving code must still
    # cross here: the shims degrade to no-ops when the profiler plugin
    # (or jax itself) is absent, so stdlib-only observability callers
    # (chiaswarm_tpu/obs) never crash a job because tracing is broken
    "jax.profiler:trace": CompatEntry(
        symbol="profiler_trace",
        modern="jax.profiler.trace",
        pinned="jax.profiler.trace",
        note="route through compat.profiler_trace: degrades to a no-op "
             "context manager when the profiler backend is unavailable",
    ),
    "jax.profiler:TraceAnnotation": CompatEntry(
        symbol="trace_annotation",
        modern="jax.profiler.TraceAnnotation",
        pinned="jax.profiler.TraceAnnotation",
        note="route through compat.trace_annotation: degrades to a no-op "
             "when the profiler backend is unavailable",
    ),
    "jax.profiler:start_trace": CompatEntry(
        symbol="profiler_start_trace",
        modern="jax.profiler.start_trace",
        pinned="jax.profiler.start_trace",
        note="route through compat.profiler_start_trace (no-op fallback)",
    ),
    "jax.profiler:stop_trace": CompatEntry(
        symbol="profiler_stop_trace",
        modern="jax.profiler.stop_trace",
        pinned="jax.profiler.stop_trace",
        note="route through compat.profiler_stop_trace (no-op fallback)",
    ),
    # io_callback lives under jax.experimental on the pin and graduates
    # to jax.io_callback on modern jax — and it is the swarmlens
    # numerics-tap emission primitive (obs/numerics.py), so serving code
    # needs ONE sanctioned spelling that survives the move
    "jax.experimental:io_callback": CompatEntry(
        symbol="io_callback",
        modern="jax.io_callback",
        pinned="jax.experimental.io_callback",
        note="graduates out of jax.experimental on modern jax; route "
             "through compat so the numerics taps survive a pin bump",
    ),
}

#: ``jax.experimental`` submodules that modules may import at module scope
#: without a try/except guard. Everything else under ``jax.experimental``
#: must be guarded or shimmed here — the ``compat-import`` rule enforces
#: it. Pallas is allowed because ``ops.attention`` already feature-probes
#: the whole kernel module before use (``_flash_available``).
ALLOWED_EXPERIMENTAL: frozenset[str] = frozenset({
    "jax.experimental.pallas",
})


def _resolve_shard_map():
    try:  # jax >= 0.6 top-level export
        from jax import shard_map as sm
    except ImportError:  # pinned 0.4.x: experimental module
        from jax.experimental.shard_map import shard_map as sm
    if not callable(sm):  # some versions expose the MODULE at jax.shard_map
        sm = sm.shard_map
    return sm


def _resolve_axis_size():
    import jax

    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size

    def axis_size(axis_name):
        """Static size of a named mesh axis inside shard_map/pmap."""
        size = jax.core.axis_frame(axis_name)
        # modern jax returns a frame object; 0.4.x returns the int itself
        return getattr(size, "size", size)

    return axis_size


class _NoopAnnotation:
    """Stand-in for jax.profiler.TraceAnnotation when the profiler (or
    jax itself) is unavailable — observability must never fail a job."""

    def __init__(self, *_args, **_kwargs) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> bool:
        return False


def _resolve_trace_annotation():
    try:
        import jax

        return jax.profiler.TraceAnnotation
    except Exception:
        return _NoopAnnotation


def _resolve_profiler_trace():
    try:
        import jax

        return jax.profiler.trace
    except Exception:
        return _NoopAnnotation  # same no-op context-manager shape


def _resolve_profiler_start_trace():
    try:
        import jax

        return jax.profiler.start_trace
    except Exception:
        return lambda *a, **k: None


def _resolve_profiler_stop_trace():
    try:
        import jax

        return jax.profiler.stop_trace
    except Exception:
        return lambda *a, **k: None


def _resolve_io_callback():
    import jax

    if hasattr(jax, "io_callback"):  # modern jax: graduated export
        return jax.io_callback
    from jax.experimental import io_callback as cb

    return cb


def shard_map_unchecked(f, *, mesh, in_specs, out_specs):
    """``shard_map`` with replication checking off — required for bodies
    containing ``pallas_call`` (no replication rule exists for it; the
    fused ring-flash kernel and its interpret oracle both hit this).
    The kwarg is ``check_rep`` on the 0.4.x pin and ``check_vma`` on
    modern jax; this is the one sanctioned spelling of that fork."""
    sm = __getattr__("shard_map")
    try:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)
    except TypeError:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)


# ---------------------------------------------------------------------------
# hardware capability probes (ISSUE 18): not moved-symbol shims, but the
# same "one place that knows" stance — convert/quantize.py's activation
# seam asks HERE whether fp8 is usable rather than sniffing device kinds
# itself. Plain functions (not lazy attrs) so callers get a stable
# signature to mock in tests.

#: TPU generations WITHOUT native fp8 matmul support. v5p/v6e and later
#: accept float8_e4m3fn operands; older chips would silently upcast (or
#: fail to lower), so the activation seam falls back to int8 there.
_FP8_LESS_TPUS = ("v2", "v3", "v4", "v5 lite", "v5e")


def float8_dtype():
    """The fp8 activation dtype (e4m3: the forward-pass variant — more
    mantissa, the weights/activations choice in every mixed-fp8 recipe),
    or None when this jax build does not ship float8 dtypes."""
    try:
        import jax.numpy as jnp

        return jnp.float8_e4m3fn
    except Exception:
        return None


def fp8_supported() -> bool:
    """True when fp8 activations can run on the CURRENT backend: the
    dtype exists AND the accelerator has fp8 matmul units. Non-TPU
    backends (the hermetic CPU tier) count as supported when the dtype
    exists — XLA emulates the conversions, which is exactly what the
    parity tests need; the generation gate only bites on real TPUs."""
    if float8_dtype() is None:
        return False
    try:
        import jax

        if jax.default_backend() != "tpu":
            return True
        kind = jax.devices()[0].device_kind.lower()
        return not any(kind.startswith(old) or old in kind
                       for old in _FP8_LESS_TPUS)
    except Exception:
        return False


_LAZY = {
    "shard_map": _resolve_shard_map,
    "axis_size": _resolve_axis_size,
    "trace_annotation": _resolve_trace_annotation,
    "profiler_trace": _resolve_profiler_trace,
    "profiler_start_trace": _resolve_profiler_start_trace,
    "profiler_stop_trace": _resolve_profiler_stop_trace,
    "io_callback": _resolve_io_callback,
}
_cache: dict[str, object] = {}


def __getattr__(name: str):
    if name in _LAZY:
        if name not in _cache:
            _cache[name] = _LAZY[name]()
        return _cache[name]
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_LAZY))
