"""Core runtime: device mesh construction, chip pool, RNG, compile cache."""

from chiaswarm_tpu.core.mesh import MeshSpec, build_mesh, local_chip_count
from chiaswarm_tpu.core.rng import draw_seed, key_for_seed
from chiaswarm_tpu.core.chip_pool import ChipPool

__all__ = [
    "MeshSpec",
    "build_mesh",
    "local_chip_count",
    "draw_seed",
    "key_for_seed",
    "ChipPool",
]
