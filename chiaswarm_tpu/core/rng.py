"""Seed handling — replaces the reference's per-device torch.Generator
(swarm/gpu/device.py:36-41) with stateless jax.random keys.

The reference draws a fresh seed with ``torch.seed()`` when the job does not
pin one and records it into the result config so any image is reproducible
(swarm/gpu/device.py:43). We keep that contract: ``draw_seed`` produces a
uint63 seed from os.urandom, ``key_for_seed`` folds it into a PRNGKey, and
the worker records the integer seed in every artifact envelope.
"""

from __future__ import annotations

import secrets

import jax


def draw_seed() -> int:
    """A fresh non-negative 63-bit seed (json-safe, torch.seed()-like range)."""
    return secrets.randbits(63)


def key_for_seed(seed: int) -> jax.Array:
    return jax.random.PRNGKey(int(seed) & 0x7FFF_FFFF_FFFF_FFFF)


def per_sample_keys(seed: int, batch: int) -> jax.Array:
    """Independent keys per batch element so batched generation matches N
    independent single-image runs with seeds seed, seed+1, ... (host-side
    loop: batch is small and this runs once per job, outside jit)."""
    return jax.numpy.stack([key_for_seed(seed + i) for i in range(batch)])
