"""Device mesh construction — the TPU-native replacement for the reference's
per-GPU device pool (swarm/gpu/device.py, swarm/gpu/device_pool.py).

Where the reference treats each CUDA GPU as an isolated executor, a TPU pod
is a single SPMD machine: we build a ``jax.sharding.Mesh`` over the chips and
express parallelism as named axes:

- ``"data"``  — batch / job-level data parallelism (ICI all-reduce free for
  inference; gradient psum for training)
- ``"model"`` — tensor parallelism (weight sharding for models larger than
  one chip's HBM, e.g. SDXL at high batch or cascade stages)
- ``"seq"``   — sequence/context parallelism (ring attention over ICI for
  long token counts: video, long-context transformers)

Multi-host pods use ``jax.distributed.initialize`` (DCN for the control
plane, ICI for collectives) — see chiaswarm_tpu.parallel.distributed.
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"

DEFAULT_AXES = (DATA_AXIS, MODEL_AXIS, SEQ_AXIS)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """A named request for a device mesh.

    ``shape`` maps axis name -> size. Sizes of ``-1`` mean "absorb all
    remaining devices" (at most one axis may be -1). Axes not listed get
    size 1. The product must equal (or, with a -1, divide) the device count.
    """

    shape: dict[str, int] = dataclasses.field(
        default_factory=lambda: {DATA_AXIS: -1}
    )
    axis_order: Sequence[str] = DEFAULT_AXES

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = {axis: 1 for axis in self.axis_order}
        for axis, size in self.shape.items():
            if axis not in sizes:
                raise ValueError(f"unknown mesh axis {axis!r}; known: {list(sizes)}")
            sizes[axis] = size
        wildcard = [a for a, s in sizes.items() if s == -1]
        if len(wildcard) > 1:
            raise ValueError("at most one mesh axis may be -1")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wildcard:
            if n_devices % fixed:
                raise ValueError(
                    f"cannot factor {n_devices} devices into {sizes} "
                    f"(fixed product {fixed} does not divide)"
                )
            sizes[wildcard[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {sizes} wants {fixed} devices but {n_devices} are present"
            )
        return sizes


def local_chip_count() -> int:
    return jax.local_device_count()


_DEFAULT_HBM_BYTES = 16 * 1024**3  # v5e-class chip; used when stats absent
# params may take at most this fraction of a chip; the rest is activations,
# compiled executables, coalesced-batch latents, and the resident-model
# ledger headroom. Since ISSUE 8 this fraction is only the INITIAL budget
# (resident_param_budget_bytes): once models load, the residency manager
# (serving/residency.py) runs on measured footprints, and the operator
# env override below wins outright.
_PARAM_HBM_FRACTION = 0.35

ENV_RESIDENCY_BUDGET = "CHIASWARM_RESIDENCY_BUDGET"


def resident_param_budget_bytes(hbm_bytes: int | None = None) -> int:
    """Per-chip byte budget for RESIDENT model params — the single
    source both the mesh policy (below) and the residency ledger
    (serving/residency.py) plan against. ``CHIASWARM_RESIDENCY_BUDGET``
    (bytes) overrides; otherwise the classic HBM fraction applies as
    the no-model-has-loaded-yet fallback (ISSUE 8 satellite)."""
    raw = os.environ.get(ENV_RESIDENCY_BUDGET, "").strip()
    if raw:
        try:
            return max(1, int(float(raw)))
        except ValueError:
            pass  # malformed override: fall through to the fraction
    if hbm_bytes is None:
        hbm_bytes = device_hbm_bytes()
    return int(_PARAM_HBM_FRACTION * hbm_bytes)


def device_hbm_bytes(device: jax.Device | None = None) -> int:
    """Per-chip memory budget from the runtime, with a v5e default when
    the platform exposes no stats (CPU test meshes, some plugins)."""
    device = device or jax.devices()[0]
    try:
        stats = device.memory_stats() or {}
        limit = int(stats.get("bytes_limit", 0))
        if limit > 0:
            return limit
    except Exception:
        pass
    return _DEFAULT_HBM_BYTES


def derive_mesh_spec(n_devices: int,
                     heaviest_param_bytes: int | None = None,
                     hbm_bytes: int | None = None,
                     latency: bool = False) -> MeshSpec:
    """Default dp x tp (x sp) policy for a serving pool — no hand-written
    ``mesh_shape`` required.

    Data parallelism is the throughput axis (cross-job coalescing rides
    it), so everything defaults to ``data``. Tensor parallelism engages
    ONLY when the heaviest catalog family's bf16 params would not fit
    comfortably on one chip (> _PARAM_HBM_FRACTION of HBM): tp doubles —
    over power-of-two divisors of the device count — until the per-chip
    shard fits. On a v5e-8 with SDXL in the catalog (~7 GB bf16) that
    lands on dp=4 x tp=2; SD1.5-only catalogs stay dp=8.

    ``latency=True`` (settings.latency_mode) flips the trade: the leftover
    devices go to the ``seq`` axis, so every job's large spatial
    self-attention runs as sequence-parallel ring attention over ICI
    (ops/attention.py::_try_ring) — shorter per-job latency instead of
    coalesced throughput."""
    if n_devices <= 1:
        return MeshSpec({DATA_AXIS: 1})
    if hbm_bytes is None:
        hbm_bytes = device_hbm_bytes()
    budget = resident_param_budget_bytes(hbm_bytes)
    tp = 1
    if heaviest_param_bytes:
        while (heaviest_param_bytes / tp > budget
               and tp * 2 <= n_devices and n_devices % (tp * 2) == 0):
            tp *= 2
    rest = n_devices // tp
    # seq must divide the power-of-two spatial token counts (4096/1024/
    # 256/64) or _try_ring can never engage: cap it to the largest
    # power-of-two factor and return the remainder to data
    sp = rest & (-rest) if latency else 1
    if sp > 1:
        return MeshSpec({DATA_AXIS: rest // sp, MODEL_AXIS: tp,
                         SEQ_AXIS: sp})
    return MeshSpec({DATA_AXIS: rest, MODEL_AXIS: tp})


def build_mesh(
    spec: MeshSpec | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a Mesh over ``devices`` (default: all addressable devices).

    Device order follows ``jax.devices()`` which already reflects ICI
    topology locality; the trailing (fastest-varying) mesh axis therefore
    rides the tightest ICI links — put the heaviest-communication axis
    (``seq`` for ring attention, else ``model``) last via ``axis_order``.
    """
    spec = spec or MeshSpec()
    devices = list(devices) if devices is not None else list(jax.devices())
    sizes = spec.resolve(len(devices))
    axis_names = tuple(spec.axis_order)
    shape = tuple(sizes[a] for a in axis_names)
    device_array = np.asarray(devices).reshape(shape)
    return Mesh(device_array, axis_names)


def split_mesh(mesh: Mesh, n: int = 2) -> list[Mesh]:
    """Partition ``mesh``'s devices into ``n`` contiguous data-axis
    submeshes — the substrate for stage-level pipeline parallelism
    (pipelines/cascade.py::generate_stage_parallel): each pipeline stage's
    params live on its own submesh, so XLA's async dispatch runs stage k
    of item i concurrently with stage k-1 of item i+1 on disjoint chips.

    Contiguous slices follow ``jax.devices()`` order, so each submesh
    keeps the tightest ICI locality available. Requires the device count
    to divide evenly."""
    devices = mesh.devices.flatten().tolist()
    if n < 1 or len(devices) % n:
        raise ValueError(
            f"cannot split {len(devices)} devices into {n} submeshes")
    per = len(devices) // n
    return [
        build_mesh(MeshSpec({DATA_AXIS: per}),
                   devices=devices[i * per:(i + 1) * per])
        for i in range(n)
    ]


def single_device_mesh(device: jax.Device | None = None) -> Mesh:
    """A 1x1x1 mesh for one chip — lets every pipeline be written against a
    mesh unconditionally (no separate single-chip code path)."""
    device = device or jax.devices()[0]
    return build_mesh(MeshSpec({DATA_AXIS: 1, MODEL_AXIS: 1, SEQ_AXIS: 1}),
                      devices=[device])


def host_cpu_mesh(n: int = 8) -> Mesh:
    """Testing helper: a CPU mesh (requires
    XLA_FLAGS=--xla_force_host_platform_device_count=N set before jax import,
    as done in tests/conftest.py)."""
    cpus = jax.devices("cpu")
    return build_mesh(MeshSpec({DATA_AXIS: -1}), devices=cpus[:n])


def env_forced_host_devices() -> int | None:
    flags = os.environ.get("XLA_FLAGS", "")
    for token in flags.split():
        if token.startswith("--xla_force_host_platform_device_count="):
            return int(token.split("=", 1)[1])
    return None
