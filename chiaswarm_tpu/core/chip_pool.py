"""ChipPool — TPU-native replacement for the reference's GPU device layer.

The reference wraps each CUDA GPU in a ``Device`` object with a non-blocking
mutex, seed injection, and a ``"cuda:N"`` device string passed to every
workload callback (swarm/gpu/device.py:6-47). On TPU the executor is not one
chip but a *mesh slot*: the pool partitions the addressable chips into one or
more submeshes (job-level data parallelism across slots, SPMD parallelism
within a slot) and wraps each in an :class:`MeshSlot` that preserves the
reference's contract:

- non-blocking busy check (busy slot -> ``SlotBusy``),
- ``model_name`` popped from kwargs and passed positionally,
- a seed drawn when the job does not pin one, recorded into the result
  config for reproducibility (parity with swarm/gpu/device.py:36-43).

Workload callbacks keep the uniform signature of the reference
(swarm/generator.py -> swarm/job_arguments.py seam)::

    callback(slot, model_name, **kwargs) -> (artifacts dict, pipeline config)

but receive a :class:`MeshSlot` (mesh + rng + precision) instead of a device
string.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Sequence

import jax
from jax.sharding import Mesh

from chiaswarm_tpu.core.mesh import MeshSpec, build_mesh
from chiaswarm_tpu.core.rng import draw_seed, key_for_seed


class SlotBusy(RuntimeError):
    """Raised when a job is dispatched to a slot that is already executing
    at full pipeline depth (parity with the reference's non-blocking
    mutex, swarm/gpu/device.py:27-29 — generalized to a bounded counter)."""


@dataclasses.dataclass
class MeshSlot:
    """One schedulable executor: a device mesh plus per-job RNG state.

    ``depth`` is the slot's job-pipeline depth: how many jobs may be
    in flight at once. The reference's torch Device is a hard mutex
    (depth 1) because its pipelines are stateful modules; these pipelines
    are pure jitted functions, so a second job can safely tokenize and
    dispatch its program while the first drains its device->host image
    transfer — XLA serializes execution on the chip's stream and the
    overlap removes the chip-idle gap (bench.py measures it at ~+7%
    steady-state throughput on SDXL-1024). Depth 2 captures the overlap;
    deeper only grows queue latency.
    """

    index: int
    mesh: Mesh
    depth: int = 2

    def __post_init__(self) -> None:
        self._slots_free = threading.BoundedSemaphore(max(1, self.depth))

    @property
    def identifier(self) -> str:
        return f"tpu-slot:{self.index}"

    @property
    def data_width(self) -> int:
        """Size of the mesh's ``data`` axis (1 when absent) — how many
        batch rows execute in parallel; drives queue sizing and the
        cross-job coalescing burst size (node/worker.py)."""
        return int(dict(zip(self.mesh.axis_names,
                            self.mesh.devices.shape)).get("data", 1))

    def descriptor(self) -> dict[str, Any]:
        devices = self.mesh.devices.flatten().tolist()
        dev0 = devices[0]
        return {
            "slot": self.index,
            "platform": dev0.platform,
            "device_kind": dev0.device_kind,
            "chips": len(devices),
            "mesh_shape": dict(zip(self.mesh.axis_names, self.mesh.devices.shape)),
        }

    def __call__(self, callback: Callable[..., tuple[dict, dict]], **kwargs):
        """Run ``callback`` on this slot, injecting seed + mesh.

        Mirrors Device.__call__ (swarm/gpu/device.py:26-47): non-blocking
        acquire, seed bookkeeping, model_name passed positionally.
        """
        if not self._slots_free.acquire(blocking=False):
            raise SlotBusy(f"{self.identifier} is busy")
        try:
            model_name = kwargs.pop("model_name", None)
            seed = kwargs.pop("seed", None)
            if seed is None:
                seed = draw_seed()
            seed = int(seed)
            artifacts, config = callback(
                self, model_name, seed=seed, **kwargs
            )
            config = dict(config)
            config["seed"] = seed
            return artifacts, config
        finally:
            self._slots_free.release()

    def call_multi(self, callback: Callable[..., list], **kwargs) -> list:
        """``__call__`` variant for coalesced callbacks that return a
        LIST of per-job (artifacts, config) — per-job seeds ride inside
        ``kwargs["jobs"]`` and each config already records its own seed
        (node/executor.py::synchronous_do_work_batch)."""
        if not self._slots_free.acquire(blocking=False):
            raise SlotBusy(f"{self.identifier} is busy")
        try:
            model_name = kwargs.pop("model_name", None)
            seed = int(kwargs.pop("seed", 0))
            outs = callback(self, model_name, seed=seed, **kwargs)
            return [(artifacts, dict(config)) for artifacts, config in outs]
        finally:
            self._slots_free.release()

    def rng(self, seed: int) -> jax.Array:
        return key_for_seed(seed)


class ChipPool:
    """Partition the addressable chips into ``n_slots`` mesh slots.

    ``n_slots=1`` (default) gives one pod-wide SPMD slot — the idiomatic TPU
    shape, where a whole batch of jobs is executed as one sharded program.
    ``n_slots=len(devices)`` reproduces the reference's one-job-per-device
    scheduling for latency-sensitive mixed workloads.
    """

    def __init__(
        self,
        n_slots: int = 1,
        mesh_spec: MeshSpec | None = None,
        devices: Sequence[jax.Device] | None = None,
        depth: int = 2,
    ) -> None:
        devices = list(devices) if devices is not None else list(jax.devices())
        if n_slots < 1 or len(devices) % n_slots:
            raise ValueError(
                f"cannot split {len(devices)} chips into {n_slots} slots"
            )
        per_slot = len(devices) // n_slots
        self.slots = [
            MeshSlot(
                index=i,
                mesh=build_mesh(mesh_spec, devices=devices[i * per_slot:(i + 1) * per_slot]),
                depth=depth,
            )
            for i in range(n_slots)
        ]

    def __len__(self) -> int:
        return len(self.slots)

    def __iter__(self):
        return iter(self.slots)

    def descriptor(self) -> list[dict[str, Any]]:
        return [slot.descriptor() for slot in self.slots]
