"""Resident compiled-pipeline cache with shape bucketing.

The reference reloads model weights from disk on every job
(swarm/diffusion/diffusion_func.py:41-46) — tolerable on CUDA where module
construction is cheap. On TPU, XLA compilation dominates: recompiling a
denoise loop per job (or per odd image size) is fatal to throughput. This
component has no reference analog and exists precisely because of the XLA
compilation model (SURVEY.md §7 "hard parts" #3):

- **Shape bucketing**: arbitrary requested resolutions/batch sizes snap to a
  small lattice of compiled shapes (latent sizes multiple of 64px at the
  image level, batch in powers of two). One compiled executable serves every
  job that lands in its bucket.
- **Param residency**: converted model weights stay on device between jobs,
  keyed by (model_name, dtype), LRU-evicted under an HBM budget.
- **Executable LRU**: jitted pipeline callables keyed by
  (model key, static config, bucketed shapes).

Thread-safe; the worker's executor threads share one cache per process.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Any, Callable, Hashable

from chiaswarm_tpu.obs.metrics import REGISTRY

_POW2 = (1, 2, 4, 8, 16, 32, 64, 128)

# ---- swarmscope hooks (chiaswarm_tpu/obs) ---------------------------------
# A runtime recompile is the R6 lint hazard made flesh: a shape/config that
# escaped the bucketing lattice silently costs seconds-to-minutes of chip
# time. These counters make every executable-cache miss — and the duration
# of the compile it triggered — visible on /metrics, labeled by the program
# tag (generate / stepper_step / encode / ...).

_CACHE_HITS = REGISTRY.counter(
    "chiaswarm_compile_cache_hits_total",
    "compile-cache lookups served from residency",
    labelnames=("cache", "tag"))
_CACHE_MISSES = REGISTRY.counter(
    "chiaswarm_compile_cache_misses_total",
    "compile-cache misses (each one built/loaded its value)",
    labelnames=("cache", "tag"))
_BUILD_SECONDS = REGISTRY.histogram(
    "chiaswarm_compile_cache_build_seconds",
    "time spent building a missed cache entry (trace/convert/load)",
    labelnames=("cache", "tag"))
_COMPILE_SECONDS = REGISTRY.histogram(
    "chiaswarm_compile_seconds",
    "first-call duration of a freshly built executable — the XLA "
    "trace+compile cost a cache miss actually paid",
    labelnames=("tag",))
_COMPILES = REGISTRY.counter(
    "chiaswarm_compiles_total",
    "executables compiled at runtime (cache-miss first calls); a "
    "nonzero rate after warmup means a shape escaped the buckets (R6)",
    labelnames=("tag",))


def _key_tag(key: Hashable) -> str:
    """Program tag from a static_cache_key-shaped key (owner, tag, ...);
    foreign key shapes fall into one bucket."""
    if isinstance(key, tuple) and len(key) >= 2 and isinstance(key[1], str):
        return key[1]
    return "other"


def _instrument_executable(fn: Any, tag: str) -> Any:
    """Time a fresh executable's FIRST call into the compile histogram.

    jax.jit compiles lazily, so the LRU-miss factory only builds the
    wrapper — the XLA work happens on first invocation. The first call
    includes one execution too; compile dominates it by orders of
    magnitude on real programs, and one timed call per executable
    lifetime costs nothing after."""
    if not callable(fn):
        return fn
    state = {"timed": False}

    def wrapped(*args: Any, **kwargs: Any) -> Any:
        if state["timed"]:
            return fn(*args, **kwargs)
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        state["timed"] = True  # benign race: worst case two observations
        _COMPILE_SECONDS.observe(time.perf_counter() - t0, tag=tag)
        _COMPILES.inc(tag=tag)
        return out

    return wrapped


def xla_compiler_options() -> dict[str, str] | None:
    """Extra per-executable XLA:TPU compiler options from the
    ``CHIASWARM_XLA_OPTIONS`` env var ("key=value,key2=value2").

    Passed as ``compiler_options`` to the pipelines' TOP-LEVEL ``jax.jit``
    calls (nested jits reject them). The main production knob is
    ``xla_tpu_scoped_vmem_limit_kib`` — the default ~16 MiB scoped VMEM
    caps the flash-attention block sweep and conv fusion buffer sizes
    (BASELINE.md block-size table)."""
    import os

    raw = os.environ.get("CHIASWARM_XLA_OPTIONS", "").strip()
    if not raw:
        return None
    return dict(kv.split("=", 1) for kv in raw.split(",") if "=" in kv)


#: Env knobs that change what a pipeline TRACES (swarmkey / ISSUE 20):
#: attention impl selection and ring threshold are read at trace time
#: (ops/attention.py), the flash block/VMEM knobs are frozen into module
#: constants at import (ops/flash_attention.py), ring-flash mode picks
#: the fused vs scan program (ops/ring_flash_attention.py), and the XLA
#: options change the compiled artifact itself. Every name here is
#: folded into static_cache_key ONLY-WHEN-SET — with all knobs unset the
#: key stays byte-identical to the historical tuple, so default
#: deployments keep every warm slot (the taps-off stance from ISSUE 11).
#: CHIASWARM_NUMERICS / CHIASWARM_ACTIVATIONS are deliberately absent:
#: those already fold their own richer fingerprints conditionally below.
_TRACE_ENV_KNOBS = (
    "CHIASWARM_ATTENTION",
    "CHIASWARM_RING_MIN_TOKENS",
    "CHIASWARM_RING_FLASH",
    "CHIASWARM_FLASH_BLOCK_Q",
    "CHIASWARM_FLASH_BLOCK_KV",
    "CHIASWARM_FLASH_VMEM_MB",
    "CHIASWARM_XLA_OPTIONS",
)


def _trace_knobs() -> tuple:
    """The set-and-nonempty trace-affecting knobs as a sorted-by-table
    ((name, value), ...) vector — empty tuple in a default environment,
    so callers can fold it only-when-set."""
    import os

    return tuple((name, os.environ[name].strip())
                 for name in _TRACE_ENV_KNOBS
                 if os.environ.get(name, "").strip())


def cache_fingerprint() -> tuple:
    """Cross-process executable-identity handle for the AOT artifact
    cache (ROADMAP item 5): compiler provenance (jax/jaxlib/plugin
    versions) plus the trace-affecting knob vector.

    The in-process key (``static_cache_key``) may embed ``id()``-based
    owners — stable within a process, meaningless outside it. A
    serialized artifact needs the opposite: every component stable
    across processes and machines (R20's jurisdiction). Versions come
    from package metadata, not ``jax.__version__``, so the lint tier can
    import this module without jax."""
    import importlib.metadata

    versions = []
    for dist in ("jax", "jaxlib", "libtpu", "libtpu-nightly"):
        try:
            versions.append((dist, importlib.metadata.version(dist)))
        except Exception:  # absent plugin: fingerprint just omits it
            continue
    return ("chiaswarm-exec-v1", tuple(versions), ("knobs", _trace_knobs()))


def artifact_cache_key(tag: str, static: dict) -> tuple:
    """Content-addressed key for a SHIPPED executable artifact: the
    persistent fingerprint plus the owner-free static key. The
    in-process owner id is dropped by construction — it can never leak
    into a serialized artifact's identity."""
    return (cache_fingerprint(),) + static_cache_key(0, tag, static)[1:]


def toplevel_jit(fn, **kwargs):
    """``jax.jit`` for the pipelines' end-to-end programs, with the
    env-configured compiler options applied."""
    import jax

    opts = xla_compiler_options()
    if opts:
        kwargs.setdefault("compiler_options", opts)
    return jax.jit(fn, **kwargs)


def enable_persistent_compilation_cache(cache_dir: str | None = None) -> None:
    """Point XLA's persistent compilation cache at a durable directory.

    The in-process LRU below amortizes compiles within one worker
    lifetime; this amortizes them ACROSS restarts — SDXL-1024 first
    compile is minutes on a tunneled chip, a cached reload is seconds.
    Idempotent and safe to call before or after backend init."""
    import os

    import jax

    cache_dir = cache_dir or os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.expanduser("~/.cache/chiaswarm_tpu/xla"))
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    except Exception:  # never let cache wiring break startup
        pass


def static_cache_key(owner: int, tag: str, static: dict) -> tuple:
    """Hashable executable-cache key from a pipeline's static build args.

    Shared by every pipeline's ``_get_fn`` (diffusion/upscale/cascade/
    audio) so dataclass-valued statics (sampler configs, ...) normalize the
    same way everywhere — including nested dataclasses and containers.

    swarmlens (ISSUE 11): while ``CHIASWARM_NUMERICS`` enables any
    probe, the live tap fingerprint is appended — a program traced with
    taps must never be served to (or from) a taps-off cache slot, and a
    probe-filter change retraces. With numerics OFF (the default) the
    key is byte-identical to the historical 3-tuple, so the taps-off
    invariance gate can hold trivially."""

    def norm(v: Any) -> Hashable:
        if dataclasses.is_dataclass(v) and not isinstance(v, type):
            return tuple(sorted(
                (f.name, norm(getattr(v, f.name)))
                for f in dataclasses.fields(v)))
        if isinstance(v, dict):
            return tuple(sorted((k, norm(x)) for k, x in v.items()))
        if isinstance(v, (list, tuple)):
            return tuple(norm(x) for x in v)
        return v

    key = (owner, tag, tuple(sorted((k, norm(v))
                                    for k, v in static.items())))
    from chiaswarm_tpu.obs import numerics

    if numerics.enabled():
        key = key + (("numerics", numerics.fingerprint()),)

    # low-precision activations (ISSUE 18): same stance as the numerics
    # fingerprint — CHIASWARM_ACTIVATIONS changes what the program
    # traces (fake-quant seams at attention q/k/v and the UNet block
    # inputs), so an enabled format must never share an executable slot
    # with the fp trace; with the knob OFF the key stays byte-identical
    from chiaswarm_tpu.convert import quantize

    if quantize.activations_enabled():
        key = key + (("activations", quantize.activations_format()),)

    # trace-affecting env knobs (swarmkey / ISSUE 20): same only-when-set
    # stance — a knob flip must retrace, a default environment must keep
    # its historical byte-identical key (and every warm slot with it)
    knobs = _trace_knobs()
    if knobs:
        key = key + (("knobs", knobs),)
    return key


def bucket_batch(n: int) -> int:
    """Round batch up to the next power of two (caps recompiles at
    log2(max_batch) executables per pipeline)."""
    if n < 1:
        raise ValueError("batch must be >= 1")
    for p in _POW2:
        if n <= p:
            return p
    raise ValueError(f"batch {n} exceeds supported maximum {_POW2[-1]}")


_STEP_BUCKETS = (16, 32, 64, 128)


def bucket_steps(n: int) -> int:
    """Round a denoise step count up to the lane capacity lattice.

    The step scheduler (serving/stepper.py) compiles ONE resident step
    program per lane whose per-row sigma/timestep tables are sized to
    this capacity; bucketing keeps the lane-program count bounded while
    letting jobs with different step counts share a lane. The step
    program executes one step per call, so capacity padding costs table
    memory only — never compute."""
    if n < 1:
        raise ValueError("steps must be >= 1")
    for cap in _STEP_BUCKETS:
        if n <= cap:
            return cap
    raise ValueError(
        f"steps {n} exceeds the lane capacity maximum {_STEP_BUCKETS[-1]}")


def bucket_image_size(height: int, width: int, *, multiple: int = 64,
                      min_size: int = 64, max_size: int = 1024) -> tuple[int, int]:
    """Snap a requested image size onto the compiled lattice.

    Mirrors the reference's size clamp (swarm/job_arguments.py:14,96-102 caps
    at 1024x1024; small sizes are honored — only a MAX clamp exists there)
    but additionally quantizes to ``multiple`` so XLA sees a bounded shape
    set. Images are generated at the bucketed size and
    center-cropped/resized on host to the exact request when they differ.
    ``multiple=64`` keeps SD latents divisible by 8, so any bucket survives
    the UNet's downsampling path.
    """

    def snap(v: int) -> int:
        v = max(min_size, min(max_size, v))
        return ((v + multiple - 1) // multiple) * multiple

    return snap(height), snap(width)


@dataclasses.dataclass
class _Entry:
    value: Any
    size_bytes: int


class LruCache:
    """A byte-budgeted LRU used for both param trees and executables."""

    def __init__(self, budget_bytes: int | None = None, max_items: int | None = None,
                 kind: str = "cache"):
        self._budget = budget_bytes
        self._max_items = max_items
        self._kind = kind  # /metrics label: "params" / "executables"
        self._entries: collections.OrderedDict[Hashable, _Entry] = collections.OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get_or_create(self, key: Hashable, factory: Callable[[], Any],
                      size_bytes: int = 0,
                      size_of: Callable[[Any], int] | None = None) -> Any:
        """``size_of`` computes the entry's byte size from the built value
        (for factories whose footprint is only known after loading)."""
        tag = _key_tag(key)
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                hit = True
            else:
                self.misses += 1
                hit = False
        if hit:
            _CACHE_HITS.inc(cache=self._kind, tag=tag)
            return entry.value
        _CACHE_MISSES.inc(cache=self._kind, tag=tag)
        # Build outside the lock: factories compile/convert and can take
        # minutes; concurrent misses on the *same* key are rare (jobs for one
        # model serialize on the slot) and harmless (last write wins).
        t0 = time.perf_counter()
        value = factory()
        _BUILD_SECONDS.observe(time.perf_counter() - t0,
                               cache=self._kind, tag=tag)
        if size_of is not None:
            size_bytes = size_of(value)
        with self._lock:
            existing = self._entries.get(key)
            if existing is not None:
                # concurrent miss on the same key: keep the first result and
                # drop ours, so byte accounting stays exact.
                self._entries.move_to_end(key)
                return existing.value
            self._entries[key] = _Entry(value, size_bytes)
            self._bytes += size_bytes
            self._evict_locked()
        return value

    def _evict_locked(self) -> None:
        while self._entries and (
            (self._budget is not None and self._bytes > self._budget)
            or (self._max_items is not None and len(self._entries) > self._max_items)
        ):
            if len(self._entries) == 1:
                break  # never evict the entry we just inserted
            _, entry = self._entries.popitem(last=False)
            self._bytes -= entry.size_bytes

    def drop_where(self, predicate: Callable[[Hashable], bool]) -> int:
        """Drop every entry whose KEY satisfies ``predicate``; returns
        the count. The residency manager (serving/residency.py) uses
        this to purge a released load-per-job model's executables —
        keyed by the dead components' ``id()``, they can never hit
        again and would otherwise thrash hot models out of the bounded
        executable LRU."""
        with self._lock:
            doomed = [k for k in self._entries if predicate(k)]
            for key in doomed:
                entry = self._entries.pop(key)
                self._bytes -= entry.size_bytes
        return len(doomed)

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def stats(self) -> dict[str, int]:
        return {
            "items": len(self._entries),
            "bytes": self._bytes,
            "hits": self.hits,
            "misses": self.misses,
        }


class CompileCache:
    """Process-wide residency for params and compiled pipelines.

    Since ISSUE 8, MODEL param residency is owned by the measured-ledger
    ``serving/residency.py::ResidencyManager`` (the registry routes every
    pipeline load through it); the byte-budgeted ``params`` LRU below
    remains for non-registry callers and API compatibility. Compiled
    executables stay here — they are per-process like before."""

    def __init__(self, param_budget_bytes: int = 24 * 1024**3,
                 max_executables: int = 16) -> None:
        self.params = LruCache(budget_bytes=param_budget_bytes,
                               kind="params")
        self.executables = LruCache(max_items=max_executables,
                                    kind="executables")

    def cached_params(self, key: Hashable, loader: Callable[[], Any],
                      size_bytes: int = 0,
                      size_of: Callable[[Any], int] | None = None) -> Any:
        return self.params.get_or_create(key, loader, size_bytes, size_of)

    def cached_executable(self, key: Hashable, builder: Callable[[], Any]) -> Any:
        # the first call of a fresh executable pays the lazy XLA compile;
        # _instrument_executable times exactly that call into /metrics
        return self.executables.get_or_create(
            key, lambda: _instrument_executable(builder(), _key_tag(key)))

    def flush_executables(self) -> int:
        """Drop EVERY cached executable (the guard's cache-flush heal
        rung, serving/guard.py): a sick device can serve a corrupted
        compiled program, and recompiling fresh is the cheapest rung
        above a lane rebuild. Params stay resident — the corruption
        mode this rung targets is the executable, not the weights.
        Returns the number dropped; the next calls recompile (or reload
        from the persistent XLA cache)."""
        return self.executables.drop_where(lambda _key: True)


GLOBAL_CACHE = CompileCache()
