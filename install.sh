#!/usr/bin/env bash
# swarm-tpu installer for TPU VMs and dev hosts (parity with the
# reference's install.sh venv bootstrap, /root/reference install.sh:1-232).
#
# Usage:  ./install.sh [--cpu]
#   --cpu   install the CPU jax backend (dev machines without a TPU)

set -euo pipefail

PYTHON=${PYTHON:-python3}
VENV_DIR=${VENV_DIR:-.venv}
BACKEND=tpu
[[ "${1:-}" == "--cpu" ]] && BACKEND=cpu

command -v "$PYTHON" >/dev/null || { echo "python3 not found"; exit 1; }
"$PYTHON" - <<'EOF' || { echo "python >= 3.10 required"; exit 1; }
import sys
sys.exit(0 if sys.version_info >= (3, 10) else 1)
EOF

echo "==> creating venv at $VENV_DIR"
"$PYTHON" -m venv "$VENV_DIR"
# shellcheck disable=SC1091
source "$VENV_DIR/bin/activate"
pip install --upgrade pip >/dev/null

echo "==> installing swarm-tpu ($BACKEND backend; deps from pyproject.toml)"
if [[ "$BACKEND" == "tpu" ]]; then
    pip install -e ".[tpu,test]" \
        -f https://storage.googleapis.com/jax-releases/libtpu_releases.html
else
    pip install -e ".[cpu,test]"
fi

echo "==> building native artifact codec"
python -c "from chiaswarm_tpu import native; print('native codec:', bool(native.load()))"

echo
echo "Done. Next steps:"
echo "  source $VENV_DIR/bin/activate"
echo "  python -m chiaswarm_tpu.cli init     # configure hive + prefetch models"
echo "  python -m chiaswarm_tpu.cli worker   # join the swarm"
