"""Two-process jax.distributed pod-mode test (SURVEY §2c multi-host).

Spawns two real OS processes on the CPU platform, each calling
``parallel/distributed.py::init_pod`` against a localhost coordinator,
builds the global 2-device mesh, and asserts a cross-process ``psum``
reduces over BOTH processes' values — the DCN-equivalent collective path
exercised for real rather than via the single-process fallback.

The subprocesses run outside the parent's jax runtime (the parent's CPU
platform is already initialized with 8 virtual devices; children get one
CPU device each).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

_CHILD = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, os.environ["_REPO"])
    import jax
    jax.config.update("jax_platforms", "cpu")

    from chiaswarm_tpu.parallel.distributed import (
        init_pod, is_multi_host, local_data_shard,
    )

    pid = int(os.environ["PROCESS_ID"])
    init_pod()  # env contract: COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID

    assert jax.process_count() == 2, jax.process_count()
    assert jax.process_index() == pid, (jax.process_index(), pid)
    assert is_multi_host()
    assert local_data_shard(8) == (pid * 4, 4)

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = np.asarray(jax.devices())  # 2 global devices, 1 per process
    assert len(devices) == 2, devices
    mesh = Mesh(devices.reshape(2), ("data",))

    # each process contributes its own value; psum must see both
    local = jnp.full((1, 4), float(pid + 1))
    arr = jax.make_array_from_single_device_arrays(
        (2, 4), NamedSharding(mesh, P("data", None)),
        [jax.device_put(local, jax.local_devices()[0])])

    # global sum over the process-spanning array — XLA inserts the
    # cross-process all-reduce (the DCN collective path in production)
    s = float(jax.jit(jnp.sum)(arr))
    assert s == (1.0 + 2.0) * 4, s

    # explicit psum through shard_map over the global mesh
    from chiaswarm_tpu.core.compat import shard_map
    ps = shard_map(
        lambda v: jax.lax.psum(v, "data"), mesh=mesh,
        in_specs=P("data", None), out_specs=P(None, None),
    )
    tot = jax.jit(ps)(arr)
    local_tot = np.asarray(
        [sh.data for sh in tot.addressable_shards][0])
    assert (local_tot == 3.0).all(), local_tot
    print(f"OK process {pid}: global sum {s}")
""")


@pytest.mark.skipif(os.environ.get("CHIASWARM_SKIP_MULTIPROC") == "1",
                    reason="multi-process test disabled")
def test_two_process_pod_psum(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    repo = str(Path(__file__).resolve().parent.parent)
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)  # no TPU plugin in children
        env.pop("XLA_FLAGS", None)             # 1 CPU device per process
        env.update({
            "_REPO": repo,
            "JAX_PLATFORMS": "cpu",
            "COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "NUM_PROCESSES": "2",
            "PROCESS_ID": str(pid),
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _CHILD], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))

    outputs = []
    for pid, proc in enumerate(procs):
        try:
            out, _ = proc.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail(f"process {pid} timed out")
        outputs.append(out)
    for pid, (proc, out) in enumerate(zip(procs, outputs)):
        assert proc.returncode == 0, f"process {pid} failed:\n{out}"
        assert f"OK process {pid}" in out
