"""Hermetic test config: force an 8-device CPU platform BEFORE jax imports,
so multi-chip mesh/sharding code is exercised without a TPU (SURVEY.md §4)."""

import os
import tempfile

# session-level settings-root isolation: the process-global residency
# manager (serving/residency.py, ISSUE 8) persists measured footprints
# under settings_root() at its FIRST registry construction — without
# this default, any test building a ModelRegistry before a per-test
# SWARM_TPU_ROOT fixture runs would write tiny/random-model footprints
# into the operator's real ~/.swarm-tpu/residency.json. Tests that set
# their own root (monkeypatch.setenv) still override per-test.
os.environ.setdefault(
    "SWARM_TPU_ROOT", tempfile.mkdtemp(prefix="swarm-tpu-test-root-"))

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The container's sitecustomize preloads jax with a TPU plugin before any
# conftest runs; re-pointing the config re-selects the backend (lazy CPU
# client init still honors the XLA_FLAGS set above).
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "float32")

# persistent XLA compile cache: the suite is compile-bound on one CPU core;
# warm reruns skip most of that
from chiaswarm_tpu.core.compile_cache import (  # noqa: E402
    enable_persistent_compilation_cache,
)

enable_persistent_compilation_cache()
# the suite is dominated by many SMALL compiles (tiny families, one
# program per test parameterization) — persist nearly all of them, not
# just the >2s ones the serving default targets
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)

import pytest  # noqa: E402

# ---- teardown-hang fix (VERDICT r2 weak #8) ---------------------------
# jax registers an atexit clean_up whose clear_backends() blocks for ~10
# minutes on this host's remote-TPU-plugin jax build, so the process
# lingers long after the summary line. atexit runs LIFO: this handler is
# registered AFTER jax's (sitecustomize imports jax at interpreter
# start), so it runs FIRST — flush the already-printed summary and exit
# with pytest's real status, skipping the hanging backend teardown.
import atexit  # noqa: E402
import os as _os  # noqa: E402
import sys as _sys  # noqa: E402

_SESSION_STATUS = {"code": 0}


def pytest_sessionfinish(session, exitstatus):
    _SESSION_STATUS["code"] = int(exitstatus)


if _os.environ.get("PALLAS_AXON_POOL_IPS"):
    # only on hosts running the remote-TPU-plugin jax build — a normal
    # install must keep its full atexit chain (coverage data saves, etc.)
    @atexit.register
    def _skip_hanging_backend_teardown():
        _sys.stdout.flush()
        _sys.stderr.flush()
        _os._exit(_SESSION_STATUS["code"])


# ---- fast / slow tiers (VERDICT r3 weak #4) ---------------------------
# Default `pytest -q` runs the fast tier; the ~10 compile-heaviest tests
# are marked `slow` and run with --slow (or CHIASWARM_SLOW=1) — the
# nightly-CI tier (.github/workflows/test.yml).


def pytest_addoption(parser):
    parser.addoption(
        "--slow", action="store_true", default=False,
        help="also run tests marked slow (full tier; nightly CI)")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: compile-heavy test, excluded from the default fast tier "
        "(run with --slow or CHIASWARM_SLOW=1)")
    config.addinivalue_line(
        "markers",
        "solo: exercises the per-job (non-lane) path — the CI "
        "stepper-off leg re-runs this subset with CHIASWARM_STEPPER=0")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--slow") or _os.environ.get("CHIASWARM_SLOW"):
        return
    skip = pytest.mark.skip(
        reason="slow tier: run with --slow or CHIASWARM_SLOW=1")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(scope="session")
def cpu_devices():
    return jax.devices("cpu")


@pytest.fixture(scope="session")
def mesh8():
    from chiaswarm_tpu.core.mesh import MeshSpec, build_mesh

    return build_mesh(MeshSpec({"data": 4, "model": 2}))
