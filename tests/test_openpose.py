"""OpenPose preprocessor tests: network fidelity vs a torch reference,
PAF assembly on synthetic fields, and the end-to-end skeleton render.

The reference gets skeletons from controlnet_aux's OpenposeDetector
(swarm/controlnet/input_processor.py:17-60); these tests pin the native
reimplementation (models/openpose.py) to the same CMU graph semantics.
"""

from __future__ import annotations

import numpy as np
import pytest

from chiaswarm_tpu.models.openpose import (
    LIMB_SEQ,
    MAP_IDX,
    N_HEAT,
    N_PAF,
    OpenposeDetector,
    assemble_people,
    draw_skeletons,
    find_peaks,
    score_limbs,
)


@pytest.mark.slow
def test_network_output_shapes():
    det = OpenposeDetector.random(seed=0)
    import jax.numpy as jnp

    paf, heat = det._fwd(det.params, jnp.zeros((1, 64, 48, 3)))
    assert paf.shape == (1, 8, 6, N_PAF)
    assert heat.shape == (1, 8, 6, N_HEAT)


def _torch_body_net():
    """Independent torch construction of the CMU graph (controlnet_aux
    layout) for conversion fidelity."""
    torch = pytest.importorskip("torch")
    import collections

    import torch.nn as nn

    def conv(i, o, k):
        return nn.Conv2d(i, o, k, padding=k // 2)

    def seq(defs):
        layers = collections.OrderedDict()
        for name, mod in defs:
            layers[name] = mod
        return nn.Sequential(layers)

    class Body(nn.Module):
        def __init__(self):
            super().__init__()
            R = nn.ReLU(inplace=False)
            P = nn.MaxPool2d(2, 2)
            self.model0 = seq([
                ("conv1_1", conv(3, 64, 3)), ("r1", R),
                ("conv1_2", conv(64, 64, 3)), ("r2", R), ("p1", P),
                ("conv2_1", conv(64, 128, 3)), ("r3", R),
                ("conv2_2", conv(128, 128, 3)), ("r4", R), ("p2", P),
                ("conv3_1", conv(128, 256, 3)), ("r5", R),
                ("conv3_2", conv(256, 256, 3)), ("r6", R),
                ("conv3_3", conv(256, 256, 3)), ("r7", R),
                ("conv3_4", conv(256, 256, 3)), ("r8", R), ("p3", P),
                ("conv4_1", conv(256, 512, 3)), ("r9", R),
                ("conv4_2", conv(512, 512, 3)), ("r10", R),
                ("conv4_3_CPM", conv(512, 256, 3)), ("r11", R),
                ("conv4_4_CPM", conv(256, 128, 3)), ("r12", R),
            ])

            def stage1(branch, out):
                return seq([
                    (f"conv5_1_CPM_L{branch}", conv(128, 128, 3)), ("a", R),
                    (f"conv5_2_CPM_L{branch}", conv(128, 128, 3)), ("b", R),
                    (f"conv5_3_CPM_L{branch}", conv(128, 128, 3)), ("c", R),
                    (f"conv5_4_CPM_L{branch}", conv(128, 512, 1)), ("d", R),
                    (f"conv5_5_CPM_L{branch}", conv(512, out, 1)),
                ])

            def stage_t(t, branch, out):
                defs = []
                ch_in = 185
                for i in (1, 2, 3, 4, 5):
                    defs += [(f"Mconv{i}_stage{t}_L{branch}",
                              conv(ch_in, 128, 7)), (f"r{i}", R)]
                    ch_in = 128
                defs += [(f"Mconv6_stage{t}_L{branch}", conv(128, 128, 1)),
                         ("r6", R),
                         (f"Mconv7_stage{t}_L{branch}", conv(128, out, 1))]
                return seq(defs)

            self.model1_1 = stage1(1, 38)
            self.model1_2 = stage1(2, 19)
            for t in range(2, 7):
                setattr(self, f"model{t}_1", stage_t(t, 1, 38))
                setattr(self, f"model{t}_2", stage_t(t, 2, 19))

        def forward(self, x):
            feat = self.model0(x)
            paf, heat = self.model1_1(feat), self.model1_2(feat)
            for t in range(2, 7):
                inp = torch.cat([paf, heat, feat], dim=1)
                paf = getattr(self, f"model{t}_1")(inp)
                heat = getattr(self, f"model{t}_2")(inp)
            return paf, heat

    torch.manual_seed(0)
    return torch, Body().eval()


def test_conversion_matches_torch_reference():
    torch, body = _torch_body_net()
    import jax.numpy as jnp

    from chiaswarm_tpu.convert.torch_to_flax import convert_openpose

    state = {k: v.detach().numpy() for k, v in body.state_dict().items()}
    det = OpenposeDetector(params=convert_openpose(state))

    x = np.random.RandomState(1).randn(1, 32, 32, 3).astype(np.float32) * 0.3
    with torch.no_grad():
        tp, th = body(torch.from_numpy(x.transpose(0, 3, 1, 2)))
    fp, fh = det._fwd(det.params, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(fp),
                               tp.numpy().transpose(0, 2, 3, 1),
                               atol=2e-4, rtol=2e-3)
    np.testing.assert_allclose(np.asarray(fh),
                               th.numpy().transpose(0, 2, 3, 1),
                               atol=2e-4, rtol=2e-3)


def test_converter_rejects_wrong_state():
    from chiaswarm_tpu.convert.torch_to_flax import convert_openpose

    with pytest.raises(ValueError, match="expected 92"):
        convert_openpose({"model0.conv1_1.weight": np.zeros((64, 3, 3, 3)),
                          "model0.conv1_1.bias": np.zeros(64)})


def _synthetic_fields(h=64, w=64):
    """Heatmaps/PAF for one person: neck (joint 1) at (20, 32) and right
    shoulder (joint 2) at (44, 32), with the matching PAF painted along
    the connecting line."""
    heat = np.zeros((h, w, N_HEAT), np.float32)
    paf = np.zeros((h, w, N_PAF), np.float32)
    a, b = (20, 32), (44, 32)  # (x, y)
    yy, xx = np.mgrid[0:h, 0:w]
    for joint, (px, py) in ((1, a), (2, b)):
        heat[:, :, joint] = np.exp(-((xx - px) ** 2 + (yy - py) ** 2) / 18.0)
    k = LIMB_SEQ.index((1, 2))
    cx, cy = MAP_IDX[k][0] - 19, MAP_IDX[k][1] - 19
    on_line = (np.abs(yy - 32) <= 2) & (xx >= a[0]) & (xx <= b[0])
    paf[:, :, cx] = on_line * 1.0   # unit vector +x
    paf[:, :, cy] = 0.0
    return paf, heat, a, b


def test_assembly_connects_synthetic_limb():
    paf, heat, a, b = _synthetic_fields()
    peaks = find_peaks(heat)
    assert len(peaks[1]) == 1 and len(peaks[2]) == 1
    assert peaks[1][0][:2] == a and peaks[2][0][:2] == b
    conns = score_limbs(paf, peaks)
    k = LIMB_SEQ.index((1, 2))
    assert len(conns[k]) == 1
    people = assemble_people(peaks, conns, min_parts=2, min_score=0.1)
    assert len(people) == 1
    canvas = draw_skeletons((64, 64), peaks, people)
    # the limb is drawn along y=32 between the two joints
    assert canvas[30:35, 22:42].sum() > 0
    assert canvas[:20].sum() == 0


@pytest.mark.slow
def test_end_to_end_random_weights_runs():
    det = OpenposeDetector.random(seed=1)
    img = (np.random.RandomState(0).rand(96, 72, 3) * 255).astype(np.uint8)
    out = det(img)
    assert out.shape == (96, 72, 3) and out.dtype == np.uint8


def test_workload_raises_without_weights(tmp_path, monkeypatch):
    from PIL import Image

    from chiaswarm_tpu.workloads import controlnet as wl

    monkeypatch.setenv("SDAAS_ROOT", str(tmp_path))
    with pytest.raises(ValueError, match="body_pose_model"):
        wl.preprocess_image(Image.new("RGB", (64, 64)),
                            {"type": "openpose", "preprocess": True})
