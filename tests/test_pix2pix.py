"""Instruct-pix2pix: 8-channel image-conditioned UNet with dual guidance.

Reference behavior covered: the timbrooks/instruct-pix2pix routing with the
strength -> image_guidance_scale x5 remap (swarm/job_arguments.py:128-131),
executed through the diffusers pix2pix pipeline in the reference — here a
static mode of the unified jitted pipeline.
"""

import numpy as np
import pytest

from chiaswarm_tpu.models.configs import get_family
from chiaswarm_tpu.pipelines import Components, DiffusionPipeline, GenerateRequest


@pytest.fixture(scope="module")
def tiny_p2p():
    return DiffusionPipeline(Components.random("tiny_p2p", seed=0))


def _image():
    rng = np.random.default_rng(5)
    return rng.integers(0, 255, (64, 64, 3), dtype=np.uint8)


def test_family_routing():
    fam = get_family("timbrooks/instruct-pix2pix")
    assert fam.name == "pix2pix"
    assert fam.image_conditioned
    assert fam.unet.sample_channels == 8


def test_pix2pix_generation(tiny_p2p):
    req = GenerateRequest(prompt="make it snowy", steps=3, height=64,
                          width=64, seed=7, guidance_scale=6.0,
                          init_image=_image(), image_guidance_scale=1.5)
    img, config = tiny_p2p(req)
    assert img.shape == (1, 64, 64, 3)
    assert config["mode"] == "pix2pix"
    assert config["image_guidance_scale"] == 1.5
    # deterministic; image guidance is traced (no recompile) and matters
    import dataclasses

    from chiaswarm_tpu.core.compile_cache import GLOBAL_CACHE

    img2, _ = tiny_p2p(req)
    assert np.array_equal(img, img2)
    before = GLOBAL_CACHE.executables.stats["misses"]
    img3, _ = tiny_p2p(dataclasses.replace(req, image_guidance_scale=3.0))
    assert GLOBAL_CACHE.executables.stats["misses"] == before
    assert not np.array_equal(img, img3)


def test_pix2pix_requires_image(tiny_p2p):
    with pytest.raises(ValueError, match="start_image_uri"):
        tiny_p2p(GenerateRequest(prompt="x", steps=2, height=64, width=64))


@pytest.mark.slow
def test_workload_pix2pix_no_strength_remap():
    """With an image_conditioned family, image_guidance_scale drives dual
    CFG directly instead of being folded into img2img strength."""
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.workloads.diffusion import diffusion_callback

    registry = ModelRegistry(catalog=[], allow_random=True)
    artifacts, config = diffusion_callback(
        "slot0", "random/tiny_p2p", seed=3, registry=registry,
        prompt="add rain", num_inference_steps=2,
        image=_image(), image_guidance_scale=2.0)
    assert config["mode"] == "pix2pix"
    assert config["image_guidance_scale"] == 2.0
    assert "primary" in artifacts
