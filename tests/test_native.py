"""Native C++ artifact codec vs. the Python reference implementations.

The codec (csrc/artifact_codec.cc) replaces the reference's PIL/hashlib
host path (swarm/output_processor.py:46-58,121-136); these tests pin it
against hashlib/base64/PIL golden behavior, including the SHA-256 padding
boundaries and PNG round-trip pixel exactness.
"""

import base64
import hashlib
import io

import numpy as np
import pytest

from chiaswarm_tpu import native


@pytest.fixture(scope="module", autouse=True)
def require_native():
    if native.load() is None:
        pytest.skip("native codec could not be built (no g++/zlib)")


@pytest.mark.parametrize("size", [0, 1, 3, 55, 56, 63, 64, 65, 119, 120,
                                  1000, 65536])
def test_sha256_matches_hashlib(size):
    data = bytes(range(256)) * (size // 256 + 1)
    data = data[:size]
    assert native.sha256_hex(data) == hashlib.sha256(data).hexdigest()


@pytest.mark.parametrize("size", [0, 1, 2, 3, 4, 5, 300, 4096])
def test_b64_matches_stdlib(size):
    data = bytes((i * 37 + 11) % 256 for i in range(size))
    assert native.b64_encode(data) == base64.b64encode(data).decode()


def test_png_roundtrip_exact():
    from PIL import Image

    rng = np.random.default_rng(0)
    arr = rng.integers(0, 255, (37, 53, 3), dtype=np.uint8)
    blob = native.png_encode_rgb(arr)
    assert blob is not None
    assert blob[:8] == b"\x89PNG\r\n\x1a\n"
    decoded = np.asarray(Image.open(io.BytesIO(blob)).convert("RGB"))
    assert np.array_equal(decoded, arr)


def test_thumbnail_box_filter():
    arr = np.zeros((64, 64, 3), np.uint8)
    arr[:, 32:] = 255  # left black, right white
    thumb = native.thumbnail_rgb(arr, 8, 8)
    assert thumb.shape == (8, 8, 3)
    assert thumb[:, :4].max() == 0
    assert thumb[:, 4:].min() == 255


def test_output_processor_uses_native_and_matches_python():
    """The envelope built through the native path must carry the same
    sha256 the hive would verify with Python."""
    from chiaswarm_tpu.node.output_processor import make_result

    blob = b"artifact-bytes" * 100
    res = make_result(blob, "application/octet-stream")
    assert res["sha256_hash"] == hashlib.sha256(blob).hexdigest()
    assert base64.b64decode(res["blob"]) == blob


def test_python_fallback_when_lib_missing(monkeypatch):
    monkeypatch.setattr(native, "load", lambda: None)
    data = b"fallback-check"
    assert native.sha256_hex(data) == hashlib.sha256(data).hexdigest()
    assert native.b64_encode(data) == base64.b64encode(data).decode()
    assert native.png_encode_rgb(np.zeros((4, 4, 3), np.uint8)) is None
    assert native.thumbnail_rgb(np.zeros((4, 4, 3), np.uint8), 2, 2) is None
