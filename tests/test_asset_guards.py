"""Asset-fetch trust-boundary hardening (ISSUE 10 satellite).

``node/job_args.py::download_image``/``get_image`` and
``workloads/stitch.py::_fetch_image`` pull bytes from hostile parties
across the open network. These tests run a REAL local HTTP server
serving crafted hostile fixtures — lying Content-Length, wrong content
types, bodies streaming past the byte cap, a decompression-bomb PNG
(tiny compressed bytes, enormous decoded dimensions), and a stalling
endpoint — and assert the guards reject each one with the right PR-2
taxonomy kind: ``bad_asset`` (deterministic cap violations, non-fatal)
vs ``transient`` (network-shaped, locally retried).
"""

from __future__ import annotations

import io
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest
from PIL import Image

from chiaswarm_tpu.node import job_args
from chiaswarm_tpu.node.job_args import (
    MAX_IMAGE_BYTES,
    download_image,
    get_image,
)
from chiaswarm_tpu.node.resilience import (
    BadAssetError,
    classify_exception,
)


def _png_bytes(pixels) -> bytes:
    buf = io.BytesIO()
    Image.fromarray(pixels).save(buf, format="PNG")
    return buf.getvalue()


_OK_PNG = _png_bytes(
    np.random.default_rng(5).integers(0, 255, (32, 32, 3), dtype=np.uint8))

# a decompression bomb: ~1-bit 6000x6000 (36 Mpx > the 16 Mpx cap)
# compressing to a few KB — the dimensions are visible before decode
_BOMB_PNG = _png_bytes(np.zeros((6000, 6000), dtype=bool))


class _HostileHandler(BaseHTTPRequestHandler):
    def log_message(self, *args):  # quiet
        pass

    def _send(self, body: bytes, content_type: str,
              content_length: int | None = None) -> None:
        self.send_response(200)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length",
                         str(len(body) if content_length is None
                             else content_length))
        self.end_headers()
        if self.command != "HEAD":
            self.wfile.write(body)

    def do_HEAD(self):
        self.do_GET(head=True)

    def do_GET(self, head: bool = False):
        path = self.path
        if path == "/ok.png":
            self._send(_OK_PNG, "image/png")
        elif path == "/not-an-image":
            self._send(b"<html>gotcha</html>", "text/html")
        elif path == "/liar-head":
            # HEAD claims image/png; GET serves text/html — the GET's
            # own content type must still be checked
            if self.command == "HEAD":
                self._send(b"", "image/png")
            else:
                self._send(b"<html>switcheroo</html>", "text/html")
        elif path == "/huge-header":
            # Content-Length far over the cap (body tiny): HEAD check
            self._send(_OK_PNG, "image/png",
                       content_length=MAX_IMAGE_BYTES * 10)
        elif path == "/oversized-stream":
            # claims a small Content-Length, streams 4 MiB anyway: the
            # capped streaming read must cut it off
            body = b"x" * (MAX_IMAGE_BYTES + 1024 * 1024)
            self.send_response(200)
            self.send_header("Content-Type", "image/png")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            if not head:
                self.wfile.write(body)
        elif path == "/bomb.png":
            self._send(_BOMB_PNG, "image/png")
        elif path == "/slow":
            if self.command == "HEAD":
                self._send(b"", "image/png")
                return
            self.send_response(200)
            self.send_header("Content-Type", "image/png")
            self.send_header("Content-Length", str(len(_OK_PNG)))
            self.end_headers()
            time.sleep(2.0)  # past the test's read timeout
            try:
                self.wfile.write(_OK_PNG)
            except BrokenPipeError:
                pass
        else:
            self.send_response(404)
            self.end_headers()


@pytest.fixture(scope="module")
def hostile_server():
    server = ThreadingHTTPServer(("127.0.0.1", 0), _HostileHandler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{server.server_port}"
    server.shutdown()
    server.server_close()


def test_happy_path_image_fetches(hostile_server):
    image = download_image(f"{hostile_server}/ok.png")
    assert image.size == (32, 32) and image.mode == "RGB"
    image = get_image(f"{hostile_server}/ok.png", None)
    assert image.size == (32, 32)


def test_wrong_content_type_is_bad_asset(hostile_server):
    with pytest.raises(BadAssetError) as excinfo:
        get_image(f"{hostile_server}/not-an-image", None)
    assert classify_exception(excinfo.value) == "bad_asset"


def test_get_content_type_checked_even_after_clean_head(hostile_server):
    """A host whose HEAD lies clean must still fail on the GET body's
    own content type."""
    with pytest.raises(BadAssetError):
        get_image(f"{hostile_server}/liar-head", None)


def test_huge_content_length_header_is_bad_asset(hostile_server):
    with pytest.raises(BadAssetError) as excinfo:
        get_image(f"{hostile_server}/huge-header", None)
    assert "too large" in str(excinfo.value)
    assert classify_exception(excinfo.value) == "bad_asset"


def test_oversized_stream_is_cut_off_not_buffered(hostile_server):
    """A body streaming past the cap is rejected mid-stream no matter
    what Content-Length claimed — the worker never buffers it whole."""
    with pytest.raises(BadAssetError) as excinfo:
        download_image(f"{hostile_server}/oversized-stream")
    assert "exceeded the cap" in str(excinfo.value)
    assert classify_exception(excinfo.value) == "bad_asset"


def test_decompression_bomb_rejected_before_decode(hostile_server):
    """A few-KB PNG claiming 6000x6000 pixels is rejected on its
    DECLARED dimensions — the bomb never inflates."""
    assert len(_BOMB_PNG) < 64 * 1024  # genuinely a bomb fixture
    with pytest.raises(BadAssetError) as excinfo:
        download_image(f"{hostile_server}/bomb.png")
    assert "decompression-bomb" in str(excinfo.value)
    assert classify_exception(excinfo.value) == "bad_asset"


def test_read_timeout_classifies_transient(hostile_server, monkeypatch):
    """A stalling asset host trips the read timeout — a network-shaped
    fault the ladder retries locally, never a fatal input error."""
    monkeypatch.setattr(job_args, "READ_TIMEOUT_S", 0.3)
    with pytest.raises(Exception) as excinfo:
        download_image(f"{hostile_server}/slow")
    assert not isinstance(excinfo.value, BadAssetError)
    assert classify_exception(excinfo.value) == "transient"


def test_bad_asset_is_nonfatal_in_the_format_path(hostile_server):
    """End to end through the executor's _format: a bomb fetched via
    start_image_uri envelopes as non-fatal ``bad_asset`` (the hive may
    retry elsewhere), not a fatal input error."""
    from chiaswarm_tpu.node.executor import _format
    from chiaswarm_tpu.node.registry import ModelRegistry

    registry = ModelRegistry(catalog=[], allow_random=True)
    job = {"id": "bomb-1", "model_name": "tiny", "prompt": "p",
           "start_image_uri": f"{hostile_server}/bomb.png",
           "content_type": "application/json"}
    formatted, fatal = _format(job, registry)
    assert formatted is None
    assert "fatal_error" not in fatal
    assert fatal["pipeline_config"]["error_kind"] == "bad_asset"


def test_stitch_fetch_uses_the_guards(hostile_server):
    from chiaswarm_tpu.workloads.stitch import _fetch_image

    image = _fetch_image(f"{hostile_server}/ok.png")
    assert image.mode == "RGB"
    with pytest.raises(BadAssetError):
        _fetch_image(f"{hostile_server}/bomb.png")
