"""Lineart detector tests: torch-reference fidelity + preprocessor wiring.

The reference's lineart mode runs controlnet_aux's LineartDetector — the
informative-drawings ``Generator`` (swarm/controlnet/input_processor.py:
17-60 dispatch); these pin the native port (models/lineart.py) to the same
graph, including the exact ConvTranspose2d(k=3,s=2,p=1,op=1) emulation.
"""

from __future__ import annotations

import numpy as np
import pytest

from chiaswarm_tpu.models.lineart import LineartDetector


def _torch_generator(n_blocks: int = 3):
    """Independent torch construction of the informative-drawings
    Generator(3, 1, n_blocks) with sigmoid head."""
    torch = pytest.importorskip("torch")
    import torch.nn as nn

    class ResidualBlock(nn.Module):
        def __init__(self, ch):
            super().__init__()
            self.conv_block = nn.Sequential(
                nn.ReflectionPad2d(1), nn.Conv2d(ch, ch, 3),
                nn.InstanceNorm2d(ch), nn.ReLU(inplace=True),
                nn.ReflectionPad2d(1), nn.Conv2d(ch, ch, 3),
                nn.InstanceNorm2d(ch),
            )

        def forward(self, x):
            return x + self.conv_block(x)

    class Generator(nn.Module):
        def __init__(self):
            super().__init__()
            self.model0 = nn.Sequential(
                nn.ReflectionPad2d(3), nn.Conv2d(3, 64, 7),
                nn.InstanceNorm2d(64), nn.ReLU(inplace=True))
            self.model1 = nn.Sequential(
                nn.Conv2d(64, 128, 3, stride=2, padding=1),
                nn.InstanceNorm2d(128), nn.ReLU(inplace=True),
                nn.Conv2d(128, 256, 3, stride=2, padding=1),
                nn.InstanceNorm2d(256), nn.ReLU(inplace=True))
            self.model2 = nn.Sequential(
                *[ResidualBlock(256) for _ in range(n_blocks)])
            self.model3 = nn.Sequential(
                nn.ConvTranspose2d(256, 128, 3, stride=2, padding=1,
                                   output_padding=1),
                nn.InstanceNorm2d(128), nn.ReLU(inplace=True),
                nn.ConvTranspose2d(128, 64, 3, stride=2, padding=1,
                                   output_padding=1),
                nn.InstanceNorm2d(64), nn.ReLU(inplace=True))
            self.model4 = nn.Sequential(
                nn.ReflectionPad2d(3), nn.Conv2d(64, 1, 7), nn.Sigmoid())

        def forward(self, x):
            return self.model4(
                self.model3(self.model2(self.model1(self.model0(x)))))

    torch.manual_seed(0)
    return torch, Generator().eval()


def test_conversion_matches_torch_reference():
    torch, net = _torch_generator()
    import jax.numpy as jnp

    from chiaswarm_tpu.convert.torch_to_flax import convert_lineart

    state = {k: v.detach().numpy() for k, v in net.state_dict().items()}
    det = LineartDetector(params=convert_lineart(state))
    x = np.random.RandomState(0).rand(1, 32, 32, 3).astype(np.float32)
    with torch.no_grad():
        tout = net(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    fout = np.asarray(det._fwd(det.params, jnp.asarray(x)))
    np.testing.assert_allclose(fout[..., 0], tout[:, 0], atol=2e-4,
                               rtol=2e-3)


def test_converter_rejects_wrong_state():
    from chiaswarm_tpu.convert.torch_to_flax import convert_lineart

    with pytest.raises(ValueError, match="Generator"):
        convert_lineart({"foo.weight": np.zeros((4, 4, 3, 3))})


def test_detector_runs_on_odd_sizes():
    det = LineartDetector.random(seed=0, canvas=64)
    img = (np.random.RandomState(1).rand(37, 53, 3) * 255).astype(np.uint8)
    lines = det(img)
    assert lines.shape == (37, 53) and lines.dtype == np.uint8


def test_lineart_uses_model_when_weights_present(monkeypatch):
    from PIL import Image

    from chiaswarm_tpu.workloads import controlnet as wl

    monkeypatch.setattr(wl, "_LINEART",
                        [LineartDetector.random(seed=2, canvas=64)])
    out = wl.preprocess_image(Image.new("RGB", (64, 48), (90, 120, 40)),
                              {"type": "lineart", "preprocess": True})
    assert np.asarray(out).shape == (48, 64, 3)


def test_lineart_falls_back_without_weights(tmp_path, monkeypatch):
    from PIL import Image

    from chiaswarm_tpu.workloads import controlnet as wl

    monkeypatch.setenv("SDAAS_ROOT", str(tmp_path))
    monkeypatch.setattr(wl, "_LINEART", [])
    out = wl.preprocess_image(Image.new("RGB", (64, 48), (90, 120, 40)),
                              {"type": "lineart", "preprocess": True})
    assert np.asarray(out).shape == (48, 64, 3)
    assert wl._LINEART == [None]  # stand-in path cached
