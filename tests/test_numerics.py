"""swarmlens numerics flight recorder (ISSUE 11): taps-off invariance,
per-step/per-shard recording, the checkpoint-boundary lane probes, and
the divergence-bisect machinery end to end.

THE gates here:

- **taps-off invariance** — with ``CHIASWARM_NUMERICS`` unset a tapped
  program lowers to HLO byte-identical to its untapped twin, cache keys
  keep their historical shape, re-running a cached program compiles
  nothing new, and the ring stays empty.
- **bisect localization** — the intentionally-divergent fixture pair
  must be localized to exactly its planted (step, probe); this is the
  same gate CI runs via ``tools/divergence_bisect.py --config fixture``.

Runs on the hermetic CPU platform (tests/conftest.py).
"""

from __future__ import annotations

import importlib.util
import os

import numpy as np
import pytest

from chiaswarm_tpu.obs import numerics

_BISECT_PATH = os.path.join(os.path.dirname(__file__), "..", "tools",
                            "divergence_bisect.py")
_spec = importlib.util.spec_from_file_location("divergence_bisect",
                                               _BISECT_PATH)
bisect_mod = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bisect_mod)


@pytest.fixture(autouse=True)
def _clean_recorder(monkeypatch):
    """Every test starts taps-off with an empty ring and fresh trace
    counters; the global recorder is shared process-wide."""
    monkeypatch.delenv("CHIASWARM_NUMERICS", raising=False)
    numerics.RING.clear()
    numerics.TAPS.reset_trace_seq()
    yield
    numerics.RING.clear()
    numerics.TAPS.reset_trace_seq()


# ---------------------------------------------------------------------------
# enablement + gating
# ---------------------------------------------------------------------------


def test_enablement_prefix_filter(monkeypatch):
    assert not numerics.enabled()
    assert not numerics.enabled_for("diffusion.eps")
    monkeypatch.setenv("CHIASWARM_NUMERICS", "1")
    assert numerics.enabled() and numerics.enabled_for("anything")
    monkeypatch.setenv("CHIASWARM_NUMERICS", "diffusion,ring")
    assert numerics.enabled_for("diffusion.eps")
    assert numerics.enabled_for("ring.hop_partial")
    assert not numerics.enabled_for("lane_row")
    assert numerics.fingerprint() == "diffusion,ring"


def test_static_cache_key_shape_invariant_off_and_fingerprinted_on(
        monkeypatch):
    """Taps-off cache keys keep the historical 3-tuple byte for byte;
    taps-on appends the fingerprint, so an env flip can never serve a
    tapped executable from a taps-off slot (or vice versa)."""
    from chiaswarm_tpu.core.compile_cache import static_cache_key

    off = static_cache_key(7, "generate", {"batch": 1})
    assert off == (7, "generate", (("batch", 1),))  # historical shape
    monkeypatch.setenv("CHIASWARM_NUMERICS", "diffusion")
    on = static_cache_key(7, "generate", {"batch": 1})
    assert on != off
    assert on[:3] == off
    assert ("numerics", "diffusion") in on[3:]
    monkeypatch.setenv("CHIASWARM_NUMERICS", "1")
    assert static_cache_key(7, "generate", {"batch": 1}) != on


# ---------------------------------------------------------------------------
# THE taps-off invariance gate
# ---------------------------------------------------------------------------


def _scan_program(tapped: bool):
    import jax
    import jax.numpy as jnp

    def fn(x):
        def body(carry, i):
            carry = carry * 1.01 + 0.001
            if tapped:
                carry = numerics.tap("invariance.carry", carry, step=i)
            return carry, None

        out, _ = jax.lax.scan(body, x, jnp.arange(4))
        if tapped:
            out = numerics.tap("invariance.out", out)
        return out

    return fn


def test_taps_off_lower_to_identical_hlo():
    """CHIASWARM_NUMERICS unset: the tapped program's lowered HLO is
    byte-identical to the untapped twin — zero callbacks, zero changed
    ops, nothing for XLA to schedule differently."""
    import jax
    import jax.numpy as jnp

    x = jnp.ones((4, 4))
    hlo_tapped = jax.jit(_scan_program(True)).lower(x).as_text()
    hlo_plain = jax.jit(_scan_program(False)).lower(x).as_text()
    assert hlo_tapped == hlo_plain
    assert "custom_call" not in hlo_tapped.replace("-", "_").lower()
    assert len(numerics.RING) == 0


def test_taps_off_reruns_compile_nothing_and_record_nothing():
    """A cached generate program re-runs under taps-off with compile
    counters unchanged — the admission/compile-cache half of the
    invariance gate."""
    import jax

    from chiaswarm_tpu.obs.metrics import REGISTRY
    from chiaswarm_tpu.pipelines import (
        Components,
        DiffusionPipeline,
        GenerateRequest,
    )

    pipe = DiffusionPipeline(Components.random("tiny", seed=3))
    req = GenerateRequest(prompt="invariance", steps=2, height=64,
                          width=64, seed=5, guidance_scale=5.0)
    first, _ = pipe(req)

    compiles = REGISTRY.get("chiaswarm_compiles_total")
    misses = REGISTRY.get("chiaswarm_compile_cache_misses_total")
    before = (dict(compiles.series()), dict(misses.series()))
    again, _ = pipe(req)
    after = (dict(compiles.series()), dict(misses.series()))
    assert after == before, "taps-off rerun moved compile counters"
    assert len(numerics.RING) == 0
    np.testing.assert_array_equal(np.asarray(first), np.asarray(again))


# ---------------------------------------------------------------------------
# taps-on recording
# ---------------------------------------------------------------------------


def test_tap_records_per_step_and_output_unchanged(monkeypatch):
    import jax
    import jax.numpy as jnp

    x = jnp.ones((4, 4))
    plain = jax.jit(_scan_program(False))(x)
    monkeypatch.setenv("CHIASWARM_NUMERICS", "invariance")
    tapped = jax.jit(_scan_program(True))(x)
    jax.block_until_ready(tapped)
    numerics.flush()
    np.testing.assert_array_equal(np.asarray(plain), np.asarray(tapped))
    records = numerics.RING.snapshot()
    carry_steps = sorted(r["step"] for r in records
                         if r["probe"] == "invariance.carry")
    assert carry_steps == [0, 1, 2, 3]
    out = [r for r in records if r["probe"] == "invariance.out"]
    assert len(out) == 1 and out[0]["step"] == -1 and out[0]["shard"] == -1
    for r in records:
        assert r["size"] == 16 and r["nonfinite"] == 0
        assert r["l2"] > 0 and r["checksum"] != 0
    assert numerics.TAPS.traced_probes()["invariance.carry"] == 1


def test_tap_counts_nonfinites_and_keeps_them_out_of_moments(monkeypatch):
    import jax
    import jax.numpy as jnp

    monkeypatch.setenv("CHIASWARM_NUMERICS", "nan_probe")

    def fn(x):
        return numerics.tap("nan_probe", x)

    x = jnp.asarray([1.0, float("nan"), 3.0, float("inf")])
    jax.block_until_ready(jax.jit(fn)(x))
    numerics.flush()
    (rec,) = numerics.RING.snapshot()
    assert rec["nonfinite"] == 2
    # moments computed over the finite values only (NaN/Inf zeroed)
    assert rec["absmax"] == pytest.approx(3.0)
    assert rec["l2"] == pytest.approx(np.sqrt(1.0 + 9.0))


def test_per_shard_taps_inside_shard_map(monkeypatch):
    """ring.* probes: each seq shard emits its own per-hop record, with
    the shard id from axis_index — the drill-down stream for the
    seq-parallel bisect."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from chiaswarm_tpu.core.compat import shard_map
    from chiaswarm_tpu.core.mesh import MeshSpec, build_mesh
    from chiaswarm_tpu.parallel.ring_attention import ring_attention

    monkeypatch.setenv("CHIASWARM_NUMERICS", "ring")
    mesh = build_mesh(MeshSpec({"seq": 4}), devices=jax.devices()[:4])
    b, l, h, d = 1, 16, 2, 8
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.normal(size=(b, l, h, d)).astype(np.float32))
               for _ in range(3))
    spec = P(None, "seq", None, None)
    fn = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="seq"),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    out = jax.jit(fn)(q, k, v)
    jax.block_until_ready(out)
    numerics.flush()
    records = numerics.RING.snapshot()
    partials = [r for r in records if r["probe"] == "ring.hop_partial"]
    # 4 shards x 4 hops, each with its own (step=hop, shard) identity
    assert {(r["step"], r["shard"]) for r in partials} == {
        (hop, shard) for hop in range(4) for shard in range(4)}
    outs = [r for r in records if r["probe"] == "ring.out"]
    assert {r["shard"] for r in outs} == {0, 1, 2, 3}

    # the tapped ring still matches the plain xla reference
    from chiaswarm_tpu.ops.attention import _xla_attention

    ref = _xla_attention(q, k, v, d ** -0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5)


def test_lane_row_probes_ride_checkpoint_boundary(monkeypatch):
    """serving/stepper.py extends the checkpoint-boundary device->host
    transfer: with the lane_row probe on (and CKPT_EVERY=1), every
    active row records a summary per step — keyed by slot and step, the
    stream the SHARD_ROWS bisect aligns."""
    from chiaswarm_tpu.pipelines import Components, DiffusionPipeline
    from chiaswarm_tpu.serving.stepper import StepScheduler

    monkeypatch.setenv("CHIASWARM_NUMERICS", "lane_row")
    monkeypatch.setenv("CHIASWARM_STEPPER_CKPT_EVERY", "1")
    monkeypatch.setenv("CHIASWARM_STEPPER_LANE_WIDTH", "2")
    pipe = DiffusionPipeline(Components.random("tiny", seed=0))
    sched = StepScheduler()
    try:
        fut = sched.submit_request(
            pipe, prompt="lane probes", steps=6, guidance_scale=7.5,
            height=64, width=64, rows=2, seed=9)
        fut.result(timeout=300)[0].wait()
    finally:
        sched.shutdown()
    records = [r for r in numerics.RING.snapshot()
               if r["probe"] == "lane_row"]
    assert records, "no lane_row records at checkpoint boundaries"
    by_shard: dict[int, list[int]] = {}
    for r in records:
        by_shard.setdefault(r["shard"], []).append(r["step"])
        assert r["nonfinite"] == 0 and r["l2"] > 0
        assert r.get("note"), "lane records carry the job id"
    assert set(by_shard) == {0, 1}  # both rows, slot-indexed
    for steps in by_shard.values():
        # strictly increasing step trail per row (one record per
        # boundary the row was active at, mid-trajectory)
        assert steps == sorted(steps) and len(set(steps)) == len(steps)
        assert len(steps) >= 3


# ---------------------------------------------------------------------------
# the bisect machinery
# ---------------------------------------------------------------------------


def _rec(probe, step, shard, l2, seq, **kw):
    base = {"probe": probe, "step": step, "shard": shard, "l2": l2,
            "mean": l2 / 10.0, "absmax": l2 / 2.0, "nonfinite": 0,
            "checksum": int(l2 * 1000) & 0xFFFFFFFF, "size": 4,
            "seq": seq}
    base.update(kw)
    return base


def test_bisect_streams_reports_first_divergence_in_program_order():
    a = [_rec("x", -1, -1, 1.0, 0),
         _rec("y", 0, -1, 2.0, 1),
         _rec("y", 1, -1, 3.0, 2),
         _rec("z", 1, -1, 4.0, 3)]
    b = [_rec("x", -1, -1, 1.0, 0),
         _rec("y", 0, -1, 2.0, 1),
         _rec("y", 1, -1, 3.3, 2),      # first real divergence
         _rec("z", 1, -1, 9.0, 3),      # later, bigger — must NOT win
         _rec("only_b", 0, 2, 5.0, 4)]
    report = bisect_mod.bisect_streams(a, b, rtol=1e-3, atol=1e-9)
    first = report["first_divergence"]
    assert (first["probe"], first["step"]) == ("y", 1)
    assert first["field"] == "l2"
    assert report["divergent"] == 2
    assert report["compared"] == 4
    assert report["probes_only_in_b"] == ["only_b"]
    assert report["probes_only_in_a"] == []


def test_bisect_nonfinite_and_checksum_semantics():
    a = [_rec("p", 0, -1, 1.0, 0)]
    b_nan = [_rec("p", 0, -1, 1.0, 0, nonfinite=3)]
    report = bisect_mod.bisect_streams(a, b_nan)
    assert report["first_divergence"]["field"] == "nonfinite"

    # same floats, different bits: counted, never a divergence
    b_bits = [_rec("p", 0, -1, 1.0, 0, checksum=42)]
    report = bisect_mod.bisect_streams(a, b_bits)
    assert report["divergent"] == 0
    assert report["bit_only_differences"] == 1


def test_bisect_duplicate_keys_keep_first_record():
    a = [_rec("p", 0, -1, 1.0, 0), _rec("p", 0, -1, 99.0, 1)]
    b = [_rec("p", 0, -1, 1.0, 0), _rec("p", 0, -1, 55.0, 1)]
    assert bisect_mod.bisect_streams(a, b)["divergent"] == 0


def test_fixture_pair_localizes_planted_divergence(monkeypatch):
    """The CI gate's in-process twin: the intentionally-divergent scan
    pair must bisect to exactly the planted (step, probe)."""
    monkeypatch.setenv("CHIASWARM_NUMERICS", "fixture")
    stream_a, stream_b, context = bisect_mod.run_fixture(steps=6)
    assert len(stream_a) == 7 and len(stream_b) == 7  # 6 carry + 1 out
    report = bisect_mod.bisect_streams(stream_a, stream_b)
    first = report["first_divergence"]
    assert first is not None
    assert first["probe"] == "fixture.carry"
    assert first["step"] == bisect_mod.FIXTURE_DIVERGE_STEP
    assert context["planted_step"] == bisect_mod.FIXTURE_DIVERGE_STEP
    # carry steps before the perturbation agree bit-for-bit (the final
    # fixture.out summary diverges too, downstream — expected)
    clean = [d for d in report["divergences"]
             if d["probe"] == "fixture.carry"
             and d["step"] < bisect_mod.FIXTURE_DIVERGE_STEP]
    assert clean == []


def test_debug_payload_shape(monkeypatch):
    monkeypatch.setenv("CHIASWARM_NUMERICS", "p")
    numerics.RING.record("p.x", step=2, shard=0, l2=1.0)
    payload = numerics.debug_payload(probe_prefix="p.", limit=10)
    assert payload["enabled"] is True
    assert payload["filter"] == "p"
    assert payload["ring"]["depth"] == 1
    assert [r["probe"] for r in payload["records"]] == ["p.x"]


# ---------------------------------------------------------------------------
# review-hardening regressions (PR 11 code review)
# ---------------------------------------------------------------------------


def test_off_values_disable_instead_of_fingerprinting(monkeypatch):
    """CHIASWARM_NUMERICS=0 (off/false/no) must mean OFF: no cache-key
    fingerprint (no silent full retrace), enabled=False on the debug
    payload — not 'enabled but matching no probe'."""
    from chiaswarm_tpu.core.compile_cache import static_cache_key

    base = static_cache_key(1, "t", {"a": 1})
    for off in ("0", "off", "false", "no", "OFF", "False"):
        monkeypatch.setenv("CHIASWARM_NUMERICS", off)
        assert not numerics.enabled(), off
        assert not numerics.enabled_for("diffusion.eps"), off
        assert numerics.fingerprint() == "", off
        assert static_cache_key(1, "t", {"a": 1}) == base, off


def test_enabled_for_is_bidirectional_for_family_guards(monkeypatch):
    """A per-probe filter (attn.q) must satisfy the call site's FAMILY
    guard (enabled_for('attn') traces the taps in) while each tap still
    filters itself — so CHIASWARM_NUMERICS=attn.q records exactly q."""
    monkeypatch.setenv("CHIASWARM_NUMERICS", "attn.q")
    assert numerics.enabled_for("attn")      # family guard passes
    assert numerics.enabled_for("attn.q")    # the probe itself
    assert not numerics.enabled_for("attn.k")
    assert not numerics.enabled_for("ring.hop_partial")

    import jax
    import jax.numpy as jnp

    from chiaswarm_tpu.ops.attention import attention

    q = jnp.ones((1, 8, 2, 4))
    jax.block_until_ready(jax.jit(
        lambda q: attention(q, q, q))(q))
    numerics.flush()
    probes = {r["probe"] for r in numerics.RING.snapshot()}
    assert probes == {"attn.q"}, probes


def test_snapshot_limit_zero_returns_nothing():
    ring = numerics.NumericsRing(capacity=8)
    for i in range(3):
        ring.record("p", step=i)
    assert ring.snapshot(limit=0) == []
    assert len(ring.snapshot(limit=2)) == 2
    assert len(ring.snapshot()) == 3


def test_bisect_first_divergence_robust_to_callback_arrival_order():
    """ordered=False callbacks can land out of program order: a step-5
    record arriving before step-3 must not steal 'first divergence',
    and pre-/post-loop unstepped probes keep their program position."""
    a = [_rec("pre", -1, -1, 1.0, 0),       # pre-loop (e.g. text ctx)
         _rec("c", 5, -1, 6.0, 1),          # step 5 ARRIVED first
         _rec("c", 3, -1, 4.0, 2),          # step 3 arrived late
         _rec("post", -1, -1, 9.0, 3)]      # post-loop output summary
    b = [_rec("pre", -1, -1, 1.0, 0),
         _rec("c", 5, -1, 7.0, 1),          # diverges
         _rec("c", 3, -1, 4.4, 2),          # diverges EARLIER in program
         _rec("post", -1, -1, 11.0, 3)]
    report = bisect_mod.bisect_streams(a, b, rtol=1e-3)
    first = report["first_divergence"]
    assert (first["probe"], first["step"]) == ("c", 3)
    # stepped records order by step regardless of arrival; the step-5
    # record never outranks step 3
    steps = [d["step"] for d in report["divergences"]
             if d["probe"] == "c"]
    assert steps == [3, 5]
    # the pre-loop probe keeps its position before every stepped record
    assert report["divergences"][0]["probe"] != "pre"
