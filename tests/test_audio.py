"""txt2audio: vocoder, mel-latent pipeline, workload path, WAV framing.

Reference behaviors covered: AudioLDM txt2audio at 20 steps / 10 s default
(swarm/audio/audioldm.py:12-36) dispatched from the ``txt2audio`` workflow
(swarm/job_arguments.py:22-25).
"""

import io
import wave

import numpy as np
import pytest

from chiaswarm_tpu.pipelines.audio import (
    AUDIO_FAMILIES,
    AudioComponents,
    AudioPipeline,
    get_audio_family,
)


@pytest.fixture(scope="module")
def tiny_audio():
    return AudioPipeline(AudioComponents.random("tiny_audio", seed=0))


def test_audio_family_routing():
    assert get_audio_family("cvssp/audioldm-s-full-v2").name == "audioldm"
    assert get_audio_family("random/tiny_audio").name == "tiny_audio"
    assert AUDIO_FAMILIES["audioldm"].vocoder.sampling_rate == 16000


def test_vocoder_shapes():
    import jax
    import jax.numpy as jnp

    from chiaswarm_tpu.models.vocoder import HifiGan, HifiGanConfig

    cfg = HifiGanConfig(model_in_dim=16, upsample_initial_channel=32,
                        upsample_rates=(4, 4), upsample_kernel_sizes=(8, 8),
                        resblock_kernel_sizes=(3,),
                        resblock_dilation_sizes=((1, 3),))
    voc = HifiGan(cfg)
    mel = jnp.zeros((2, 10, 16))
    params = voc.init(jax.random.PRNGKey(0), mel)
    wav = voc.apply(params, mel)
    assert wav.shape == (2, 10 * cfg.hop_length)
    assert cfg.hop_length == 16
    assert np.abs(np.asarray(wav)).max() <= 1.0


@pytest.mark.slow
def test_txt2audio_pipeline(tiny_audio):
    wav, sr, config = tiny_audio("rain on a tin roof", steps=2,
                                 duration_s=0.05, seed=3)
    assert wav.ndim == 2 and wav.shape[0] == 1
    assert sr == 16000
    assert np.isfinite(wav).all()
    assert config["mode"] == "txt2audio"
    # determinism per seed
    wav2, _, _ = tiny_audio("rain on a tin roof", steps=2,
                            duration_s=0.05, seed=3)
    assert np.array_equal(wav, wav2)


def test_convert_hifigan_weight_norm_folding():
    from chiaswarm_tpu.convert.torch_to_flax import convert_hifigan

    v = np.random.default_rng(0).normal(size=(32, 16, 7)).astype(np.float32)
    g = np.full((32, 1, 1), 2.0, np.float32)
    state = {
        "conv_pre.weight_v": v,
        "conv_pre.weight_g": g,
        "conv_pre.bias": np.zeros((32,), np.float32),
        "upsampler.0.weight_v": np.zeros((32, 16, 8), np.float32),
        "upsampler.0.weight_g": np.ones((32, 1, 1), np.float32),
        "resblocks.0.convs1.0.weight_v": np.zeros((16, 16, 3), np.float32),
        "resblocks.0.convs1.0.weight_g": np.ones((16, 1, 1), np.float32),
    }
    tree = convert_hifigan(state, num_resblock_kernels=1)["params"]
    kernel = tree["conv_pre"]["kernel"]          # (K, I, O)
    assert kernel.shape == (7, 16, 32)
    # folded norm: each output filter has L2 norm == g
    norms = np.sqrt((kernel ** 2).sum(axis=(0, 1)))
    np.testing.assert_allclose(norms, 2.0, rtol=1e-5)
    assert tree["upsampler_0"]["kernel"].shape == (8, 32, 16)
    assert tree["resblocks_0_0"]["convs1_0"]["kernel"].shape == (3, 16, 16)


def test_workload_txt2audio_wav_artifact(monkeypatch):
    """The txt2audio workflow emits a parseable WAV artifact (mp3 encode
    stubbed off so the assertion holds on ffmpeg-carrying hosts too)."""
    from chiaswarm_tpu.node.job_args import format_args
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.workloads import audio as audio_wl

    monkeypatch.setattr(audio_wl, "mp3_bytes",
                        lambda s, sr, bitrate="128k": None)
    registry = ModelRegistry(catalog=[], allow_random=True)
    job = {"workflow": "txt2audio", "model_name": "random/tiny_audio",
           "prompt": "wind chimes", "num_inference_steps": 2,
           "audio_length_in_s": 0.05}
    callback, kwargs = format_args(job, registry)
    artifacts, config = callback("slot0", kwargs.pop("model_name"),
                                 seed=5, **kwargs)
    assert config["mode"] == "txt2audio"
    blob = artifacts["primary"]["blob"]
    import base64

    raw = base64.b64decode(blob)
    with wave.open(io.BytesIO(raw)) as wav:
        assert wav.getframerate() == 16000
        assert wav.getnchannels() == 1
        assert wav.getnframes() > 0
    assert artifacts["primary"]["content_type"] == "audio/wav"


def test_audio_artifact_prefers_mp3_when_encoder_present(monkeypatch):
    """With an mp3 encoder available the artifact is audio/mpeg (the
    reference's pydub/ffmpeg transcode, swarm/audio/audioldm.py:23-33);
    without one it is an honest audio/wav."""
    import base64

    from chiaswarm_tpu.workloads import audio as wl

    wav = np.sin(np.linspace(0, 440 * 2 * np.pi, 16000)).astype(np.float32)
    monkeypatch.setattr(wl, "mp3_bytes",
                        lambda s, sr, bitrate="128k": b"\xff\xfbFAKEMP3")
    art = wl.audio_artifact(wav, 16000)
    assert art["content_type"] == "audio/mpeg"
    assert base64.b64decode(art["blob"]).startswith(b"\xff\xfb")

    monkeypatch.setattr(wl, "mp3_bytes", lambda s, sr, bitrate="128k": None)
    art = wl.audio_artifact(wav, 16000)
    assert art["content_type"] == "audio/wav"


def test_mp3_bytes_none_without_ffmpeg(monkeypatch):
    from chiaswarm_tpu.workloads import audio as wl

    wl._ffmpeg_path.cache_clear()
    monkeypatch.setenv("PATH", "")
    try:
        assert wl.mp3_bytes(np.zeros(100, np.float32), 16000) is None
    finally:
        wl._ffmpeg_path.cache_clear()
