"""swarmsight suite (ISSUE 13): cross-worker flight records.

Four layers:

- **Recorder units** (fake clock, no workers): trace-context stamping at
  grant, span-digest capture at settle, the hive-clock event timeline,
  deadline-budget attribution arithmetic, verify() anomaly detection,
  and the bounded store.
- **Timeline stitching through MiniHive** (fake clock): shed -> requeue
  -> complete and late-upload salvage each yield exactly ONE flight
  record with the full attempt chain.
- **Real-worker wire contract** (ChaoticExecutor, no pipelines): a
  context-carrying job uploads a span digest the hive pops into the
  record; with NO hive trace context (reference-hive parity) the upload
  payload keeps today's exact key set and the trace still carries the
  ``queued_s``/``attempt`` root attributes.
- **THE acceptance gate** (slow tier; real lanes): a 3-worker fleet
  with one scripted mid-lane kill yields a single stitched record for
  the killed job spanning both workers — grant(1, A) -> checkpoints ->
  redelivery -> grant(2, B) with resume_step >= 1 -> exactly-once
  settle — and tools/job_flight.py renders it.
"""

from __future__ import annotations

import asyncio
import json
import subprocess
import sys
import time
from pathlib import Path

import pytest

from chiaswarm_tpu.node.chaos import ChaoticExecutor, ChaoticHive
from chiaswarm_tpu.node.executor import error_result
from chiaswarm_tpu.node.minihive import MiniHive
from chiaswarm_tpu.node.registry import ModelRegistry
from chiaswarm_tpu.node.worker import Worker
from chiaswarm_tpu.obs import flight as obs_flight
from chiaswarm_tpu.obs import trace as obs_trace
from chiaswarm_tpu.obs.flight import (
    ATTRIBUTION_PHASES,
    SPAN_DIGEST_KEY,
    TRACE_CTX_KEY,
    FlightRecorder,
    budget_attribution,
    flight_to_chrome,
    render_timeline,
    render_tree,
    span_digest,
)

REPO = Path(__file__).resolve().parents[1]


@pytest.fixture(autouse=True)
def _tmp_root(tmp_path, monkeypatch):
    monkeypatch.setenv("SWARM_TPU_ROOT", str(tmp_path))
    return tmp_path


@pytest.fixture(autouse=True)
def _restore_matmul_precision():
    import jax

    before = jax.config.jax_default_matmul_precision
    yield
    jax.config.update("jax_default_matmul_precision", before)


def _job(job_id: str, chaos=None, model: str = "shared/tiny", **over):
    job = {"id": job_id, "model_name": model, "prompt": f"p {job_id}",
           "num_inference_steps": 2, "height": 64, "width": 64,
           "workflow": "txt2img", "deadline_s": 2.0,
           "content_type": "application/json"}
    if chaos is not None:
        job["chaos"] = chaos
    job.update(over)
    return job


def _ok_result(job_id: str, worker: str = "", digest=None) -> dict:
    result = {"id": job_id, "artifacts": {}, "nsfw": False,
              "pipeline_config": {"mode": "test"}}
    if worker:
        result["worker_name"] = worker
    if digest is not None:
        result[SPAN_DIGEST_KEY] = digest
    return result


def _digest(attempt: int, worker: str, *, duration_s: float = 0.5,
            splice_wait_s: float = 0.0) -> dict:
    """Hand-built digest shaped exactly like obs_flight.span_digest's
    output (the units below prove the real builder matches)."""
    return {
        "trace_id": "t" * 16, "span_id": f"{'t' * 16}.{attempt}",
        "attempt": attempt, "worker": worker,
        "started_at_unix": 1_700_000_000.0,
        "duration_s": duration_s,
        "phases": [
            {"name": "poll", "t0_s": 0.0, "dur_s": 0.05},
            {"name": "execute", "t0_s": 0.05,
             "dur_s": duration_s - 0.05},
        ],
        "spans": [
            {"name": "format", "phase": "execute", "t0_s": 0.05,
             "dur_s": 0.01},
            {"name": "encode", "phase": "execute", "t0_s": 0.06,
             "dur_s": 0.04},
            {"name": "step", "phase": "execute", "t0_s": 0.1,
             "dur_s": 0.3,
             "meta": {"splice_wait_s": splice_wait_s, "resume_step": 0}},
            {"name": "decode", "phase": "execute", "t0_s": 0.4,
             "dur_s": 0.05},
        ],
    }


# ---------------------------------------------------------------------------
# recorder units (fake clock)
# ---------------------------------------------------------------------------


def test_grant_stamps_trace_context_and_settle_builds_attribution():
    clock = [0.0]
    hive = MiniHive(lease_s=30.0, clock=lambda: clock[0])
    hive.submit(_job("f1"))

    [payload] = hive._take_jobs("wA")
    ctx = payload[TRACE_CTX_KEY]
    assert ctx["attempt"] == 1
    assert ctx["span_id"] == f"{ctx['trace_id']}.1"

    clock[0] = 1.0
    ack = hive._record_result(
        _ok_result("f1", "wA", digest=_digest(1, "wA")), "wA")
    assert ack == {"status": "ok"}
    # the digest was popped OFF the stored envelope into the record
    assert SPAN_DIGEST_KEY not in hive.completed["f1"]

    record = hive.flights.get("f1")
    assert record["model"] == "shared/tiny"
    assert record["workflow"] == "txt2img"
    assert record["deadline_s"] == 2.0
    assert [e["event"] for e in record["events"]] == \
        ["submit", "grant", "settled"]
    [attempt] = record["attempts"]
    assert attempt["attempt"] == 1 and attempt["worker"] == "wA"
    assert attempt["digest"]["worker"] == "wA"

    attribution = record["attribution"]
    assert attribution["measured"] is True
    assert set(attribution["phases"]) == set(ATTRIBUTION_PHASES)
    # grant at t=0, settle at t=1.0, digest covers 0.5s of worker time:
    # the upload leg is the hive-anchored remainder
    assert attribution["phases"]["upload"] == pytest.approx(0.5)
    assert attribution["phases"]["admission"] == pytest.approx(0.1)
    assert attribution["phases"]["steps"] == pytest.approx(0.3)
    assert attribution["phases"]["decode"] == pytest.approx(0.05)
    assert attribution["total_s"] == pytest.approx(1.0)
    assert hive.flights.verify(["f1"]) == []

    # the lane splice wait splits out of the step span
    hive.submit(_job("f2"))
    hive._take_jobs("wA")
    clock[0] = 2.0
    hive._record_result(
        _ok_result("f2", "wA",
                   digest=_digest(1, "wA", splice_wait_s=0.2)), "wA")
    phases = hive.flights.get("f2")["attribution"]["phases"]
    assert phases["lane_wait"] == pytest.approx(0.2)
    assert phases["steps"] == pytest.approx(0.1)

    # a garbage digest "attempt" from the wire must degrade to the
    # lease books (digest dropped, not filed as an orphan), never crash
    # an already-counted settle into a permanently unsettled record
    hive.submit(_job("f3"))
    hive._take_jobs("wA")
    clock[0] = 3.0
    bad = _ok_result("f3", "wA",
                     digest={"attempt": "x", "worker": "wA"})
    assert hive._record_result(bad, "wA") == {"status": "ok"}
    record = hive.flights.get("f3")
    assert record["settled"]["attempt"] == 1
    assert all(a["digest"] is None for a in record["attempts"])
    assert hive.flights.verify(["f3"]) == []


def test_flight_endpoints_serve_record_and_404():
    async def scenario():
        import aiohttp

        clock = [0.0]
        hive = MiniHive(lease_s=30.0, clock=lambda: clock[0])
        await hive.start()
        try:
            hive.submit(_job("e1"))
            hive._take_jobs("wA")
            clock[0] = 0.4
            hive._record_result(
                _ok_result("e1", "wA", digest=_digest(1, "wA")), "wA")
            async with aiohttp.ClientSession() as session:
                async with session.get(
                        f"{hive.uri}/api/flight/e1") as resp:
                    assert resp.status == 200
                    record = await resp.json()
                async with session.get(
                        f"{hive.uri}/api/flight/ghost") as resp:
                    assert resp.status == 404
                    missing = await resp.json()
                async with session.get(
                        f"{hive.uri}/api/flight") as resp:
                    assert resp.status == 200
                    index = await resp.json()
        finally:
            await hive.stop()
        return record, missing, index

    record, missing, index = asyncio.run(scenario())
    assert record["job_id"] == "e1"
    assert record["settled"]["outcome"] == "ok"
    assert record["attribution"]["measured"] is True
    assert missing["status"] == "unknown"
    assert index["jobs"] == ["e1"] and index["settled"] == 1


def test_shed_requeue_complete_yields_one_record_with_attempt_chain():
    clock = [0.0]
    hive = MiniHive(lease_s=30.0, clock=lambda: clock[0])
    hive.submit(_job("s1"))

    [first] = hive._take_jobs("wA")
    clock[0] = 0.5
    shed = error_result(_job("s1"), "shed by overload control",
                        kind="overloaded")
    shed[SPAN_DIGEST_KEY] = _digest(1, "wA", duration_s=0.1)
    assert hive._record_result(shed, "wA")["status"] == "requeued"

    clock[0] = 1.0
    [second] = hive._take_jobs("wB")
    assert second[TRACE_CTX_KEY]["attempt"] == 2
    assert second[TRACE_CTX_KEY]["trace_id"] == \
        first[TRACE_CTX_KEY]["trace_id"]

    clock[0] = 2.0
    hive._record_result(_ok_result("s1", "wB", digest=_digest(2, "wB")),
                        "wB")

    record = hive.flights.get("s1")
    events = [e["event"] for e in record["events"]]
    assert events == ["submit", "grant", "redispatched", "grant",
                      "settled"]
    assert [a["attempt"] for a in record["attempts"]] == [1, 2]
    # BOTH attempts' digests are part of the story — the shed one too
    assert [a["digest"]["worker"] for a in record["attempts"]] == \
        ["wA", "wB"]
    assert record["settled"] == {"t": 2.0, "worker": "wB",
                                 "outcome": "ok", "attempt": 2}
    # the failed attempt's wall time books as retry overhead
    assert record["attribution"]["phases"]["retry"] == pytest.approx(0.5)
    assert hive.flights.verify(["s1"]) == []


def test_late_upload_salvage_completes_the_record():
    clock = [0.0]
    hive = MiniHive(lease_s=1.0, max_attempts=2, clock=lambda: clock[0])
    hive.submit(_job("z1"))
    for worker in ("wA", "wB"):
        hive._take_jobs(worker)
        clock[0] += 2.0
        hive.sweep()
    assert hive.abandoned == ["z1"]

    # the straggler upload lands anyway: salvage settles the record
    clock[0] += 1.0
    ack = hive._record_result(
        _ok_result("z1", "wB", digest=_digest(2, "wB")), "wB")
    assert ack == {"status": "ok"}
    record = hive.flights.get("z1")
    events = [e["event"] for e in record["events"]]
    assert "abandoned" in events and "salvaged" in events
    assert events.count("settled") == 1
    assert events.count("lease_expired") == 2
    assert record["settled"]["attempt"] == 2
    assert hive.flights.verify(["z1"]) == []
    # attribution must NOT double-count the salvaged attempt: attempt 1
    # (grant t=0 -> expiry t=2) is retry; attempt 2's grant-to-expiry
    # wall is the productive work its own digest attributes, so only
    # 2.0s books as retry, not 4.0
    attribution = record["attribution"]
    assert attribution["phases"]["retry"] == pytest.approx(2.0)
    total = attribution["total_s"]
    assert sum(attribution["phases"].values()) == pytest.approx(
        total, rel=0.01)

    # duplicate after settle: recorded, never re-settled
    hive._record_result(_ok_result("z1", "wA"), "wA")
    record = hive.flights.get("z1")
    assert [e["event"] for e in record["events"]].count("settled") == 1
    assert "duplicate_upload" in [e["event"] for e in record["events"]]


def test_verify_flags_missing_gaps_orphans_and_unsettled():
    recorder = FlightRecorder(capacity=8)
    recorder.open("v1", _job("v1"), t=0.0)
    recorder.grant("v1", attempt=1, worker="wA", t=0.1)
    assert recorder.verify(["v1"], require_settled=False) == []
    assert recorder.verify(["v1"]) == ["v1: never settled"]
    assert recorder.verify(["ghost"], require_settled=False) == \
        ["ghost: no flight record"]

    # attempt gap: grant 3 without 2
    recorder.grant("v1", attempt=3, worker="wB", t=0.2)
    problems = recorder.verify(["v1"], require_settled=False)
    assert any("attempt gap" in p for p in problems)

    # orphan digest: an attempt never granted
    recorder.open("v2", _job("v2"), t=0.0)
    recorder.grant("v2", attempt=1, worker="wA", t=0.1)
    recorder.add_digest("v2", _digest(7, "wX"))
    problems = recorder.verify(["v2"], require_settled=False)
    assert any("orphan span digest" in p for p in problems)

    # bounded store: eviction is counted
    small = FlightRecorder(capacity=2)
    for i in range(4):
        small.open(f"b{i}", _job(f"b{i}"), t=float(i))
    assert len(small) == 2 and small.evicted == 2
    assert small.snapshot()["evicted"] == 2


def test_span_digest_matches_real_trace_shape():
    trace = obs_trace.JobTrace(
        "job", id="d1", worker="wZ", attempt=2, trace_id="abc",
        span_id="abc.2", queued_s=0.25, resume_step=3)
    trace.phase("poll")
    trace.phase("execute")
    with trace.active():
        with obs_trace.span("format"):
            pass
        with obs_trace.span("encode"):
            pass
        with obs_trace.span("step", steps=2) as step:
            time.sleep(0.01)
            step.meta["splice_wait_s"] = 0.004
        with obs_trace.span("decode"):
            pass
    trace.phase("upload")
    digest = span_digest(trace, worker_name="wZ")
    assert digest["trace_id"] == "abc" and digest["span_id"] == "abc.2"
    assert digest["attempt"] == 2 and digest["worker"] == "wZ"
    assert digest["queued_s"] == 0.25 and digest["resume_step"] == 3.0
    assert [p["name"] for p in digest["phases"]] == \
        ["poll", "execute", "upload"]
    names = [s["name"] for s in digest["spans"]]
    assert names == ["format", "encode", "step", "decode"]
    step_entry = digest["spans"][2]
    assert step_entry["phase"] == "execute"
    assert step_entry["meta"]["splice_wait_s"] == 0.004
    assert step_entry["dur_s"] > 0
    json.dumps(digest)  # wire-safe

    # feed it through attribution end to end
    recorder = FlightRecorder(capacity=4)
    recorder.open("d1", _job("d1"), t=0.0)
    recorder.grant("d1", attempt=2, worker="wZ", t=0.1)
    recorder.add_digest("d1", digest)
    recorder.settle("d1", t=1.0, worker="wZ", outcome="ok", attempt=2)
    attribution = recorder.get("d1")["attribution"]
    assert attribution["phases"]["lane_wait"] == pytest.approx(
        0.004, abs=1e-6)
    assert attribution["phases"]["steps"] > 0


def test_attribution_without_digest_degrades_to_hive_phases():
    recorder = FlightRecorder(capacity=4)
    recorder.open("h1", _job("h1"), t=0.0)
    recorder.grant("h1", attempt=1, worker="wA", t=0.5)
    recorder.settle("h1", t=2.0, worker="wA", outcome="ok", attempt=1)
    attribution = recorder.get("h1")["attribution"]
    assert attribution["measured"] is False
    assert attribution["phases"]["hive_queue"] == pytest.approx(0.5)
    # the worker-side seconds are unattributable without a digest
    assert attribution["phases"]["other"] == pytest.approx(1.5)
    assert budget_attribution({"settled": None}) is None


# ---------------------------------------------------------------------------
# renderers + the CLI
# ---------------------------------------------------------------------------


def _settled_record() -> dict:
    clock = [0.0]
    hive = MiniHive(lease_s=30.0, clock=lambda: clock[0])
    hive.submit(_job("r1"))
    hive._take_jobs("wA")
    clock[0] = 0.5
    shed = error_result(_job("r1"), "shed", kind="overloaded")
    shed[SPAN_DIGEST_KEY] = _digest(1, "wA", duration_s=0.1)
    hive._record_result(shed, "wA")
    clock[0] = 1.0
    hive._take_jobs("wB")
    clock[0] = 2.0
    hive._record_result(
        _ok_result("r1", "wB", digest=_digest(2, "wB")), "wB")
    return hive.flights.get("r1")


def test_renderers_stitch_attempts_across_workers():
    record = _settled_record()
    tree = render_tree(record)
    assert "attempt 1 on wA" in tree and "attempt 2 on wB" in tree
    assert "redispatched" in tree and "budget attribution" in tree
    assert "clock_skew_s" in tree

    timeline = render_timeline(record)
    assert "[wA#1]" in timeline and "[wB#2]" in timeline
    assert "[hive] settled" in timeline

    chrome = flight_to_chrome(record)
    events = chrome["traceEvents"]
    # pid 0 = hive instants; one pid per worker; tid = attempt
    pids = {e["pid"] for e in events}
    assert {0, 1, 2} <= pids
    assert any(e["ph"] == "i" and e["name"] == "grant" for e in events)
    worker_names = {e["args"]["name"] for e in events
                    if e.get("name") == "process_name"}
    assert {"hive", "worker wA", "worker wB"} <= worker_names
    span_events = [e for e in events if e["ph"] == "X"]
    assert all(e["dur"] >= 1 for e in span_events)
    json.dumps(chrome)


def test_job_flight_cli_renders_from_file(tmp_path):
    record = _settled_record()
    path = tmp_path / "flight.json"
    path.write_text(json.dumps(record))
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "job_flight.py"),
         "--file", str(path)],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "attempt 2 on wB" in out.stdout
    perfetto = subprocess.run(
        [sys.executable, str(REPO / "tools" / "job_flight.py"),
         "--file", str(path), "--format", "perfetto"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert perfetto.returncode == 0, perfetto.stderr
    doc = json.loads(perfetto.stdout)
    assert doc["traceEvents"]


# ---------------------------------------------------------------------------
# real-worker wire contract (ChaoticExecutor — no pipelines)
# ---------------------------------------------------------------------------


class StubSlot:
    depth = 2
    data_width = 1

    def descriptor(self):
        return "stub"


def _worker_settings(uri: str, name: str, **over):
    from chiaswarm_tpu.node.settings import Settings

    base = dict(
        hive_uri=uri, hive_token="t", worker_name=name,
        job_deadline_s=30.0, poll_busy_s=0.02, poll_idle_s=0.04,
        poll_backoff_base_s=0.02, poll_backoff_cap_s=0.1,
        upload_retries=3, upload_retry_delay_s=0.02,
        drain_timeout_s=5.0, result_drain_timeout_s=5.0,
        install_signal_handlers=False,
    )
    base.update(over)
    return Settings(**base)


def _run_worker_against(hive, jobs, **settings_over):
    async def scenario():
        uri = await hive.start()
        for job in jobs:
            hive.submit(job)
        worker = Worker(settings=_worker_settings(uri, "flight-w",
                                                  **settings_over),
                        pool=[StubSlot()],
                        registry=ModelRegistry(catalog=[],
                                               allow_random=True),
                        executor=ChaoticExecutor())
        task = asyncio.create_task(worker.run())
        try:
            await hive.wait_for_results(len(jobs), timeout=60)
        finally:
            worker.request_stop()
            await asyncio.wait_for(task, timeout=20)
            await hive.stop()
        return worker

    return asyncio.run(scenario())


def test_reference_hive_parity_no_context_no_digest():
    """With no hive trace context the upload payload is byte-compatible
    with today's: exactly the historical key set, no span digest — and
    the trace still stamps queued_s + attempt as root attributes
    (ISSUE 13 satellite)."""
    hive = ChaoticHive()
    worker = _run_worker_against(hive, [_job("p1")])
    [result] = hive.results
    assert set(result) == {"id", "artifacts", "nsfw", "worker_version",
                           "pipeline_config", "worker_name"}
    assert SPAN_DIGEST_KEY not in result
    [trace] = worker.traces.traces()
    assert trace.meta["attempt"] == 1
    assert trace.meta["queued_s"] == 0.0
    assert "trace_id" not in trace.meta


def test_minihive_job_uploads_digest_and_record_settles():
    """A context-carrying job's upload rides a real span digest; the
    hive pops it into the flight record (stored envelope unchanged) and
    the settled record attributes the budget."""
    hive = MiniHive(lease_s=30.0, delay_s=0.01)
    worker = _run_worker_against(hive, [_job("m1")])
    result = hive.completed["m1"]
    assert SPAN_DIGEST_KEY not in result
    assert set(result) == {"id", "artifacts", "nsfw", "worker_version",
                           "pipeline_config", "worker_name"}

    record = hive.flights.get("m1")
    [attempt] = record["attempts"]
    digest = attempt["digest"]
    assert digest["worker"] == "flight-w" and digest["attempt"] == 1
    assert [p["name"] for p in digest["phases"]] == \
        ["poll", "execute", "upload"]
    assert digest["trace_id"] == record["trace_id"]
    assert digest["span_id"] == f"{record['trace_id']}.1"
    assert record["settled"]["outcome"] == "ok"
    assert record["attribution"]["measured"] is True
    assert hive.flights.verify(["m1"]) == []
    # the worker-side trace JOINed the hive context
    [trace] = worker.traces.traces()
    assert trace.meta["trace_id"] == record["trace_id"]
    # queued_s rides the trace root on context-ful jobs too
    assert trace.meta["queued_s"] >= 0.0


def test_fleet_snapshot_from_real_heartbeats():
    """Heartbeats push per-worker metric snapshots; /api/fleet (and
    fleet_snapshot()) aggregates them — the item-5 data plane."""
    async def scenario():
        hive = MiniHive(lease_s=30.0, delay_s=0.01)
        uri = await hive.start()
        hive.submit(_job("hb1"))
        worker = Worker(settings=_worker_settings(uri, "flight-w",
                                                  heartbeat_s=0.05),
                        pool=[StubSlot()],
                        registry=ModelRegistry(catalog=[],
                                               allow_random=True),
                        executor=ChaoticExecutor())
        task = asyncio.create_task(worker.run())
        try:
            await hive.wait_for_results(1, timeout=60)
            # idle beats keep pushing metrics: wait for the first one
            deadline = time.monotonic() + 30
            while "flight-w" not in hive.fleet and \
                    time.monotonic() < deadline:
                await asyncio.sleep(0.02)
        finally:
            worker.request_stop()
            await asyncio.wait_for(task, timeout=20)
            await hive.stop()
        return hive

    hive = asyncio.run(scenario())
    snap = hive.fleet_snapshot()
    assert "flight-w" in snap["workers"]
    entry = snap["workers"]["flight-w"]
    for key in ("queue_depth", "inflight_jobs", "jobs_done",
                "chips_in_service", "overload"):
        assert key in entry, key
    aggregate = snap["aggregate"]
    assert aggregate["workers_reporting"] == 1
    assert aggregate["chips_in_service"] >= 1
    assert aggregate["completed_jobs"] == 1
    assert aggregate["observed_arrival_jobs_s"] >= 0.0

    # a DEAD worker's stale snapshot stays visible per-worker but must
    # not inflate the aggregate capacity an autoscaler provisions by
    hive.fleet["ghost"] = {"at": -1e9,
                           "metrics": {"chips_in_service": 50,
                                       "arrival_rate_rows_s": 99.0}}
    snap2 = hive.fleet_snapshot()
    assert snap2["workers"]["ghost"]["live"] is False
    assert snap2["aggregate"]["workers_reporting"] == 2
    assert snap2["aggregate"]["chips_in_service"] == \
        aggregate["chips_in_service"]
    assert snap2["aggregate"]["arrival_rate_rows_s"] < 99.0


# ---------------------------------------------------------------------------
# THE acceptance gate (slow tier; always runs in the CI Flight suite)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_flight_gate_kill_mid_lane_single_stitched_record(
        monkeypatch, tmp_path):
    """ISSUE 13 acceptance: 3 real-lane workers, one scripted mid-lane
    kill — the killed job's flight record stitches BOTH workers into
    one story (grant attempt 1 on the victim, checkpoint markers,
    redelivery, grant attempt 2 on a survivor whose digest records
    resume_step >= 1, exactly-once settle), and tools/job_flight.py
    renders it."""
    import jax

    from chiaswarm_tpu.core.chip_pool import ChipPool
    from chiaswarm_tpu.core.mesh import MeshSpec

    monkeypatch.setenv("CHIASWARM_STEPPER", "1")
    monkeypatch.setenv("CHIASWARM_STEPPER_CKPT_EVERY", "1")
    monkeypatch.setenv("CHIASWARM_STEPPER_STEP_DELAY_S", "0.08")

    registry = ModelRegistry(
        catalog=[{"name": "tiny", "family": "tiny", "parameters": {}}],
        allow_random=True)

    def lane_job(i: int) -> dict:
        return {"id": f"fl-{i}", "model_name": "tiny",
                "prompt": f"flight prompt {i}", "seed": 700 + i,
                "num_inference_steps": 24, "guidance_scale": 7.5,
                "height": 64, "width": 64, "content_type": "image/png"}

    async def scenario():
        hive = MiniHive(lease_s=60.0, delay_s=0.01, max_jobs_per_poll=1)
        uri = await hive.start()
        for i in range(3):
            hive.submit(lane_job(i))
        workers = []
        for tag in ("a", "b", "c"):
            pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 1}),
                            devices=jax.devices()[:1])
            workers.append(Worker(
                settings=_worker_settings(uri, f"flgate-{tag}",
                                          job_deadline_s=600.0,
                                          heartbeat_s=0.05),
                registry=registry, pool=pool))
        tasks = {w.settings.worker_name: asyncio.create_task(w.run())
                 for w in workers}
        victim = victim_job = None
        try:
            deadline = time.monotonic() + 240
            while victim is None and time.monotonic() < deadline:
                for job_id, ckpt in list(hive.checkpoints.items()):
                    holder = hive.lease_holder(job_id)
                    if ckpt.get("kind") == "lane" and \
                            int(ckpt.get("step", 0)) >= 1 and \
                            holder is not None:
                        victim_job, victim = job_id, holder
                        hive.partition(holder)
                        break
                if victim is None:
                    await asyncio.sleep(0.02)
            assert victim is not None, \
                f"no lane checkpoint ever reached the hive: {hive.stats()}"
            tasks[victim].cancel()
            await asyncio.gather(tasks[victim], return_exceptions=True)
            assert victim_job in hive.expire_worker(victim)
            await hive.wait_for_results(3, timeout=300)
        finally:
            for worker in workers:
                worker.request_stop()
            await asyncio.gather(*(asyncio.wait_for(t, timeout=60)
                                   for t in tasks.values()),
                                 return_exceptions=True)
            for worker in workers:
                for slot in worker.pool:
                    stepper = getattr(slot, "_stepper", None)
                    if stepper is not None:
                        stepper.shutdown()
            await hive.stop()
        return hive, victim, victim_job

    hive, victim, victim_job = asyncio.run(scenario())

    # exactly-once settle for every job, complete flight records all
    uploaded = hive.uploaded_ids()
    assert sorted(uploaded) == ["fl-0", "fl-1", "fl-2"]
    assert len(uploaded) == len(set(uploaded))
    assert hive.flights.verify(["fl-0", "fl-1", "fl-2"]) == []

    # ONE stitched record spans both workers with the full chain
    record = hive.flights.get(victim_job)
    events = [e["event"] for e in record["events"]]
    assert events.count("settled") == 1
    assert "checkpoint" in events
    assert "redelivered" in events or "lease_expired" in events
    grants = [e for e in record["events"] if e["event"] == "grant"]
    assert [g["attempt"] for g in grants][:2] == [1, 2]
    assert grants[0]["worker"] == victim
    survivor = record["settled"]["worker"]
    assert survivor != victim

    # the settling attempt's digest proves the mid-trajectory resume
    digests = {a["attempt"]: a["digest"]
               for a in record["attempts"] if a["digest"]}
    final = digests[record["settled"]["attempt"]]
    assert final["worker"] == survivor
    assert float(final.get("resume_step") or 0) >= 1
    step_spans = [s for s in final["spans"] if s["name"] == "step"]
    assert step_spans and all(s["dur_s"] > 0 for s in step_spans)
    assert record["attribution"]["phases"]["steps"] > 0

    # checkpoint markers on the timeline carry the victim's progress
    marks = [e for e in record["events"] if e["event"] == "checkpoint"]
    assert any(int(m.get("step") or 0) >= 1 for m in marks)

    # and the CLI renders the stitched record
    path = tmp_path / "gate-flight.json"
    path.write_text(json.dumps(record))
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "job_flight.py"),
         "--file", str(path), "--format", "timeline"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert out.returncode == 0, out.stderr
    assert f"[{survivor}#" in out.stdout
    assert "checkpoint" in out.stdout
