import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chiaswarm_tpu.models.clip import ClipTextEncoder
from chiaswarm_tpu.models.configs import FAMILIES, get_family
from chiaswarm_tpu.models.unet import UNet, timestep_embedding
from chiaswarm_tpu.models.vae import AutoencoderKL, tiled_decode

TINY = FAMILIES["tiny"]
TINY_XL = FAMILIES["tiny_xl"]


def test_family_lookup():
    assert get_family("stabilityai/stable-diffusion-xl-base-1.0").name == "sdxl"
    assert get_family("stabilityai/stable-diffusion-2-1").name == "sd21"
    assert get_family("runwayml/stable-diffusion-v1-5").name == "sd15"
    assert get_family("tiny").name == "tiny"


def test_timestep_embedding_properties():
    emb = timestep_embedding(jnp.array([0.0, 500.5, 999.0]), 32)
    assert emb.shape == (3, 32)
    assert np.isfinite(np.asarray(emb)).all()
    # distinct timesteps -> distinct embeddings
    assert not np.allclose(np.asarray(emb[0]), np.asarray(emb[1]))


def test_clip_text_encoder_shapes_and_pooling():
    cfg = TINY.text_encoders[0]
    model = ClipTextEncoder(cfg)
    ids = jnp.array([[1, 5, 7, cfg.eos_token_id] + [0] * 73], dtype=jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)
    seq, pooled = model.apply(params, ids)
    assert seq.shape == (1, 77, cfg.hidden_size)
    assert pooled.shape == (1, cfg.hidden_size)

    # projection head variant (SDXL encoder 2 shape)
    cfg2 = TINY_XL.text_encoders[1]
    model2 = ClipTextEncoder(cfg2)
    params2 = model2.init(jax.random.PRNGKey(0), ids)
    seq2, pooled2 = model2.apply(params2, ids)
    assert pooled2.shape == (1, cfg2.projection_dim)
    # penultimate readout without final LN differs from final-LN readout
    assert seq2.shape == (1, 77, cfg2.hidden_size)


def test_clip_causality():
    """Changing a later token must not affect earlier sequence outputs."""
    cfg = TINY.text_encoders[0]
    model = ClipTextEncoder(cfg)
    ids = jnp.zeros((1, 10), dtype=jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)
    a, _ = model.apply(params, ids.at[0, 9].set(3))
    b, _ = model.apply(params, ids.at[0, 9].set(7))
    assert np.allclose(np.asarray(a[0, :9]), np.asarray(b[0, :9]), atol=1e-5)
    assert not np.allclose(np.asarray(a[0, 9]), np.asarray(b[0, 9]), atol=1e-5)


def test_unet_forward_tiny():
    unet = UNet(TINY.unet)
    x = jnp.zeros((2, 8, 8, 4))
    t = jnp.array([10.0, 500.0])
    ctx = jnp.zeros((2, 77, TINY.unet.cross_attention_dim))
    params = jax.jit(unet.init)(jax.random.PRNGKey(0), x, t, ctx)
    out = jax.jit(unet.apply)(params, x, t, ctx)
    assert out.shape == (2, 8, 8, 4)
    assert np.isfinite(np.asarray(out)).all()


def test_unet_forward_tiny_xl_added_cond():
    unet = UNet(TINY_XL.unet)
    x = jnp.zeros((1, 8, 8, 4))
    t = jnp.array([3.0])
    ctx = jnp.zeros((1, 77, TINY_XL.unet.cross_attention_dim))
    added = {
        "time_ids": jnp.ones((1, 6)),
        "text_embeds": jnp.ones((1, TINY_XL.unet.addition_pooled_dim)),
    }
    params = unet.init(jax.random.PRNGKey(0), x, t, ctx, added)
    out = unet.apply(params, x, t, ctx, added)
    assert out.shape == (1, 8, 8, 4)

    with pytest.raises(ValueError):
        unet.init(jax.random.PRNGKey(0), x, t, ctx, None)


def test_unet_class_label_conditioning():
    """x4-upscaler-class noise-level conditioning: a class-embedding
    family forwards with labels, responds to the label value, and
    refuses to run without one."""
    from chiaswarm_tpu.models.configs import TINY_UP4

    unet = UNet(TINY_UP4.unet)
    x = jnp.ones((2, 8, 8, TINY_UP4.unet.sample_channels)) * 0.1
    t = jnp.array([10.0, 10.0])
    ctx = jnp.zeros((2, 77, TINY_UP4.unet.cross_attention_dim))
    labels = jnp.array([0, 0], jnp.int32)
    params = unet.init(jax.random.PRNGKey(0), x, t, ctx,
                       class_labels=labels)
    out = unet.apply(params, x, t, ctx, class_labels=labels)
    assert out.shape == (2, 8, 8, TINY_UP4.unet.out_channels)
    # the embedding table participates: different levels, different output
    out2 = unet.apply(params, x, t, ctx,
                      class_labels=jnp.array([40, 40], jnp.int32))
    assert not np.allclose(np.asarray(out), np.asarray(out2), atol=1e-5)

    with pytest.raises(ValueError, match="class_labels"):
        unet.init(jax.random.PRNGKey(0), x, t, ctx)


def test_unet_timestep_sensitivity():
    unet = UNet(TINY.unet)
    x = jnp.ones((1, 8, 8, 4)) * 0.1
    ctx = jnp.zeros((1, 77, TINY.unet.cross_attention_dim))
    params = unet.init(jax.random.PRNGKey(0), x, jnp.array([1.0]), ctx)
    o1 = unet.apply(params, x, jnp.array([1.0]), ctx)
    o2 = unet.apply(params, x, jnp.array([900.0]), ctx)
    assert not np.allclose(np.asarray(o1), np.asarray(o2), atol=1e-4)


def test_vae_roundtrip_shapes():
    vae = AutoencoderKL(TINY.vae)
    img = jnp.zeros((1, 32, 32, 3))
    params = vae.init(jax.random.PRNGKey(0), img)
    z = vae.apply(params, img, method=AutoencoderKL.encode)
    f = TINY.vae.downscale
    assert z.shape == (1, 32 // f, 32 // f, TINY.vae.latent_channels)
    rec = vae.apply(params, z, method=AutoencoderKL.decode)
    assert rec.shape == (1, 32, 32, 3)


def test_vae_encode_is_stochastic_only_with_rng():
    vae = AutoencoderKL(TINY.vae)
    img = jnp.ones((1, 16, 16, 3)) * 0.5
    params = vae.init(jax.random.PRNGKey(0), img)
    z1 = vae.apply(params, img, method=AutoencoderKL.encode)
    z2 = vae.apply(params, img, method=AutoencoderKL.encode)
    assert np.allclose(np.asarray(z1), np.asarray(z2))
    z3 = vae.apply(params, img, jax.random.PRNGKey(1),
                   method=AutoencoderKL.encode)
    assert not np.allclose(np.asarray(z1), np.asarray(z3))


def test_tiled_decode_matches_single_tile():
    vae = AutoencoderKL(TINY.vae)
    rng = np.random.default_rng(3)
    img = jnp.asarray(rng.uniform(-1, 1, (1, 32, 32, 3)), dtype=jnp.float32)
    params = vae.init(jax.random.PRNGKey(0), img)
    z = vae.apply(params, img, method=AutoencoderKL.encode)
    direct = np.asarray(vae.apply(params, z, method=AutoencoderKL.decode))
    # tile covers the whole latent -> must match direct decode exactly,
    # including the first/last rows and columns (border-weight regression)
    whole = np.asarray(tiled_decode(vae, params, z, tile=64, overlap=8))
    assert np.allclose(whole, direct, atol=1e-5)
    assert abs(whole[0, 0].mean() - direct[0, 0].mean()) < 1e-5
    # smaller tiles: same shape, finite, borders not zeroed, interior close
    tiled = np.asarray(tiled_decode(vae, params, z, tile=8, overlap=4))
    assert tiled.shape == direct.shape
    assert np.isfinite(tiled).all()
    assert abs(tiled[0, 0]).max() > 0  # no black border line
    assert abs(tiled[0, :, 0]).max() > 0


def test_cross_attention_single_key_fast_path_exact():
    """A one-token context makes softmax degenerate (one key -> weight 1),
    so CrossAttention's fast path must equal the full attention math:
    out = to_out(to_v(ctx)) at every query position, queries irrelevant."""
    from chiaswarm_tpu.models.unet import CrossAttention

    attn = CrossAttention(num_heads=2, head_dim=8)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(2, 5, 16)), jnp.float32)
    ctx1 = jnp.asarray(rng.normal(size=(2, 1, 12)), jnp.float32)
    params = attn.init(jax.random.PRNGKey(0), x, ctx1)
    out = np.asarray(attn.apply(params, x, ctx1))

    # reference: the general math with an explicit softmax over the 1 key
    p = params["params"]
    v = ctx1 @ p["to_v"]["kernel"]                       # (2, 1, 16)
    ref = v @ p["to_out"]["kernel"] + p["to_out"]["bias"]
    ref = np.broadcast_to(np.asarray(ref), out.shape)
    np.testing.assert_allclose(out, ref, atol=1e-6)
    # every query position sees the same attended value
    assert np.allclose(out[:, 0], out[:, 1])

    # divisible-batch form: an unbroadcast (B, 1, D) context against
    # (B*m, L, D) queries must equal broadcasting the context by hand
    xb = jnp.asarray(rng.normal(size=(6, 5, 16)), jnp.float32)  # m = 3
    manual = np.asarray(attn.apply(
        params, xb,
        jnp.repeat(ctx1, 3, axis=0)))  # b-major repeat: [c0,c0,c0,c1,...]
    fast = np.asarray(attn.apply(params, xb, ctx1))
    np.testing.assert_allclose(fast, manual, atol=1e-6)

    # the general path (s > 1) accepts the same un-broadcast form
    ctx2 = jnp.asarray(rng.normal(size=(2, 4, 12)), jnp.float32)
    manual2 = np.asarray(attn.apply(params, xb, jnp.repeat(ctx2, 3, axis=0)))
    general = np.asarray(attn.apply(params, xb, ctx2))
    np.testing.assert_allclose(general, manual2, atol=1e-6)
