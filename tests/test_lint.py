"""Tier-1 lint gate: the repo stays clean under its own static analysis.

``swarmlint`` (chiaswarm_tpu/analysis) enforces the TPU invariants the
runtime modules document in prose — no host sync reachable from jit, no
PRNG key reuse, compat-shimmed jax imports, no import-time device init,
toplevel_jit hygiene, shape bucketing before compiled code. This gate
fails the suite the moment a non-baselined finding lands, and fails under
strict mode when a baseline entry goes stale (fixed findings must be
deleted from the baseline — it only shrinks).
"""

from __future__ import annotations

import os
import subprocess
import sys

from chiaswarm_tpu.analysis import run
from chiaswarm_tpu.analysis.runner import DEFAULT_LINT_PATHS, repo_root

ROOT = repo_root()


def test_package_and_tests_are_lint_clean():
    result = run([os.path.join(ROOT, p) for p in DEFAULT_LINT_PATHS],
                 strict=True)
    assert result.exit_code == 0, "\n" + result.report
    assert not result.errors, result.errors


def test_cli_entrypoint_is_clean_and_exits_zero():
    """The exact command the docs/CI advertise (default paths)."""
    proc = subprocess.run(
        [sys.executable, "-m", "chiaswarm_tpu.analysis", "--strict"],
        cwd=ROOT, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 findings" in proc.stdout, proc.stdout


def test_linter_is_stdlib_only(tmp_path):
    """The pass must run where jax is NOT installed (CI lint job, hooks).
    Block jax imports with a poisoned stub and rerun the gate."""
    (tmp_path / "jax.py").write_text(
        'raise ImportError("jax unavailable in the lint environment")\n')
    env = dict(os.environ, PYTHONPATH=str(tmp_path))
    proc = subprocess.run(
        [sys.executable, "-m", "chiaswarm_tpu.analysis", "--strict"],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_all_rules_are_registered():
    from chiaswarm_tpu.analysis import all_rules

    codes = [r.code for r in all_rules()]
    assert codes == ["R1", "R2", "R3", "R4", "R5", "R6", "R7",
                     "R8", "R9", "R10", "R11", "R12", "R13",
                     "R14", "R15", "R16", "R17",
                     "R18", "R19", "R20", "R21"], codes
