"""Chaos suite: deterministic fault injection against a REAL Worker.

Acceptance invariant (ISSUE 2): under a scripted schedule of fault modes
(dropped polls, hive 5xx, injected latency, non-JSON 400s, malformed
jobs, executor crashes, OOMs, transient fetch failures, hangs past the
deadline, upload failures), every injected job ends as exactly ONE
uploaded success-or-error envelope or ONE dead-letter file — no silent
drops — and the worker exits cleanly on stop.

Everything here is hermetic and deterministic: explicit fault scripts
(node/chaos.py), seeded jitter (node/resilience.py), no real pipelines
(the ChaoticExecutor replaces the executor seam), no network beyond
loopback.
"""

from __future__ import annotations

import asyncio
import json

import pytest

from chiaswarm_tpu.node.chaos import ChaoticExecutor, ChaoticHive
from chiaswarm_tpu.node.hive import BadWorkerError, HiveClient
from chiaswarm_tpu.node.registry import ModelRegistry
from chiaswarm_tpu.node.resilience import (
    Backoff,
    BreakerBoard,
    DeadLetterSpool,
    backoff_delay,
    classify_exception,
    classify_result,
)
from chiaswarm_tpu.node.settings import Settings
from chiaswarm_tpu.node.worker import Worker


@pytest.fixture(autouse=True)
def _tmp_root(tmp_path, monkeypatch):
    """Isolate settings root (logs, dead-letter spool) per test."""
    monkeypatch.setenv("SWARM_TPU_ROOT", str(tmp_path))
    return tmp_path


@pytest.fixture(autouse=True)
def _restore_matmul_precision():
    """Worker.startup() pins bf16 matmuls; restore the suite's precision
    so chaos tests (early in collection order) don't skew later numeric
    tests."""
    import jax

    before = jax.config.jax_default_matmul_precision
    yield
    jax.config.update("jax_default_matmul_precision", before)


class StubSlot:
    """Executor-less slot: the ChaoticExecutor never touches the mesh.
    ``__call__`` mirrors the real slot contract (core/chip_pool.py) just
    enough for tests that drive the REAL executor's error paths —
    callbacks that raise before touching any device."""

    def __init__(self, depth: int = 2, data_width: int = 1,
                 name: str = "stub"):
        self.depth = depth
        self.data_width = data_width
        self.name = name

    def descriptor(self):
        return self.name

    def __call__(self, callback, **kwargs):
        model_name = kwargs.pop("model_name", None)
        seed = int(kwargs.pop("seed", None) or 0)
        artifacts, config = callback(self, model_name, seed=seed, **kwargs)
        config = dict(config)
        config["seed"] = seed
        return artifacts, config


def chaos_settings(uri: str = "http://unused", **over) -> Settings:
    base = dict(
        hive_uri=uri, hive_token="t", worker_name="chaos-worker",
        job_deadline_s=0.25,
        transient_retries=2,
        retry_backoff_s=0.01, retry_backoff_cap_s=0.05,
        breaker_threshold=2, breaker_cooldown_s=3600.0,
        poll_busy_s=0.02, poll_idle_s=0.05,
        poll_backoff_base_s=0.02, poll_backoff_cap_s=0.1,
        upload_retries=3, upload_retry_delay_s=0.01,
        drain_timeout_s=5.0, result_drain_timeout_s=5.0,
        install_signal_handlers=False,
    )
    base.update(over)
    return Settings(**base)


def _cjob(job_id: str, chaos=None, model: str | None = None, **over):
    job = {"id": job_id, "model_name": model or f"model/{job_id}",
           "prompt": f"p {job_id}", "num_inference_steps": 2,
           "height": 64, "width": 64, "content_type": "application/json"}
    if chaos is not None:
        job["chaos"] = chaos
    job.update(over)
    return job


def _worker(settings: Settings, executor: ChaoticExecutor,
            registry=None, hive=None, slots=None) -> Worker:
    return Worker(settings=settings,
                  pool=slots if slots is not None else [StubSlot()],
                  registry=registry if registry is not None else object(),
                  hive=hive if hive is not None else object(),
                  executor=executor)


# ---------------------------------------------------------------------------
# the acceptance scenario: scripted multi-mode fault schedule, zero loss
# ---------------------------------------------------------------------------


def test_chaos_zero_loss_e2e(tmp_path):
    """≥5 fault modes in one scripted run; every job accounted for as
    exactly one uploaded envelope or one dead-letter file; clean exit."""

    async def scenario():
        hive = ChaoticHive(
            # poll-side faults: dropped connection, server error, injected
            # latency, non-JSON misbehaving-worker 400, malformed job
            poll_faults=["drop", "ok", "http_500", "delay", "bad_worker",
                         "malformed"],
            # result-side faults, keyed by job id so upload order is moot
            result_faults={
                "c-retry": ["http_500", "ok"],
                "c-retry2": ["drop", "ok"],
                "c-dead": ["http_500"] * 10,  # exhausts every attempt
            },
            delay_s=0.02,
        )
        uri = await hive.start()
        jobs = [
            _cjob("c-ok"),
            _cjob("c-crash", chaos=["crash"]),       # executor raises
            _cjob("c-oom", chaos=["oom", "ok"]),     # ladder re-runs solo
            _cjob("c-fetch", chaos=["fetch", "ok"]),  # transient retry
            _cjob("c-hang", chaos=["hang"]),         # exceeds the deadline
            _cjob("c-fatal", chaos=["fatal"]),       # bad inputs
            _cjob("c-retry"),
            _cjob("c-retry2"),
            _cjob("c-dead"),
        ]
        for job in jobs:
            hive.submit(job)

        executor = ChaoticExecutor(hang_s=1.0)
        registry = ModelRegistry(catalog=[], allow_random=True)
        worker = Worker(settings=chaos_settings(uri), pool=[StubSlot()],
                        registry=registry, executor=executor)
        task = asyncio.create_task(worker.run())
        try:
            # all ids upload except c-dead (which must dead-letter);
            # malformed-1 is injected by the hive's own fault schedule
            await hive.wait_for_results(len(jobs) - 1 + 1, timeout=60)
            for _ in range(200):  # c-dead spools after its last retry
                if worker.dead_letters.depth() >= 1:
                    break
                await asyncio.sleep(0.05)
        finally:
            worker.request_stop()
            await asyncio.wait_for(task, timeout=20)  # clean exit
            await hive.stop()

        uploaded = hive.uploaded_ids()
        expected_upload = {j["id"] for j in jobs} - {"c-dead"}
        expected_upload.add("malformed-1")
        # exactly-once: no duplicates, no silent drops
        assert sorted(uploaded) == sorted(expected_upload)
        dead = list(worker.dead_letters.directory.glob("*.json"))
        assert len(dead) == 1
        assert json.loads(dead[0].read_text())["id"] == "c-dead"

        by_id = {r["id"]: r for r in hive.results}
        assert "error" not in by_id["c-ok"]["pipeline_config"]
        assert by_id["c-crash"]["pipeline_config"]["error_kind"] == "error"
        assert by_id["c-hang"]["pipeline_config"]["error_kind"] == "timeout"
        assert by_id["c-fatal"]["fatal_error"] is True
        # the ladder recovered these: final envelopes are successes
        for recovered in ("c-oom", "c-fetch"):
            assert "error" not in by_id[recovered]["pipeline_config"]
            assert executor.attempts[recovered] == 2

        # degradation-ladder observability (satellite: health counters)
        health = worker.health()
        assert health["jobs_timed_out"] >= 1
        assert health["jobs_retried"] >= 2
        assert health["jobs_failed"] >= 3
        assert health["upload_retries"] >= 3
        assert health["results_dead_lettered"] == 1
        assert health["dead_letter_depth"] == 1
        assert "breakers" in health
        # backoff reset on the first successful poll after the errors
        assert health["poll_consecutive_errors"] == 0

    asyncio.run(scenario())


# ---------------------------------------------------------------------------
# degradation ladder units (driven through the real Worker methods)
# ---------------------------------------------------------------------------


def test_oom_burst_splits_and_reruns_serially():
    """An OOM'd coalesced burst degrades to serial solo re-runs — the
    batched attempt happens once, then each member solo."""

    async def scenario():
        executor = ChaoticExecutor()
        worker = _worker(chaos_settings(), executor)
        jobs = [_cjob(f"b{i}", chaos=["oom", "ok"], model="shared/model")
                for i in range(3)]
        results = await worker._execute_burst(jobs, StubSlot())
        assert [classify_result(r) for r in results] == ["ok"] * 3
        assert executor.events[0] == ("batch", ["b0", "b1", "b2"])
        assert executor.events[1:] == [("solo", ["b0"]), ("solo", ["b1"]),
                                       ("solo", ["b2"])]
        assert worker.stats.jobs_retried == 3
        assert worker.stats.jobs_failed == 0  # all recovered

    asyncio.run(scenario())


def test_transient_fetch_failure_retries_with_backoff():
    async def scenario():
        executor = ChaoticExecutor()
        worker = _worker(chaos_settings(), executor)
        [result] = await worker._execute_burst(
            [_cjob("t1", chaos=["fetch", "fetch", "ok"])], StubSlot())
        assert classify_result(result) == "ok"
        assert executor.attempts["t1"] == 3  # 1 + transient_retries
        assert worker.stats.jobs_retried == 2

    asyncio.run(scenario())


def test_fatal_error_never_retried():
    async def scenario():
        executor = ChaoticExecutor()
        worker = _worker(chaos_settings(), executor)
        [result] = await worker._execute_burst(
            [_cjob("f1", chaos=["fatal", "ok"])], StubSlot())
        assert result["fatal_error"] is True
        assert executor.attempts["f1"] == 1
        assert worker.stats.jobs_failed == 1

    asyncio.run(scenario())


def test_deadline_uses_per_workflow_budget():
    """A hung job times out against ITS workflow's budget and reports an
    explicit timeout envelope (not a silent disappearance)."""

    async def scenario():
        executor = ChaoticExecutor(hang_s=30.0)
        settings = chaos_settings(
            job_deadline_s=100.0,  # generous default...
            workflow_deadline_s={"slowflow": 0.05})  # ...tight override
        worker = _worker(settings, executor)
        [result] = await worker._execute_burst(
            [_cjob("d1", chaos=["hang"], workflow="slowflow")], StubSlot())
        config = result["pipeline_config"]
        assert config["error_kind"] == "timeout"
        assert "deadline" in config["error"]
        assert "fatal_error" not in result  # the hive may retry elsewhere
        assert worker.stats.jobs_timed_out == 1

    asyncio.run(scenario())


def test_breaker_quarantines_model_then_probes_and_recovers():
    """K consecutive permanent failures quarantine the model in the
    registry (fast-refusal envelopes, no chip time); after the cooldown a
    half-open probe's success lifts the quarantine."""

    async def scenario():
        clock = [0.0]
        executor = ChaoticExecutor()
        registry = ModelRegistry(catalog=[], allow_random=True)
        worker = _worker(chaos_settings(), executor, registry=registry)
        worker.breakers = BreakerBoard(
            threshold=2, cooldown_s=10.0, clock=lambda: clock[0],
            on_open=registry.quarantine, on_close=registry.unquarantine,
            on_probe=registry.unquarantine)
        bad = "bad/checkpoint"

        for i in range(2):  # two consecutive execution crashes
            [result] = await worker._execute_burst(
                [_cjob(f"q{i}", chaos=["crash"], model=bad)], StubSlot())
            assert classify_result(result) == "error"
        assert registry.is_quarantined(bad)
        # satellite (ISSUE 8): quarantine surfaces through the ONE
        # authoritative per-model state enum /healthz serves
        assert registry.model_states()[bad] == "quarantined"
        assert worker.health()["models"][bad] == "quarantined"
        assert worker.health()["breakers"][bad]["state"] == "open"
        with pytest.raises(ValueError, match="quarantined"):
            registry.pipeline(bad)

        # while open: refused fast, executor never invoked
        [refused] = await worker._execute_burst(
            [_cjob("q2", chaos=["ok"], model=bad)], StubSlot())
        assert refused["pipeline_config"]["error_kind"] == "quarantined"
        assert "fatal_error" not in refused  # other nodes may serve it
        assert "q2" not in executor.attempts
        assert worker.stats.jobs_quarantined == 1

        clock[0] = 11.0  # past the cooldown: one half-open probe runs
        [probe] = await worker._execute_burst(
            [_cjob("q3", chaos=["ok"], model=bad)], StubSlot())
        assert classify_result(probe) == "ok"
        assert not registry.is_quarantined(bad)
        assert registry.model_states().get(bad) != "quarantined"
        assert worker.health()["breakers"][bad]["state"] == "closed"

    asyncio.run(scenario())


def test_half_open_admits_exactly_one_probe():
    """When the cooldown expires, a queued backlog must not stampede the
    likely-broken model: one probe at a time; its verdict decides."""
    clock = [0.0]
    board = BreakerBoard(threshold=1, cooldown_s=10.0,
                         clock=lambda: clock[0])
    board.record("m", ok=False)           # opens immediately (threshold 1)
    assert not board.allow("m")
    clock[0] = 11.0
    assert board.allow("m")               # the single half-open probe
    assert not board.allow("m")           # backlog stays gated
    assert not board.allow("m")
    board.record("m", ok=True)            # probe verdict: healthy
    assert board.allow("m") and board.allow("m")  # closed: all flow

    # failure verdict re-opens and re-arms the cooldown
    board.record("m", ok=False)
    assert not board.allow("m")           # 11.0 is the new open stamp
    clock[0] = 22.0
    assert board.allow("m")

    # an INCONCLUSIVE probe (bad user inputs) frees the slot for the
    # next probe instead of wedging the breaker half-open forever
    assert not board.allow("m")
    board.record_inconclusive("m")
    assert board.allow("m")
    assert not board.allow("m")


def test_burst_level_failure_counts_once_toward_breaker():
    """One incident on an N-job coalesced burst (e.g. a deadline expiry
    during a cold compile) is ONE consecutive failure, not N — it must
    not single-handedly quarantine the model."""

    async def scenario():
        executor = ChaoticExecutor(hang_s=30.0)
        registry = ModelRegistry(catalog=[], allow_random=True)
        worker = _worker(chaos_settings(job_deadline_s=0.05),
                         executor, registry=registry)
        jobs = [_cjob(f"bt{i}", chaos=["hang"], model="one/model")
                for i in range(3)]  # breaker threshold is 2
        results = await worker._execute_burst(jobs, StubSlot())
        assert [r["pipeline_config"]["error_kind"] for r in results] == \
            ["timeout"] * 3
        assert not registry.is_quarantined("one/model")
        breakers = worker.health()["breakers"]
        assert breakers["one/model"]["consecutive_failures"] == 1

    asyncio.run(scenario())


def test_model_unavailable_redispatchable_but_still_breaker_fodder():
    """ISSUE 6 satellite (resolves the PR-2 taxonomy tension): a
    node-local model-unavailable uploads WITHOUT the fatal flag and with
    ``error_kind=model_unavailable`` — the hive may redispatch it — yet
    it still counts toward the model's circuit breaker, so K misses in a
    row quarantine the checkpoint locally exactly as before."""
    from chiaswarm_tpu.node.executor import synchronous_do_work
    from chiaswarm_tpu.node.resilience import BREAKER_KINDS, REDISPATCH_KINDS

    assert "model_unavailable" in BREAKER_KINDS
    assert "model_unavailable" in REDISPATCH_KINDS
    assert "quarantined" in REDISPATCH_KINDS

    # the REAL executor path: a registry without the model raises the
    # load ValueError; the envelope must be non-fatal + redispatchable
    registry = ModelRegistry(catalog=[], allow_random=False)
    result = synchronous_do_work(
        _cjob("mu-1", model="not/served"), StubSlot(), registry)
    config = result["pipeline_config"]
    assert config["error_kind"] == "model_unavailable"
    assert "fatal_error" not in result  # the hive may redispatch

    async def breaker_still_quarantines():
        executor = ChaoticExecutor()
        reg = ModelRegistry(catalog=[], allow_random=True)
        worker = _worker(chaos_settings(), executor, registry=reg)
        bad = "missing/checkpoint"

        async def refuse(job, slot, registry):
            return {
                "id": job.get("id"),
                "artifacts": {},
                "pipeline_config": {
                    "error": "model is not available on this node",
                    "error_kind": "model_unavailable"},
            }

        executor.do_work = refuse  # threshold is 2
        for i in range(2):
            [envelope] = await worker._execute_burst(
                [_cjob(f"mu{i}", model=bad)], StubSlot())
            assert classify_result(envelope) == "model_unavailable"
        assert reg.is_quarantined(bad)
        assert worker.health()["breakers"][bad]["state"] == "open"
        # and the refusal envelope of the OPEN breaker is itself
        # redispatchable (kind "quarantined", non-fatal)
        [refused] = await worker._execute_burst(
            [_cjob("mu2", model=bad)], StubSlot())
        assert refused["pipeline_config"]["error_kind"] == "quarantined"
        assert "fatal_error" not in refused

    asyncio.run(breaker_still_quarantines())


def test_breaker_ignores_user_input_errors():
    """K bad *requests* in a row must not quarantine a healthy model."""

    async def scenario():
        executor = ChaoticExecutor()
        registry = ModelRegistry(catalog=[], allow_random=True)
        worker = _worker(chaos_settings(), executor, registry=registry)
        model = "healthy/model"
        for i in range(4):  # threshold is 2; fatal kinds never count
            await worker._execute_burst(
                [_cjob(f"u{i}", chaos=["fatal"], model=model)], StubSlot())
        assert not registry.is_quarantined(model)
        assert worker.health()["breakers"] == {}

    asyncio.run(scenario())


def test_crashed_burst_reports_an_envelope_per_job():
    """A crash escaping the executor (reference behavior: job silently
    eaten, hive times out) must yield one explicit error envelope per
    burst member through the normal result path."""

    async def scenario():
        executor = ChaoticExecutor()
        slot = StubSlot(depth=1, data_width=4)
        worker = _worker(chaos_settings(), executor, slots=[slot])
        jobs = [_cjob(f"x{i}", chaos=["crash"], model="tiny")
                for i in range(3)]
        for job in jobs:
            worker.work_queue.put_nowait(job)
        task = asyncio.create_task(worker._slot_worker(slot))
        await asyncio.wait_for(worker.work_queue.join(), timeout=10)
        task.cancel()
        await asyncio.gather(task, return_exceptions=True)
        envelopes = []
        while not worker.result_queue.empty():
            envelopes.append(worker.result_queue.get_nowait())
        got = sorted(e["id"] for e in envelopes)
        assert got == ["x0", "x1", "x2"]
        for envelope in envelopes:
            assert envelope["pipeline_config"]["error_kind"] == "error"
            assert "chaos: executor crash" in \
                envelope["pipeline_config"]["error"]

    asyncio.run(scenario())


# ---------------------------------------------------------------------------
# graceful shutdown + durability (satellites)
# ---------------------------------------------------------------------------


def test_shutdown_drains_inflight_burst_and_uploads_result():
    """Stop while a job is mid-execution: the burst completes and its
    result uploads BEFORE run() returns — chip time already spent is
    never discarded by shutdown."""

    async def scenario():
        hive = ChaoticHive()
        uri = await hive.start()
        executor = ChaoticExecutor(slow_s=0.4)
        hive.submit(_cjob("c-slow", chaos=["slow"]))
        worker = Worker(settings=chaos_settings(uri, job_deadline_s=10.0),
                        pool=[StubSlot()],
                        registry=ModelRegistry(catalog=[],
                                               allow_random=True),
                        executor=executor)
        task = asyncio.create_task(worker.run())
        try:
            await asyncio.wait_for(executor.started.wait(), timeout=30)
            worker.request_stop()  # job is in flight RIGHT NOW
            await asyncio.wait_for(task, timeout=20)
        finally:
            await hive.stop()
        assert hive.uploaded_ids() == ["c-slow"]  # uploaded before exit
        assert worker.dead_letters.depth() == 0

    asyncio.run(scenario())


def test_forced_cancel_requeues_held_job():
    """A job claimed by the burst drain but never dispatched (the held
    mismatch) must return to the queue on forced cancellation — never be
    dropped."""

    async def scenario():
        executor = ChaoticExecutor(hang_s=30.0)
        worker = _worker(chaos_settings(job_deadline_s=100.0), executor,
                         slots=[StubSlot(depth=1, data_width=4)])
        job_a = _cjob("A", chaos=["hang"], model="tiny")
        # key mismatch -> held: size splits the burst key even with lanes
        # on (steps/guidance/strength relax when the stepper rides them
        # per row, ISSUE 7 — a size mismatch never relaxes)
        job_b = _cjob("B", chaos=["ok"], model="tiny", height=128)
        worker.work_queue.put_nowait(job_a)
        worker.work_queue.put_nowait(job_b)
        task = asyncio.create_task(worker._slot_worker(worker.pool[0]))
        await asyncio.wait_for(executor.started.wait(), timeout=10)
        await asyncio.sleep(0.05)  # A hangs in flight; B is held
        task.cancel()
        await asyncio.gather(task, return_exceptions=True)
        assert worker.work_queue.qsize() == 1
        assert worker.work_queue.get_nowait()["id"] == "B"

    asyncio.run(scenario())


def test_unsent_results_spool_and_replay_on_next_start(tmp_path):
    """Durability across restarts: an envelope that exhausted its upload
    retries lands in the dead-letter directory; the NEXT worker startup
    replays and uploads it, then removes the file."""

    async def scenario():
        from chiaswarm_tpu.node.executor import error_result

        # the default spool is namespaced by worker name so one worker
        # can never replay-and-delete another's results
        spool = DeadLetterSpool(tmp_path / "dead_letter" / "chaos-worker")
        envelope = error_result({"id": "dl-1",
                                 "content_type": "application/json"},
                                "spooled by a previous run", kind="error")
        spool.spool(envelope)
        assert spool.depth() == 1

        hive = ChaoticHive()
        uri = await hive.start()
        worker = Worker(settings=chaos_settings(uri), pool=[StubSlot()],
                        registry=ModelRegistry(catalog=[],
                                               allow_random=True),
                        executor=ChaoticExecutor())
        task = asyncio.create_task(worker.run())
        try:
            await hive.wait_for_results(1, timeout=30)
        finally:
            worker.request_stop()
            await asyncio.wait_for(task, timeout=20)
            await hive.stop()
        assert hive.uploaded_ids() == ["dl-1"]
        assert worker.stats.results_replayed == 1
        assert spool.depth() == 0  # discarded after the upload succeeded

    asyncio.run(scenario())


def test_drain_with_fewer_jobs_than_slots_exits_promptly():
    """Two slots racing for the last queued job during drain: the loser
    must notice the queue went dry and exit instead of blocking the
    whole shutdown until the drain timeout force-cancels it."""

    async def scenario():
        executor = ChaoticExecutor()
        slots = [StubSlot(name="s0"), StubSlot(name="s1")]
        worker = _worker(chaos_settings(), executor, slots=slots)
        tasks = [asyncio.create_task(worker._slot_worker(s))
                 for s in slots]
        for _ in range(5):  # both slots parked on the queue
            await asyncio.sleep(0)
        worker.work_queue.put_nowait(_cjob("last-one"))
        worker._draining.set()
        # well under drain_timeout_s (5s): the losing slot must not hang
        await asyncio.wait_for(asyncio.gather(*tasks), timeout=3.0)
        assert worker.result_queue.qsize() == 1
        assert worker.result_queue.get_nowait()["id"] == "last-one"

    asyncio.run(scenario())


def test_poll_loop_full_queue_respects_stop():
    """Satellite: the poll loop's backpressure wait must observe _stop —
    a full work queue can no longer delay shutdown indefinitely."""

    async def scenario():
        worker = _worker(chaos_settings(), ChaoticExecutor(),
                         slots=[StubSlot(depth=1, data_width=1)])
        worker.work_queue.put_nowait(_cjob("fill"))  # maxsize 1 -> full
        assert worker.work_queue.full()
        task = asyncio.create_task(worker._poll_loop())
        await asyncio.sleep(0.1)  # parked in the backpressure wait
        worker.request_stop()
        await asyncio.wait_for(task, timeout=2.0)  # returns, not cancelled

    asyncio.run(scenario())


# ---------------------------------------------------------------------------
# hive client + resilience primitives (satellites)
# ---------------------------------------------------------------------------


def test_get_work_nonjson_400_still_raises_bad_worker():
    """Satellite: a misbehaving-worker signal with a non-JSON body must
    stay a BadWorkerError, not demote to a generic poll failure."""

    async def scenario():
        import aiohttp

        hive = ChaoticHive(poll_faults=["bad_worker"])
        uri = await hive.start()
        try:
            client = HiveClient(uri, "t", "w")
            async with aiohttp.ClientSession() as session:
                with pytest.raises(BadWorkerError, match="bad worker"):
                    await client.get_work(session)
        finally:
            await hive.stop()

    asyncio.run(scenario())


def test_poll_backoff_grows_caps_and_resets():
    """Satellite: capped exponential backoff + jitter replaces the flat
    121 s error delay; the schedule resets on the first success."""
    backoff = Backoff(base=2.0, cap=121.0, seed="poll:test")
    delays = [backoff.next() for _ in range(10)]
    assert 1.0 <= delays[0] <= 2.0  # equal jitter around the base
    assert all(d <= 121.0 for d in delays)
    assert max(delays[6:]) > 30.0   # actually grew toward the cap
    backoff.reset()
    assert 1.0 <= backoff.next() <= 2.0
    # determinism: same seed -> same schedule (chaos reproducibility)
    again = Backoff(base=2.0, cap=121.0, seed="poll:test")
    assert [again.next() for _ in range(10)] == delays


def test_classify_exception_taxonomy():
    import requests

    assert classify_exception(ValueError("max image size")) == "fatal"
    assert classify_exception(
        RuntimeError("RESOURCE_EXHAUSTED: out of memory")) == "oom"
    # ISSUE 6: node-local model-unavailable is a redispatch signal, not
    # a fatal user-input error (the hive routes it to another worker)
    assert classify_exception(
        ValueError("model 'x' is not available on this node")) == \
        "model_unavailable"
    assert classify_exception(ConnectionResetError("peer")) == "transient"
    assert classify_exception(
        requests.exceptions.ConnectTimeout("slow cdn")) == "transient"
    assert classify_exception(requests.exceptions.HTTPError(
        "503 Server Error: upstream")) == "transient"
    assert classify_exception(requests.exceptions.HTTPError(
        "404 Client Error: gone")) == "fatal"
    # 5xx-looking digits in the URL must not fool the classifier
    assert classify_exception(requests.exceptions.HTTPError(
        "404 Client Error: Not Found for url: "
        "https://cdn/500x500/a.png")) == "fatal"
    assert classify_exception(KeyError("wat")) == "error"
    # deterministic jitter helper stays within the envelope
    import random as _random
    rng = _random.Random(7)
    for attempt in range(1, 12):
        delay = backoff_delay(attempt, 0.5, 30.0, rng)
        assert 0.0 < delay <= 30.0


def test_malformed_job_through_real_executor_is_fatal_envelope():
    """The real formatting path contains garbage jobs as fatal envelopes
    (the chaos hive's 'malformed' mode rides the same shape)."""
    from chiaswarm_tpu.node.chaos import _malformed_job
    from chiaswarm_tpu.node.executor import synchronous_do_work

    registry = ModelRegistry(catalog=[], allow_random=True)
    result = synchronous_do_work(_malformed_job(1), StubSlot(), registry)
    assert result["id"] == "malformed-1"
    assert result["fatal_error"] is True
    assert result["pipeline_config"]["error_kind"] == "fatal"


def test_transient_format_failure_is_not_fatal():
    """An input-image fetch blip during formatting uploads WITHOUT the
    fatal flag (and tagged transient) so the ladder/hive may retry it —
    only genuinely bad inputs are fatal."""
    from chiaswarm_tpu.node.executor import synchronous_do_work

    registry = ModelRegistry(catalog=[], allow_random=True)
    job = _cjob("fetch-blip", model="tiny",
                start_image_uri="http://127.0.0.1:9/never-listens.png")
    result = synchronous_do_work(job, StubSlot(), registry)
    config = result["pipeline_config"]
    assert "error" in config
    assert config["error_kind"] == "transient"
    assert "fatal_error" not in result

    async def retries_then_succeeds():
        # the worker-side ladder picks the transient envelope up and
        # re-runs; here the re-run is scripted to succeed
        executor = ChaoticExecutor()
        worker = _worker(chaos_settings(), executor)
        [final] = await worker._execute_burst(
            [_cjob("fb2", chaos=["fetch", "ok"])], StubSlot())
        assert classify_result(final) == "ok"

    asyncio.run(retries_then_succeeds())


def test_breaker_state_persists_across_restarts():
    """ISSUE 4 satellite (ROADMAP PR-2 candidate): a model quarantined
    before a restart is still quarantined after it — the breaker board
    serializes open breakers next to the dead-letter spool and a fresh
    worker on the same settings root reloads them (and re-mirrors the
    registry quarantine) without a single new failure."""

    async def scenario():
        executor = ChaoticExecutor()
        registry = ModelRegistry(catalog=[], allow_random=True)
        settings = chaos_settings()  # threshold 2, cooldown 3600
        worker1 = _worker(settings, executor, registry=registry)
        bad = "bad/checkpoint"
        for i in range(2):
            await worker1._execute_burst(
                [_cjob(f"bp{i}", chaos=["crash"], model=bad)], StubSlot())
        assert registry.is_quarantined(bad)
        assert worker1._breaker_state_path().is_file()

        # "restart": fresh worker AND fresh registry on the same root
        registry2 = ModelRegistry(catalog=[], allow_random=True)
        worker2 = _worker(settings, executor, registry=registry2)
        assert registry2.is_quarantined(bad)  # restored at construction
        assert worker2.health()["breakers"][bad]["state"] == "open"
        [refused] = await worker2._execute_burst(
            [_cjob("bp2", chaos=["ok"], model=bad)], StubSlot())
        assert refused["pipeline_config"]["error_kind"] == "quarantined"
        assert "bp2" not in executor.attempts  # no chip time burned

        # a successful probe after the cooldown clears the state file
        worker2.breakers = BreakerBoard(
            threshold=2, cooldown_s=0.0,
            on_open=registry2.quarantine, on_close=registry2.unquarantine,
            on_probe=registry2.unquarantine,
            persist_path=worker2._breaker_state_path())
        [probe] = await worker2._execute_burst(
            [_cjob("bp3", chaos=["ok"], model=bad)], StubSlot())
        assert classify_result(probe) == "ok"
        assert not worker2._breaker_state_path().is_file()

    asyncio.run(scenario())


def test_breaker_persistence_restores_remaining_cooldown(tmp_path):
    """The monotonic clock dies with the process, so the file carries
    the REMAINING cooldown: save() at shutdown refreshes it and the
    restored breaker re-opens for exactly that residue."""
    clock = [100.0]
    path = tmp_path / "breakers.json"
    board = BreakerBoard(threshold=1, cooldown_s=50.0,
                         clock=lambda: clock[0], persist_path=path)
    board.record("m", ok=False)  # opens at t=100; file says remaining 50
    clock[0] = 120.0
    board.save()                 # clean shutdown: remaining 30

    clock2 = [1000.0]            # new process, new monotonic epoch
    board2 = BreakerBoard(threshold=1, cooldown_s=50.0,
                          clock=lambda: clock2[0], persist_path=path)
    assert board2.states()["m"]["state"] == "open"
    assert not board2.allow("m")
    clock2[0] = 1029.0           # 29s later: still inside the residue
    assert not board2.allow("m")
    clock2[0] = 1031.0           # residue elapsed: half-open probe
    assert board2.allow("m")

    # a corrupt state file must not break startup
    path.write_text("{not json", encoding="utf-8")
    board3 = BreakerBoard(threshold=1, cooldown_s=50.0,
                          clock=lambda: clock2[0], persist_path=path)
    assert board3.states() == {}


@pytest.mark.slow
def test_chaos_soak_zero_loss_from_seed():
    """Nightly soak (ISSUE 4 satellite): a LONG randomized fault script
    expanded from a seed (CHIASWARM_SOAK_SEED, defaulting stable for
    local runs; nightly CI passes the run id) drives a real worker
    through poll faults, executor faults, and upload faults at once —
    and the PR-2 invariant must hold at scale: every issued job settles
    as exactly one uploaded envelope or one dead-letter file."""
    import os
    import random

    from chiaswarm_tpu.node.chaos import ChaosSchedule

    seed = os.environ.get("CHIASWARM_SOAK_SEED", "soak-default")
    n_jobs = int(os.environ.get("CHIASWARM_SOAK_JOBS", "60"))
    rng = random.Random(f"chaos-soak:{seed}")

    # every script terminates in a deterministic envelope: ok, a
    # recovered retry, a fatal, a crash envelope, or a deadline timeout
    outcome_scripts = (
        (["ok"], 6),
        (["oom", "ok"], 2),
        (["fetch", "ok"], 2),
        (["fetch", "fetch", "ok"], 1),
        (["crash"], 1),
        (["fatal"], 1),
        (["hang"], 1),
        (["slow"], 1),
    )
    weighted = [script for script, w in outcome_scripts for _ in range(w)]
    jobs = [_cjob(f"soak-{i}", chaos=list(rng.choice(weighted)))
            for i in range(n_jobs)]

    # upload-side faults for a seeded subset; a couple exhaust every
    # retry and MUST land in the dead-letter spool
    result_faults: dict[str, list[str]] = {}
    flaky = rng.sample([j["id"] for j in jobs], k=max(2, n_jobs // 6))
    dead_ids = set(flaky[:2])
    for job_id in flaky:
        if job_id in dead_ids:
            result_faults[job_id] = ["http_500"] * 10
        else:
            result_faults[job_id] = [rng.choice(["http_500", "drop"]), "ok"]

    poll_faults = ChaosSchedule.from_seed(
        f"poll:{seed}",
        ("ok", "ok", "ok", "drop", "delay", "http_500", "malformed"),
        length=n_jobs)

    async def scenario():
        hive = ChaoticHive(poll_faults=poll_faults._script,
                           result_faults=result_faults, delay_s=0.01)
        uri = await hive.start()
        for job in jobs:
            hive.submit(job)
        executor = ChaoticExecutor(hang_s=1.0, slow_s=0.05)
        worker = Worker(settings=chaos_settings(uri), pool=[StubSlot()],
                        registry=ModelRegistry(catalog=[],
                                               allow_random=True),
                        executor=executor)
        task = asyncio.create_task(worker.run())
        try:
            deadline = asyncio.get_running_loop().time() + 300
            while asyncio.get_running_loop().time() < deadline:
                settled = len(hive.results) + worker.dead_letters.depth()
                if settled >= len(hive.issued_ids) and \
                        len(hive.results) >= len(hive.issued_ids) - \
                        len(dead_ids):
                    break
                await asyncio.sleep(0.1)
        finally:
            worker.request_stop()
            await asyncio.wait_for(task, timeout=30)
            await hive.stop()

        uploaded = hive.uploaded_ids()
        dead = {json.loads(p.read_text())["id"]
                for p in worker.dead_letters.directory.glob("*.json")}
        issued = set(hive.issued_ids)
        # the zero-loss invariant, at soak scale: exactly-once settling
        assert len(uploaded) == len(set(uploaded)), "duplicate uploads"
        assert set(uploaded) | dead == issued
        assert set(uploaded) & dead == set()
        assert dead == dead_ids

    asyncio.run(scenario())


def test_mid_lane_fault_keeps_zero_loss(monkeypatch):
    """ISSUE 3: a crash/OOM injected into a RUNNING step-scheduler lane
    (serving/stepper.py) with spliced rows resident must not lose a job:
    every row's future fails, the executor bounces each job to the
    per-job path, and every id uploads exactly one envelope through a
    real Worker loop."""
    import sys

    sys.path.insert(0, "tests")
    from fake_hive import FakeHive

    import jax

    from chiaswarm_tpu.core.chip_pool import ChipPool
    from chiaswarm_tpu.core.mesh import MeshSpec
    from chiaswarm_tpu.node.worker import Worker
    from chiaswarm_tpu.serving.stepper import get_stepper

    monkeypatch.setenv("CHIASWARM_STEPPER", "1")
    registry = ModelRegistry(
        catalog=[{"name": "tiny", "family": "tiny", "parameters": {}}],
        allow_random=True)
    pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 1}),
                    devices=jax.devices()[:1])
    slot = pool.slots[0]
    stepper = get_stepper(slot)
    # the fault fires DURING the lane's denoise loop, after the rows of
    # this burst have been admitted (mid-flight, not at submit time)
    stepper.inject_fault(
        after_steps=stepper.stats().get("steps_executed", 0) + 1,
        exc=RuntimeError("RESOURCE_EXHAUSTED: chaos mid-lane"))

    async def scenario():
        hive = FakeHive()
        await hive.start()
        for i in range(3):
            hive.jobs.append({
                "id": f"lane-{i}", "model_name": "tiny",
                "prompt": f"p{i}", "seed": 500 + i,
                # mixed steps: only a lane (relaxed key) can merge these
                "num_inference_steps": 2 + i,
                "height": 64, "width": 64, "content_type": "image/png"})
        worker = Worker(
            settings=chaos_settings(hive.uri, job_deadline_s=600.0,
                                    workflow_deadline_s={}),
            registry=registry, pool=pool)
        task = asyncio.create_task(worker.run())
        try:
            await hive.wait_for_results(3, timeout=300)
        finally:
            worker.request_stop()
            await asyncio.wait_for(task, timeout=30)
            await hive.stop()
        return hive.results

    results = asyncio.run(scenario())
    by_id = {r["id"]: r for r in results}
    # exactly-once: all three ids, no duplicates, no silent drops
    assert sorted(by_id) == ["lane-0", "lane-1", "lane-2"]
    assert len(results) == 3
    for r in results:
        # the fallback path served every bounced row successfully
        assert r["pipeline_config"].get("error") is None, r
        assert "fatal_error" not in r
    assert stepper.stats().get("lanes_failed", 0) >= 1


# ---------------------------------------------------------------------------
# ISSUE 8: the budget-squeeze fault — residency churn under the chaos
# harness (evict -> reload -> degraded load-per-job -> bounce/redispatch)
# ---------------------------------------------------------------------------


def _residency_worker_parts(budget_bytes, hard_bytes, models,
                            monkeypatch):
    """Real tiny pipelines + a private residency ledger + a single-chip
    pool — the substrate both squeeze tests share. Lanes are opted out:
    a lane holds its pipe between jobs, which would blur the ledger
    accounting these tests assert exactly."""
    import jax

    from chiaswarm_tpu.core.chip_pool import ChipPool
    from chiaswarm_tpu.core.mesh import MeshSpec
    from chiaswarm_tpu.obs.metrics import Registry as ObsRegistry
    from chiaswarm_tpu.serving.residency import ResidencyManager

    monkeypatch.setenv("CHIASWARM_STEPPER", "0")
    manager = ResidencyManager(budget_bytes=budget_bytes,
                               hard_limit_bytes=hard_bytes,
                               metrics_registry=ObsRegistry(),
                               persist_path=None, reserve_wait_s=0.2)
    registry = ModelRegistry(
        catalog=[{"name": name, "family": "tiny"} for name in models],
        allow_random=True, residency=manager)
    pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 1}),
                    devices=jax.devices()[:1])
    return manager, registry, pool


def test_budget_squeeze_churn_zero_loss(monkeypatch):
    """ISSUE 8 satellite: a scripted budget squeeze while a mixed-model
    stream flows — models churn through every rung (resident -> evicted
    -> reloaded -> degraded load-per-job -> model_unavailable bounce)
    and NO job is lost: every id settles as exactly one envelope, the
    bounce uploads non-fatal model_unavailable (redispatchable, PR 6),
    and peak ledger bytes never exceed budget + one model."""
    import sys

    sys.path.insert(0, "tests")
    from fake_hive import FakeHive

    models = ["tiny/a", "tiny/b"]
    # probe one load to denominate the budget in measured bytes
    probe_mgr, probe_reg, _ = _residency_worker_parts(
        1 << 30, 2 << 30, ["tiny/probe"], monkeypatch)
    probe_reg.pipeline("tiny/probe")
    footprint = probe_mgr.measured_footprints()["tiny/probe"]

    budget = int(footprint * 1.5)
    manager, registry, pool = _residency_worker_parts(
        budget, footprint * 4, models, monkeypatch)
    manager.reset_peak()

    async def scenario():
        hive = FakeHive()
        await hive.start()
        worker = Worker(
            settings=chaos_settings(hive.uri, job_deadline_s=600.0,
                                    workflow_deadline_s={}),
            registry=registry, pool=pool)
        task = asyncio.create_task(worker.run())
        try:
            # phase 1: alternate models under the tight budget — churn.
            # One job at a time: a depth-2 slot would otherwise load
            # both models concurrently and make the eviction count
            # depend on admit order.
            for i in range(3):
                hive.jobs.append(
                    {"id": f"sq-{i}", "model_name": models[i % 2],
                     "prompt": f"p{i}", "seed": 40 + i,
                     "num_inference_steps": 2, "height": 64, "width": 64,
                     "content_type": "image/png"})
                await hive.wait_for_results(i + 1, timeout=600)
            # phase 2: SQUEEZE below one model — the next job must
            # degrade to load-per-job, not fail
            manager.set_budget(int(footprint * 0.5))
            hive.jobs.append(
                {"id": "sq-degraded", "model_name": models[0],
                 "prompt": "pd", "seed": 50, "num_inference_steps": 2,
                 "height": 64, "width": 64,
                 "content_type": "image/png"})
            await hive.wait_for_results(4, timeout=600)
            # phase 3: squeeze the HARD limit below one model — the job
            # bounces model_unavailable for the hive to redispatch
            manager.set_budget(int(footprint * 0.5),
                               hard_limit_bytes=int(footprint * 0.6))
            hive.jobs.append(
                {"id": "sq-bounce", "model_name": models[1],
                 "prompt": "pb", "seed": 51, "num_inference_steps": 2,
                 "height": 64, "width": 64,
                 "content_type": "application/json"})
            await hive.wait_for_results(5, timeout=600)
        finally:
            worker.request_stop()
            await asyncio.wait_for(task, timeout=60)
            await hive.stop()
        return hive.results

    results = asyncio.run(scenario())
    by_id = {r["id"]: r for r in results}
    # zero loss: every id exactly once
    assert sorted(by_id) == ["sq-0", "sq-1", "sq-2", "sq-bounce",
                             "sq-degraded"]
    assert len(results) == 5
    for i in range(3):
        assert by_id[f"sq-{i}"]["pipeline_config"].get("error") is None
    degraded = by_id["sq-degraded"]["pipeline_config"]
    assert degraded.get("error") is None
    assert degraded.get("residency") == "per_job"
    bounce = by_id["sq-bounce"]
    assert bounce["pipeline_config"]["error_kind"] == "model_unavailable"
    assert "fatal_error" not in bounce  # a lease-aware hive redispatches
    from chiaswarm_tpu.node.resilience import REDISPATCH_KINDS

    assert bounce["pipeline_config"]["error_kind"] in REDISPATCH_KINDS
    # the ledger churned within its invariant
    snap = manager.snapshot()
    assert snap["evictions"] >= 2
    assert snap["degraded_loads"] >= 1
    assert snap["bounces"] >= 1
    largest = max(manager.measured_footprints().values())
    assert manager.peak_bytes <= budget + largest


@pytest.mark.slow
def test_residency_squeeze_soak_zero_loss(monkeypatch):
    """Nightly residency soak (ISSUE 8 satellite, runs in the chaos-soak
    workflow's ``-k soak`` selection): a seeded mixed-model stream with
    randomized mid-run budget squeezes/restores. The gate is the
    zero-loss invariant plus the no-double-buffer peak bound, at soak
    scale."""
    import os
    import random
    import sys

    sys.path.insert(0, "tests")
    from fake_hive import FakeHive

    seed = os.environ.get("CHIASWARM_SOAK_SEED", "residency-default")
    # divided down from the chaos-soak job knob: unlike the stub-executor
    # soaks, every one of these jobs runs a REAL tiny pipeline, and every
    # swap recompiles — ~10x the per-job cost
    n_jobs = max(8, int(os.environ.get("CHIASWARM_SOAK_JOBS", "120")) // 10)
    rng = random.Random(f"residency-soak:{seed}")

    models = ["tiny/a", "tiny/b", "tiny/c"]
    probe_mgr, probe_reg, _ = _residency_worker_parts(
        1 << 30, 2 << 30, ["tiny/probe"], monkeypatch)
    probe_reg.pipeline("tiny/probe")
    footprint = probe_mgr.measured_footprints()["tiny/probe"]
    budget = int(footprint * 1.7)
    manager, registry, pool = _residency_worker_parts(
        budget, footprint * 4, models, monkeypatch)
    manager.reset_peak()

    async def scenario():
        hive = FakeHive()
        await hive.start()
        worker = Worker(
            settings=chaos_settings(hive.uri, job_deadline_s=600.0,
                                    workflow_deadline_s={}),
            registry=registry, pool=pool)
        task = asyncio.create_task(worker.run())
        try:
            done = 0
            for i in range(n_jobs):
                hive.jobs.append(
                    {"id": f"rsoak-{i}",
                     "model_name": rng.choice(models),
                     "prompt": f"p{i}", "seed": 7000 + i,
                     "num_inference_steps": 2, "height": 64,
                     "width": 64, "content_type": "image/png"})
                done += 1
                await hive.wait_for_results(done, timeout=600)
                # seeded squeezes: shrink below one model (degrade) or
                # restore; the stream must keep settling either way
                roll = rng.random()
                if roll < 0.25:
                    manager.set_budget(int(footprint * 0.5))
                elif roll < 0.5:
                    manager.set_budget(budget)
        finally:
            worker.request_stop()
            await asyncio.wait_for(task, timeout=60)
            await hive.stop()
        return hive.results

    results = asyncio.run(scenario())
    ids = [r["id"] for r in results]
    assert len(ids) == len(set(ids)) == n_jobs  # exactly once, no loss
    for r in results:
        assert r["pipeline_config"].get("error") is None, r
    largest = max(manager.measured_footprints().values())
    assert manager.peak_bytes <= budget + largest
