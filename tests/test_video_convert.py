"""Video-UNet checkpoint fidelity (VERDICT r4 #1).

The reference serves REAL ModelScope snapshots (swarm/video/tx2vid.py:
24-27); BASELINE config #5 names the SVD class. These tests prove, without
weights or diffusers:

- forward parity: a torch model in the EXACT published layout/state-dict
  naming (tests/torch_video_ref.py), randomized, converted, must
  reproduce the torch forward number-for-number through the Flax modules;
- conversion completeness at the FULL published configs: every leaf of
  the 1.4B-param layouts converts — nothing is synthesized (the silent
  motion-loss failure VERDICT r4 flagged);
- the end-to-end load path: a full-layout snapshot on disk -> strict
  from_checkpoint -> clip, for both families; a 2D snapshot into an
  SVD-class family must raise, not silently inflate.
"""

from __future__ import annotations

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from chiaswarm_tpu.convert.torch_to_flax import (  # noqa: E402
    convert_unet3d,
    convert_unet_spatio_temporal,
)
from chiaswarm_tpu.pipelines.video import (  # noqa: E402
    MODELSCOPE,
    SVD,
    VIDEO_FAMILIES,
    _strict_match,
    _unet_init_args,
    make_video_unet,
)

from tests.torch_video_ref import (  # noqa: E402
    UNet3DRef,
    UNetSpatioTemporalRef,
    randomize_,
)


def _np_state(model) -> dict[str, np.ndarray]:
    return {k: v.detach().numpy() for k, v in model.state_dict().items()}


def test_unet3d_forward_parity():
    """ModelScope layout at the tiny config: converted weights reproduce
    the torch forward — covers the temporal conv stack, the double-self
    temporal attention, transformer_in, and the interleaving order."""
    fam = VIDEO_FAMILIES["tiny_vid"]
    tm = UNet3DRef(fam.unet).eval()
    randomize_(tm, seed=0)
    params = convert_unet3d(_np_state(tm), fam.unet)

    rng = np.random.default_rng(1)
    b, f, s = 2, 3, 7
    sample = rng.normal(size=(b, f, 16, 16, 4)).astype(np.float32)
    t = np.asarray([3.0, 250.0], np.float32)
    ctx = rng.normal(size=(b, s, fam.unet.cross_attention_dim)
                     ).astype(np.float32)

    with torch.no_grad():
        want = tm(torch.from_numpy(sample.transpose(0, 4, 1, 2, 3)),
                  torch.from_numpy(t), torch.from_numpy(ctx)).numpy()
    unet = make_video_unet(fam)
    got = jax.jit(unet.apply)(params, jnp.asarray(sample), jnp.asarray(t),
                              jnp.asarray(ctx))
    np.testing.assert_allclose(np.asarray(got),
                               want.transpose(0, 2, 3, 4, 1),
                               atol=3e-4, rtol=3e-4)


def test_unet_spatio_temporal_forward_parity():
    """SVD layout at the tiny config: spatio-temporal res blocks (learned
    blends), temporal transformer blocks (ff_in residual, first-frame
    cross-attention), frame-position embedding, micro-conditioning."""
    fam = VIDEO_FAMILIES["tiny_svd"]
    tm = UNetSpatioTemporalRef(fam.unet).eval()
    randomize_(tm, seed=2)
    params = convert_unet_spatio_temporal(_np_state(tm), fam.unet)

    rng = np.random.default_rng(3)
    b, f = 2, 3
    sample = rng.normal(size=(b, f, 16, 16, fam.unet.sample_channels)
                        ).astype(np.float32)
    t = np.asarray([0.7, 1.4], np.float32)
    ctx = rng.normal(size=(b, 1, fam.unet.cross_attention_dim)
                     ).astype(np.float32)
    ids = np.asarray([[6.0, 127.0, 0.02], [7.0, 60.0, 0.1]], np.float32)

    with torch.no_grad():
        want = tm(torch.from_numpy(sample.transpose(0, 1, 4, 2, 3)),
                  torch.from_numpy(t), torch.from_numpy(ctx),
                  torch.from_numpy(ids)).numpy()
    unet = make_video_unet(fam)
    got = jax.jit(unet.apply)(params, jnp.asarray(sample), jnp.asarray(t),
                              jnp.asarray(ctx),
                              {"time_ids": jnp.asarray(ids)})
    np.testing.assert_allclose(np.asarray(got),
                               want.transpose(0, 1, 3, 4, 2),
                               atol=3e-4, rtol=3e-4)


@pytest.mark.slow
@pytest.mark.parametrize("family,ref_cls,converter", [
    (MODELSCOPE, UNet3DRef, convert_unet3d),
    (SVD, UNetSpatioTemporalRef, convert_unet_spatio_temporal),
], ids=["modelscope", "svd"])
def test_full_published_config_conversion_complete(family, ref_cls,
                                                   converter):
    """At the FULL published configs (4 levels, 2 layers/block, head-dim
    64, ~1.4B params) every checkpoint key must land on exactly one module
    leaf with the right shape — the completeness guarantee
    from_checkpoint's strict mode enforces for real snapshots."""
    tm = ref_cls(family.unet)
    converted = converter(_np_state(tm), family.unet)
    del tm
    unet = make_video_unet(family)
    shapes = jax.eval_shape(unet.init, jax.random.PRNGKey(0),
                            *_unet_init_args(family))
    _strict_match(shapes, converted, family.name)  # raises on any gap


def test_temporal_vae_decoder_forward_parity():
    """The SVD VAE's TemporalDecoder at a tiny config: converted weights
    reproduce the torch forward — covers the switched learned blends,
    the temb-free temporal resnets, the mid attention and time_conv_out."""
    from chiaswarm_tpu.convert.torch_to_flax import convert_temporal_vae
    from chiaswarm_tpu.models.vae import TemporalVaeDecoder

    from tests.torch_video_ref import TemporalDecoderRef

    fam = VIDEO_FAMILIES["tiny_svd"]
    tm = TemporalDecoderRef(fam.vae).eval()
    randomize_(tm, seed=6)
    state = {f"decoder.{k}": v for k, v in _np_state(tm).items()}
    tree = convert_temporal_vae(state, fam.vae)
    params = {"params": tree["params"]["decoder"]}

    rng = np.random.default_rng(7)
    z = rng.normal(size=(2, 3, 4, 4, fam.vae.latent_channels)
                   ).astype(np.float32)
    with torch.no_grad():
        want = tm(torch.from_numpy(z.transpose(0, 1, 4, 2, 3)), 3).numpy()
    got = jax.jit(TemporalVaeDecoder(fam.vae).apply)(params,
                                                      jnp.asarray(z))
    np.testing.assert_allclose(np.asarray(got),
                               want.transpose(0, 1, 3, 4, 2),
                               atol=3e-4, rtol=3e-4)


@pytest.mark.slow
def test_full_published_svd_vae_conversion_complete():
    """The published SVD VAE (AutoencoderKLTemporalDecoder at the
    (128,256,512,512)x2 layout): every key converts, nothing synthesized
    — including the temporal decoder and the absence of
    post_quant_conv."""
    from chiaswarm_tpu.convert.torch_to_flax import convert_temporal_vae
    from chiaswarm_tpu.models.configs import VAEConfig
    from chiaswarm_tpu.models.vae import (
        AutoencoderKL,
        AutoencoderKLTemporalDecoder,
    )
    from chiaswarm_tpu.pipelines.components import materialize_host

    from tests.torch_export import export_vae
    from tests.torch_video_ref import TemporalDecoderRef

    cfg = VAEConfig()
    # encoder keys via the standard flax export, decoder via the torch ref
    enc = materialize_host(
        jax.eval_shape(AutoencoderKL(cfg).init, jax.random.PRNGKey(0),
                       jnp.zeros((1, 16, 16, cfg.in_channels))),
        np.random.default_rng(9), "bfloat16")
    state = {k: v for k, v in export_vae(enc, 4).items()
             if not k.startswith("decoder.") and not k.startswith("post_quant_conv")}
    state.update({f"decoder.{k}": v
                  for k, v in _np_state(TemporalDecoderRef(cfg)).items()})
    converted = convert_temporal_vae(state, cfg)
    shapes = jax.eval_shape(
        AutoencoderKLTemporalDecoder(cfg).init, jax.random.PRNGKey(0),
        jnp.zeros((1, 2, 16, 16, cfg.in_channels)))
    _strict_match(shapes, converted, "svd-vae")


def _write_safetensors(dirpath, state: dict[str, np.ndarray]) -> None:
    from pathlib import Path

    from safetensors.numpy import save_file

    d = Path(dirpath)
    d.mkdir(parents=True, exist_ok=True)
    save_file({k: np.ascontiguousarray(v) for k, v in state.items()},
              str(d / "model.safetensors"))


def _write_tiny_vae_and_text(root) -> None:
    from chiaswarm_tpu.pipelines.components import Components
    from tests.torch_export import export_text_encoder, export_vae

    src = Components.random("tiny", seed=7)
    _write_safetensors(root / "vae", export_vae(src.params["vae"], 2))
    _write_safetensors(root / "text_encoder",
                       export_text_encoder(src.params["text_encoder_0"]))


def test_modelscope_snapshot_loads_trained_temporal_weights(tmp_path):
    """A native UNet3DConditionModel snapshot on disk converts completely
    — the trained temporal weights land (spot-checked against the torch
    state), no identity fill — and the pipeline renders from it."""
    from chiaswarm_tpu.pipelines.video import VideoComponents, VideoPipeline

    fam = VIDEO_FAMILIES["tiny_vid"]
    tm = UNet3DRef(fam.unet)
    randomize_(tm, seed=11)
    state = _np_state(tm)
    _write_safetensors(tmp_path / "unet", state)
    _write_tiny_vae_and_text(tmp_path)

    vc = VideoComponents.from_checkpoint(tmp_path, "tiny-ms-native",
                                         "tiny_vid")
    # trained temporal weights, not identity: conv4 of a temp conv equals
    # the checkpoint value (transposed), and is NOT zero
    want = state["down_blocks.0.temp_convs.0.conv4.3.weight"]
    got = np.asarray(
        vc.params["unet"]["params"]["down_0_tconvs_0"]["conv4"]["kernel"])
    np.testing.assert_array_equal(got, want.transpose(2, 3, 4, 1, 0))
    assert np.abs(got).max() > 0

    frames, config = VideoPipeline(vc)("a test", num_frames=4, steps=2,
                                       height=64, width=64, seed=1)
    assert frames.shape == (4, 64, 64, 3)
    assert config["mode"] == "txt2vid"


@pytest.mark.slow
def test_svd_snapshot_end_to_end_load_path(tmp_path):
    """A full spatio-temporal snapshot (unet + image_encoder + vae)
    loads strictly and renders an img2vid clip."""
    transformers = pytest.importorskip("transformers")

    from chiaswarm_tpu.pipelines.video import (
        Img2VidPipeline,
        VideoComponents,
    )
    from tests.torch_export import export_vae

    from chiaswarm_tpu.models.vae import AutoencoderKL

    from tests.torch_video_ref import TemporalDecoderRef

    fam = VIDEO_FAMILIES["tiny_svd"]
    tm = UNetSpatioTemporalRef(fam.unet)
    randomize_(tm, seed=12)
    state = _np_state(tm)
    _write_safetensors(tmp_path / "unet", state)
    # the published temporal-decoder VAE layout: standard encoder keys +
    # "decoder."-prefixed TemporalDecoder keys, no post_quant_conv
    enc = jax.jit(AutoencoderKL(fam.vae).init)(
        jax.random.PRNGKey(8), jnp.zeros((1, 16, 16, 3)))
    vae_state = {k: v for k, v in export_vae(enc, 2).items()
                 if not k.startswith("decoder.") and not k.startswith("post_quant_conv")}
    tdec = TemporalDecoderRef(fam.vae)
    randomize_(tdec, seed=13)
    vae_state.update({f"decoder.{k}": v
                      for k, v in _np_state(tdec).items()})
    _write_safetensors(tmp_path / "vae", vae_state)
    v = fam.vision
    torch.manual_seed(5)
    vision = transformers.CLIPVisionModelWithProjection(
        transformers.CLIPVisionConfig(
            hidden_size=v.hidden_size, intermediate_size=v.intermediate_size,
            num_hidden_layers=v.num_layers, num_attention_heads=v.num_heads,
            image_size=v.image_size, patch_size=v.patch_size,
            projection_dim=v.projection_dim))
    _write_safetensors(tmp_path / "image_encoder", _np_state(vision))

    vc = VideoComponents.from_checkpoint(tmp_path, "tiny-svd-native",
                                         "tiny_svd")
    # the learned blend factors came from the snapshot
    want = state["mid_block.resnets.0.time_mixer.mix_factor"]
    got = np.asarray(
        vc.params["unet"]["params"]["mid_resnets_0"]["mix_factor"])
    np.testing.assert_array_equal(got, want)

    rng = np.random.default_rng(4)
    image = rng.integers(0, 255, (64, 64, 3), dtype=np.uint8)
    frames, config = Img2VidPipeline(vc)(image, num_frames=4, steps=2,
                                         height=64, width=64, seed=3)
    assert frames.shape == (4, 64, 64, 3)
    assert config["mode"] == "img2vid"


def test_svd_family_rejects_2d_snapshot(tmp_path):
    """Feeding a plain SD-style 2D snapshot to an image-conditioned
    family must raise the dedicated error (ADVICE r4 #5) — never
    silently inflate."""
    from chiaswarm_tpu.pipelines.components import Components
    from chiaswarm_tpu.pipelines.video import VideoComponents
    from tests.torch_export import write_checkpoint

    write_checkpoint(tmp_path, Components.random("tiny", seed=3))
    with pytest.raises(ValueError, match="spatio-temporal"):
        VideoComponents.from_checkpoint(tmp_path, "bad-svd", "tiny_svd")


def test_modelscope_strict_mode_rejects_truncated_snapshot(tmp_path):
    """A native snapshot with a temporal key REMOVED must fail loudly —
    the strict matcher guards against partial conversions replacing
    trained weights."""
    from chiaswarm_tpu.pipelines.video import VideoComponents

    fam = VIDEO_FAMILIES["tiny_vid"]
    tm = UNet3DRef(fam.unet)
    state = _np_state(tm)
    state.pop("mid_block.temp_attentions.0.proj_out.weight")
    _write_safetensors(tmp_path / "unet", state)
    _write_tiny_vae_and_text(tmp_path)
    with pytest.raises(ValueError, match="missing"):
        VideoComponents.from_checkpoint(tmp_path, "truncated", "tiny_vid")
