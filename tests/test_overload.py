"""Overload control (ISSUE 9, node/overload.py): the admission
estimator, backpressure, and brownout — units on a fake clock, then the
worker-level shed path against a lease-aware mini-hive.

Three layers:

- **Controller units**: service EWMAs, the shed verdicts (cold never
  sheds; predicted-past-margin and expired-in-queue shed), the
  brownout rung state machine, and the poll-throttle brake.
- **Taxonomy**: ``overloaded`` is a redispatch kind (non-fatal, NOT
  breaker fodder) and the mini-hive requeues it with the shedding
  worker excluded.
- **Worker level** (real Worker + SyntheticExecutor, no pipelines): a
  flooded overload-controlled worker sheds stale jobs as redispatchable
  envelopes counted DISTINCTLY from failures, while a control-off
  worker (reference parity) admits everything.
"""

from __future__ import annotations

import asyncio
import time

import pytest

from chiaswarm_tpu.node.executor import error_result
from chiaswarm_tpu.node.minihive import MiniHive
from chiaswarm_tpu.node.overload import OverloadController
from chiaswarm_tpu.node.resilience import (
    BREAKER_KINDS,
    NONFATAL_KINDS,
    REDISPATCH_KINDS,
    classify_result,
)
from chiaswarm_tpu.obs.metrics import Registry


@pytest.fixture(autouse=True)
def _tmp_root(tmp_path, monkeypatch):
    monkeypatch.setenv("SWARM_TPU_ROOT", str(tmp_path))
    return tmp_path


def controller(clock, **over) -> OverloadController:
    over.setdefault("metrics_registry", Registry())
    return OverloadController(clock=clock, **over)


# ---------------------------------------------------------------------------
# taxonomy
# ---------------------------------------------------------------------------


def test_overloaded_is_redispatchable_and_not_breaker_fodder():
    assert "overloaded" in REDISPATCH_KINDS
    assert "overloaded" in NONFATAL_KINDS
    # shedding says nothing about the model: K sheds in a row must not
    # quarantine a healthy checkpoint
    assert "overloaded" not in BREAKER_KINDS
    envelope = error_result({"id": "j1", "content_type":
                             "application/json"},
                            "shed by overload control", kind="overloaded")
    assert classify_result(envelope) == "overloaded"
    assert not envelope.get("fatal_error")


def test_minihive_redispatches_overloaded_with_shedder_excluded():
    clock = [0.0]
    hive = MiniHive(lease_s=30.0, clock=lambda: clock[0])
    assert hive._take_jobs("wB") == []  # wB is a live alternative
    hive.submit({"id": "j1", "model_name": "m"})
    [handed] = hive._take_jobs("wA")
    assert handed.get("queued_s") == 0.0  # age rides every delivery
    shed = error_result({"id": "j1", "content_type": "application/json"},
                        "shed", kind="overloaded")
    ack = hive._record_result(shed, "wA")
    assert ack == {"status": "requeued", "kind": "overloaded"}
    assert hive.uploaded_ids() == []           # NOT settled
    assert hive._take_jobs("wA") == []         # shedder excluded
    clock[0] = 5.0
    [redelivered] = hive._take_jobs("wB")      # a less-loaded worker
    assert redelivered["attempt"] == 2
    assert redelivered["queued_s"] == 5.0      # age keeps accruing
    assert hive.metrics.get("chiaswarm_hive_jobs_redispatched_total") \
        .value(kind="overloaded") == 1


# ---------------------------------------------------------------------------
# controller units (fake clock)
# ---------------------------------------------------------------------------


def test_cold_estimator_never_sheds_on_predictions():
    ctl = controller(lambda: 0.0)
    # no service evidence: a PREDICTION-based shed is impossible...
    decision = ctl.should_shed(workflow="txt2img", waited_s=0.5,
                               deadline_s=1.0, queued_ahead=50, slots=1)
    assert not decision.shed and decision.reason == "cold"
    # ...but an ALREADY-expired budget needs no evidence: even a
    # just-restarted worker must not burn chip time on a sure miss
    expired = ctl.should_shed(workflow="txt2img", waited_s=100.0,
                              deadline_s=1.0, queued_ahead=0, slots=1)
    assert expired.shed and "expired" in expired.reason


def test_sheds_when_predicted_exceeds_remaining_budget():
    ctl = controller(lambda: 0.0)
    ctl.note_service("txt2img", 2.0)
    # plenty of budget: admit
    ok = ctl.should_shed(workflow="txt2img", waited_s=0.0, deadline_s=30.0,
                         queued_ahead=0, slots=1)
    assert not ok.shed
    # 5 queued x 2 s + own 2 s = 12 s predicted vs 10 s remaining: shed
    shed = ctl.should_shed(workflow="txt2img", waited_s=0.0,
                           deadline_s=10.0, queued_ahead=5, slots=1)
    assert shed.shed and shed.predicted_s == pytest.approx(12.0)
    # the per-workflow EWMA is the estimate (not the overall)
    ctl.note_service("img2img", 0.1)
    assert ctl.service_estimate("img2img") == pytest.approx(0.1)
    assert ctl.service_estimate("txt2img") == pytest.approx(2.0)
    # "" and None normalize to the plain txt2img path
    assert ctl.service_estimate(None) == ctl.service_estimate("txt2img")


def test_expired_in_queue_sheds_even_with_fast_service():
    ctl = controller(lambda: 0.0)
    ctl.note_service("txt2img", 0.01)
    decision = ctl.should_shed(workflow="txt2img", waited_s=5.0,
                               deadline_s=2.0, queued_ahead=0, slots=1)
    assert decision.shed and "expired" in decision.reason


def test_lane_estimate_floors_a_cold_workflow_ewma():
    ctl = controller(lambda: 0.0)
    ctl.note_service("txt2img", 0.05)  # warm overall, cheap workflow
    # 30 steps x 0.2 s/step floors the prediction at 6 s
    decision = ctl.should_shed(workflow="txt2img", waited_s=0.0,
                               deadline_s=3.0, queued_ahead=0, slots=1,
                               lane_estimate_s=6.0)
    assert decision.shed and decision.predicted_s >= 6.0


def test_brownout_trips_on_sustained_sheds_and_cools_down():
    clock = [0.0]
    ctl = controller(lambda: clock[0], brownout_sheds=3, window_s=10.0,
                     cooldown_s=5.0, admission_cap_rows=2)
    ctl.note_service("txt2img", 1.0)
    assert ctl.admission_cap() is None

    def shed_once():
        decision = ctl.should_shed(workflow="txt2img", waited_s=9.0,
                                   deadline_s=1.0, queued_ahead=0,
                                   slots=1)
        assert decision.shed

    shed_once()
    shed_once()
    assert ctl.state == "normal"       # below the rung
    shed_once()
    assert ctl.state == "brownout"     # 3 sheds inside the window
    assert ctl.admission_cap() == 2
    assert ctl.snapshot()["admission_cap"] == 2
    # sheds keep it held; a shed-free cooldown clears it
    clock[0] = 4.0
    shed_once()
    clock[0] = 8.0
    assert ctl.admission_cap() == 2
    clock[0] = 9.5                     # 5.5 s past the last shed
    assert ctl.admission_cap() is None
    assert ctl.state == "normal"
    # ...and STAYS normal: the sheds that tripped the rung drained
    # with the transition, so repeated polls inside the old window
    # must not flap the state (regression: review finding)
    for dt in (0.1, 0.2, 0.3, 2.0):
        clock[0] = 9.5 + dt
        assert ctl.admission_cap() is None
        assert ctl.state == "normal"


def test_brownout_tightens_the_shed_margin():
    clock = [0.0]
    ctl = controller(lambda: clock[0], brownout_sheds=2, window_s=10.0,
                     cooldown_s=60.0, brownout_margin_scale=0.5)
    ctl.note_service("txt2img", 1.0)
    borderline = dict(workflow="txt2img", waited_s=0.0, deadline_s=1.5,
                      queued_ahead=0, slots=1)
    assert not ctl.should_shed(**borderline).shed  # 1.0 < 1.5 admits
    for _ in range(2):                              # trip the rung
        assert ctl.should_shed(workflow="txt2img", waited_s=9.0,
                               deadline_s=1.0, queued_ahead=0,
                               slots=1).shed
    assert ctl.state == "brownout"
    # same job now sheds: 1.0 > 0.5 x 1.5
    assert ctl.should_shed(**borderline).shed


def test_poll_throttle_engages_past_backpressure_budget():
    ctl = controller(lambda: 0.0, backpressure_s=1.0)
    assert ctl.poll_throttle(queue_depth=100, slots=1) == 0.0  # cold
    ctl.note_service("txt2img", 0.5)
    assert ctl.poll_throttle(queue_depth=1, slots=1) == 0.0
    wait = ctl.poll_throttle(queue_depth=10, slots=1)  # 5 s drain > 1 s
    assert 0.05 <= wait <= 2.0
    assert ctl.backpressure_waits == 1
    # more slots drain the same queue faster: below budget again
    assert ctl.poll_throttle(queue_depth=10, slots=8) == 0.0
    snap = ctl.snapshot()
    assert snap["backpressure_waits"] == 1
    assert snap["service_ewma_s"]["txt2img"] == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# worker level: the shed path end to end
# ---------------------------------------------------------------------------


def _worker(uri: str, name: str, **over):
    from chiaswarm_tpu.node.loadgen import default_worker_factory

    return default_worker_factory(seed=name, **over)(uri, name)


def _flood_jobs(n: int, deadline_s: float) -> list[dict]:
    return [{"id": f"flood-{i}", "model_name": "m", "workflow": "txt2img",
             "prompt": f"p{i}", "deadline_s": deadline_s,
             "content_type": "application/json"} for i in range(n)]


def test_worker_sheds_stale_jobs_distinctly_from_failures():
    """A flooded overload-controlled worker: stale jobs (hive queue age
    past the deadline) shed as redispatchable envelopes; jobs_shed
    counts them, jobs_failed does NOT, and every job still settles
    exactly once (the shed->redispatch->final-attempt-settles flow)."""

    async def scenario():
        hive = MiniHive(lease_s=5.0, delay_s=0.0, max_attempts=2,
                        max_jobs_per_poll=4)
        uri = await hive.start()
        for job in _flood_jobs(24, deadline_s=0.4):
            hive.submit(job)
        # one slow worker: service ~0.15 s vs 0.4 s deadlines at 24
        # deep — most of the queue is doomed and must shed
        worker = _worker(uri, "shed-w0")
        task = asyncio.create_task(worker.run())
        try:
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                hive.sweep()
                if len(hive.completed) >= 24:
                    break
                await asyncio.sleep(0.05)
        finally:
            worker.request_stop()
            await asyncio.wait_for(task, timeout=30)
            await hive.stop()
        return hive, worker

    hive, worker = asyncio.run(scenario())
    uploaded = hive.uploaded_ids()
    assert len(uploaded) == len(set(uploaded))
    assert sorted(hive.completed) == sorted(f"flood-{i}"
                                            for i in range(24))
    kinds = {classify_result(r) for r in hive.completed.values()}
    assert "overloaded" in kinds            # sheds happened...
    assert worker.stats.jobs_shed > 0
    assert worker.stats.jobs_failed == 0    # ...but are NOT failures
    redispatched = hive.metrics.get(
        "chiaswarm_hive_jobs_redispatched_total")
    assert redispatched.value(kind="overloaded") >= 1
    # /healthz surfaces the controller next to the resilience stats
    health = worker.health()
    assert health["overload"]["enabled"] is True
    assert health["overload"]["sheds_total"] == worker.stats.jobs_shed
    assert health["jobs_shed"] == worker.stats.jobs_shed


def test_overload_control_off_is_reference_parity():
    """The settings gate OFF (the default): the same flood admits
    everything — zero sheds, zero backpressure waits — because sheds
    only help when the hive redispatches them."""

    async def scenario():
        hive = MiniHive(lease_s=10.0, delay_s=0.0, max_jobs_per_poll=4)
        uri = await hive.start()
        for job in _flood_jobs(10, deadline_s=0.2):
            hive.submit(job)
        worker = _worker(uri, "parity-w0", overload_control=False)
        assert worker.settings.overload_control is False
        task = asyncio.create_task(worker.run())
        try:
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if len(hive.completed) >= 10:
                    break
                await asyncio.sleep(0.05)
        finally:
            worker.request_stop()
            await asyncio.wait_for(task, timeout=30)
            await hive.stop()
        return hive, worker

    hive, worker = asyncio.run(scenario())
    assert worker.stats.jobs_shed == 0
    assert worker.stats.polls_backpressured == 0
    assert all(classify_result(r) == "ok"
               for r in hive.completed.values())
    assert worker.health()["overload"]["enabled"] is False
