"""Node-layer tests: artifact envelope, dispatcher, executor error taxonomy,
and the full worker loop against the in-process FakeHive — all hermetic on
the 8-device CPU platform (SURVEY.md §4)."""

import asyncio
import base64
import hashlib
import json

import numpy as np
import pytest

from chiaswarm_tpu import WORKER_VERSION
from chiaswarm_tpu.core.chip_pool import ChipPool
from chiaswarm_tpu.node.executor import synchronous_do_work
from chiaswarm_tpu.node.job_args import format_args
from chiaswarm_tpu.node.output_processor import (
    OutputProcessor,
    image_grid,
    make_text_result,
)
from chiaswarm_tpu.node.registry import ModelRegistry
from chiaswarm_tpu.node.settings import Settings
from chiaswarm_tpu.node.worker import Worker
from chiaswarm_tpu.workloads.audio import pcm16_wav
from chiaswarm_tpu.workloads.stitch import stitch_callback

from tests.fake_hive import FakeHive


@pytest.fixture()
def registry():
    return ModelRegistry(
        catalog=[{"name": "tiny", "family": "tiny", "parameters": {}}],
        allow_random=True,
    )


@pytest.fixture()
def pool():
    return ChipPool(n_slots=1)


# ---------- output processor ----------

def test_artifact_envelope_roundtrip():
    proc = OutputProcessor("image/png")
    imgs = np.zeros((2, 32, 32, 3), np.uint8)
    imgs[0, :, :, 0] = 255
    proc.add_images(imgs)
    results = proc.get_results()
    primary = results["primary"]
    blob = base64.b64decode(primary["blob"])
    assert primary["content_type"] == "image/png"
    assert primary["sha256_hash"] == hashlib.sha256(blob).hexdigest()
    assert len(base64.b64decode(primary["thumbnail"])) > 0


def test_text_result_wire_shape():
    result = make_text_result("a red fox")
    payload = json.loads(base64.b64decode(result["blob"]))
    assert payload == {"caption": "a red fox"}
    assert result["content_type"] == "application/json"


def test_image_grid_layouts():
    from PIL import Image

    imgs = [Image.new("RGB", (16, 16)) for _ in range(4)]
    assert image_grid(imgs).size == (32, 32)      # 2x2
    assert image_grid(imgs[:2]).size == (32, 16)  # 1x2
    assert image_grid(imgs[:1]).size == (16, 16)


def test_wav_encode():
    samples = np.sin(np.linspace(0, 440 * 2 * np.pi, 16000)).astype(np.float32)
    wav = pcm16_wav(samples, 16000)
    assert wav[:4] == b"RIFF" and wav[8:12] == b"WAVE"


# ---------- dispatcher ----------

def test_format_rejects_oversize(registry):
    with pytest.raises(ValueError, match="max image size"):
        format_args({"model_name": "tiny", "height": 4096, "width": 4096,
                     "prompt": "x"}, registry)


def test_format_defaults_steps(registry):
    cb, kwargs = format_args({"model_name": "tiny", "prompt": "x"}, registry)
    assert kwargs["num_inference_steps"] == 30
    assert cb.__name__ == "diffusion_callback"


def test_format_strips_unsupported(registry):
    _, kwargs = format_args({
        "model_name": "tiny", "prompt": "x", "negative_prompt": "y",
        "parameters": {"unsupported_pipeline_arguments": ["negative_prompt"]},
    }, registry)
    assert "negative_prompt" not in kwargs


def test_format_routes_workflows(registry):
    cb, _ = format_args({"workflow": "stitch", "model_name": "x",
                         "jobs": []}, registry)
    assert cb.__name__ == "stitch_callback"
    cb, _ = format_args({"workflow": "txt2vid", "model_name": "x"}, registry)
    assert cb.__name__ == "txt2vid_callback"
    cb, _ = format_args({"model_name": "DeepFloyd/IF-I-XL-v1.0",
                         "prompt": "x"}, registry)
    assert cb.__name__ == "cascade_callback"


# ---------- executor error taxonomy ----------

def test_executor_runs_txt2img(registry, pool):
    job = {"id": "job-1", "model_name": "tiny", "prompt": "a fish",
           "num_inference_steps": 2, "height": 64, "width": 64,
           "content_type": "image/png"}
    result = synchronous_do_work(job, pool.slots[0], registry)
    assert result["id"] == "job-1"
    assert result["worker_version"] == WORKER_VERSION
    assert "fatal_error" not in result
    assert result["pipeline_config"]["seed"] >= 0
    assert "primary" in result["artifacts"]


def test_executor_format_error_is_fatal(registry, pool):
    job = {"id": "job-2", "model_name": "tiny", "height": 9999,
           "width": 9999, "prompt": "x"}
    result = synchronous_do_work(job, pool.slots[0], registry)
    assert result["fatal_error"] is True
    assert "error" in result["pipeline_config"]
    assert "primary" in result["artifacts"]  # error rendered as artifact


def test_executor_unavailable_model_is_redispatchable(pool):
    """ISSUE 6 taxonomy resolution: a node-LOCAL model-unavailable is a
    routing problem — the envelope uploads with
    ``error_kind=model_unavailable`` and WITHOUT the fatal flag, so a
    lease-aware hive (node/minihive.py) redispatches it to a node that
    serves the model instead of failing the job forever."""
    registry = ModelRegistry(catalog=[], allow_random=False)
    job = {"id": "job-3", "model_name": "some/unknown-model", "prompt": "x",
           "num_inference_steps": 1}
    result = synchronous_do_work(job, pool.slots[0], registry)
    assert "fatal_error" not in result
    config = result["pipeline_config"]
    assert config["error_kind"] == "model_unavailable"
    assert "is not available on this node" in config["error"]


def test_executor_txt2audio_workflow(registry, pool):
    """txt2audio through the full executor path (formerly a fatal stub —
    now the jitted AudioLDM-class pipeline, workloads/audio.py)."""
    job = {"id": "job-4", "workflow": "txt2audio",
           "model_name": "random/tiny_audio", "prompt": "rain",
           "num_inference_steps": 2, "audio_length_in_s": 0.05}
    result = synchronous_do_work(job, pool.slots[0], registry)
    assert "fatal_error" not in result
    assert result["artifacts"]["primary"]["content_type"] in (
        "audio/wav", "audio/mpeg")  # mpeg when an ffmpeg binary is present
    assert result["pipeline_config"]["mode"] == "txt2audio"


# ---------- workloads ----------

def test_stitch_with_injected_images():
    from PIL import Image

    images = [Image.new("RGB", (64, 64), (i * 40, 10, 10)) for i in range(3)]
    artifacts, config = stitch_callback(
        None, "stitch", seed=0,
        jobs=[{"resultUri": f"http://x/{i}"} for i in range(3)],
        images=images,
    )
    assert "primary" in artifacts
    assert len(config["image_map"]) == 3
    assert config["image_map"][0]["shape"] == "rect"


def test_vid2vid_frame_batched(registry):
    from chiaswarm_tpu.workloads.video import vid2vid_callback

    pool = ChipPool(n_slots=1)
    frames = [np.full((64, 64, 3), 30 * i, np.uint8) for i in range(3)]
    artifacts, config = vid2vid_callback(
        pool.slots[0], "tiny", seed=5, registry=registry,
        frames=frames, fps=8.0, num_inference_steps=2, strength=0.5,
        prompt="watercolor", content_type="video/mp4",
    )
    assert config["frames"] == 3
    assert config["compute_cost"] == 512 * 512 * 2 * 3
    assert "primary" in artifacts and "thumbnail" in artifacts


# ---------- full worker loop against FakeHive ----------

def test_worker_end_to_end(registry):
    async def scenario():
        hive = FakeHive()
        uri = await hive.start()
        hive.jobs.append({
            "id": "e2e-1", "model_name": "tiny", "prompt": "a house",
            "num_inference_steps": 2, "height": 64, "width": 64,
            "content_type": "image/png",
        })
        settings = Settings(hive_uri=uri, hive_token="t", worker_name="test")
        worker = Worker(settings=settings, pool=ChipPool(n_slots=1),
                        registry=registry)
        task = asyncio.create_task(worker.run())
        try:
            await hive.wait_for_results(1, timeout=120)
        finally:
            worker.request_stop()
            await asyncio.wait_for(task, timeout=10)
            await hive.stop()

        assert len(hive.results) == 1
        result = hive.results[0]
        assert result["id"] == "e2e-1"
        assert "primary" in result["artifacts"]
        assert result["pipeline_config"]["model_name"] == "tiny"
        assert worker.jobs_done == 1

    asyncio.run(scenario())


@pytest.mark.slow
def test_worker_e2e_runs_real_safety_checker(registry, tmp_path,
                                             monkeypatch):
    """Full worker loop with a PROVISIONED checker: a tiny converted
    checker fixture on disk (the layout `swarm-tpu init` provisions)
    must actually screen generated images — result carries real per-image
    flags, not the ``safety_checker: "unavailable"`` signal
    (swarm/diffusion/diffusion_func.py:99-111)."""
    monkeypatch.setenv("SWARM_TPU_ROOT", str(tmp_path))
    from chiaswarm_tpu.node.registry import model_dir
    from chiaswarm_tpu.workloads import safety

    from tests.test_safety import write_checker_fixture

    write_checker_fixture(
        model_dir("CompVis/stable-diffusion-safety-checker"),
        threshold=-2.0)  # cosine head flags every image
    monkeypatch.setattr(safety, "_CACHE", {})

    async def scenario():
        hive = FakeHive()
        uri = await hive.start()
        hive.jobs.append({
            "id": "nsfw-1", "model_name": "tiny", "prompt": "a house",
            "num_inference_steps": 2, "height": 64, "width": 64,
            "content_type": "image/png",
        })
        settings = Settings(hive_uri=uri, hive_token="t",
                            worker_name="safety-e2e")
        worker = Worker(settings=settings, pool=ChipPool(n_slots=1),
                        registry=registry)
        task = asyncio.create_task(worker.run())
        try:
            await hive.wait_for_results(1, timeout=120)
        finally:
            worker.request_stop()
            await asyncio.wait_for(task, timeout=10)
            await hive.stop()

        result = hive.results[0]
        assert result["nsfw"] is True
        cfg = result["pipeline_config"]
        assert cfg["nsfw_flags"] == [True]
        assert "safety_checker" not in cfg  # real checker, not unavailable

    asyncio.run(scenario())


def test_worker_health_endpoint(registry):
    """GET /healthz (SURVEY.md §5 observability gap fix): live counters
    while the worker serves against the FakeHive."""
    async def scenario():
        import aiohttp

        hive = FakeHive()
        uri = await hive.start()
        settings = Settings(hive_uri=uri, hive_token="t",
                            worker_name="health-test",
                            health_bind_ephemeral=True)  # port 0, no clash
        worker = Worker(settings=settings, pool=ChipPool(n_slots=1),
                        registry=registry)
        task = asyncio.create_task(worker.run())
        try:
            for _ in range(50):
                if getattr(worker, "health_address", None):
                    break
                await asyncio.sleep(0.1)
            host, port = worker.health_address
            async with aiohttp.ClientSession() as session:
                async with session.get(
                        f"http://{host}:{port}/healthz") as resp:
                    assert resp.status == 200
                    payload = await resp.json()
        finally:
            worker.request_stop()
            await asyncio.wait_for(task, timeout=10)
            await hive.stop()
        assert payload["status"] == "ok"
        assert payload["worker_name"] == "health-test"
        assert payload["slots"] == 1
        assert "jobs_done" in payload and "queue_depth" in payload

    asyncio.run(scenario())


@pytest.mark.slow
def test_worker_input_image_fetch(registry):
    """img2img through the worker: input image served by the FakeHive."""

    async def scenario():
        hive = FakeHive()
        uri = await hive.start()
        hive.jobs.append({
            "id": "e2e-2", "model_name": "tiny", "prompt": "blue",
            "num_inference_steps": 2, "strength": 0.6,
            "start_image_uri": f"{uri}/assets/image.png",
            "content_type": "image/png",
        })
        settings = Settings(hive_uri=uri, hive_token="t", worker_name="test")
        worker = Worker(settings=settings, pool=ChipPool(n_slots=1),
                        registry=registry)
        task = asyncio.create_task(worker.run())
        try:
            # generous: the img2img program first-compiles inside this
            # window and CI hosts run the suite next to other compiles
            await hive.wait_for_results(1, timeout=420)
        finally:
            worker.request_stop()
            await asyncio.wait_for(task, timeout=30)
            await hive.stop()

        result = hive.results[0]
        assert "fatal_error" not in result, result["pipeline_config"]
        assert result["pipeline_config"]["mode"] == "img2img"

    asyncio.run(scenario())
