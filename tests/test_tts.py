"""Bark-class TTS: GPT KV-cache decode, codec decoder, 3-stage pipeline.

Reference behavior covered: the suno-bark txt2audio path
(swarm/audio/bark.py:11-38, dispatched for model_name == "suno/bark" at
swarm/job_arguments.py:22-23).
"""

import io
import wave

import numpy as np
import pytest

from chiaswarm_tpu.pipelines.tts import (
    TTS_FAMILIES,
    TTSComponents,
    TTSPipeline,
    get_tts_family,
)


@pytest.fixture(scope="module")
def tiny_tts():
    return TTSPipeline(TTSComponents.random("tiny_tts", seed=0))


@pytest.mark.slow
def test_gpt_cached_decode_matches_full_forward():
    """Incremental KV-cache decode must produce the same logits as a full
    forward over the whole sequence (the cache-correctness invariant)."""
    import jax
    import jax.numpy as jnp

    from chiaswarm_tpu.models.gpt import GPT, GPTConfig, init_caches

    cfg = GPTConfig(vocab_size=50, n_layer=2, n_head=2, n_embd=16,
                    block_size=16)
    gpt = GPT(cfg)
    ids = jnp.asarray([[3, 7, 11, 2, 9, 4]], jnp.int32)
    caches = init_caches(cfg, 1)
    params = gpt.init(jax.random.PRNGKey(0), ids, caches, 0, jnp.int32(6))

    full_logits, _ = gpt.apply(params, ids, init_caches(cfg, 1), 0,
                               jnp.int32(6))

    # prefill 3, then decode one token at a time
    caches = init_caches(cfg, 1)
    logits_3, caches = gpt.apply(params, ids[:, :3], caches, 0, jnp.int32(3))
    np.testing.assert_allclose(np.asarray(logits_3),
                               np.asarray(full_logits[:, :3]), atol=1e-4)
    for t in range(3, 6):
        step_logits, caches = gpt.apply(params, ids[:, t:t + 1], caches, t,
                                        jnp.int32(t + 1))
        np.testing.assert_allclose(np.asarray(step_logits[:, 0]),
                                   np.asarray(full_logits[:, t]), atol=1e-4)


@pytest.mark.slow
def test_gpt_generate_deterministic():
    import jax
    import jax.numpy as jnp

    from chiaswarm_tpu.models.gpt import GPT, GPTConfig, generate, init_caches

    cfg = GPTConfig(vocab_size=40, output_vocab_size=20, n_layer=2,
                    n_head=2, n_embd=16, block_size=32)
    gpt = GPT(cfg)
    ids = jnp.asarray([[5, 1, 7, 3]], jnp.int32)
    params = gpt.init(jax.random.PRNGKey(1), ids, init_caches(cfg, 1), 0,
                      jnp.int32(4))
    out1 = generate(gpt, params, ids, jax.random.PRNGKey(2), prefill_len=4,
                    max_new=8, temperature=0.8, top_k=5)
    out2 = generate(gpt, params, ids, jax.random.PRNGKey(2), prefill_len=4,
                    max_new=8, temperature=0.8, top_k=5)
    assert out1.shape == (1, 8)
    assert np.array_equal(np.asarray(out1), np.asarray(out2))
    assert (np.asarray(out1) < cfg.out_vocab).all()
    out3 = generate(gpt, params, ids, jax.random.PRNGKey(3), prefill_len=4,
                    max_new=8, temperature=0.8, top_k=5)
    assert not np.array_equal(np.asarray(out1), np.asarray(out3))


@pytest.mark.slow
def test_codec_decoder_shapes():
    import jax
    import jax.numpy as jnp

    from chiaswarm_tpu.models.codec import CodecConfig, CodecDecoder

    cfg = CodecConfig(n_codebooks=4, codebook_size=16, codebook_dim=8,
                      num_filters=4, upsampling_ratios=(4, 2),
                      num_lstm_layers=1)
    dec = CodecDecoder(cfg)
    codes = jnp.zeros((2, 4, 10), jnp.int32)
    params = dec.init(jax.random.PRNGKey(0), codes)
    wav = dec.apply(params, codes)
    assert cfg.hop_length == 8
    assert wav.shape == (2, 80)
    assert np.isfinite(np.asarray(wav)).all()


def test_tts_family_routing():
    assert get_tts_family("suno/bark").name == "bark"
    assert get_tts_family("random/tiny_tts").name == "tiny_tts"
    assert TTS_FAMILIES["bark"].codec.sampling_rate == 24000


def test_tts_pipeline_end_to_end(tiny_tts):
    wav, sr, config = tiny_tts("hello world", duration_s=0.3, seed=6)
    assert wav.ndim == 2 and wav.shape[0] == 1 and wav.shape[1] > 0
    assert sr == 16000
    assert np.isfinite(wav).all()
    assert config["mode"] == "tts"
    wav2, _, _ = tiny_tts("hello world", duration_s=0.3, seed=6)
    assert np.array_equal(wav, wav2)


def test_tts_workload_wav_artifact(monkeypatch):
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.workloads import audio as audio_wl
    from chiaswarm_tpu.workloads.audio import tts_callback

    # pin the wav fallback so the wave-parse below holds on ffmpeg hosts
    monkeypatch.setattr(audio_wl, "mp3_bytes",
                        lambda s, sr, bitrate="128k": None)
    registry = ModelRegistry(catalog=[], allow_random=True)
    artifacts, config = tts_callback(
        "slot0", "random/tiny_tts", seed=2, registry=registry,
        prompt="good morning", audio_length_in_s=0.3)
    assert config["mode"] == "tts"
    import base64

    raw = base64.b64decode(artifacts["primary"]["blob"])
    with wave.open(io.BytesIO(raw)) as f:
        assert f.getnframes() > 0
        assert f.getframerate() == 16000


def test_voice_preset_history_changes_output(tiny_tts):
    """A full bark voice preset {semantic, coarse, fine} must condition
    all three stages (coarse history rides the sliding window, fine
    history prepends to the fill buffer)."""
    fam = tiny_tts.c.family
    rng = np.random.RandomState(0)
    history = {
        "semantic_prompt": rng.randint(0, fam.semantic_vocab, size=8),
        "coarse_prompt": rng.randint(0, fam.codebook_size,
                                     size=(fam.n_coarse, 10)),
        "fine_prompt": rng.randint(0, fam.codebook_size,
                                   size=(fam.n_fine, 10)),
    }
    base, _, _ = tiny_tts("same words", duration_s=0.3, seed=9)
    cond, _, cfg = tiny_tts("same words", duration_s=0.3, seed=9,
                            history=history)
    assert np.isfinite(cond).all() and cfg["mode"] == "tts"
    # histories shift every stage; identical output would mean they were
    # silently dropped
    assert base.shape != cond.shape or not np.array_equal(base, cond)


def test_semantic_text_encoding_bark_protocol():
    """Regression: the semantic-stage text window must be raw wordpiece ids
    (no [CLS]/[SEP]) with text_pad_token in every unused slot — bark
    tokenizes with add_special_tokens=False and masked_fills pads with
    text_pad_token (HF modeling_bark.py:635). encode()'s [PAD]=0 rows
    would become 0+text_encoding_offset, an untrained in-vocab token."""
    from chiaswarm_tpu.models.tokenizer import WordPieceTokenizer
    from chiaswarm_tpu.pipelines.tts import encode_semantic_text

    vocab = {"[PAD]": 0, "[UNK]": 1, "[CLS]": 2, "[SEP]": 3,
             "hello": 4, "world": 5}
    tok = WordPieceTokenizer(vocab, max_length=16)
    fam = get_tts_family("suno/bark")
    row = encode_semantic_text(tok, "hello world", fam,
                               fam.semantic.vocab_size)[0]
    L = fam.max_input_semantic_length
    assert row.shape == (L,)
    off = fam.text_encoding_offset
    assert row[0] == 4 + off and row[1] == 5 + off
    # every remaining slot is the real pad token, not [PAD]+offset or
    # [CLS]/[SEP]+offset
    assert (row[2:] == fam.text_pad_token).all()
    assert 0 + off not in row and 2 + off not in row and 3 + off not in row


def test_hash_tokenizer_tokenize_matches_encode_body():
    """HashTokenizer.tokenize() must be the specials-free body of
    encode() (same hashed ids, no bos/eos/pad)."""
    from chiaswarm_tpu.models.tokenizer import HashTokenizer

    tok = HashTokenizer(vocab_size=100, max_length=12)
    raw = tok.tokenize("a few words here")
    enc = tok.encode("a few words here")
    assert enc[0] == tok.bos_id
    assert enc[1:1 + len(raw)] == raw
    assert all(i < tok.vocab_size - 2 for i in raw)
