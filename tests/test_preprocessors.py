"""ControlNet input preprocessors (host-side CPU ops).

Capability parity with swarm/controlnet/input_processor.py:17-272 — the
12-mode conditioning dispatch that runs before generation on the user's
input image (invoked from node/job_args.py:get_image, mirroring
swarm/job_arguments.py:187-188).
"""

import numpy as np
import pytest
from PIL import Image

from chiaswarm_tpu.workloads.controlnet import (
    _PREPROCESSORS,
    image_to_tile,
    preprocess_image,
)


@pytest.fixture(scope="module")
def photo():
    """Structured test image: gradient + bright box + dark diagonal."""
    rng = np.random.default_rng(1)
    arr = np.tile(np.linspace(0, 255, 128, dtype=np.uint8)[None, :, None],
                  (128, 1, 3))
    arr[24:56, 24:56] = [250, 40, 40]
    for i in range(100):
        arr[i + 10, i + 10] = 0
    arr = (arr.astype(np.int32) +
           rng.integers(-8, 8, arr.shape)).clip(0, 255).astype(np.uint8)
    return Image.fromarray(arr)


def test_all_modes_registered():
    expected = {"canny", "mlsd", "depth", "normal", "normalbae", "seg",
                "lineart", "pix2pix", "scribble", "softedge", "shuffle",
                "tile"}
    assert expected <= set(_PREPROCESSORS)


@pytest.mark.parametrize("mode", sorted(_PREPROCESSORS))
def test_each_mode_produces_rgb(photo, mode, monkeypatch):
    if mode == "openpose":
        # weight-gated: run it with a random-init detector
        from chiaswarm_tpu.models.openpose import OpenposeDetector
        from chiaswarm_tpu.workloads import controlnet as wl

        monkeypatch.setattr(wl, "_OPENPOSE",
                            [OpenposeDetector.random(seed=0)])
    out = preprocess_image(photo, {"type": mode, "preprocess": True})
    arr = np.asarray(out)
    assert arr.ndim == 3 and arr.shape[2] == 3
    assert arr.dtype == np.uint8


def test_canny_finds_edges(photo):
    out = np.asarray(preprocess_image(photo, {"type": "canny", "preprocess": True}))
    assert out.max() == 255  # box/diagonal edges present
    assert (out > 0).mean() < 0.5  # sparse edge map


def test_mlsd_draws_segments(photo):
    out = np.asarray(preprocess_image(photo, {"type": "mlsd", "preprocess": True}))
    assert out.max() == 255  # straight box edges produce segments
    assert (out == 0).mean() > 0.5  # mostly black wireframe


def test_depth_monotone_prior(photo):
    out = np.asarray(preprocess_image(photo, {"type": "depth", "preprocess": True}))[..., 0]
    # position prior: bottom rows read nearer (brighter) than top rows
    assert out[-8:].mean() > out[:8].mean()


def test_normal_is_unit_encoded(photo):
    out = np.asarray(preprocess_image(photo, {"type": "normalbae", "preprocess": True}))
    n = out.astype(np.float32) / 255.0 * 2.0 - 1.0
    norms = np.sqrt((n ** 2).sum(-1))
    assert np.isclose(np.median(norms), 1.0, atol=0.15)


def test_seg_uses_palette_colors(photo):
    from chiaswarm_tpu.workloads.controlnet import _ADE_PALETTE

    out = np.asarray(preprocess_image(photo, {"type": "seg", "preprocess": True}))
    palette = {tuple(c) for c in _ADE_PALETTE}
    colors = {tuple(c) for c in out.reshape(-1, 3)[::37]}
    assert colors <= palette


def test_tile_scales_short_side_to_resolution(photo):
    """Reference tile semantics (input_processor.py:63-71): scale so the
    SHORT side hits the target resolution (small inputs upscale), then
    round each side to the NEAREST 64 multiple."""
    resized = photo.resize((130, 70))
    out = image_to_tile(resized)
    # k = 1024/70; 130k = 1901.7 -> 1920 (nearest 64), 70k = 1024
    assert out.size == (1920, 1024)
    # at the target scale already: nearest-64 rounding only
    assert image_to_tile(photo.resize((1030, 1100))).size == (1024, 1088)
    # parameterized resolution keeps test shapes small
    assert image_to_tile(resized, resolution=128).size == (256, 128)


def test_canny_honors_job_thresholds(photo):
    """Per-job low/high thresholds (input_processor.py:77-81): a
    permissive threshold pair must mark at least as many edge pixels as
    a strict pair on the same image."""
    loose = np.asarray(preprocess_image(
        photo, {"type": "canny", "preprocess": True,
                "low_threshold": 10, "high_threshold": 40}))
    strict = np.asarray(preprocess_image(
        photo, {"type": "canny", "preprocess": True,
                "low_threshold": 200, "high_threshold": 250}))
    default = np.asarray(preprocess_image(
        photo, {"type": "canny", "preprocess": True}))
    assert (loose > 0).sum() > (strict > 0).sum()
    assert (loose > 0).sum() >= (default > 0).sum() >= (strict > 0).sum()


def test_preprocess_false_passthrough(photo):
    out = preprocess_image(photo, {"type": "canny", "preprocess": False})
    assert out is photo


def test_preprocess_defaults_off(photo):
    """Reference default (input_processor.py:18): no ``preprocess`` key
    means the input is already a conditioning image — pass through."""
    out = preprocess_image(photo, {"type": "canny"})
    assert out is photo
    # even for weight-gated modes: no preprocessing, no weight demands
    assert preprocess_image(photo, {"type": "openpose"}) is photo


def test_openpose_without_weights_raises(photo, tmp_path, monkeypatch):
    monkeypatch.setenv("SDAAS_ROOT", str(tmp_path))
    with pytest.raises(ValueError, match="body_pose_model"):
        preprocess_image(photo, {"type": "openpose", "preprocess": True})


def test_unknown_mode_raises(photo):
    with pytest.raises(ValueError, match="not yet supported"):
        preprocess_image(photo, {"type": "telekinesis", "preprocess": True})
