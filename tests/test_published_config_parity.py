"""Published-config-scale oracle runs for Bark / BLIP / DPT / UperNet.

VERDICT r3: the tiny-config torch-fidelity harnesses (test_bark_convert,
test_caption, test_dpt, test_upernet) prove the conversion rules, but the
CLIP real-config lesson (eps + GELU bugs invisible at tiny widths) says
the published configs themselves must go through the same comparisons.
This file re-runs each harness at the exact published architecture
against transformers' own classes with random weights — the full offline
slice of the real-weights proof. Slow tier: full-width forwards are
compile-heavy on the CPU test platform.

Reference serving sites: Bark swarm/audio/bark.py:11-38, BLIP
swarm/captioning/caption_image.py, DPT + UperNet preprocessors
swarm/controlnet/input_processor.py:87-117.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp  # noqa: E402

pytestmark = pytest.mark.slow


def _randomize(model, seed: int, scale: float = 0.05):
    """Non-degenerate deterministic weights (HF inits leave zeros that
    would hide transposition/mapping bugs)."""
    sd = model.state_dict()
    gen = torch.Generator().manual_seed(seed)
    for key, value in sd.items():
        if not value.dtype.is_floating_point:
            continue
        if key.endswith("running_var"):
            sd[key] = torch.rand(value.shape, generator=gen) + 0.5
        elif key.endswith("running_mean"):
            sd[key] = torch.randn(value.shape, generator=gen) * 0.1
        else:
            sd[key] = torch.randn(value.shape, generator=gen) * scale
    model.load_state_dict(sd)
    return model


# ------------------------------------------------------------- DPT-large

def test_dpt_large_published_config_parity():
    """Intel/dpt-large — the depth preprocessor's published architecture
    (24x1024 ViT backbone, 4-level reassemble neck, 384px)."""
    from transformers import DPTConfig as HFDPTConfig
    from transformers import DPTForDepthEstimation

    from chiaswarm_tpu.convert.torch_to_flax import convert_dpt
    from chiaswarm_tpu.models.dpt import DPT_LARGE, DPTDepth

    cfg = HFDPTConfig(
        hidden_size=1024, intermediate_size=4096, num_hidden_layers=24,
        num_attention_heads=16, image_size=384, patch_size=16,
        backbone_out_indices=[5, 11, 17, 23],
        neck_hidden_sizes=[256, 512, 1024, 1024], fusion_hidden_size=256,
        reassemble_factors=[4, 2, 1, 0.5], readout_type="project",
        is_hybrid=False, qkv_bias=True, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, add_projection=False,
        use_batch_norm_in_fusion_residual=False,
    )
    torch.manual_seed(0)
    hf = _randomize(DPTForDepthEstimation(cfg).eval(), seed=3)
    state = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    params = convert_dpt(state)
    x = np.random.RandomState(1).randn(1, 384, 384, 3).astype(np.float32)
    with torch.no_grad():
        want = hf(torch.from_numpy(x.transpose(0, 3, 1, 2))
                  ).predicted_depth.numpy()
    got = np.asarray(DPTDepth(DPT_LARGE).apply(params, jnp.asarray(x)))
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, atol=5e-3, rtol=5e-3)


# ------------------------------------------------------------- BLIP-base

def test_blip_base_published_config_parity():
    """Salesforce/blip-image-captioning-base (the exact model name the
    reference routes img2txt to): 12x768 vision at 384px + 12x768
    cross-attending BERT decoder over the 30524-row vocab."""
    from transformers import BlipConfig as HFBlipConfig
    from transformers import BlipForConditionalGeneration

    from chiaswarm_tpu.convert.torch_to_flax import (
        convert_blip_text,
        convert_blip_vision,
    )
    from chiaswarm_tpu.models.blip import (
        BLIP_BASE,
        BlipTextModel,
        BlipVisionEncoder,
    )

    # the published snapshot's text_config, NOT the transformers class
    # defaults — those say 8 attention heads where the checkpoint ships
    # 12 (BERT-base), a mismatch this suite exists to catch
    cfg = HFBlipConfig.from_text_vision_configs(
        text_config=transformers.BlipTextConfig(
            vocab_size=30524, hidden_size=768, intermediate_size=3072,
            num_hidden_layers=12, num_attention_heads=12,
            max_position_embeddings=512, encoder_hidden_size=768,
            is_decoder=True, attention_probs_dropout_prob=0.0,
            hidden_dropout_prob=0.0),
        vision_config=transformers.BlipVisionConfig(
            hidden_size=768, intermediate_size=3072, num_hidden_layers=12,
            num_attention_heads=12, image_size=384, patch_size=16,
            attention_dropout=0.0),
    )
    torch.manual_seed(1)
    hf = BlipForConditionalGeneration(cfg).eval()
    state = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    vparams = convert_blip_vision(state)
    tparams = convert_blip_text(state, "text_decoder.")

    pixels = np.random.RandomState(2).randn(1, 384, 384, 3).astype(
        np.float32)
    with torch.no_grad():
        tv = hf.vision_model(
            torch.from_numpy(pixels.transpose(0, 3, 1, 2))
        ).last_hidden_state.numpy()
    fv = np.asarray(BlipVisionEncoder(BLIP_BASE.vision).apply(
        vparams, jnp.asarray(pixels)))
    np.testing.assert_allclose(fv, tv, atol=2e-3, rtol=5e-3)

    ids = np.array([[30522, 1037, 3861, 1997]], np.int32)  # [DEC] a picture of
    with torch.no_grad():
        tl = hf.text_decoder(
            input_ids=torch.from_numpy(ids.astype(np.int64)),
            encoder_hidden_states=torch.from_numpy(tv),
            is_decoder=True,
        ).logits.numpy()
    decoder = BlipTextModel(BLIP_BASE.text)
    cross_kvs = decoder.apply(tparams, jnp.asarray(tv), method="cross_kvs")
    fl, _ = decoder.apply(tparams, jnp.asarray(ids), causal=True,
                          cross_kvs=cross_kvs)
    np.testing.assert_allclose(np.asarray(fl), tl, atol=2e-3, rtol=5e-3)


# ------------------------------------------- UperNet (convnext-small)

def test_upernet_convnext_small_published_config_parity():
    """openmmlab/upernet-convnext-small — the seg preprocessor's
    published architecture (depths 3/3/27/3, dims 96..768, 512-ch head,
    150 ADE labels)."""
    from transformers import ConvNextConfig, UperNetConfig
    from transformers import UperNetForSemanticSegmentation

    from chiaswarm_tpu.convert.torch_to_flax import convert_upernet
    from chiaswarm_tpu.models.upernet import (
        UPERNET_CONVNEXT_SMALL,
        UperNetSeg,
    )

    backbone = ConvNextConfig(
        depths=[3, 3, 27, 3], hidden_sizes=[96, 192, 384, 768],
        out_features=["stage1", "stage2", "stage3", "stage4"],
        drop_path_rate=0.0)
    cfg = UperNetConfig(
        backbone_config=backbone, hidden_size=512,
        pool_scales=[1, 2, 3, 6], num_labels=150,
        use_auxiliary_head=True, auxiliary_in_channels=384)
    torch.manual_seed(2)
    hf = _randomize(UperNetForSemanticSegmentation(cfg).eval(), seed=5)
    state = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    params = convert_upernet(state)
    x = np.random.RandomState(3).randn(1, 256, 256, 3).astype(np.float32)
    with torch.no_grad():
        tl = hf(torch.from_numpy(x.transpose(0, 3, 1, 2))).logits
        tseg = tl.argmax(dim=1).numpy().astype(np.uint8)
    fseg = np.asarray(UperNetSeg(UPERNET_CONVNEXT_SMALL).apply(
        params, jnp.asarray(x)))
    assert fseg.shape == tseg.shape
    agree = (fseg == tseg).mean()
    assert agree > 0.99, agree


# ------------------------------------------------------------ Bark (big)

@pytest.fixture(scope="module")
def bark_published():
    """suno/bark's published stage architectures (24x16x1024, the real
    129600/10048/12096/1056 vocabs) + the published 24 kHz EnCodec."""
    from transformers import BarkModel
    from transformers.models.bark import (
        BarkCoarseConfig,
        BarkConfig,
        BarkFineConfig,
        BarkSemanticConfig,
    )
    from transformers.models.bark import modeling_bark as mb
    from transformers.models.encodec.configuration_encodec import (
        EncodecConfig,
    )

    from chiaswarm_tpu.convert.torch_to_flax import convert_bark
    from chiaswarm_tpu.pipelines.tts import BARK

    gpt_kw = dict(block_size=1024, num_layers=24, num_heads=16,
                  hidden_size=1024, dropout=0.0, bias=False)
    cfg = BarkConfig(
        semantic_config=BarkSemanticConfig(
            input_vocab_size=129_600, output_vocab_size=10_048,
            **gpt_kw).to_dict(),
        coarse_acoustics_config=BarkCoarseConfig(
            input_vocab_size=12_096, output_vocab_size=12_096,
            **gpt_kw).to_dict(),
        fine_acoustics_config=BarkFineConfig(
            input_vocab_size=1056, output_vocab_size=1056,
            n_codes_total=8, n_codes_given=1, **gpt_kw).to_dict(),
        codec_config=EncodecConfig().to_dict(),  # published 24 kHz model
    )
    torch.manual_seed(3)
    orig = mb.BarkPreTrainedModel._init_weights

    def safe_init(self, module):
        import torch.nn as nn

        if isinstance(module, nn.LayerNorm) and module.bias is None:
            module.weight.data.fill_(1.0)
            return
        orig(self, module)

    mb.BarkPreTrainedModel._init_weights = safe_init
    try:
        hf = BarkModel(cfg).eval()
    finally:
        mb.BarkPreTrainedModel._init_weights = orig
    sd = hf.state_dict()
    gen = torch.Generator().manual_seed(11)
    for key, value in sd.items():
        if value.dtype.is_floating_point and value.ndim >= 2:
            sd[key] = torch.randn(value.shape, generator=gen) * 0.02
    hf.load_state_dict(sd)

    fam = dataclasses.replace(
        BARK,
        semantic=dataclasses.replace(BARK.semantic, dtype="float32"),
        coarse=dataclasses.replace(BARK.coarse, dtype="float32"),
        fine=dataclasses.replace(BARK.fine, dtype="float32"),
    )
    state = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    return hf, fam, convert_bark(state, fam)


def test_bark_semantic_published_config_parity(bark_published):
    from chiaswarm_tpu.models.gpt import GPT, init_caches

    hf, fam, params = bark_published
    ids = np.array([[11, 3000, 77777, 129_000, 42]], np.int64)
    with torch.no_grad():
        tl = hf.semantic(input_ids=torch.from_numpy(ids)).logits.numpy()
    gpt = GPT(fam.semantic)
    fl, _ = gpt.apply(params["semantic"], jnp.asarray(ids, jnp.int32),
                      init_caches(fam.semantic, 1), 0, jnp.int32(5))
    np.testing.assert_allclose(np.asarray(fl), tl, atol=2e-3, rtol=5e-3)


def test_bark_fine_published_config_parity(bark_published):
    from chiaswarm_tpu.models.gpt import FineGPT

    hf, fam, params = bark_published
    rng = np.random.RandomState(0)
    codes = rng.randint(0, 1056, size=(1, 16, 8)).astype(np.int64)
    fine = FineGPT(fam.fine, n_codes_total=8, n_codes_given=1)
    for ci in (1, 7):
        with torch.no_grad():
            tl = hf.fine_acoustics(
                codebook_idx=ci,
                input_ids=torch.from_numpy(codes)).logits.numpy()
        fl = fine.apply(params["fine"], jnp.asarray(codes, jnp.int32), ci)
        np.testing.assert_allclose(np.asarray(fl), tl, atol=2e-3,
                                   rtol=5e-3, err_msg=f"codebook {ci}")


def test_encodec_published_decoder_parity(bark_published):
    from chiaswarm_tpu.models.codec import CodecDecoder

    hf, fam, params = bark_published
    rng = np.random.RandomState(1)
    codes = rng.randint(0, 1024, size=(1, 8, 9)).astype(np.int64)
    with torch.no_grad():
        emb = hf.codec_model.quantizer.decode(
            torch.from_numpy(codes.transpose(1, 0, 2)))
        twav = hf.codec_model.decoder(emb).numpy()[:, 0]
    dec = CodecDecoder(fam.codec)
    fwav = np.asarray(dec.apply(params["codec"],
                                jnp.asarray(codes, jnp.int32)))
    assert fwav.shape == twav.shape
    np.testing.assert_allclose(fwav, twav, atol=1e-3, rtol=5e-3)


# ---- ControlNet preprocessor nets at published INFERENCE scale ---------
#
# VERDICT r4 #5: the four hand-built oracles already use the published
# channel widths, but their conversion checks ran on 32-64px inputs with
# small activations — the regime that hid the DPT ConvTranspose flip.
# These re-run the same torch-vs-flax comparisons at the real serving
# grids (controlnet_aux resizes to 512; openpose's boxsize is 368) with
# default-init (kaiming-magnitude) weights.


def test_openpose_published_scale_parity():
    from chiaswarm_tpu.convert.torch_to_flax import convert_openpose
    from chiaswarm_tpu.models.openpose import OpenposeDetector

    from tests.test_openpose import _torch_body_net

    _torch, body = _torch_body_net()
    state = {k: v.detach().numpy() for k, v in body.state_dict().items()}
    det = OpenposeDetector(params=convert_openpose(state))
    x = np.random.RandomState(3).rand(1, 368, 368, 3).astype(
        np.float32) - 0.5
    with _torch.no_grad():
        tp, th = body(_torch.from_numpy(x.transpose(0, 3, 1, 2)))
    fp, fh = det._fwd(det.params, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(fp),
                               tp.numpy().transpose(0, 2, 3, 1),
                               atol=1e-3, rtol=1e-2)
    np.testing.assert_allclose(np.asarray(fh),
                               th.numpy().transpose(0, 2, 3, 1),
                               atol=1e-3, rtol=1e-2)


def test_hed_published_scale_parity():
    from chiaswarm_tpu.convert.torch_to_flax import convert_hed
    from chiaswarm_tpu.models.hed import HEDDetector

    from tests.test_hed import _torch_hed

    _torch, net = _torch_hed()
    state = {k: v.detach().numpy() for k, v in net.state_dict().items()}
    det = HEDDetector(params=convert_hed(state))
    x = (np.random.RandomState(4).rand(1, 512, 512, 3) * 255).astype(
        np.float32)
    with _torch.no_grad():
        tsides = net(_torch.from_numpy(x.transpose(0, 3, 1, 2)))
    fsides = det._fwd(det.params, jnp.asarray(x))
    assert len(fsides) == len(tsides)
    for fs, ts in zip(fsides, tsides):
        np.testing.assert_allclose(np.asarray(fs).transpose(0, 3, 1, 2),
                                   ts.numpy(), atol=1e-3, rtol=1e-2)


def test_mlsd_published_scale_parity():
    from chiaswarm_tpu.convert.torch_to_flax import convert_mlsd
    from chiaswarm_tpu.models.mlsd import MLSDDetector

    from tests.test_mlsd import _torch_mlsd

    _torch, net = _torch_mlsd()
    state = {k: v.detach().numpy() for k, v in net.state_dict().items()}
    det = MLSDDetector(params=convert_mlsd(state))
    x = np.random.RandomState(5).rand(1, 512, 512, 4).astype(
        np.float32) * 2 - 1
    with _torch.no_grad():
        tout = net(_torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    fout = np.asarray(det._fwd(det.params, jnp.asarray(x)))
    np.testing.assert_allclose(fout.transpose(0, 3, 1, 2), tout,
                               atol=2e-3, rtol=1e-2)


def test_lineart_published_scale_parity():
    from chiaswarm_tpu.convert.torch_to_flax import convert_lineart
    from chiaswarm_tpu.models.lineart import LineartDetector

    from tests.test_lineart import _torch_generator

    _torch, net = _torch_generator()
    state = {k: v.detach().numpy() for k, v in net.state_dict().items()}
    det = LineartDetector(params=convert_lineart(state))
    x = np.random.RandomState(6).rand(1, 512, 512, 3).astype(np.float32)
    with _torch.no_grad():
        tout = net(_torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    fout = np.asarray(det._fwd(det.params, jnp.asarray(x)))
    np.testing.assert_allclose(fout[..., 0], tout[:, 0], atol=1e-3,
                               rtol=1e-2)
