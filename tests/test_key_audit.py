"""swarmkey's compiled face, in-process: the knob fold in
static_cache_key, the persistent fingerprint, and the audit tool's
scenario coverage. The full subprocess sweep (tools/key_audit.py builds
real programs under each knob) runs as its own CI step; these tests pin
the key algebra itself so a regression is caught in the unit tier."""

from __future__ import annotations

import pytest

from chiaswarm_tpu.core.compile_cache import (
    _TRACE_ENV_KNOBS, artifact_cache_key, cache_fingerprint,
    static_cache_key,
)


@pytest.fixture
def scrubbed(monkeypatch):
    for name in _TRACE_ENV_KNOBS:
        monkeypatch.delenv(name, raising=False)
    monkeypatch.delenv("CHIASWARM_NUMERICS", raising=False)
    monkeypatch.delenv("CHIASWARM_ACTIVATIONS", raising=False)
    return monkeypatch


def test_default_key_is_byte_identical_historical_tuple(scrubbed):
    """The acceptance clause: with every knob at its default the key is
    the pre-PR 3-tuple — default deployments keep every warm slot."""
    key = static_cache_key(7, "gen", {"h": 64, "s": "euler"})
    assert key == (7, "gen", (("h", 64), ("s", "euler")))


def test_every_trace_knob_flips_the_key_append_only(scrubbed):
    base = static_cache_key(7, "gen", {"h": 64})
    for name in _TRACE_ENV_KNOBS:
        scrubbed.setenv(name, "1")
        key = static_cache_key(7, "gen", {"h": 64})
        scrubbed.delenv(name)
        assert key != base, name
        # append-only: the historical prefix survives, so turning the
        # knob OFF again lands back on the original slot
        assert key[:3] == base
        assert key[3] == ("knobs", ((name, "1"),)), name


def test_whitespace_only_value_is_not_set(scrubbed):
    scrubbed.setenv("CHIASWARM_ATTENTION", "   ")
    assert static_cache_key(1, "t", {}) == (1, "t", ())


def test_knob_vector_is_table_ordered_and_value_bearing(scrubbed):
    scrubbed.setenv("CHIASWARM_RING_FLASH", "scan")
    scrubbed.setenv("CHIASWARM_ATTENTION", " flash ")
    key = static_cache_key(1, "tv", {})
    assert key[3] == ("knobs", (("CHIASWARM_ATTENTION", "flash"),
                                ("CHIASWARM_RING_FLASH", "scan")))


def test_cache_fingerprint_shape_and_stability(scrubbed):
    fp = cache_fingerprint()
    assert fp[0] == "chiaswarm-exec-v1"
    assert dict(fp[1])["jax"]  # version metadata present without jax import
    assert fp[2] == ("knobs", ())
    assert fp == cache_fingerprint()
    scrubbed.setenv("CHIASWARM_ATTENTION", "flash")
    assert dict((cache_fingerprint()[2],))["knobs"] == (
        ("CHIASWARM_ATTENTION", "flash"),)


def test_artifact_key_drops_the_in_process_owner(scrubbed):
    """The R20 stance by construction: two processes with different
    owner ids produce the SAME artifact key for the same program."""
    a = artifact_cache_key("gen", {"h": 64})
    assert a[0] == cache_fingerprint()
    assert a[1:] == static_cache_key(12345, "gen", {"h": 64})[1:]
    assert a == artifact_cache_key("gen", {"h": 64})


def test_audit_scenarios_cover_every_trace_knob():
    from tools.key_audit import SCENARIOS

    assert set(SCENARIOS) == set(_TRACE_ENV_KNOBS)
    for knob, (program, value, _) in SCENARIOS.items():
        assert value.strip(), knob
        assert program in ("local", "ringmesh", "flash", "none"), knob
