"""Cross-job coalescing: compatible txt2img jobs ride one batched program.

No reference analog — this is the dp-mesh efficiency path: a data-sharded
slot replicates a batch=1 job on every data row, so merging compatible
jobs into one batched program is what makes multi-chip slots earn their
chips (node/executor.py::synchronous_do_work_batch,
workloads/diffusion.py::diffusion_coalesced_callback). Per-sample
(seed, row) noise keys guarantee each job's images match its solo run.

Runs on the virtual 8-device CPU mesh (tests/conftest.py).
"""

from __future__ import annotations

import numpy as np
import pytest

from chiaswarm_tpu.core.chip_pool import ChipPool
from chiaswarm_tpu.core.mesh import MeshSpec
from chiaswarm_tpu.node.executor import (
    synchronous_do_work,
    synchronous_do_work_batch,
)
from chiaswarm_tpu.node.registry import ModelRegistry


@pytest.fixture()
def registry():
    return ModelRegistry(
        catalog=[{"name": "tiny", "family": "tiny", "parameters": {}}],
        allow_random=True,
    )


def _job(i: int, **over):
    job = {"id": f"j{i}", "model_name": "tiny", "prompt": f"prompt {i}",
           "seed": 100 + i, "num_inference_steps": 2,
           "height": 64, "width": 64, "content_type": "image/png"}
    job.update(over)
    return job


@pytest.mark.slow
def test_burst_coalesces_and_matches_solo(registry):
    """Three compatible jobs coalesce onto one program; each job's image
    agrees with its solo run (same seed) to uint8 quantization."""
    pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 4, "model": 2}))
    slot = pool.slots[0]
    jobs = [_job(0), _job(1), _job(2)]
    results = synchronous_do_work_batch(jobs, slot, registry)
    assert [r["id"] for r in results] == ["j0", "j1", "j2"]
    for r in results:
        assert "fatal_error" not in r
        assert r["pipeline_config"]["coalesced"] == 3
        assert r["pipeline_config"]["seed"] in (100, 101, 102)

    import base64
    import io

    from PIL import Image

    solo = synchronous_do_work(_job(1), slot, registry)
    solo_img = np.asarray(Image.open(io.BytesIO(
        base64.b64decode(solo["artifacts"]["primary"]["blob"]))))
    co_img = np.asarray(Image.open(io.BytesIO(
        base64.b64decode(results[1]["artifacts"]["primary"]["blob"]))))
    diff = np.abs(co_img.astype(int) - solo_img.astype(int))
    # different compiled batch shapes: agreement to quantization, not bits
    assert diff.max() <= 3 and (diff <= 1).mean() > 0.99, (
        diff.max(), (diff <= 1).mean())


@pytest.mark.slow
def test_incompatible_jobs_run_separately(registry):
    """A burst with mixed static params: the two compatible jobs coalesce,
    the odd one (different steps) runs alone; all ids come back."""
    pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 4, "model": 2}))
    slot = pool.slots[0]
    jobs = [_job(0), _job(1, num_inference_steps=3), _job(2)]
    results = synchronous_do_work_batch(jobs, slot, registry)
    by_id = {r["id"]: r for r in results}
    assert set(by_id) == {"j0", "j1", "j2"}
    assert by_id["j0"]["pipeline_config"]["coalesced"] == 2
    assert by_id["j2"]["pipeline_config"]["coalesced"] == 2
    assert "coalesced" not in by_id["j1"]["pipeline_config"]


@pytest.mark.slow
def test_mixed_mode_jobs_do_not_coalesce_with_each_other(registry):
    """txt2img and img2img in one burst: modes must not merge (different
    compiled programs) — each runs its own path."""
    rng = np.random.default_rng(0)
    init = rng.integers(0, 255, (64, 64, 3), dtype=np.uint8)
    pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 4, "model": 2}))
    jobs = [_job(0), _job(1, image=init, strength=0.6)]
    results = synchronous_do_work_batch(jobs, pool.slots[0], registry)
    by_id = {r["id"]: r for r in results}
    assert "coalesced" not in by_id["j0"]["pipeline_config"]
    assert "coalesced" not in by_id["j1"]["pipeline_config"]
    assert by_id["j1"]["pipeline_config"]["mode"] == "img2img"


def _round_trip_image(result) -> np.ndarray:
    import base64
    import io

    from PIL import Image

    return np.asarray(Image.open(io.BytesIO(
        base64.b64decode(result["artifacts"]["primary"]["blob"]))))


@pytest.mark.slow
def test_img2img_jobs_coalesce_and_match_solo(registry):
    """VERDICT r4 #2: image-conditioned 512px-class jobs join the burst —
    per-job init stacks + per-job VAE-encode seeds keep every job's
    images equal to its solo run (to uint8 quantization across batch
    shapes)."""
    rng = np.random.default_rng(1)
    inits = [rng.integers(0, 255, (64, 64, 3), dtype=np.uint8)
             for _ in range(3)]
    pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 4, "model": 2}))
    slot = pool.slots[0]
    jobs = [_job(i, image=inits[i], strength=0.6) for i in range(3)]
    results = synchronous_do_work_batch(jobs, slot, registry)
    by_id = {r["id"]: r for r in results}
    for r in results:
        assert "fatal_error" not in r, r
        assert r["pipeline_config"]["coalesced"] == 3
        assert r["pipeline_config"]["mode"] == "img2img"

    solo = synchronous_do_work(_job(1, image=inits[1], strength=0.6),
                               slot, registry)
    assert solo["pipeline_config"]["mode"] == "img2img"
    diff = np.abs(_round_trip_image(by_id["j1"]).astype(int)
                  - _round_trip_image(solo).astype(int))
    assert diff.max() <= 3 and (diff <= 1).mean() > 0.99, (
        diff.max(), (diff <= 1).mean())


@pytest.mark.slow
def test_inpaint_jobs_coalesce_with_distinct_masks(registry):
    """Inpaint jobs with DIFFERENT masks ride one program: the mask is a
    per-row stack; each job's kept region comes from its own source."""
    rng = np.random.default_rng(2)
    inits = [rng.integers(0, 255, (64, 64, 3), dtype=np.uint8)
             for _ in range(2)]
    masks = [np.zeros((64, 64), np.float32), np.zeros((64, 64), np.float32)]
    masks[0][:32] = 1.0          # regenerate top half
    masks[1][:, 32:] = 1.0       # regenerate right half
    pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 4, "model": 2}))
    slot = pool.slots[0]
    jobs = [_job(i, image=inits[i], mask_image=masks[i], strength=0.8)
            for i in range(2)]
    results = synchronous_do_work_batch(jobs, slot, registry)
    by_id = {r["id"]: r for r in results}
    for r in results:
        assert "fatal_error" not in r, r
        assert r["pipeline_config"]["coalesced"] == 2
        assert r["pipeline_config"]["mode"] == "inpaint"

    solo = synchronous_do_work(
        _job(1, image=inits[1], mask_image=masks[1], strength=0.8),
        slot, registry)
    diff = np.abs(_round_trip_image(by_id["j1"]).astype(int)
                  - _round_trip_image(solo).astype(int))
    assert diff.max() <= 3 and (diff <= 1).mean() > 0.99, (
        diff.max(), (diff <= 1).mean())


@pytest.mark.slow
def test_burst_with_formatting_error_still_returns_all(registry):
    jobs = [_job(0), _job(1, height=9999, width=9999), _job(2)]
    pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 4, "model": 2}))
    results = synchronous_do_work_batch(jobs, pool.slots[0], registry)
    by_id = {r["id"]: r for r in results}
    assert set(by_id) == {"j0", "j1", "j2"}
    assert by_id["j1"]["fatal_error"] is True
    assert by_id["j0"]["pipeline_config"]["coalesced"] == 2


@pytest.mark.slow
def test_worker_coalesces_queue_burst(registry):
    """Full worker loop on a dp=4 mesh slot: a burst of four compatible
    jobs arrives in one poll; the slot merges them into one program
    (every result reports coalesced=4)."""
    import asyncio
    import sys

    sys.path.insert(0, "tests")
    from fake_hive import FakeHive

    from chiaswarm_tpu.node.settings import Settings
    from chiaswarm_tpu.node.worker import Worker

    async def main():
        hive = FakeHive()
        await hive.start()
        for i in range(4):
            hive.jobs.append(_job(i))
        pool = ChipPool(n_slots=1,
                        mesh_spec=MeshSpec({"data": 4, "model": 2}))
        assert pool.slots[0].mesh.devices.size == 8
        worker = Worker(
            settings=Settings(hive_uri=hive.uri, hive_token="t",
                              worker_name="coalesce-test"),
            registry=registry, pool=pool)
        assert worker.work_queue.maxsize == 4  # data-axis capacity
        task = asyncio.create_task(worker.run())
        await hive.wait_for_results(4, timeout=300)
        worker.request_stop()
        try:
            await asyncio.wait_for(task, timeout=20)
        except asyncio.TimeoutError:
            task.cancel()
        await hive.stop()
        assert sorted(r["id"] for r in hive.results) == \
            ["j0", "j1", "j2", "j3"]
        merged = [r["pipeline_config"].get("coalesced")
                  for r in hive.results]
        # the poll delivers all four before the slot picks them up, so at
        # least some (normally all) coalesce; none may fail
        assert all(r["pipeline_config"].get("error") is None
                   for r in hive.results)
        assert any(m and m >= 2 for m in merged), merged

    asyncio.run(main())


def test_burst_key_prefilter(monkeypatch):
    """The worker's raw-job drain filter: txt2img/img2img/inpaint jobs
    with identical static fields share a burst key; modes never mix;
    cascade/controlnet/upscale/pix2pix stay per-job. Runs with lanes
    opted OUT — the strict per-field key is the pre-lane burst-path
    contract that CHIASWARM_STEPPER=0 must restore
    (test_stepper.py::test_burst_key_relaxes_only_with_stepper covers
    the lanes-on relaxation)."""
    from chiaswarm_tpu.node.worker import _burst_key

    monkeypatch.setenv("CHIASWARM_STEPPER", "0")
    a = _job(0)
    b = _job(1)
    assert _burst_key(a) is not None
    assert _burst_key(a) == _burst_key(b)
    assert _burst_key(_job(2, num_inference_steps=9)) != _burst_key(a)
    assert _burst_key(_job(3, workflow="txt2vid")) is None
    assert _burst_key(_job(5, model_name="DeepFloyd/IF-I-XL-v1.0")) is None
    assert _burst_key(
        _job(6, parameters={"controlnet": {"type": "canny"}})) is None
    assert _burst_key(_job(7, parameters={"upscale": True})) is None
    # img2img joins the drain (VERDICT r4 #2) but never mixes with
    # txt2img, other strengths, or inpaint
    i1 = _burst_key(_job(8, start_image_uri="http://x/i.png",
                         strength=0.6))
    i2 = _burst_key(_job(9, start_image_uri="http://x/other.png",
                         strength=0.6))
    assert i1 is not None and i1 == i2
    assert i1 != _burst_key(a)
    assert i1 != _burst_key(_job(10, start_image_uri="http://x/i.png",
                                 strength=0.9))
    assert i1 != _burst_key(_job(11, start_image_uri="http://x/i.png",
                                 mask_image_uri="http://x/m.png",
                                 strength=0.6))
    assert _burst_key(_job(12, model_name="timbrooks/instruct-pix2pix",
                           start_image_uri="http://x/i.png")) is None


def test_row_chunks_bounds_total_batch_rows():
    """num_images_per_prompt multiplies rows: 4 jobs x 8 images must NOT
    merge into one batch-32 program on a dp=4 slot (that is data_width
    times the per-device memory of any solo run); batch=1 jobs still
    coalesce up to data_width."""
    from chiaswarm_tpu.node.executor import _row_chunks

    def item(i, n):
        return (i, f"j{i}", "image/png", {"num_images_per_prompt": n})

    big = [item(i, 8) for i in range(4)]
    assert [len(c) for c in _row_chunks(big, 4)] == [1, 1, 1, 1]

    small = [item(i, 1) for i in range(4)]
    assert [len(c) for c in _row_chunks(small, 4)] == [4]

    # two n=2 jobs fit in one dp=4 program (4 rows); a third would not
    pairs = [item(i, 2) for i in range(3)]
    assert [len(c) for c in _row_chunks(pairs, 4)] == [2, 1]


def test_oversized_rows_run_per_job_not_batched(registry):
    """The per-device row budget guards the batch: 1024px-class jobs
    (single_chip_rows == 1) never merge past one solo footprint per
    device — pinned at the chunking layer, where the size class is the
    only input that matters. 512px-class jobs (budget 4/device) DO merge
    the same row counts (the r4 measured policy), covered end-to-end by
    test_single_chip_slot_batches_small_jobs."""
    from chiaswarm_tpu.node.executor import _row_chunks

    def item(i, n, size):
        return (i, f"j{i}", "image/png",
                {"num_images_per_prompt": n, "height": size, "width": size})

    big = [item(i, 4, 1024) for i in range(2)]
    assert [len(c) for c in _row_chunks(big, 4)] == [1, 1]
    small = [item(i, 4, 512) for i in range(2)]
    assert [len(c) for c in _row_chunks(small, 4)] == [2]
    # the budget is max(solo footprint, profitable batch), NOT their
    # product: a multi-image 512px job never multiplies into 4x its own
    # solo per-device memory
    multi = [item(i, 16, 512) for i in range(2)]
    assert [len(c) for c in _row_chunks(multi, 4)] == [1, 1]


@pytest.mark.slow
def test_oversized_rows_fall_back_per_job_e2e(registry):
    """End to end through synchronous_do_work_batch: jobs whose combined
    rows exceed the per-device budget run the per-job path — correct
    results, no 'coalesced' marker (the non-merging direction of the
    batching policy, e2e like its merging twin)."""
    pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 4, "model": 2}))
    jobs = [_job(0, num_images_per_prompt=16),
            _job(1, num_images_per_prompt=16)]
    results = synchronous_do_work_batch(jobs, pool.slots[0], registry)
    by_id = {r["id"]: r for r in results}
    assert set(by_id) == {"j0", "j1"}
    for r in results:
        assert "coalesced" not in r["pipeline_config"]
        assert r["pipeline_config"].get("error") is None


def test_mismatched_job_keeps_fifo_position(monkeypatch):
    """The drain holds a non-matching candidate as the NEXT burst instead
    of re-queueing it at the tail (ADVICE r2): with queue
    [A, B, A2, A3] the mismatch B must execute before A2/A3 — the old
    tail re-queue ran [A, A2?]... and pushed B behind later arrivals.
    Lanes opted out: with the ISSUE-7 relaxed key the whole queue would
    drain as ONE burst and there would be no mismatch to hold."""
    import asyncio

    from chiaswarm_tpu.node import worker as worker_mod
    from chiaswarm_tpu.node.settings import Settings
    from chiaswarm_tpu.node.worker import Worker

    monkeypatch.setenv("CHIASWARM_STEPPER", "0")

    class StubSlot:
        depth = 1          # serialize bursts so order is deterministic
        data_width = 4

        def descriptor(self):
            return "stub"

    class StubPool(list):
        pass

    bursts: list[list[str]] = []

    async def fake_do_work(job, slot, registry):
        bursts.append([job["id"]])
        return {"id": job["id"], "artifacts": {}, "pipeline_config": {}}

    async def fake_do_work_batch(jobs, slot, registry):
        bursts.append([j["id"] for j in jobs])
        return [{"id": j["id"], "artifacts": {}, "pipeline_config": {}}
                for j in jobs]

    monkeypatch.setattr(worker_mod, "do_work", fake_do_work)
    monkeypatch.setattr(worker_mod, "do_work_batch", fake_do_work_batch)

    async def main():
        pool = StubPool([StubSlot()])
        worker = Worker(
            settings=Settings(hive_uri="http://unused", hive_token="t",
                              worker_name="fifo-test"),
            registry=object(), pool=pool, hive=object())
        jobs = [_job(0), _job(1, num_inference_steps=3),
                _job(2), _job(3)]
        for job in jobs:
            worker.work_queue.put_nowait(job)
        task = asyncio.create_task(worker._slot_worker(pool[0]))
        await asyncio.wait_for(worker.work_queue.join(), timeout=30)
        task.cancel()
        await asyncio.gather(task, return_exceptions=True)

    asyncio.run(main())
    flat = [i for burst in bursts for i in burst]
    # j1 (the mismatch) runs immediately after the burst that found it,
    # NOT behind j2/j3
    assert flat == ["j0", "j1", "j2", "j3"], bursts
    assert bursts[1] == ["j1"], bursts
    # the compatible tail pair still coalesces after the held job ran
    assert ["j2", "j3"] in bursts, bursts


def test_multislot_pool_coalesces_with_fairness_reserve(monkeypatch):
    """VERDICT r2 weak #7: coalescing must also fire on multi-slot pools.
    Two dp=4 slots, four compatible jobs queued while BOTH slots wait:
    the first slot's drain leaves the fairness reserve (one job for the
    hungry neighbor) instead of stripping the whole queue — so the burst
    coalesces AND the second slot still gets work."""
    import asyncio

    from chiaswarm_tpu.node import worker as worker_mod
    from chiaswarm_tpu.node.settings import Settings
    from chiaswarm_tpu.node.worker import Worker

    class StubSlot:
        depth = 1
        data_width = 4

        def __init__(self, name):
            self.name = name

        def descriptor(self):
            return self.name

    bursts: list[tuple[str, list[str]]] = []

    async def fake_do_work(job, slot, registry):
        bursts.append((slot.name, [job["id"]]))
        return {"id": job["id"], "artifacts": {}, "pipeline_config": {}}

    async def fake_do_work_batch(jobs, slot, registry):
        bursts.append((slot.name, [j["id"] for j in jobs]))
        return [{"id": j["id"], "artifacts": {}, "pipeline_config": {}}
                for j in jobs]

    monkeypatch.setattr(worker_mod, "do_work", fake_do_work)
    monkeypatch.setattr(worker_mod, "do_work_batch", fake_do_work_batch)

    async def main():
        pool = [StubSlot("s0"), StubSlot("s1")]
        worker = Worker(
            settings=Settings(hive_uri="http://unused", hive_token="t",
                              worker_name="multislot-test"),
            registry=object(), pool=pool, hive=object())
        tasks = [asyncio.create_task(worker._slot_worker(s)) for s in pool]
        for _ in range(5):  # let both slots block on work_queue.get()
            await asyncio.sleep(0)
        assert worker._hungry_slots == 2
        for i in range(4):
            worker.work_queue.put_nowait(_job(i))
        await asyncio.wait_for(worker.work_queue.join(), timeout=30)
        for task in tasks:
            task.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)

    asyncio.run(main())
    ran = sorted(i for _, burst in bursts for i in burst)
    assert ran == ["j0", "j1", "j2", "j3"], bursts
    sizes = sorted(len(burst) for _, burst in bursts)
    # coalescing fired on a multi-slot pool...
    assert sizes[-1] >= 2, bursts
    # ...but no slot drained everything: both slots executed work
    assert len({name for name, _ in bursts}) == 2, bursts


@pytest.mark.slow
def test_coalesced_default_content_type_is_png(registry):
    """Solo-equivalence of encoding: a job without content_type must come
    back PNG from the coalesced path (the solo callback's default), not
    the executor's jpeg error default."""
    import base64

    pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 4, "model": 2}))
    jobs = []
    for i in range(2):
        job = _job(i)
        job.pop("content_type")
        jobs.append(job)
    results = synchronous_do_work_batch(jobs, pool.slots[0], registry)
    for r in results:
        assert r["pipeline_config"]["coalesced"] == 2
        assert r["artifacts"]["primary"]["content_type"] == "image/png"
        raw = base64.b64decode(r["artifacts"]["primary"]["blob"])
        assert raw.startswith(b"\x89PNG")
        # per-job throughput keeps solo semantics; program total reported
        # separately
        cfg = r["pipeline_config"]
        assert cfg["batch_images_per_sec"] >= cfg["images_per_sec"]


@pytest.mark.slow
def test_single_chip_slot_batches_small_jobs(registry):
    """A data_width=1 slot merges 512px-class jobs into one batched
    program — one chip is not saturated by them at batch 1 (+20%
    images/sec measured at batch 4 on the real chip, BASELINE.md r4).
    1024px-class jobs stay one row per device (saturated at batch 1)."""
    from chiaswarm_tpu.node.executor import single_chip_rows

    assert single_chip_rows({"height": 512, "width": 512}) == 4
    assert single_chip_rows({"height": 64, "width": 64}) == 4
    assert single_chip_rows({"height": 1024, "width": 1024}) == 1
    assert single_chip_rows({"height": None, "width": None}) == 1

    import jax

    pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 1}),
                    devices=jax.devices()[:1])
    assert pool.slots[0].data_width == 1
    jobs = [_job(i) for i in range(4)]
    results = synchronous_do_work_batch(jobs, pool.slots[0], registry)
    assert len(results) == 4
    assert all(r["pipeline_config"].get("error") is None for r in results)
    merged = [r["pipeline_config"].get("coalesced") for r in results]
    assert merged == [4, 4, 4, 4], merged


def test_coalesce_key_splits_mismatched_image_and_mask_grids():
    """The executor's grouping key must carry the fetched image AND mask
    shapes: free-form mask sizes are valid solo (the pipeline resizes),
    so keying on presence alone would group unstackable per-job masks
    and silently demote the burst to per-job execution."""
    from chiaswarm_tpu.node.executor import _coalesce_key

    img64 = np.zeros((64, 64, 3), np.uint8)
    img96 = np.zeros((96, 64, 3), np.uint8)
    m64 = np.zeros((64, 64), np.float32)
    m32 = np.zeros((32, 32), np.float32)
    base = {"model_name": "tiny", "num_inference_steps": 2,
            "strength": 0.6}
    k_a = _coalesce_key({**base, "image": img64, "mask_image": m64})
    k_b = _coalesce_key({**base, "image": img64, "mask_image": m64})
    assert k_a == k_b
    # different mask grid -> different group
    assert k_a != _coalesce_key({**base, "image": img64,
                                 "mask_image": m32})
    # different image grid -> different group
    assert k_a != _coalesce_key({**base, "image": img96,
                                 "mask_image": m64})
    # img2img vs inpaint -> different group
    assert k_a != _coalesce_key({**base, "image": img64})
    # strength is a static (schedule start index) -> different group
    assert _coalesce_key({**base, "image": img64}) != _coalesce_key(
        {**base, "image": img64, "strength": 0.9})
