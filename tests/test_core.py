import jax
import numpy as np
import pytest

from chiaswarm_tpu.core.chip_pool import ChipPool, SlotBusy
from chiaswarm_tpu.core.compile_cache import (
    LruCache,
    bucket_batch,
    bucket_image_size,
)
from chiaswarm_tpu.core.mesh import MeshSpec, build_mesh
from chiaswarm_tpu.core.rng import draw_seed, key_for_seed, per_sample_keys


def test_mesh_auto_factorization():
    mesh = build_mesh(MeshSpec({"data": -1}))
    assert mesh.devices.size == 8
    assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
        "data": 8, "model": 1, "seq": 1,
    }


def test_mesh_explicit_shape(mesh8):
    assert dict(zip(mesh8.axis_names, mesh8.devices.shape)) == {
        "data": 4, "model": 2, "seq": 1,
    }


def test_mesh_bad_shape_raises():
    with pytest.raises(ValueError):
        build_mesh(MeshSpec({"data": 3}))
    with pytest.raises(ValueError):
        build_mesh(MeshSpec({"data": -1, "model": -1}))


def test_derive_mesh_spec_policy():
    """Default dp x tp policy: tp engages exactly when the heaviest
    family's params exceed the per-chip budget; everything else is dp."""
    from chiaswarm_tpu.core.mesh import derive_mesh_spec

    gib = 1024**3
    # single chip: trivially dp=1
    assert derive_mesh_spec(1, 100 * gib).shape == {"data": 1}
    # small model on 8 chips: dp-only
    assert derive_mesh_spec(8, 2 * gib, hbm_bytes=16 * gib).shape == \
        {"data": 8, "model": 1}
    # SDXL-class (~7 GB bf16) exceeds 0.35 * 16 GiB -> tp=2
    assert derive_mesh_spec(8, 7 * gib, hbm_bytes=16 * gib).shape == \
        {"data": 4, "model": 2}
    # bigger model: tp grows until the shard fits (20/4 = 5 GiB < budget)
    assert derive_mesh_spec(8, 20 * gib, hbm_bytes=16 * gib).shape == \
        {"data": 2, "model": 4}
    # enormous model: tp absorbs every chip before giving up
    assert derive_mesh_spec(8, 30 * gib, hbm_bytes=16 * gib).shape == \
        {"data": 1, "model": 8}
    # unknown catalog: stay dp-only
    assert derive_mesh_spec(8, None, hbm_bytes=16 * gib).shape == \
        {"data": 8, "model": 1}
    # odd device counts cannot split: dp-only even for big models
    assert derive_mesh_spec(3, 30 * gib, hbm_bytes=16 * gib).shape == \
        {"data": 3, "model": 1}
    # latency mode: leftover chips ride ``seq`` (ring attention) not dp
    assert derive_mesh_spec(8, 2 * gib, hbm_bytes=16 * gib,
                            latency=True).shape == \
        {"data": 1, "model": 1, "seq": 8}
    assert derive_mesh_spec(8, 7 * gib, hbm_bytes=16 * gib,
                            latency=True).shape == \
        {"data": 1, "model": 2, "seq": 4}
    # latency mode on one chip degenerates to the single-chip mesh
    assert derive_mesh_spec(1, 7 * gib, latency=True).shape == {"data": 1}
    # non-pow2 remainder: seq takes only the pow2 factor (it must divide
    # the pow2 spatial token counts or ring attention never engages);
    # the rest returns to data
    assert derive_mesh_spec(6, 2 * gib, hbm_bytes=16 * gib,
                            latency=True).shape == \
        {"data": 3, "model": 1, "seq": 2}
    assert derive_mesh_spec(3, 2 * gib, hbm_bytes=16 * gib,
                            latency=True).shape == \
        {"data": 3, "model": 1}


def test_split_mesh_partitions_devices():
    """split_mesh: contiguous, disjoint, covering data-axis submeshes —
    the substrate for the cascade's stage-level pipeline parallelism."""
    import jax
    import pytest

    from chiaswarm_tpu.core.mesh import MeshSpec, build_mesh, split_mesh

    mesh = build_mesh(MeshSpec({"data": -1}))
    halves = split_mesh(mesh, 2)
    assert len(halves) == 2
    seen = []
    for sub in halves:
        assert dict(sub.shape)["data"] == len(jax.devices()) // 2
        seen += sub.devices.flatten().tolist()
    assert seen == mesh.devices.flatten().tolist()  # disjoint AND ordered
    with pytest.raises(ValueError):
        split_mesh(mesh, 3)  # 8 devices do not split three ways


@pytest.mark.slow
def test_worker_default_pool_derives_tp_for_big_families(monkeypatch):
    """A stock 8-device worker with an SDXL-class catalog builds a
    dp=4 x tp=2 slot WITHOUT any hand-written mesh_shape; a small-model
    catalog stays dp=8 (VERDICT r2: the Megatron layer must not sit idle
    behind operator configuration)."""
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.node.settings import Settings
    from chiaswarm_tpu.node.worker import Worker

    # estimate_family_bytes traces full SDXL abstractly (seconds); pin the
    # HBM budget so the test is deterministic across backends
    from chiaswarm_tpu.core import mesh as mesh_mod

    monkeypatch.setattr(mesh_mod, "device_hbm_bytes",
                        lambda device=None: 16 * 1024**3)

    sdxl_reg = ModelRegistry(
        catalog=[{"name": "stabilityai/stable-diffusion-xl-base-1.0",
                  "family": "sdxl", "parameters": {}}],
        allow_random=True)
    worker = Worker(settings=Settings(hive_uri="http://x", hive_token="t"),
                    registry=sdxl_reg)
    shape = worker.pool.slots[0].descriptor()["mesh_shape"]
    assert shape == {"data": 4, "model": 2, "seq": 1}

    tiny_reg = ModelRegistry(
        catalog=[{"name": "tiny", "family": "tiny", "parameters": {}}],
        allow_random=True)
    worker2 = Worker(settings=Settings(hive_uri="http://x", hive_token="t"),
                     registry=tiny_reg)
    shape2 = worker2.pool.slots[0].descriptor()["mesh_shape"]
    assert shape2 == {"data": 8, "model": 1, "seq": 1}

    # latency_mode flips the leftover chips onto the ring-attention axis
    worker3 = Worker(settings=Settings(hive_uri="http://x", hive_token="t",
                                       latency_mode=True),
                     registry=tiny_reg)
    shape3 = worker3.pool.slots[0].descriptor()["mesh_shape"]
    assert shape3 == {"data": 1, "model": 1, "seq": 8}


def test_chip_pool_slots_and_seed_recording():
    pool = ChipPool(n_slots=4)
    assert len(pool) == 4
    slot = pool.slots[0]
    assert slot.descriptor()["chips"] == 2

    def callback(s, model_name, seed=None, **kw):
        assert model_name == "m"
        assert isinstance(seed, int)
        return {"ok": True}, {"model": model_name}

    artifacts, config = slot(callback, model_name="m")
    assert artifacts == {"ok": True}
    assert isinstance(config["seed"], int)

    _, config2 = slot(callback, model_name="m", seed=123)
    assert config2["seed"] == 123


def test_chip_pool_busy_raises_past_pipeline_depth():
    """Depth-1 slot == the reference's hard mutex; the default depth-2
    slot admits ONE extra in-flight job, then raises."""
    slot1 = ChipPool(n_slots=1, depth=1).slots[0]

    def reentrant(s, model_name, seed=None, **kw):
        with pytest.raises(SlotBusy):
            slot1(lambda *a, **k: ({}, {}))
        return {}, {}

    slot1(reentrant, model_name=None)

    slot2 = ChipPool(n_slots=1, depth=2).slots[0]

    def two_deep(s, model_name, seed=None, **kw):
        def inner(s2, model_name2, seed=None, **kw2):
            with pytest.raises(SlotBusy):  # third concurrent job: full
                slot2(lambda *a, **k: ({}, {}))
            return {}, {}

        slot2(inner, model_name=None)  # second concurrent job: admitted
        return {}, {}

    slot2(two_deep, model_name=None)


def test_rng_determinism():
    k1 = key_for_seed(42)
    k2 = key_for_seed(42)
    assert (jax.random.normal(k1, (4,)) == jax.random.normal(k2, (4,))).all()
    seeds = {draw_seed() for _ in range(8)}
    assert len(seeds) == 8
    keys = per_sample_keys(7, 3)
    assert keys.shape[0] == 3
    assert np.array_equal(np.asarray(keys[1]), np.asarray(key_for_seed(8)))


def test_bucketing():
    assert bucket_batch(1) == 1
    assert bucket_batch(3) == 4
    assert bucket_image_size(512, 512) == (512, 512)
    assert bucket_image_size(500, 700) == (512, 704)
    # small sizes are honored (reference has only a MAX clamp,
    # job_arguments.py:96-102); quantized up to the 64 lattice
    assert bucket_image_size(70, 60) == (128, 64)
    assert bucket_image_size(192, 192) == (192, 192)
    assert bucket_image_size(4000, 100) == (1024, 128)


def test_lru_cache_eviction_and_stats():
    cache = LruCache(max_items=2)
    cache.get_or_create("a", lambda: 1)
    cache.get_or_create("b", lambda: 2)
    cache.get_or_create("a", lambda: -1)  # hit, refreshes
    cache.get_or_create("c", lambda: 3)   # evicts b
    assert cache.get_or_create("a", lambda: -1) == 1
    assert cache.get_or_create("b", lambda: 99) == 99  # was evicted
    assert cache.stats["hits"] == 2

    budget = LruCache(budget_bytes=100)
    budget.get_or_create("x", lambda: "x", size_bytes=60)
    budget.get_or_create("y", lambda: "y", size_bytes=60)  # evicts x
    assert budget.stats["bytes"] == 60


def test_depth2_slot_runs_two_jobs_concurrently():
    """The serving overlap mechanism: two blocking jobs must be able to
    execute on ONE slot at the same time (each waits on a barrier only
    the other can release)."""
    import threading

    slot = ChipPool(n_slots=1, depth=2).slots[0]
    barrier = threading.Barrier(2, timeout=30)
    results = []

    def job(s, model_name, seed=None, **kw):
        barrier.wait()  # deadlocks unless both jobs are in flight
        return {}, {"ok": True}

    def run():
        results.append(slot(job, model_name=None))

    t1 = threading.Thread(target=run)
    t2 = threading.Thread(target=run)
    t1.start(); t2.start()
    t1.join(60); t2.join(60)
    assert len(results) == 2
    assert all(cfg["ok"] for _, cfg in results)
