"""swarmplan (ISSUE 19): the capacity-model-driven fleet autoscaler.

Three tiers:

- **Planning units** (fake clock, no workers): backlog-driven scale-up
  with cooldown and bounds holds, graceful scale-down with the
  fewest-leases drain pick and the draining ledger (one slow drain is
  never re-issued tick after tick), the hysteresis deadband, the
  Δ-arrival estimator that outruns the hive's 30 s EWMA on a fresh
  ramp, and deterministic demand-share placement.
- **Seam units**: the journaled-plan recovery contract (a re-attached
  planner inherits the dead process's cooldown clocks — intent
  survives, actuation does not repeat), the ``GET /api/plan``
  supervisor endpoint (404 without a planner: wire parity), heartbeat
  acks carrying placement hints only when a plan exists, and the
  residency ledger warming hinted models ahead of its local arrival
  ranking.
- **THE acceptance gate** (slow): a seeded diurnal schedule with a
  spike, driven once under the planner and once per static roster in
  the swept set — zero loss, contention-adjusted admitted p99 within
  deadline, at least one scale-up AND one scale-down actuated, and
  planner worker-hours strictly below the cheapest feasible static
  roster. Plus the nightly federated soak: same elastic fleet over 3
  journaled shards with a seeded mid-run shard SIGKILL/recovery
  (CHIASWARM_SOAK_SEED replays a CI run exactly).

Everything is hermetic (loopback only) and scripted/seeded.
"""

from __future__ import annotations

import asyncio
import os
import time

import pytest

from chiaswarm_tpu.node.hivelog import HiveJournal
from chiaswarm_tpu.node.minihive import MiniHive
from chiaswarm_tpu.node.planner import (
    PLAN_FLIGHT_ID,
    FleetPlanner,
    PlannerConfig,
)


@pytest.fixture(autouse=True)
def _tmp_root(tmp_path, monkeypatch):
    monkeypatch.setenv("SWARM_TPU_ROOT", str(tmp_path))
    return tmp_path


def _job(job_id: str, model: str = "shared/tiny", **over):
    job = {"id": job_id, "model_name": model, "prompt": f"p {job_id}",
           "num_inference_steps": 2, "height": 64, "width": 64,
           "content_type": "application/json"}
    job.update(over)
    return job


def _seed_worker(hive: MiniHive, name: str, now: float,
                 **metrics) -> None:
    """Make ``name`` a live fleet member without a real worker: a
    heartbeat's two side effects (liveness stamp + metric snapshot)."""
    hive.known_workers.add(name)
    hive.worker_seen[name] = now
    hive.fleet[name] = {"at": now,
                        "metrics": dict({"chips_in_service": 1},
                                        **metrics)}


def _cfg(**over) -> PlannerConfig:
    base = dict(min_workers=1, max_workers=3, target_utilization=1.0,
                smoothing_window_s=0.01, hysteresis=0.0,
                cooldown_up_s=5.0, cooldown_down_s=5.0,
                backlog_drain_s=1.0, capacity_jobs_s_per_worker=2.0)
    base.update(over)
    return PlannerConfig(**base)


# ---------------------------------------------------------------------------
# planning units (fake clock)
# ---------------------------------------------------------------------------


def test_tick_scales_up_on_backlog_then_cooldown_then_bounds():
    clock = [0.0]
    hive = MiniHive(lease_s=10.0, delay_s=0.0, clock=lambda: clock[0])
    planner = FleetPlanner(hive, _cfg(), clock=lambda: clock[0])
    assert hive.planner is planner  # attach publishes /api/plan
    _seed_worker(hive, "w0", 0.0)
    for i in range(6):
        hive.submit(_job(f"p{i}", model="m/hot"))

    clock[0] = 1.0
    decision = planner.tick()
    # 6 queued jobs / 1 s drain horizon >> the warming arrival EWMA:
    # the backlog term is what makes the spike visible this early
    assert decision["direction"] == "up"
    assert decision["reason"] == "backlog"
    assert decision["target"] == 3 and decision["actual"] == 1
    assert decision["spawn"] == 2 and decision["drain"] == []
    # the sole observed model homes on the sole survivor
    assert decision["placement"]["w0"] == ["m/hot"]
    assert planner.placement_for("w0") == ("m/hot",)
    assert planner.placement_for("missing") == ()
    # an actuating decision is journaled: last_plan + the flight note
    # on the fleet-planner pseudo record
    assert hive.last_plan == decision
    record = hive.flights.get(PLAN_FLIGHT_ID)
    assert [e["event"] for e in record["events"]].count("plan") == 1

    # inside the up cooldown the same pressure holds, explicitly
    clock[0] = 1.5
    held = planner.tick()
    assert held["direction"] == "hold" and held["reason"] == "cooldown"
    assert held["spawn"] == 0

    # cooldown over, fleet at max, demand still wants more: a BOUNDS
    # hold (operator alert), not a steady one
    clock[0] = 10.0
    for name in ("w1", "w2"):
        _seed_worker(hive, name, 10.0)
    hive.submit(_job("p6", model="m/hot"))
    hive.submit(_job("p7", model="m/hot"))
    bounded = planner.tick()
    assert bounded["direction"] == "hold"
    assert bounded["reason"] == "bounds"
    assert bounded["target"] == 3 and bounded["actual"] == 3


def test_tick_scales_down_via_drain_pick_and_draining_ledger():
    clock = [100.0]
    hive = MiniHive(lease_s=10.0, delay_s=0.0, clock=lambda: clock[0])
    planner = FleetPlanner(
        hive, _cfg(max_workers=5, hysteresis=0.1, cooldown_down_s=5.0),
        clock=lambda: clock[0])
    for name in ("wa", "wb", "wc"):
        _seed_worker(hive, name, 100.0)
    hive.submit(_job("d1"))
    [handed] = hive._take_jobs("wc")  # wc holds the only lease
    assert handed["id"] == "d1"

    clock[0] = 101.0
    decision = planner.tick()
    # no demand, no backlog -> min_workers; the TWO surplus workers
    # drain in one decision, fewest leases first (cheapest preemption),
    # name tie-break — never the lease holder
    assert decision["direction"] == "down"
    assert decision["reason"] == "demand"
    assert decision["target"] == 1 and decision["actual"] == 3
    assert decision["drain"] == ["wa", "wb"]
    assert hive.last_plan["direction"] == "down"

    # next tick: the victims are still heartbeating (a drain takes a
    # while) but the ledger excludes them — actual already reads 1 and
    # the drain is NOT re-issued
    clock[0] = 101.4
    held = planner.tick()
    assert held["direction"] == "hold"
    assert held["actual"] == 1 and held["drain"] == []
    assert set(planner._draining) == {"wa", "wb"}

    # a victim that actually left (stopped heartbeating) clears its
    # ledger entry; the still-draining one stays excluded
    del hive.worker_seen["wa"]
    clock[0] = 102.0
    planner.tick()
    assert set(planner._draining) == {"wb"}

    # one stuck past the 60 s grace window re-enters the live view and
    # is re-decided (the cooldown long expired; both survivors are
    # still heartbeating)
    clock[0] = 162.0
    _seed_worker(hive, "wb", 162.0)
    _seed_worker(hive, "wc", 162.0)
    redecided = planner.tick()
    assert redecided["direction"] == "down"
    assert redecided["drain"] == ["wb"]


def test_hysteresis_deadband_and_delta_arrival_estimator():
    clock = [0.0]
    hive = MiniHive(lease_s=10.0, delay_s=0.0, clock=lambda: clock[0])
    planner = FleetPlanner(hive, _cfg(max_workers=2, hysteresis=0.6),
                           clock=lambda: clock[0])
    for name in ("wa", "wb"):
        _seed_worker(hive, name, 0.0)

    # anchor tick under a queued burst: demand wants past the ceiling,
    # the 2-worker fleet is already there -> bounds hold
    clock[0] = 1.0
    for i in range(4):
        hive.submit(_job(f"h{i}"))
    first = planner.tick()
    assert first["direction"] == "hold" and first["reason"] == "bounds"
    hive._take_jobs("wa")  # burst leased away: no backlog term below

    # 4 more submissions over 2 s = 2.0 jobs/s. The hive's own 30 s
    # EWMA has barely warmed (~0.2), so the planner's Δsubmitted/dt
    # estimator must carry the reading...
    clock[0] = 3.0
    for i in range(4, 8):
        hive.submit(_job(f"h{i}"))
    hive._take_jobs("wa")
    second = planner.tick()
    assert second["observed_jobs_s"] >= 1.9, second
    # ...which lands raw demand at ~1 worker: below actual=2 but
    # inside the 0.6 deadband -> hysteresis hold, nothing drains
    assert second["direction"] == "hold"
    assert second["reason"] == "hysteresis"
    assert second["target"] == 1 and second["actual"] == 2
    assert second["drain"] == []


def test_placement_replicates_hot_models_deterministically():
    hive = MiniHive(lease_s=10.0, delay_s=0.0, clock=lambda: 0.0)
    planner = FleetPlanner(hive, PlannerConfig(replicate_max=2))
    rates = {"m/a": 3.0, "m/b": 1.0, "m/c": 0.5}
    plan = planner._plan_placement(rates, ["w1", "w0", "w2"])
    # m/a owns 2/3 of the demand -> 2 homes (replicate_max caps it);
    # every observed model keeps >= 1 home; homes fill least-loaded
    # first with a name tie-break
    assert plan == {"w0": ("m/a", "m/c"), "w1": ("m/a",),
                    "w2": ("m/b",)}
    # deterministic under input-order permutations: recovery replays
    # the exact same plan from the same observations
    shuffled = dict(reversed(list(rates.items())))
    assert planner._plan_placement(shuffled, ["w2", "w1", "w0"]) == plan
    assert planner._plan_placement({}, ["w0"]) == {}
    assert planner._plan_placement(rates, []) == {}


# ---------------------------------------------------------------------------
# seam units: journal recovery, /api/plan, heartbeat hints, residency
# ---------------------------------------------------------------------------


def test_journaled_plan_seeds_reattached_planner_no_double_actuation(
        tmp_path):
    clock = [0.0]
    journal = HiveJournal(tmp_path / "hive", fsync=False)
    hive = MiniHive(lease_s=10.0, delay_s=0.0, journal=journal,
                    clock=lambda: clock[0])
    cfg = _cfg(max_workers=4, cooldown_up_s=30.0, cooldown_down_s=30.0)
    planner = FleetPlanner(hive, cfg, clock=lambda: clock[0])
    _seed_worker(hive, "w0", 0.0)
    for i in range(6):
        hive.submit(_job(f"r{i}", model="m/hot"))
    clock[0] = 1.0
    decision = planner.tick()
    assert decision["direction"] == "up" and decision["spawn"] >= 1

    # crash: the process dies with the scale-up decided but the spawns
    # not yet serving. Recovery replays the plan into last_plan...
    from chiaswarm_tpu.node.minihive import kill_hive

    asyncio.run(kill_hive(hive))
    clock[0] = 2.0
    recovered = MiniHive.recover(
        HiveJournal(tmp_path / "hive", fsync=False),
        lease_s=10.0, delay_s=0.0, clock=lambda: clock[0])
    assert recovered.last_plan is not None
    assert recovered.last_plan["direction"] == "up"
    assert recovered.last_plan["target"] == decision["target"]
    assert recovered.last_plan["at_s"] == decision["at_s"]
    # ...and the replayed flight timeline carries the decision note
    record = recovered.flights.get(PLAN_FLIGHT_ID)
    assert any(e["event"] == "plan" for e in record["events"])

    # a fresh planner attached to the recovered hive treats the dead
    # process's decision as its own recent one: same pressure, but the
    # inherited up-cooldown pins the fleet — no double-actuation
    replanner = FleetPlanner(recovered, cfg, clock=lambda: clock[0])
    assert recovered.planner is replanner
    _seed_worker(recovered, "w0", 2.0)
    clock[0] = 3.0
    after = replanner.tick()
    assert after["direction"] == "hold"
    assert after["reason"] == "cooldown"
    assert after["spawn"] == 0 and after["drain"] == []


def test_api_plan_endpoint_and_heartbeat_placement_ack():
    import aiohttp

    async def scenario():
        hive = MiniHive(lease_s=5.0, delay_s=0.0)
        uri = await hive.start()
        beat = {"worker_name": "hb-w0",
                "metrics": {"chips_in_service": 1}, "jobs": []}
        try:
            async with aiohttp.ClientSession() as session:
                # pre-planner wire parity: /api/plan 404s and the
                # heartbeat ack carries NO placement key at all
                async with session.get(uri + "/api/plan") as resp:
                    assert resp.status == 404
                async with session.post(uri + "/api/heartbeat",
                                        json=beat) as resp:
                    assert resp.status == 200
                    ack = await resp.json()
                assert ack["status"] == "ok"
                assert "placement" not in ack

                planner = FleetPlanner(hive, _cfg())
                hive.submit(_job("plan-1", model="m/hinted"))
                planner.tick()

                async with session.get(uri + "/api/plan") as resp:
                    assert resp.status == 200
                    body = await resp.json()
                assert body["ticks"] == 1
                assert body["config"]["min_workers"] == 1
                assert body["decision"]["target"] >= 1
                assert body["decision"]["placement"]["hb-w0"] == \
                    ["m/hinted"]
                async with session.post(uri + "/api/heartbeat",
                                        json=beat) as resp:
                    ack = await resp.json()
                assert ack["placement"] == ["m/hinted"]
        finally:
            await hive.stop()

    asyncio.run(scenario())


def test_residency_placement_hint_outranks_local_arrival_ewma():
    from chiaswarm_tpu.obs.metrics import Registry
    from chiaswarm_tpu.serving.residency import ResidencyManager

    class FakeModel:
        def __init__(self, nbytes: int) -> None:
            self.nbytes = nbytes

    loads: list[str] = []

    def loader_of(name: str, nbytes: int):
        def load():
            loads.append(name)
            return FakeModel(nbytes)

        return load

    manager = ResidencyManager(
        budget_bytes=1000, hard_limit_bytes=2000,
        metrics_registry=Registry(), persist_path=None,
        reserve_wait_s=0.2)
    size_of = lambda value: value.nbytes  # noqa: E731
    for _ in range(5):  # a is the locally-hot model by arrival EWMA
        manager.acquire("ka", loader_of("a", 400), model="a",
                        size_of=size_of)
    manager.acquire("kb", loader_of("b", 400), model="b",
                    size_of=size_of)
    manager.set_budget(100)
    manager.set_budget(1000)
    assert manager.resident_models() == []

    # the plan says b belongs here: the hint outranks a's hotter EWMA
    manager.note_placement(["b"])
    assert manager.placement_hints == 1
    manager.note_placement(("b",))  # unchanged hint is not re-counted
    assert manager.placement_hints == 1
    assert manager.note_idle()
    deadline = 100
    while "b" not in manager.resident_models() and deadline:
        deadline -= 1
        time.sleep(0.02)
    assert manager.resident_models() == ["b"], loads
    snap = manager.snapshot()
    assert snap["placement"] == ["b"]
    assert snap["placement_hints"] == 1


# ---------------------------------------------------------------------------
# THE acceptance gate (slow): elastic fleet vs the static roster sweep
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_autoscaler_gate_tracks_diurnal_and_beats_static():
    """ISSUE 19 acceptance: a seeded diurnal schedule with a spike,
    driven by ``run_load`` under the planner and under every static
    roster in the swept set. The planner must lose nothing, keep the
    contention-adjusted admitted p99 within deadline, actuate at least
    one scale-up AND one scale-down, and spend STRICTLY fewer
    worker-hours than the best feasible static roster."""
    from chiaswarm_tpu.node.loadgen import (
        AutoscalePlan,
        DiurnalCurve,
        UserPopulation,
        autoscale_comparison,
        generate_schedule,
    )

    seed = "swarmplan"
    population = UserPopulation(n_users=200, seed=f"plan:{seed}")
    curve = DiurnalCurve(amplitude=0.8, spikes=1, spike_mult=2.0,
                         seed=f"plan:{seed}")
    schedule = generate_schedule(population, curve, duration_s=12.0,
                                 rate_jobs_s=90.0, seed=f"plan:{seed}",
                                 id_prefix="plangate")
    plan = AutoscalePlan(min_workers=1, max_workers=5,
                         tick_every_s=0.2,
                         capacity_jobs_s_per_worker=40.0,
                         backlog_drain_s=1.5, cooldown_up_s=0.4,
                         cooldown_down_s=2.0, smoothing_window_s=1.5)
    table = asyncio.run(autoscale_comparison(
        schedule, autoscale=plan, static_rosters=[1, 2, 3, 4, 5],
        seed=seed, settle_timeout_s=180.0))

    planner_row, gate = table["planner"], table["gate"]
    report = table["planner_report"]
    assert planner_row["zero_loss"], report["reconciliation"]
    assert planner_row["p99_ok"], report["admitted_deadline"]
    events = report["autoscale"]["events"]
    assert any(e["direction"] == "up" for e in events), events
    assert any(e["direction"] == "down" for e in events), events
    assert report["worker_time"]["peak_workers"] > plan.min_workers
    # the planner's economics claim, against rosters that actually
    # served the traffic (zero loss, p99 in deadline, shed parity)
    assert gate["feasible_static"], table["static"]
    assert gate["planner_beats_best_static"], {
        "gate": gate, "static": table["static"]}


# ---------------------------------------------------------------------------
# nightly federated soak (slow): elastic fleet + mid-run shard SIGKILL
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_autoscaler_soak_diurnal_with_shard_kill(tmp_path):
    """Nightly seeded soak (replay: ``CHIASWARM_SOAK_SEED=<run id>
    pytest tests/test_planner.py --slow -k soak``): the elastic fleet
    over 3 journaled shards, one seeded shard SIGKILL'd and recovered
    from its own journal mid-run. Zero loss fleet-wide across the
    epoch bump, the planner actuated at least one scale-up, and every
    settled job's stitched flight record verifies clean."""
    from chiaswarm_tpu.node.federation import shard_of
    from chiaswarm_tpu.node.loadgen import (
        AutoscalePlan,
        DiurnalCurve,
        FederatedLoadHive,
        UserPopulation,
        generate_schedule,
        run_load,
    )

    seed = os.environ.get("CHIASWARM_SOAK_SEED", "plan-soak-default")
    n_jobs = int(os.environ.get("CHIASWARM_SOAK_JOBS", "600"))
    duration_s = 10.0
    population = UserPopulation(n_users=300, seed=f"plansoak:{seed}")
    curve = DiurnalCurve(amplitude=0.7, spikes=2, spike_mult=2.0,
                         seed=f"plansoak:{seed}")
    schedule = generate_schedule(
        population, curve, duration_s=duration_s,
        rate_jobs_s=max(10.0, n_jobs / duration_s),
        seed=f"plansoak:{seed}", id_prefix="plansoak")
    hive = FederatedLoadHive(3, journal_root=tmp_path / "fed",
                             journal_fsync=False, lease_s=5.0,
                             delay_s=0.0, max_attempts=6,
                             max_jobs_per_poll=2)
    plan = AutoscalePlan(min_workers=1, max_workers=5,
                         tick_every_s=0.2,
                         capacity_jobs_s_per_worker=40.0,
                         backlog_drain_s=1.5, cooldown_up_s=0.4,
                         cooldown_down_s=2.0, smoothing_window_s=1.5)
    victim_shard = shard_of(str(seed), 3)  # seeded, replayable pick
    kill_at = max(2, len(schedule) // 2)
    state = {"cycled": False}

    async def chaos(done: int, federation) -> None:
        if state["cycled"] or done < kill_at:
            return
        state["cycled"] = True
        await federation.kill_shard(victim_shard)
        await asyncio.sleep(0.3)
        await federation.restart_shard(victim_shard)

    report = asyncio.run(run_load(
        schedule, hive=hive, autoscale=plan, on_submit=chaos,
        seed=f"plansoak-{seed}", settle_timeout_s=600.0))

    assert state["cycled"], "the scripted shard kill never fired"
    rec = report["reconciliation"]
    assert rec["zero_loss"], rec
    events = report["autoscale"]["events"]
    assert any(e["direction"] == "up" for e in events), events
    # the killed shard recovered into a bumped epoch; the others kept
    # their first life
    epochs = hive.stats()["aggregate"]["epochs"]
    assert sorted(epochs) == [1, 1, 2], epochs
    # flight completeness fleet-wide: every settled job's stitched
    # record replays a gapless grant chain and exactly one settle
    settled = [str(item.job["id"]) for item in schedule
               if str(item.job["id"]) in hive.completed]
    assert settled
    assert hive.flights.verify(settled) == []
