"""Latent 2x upscaler: pipeline, family routing, workload integration.

Reference behaviors covered: the post-generation sd-x2-latent-upscaler pass
at 20 steps / guidance 0 (swarm/diffusion/upscale.py:6-32) triggered by the
server's ``upscale`` model parameter (swarm/job_arguments.py:104-110).
"""

import numpy as np
import pytest

from chiaswarm_tpu.models.configs import get_family
from chiaswarm_tpu.pipelines import Components
from chiaswarm_tpu.pipelines.upscale import LatentUpscalePipeline


@pytest.fixture(scope="module")
def tiny_upscaler():
    return LatentUpscalePipeline(Components.random("tiny_up", seed=0))


def test_family_routing():
    assert get_family("stabilityai/sd-x2-latent-upscaler").name == "upscaler_x2"
    assert get_family("stabilityai/sd-x2-latent-upscaler").kind == "upscaler"
    assert get_family("runwayml/stable-diffusion-v1-5").kind == "sd"


def test_upscale_doubles_size(tiny_upscaler):
    rng = np.random.default_rng(3)
    img = rng.integers(0, 255, (1, 64, 64, 3), dtype=np.uint8)
    out, config = tiny_upscaler(img, prompt="sharp photo", steps=3, seed=7)
    assert out.shape == (1, 128, 128, 3)
    assert out.dtype == np.uint8
    assert config["scale"] == 2
    # determinism per seed
    out2, _ = tiny_upscaler(img, prompt="sharp photo", steps=3, seed=7)
    assert np.array_equal(out, out2)


def test_workload_upscale_flag():
    """diffusion_callback with upscale=True emits 2x-size artifacts."""
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.workloads.diffusion import diffusion_callback

    registry = ModelRegistry(catalog=[], allow_random=True)
    artifacts, config = diffusion_callback(
        "slot0", "random/tiny", seed=3, registry=registry,
        prompt="a pier", num_inference_steps=2, height=64, width=64,
        upscale=True, upscaler_model_name="random/tiny_up")
    assert "primary" in artifacts
    assert config["scale"] == 2
    assert config["upscaler"] == "random/tiny_up"
