"""Upscaler pipelines: latent 2x and SD-x4, routing, workload integration.

Reference behaviors covered: the post-generation sd-x2-latent-upscaler pass
at 20 steps / guidance 0 (swarm/diffusion/upscale.py:6-32) triggered by the
server's ``upscale`` model parameter (swarm/job_arguments.py:104-110), and
the IF cascade's SD-x4-upscaler stage 3 model class
(swarm/diffusion/diffusion_func_if.py:31-40).
"""

import numpy as np
import pytest

from chiaswarm_tpu.models.configs import get_family
from chiaswarm_tpu.pipelines import Components
from chiaswarm_tpu.pipelines.upscale import (
    LatentUpscalePipeline,
    Upscale4xPipeline,
)


@pytest.fixture(scope="module")
def tiny_upscaler():
    return LatentUpscalePipeline(Components.random("tiny_up", seed=0))


@pytest.fixture(scope="module")
def tiny_upscaler4():
    return Upscale4xPipeline(Components.random("tiny_up4", seed=0))


def test_family_routing():
    assert get_family("stabilityai/sd-x2-latent-upscaler").name == "upscaler_x2"
    assert get_family("stabilityai/sd-x2-latent-upscaler").kind == "upscaler"
    assert get_family("runwayml/stable-diffusion-v1-5").kind == "sd"


def test_x4_family_routing():
    """The reference's stage-3 checkpoint name routes to the x4 family
    (diffusion_func_if.py:31-40), NOT the generic 'upscale' hint."""
    fam = get_family("stabilityai/stable-diffusion-x4-upscaler")
    assert fam.name == "upscaler_x4"
    assert fam.kind == "upscaler4"
    assert fam.unet.sample_channels == 7
    assert fam.unet.num_class_embeds == 1000
    assert fam.vae.downscale == 4
    assert fam.prediction_type == "v_prediction"


def test_x4_quadruples_size(tiny_upscaler4):
    """Input at the low-res grid; f=4 VAE decodes straight to 4x pixels.
    CFG + noise-level conditioning run inside one jitted program."""
    rng = np.random.default_rng(3)
    img = rng.integers(0, 255, (1, 64, 64, 3), dtype=np.uint8)
    out, config = tiny_upscaler4(img, prompt="sharp photo", steps=2,
                                 guidance_scale=5.0, noise_level=7, seed=4)
    assert out.shape == (1, 256, 256, 3)
    assert out.dtype == np.uint8
    assert config["scale"] == 4
    assert config["upscale_noise_level"] == 7
    # determinism per seed
    out2, _ = tiny_upscaler4(img, prompt="sharp photo", steps=2,
                             guidance_scale=5.0, noise_level=7, seed=4)
    assert np.array_equal(out, out2)
    # the noise level feeds the class embedding AND the low-res noising:
    # a different level must change the result
    out3, _ = tiny_upscaler4(img, prompt="sharp photo", steps=2,
                             guidance_scale=5.0, noise_level=30, seed=4)
    assert not np.array_equal(out, out3)


@pytest.mark.slow
def test_upscale_doubles_size(tiny_upscaler):
    rng = np.random.default_rng(3)
    img = rng.integers(0, 255, (1, 64, 64, 3), dtype=np.uint8)
    out, config = tiny_upscaler(img, prompt="sharp photo", steps=3, seed=7)
    assert out.shape == (1, 128, 128, 3)
    assert out.dtype == np.uint8
    assert config["scale"] == 2
    # determinism per seed
    out2, _ = tiny_upscaler(img, prompt="sharp photo", steps=3, seed=7)
    assert np.array_equal(out, out2)


@pytest.mark.slow
def test_workload_upscale_flag():
    """diffusion_callback with upscale=True emits 2x-size artifacts."""
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.workloads.diffusion import diffusion_callback

    registry = ModelRegistry(catalog=[], allow_random=True)
    artifacts, config = diffusion_callback(
        "slot0", "random/tiny", seed=3, registry=registry,
        prompt="a pier", num_inference_steps=2, height=64, width=64,
        upscale=True, upscaler_model_name="random/tiny_up")
    assert "primary" in artifacts
    assert config["scale"] == 2
    assert config["upscaler"] == "random/tiny_up"
