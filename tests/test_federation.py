"""swarmfed (ISSUE 17): the federated hive — sharded control plane.

Units pin the contracts the federation rides on:

- **Hash stability**: the job-space partition is a pure function of
  (job id, H) built on sha256 — identical in-process, across a process
  restart (Python's salted ``hash()`` would re-partition every boot),
  and across shard recoveries.
- **Owner-journaled steals**: a cross-shard steal grant is the OWNER's
  journaled state transition; recovery replay rebuilds the steal books
  (counter + flight marker) identically, so ``/api/stats`` reconciles
  across restarts.
- **Per-shard blast radius**: killing one shard degrades only its own
  traffic — the multiplexed worker's OTHER sessions keep serving.
- **Wrong-shard uploads**: forwarded through the router to the owner,
  whose settle set stays the single exactly-once arbiter (a duplicate
  is acked ``duplicate`` there, never double-settled anywhere).
- **Wire parity**: H=1 (and un-federated ShardHive) grants carry
  exactly the PR-14 key set — no ``hive_shard`` stamp anywhere.

THE acceptance gate (slow): 3 shards + 3 real-lane workers, one shard
SIGKILL'd mid-lane and recovered from its own journal — zero job loss,
exactly-once settlement fleet-wide across the epoch bump, the victim
shard's in-flight job resumes at step >= 1 on a survivor, >= 1
cross-shard steal in ``/api/stats``, and one stitched flight record
spanning the steal and both epochs.

Nightly seeded soak (slow; replay with
``CHIASWARM_SOAK_SEED=<run id> pytest tests/test_federation.py --slow
-k soak``): shard-SIGKILL/restart cycles under churn, flight
completeness fleet-wide.
"""

from __future__ import annotations

import asyncio
import subprocess
import sys
import time

import pytest

from chiaswarm_tpu.node.chaos import ChaoticExecutor
from chiaswarm_tpu.node.federation import (
    HIVE_SHARD_KEY,
    FederatedHive,
    ShardHive,
    ShardRouter,
    shard_of,
)
from chiaswarm_tpu.node.hivelog import HIVE_EPOCH_KEY
from chiaswarm_tpu.node.registry import ModelRegistry
from chiaswarm_tpu.node.settings import Settings
from chiaswarm_tpu.node.worker import Worker


@pytest.fixture(autouse=True)
def _tmp_root(tmp_path, monkeypatch):
    monkeypatch.setenv("SWARM_TPU_ROOT", str(tmp_path))
    return tmp_path


@pytest.fixture(autouse=True)
def _restore_matmul_precision():
    import jax

    before = jax.config.jax_default_matmul_precision
    yield
    jax.config.update("jax_default_matmul_precision", before)


class StubSlot:
    """Executor-less slot (the test_chaos/test_durability stand-in)."""

    def __init__(self, depth: int = 4, data_width: int = 1,
                 name: str = "stub"):
        self.depth = depth
        self.data_width = data_width
        self.name = name

    def descriptor(self):
        return self.name

    def __call__(self, callback, **kwargs):
        model_name = kwargs.pop("model_name", None)
        seed = int(kwargs.pop("seed", None) or 0)
        artifacts, config = callback(self, model_name, seed=seed,
                                     **kwargs)
        config = dict(config)
        config["seed"] = seed
        return artifacts, config


def fed_settings(uri: str, name: str, **over) -> Settings:
    """Worker settings dialing a federation: ``uri`` is the
    comma-joined shard list (FederatedHive.worker_uri), which
    Settings.hive_uris parses back into one session per shard."""
    base = dict(
        hive_uri=uri, hive_token="t", worker_name=name,
        job_deadline_s=5.0,
        transient_retries=1,
        retry_backoff_s=0.01, retry_backoff_cap_s=0.05,
        breaker_threshold=5, breaker_cooldown_s=3600.0,
        poll_busy_s=0.02, poll_idle_s=0.04,
        poll_backoff_base_s=0.02, poll_backoff_cap_s=0.1,
        upload_retries=3, upload_retry_delay_s=0.02,
        drain_timeout_s=5.0, result_drain_timeout_s=5.0,
        install_signal_handlers=False,
        heartbeat_s=0.05,
    )
    base.update(over)
    return Settings(**base)


def _job(job_id: str, chaos=None, model: str = "shared/tiny", **over):
    job = {"id": job_id, "model_name": model, "prompt": f"p {job_id}",
           "num_inference_steps": 2, "height": 64, "width": 64,
           "content_type": "application/json"}
    if chaos is not None:
        job["chaos"] = chaos
    job.update(over)
    return job


def _ok_result(job_id: str, worker: str = "", shard=None) -> dict:
    result = {"id": job_id, "artifacts": {}, "nsfw": False,
              "pipeline_config": {"mode": "test"}}
    if worker:
        result["worker_name"] = worker
    if shard is not None:
        result[HIVE_SHARD_KEY] = shard
    return result


def _worker(settings: Settings, **over) -> Worker:
    kwargs = dict(pool=[StubSlot(name=settings.worker_name)],
                  registry=ModelRegistry(catalog=[], allow_random=True),
                  executor=ChaoticExecutor())
    kwargs.update(over)
    return Worker(settings=settings, **kwargs)


# ids pre-sorted by their 3-shard owner (golden against sha256; the
# stability test below pins the function itself)
OWNED_BY = {
    0: ["fed-0", "fed-9", "fed-11", "fed-17", "fed-20", "fed-21"],
    1: ["fed-3", "fed-4", "fed-5", "fed-12", "fed-13", "fed-29"],
    2: ["fed-1", "fed-2", "fed-6", "fed-7", "fed-8", "fed-10"],
}


# ---------------------------------------------------------------------------
# hash routing
# ---------------------------------------------------------------------------


def test_shard_of_stable_golden_and_balanced():
    # golden pins: these values are sha256 facts, not implementation
    # accidents — a change here re-partitions every deployed job space
    assert shard_of("load-7", 3) == 1
    assert shard_of("dur-0", 3) == 0
    assert shard_of("42", 5) == 2
    for index, ids in OWNED_BY.items():
        for job_id in ids:
            assert shard_of(job_id, 3) == index
    # H<=1 degenerates to the single hive
    assert shard_of("anything", 1) == 0
    assert shard_of("anything", 0) == 0
    # no shard starves under a uniform id sweep
    counts = [0, 0, 0]
    for i in range(600):
        counts[shard_of(f"bal-{i}", 3)] += 1
    assert min(counts) > 100, counts
    router = ShardRouter(3)
    assert router.owner_index("dur-0") == shard_of("dur-0", 3)


def test_shard_of_stable_across_process_restart():
    """The property ``hash()`` would break: a FRESH interpreter (new
    hash salt) computes the identical partition."""
    ids = [job_id for ids in OWNED_BY.values() for job_id in ids]
    script = (
        "from chiaswarm_tpu.node.federation import shard_of\n"
        f"print([shard_of(j, 3) for j in {ids!r}])\n")
    out = subprocess.run([sys.executable, "-c", script],
                         capture_output=True, text=True, check=True)
    assert eval(out.stdout.strip()) == [shard_of(j, 3) for j in ids]


# ---------------------------------------------------------------------------
# wire parity (the PR-14 contract, extended per ISSUE 17)
# ---------------------------------------------------------------------------


def test_wire_parity_h1_and_unfederated():
    """H=1 federation and un-federated ShardHive grant exactly the
    PR-14 key set: no ``hive_shard`` stamp, no epoch without a journal
    (the test_durability parity gate, extended across the federation
    seam)."""
    job = _job("p-0")
    expected = set(job) | {"attempt", "queued_s", "trace_ctx"}

    # un-federated ShardHive is a plain MiniHive on the wire
    solo = ShardHive(lease_s=5.0, delay_s=0.0, shard_index=0)
    solo.submit(dict(job))
    [payload] = solo._take_jobs("w1")
    assert set(payload) == expected
    ack = solo._record_result(_ok_result("p-0", "w1", shard=7), "w1")
    assert ack == {"status": "ok"}
    assert HIVE_SHARD_KEY not in solo.completed["p-0"]

    # H=1 federation: same contract end to end
    fed = FederatedHive(n_shards=1, lease_s=5.0, delay_s=0.0)
    fed.submit(dict(job))
    [payload] = fed.shards[0]._take_jobs("w1")
    assert set(payload) == expected


def test_wire_parity_federated_adds_exactly_shard_key(tmp_path):
    job = _job("fed-0")  # owned by shard 0 of 3
    expected = set(job) | {"attempt", "queued_s", "trace_ctx"}

    # journal OFF: federated grants add exactly the shard stamp
    fed = FederatedHive(n_shards=3, lease_s=5.0, delay_s=0.0)
    assert fed.submit(dict(job)) == 0
    [payload] = fed.shards[0]._take_jobs("w1")
    assert set(payload) == expected | {HIVE_SHARD_KEY}
    assert payload[HIVE_SHARD_KEY] == 0

    # journal ON: shard stamp + epoch stamp, nothing else
    fedj = FederatedHive(n_shards=3, journal_root=tmp_path / "hive",
                         journal_fsync=False, lease_s=5.0, delay_s=0.0)
    fedj.submit(dict(job))
    [payload] = fedj.shards[0]._take_jobs("w1")
    assert set(payload) == expected | {HIVE_SHARD_KEY, HIVE_EPOCH_KEY}


def test_api_shards_bootstraps_worker_from_one_front_address():
    """swarmplan satellite (ISSUE 19, PR-17 residue): the front is an
    aggregation plane, not a proxy — workers must dial the shards
    directly. ``GET /api/shards`` closes the bootstrap gap: a worker
    configured with ONE ``hive_front_uri`` resolves the live shard
    list at startup and rebuilds its session bundles from it,
    replacing any stale hand-configured list."""
    import aiohttp

    from chiaswarm_tpu.node.federation import bootstrap_shard_uris

    async def scenario():
        fed = FederatedHive(n_shards=3, lease_s=30.0)
        front = await fed.start()
        try:
            uris = await bootstrap_shard_uris(front)
            assert list(uris) == fed.shard_uris() and len(uris) == 3
            async with aiohttp.ClientSession() as session:
                async with session.get(front + "/api/shards") as resp:
                    assert resp.status == 200
                    body = await resp.json()
            assert body["n_shards"] == 3
            assert body["shards"] == fed.shard_uris()
            assert body["worker_uri"] == fed.worker_uri()

            # a worker knowing only the front (its configured hive_uri
            # is a stale guess) comes up multiplexing every shard
            worker = _worker(fed_settings("http://127.0.0.1:9",
                                          "boot-w0",
                                          hive_front_uri=front))
            await worker._bootstrap_from_front()
            assert worker.settings.hive_shard_uris == uris
            assert worker.settings.hive_uris() == list(uris)
            assert len(worker.shards) == 3

            # an injected hive client is the chaos/test seam and must
            # always win over the bootstrap
            class _Stub:
                pass

            pinned = _worker(fed_settings("http://127.0.0.1:9",
                                          "boot-w1",
                                          hive_front_uri=front),
                             hive=_Stub())
            await pinned._bootstrap_from_front()
            assert len(pinned.shards) == 1
        finally:
            await fed.stop()

    asyncio.run(scenario())


# ---------------------------------------------------------------------------
# stealing + wrong-shard uploads (direct seam units, no HTTP)
# ---------------------------------------------------------------------------


def test_steal_routes_deepest_peer_and_owner_keeps_books():
    fed = FederatedHive(n_shards=3, lease_s=5.0, delay_s=0.0)
    for job_id in OWNED_BY[1][:1]:
        fed.submit(_job(job_id))
    for job_id in OWNED_BY[2][:3]:  # shard 2 is the deepest peer
        fed.submit(_job(job_id))
    # a poll on EMPTY shard 0 steals exactly one job from shard 2
    [payload] = fed.shards[0]._take_jobs("w1")
    stolen_id = str(payload["id"])
    assert payload[HIVE_SHARD_KEY] == 2
    assert stolen_id in OWNED_BY[2]
    # the lease lives on the OWNER; the thief holds nothing
    assert fed.shards[2].lease_holder(stolen_id) == "w1"
    assert fed.shards[0].leased_ids("w1") == []
    # the steal books: owner's counter + owner's flight marker
    assert fed.shards[2]._steals.value(**{"from": "2", "to": "0"}) == 1
    events = [e["event"] for e in
              fed.shards[2].flights.get(stolen_id)["events"]]
    assert "stolen" in events
    # settle through the owner: exactly-once, fleet-wide
    ack = fed.shards[2]._record_result(
        _ok_result(stolen_id, "w1", shard=2), "w1")
    assert ack == {"status": "ok"}
    assert fed.stats()["aggregate"]["steals"] == {"2->0": 1.0}


def test_steal_skips_shard_partitioned_from_worker():
    fed = FederatedHive(n_shards=3, lease_s=5.0, delay_s=0.0)
    for job_id in OWNED_BY[2][:2]:
        fed.submit(_job(job_id))
    # the only backlogged peer cannot reach this worker: no steal (the
    # lease would live on a hive the worker cannot upload to)
    fed.shards[2].partition("w1")
    assert fed.shards[0]._take_jobs("w1") == []
    # a different worker still steals
    [payload] = fed.shards[0]._take_jobs("w2")
    assert payload[HIVE_SHARD_KEY] == 2


def test_steal_disabled_leaves_empty_polls_empty():
    fed = FederatedHive(n_shards=2, steal=False, lease_s=5.0,
                        delay_s=0.0)
    fed.submit(_job(OWNED_BY[1][0]))
    assert fed.shards[0]._take_jobs("w1") == []
    assert len(fed.shards[1].pending_jobs) == 1


def test_wrong_shard_duplicate_upload_acked_duplicate_not_resettled():
    """ISSUE 17 satellite: an upload duplicated to the WRONG shard is
    forwarded to the owner and acked ``duplicate`` — never
    double-settled on any shard."""
    fed = FederatedHive(n_shards=3, lease_s=5.0, delay_s=0.0)
    job_id = OWNED_BY[1][0]
    fed.submit(_job(job_id))
    [payload] = fed.shards[1]._take_jobs("w1")
    # first settle lands on the owner (normal path)
    ack = fed.shards[1]._record_result(
        _ok_result(job_id, "w1", shard=1), "w1")
    assert ack == {"status": "ok"}
    # the retry lands on the WRONG shard: forwarded, acked duplicate
    ack = fed.shards[0]._record_result(
        _ok_result(job_id, "w1", shard=1), "w1")
    assert ack["status"] == "duplicate"
    aggregate = fed.stats()["aggregate"]
    assert aggregate["completed"] == 1
    assert aggregate["duplicates"] == 1
    assert aggregate["forwarded_uploads"] == 1
    assert len(fed.uploaded_ids()) == 1
    # the duplicate book lives on the owner, not the mis-routed shard
    assert len(fed.shards[1].duplicate_results) == 1
    assert fed.shards[0].duplicate_results == []
    # the stored result never carries routing metadata
    assert HIVE_SHARD_KEY not in fed.completed[job_id]


# ---------------------------------------------------------------------------
# owner-journaled steal: recovery replay reconciles
# ---------------------------------------------------------------------------


def test_steal_grant_journaled_by_owner_replay_reconciles(tmp_path):
    """The steal is the owner's journaled transition: SIGKILL the owner
    shard and recover it from ITS journal — the steal counter, the
    flight marker, and the stolen job's lease all come back; the
    worker's settle (carrying the epoch-1 grant) salvages on the
    recovered epoch-2 shard exactly once."""

    async def scenario():
        fed = FederatedHive(n_shards=2, journal_root=tmp_path / "hive",
                            journal_fsync=False, lease_s=30.0,
                            delay_s=0.0)
        await fed.start()
        victim_id = None
        try:
            for job_id in ("fed-0", "fed-10"):  # shard 0 of 2 owns both
                fed.submit(_job(job_id))
            # steal via an empty poll on shard 1
            [payload] = fed.shards[1]._take_jobs("w1")
            victim_id = str(payload["id"])
            assert payload[HIVE_SHARD_KEY] == 0
            assert fed.shards[0]._steals.value(
                **{"from": "0", "to": "1"}) == 1

            await fed.kill_shard(0)
            recovered = await fed.restart_shard(0)
            # replay rebuilt the steal books identically
            assert recovered._steals.value(
                **{"from": "0", "to": "1"}) == 1
            events = [e["event"] for e in
                      recovered.flights.get(victim_id)["events"]]
            assert "stolen" in events
            assert recovered.hive_epoch == 2
            # the stolen job's lease survived recovery on the OWNER
            assert recovered.lease_holder(victim_id) == "w1"
            # the settle (epoch-1 grant echo) salvages exactly once
            ack = recovered._record_result(
                _ok_result(victim_id, "w1", shard=0), "w1")
            assert ack == {"status": "ok"}
            ack = recovered._record_result(
                _ok_result(victim_id, "w1", shard=0), "w1")
            assert ack["status"] == "duplicate"
            assert fed.stats()["aggregate"]["steals"] == {"0->1": 1.0}
        finally:
            await fed.stop()
        return fed, victim_id

    fed, victim_id = asyncio.run(scenario())
    assert fed.uploaded_ids() == [victim_id]


# ---------------------------------------------------------------------------
# per-shard outage independence (the blast-radius contract)
# ---------------------------------------------------------------------------


def test_shard_outage_degrades_only_its_own_traffic(tmp_path):
    """Kill shard 1 of 3 under a live multiplexed worker: sessions to
    shards 0/2 stay online and their jobs keep settling; only shard
    1's session rides an outage. Restarting shard 1 from its journal
    heals the session and recovers its jobs — fleet-wide exactly-once."""

    async def scenario():
        fed = FederatedHive(n_shards=3, journal_root=tmp_path / "hive",
                            journal_fsync=False, lease_s=30.0,
                            delay_s=0.0)
        await fed.start()
        issued = (OWNED_BY[0][:2] + OWNED_BY[1][:2] + OWNED_BY[2][:2])
        worker = _worker(fed_settings(fed.worker_uri(), "fedrider",
                                      hive_outage_after=2))
        task = asyncio.create_task(worker.run())
        try:
            for job_id in OWNED_BY[1][:2]:
                fed.submit(_job(job_id))
            await fed.kill_shard(1)

            # shards 0/2 keep settling while shard 1 is down
            for job_id in OWNED_BY[0][:2] + OWNED_BY[2][:2]:
                fed.submit(_job(job_id))
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if len(fed.completed) >= 4 \
                        and worker.shards[1].session.in_outage:
                    break
                await asyncio.sleep(0.05)
            assert len(fed.completed) >= 4, fed.stats()["aggregate"]
            assert worker.shards[1].session.in_outage
            assert not worker.shards[0].session.in_outage
            assert not worker.shards[2].session.in_outage
            # the per-shard health surface names the sick session
            states = {b["shard"]: b["session"]["state"]
                      for b in worker.health()["hive_shards"]}
            assert states == {0: "online", 1: "outage", 2: "online"}

            # recovery: shard 1's journal redelivers its jobs; the
            # worker's shard-1 session heals on its next poll
            await fed.restart_shard(1)
            await fed.wait_for_results(6, timeout=60)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if not worker.shards[1].session.in_outage:
                    break
                await asyncio.sleep(0.05)
            assert not worker.shards[1].session.in_outage
        finally:
            worker.request_stop()
            await asyncio.wait_for(
                asyncio.gather(task, return_exceptions=True), timeout=30)
            await fed.stop()
        return fed, worker, issued

    fed, worker, issued = asyncio.run(scenario())
    uploaded = fed.uploaded_ids()
    assert sorted(uploaded) == sorted(issued)
    assert len(uploaded) == len(set(uploaded))
    assert fed.abandoned == []
    assert fed.verify_flights(issued) == []
    # only the killed shard bumped its epoch
    assert fed.stats()["aggregate"]["epochs"] == [1, 2, 1]
    # a multiplexed worker counts ONCE in the merged /api/fleet view
    fleet = fed.fleet_snapshot()
    assert list(fleet["workers"]) == ["fedrider"]


# ---------------------------------------------------------------------------
# THE acceptance gate (slow): shard SIGKILL mid-lane, fleet-wide
# exactly-once across the epoch bump, steal + stitched flight
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_federated_shard_sigkill_mid_lane_recovery_gate(tmp_path,
                                                        monkeypatch):
    """ISSUE 17 acceptance: 3 hive shards + 3 real-lane workers under
    mixed-workload churn; the shard owning every gate job is SIGKILL'd
    mid-lane (and the worker holding a checkpointed job dies in the
    same incident window), then recovered from its own journal. Zero
    job loss; exactly-once settlement FLEET-WIDE across the epoch
    bump; the victim shard's in-flight job resumes at step >= 1 on a
    survivor; >= 1 cross-shard steal reconciles in /api/stats; and one
    stitched flight record spans the steal and both epochs."""
    import jax

    from chiaswarm_tpu.core.chip_pool import ChipPool
    from chiaswarm_tpu.core.mesh import MeshSpec

    monkeypatch.setenv("CHIASWARM_STEPPER", "1")
    monkeypatch.setenv("CHIASWARM_STEPPER_CKPT_EVERY", "1")
    monkeypatch.setenv("CHIASWARM_STEPPER_STEP_DELAY_S", "0.08")

    registry = ModelRegistry(
        catalog=[{"name": "tiny", "family": "tiny", "parameters": {}}],
        allow_random=True)

    def lane_job(job_id: str, i: int) -> dict:
        return {"id": job_id, "model_name": "tiny",
                "prompt": f"federated prompt {i}", "seed": 1700 + i,
                "num_inference_steps": 24, "guidance_scale": 7.5,
                "height": 64, "width": 64, "content_type": "image/png"}

    # every gate job is owned by shard 0: polls landing on (empty)
    # shards 1/2 MUST steal, and shard 0 is the in-flight victim
    gate_ids = OWNED_BY[0][:4]

    async def scenario():
        fed = FederatedHive(n_shards=3, journal_root=tmp_path / "hive",
                            journal_fsync=False, lease_s=60.0,
                            delay_s=0.01, max_jobs_per_poll=1)
        await fed.start()
        wuri = fed.worker_uri()
        workers = []
        for tag in ("a", "b", "c"):
            pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 1}),
                            devices=jax.devices()[:1])
            workers.append(Worker(
                settings=fed_settings(wuri, f"fedfleet-{tag}",
                                      job_deadline_s=600.0,
                                      drain_timeout_s=30.0,
                                      result_drain_timeout_s=30.0),
                registry=registry, pool=pool))
        tasks = {w.settings.worker_name: asyncio.create_task(w.run())
                 for w in workers}
        for i, job_id in enumerate(gate_ids):
            fed.submit(lane_job(job_id, i))

        shard0 = fed.shards[0]
        victim = victim_job = None
        recovered = None
        try:
            # wait for a lane checkpoint (step >= 1) journaled on
            # shard 0, PREFERRING a stolen job — then SIGKILL the
            # shard mid-lane; the lease holder dies in the same
            # incident window, so its job can only come back through
            # shard-0 journal recovery + redelivery-with-resume
            deadline = time.monotonic() + 240
            fallback_at = time.monotonic() + 120
            while victim is None and time.monotonic() < deadline:
                candidates = []
                for job_id, ckpt in list(shard0.checkpoints.items()):
                    holder = shard0.lease_holder(job_id)
                    if ckpt.get("kind") == "lane" and \
                            int(ckpt.get("step", 0)) >= 1 and \
                            holder is not None:
                        record = shard0.flights.get(job_id) or {}
                        stolen = any(e["event"] == "stolen"
                                     for e in record.get("events", []))
                        candidates.append((stolen, job_id, holder))
                stolen_first = sorted(candidates, reverse=True)
                if stolen_first and (stolen_first[0][0]
                                     or time.monotonic() > fallback_at):
                    _, victim_job, victim = stolen_first[0]
                    break
                await asyncio.sleep(0.02)
            assert victim is not None, \
                f"no lane checkpoint ever journaled: {shard0.stats()}"
            # the survivors' unfinished leases at the incident moment:
            # every one of them MUST come back out of a dead-letter
            # spool (their uploads can only reach the dead owner)
            survivors = [w for w in workers
                         if w.settings.worker_name != victim]
            survivor_leases = {
                w.settings.worker_name:
                    shard0.leased_ids(w.settings.worker_name)
                for w in survivors}
            dead0 = shard0  # in-memory corpse: settle set freezes here
            await fed.kill_shard(0)       # the shard SIGKILL
            tasks[victim].cancel()        # same-incident worker loss
            await asyncio.gather(tasks[victim], return_exceptions=True)

            # survivors ride through: every upload routes to the dead
            # OWNER shard, so finished lanes spool while shards 1/2
            # keep answering their polls (no fleet-wide outage). A
            # settle can land in the kill window, so the expectation
            # re-filters against the corpse's (frozen) settle set.
            def expected_spooled() -> int:
                return sum(
                    1 for name, leased in survivor_leases.items()
                    for job_id in leased
                    if job_id not in dead0.completed)

            deadline = time.monotonic() + 240
            while time.monotonic() < deadline:
                total = sum(w.shards[0].spool.depth()
                            for w in survivors)
                if total >= expected_spooled() \
                        and all(not w._inflight for w in survivors) \
                        and all(w.shards[0].session.in_outage
                                for w in survivors):
                    break
                await asyncio.sleep(0.05)
            spooled_total = sum(w.shards[0].spool.depth()
                                for w in survivors)
            assert spooled_total >= expected_spooled(), (
                survivor_leases,
                [w.shards[0].session.snapshot() for w in survivors])
            for w in survivors:
                # the dead shard's session rides an outage...
                assert w.shards[0].session.in_outage, \
                    w.shards[0].session.snapshot()
                # ...while the blast radius held: the OTHERS are fine
                assert not w.shards[1].session.in_outage
                assert not w.shards[2].session.in_outage

            # recover shard 0 from ITS OWN journal on its old port:
            # survivors heal, spools replay live, and the victim's
            # checkpointed job redelivers WITH resume state
            recovered = await fed.restart_shard(0)
            await fed.wait_for_results(len(gate_ids), timeout=300)
        finally:
            for worker in workers:
                worker.request_stop()
            await asyncio.gather(*(asyncio.wait_for(t, timeout=60)
                                   for t in tasks.values()),
                                 return_exceptions=True)
            for worker in workers:
                for slot in worker.pool:
                    stepper = getattr(slot, "_stepper", None)
                    if stepper is not None:
                        stepper.shutdown()
            await fed.stop()
        return fed, recovered, workers, victim, victim_job, spooled_total

    fed, recovered, workers, victim, victim_job, spooled_total = \
        asyncio.run(scenario())

    # zero job loss, exactly-once settlement FLEET-WIDE across epochs
    uploaded = fed.uploaded_ids()
    assert sorted(set(uploaded)) == sorted(gate_ids)
    assert len(uploaded) == len(set(uploaded))
    assert fed.abandoned == []
    for result in fed.results:
        assert result["pipeline_config"].get("error") is None, result
        assert "fatal_error" not in result
        assert HIVE_EPOCH_KEY not in result
        assert HIVE_SHARD_KEY not in result
    stats = fed.stats()
    assert stats["aggregate"]["epochs"] == [2, 1, 1]
    assert stats["aggregate"]["completed"] == len(gate_ids)

    # >= 1 cross-shard steal reconciles in /api/stats (and recovery
    # replay preserved the owner's steal books across the kill)
    assert stats["aggregate"]["steals_total"] >= 1, stats["aggregate"]
    assert any(key.startswith("0->")
               for key in stats["aggregate"]["steals"])

    # the victim shard's in-flight job resumed at step >= 1 on a
    # survivor — its only path: the holder died with the shard, so the
    # resume state crossed the crash through shard 0's WAL
    resumed = fed.completed[victim_job]
    assert resumed["worker_name"] != victim
    stepper_info = resumed["pipeline_config"].get("stepper") or {}
    assert int(stepper_info.get("resume_step", 0)) >= 1, stepper_info
    survivor_stats = [
        slot._stepper.stats()
        for worker in workers
        if worker.settings.worker_name != victim
        for slot in worker.pool
        if getattr(slot, "_stepper", None) is not None
    ]
    assert sum(s.get("rows_resumed", 0) for s in survivor_stats) >= 1

    # one stitched flight record spanning the steal and both epochs:
    # the victim job's record (whole on its owner) carries grants from
    # epoch 1 AND epoch 2 plus the recovery marker; the steal marker
    # sits on the stolen job's record (the victim itself when the
    # preferred selection found one)
    record = fed.flight(victim_job)
    events = [e["event"] for e in record["events"]]
    grant_epochs = {e.get("epoch") for e in record["events"]
                    if e["event"] == "grant"}
    assert "hive_recovered" in events
    assert {1, 2} <= grant_epochs, record["events"]
    stolen_records = [
        job_id for job_id in gate_ids
        if any(e["event"] == "stolen"
               for e in (fed.flight(job_id) or {}).get("events", []))]
    assert stolen_records, "no stolen flight record anywhere"
    assert fed.verify_flights(gate_ids) == []

    # riding-through survivors replayed their spools LIVE (every
    # envelope that spooled during the outage drained on heal)
    live_total = sum(
        worker.metrics.get("chiaswarm_dead_letter_replayed_total")
        .value(when="live")
        for worker in workers
        if worker.settings.worker_name != victim)
    assert live_total >= spooled_total, (live_total, spooled_total)


# ---------------------------------------------------------------------------
# nightly seeded shard-kill soak (CI satellite; replay with
#   CHIASWARM_SOAK_SEED=<run id> pytest tests/test_federation.py --slow
#   -k soak)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_federated_shard_restart_soak_exactly_once(tmp_path):
    """Nightly federation soak (seed = run id): a seeded chaos job mix
    over 3 journaled shards with seeded mid-run shard-SIGKILL/restart
    cycles under 3 riding-through multiplexed workers. Every issued
    job settles exactly once FLEET-WIDE, and every flight record is
    complete on its owner shard."""
    import os
    import random

    seed = os.environ.get("CHIASWARM_SOAK_SEED", "fed-soak-default")
    n_jobs = int(os.environ.get("CHIASWARM_SOAK_JOBS", "36"))
    rng = random.Random(f"fed-soak:{seed}")
    scripts = ([["ok"]] * 5 + [["slow"]] * 3 + [["oom", "ok"]] * 2
               + [["fetch", "ok"]] * 2 + [["crash"]] + [["fatal"]])
    jobs = [_job(f"fsoak-{i}", chaos=list(rng.choice(scripts)))
            for i in range(n_jobs)]
    restarts = sorted(rng.sample(range(n_jobs // 5, 4 * n_jobs // 5), 2))
    kill_order = [rng.randrange(3) for _ in restarts]

    async def scenario():
        fed = FederatedHive(n_shards=3, journal_root=tmp_path / "hive",
                            journal_fsync=False, lease_s=2.0,
                            delay_s=0.0, max_attempts=6,
                            max_jobs_per_poll=3)
        await fed.start()
        for job in jobs:
            fed.submit(job)
        workers = [_worker(
            fed_settings(fed.worker_uri(), f"fsoak-{tag}",
                         job_deadline_s=0.5),
            executor=ChaoticExecutor(hang_s=1.0, slow_s=0.1))
            for tag in ("a", "b", "c")]
        tasks = {w.settings.worker_name: asyncio.create_task(w.run())
                 for w in workers}
        cycles = 0
        try:
            deadline = time.monotonic() + 300
            while time.monotonic() < deadline:
                settled = (len(fed.completed) + len(fed.abandoned))
                if cycles < len(restarts) and \
                        settled >= restarts[cycles]:
                    # the seeded kill/restart cycle: SIGKILL one
                    # shard, then recover it from ITS journal on the
                    # same port while the other two keep serving
                    index = kill_order[cycles]
                    await fed.kill_shard(index)
                    await asyncio.sleep(0.3)  # let outages flip
                    await fed.restart_shard(index)
                    cycles += 1
                    # re-check thresholds before the settled-break: a
                    # burst can settle EVERYTHING during the restart
                    # awaits, and the remaining cycles must still run
                    # (killing a drained shard still proves recovery)
                    continue
                if len(fed.completed) + len(fed.abandoned) >= n_jobs:
                    break
                fed.sweep()
                await asyncio.sleep(0.05)
        finally:
            for worker in workers:
                worker.request_stop()
            await asyncio.gather(*(asyncio.wait_for(t, timeout=30)
                                   for t in tasks.values()),
                                 return_exceptions=True)
            await fed.stop()
        return fed, cycles

    fed, cycles = asyncio.run(scenario())
    assert cycles == 2
    issued = [j["id"] for j in jobs]
    completed = set(fed.completed)
    abandoned = set(fed.abandoned)
    assert completed.isdisjoint(abandoned)
    assert completed | abandoned == set(issued), \
        sorted(set(issued) - completed - abandoned)
    uploaded = fed.uploaded_ids()
    assert len(uploaded) == len(set(uploaded))
    # each killed shard recovered through its OWN journal
    epochs = fed.stats()["aggregate"]["epochs"]
    assert sum(epochs) == 3 + len(restarts), epochs
    # flight completeness FLEET-WIDE (the chaos-soak.yml gate)
    assert fed.verify_flights(issued, require_settled=False) == []
    assert fed.verify_flights(sorted(completed)) == []
