"""swarmturbo (ISSUE 12): the step-collapse gates.

Two halves, both attacking the steps x full-UNet product the 15x
headline gap is made of:

- **Few-step sampler family** — the ``lcm`` kind (boundary-condition
  step, timestep-shifted trailing ladder, guidance-embedded/CFG-free
  mode): registry resolution, schedule shape, the final-step boundary
  condition, and THE gate — a 4-step lcm row spliced into a running
  lane is solo-trajectory-exact (the PR-3 splice-equivalence pattern),
  including at guidance 1.0, where the lane's per-row combine selects
  the pure conditional prediction.
- **DeepCache feature reuse** — ``CHIASWARM_DEEPCACHE`` + per-job
  ``reuse_schedule``: OFF is bit-identical to pre-reuse behavior (same
  executable, zero new compiles, identical images), ON passes the
  PSNR/SSIM quality gate vs the full-step reference, schedules ride as
  traced tables (no recompile per schedule), lanes match their solo
  twins, checkpoints carry the cache so a mid-schedule resume is
  bit-identical, and a tampered schedule in the resume payload
  restarts clean through ``_validate_resume``.

Admission still compiles nothing once the lcm/reuse lane buckets are
warm (the compile-cache counter gate), and the stepper-off CI leg runs
the ``solo``-marked subset with CHIASWARM_STEPPER=0 to prove few-step
jobs serve correctly through the per-job path.

Tiering: tier-1's wall-clock budget has no room for more compiles
(the suite already runs ~95% of it), so every compile-heavy gate here
is ``slow``-marked and ALWAYS runs in the dedicated CI step
(test.yml "Fast-sampling suite", ``--slow``); the default tier keeps
the host-side units plus the cheap off-gate/solo checks.

Runs on the hermetic CPU platform (tests/conftest.py).
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from chiaswarm_tpu.core.compile_cache import GLOBAL_CACHE
from chiaswarm_tpu.pipelines import (
    Components,
    DiffusionPipeline,
    GenerateRequest,
)
from chiaswarm_tpu.pipelines.diffusion import (
    deepcache_enabled,
    normalize_reuse_schedule,
)
from chiaswarm_tpu.schedulers import FEWSTEP_KINDS, SAMPLERS, resolve
from chiaswarm_tpu.serving.stepper import LaneReject, StepScheduler


@pytest.fixture(scope="module")
def tiny_pipe():
    return DiffusionPipeline(Components.random("tiny", seed=0))


def _wait_steps(sched: StepScheduler, n: int, timeout: float = 120.0):
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        if sched.stats().get("steps_executed", 0) >= n:
            return
        time.sleep(0.005)
    raise AssertionError(
        f"scheduler never reached {n} steps: {sched.stats()}")


def _close(lane_img: np.ndarray, solo_img: np.ndarray) -> None:
    # the PR-3 splice-equivalence tolerance: agreement to uint8
    # quantization across different compiled batch shapes
    diff = np.abs(lane_img.astype(int) - solo_img.astype(int))
    assert diff.max() <= 3 and (diff <= 1).mean() > 0.99, (
        diff.max(), (diff <= 1).mean())


# ---------------------------------------------------------------------------
# the lcm sampler kind: registration + schedule + step math
# ---------------------------------------------------------------------------


def test_lcm_registered_and_resolves_shifted_schedule():
    """Catalog-level registration: the hive requests the few-step
    family by diffusers class name like every other scheduler, and the
    resolved config pins the timestep-SHIFTED trailing ladder with
    karras respacing forced off (the distillation contract)."""
    assert SAMPLERS["LCMScheduler"] == "lcm"
    assert SAMPLERS["TCDScheduler"] == "lcm"
    assert "lcm" in FEWSTEP_KINDS
    cfg = resolve("LCMScheduler")
    assert cfg.kind == "lcm"
    assert cfg.timestep_spacing == "trailing"
    assert cfg.use_karras_sigmas is False
    # the shifted ladder lands its FIRST step on the training boundary
    from chiaswarm_tpu.schedulers.sampling import make_for

    _, sched = make_for("sd", 4, cfg)
    ts = np.asarray(sched.timesteps)
    sig = np.asarray(sched.sigmas)
    assert ts.shape == (4,) and sig.shape == (5,)
    assert ts[0] == pytest.approx(999.0)       # boundary timestep
    assert np.all(np.diff(ts) < 0)             # descending
    assert np.all(np.diff(sig) < 0) and sig[-1] == 0.0


def test_lcm_step_boundary_condition():
    """The lcm step: full re-noise onto the next level, and at
    sigma_next == 0 it returns the boundary-conditioned x0 exactly
    (LCMScheduler's final step emits denoised, no noise)."""
    import jax.numpy as jnp

    from chiaswarm_tpu.schedulers.sampling import (
        init_sampler_state,
        make_for,
        sampler_step,
    )

    cfg = resolve("LCMScheduler")
    _, sched = make_for("sd", 2, cfg)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((1, 4, 4, 4)), jnp.float32)
    eps = jnp.asarray(rng.standard_normal((1, 4, 4, 4)), jnp.float32)
    noise = jnp.asarray(rng.standard_normal((1, 4, 4, 4)), jnp.float32)
    state = init_sampler_state(x)
    # step 0: re-noised by sigma[1] — must depend on the noise argument
    x1a, _ = sampler_step(cfg, sched, 0, x, eps, state, noise=noise)
    x1b, _ = sampler_step(cfg, sched, 0, x, eps, state,
                          noise=jnp.zeros_like(noise))
    assert not np.allclose(np.asarray(x1a), np.asarray(x1b))
    # final step (sigma_next == 0): noise-independent boundary output
    x2a, _ = sampler_step(cfg, sched, 1, x, eps, state, noise=noise)
    x2b, _ = sampler_step(cfg, sched, 1, x, eps, state,
                          noise=jnp.zeros_like(noise))
    np.testing.assert_array_equal(np.asarray(x2a), np.asarray(x2b))
    assert np.isfinite(np.asarray(x2a)).all()


@pytest.mark.solo
def test_lcm_solo_four_step_cfg_free(tiny_pipe):
    """The solo path serves a 4-step guidance-embedded (CFG-free) lcm
    job: the no-CFG program compiles, the config records the kind and
    the collapsed per-image eval count."""
    imgs, cfg = tiny_pipe(GenerateRequest(
        prompt="turbo", steps=4, guidance_scale=1.0, height=64,
        width=64, seed=11, scheduler="LCMScheduler"))
    assert imgs.shape == (1, 64, 64, 3)
    assert np.isfinite(imgs).all()
    assert cfg["scheduler"] == "lcm"
    assert cfg["unet_evals"] == 4 and cfg["steps_skipped"] == 0


@pytest.mark.slow
def test_lcm_lane_rows_match_solo_trajectory(tiny_pipe):
    """THE few-step gate (PR-3 pattern): a 4-step CFG-free lcm row
    splices into a running lcm lane mid-flight and matches its solo run
    — as does its longer lane-mate. Guidance 1.0 RIDES the lane (the
    relaxed eligibility for FEWSTEP_KINDS)."""
    sched = StepScheduler()
    base = sched.stats().get("steps_executed", 0)
    fa = sched.submit_request(
        tiny_pipe, prompt="lcm long", steps=8, guidance_scale=1.0,
        height=64, width=64, rows=1, seed=21, scheduler="LCMScheduler")
    _wait_steps(sched, base + 1)
    fb = sched.submit_request(
        tiny_pipe, prompt="lcm fast", steps=4, guidance_scale=1.0,
        height=64, width=64, rows=1, seed=22, scheduler="LCMScheduler")
    pending_b, info_b = fb.result(timeout=300)
    pending_a, info_a = fa.result(timeout=300)
    img_a, img_b = pending_a.wait(), pending_b.wait()
    assert info_b["lane"] == info_a["lane"]
    assert 1 <= info_b["admitted_at_step"] < 8

    solo_a, _ = tiny_pipe(GenerateRequest(
        prompt="lcm long", steps=8, guidance_scale=1.0, height=64,
        width=64, seed=21, scheduler="LCMScheduler"))
    solo_b, _ = tiny_pipe(GenerateRequest(
        prompt="lcm fast", steps=4, guidance_scale=1.0, height=64,
        width=64, seed=22, scheduler="LCMScheduler"))
    _close(img_a, solo_a)
    _close(img_b, solo_b)
    # CFG'd lcm rows ride the same lane program too
    fc = sched.submit_request(
        tiny_pipe, prompt="lcm cfg", steps=4, guidance_scale=5.0,
        height=64, width=64, rows=1, seed=23, scheduler="LCMScheduler")
    img_c = fc.result(timeout=300)[0].wait()
    solo_c, _ = tiny_pipe(GenerateRequest(
        prompt="lcm cfg", steps=4, guidance_scale=5.0, height=64,
        width=64, seed=23, scheduler="LCMScheduler"))
    _close(img_c, solo_c)
    sched.shutdown()


def test_non_fewstep_low_guidance_still_rejected(tiny_pipe):
    """The guidance relaxation is SCOPED to the few-step kinds: a
    low-guidance dpm job still runs the solo no-CFG program."""
    sched = StepScheduler()
    with pytest.raises(LaneReject):
        sched.submit_request(tiny_pipe, prompt="x", steps=4,
                             guidance_scale=1.0, height=64, width=64,
                             rows=1, seed=1)
    sched.shutdown()


@pytest.mark.slow
def test_fewstep_admission_compiles_nothing_once_warm(
        tiny_pipe, monkeypatch):
    """The compile-cache counter gate: once the lcm lane bucket is
    warm, 4-step jobs with new step counts/guidance/seeds splice in
    with ZERO new executables — few-step serving is admission-
    compatible with the existing lane machinery."""
    monkeypatch.setenv("CHIASWARM_STEPPER_LANE_WIDTH", "4")
    sched = StepScheduler()
    sched.submit_request(
        tiny_pipe, prompt="warm", steps=6, guidance_scale=1.0,
        height=64, width=64, rows=1, seed=1,
        scheduler="LCMScheduler").result(timeout=300)[0].wait()
    before = GLOBAL_CACHE.executables.stats["misses"]
    futs = [sched.submit_request(
        tiny_pipe, prompt=f"fewstep {i}", steps=steps,
        guidance_scale=g, height=64, width=64, rows=1, seed=40 + i,
        scheduler="LCMScheduler")
        for i, (steps, g) in enumerate([(4, 1.0), (2, 1.0), (8, 4.0)])]
    for fut in futs:
        fut.result(timeout=300)[0].wait()
    after = GLOBAL_CACHE.executables.stats["misses"]
    assert after == before, (before, after)
    sched.shutdown()


# ---------------------------------------------------------------------------
# DeepCache: the off-gate, the quality gate, traced schedules, lanes
# ---------------------------------------------------------------------------


def test_reuse_schedule_normalization():
    assert normalize_reuse_schedule(8, (4, 2, 4)) == (2, 4)
    assert normalize_reuse_schedule(8, "every:2") == (1, 3, 5, 7)
    assert normalize_reuse_schedule(8, "every:3", 2) == (3, 4, 6, 7)
    assert normalize_reuse_schedule(8, ()) == ()
    with pytest.raises(ValueError):
        normalize_reuse_schedule(8, (0,))    # first step fills the cache
    with pytest.raises(ValueError):
        normalize_reuse_schedule(8, (8,))    # past the ladder
    with pytest.raises(ValueError):
        normalize_reuse_schedule(8, (2,), 2)  # at the start index
    with pytest.raises(ValueError):
        normalize_reuse_schedule(8, "every:1")
    with pytest.raises(ValueError):
        normalize_reuse_schedule(8, "sometimes")
    # malformed payloads stay ValueError (the user-error taxonomy):
    # a TypeError escaping here would feed the model circuit breaker
    with pytest.raises(ValueError):
        normalize_reuse_schedule(8, 2)          # bare int, not a list
    with pytest.raises(ValueError):
        normalize_reuse_schedule(8, [None, 2])  # null entries


@pytest.mark.solo
def test_deepcache_off_is_bit_identical(tiny_pipe):
    """THE off-gate (the PR-11 taps-off pattern): with
    CHIASWARM_DEEPCACHE unset a request carrying a reuse_schedule hits
    the SAME cached executable as the plain request (zero new
    compiles) and returns bit-identical images — pre-PR behavior
    exactly."""
    assert not deepcache_enabled()
    req = dict(prompt="offgate", steps=5, guidance_scale=7.5,
               height=64, width=64, seed=9)
    base, base_cfg = tiny_pipe(GenerateRequest(**req))
    before = (GLOBAL_CACHE.executables.stats["misses"],
              GLOBAL_CACHE.executables.stats["hits"])
    off, off_cfg = tiny_pipe(GenerateRequest(**req,
                                             reuse_schedule=(2, 4)))
    after = (GLOBAL_CACHE.executables.stats["misses"],
             GLOBAL_CACHE.executables.stats["hits"])
    assert after[0] == before[0], "env-off reuse request compiled"
    assert after[1] > before[1], "env-off reuse request missed the cache"
    np.testing.assert_array_equal(base, off)
    assert off_cfg["unet_evals"] == base_cfg["unet_evals"] == 5
    assert "reuse_schedule" not in off_cfg


@pytest.mark.slow
def test_unet_seam_default_lowering_is_byte_identical():
    """The DeepCache seam is ZERO-cost at trace time when off (the
    PR-11 taps-off invariance pattern applied to the model seam): a
    UNet lowered with the seam arguments at their defaults is
    byte-identical HLO to one lowered without mentioning them."""
    import jax
    import jax.numpy as jnp

    from chiaswarm_tpu.models.configs import get_family
    from chiaswarm_tpu.models.unet import UNet

    fam = get_family("tiny")
    unet = UNet(fam.unet)
    key_x, key_ctx, key_init = jax.random.split(jax.random.PRNGKey(0), 3)
    x = jax.random.normal(key_x, (1, 8, 8, 4))
    t = jnp.ones((1,), jnp.float32)
    ctx = jax.random.normal(key_ctx, (1, 7, fam.unet.cross_attention_dim))
    params = unet.init(key_init, x, t, ctx)
    plain = jax.jit(
        lambda p, a, b, c: unet.apply(p, a, b, c)
    ).lower(params, x, t, ctx).as_text()
    seamed = jax.jit(
        lambda p, a, b, c: unet.apply(p, a, b, c, cached_deep=None,
                                      return_deep=False)
    ).lower(params, x, t, ctx).as_text()
    assert plain == seamed


@pytest.mark.slow
def test_deepcache_quality_gate(tiny_pipe, monkeypatch):
    """THE quality gate (the int8 pattern): DeepCache-on output at an
    every:2 cadence stays within PSNR >= 30 dB / SSIM >= 0.9 of the
    same-seed full-step reference on the tiny family."""
    from chiaswarm_tpu.obs.quality import quality_report

    req = dict(prompt="quality", steps=10, guidance_scale=7.5,
               height=64, width=64, seed=17)
    ref, _ = tiny_pipe(GenerateRequest(**req))
    monkeypatch.setenv("CHIASWARM_DEEPCACHE", "1")
    out, cfg = tiny_pipe(GenerateRequest(**req, reuse_schedule="every:2"))
    assert cfg["unet_evals"] == 5 and cfg["steps_skipped"] == 5
    report = quality_report(out, ref)
    assert report["passed"], report


@pytest.mark.slow
def test_deepcache_schedule_is_traced_not_static(tiny_pipe, monkeypatch):
    """Changing the reuse schedule (same steps) must NOT recompile:
    the schedule rides as a traced table, only the static reuse flag
    keys the executable."""
    monkeypatch.setenv("CHIASWARM_DEEPCACHE", "1")
    req = dict(prompt="traced", steps=6, guidance_scale=7.5,
               height=64, width=64, seed=2)
    tiny_pipe(GenerateRequest(**req, reuse_schedule=(2,)))  # warm
    before = GLOBAL_CACHE.executables.stats["misses"]
    _, cfg_a = tiny_pipe(GenerateRequest(**req, reuse_schedule=(2, 4)))
    _, cfg_b = tiny_pipe(GenerateRequest(**req,
                                         reuse_schedule="every:2"))
    after = GLOBAL_CACHE.executables.stats["misses"]
    assert after == before, (before, after)
    assert cfg_a["unet_evals"] == 4
    assert cfg_b["reuse_schedule"] == [1, 3, 5]


@pytest.mark.slow
def test_deepcache_lane_matches_solo_and_counts_evals(
        tiny_pipe, monkeypatch):
    """A reuse-schedule job rides a reuse-keyed lane and matches its
    solo DeepCache twin (single-job lane: the lane-wide decision
    aligns with the row's schedule), with the per-image eval
    accounting in the lane info and the obs counters moving."""
    from chiaswarm_tpu.obs.metrics import REGISTRY

    monkeypatch.setenv("CHIASWARM_DEEPCACHE", "1")
    evals = REGISTRY.get("chiaswarm_stepper_unet_evals_total")
    skipped = REGISTRY.get("chiaswarm_stepper_steps_skipped_total")
    before_reuse = evals.value(mode="reuse")
    before_skip = skipped.value()
    sched = StepScheduler()
    fut = sched.submit_request(
        tiny_pipe, prompt="dc lane", steps=6, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=5, reuse_schedule=(2, 4))
    pending, info = fut.result(timeout=300)
    img = pending.wait()
    assert info["unet_evals"] == 4 and info["steps_skipped"] == 2
    solo, solo_cfg = tiny_pipe(GenerateRequest(
        prompt="dc lane", steps=6, guidance_scale=7.5, height=64,
        width=64, seed=5, reuse_schedule=(2, 4)))
    assert solo_cfg["unet_evals"] == 4
    _close(img, solo)
    assert evals.value(mode="reuse") >= before_reuse + 2
    assert skipped.value() >= before_skip + 2
    # scheduler-level reuse counters rode along
    stats = sched.stats()
    assert stats.get("steps_reused", 0) >= 2
    assert stats.get("row_steps_reused", 0) >= 2
    sched.shutdown()


@pytest.mark.slow
def test_deepcache_lane_admission_compiles_nothing_once_warm(
        tiny_pipe, monkeypatch):
    """Reuse-schedule jobs splice into the warm reuse lane bucket with
    zero new executables — schedules and step counts ride per row."""
    monkeypatch.setenv("CHIASWARM_DEEPCACHE", "1")
    monkeypatch.setenv("CHIASWARM_STEPPER_LANE_WIDTH", "4")
    sched = StepScheduler()
    sched.submit_request(
        tiny_pipe, prompt="warm", steps=6, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=1,
        reuse_schedule=(2,)).result(timeout=300)[0].wait()
    before = GLOBAL_CACHE.executables.stats["misses"]
    futs = [sched.submit_request(
        tiny_pipe, prompt=f"dc {i}", steps=steps, guidance_scale=g,
        height=64, width=64, rows=1, seed=60 + i,
        reuse_schedule=schedule)
        for i, (steps, g, schedule) in enumerate(
            [(6, 5.0, (3, 4)), (4, 7.5, (2,)), (7, 6.0, "every:2")])]
    for fut in futs:
        fut.result(timeout=300)[0].wait()
    after = GLOBAL_CACHE.executables.stats["misses"]
    assert after == before, (before, after)
    sched.shutdown()


# ---------------------------------------------------------------------------
# resume across a reuse schedule (the PR-6 gate extended)
# ---------------------------------------------------------------------------


class _SpoolSlot:
    data_width = 1

    def __init__(self, spool):
        self._checkpoint_spool = spool


@pytest.mark.slow
def test_resume_mid_reuse_schedule_is_bit_identical(
        tiny_pipe, tmp_path, monkeypatch):
    """A lane checkpointed MID-reuse-schedule and redelivered resumes
    bit-identical to the uninterrupted run: the snapshot carries the
    deep caches + validity + skipped tally, so every remaining reuse
    decision replays exactly (the PR-6 resume-equivalence gate over
    the new state)."""
    from chiaswarm_tpu.node.resilience import CheckpointSpool

    monkeypatch.setenv("CHIASWARM_DEEPCACHE", "1")
    monkeypatch.setenv("CHIASWARM_STEPPER_CKPT_EVERY", "1")
    schedule = (2, 3, 5, 6)
    spool = CheckpointSpool(tmp_path / "ckpt")
    sched = StepScheduler(_SpoolSlot(spool))
    fut = sched.submit_request(
        tiny_pipe, prompt="resume reuse", steps=8, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=77, job_id="rr-1",
        reuse_schedule=schedule)
    pending, info = fut.result(timeout=300)
    imgs_fresh = pending.wait()
    assert info["unet_evals"] == 4 and info["steps_skipped"] == 4

    ckpt = spool.load("rr-1")
    assert ckpt is not None and ckpt["kind"] == "lane"
    assert ckpt["reuse_schedule"] == list(schedule)
    assert {"cache_u", "cache_c", "cache_ok", "skipped"} <= set(ckpt)
    assert 1 <= ckpt["step"] < 8

    sched2 = StepScheduler()
    fut2 = sched2.submit_request(
        tiny_pipe, prompt="resume reuse", steps=8, guidance_scale=7.5,
        height=64, width=64, rows=1,
        seed=0,  # resume must not re-derive keys from the seed
        job_id="rr-1", resume=ckpt, reuse_schedule=schedule)
    pending2, info2 = fut2.result(timeout=300)
    assert info2["resume_step"] == ckpt["step"] >= 1
    # whole-trajectory accounting survives the resume
    assert info2["unet_evals"] == 4 and info2["steps_skipped"] == 4
    assert np.array_equal(pending2.wait(), imgs_fresh)
    sched.shutdown()
    sched2.shutdown()


@pytest.mark.slow
def test_resume_rejects_tampered_reuse_schedule(
        tiny_pipe, tmp_path, monkeypatch):
    """A tampered (or stripped) reuse_schedule in the resume payload
    restarts CLEAN via _validate_resume: a checkpoint stepped under a
    different schedule walked a different trajectory and must never
    finish under this job's identity."""
    from chiaswarm_tpu.node.resilience import CheckpointSpool

    monkeypatch.setenv("CHIASWARM_DEEPCACHE", "1")
    monkeypatch.setenv("CHIASWARM_STEPPER_CKPT_EVERY", "1")
    schedule = (2, 4)
    spool = CheckpointSpool(tmp_path / "ckpt2")
    sched = StepScheduler(_SpoolSlot(spool))
    sched.submit_request(
        tiny_pipe, prompt="tamper reuse", steps=6, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=31, job_id="tr-1",
        reuse_schedule=schedule).result(timeout=300)[0].wait()
    ckpt = spool.load("tr-1")
    assert ckpt is not None

    sched2 = StepScheduler()
    # tampered schedule -> rejected, clean restart
    tampered = dict(ckpt)
    tampered["reuse_schedule"] = [2, 3]
    fut = sched2.submit_request(
        tiny_pipe, prompt="tamper reuse", steps=6, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=31, resume=tampered,
        reuse_schedule=schedule)
    pending, info = fut.result(timeout=300)
    assert info["resume_step"] == 0
    assert sched2.stats().get("resumes_rejected", 0) == 1
    assert pending.wait().shape == (1, 64, 64, 3)
    # corrupt cache state -> rejected the same way
    garbage = dict(ckpt)
    garbage["cache_u"] = {"dtype": "float32", "shape": [1], "b64": "!!!"}
    fut2 = sched2.submit_request(
        tiny_pipe, prompt="tamper reuse", steps=6, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=31, resume=garbage,
        reuse_schedule=schedule)
    _, info2 = fut2.result(timeout=300)
    assert info2["resume_step"] == 0
    assert sched2.stats().get("resumes_rejected", 0) == 2
    # a reuse checkpoint offered to a schedule-less job -> clean restart
    sched3 = StepScheduler()
    fut3 = sched3.submit_request(
        tiny_pipe, prompt="tamper reuse", steps=6, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=31, resume=dict(ckpt))
    _, info3 = fut3.result(timeout=300)
    assert info3["resume_step"] == 0
    assert sched3.stats().get("resumes_rejected", 0) == 1
    sched.shutdown()
    sched2.shutdown()
    sched3.shutdown()


# ---------------------------------------------------------------------------
# the executor path (the stepper-off CI leg runs the solo-marked subset)
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.solo
def test_executor_serves_fewstep_job_end_to_end(monkeypatch, tmp_path):
    """A formatted lcm job runs through the real executor — lanes on
    (default) or off (the CI stepper-off leg sets CHIASWARM_STEPPER=0)
    — and produces a completed envelope with the collapsed step
    count. Proves the few-step family serves through WHICHEVER path
    the routing picks."""
    monkeypatch.setenv("SWARM_TPU_ROOT", str(tmp_path))
    import jax

    from chiaswarm_tpu.core.chip_pool import ChipPool
    from chiaswarm_tpu.core.mesh import MeshSpec
    from chiaswarm_tpu.node.executor import synchronous_do_work
    from chiaswarm_tpu.node.registry import ModelRegistry

    pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 1}),
                    devices=jax.devices()[:1])
    registry = ModelRegistry(
        catalog=[{"name": "tiny", "family": "tiny", "parameters": {}}],
        allow_random=True)
    job = {
        "id": "fewstep-e2e",
        "model_name": "tiny",
        "workflow": "txt2img",
        "prompt": "a fast fox",
        "num_inference_steps": 4,
        "guidance_scale": 1.0,
        "height": 64, "width": 64,
        "seed": 9,
        "content_type": "image/png",
        "parameters": {"scheduler_type": "LCMScheduler"},
    }
    result = synchronous_do_work(job, pool.slots[0], registry)
    cfg = result["pipeline_config"]
    assert cfg.get("error") is None, cfg
    assert cfg["scheduler"] == "lcm"
    assert cfg["steps"] == 4
    assert result["artifacts"]
