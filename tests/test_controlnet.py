"""ControlNet: model, pipeline integration, converter naming, workload path.

Reference behaviors covered: ControlNet loaded next to the pipeline and run
in the denoise hot loop (swarm/diffusion/diffusion_func.py:29-39,96), the
preprocessed-input echo artifact (:36-39), and the job_arguments rewiring
(swarm/job_arguments.py:116-124).
"""

import numpy as np
import pytest

from chiaswarm_tpu.pipelines import (
    Components,
    ControlNetBundle,
    DiffusionPipeline,
    GenerateRequest,
)


@pytest.fixture(scope="module")
def tiny_pipeline():
    return DiffusionPipeline(Components.random("tiny", seed=0))


@pytest.fixture(scope="module")
def tiny_controlnet():
    return ControlNetBundle.random("tiny", seed=1)


def _cond_image():
    rng = np.random.default_rng(7)
    return rng.integers(0, 255, (64, 64, 3), dtype=np.uint8)


def test_zero_init_controlnet_is_noop(tiny_pipeline, tiny_controlnet):
    """Freshly-initialized ControlNet has zero output convs: generation
    must match plain txt2img exactly (the zero-conv design invariant)."""
    base = GenerateRequest(prompt="a fox", steps=3, height=64, width=64,
                          seed=5, guidance_scale=5.0)
    plain, _ = tiny_pipeline(base)
    import dataclasses

    controlled, config = tiny_pipeline(dataclasses.replace(
        base, controlnet=tiny_controlnet, control_image=_cond_image()))
    assert np.array_equal(plain, controlled)
    assert config["controlnet"] == tiny_controlnet.model_name


def test_trained_controlnet_steers(tiny_pipeline, tiny_controlnet):
    """With non-zero output convs the residuals must change the image, and
    conditioning_scale=0 must recover the uncontrolled output without
    recompiling (scale is traced)."""
    import jax

    # fabricate "trained" zero convs: bump every controlnet head kernel
    params = jax.tree.map(lambda x: x, tiny_controlnet.params)  # copy

    def bump(tree):
        return jax.tree.map(lambda x: x + 0.05, tree)

    net = dict(params["net"]["params"])
    for key in list(net):
        if key.startswith("controlnet_"):
            net[key] = bump(net[key])
    params["net"] = {"params": net}
    trained = ControlNetBundle(family=tiny_controlnet.family,
                               model_name="trained/controlnet",
                               params=params)

    base = GenerateRequest(prompt="a fox", steps=3, height=64, width=64,
                          seed=5, guidance_scale=5.0)
    plain, _ = tiny_pipeline(base)
    import dataclasses

    steered, _ = tiny_pipeline(dataclasses.replace(
        base, controlnet=trained, control_image=_cond_image()))
    assert not np.array_equal(plain, steered)

    from chiaswarm_tpu.core.compile_cache import GLOBAL_CACHE

    before = GLOBAL_CACHE.executables.stats["misses"]
    zeroed, _ = tiny_pipeline(dataclasses.replace(
        base, controlnet=trained, control_image=_cond_image(),
        control_scale=0.0))
    assert GLOBAL_CACHE.executables.stats["misses"] == before
    assert np.array_equal(plain, zeroed)


def test_controlnet_requires_cond_image(tiny_pipeline, tiny_controlnet):
    with pytest.raises(ValueError, match="conditioning image"):
        tiny_pipeline(GenerateRequest(prompt="x", steps=2, height=64,
                                      width=64, controlnet=tiny_controlnet))


def test_convert_controlnet_naming():
    """Torch-layout ControlNetModel keys land on the bundle's param paths."""
    from chiaswarm_tpu.convert.torch_to_flax import convert_controlnet
    from chiaswarm_tpu.models.configs import FAMILIES

    cfg = FAMILIES["tiny"].unet
    state = {
        "controlnet_cond_embedding.conv_in.weight": np.zeros((16, 3, 3, 3)),
        "controlnet_cond_embedding.conv_in.bias": np.zeros((16,)),
        "controlnet_cond_embedding.blocks.0.weight": np.zeros((16, 16, 3, 3)),
        "controlnet_cond_embedding.conv_out.weight": np.zeros((32, 256, 3, 3)),
        "controlnet_down_blocks.0.weight": np.zeros((32, 32, 1, 1)),
        "controlnet_down_blocks.0.bias": np.zeros((32,)),
        "controlnet_mid_block.weight": np.zeros((64, 64, 1, 1)),
        "conv_in.weight": np.zeros((32, 4, 3, 3)),
        "time_embedding.linear_1.weight": np.zeros((128, 32)),
        "down_blocks.0.resnets.0.conv1.weight": np.zeros((32, 32, 3, 3)),
        "mid_block.resnets.0.conv1.weight": np.zeros((64, 64, 3, 3)),
    }
    out = convert_controlnet(state, cfg)
    embed = out["embed"]["params"]
    net = out["net"]["params"]
    assert embed["conv_in"]["kernel"].shape == (3, 3, 3, 16)
    assert embed["blocks_0"]["kernel"].shape == (3, 3, 16, 16)
    assert embed["conv_out"]["kernel"].shape == (3, 3, 256, 32)
    assert net["controlnet_down_blocks_0"]["kernel"].shape == (1, 1, 32, 32)
    assert net["controlnet_mid_block"]["kernel"].shape == (1, 1, 64, 64)
    assert net["conv_in"]["kernel"].shape == (3, 3, 4, 32)
    assert net["time_embedding"]["linear_1"]["kernel"].shape == (32, 128)
    assert net["down_0_resnets_0"]["conv1"]["kernel"].shape == (3, 3, 32, 32)
    assert net["mid_resnets_0"]["conv1"]["kernel"].shape == (3, 3, 64, 64)


@pytest.mark.slow
def test_controlnet_residual_count_matches_unet_skips(tiny_controlnet):
    """The control branch must emit exactly one residual per UNet skip."""
    import jax
    import jax.numpy as jnp

    from chiaswarm_tpu.models.configs import FAMILIES
    from chiaswarm_tpu.models.controlnet import (
        ControlCondEmbedding,
        ControlNet,
    )

    fam = FAMILIES["tiny"]
    cfg = fam.unet
    net = ControlNet(cfg)
    embed = ControlCondEmbedding(cfg.block_out_channels[0],
                                 downscale=fam.vae.downscale)
    f = fam.vae.downscale
    latent = jnp.zeros((1, 8, 8, cfg.sample_channels))
    cond = jnp.zeros((1, 8 * f, 8 * f, 3))
    ctx = jnp.zeros((1, 77, cfg.cross_attention_dim))
    cond_emb = embed.apply(tiny_controlnet.params["embed"], cond)
    down, mid = net.apply(tiny_controlnet.params["net"], latent,
                          jnp.zeros((1,)), ctx, cond_emb)
    n_levels = len(cfg.block_out_channels)
    expected = 1 + n_levels * cfg.layers_per_block + (n_levels - 1)
    assert len(down) == expected
    assert mid.shape[-1] == cfg.block_out_channels[-1]


@pytest.mark.slow
def test_workload_controlnet_echo_artifact():
    """diffusion_callback with controlnet_model_name: conditioning steers a
    txt2img pass and the preprocessed input echoes back as an artifact."""
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.workloads.diffusion import diffusion_callback

    registry = ModelRegistry(catalog=[], allow_random=True)
    artifacts, config = diffusion_callback(
        "slot0", "random/tiny", seed=3, registry=registry,
        prompt="a bridge", num_inference_steps=2, height=64, width=64,
        image=_cond_image(),
        controlnet_model_name="random/controlnet-tiny",
        save_preprocessed_input=True,
    )
    assert "primary" in artifacts
    assert "preprocessed_input" in artifacts
    assert config["mode"] == "txt2img"  # control image is NOT an init image
    assert config["controlnet"] == "random/controlnet-tiny"
