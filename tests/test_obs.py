"""swarmscope suite (ISSUE 4): metrics registry semantics, Prometheus
exposition, span-tree construction across threads, trace-ring eviction,
the worker's /metrics + /debug/traces endpoints, and the end-to-end
acceptance gate: a tiny txt2img job through a REAL worker — stepper
opted out and on (the ISSUE-7 default) — must yield a trace whose span
tree nests
poll/execute/encode/step/decode/upload with positive durations,
exported as Perfetto-loadable JSON.
"""

from __future__ import annotations

import asyncio
import json
import threading

import pytest

from chiaswarm_tpu.obs import metrics as obs_metrics
from chiaswarm_tpu.obs import trace as obs_trace
from chiaswarm_tpu.obs.metrics import Registry, render_all
from chiaswarm_tpu.obs.trace import JobTrace, TraceRing, span


@pytest.fixture(autouse=True)
def _tmp_root(tmp_path, monkeypatch):
    monkeypatch.setenv("SWARM_TPU_ROOT", str(tmp_path))
    return tmp_path


@pytest.fixture(autouse=True)
def _restore_matmul_precision():
    """Worker.startup() pins bf16 matmuls; restore the suite default."""
    import jax

    before = jax.config.jax_default_matmul_precision
    yield
    jax.config.update("jax_default_matmul_precision", before)


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------


def test_registry_counter_gauge_histogram_semantics():
    reg = Registry()
    jobs = reg.counter("jobs_total", "jobs", labelnames=("outcome",))
    jobs.inc(outcome="ok")
    jobs.inc(2, outcome="ok")
    jobs.inc(outcome="error")
    assert jobs.value(outcome="ok") == 3
    assert jobs.value(outcome="error") == 1
    assert jobs.value(outcome="never") == 0
    with pytest.raises(ValueError):
        jobs.inc(-1, outcome="ok")  # counters only go up
    with pytest.raises(ValueError):
        jobs.inc(bogus="label")  # undeclared label set

    depth = reg.gauge("queue_depth", "depth")
    depth.set(7)
    depth.dec(3)
    assert depth.value() == 4

    lat = reg.histogram("lat_seconds", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.7, 5.0, 50.0):
        lat.observe(v)
    assert lat.count() == 5
    assert lat.sum() == pytest.approx(56.25)

    # get-or-create: same object back; type/label mismatch raises
    assert reg.counter("jobs_total", labelnames=("outcome",)) is jobs
    with pytest.raises(ValueError):
        reg.gauge("jobs_total")
    with pytest.raises(ValueError):
        reg.counter("jobs_total", labelnames=("other",))

    # set_to mirrors an external monotonic total and never regresses
    done = reg.counter("done_total")
    done.set_to(10)
    done.set_to(4)
    assert done.value() == 10


def test_registry_collectors_run_at_scrape_time_and_never_raise():
    reg = Registry()
    calls = []

    def good():
        calls.append("good")
        reg.gauge("live").set(len(calls))

    def broken():
        raise RuntimeError("mirror cracked")

    reg.add_collector(good)
    reg.add_collector(broken)
    reg.render()
    snap = reg.snapshot()
    assert calls == ["good", "good"]  # once per scrape, errors contained
    assert snap["live"]["values"][""] == 2


def test_prometheus_exposition_format():
    reg = Registry()
    c = reg.counter("swarm_jobs_total", 'jobs with "quotes"\nand newline',
                    labelnames=("model",))
    c.inc(3, model='tiny "v1"\n')
    reg.gauge("swarm_depth", "queue depth").set(2.5)
    h = reg.histogram("swarm_lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(9.0)
    body = reg.render()
    lines = body.splitlines()
    assert "# TYPE swarm_jobs_total counter" in lines
    # label values escape quotes and newlines per the text format
    assert 'swarm_jobs_total{model="tiny \\"v1\\"\\n"} 3' in lines
    assert "# HELP swarm_jobs_total jobs with \"quotes\"\\nand newline" \
        in lines
    assert "swarm_depth 2.5" in lines
    # histogram: cumulative le buckets, +Inf == count, sum present
    assert 'swarm_lat_seconds_bucket{le="0.1"} 1' in lines
    assert 'swarm_lat_seconds_bucket{le="1"} 2' in lines
    assert 'swarm_lat_seconds_bucket{le="+Inf"} 3' in lines
    assert "swarm_lat_seconds_count 3" in lines
    assert body.endswith("\n")

    # an unlabeled counter renders an explicit 0 from registration; a
    # labeled one renders its TYPE header even before any sample
    reg2 = Registry()
    reg2.counter("zero_total", "nothing yet")
    reg2.counter("labeled_total", "nothing yet", labelnames=("tag",))
    body2 = reg2.render()
    assert "zero_total 0" in body2
    assert "# TYPE labeled_total counter" in body2
    # merged scrape bodies concatenate cleanly
    merged = render_all([reg, reg2])
    assert "swarm_depth 2.5" in merged and "zero_total 0" in merged


# ---------------------------------------------------------------------------
# span trees + ring
# ---------------------------------------------------------------------------


def test_span_tree_nesting_and_ordering_across_threads():
    """The worker's cross-thread shape, faked: phases open on the event
    -loop side, pipeline spans attach from an executor thread via
    activate(), and the finished tree nests in submission order."""
    trace = JobTrace("job", id="fake-1", model="tiny")
    trace.phase("poll")

    def executor_thread():
        with obs_trace.activate(trace):
            with span("format"):
                pass
            with span("encode", batch=1):
                with span("tokenize"):
                    pass
            with span("step", steps=2):
                pass
            with span("decode"):
                pass

    trace.phase("execute")
    worker = threading.Thread(target=executor_thread)
    worker.start()
    worker.join()
    trace.phase("upload")
    ring = TraceRing(capacity=4)
    trace.finish(ring)
    trace.finish(ring)  # idempotent: one ring entry
    assert len(ring) == 1

    root = trace.root
    assert [c.name for c in root.children] == ["poll", "execute", "upload"]
    execute = root.children[1]
    assert [c.name for c in execute.children] == \
        ["format", "encode", "step", "decode"]
    assert [c.name for c in execute.find("encode").children] == ["tokenize"]
    for name in ("poll", "execute", "encode", "step", "decode", "upload"):
        node = root.find(name)
        assert node is not None and not node.open
        assert node.duration_s > 0
    # phases close their predecessor: no overlap leaks
    assert root.children[0].t1 <= root.children[1].t0 + 1e-9

    # chrome export: complete events, positive integer durations
    events = trace.to_chrome_events(tid=3)
    names = [e["name"] for e in events]
    assert names[0] == "job" and "tokenize" in names
    for event in events:
        assert event["ph"] == "X"
        assert isinstance(event["ts"], int)
        assert event["dur"] >= 1
        assert event["tid"] == 3
    # the whole document is JSON-serializable as exported
    json.dumps(ring.to_chrome())


def test_span_outside_any_trace_is_detached_and_harmless():
    with span("orphan") as orphan:
        pass
    assert orphan.duration_s > 0
    assert obs_trace.current_span() is None


def test_trace_ring_eviction_keeps_newest():
    ring = TraceRing(capacity=3)
    for i in range(5):
        trace = JobTrace("job", id=f"t{i}")
        trace.finish(ring)
    assert len(ring) == 3
    kept = [t.meta["id"] for t in ring.traces()]
    assert kept == ["t2", "t3", "t4"]
    chrome = ring.to_chrome()
    assert len(chrome["traceEvents"]) == 3
    # tree export carries the metadata
    tree = ring.to_dicts()
    assert tree[0]["root"]["meta"]["id"] == "t2"
    assert "started_at_unix" in tree[0]

    # eviction accounting + the ?since= cursor (ISSUE 13 satellite):
    # 5 pushed into capacity 3 evicts 2 root-only traces (2 spans); the
    # cursor exposes the gap a slow scraper must detect
    assert ring.traces_evicted == 2 and ring.spans_evicted == 2
    cursor = ring.cursor()
    assert cursor["last_seq"] == 5 and cursor["oldest_seq"] == 3
    assert cursor["evicted_spans"] == 2
    assert [t.meta["id"] for t in ring.traces(since=3)] == ["t3", "t4"]
    assert [t["seq"] for t in ring.to_dicts(since=3)] == [4, 5]
    assert ring.to_chrome(since=5)["traceEvents"] == []


def test_trace_rides_job_dicts_via_attach_detach():
    job = {"id": "x"}
    trace = JobTrace("job", id="x")
    obs_trace.attach(job, trace)
    assert obs_trace.job_trace(job) is trace
    assert obs_trace.detach(job) is trace
    assert obs_trace.TRACE_KEY not in job
    assert obs_trace.detach(job) is None
    assert obs_trace.job_trace(None) is None


# ---------------------------------------------------------------------------
# profiler hooks (unit level; the capture endpoint is covered below)
# ---------------------------------------------------------------------------


def test_profiler_capture_and_job_profile_with_stub_backend(
        tmp_path, monkeypatch):
    from chiaswarm_tpu.core import compat
    from chiaswarm_tpu.obs import profiling

    calls = []
    monkeypatch.setitem(compat._cache, "profiler_start_trace",
                        lambda target: calls.append(("start", target)))
    monkeypatch.setitem(compat._cache, "profiler_stop_trace",
                        lambda: calls.append(("stop",)))
    out = profiling.capture(0.01, out=str(tmp_path / "prof"))
    assert out["status"] == "ok"
    assert calls[0][0] == "start" and calls[-1] == ("stop",)
    assert out["dir"].startswith(str(tmp_path / "prof"))

    class StubTrace:
        def __init__(self, target):
            calls.append(("job", target))

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

    monkeypatch.setitem(compat._cache, "profiler_trace", StubTrace)
    monkeypatch.setenv(profiling.PROFILE_DIR_ENV, str(tmp_path / "jobs"))
    with profiling.job_profile("job-7") as active:
        assert active is True
    assert calls[-1] == ("job", str(tmp_path / "jobs" / "job-7"))

    monkeypatch.delenv(profiling.PROFILE_DIR_ENV)
    with profiling.job_profile("job-8") as active:
        assert active is False  # opt-in: no dir, no trace
    assert profiling.capture(0.01)["status"] == "error"  # no dir either


# ---------------------------------------------------------------------------
# worker endpoints (/metrics, /debug/traces, /debug/profile, /healthz)
# ---------------------------------------------------------------------------


def _endpoint_settings(uri: str):
    from chiaswarm_tpu.node.settings import Settings

    return Settings(
        hive_uri=uri, hive_token="t", worker_name="obs-worker",
        health_bind_ephemeral=True, install_signal_handlers=False,
        job_deadline_s=600.0, poll_busy_s=0.02, poll_idle_s=0.05,
        poll_backoff_base_s=0.02, poll_backoff_cap_s=0.1,
        upload_retries=2, upload_retry_delay_s=0.01,
        drain_timeout_s=5.0, result_drain_timeout_s=5.0)


def test_worker_serves_metrics_and_traces_endpoints():
    """The health app (loopback) grows /metrics (Prometheus text,
    resilience + stepper + compile-cache families), /debug/traces
    (Perfetto JSON from the worker's ring), and /debug/profile
    (validated, explicit errors) — while /healthz keeps its JSON keys
    as the read-through view."""
    import aiohttp

    from chiaswarm_tpu.node.chaos import ChaoticExecutor, ChaoticHive
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.node.worker import Worker

    class StubSlot:
        depth = 2
        data_width = 1

        def descriptor(self):
            return "stub"

    async def scenario():
        hive = ChaoticHive()
        uri = await hive.start()
        hive.submit({"id": "m-ok", "model_name": "m/ok", "prompt": "p",
                     "content_type": "application/json"})
        hive.submit({"id": "m-err", "model_name": "m/err", "prompt": "p",
                     "chaos": ["crash"],
                     "content_type": "application/json"})
        worker = Worker(settings=_endpoint_settings(uri),
                        pool=[StubSlot()],
                        registry=ModelRegistry(catalog=[],
                                               allow_random=True),
                        executor=ChaoticExecutor())
        task = asyncio.create_task(worker.run())
        try:
            await hive.wait_for_results(2, timeout=30)
            for _ in range(100):
                if getattr(worker, "health_address", None):
                    break
                await asyncio.sleep(0.05)
            host, port = worker.health_address
            base = f"http://{host}:{port}"
            async with aiohttp.ClientSession() as session:
                async with session.get(f"{base}/healthz") as resp:
                    health = await resp.json()
                async with session.get(f"{base}/metrics") as resp:
                    assert resp.status == 200
                    assert resp.headers["Content-Type"].startswith(
                        "text/plain")
                    metrics_body = await resp.text()
                async with session.get(f"{base}/debug/traces") as resp:
                    chrome = await resp.json()
                async with session.get(
                        f"{base}/debug/traces?format=tree") as resp:
                    tree = await resp.json()
                # ISSUE 13 satellite: the ?since= scrape cursor — a
                # caught-up scraper gets zero traces back, a bad value
                # is an explicit 400, and the cursor block carries the
                # eviction counters gap detection needs
                async with session.get(f"{base}/debug/traces"
                                       f"?format=tree&since=0") as resp:
                    tree_since = await resp.json()
                last_seq = tree_since["cursor"]["last_seq"]
                async with session.get(
                        f"{base}/debug/traces?format=tree"
                        f"&since={last_seq}") as resp:
                    tree_tail = await resp.json()
                async with session.get(
                        f"{base}/debug/traces?since=abc") as resp:
                    assert resp.status == 400
                async with session.get(
                        f"{base}/debug/profile?seconds=abc") as resp:
                    assert resp.status == 400
                async with session.get(
                        f"{base}/debug/profile?seconds=0.2") as resp:
                    # no CHIASWARM_PROFILE_DIR and no ?dir= -> explicit
                    # error, never a crash
                    assert resp.status == 500
                    assert (await resp.json())["status"] == "error"
                # swarmlens (ISSUE 11): the numerics flight-recorder view
                async with session.get(f"{base}/debug/numerics") as resp:
                    assert resp.status == 200
                    numerics_payload = await resp.json()
                async with session.get(
                        f"{base}/debug/numerics?limit=abc") as resp:
                    assert resp.status == 400
        finally:
            worker.request_stop()
            await asyncio.wait_for(task, timeout=20)
            await hive.stop()
        return (health, metrics_body, chrome, tree, tree_since,
                tree_tail, numerics_payload, worker)

    (health, body, chrome, tree, tree_since, tree_tail,
     numerics_payload, worker) = asyncio.run(scenario())

    # the scrape cursor (ISSUE 13): since=0 returns both traces with
    # their ring seqs; since=last returns none; nothing evicted yet so
    # the counter reads zero and the oldest seq is still 1
    assert len(tree_since["traces"]) == 2
    assert [t["seq"] for t in tree_since["traces"]] == [1, 2]
    assert tree_since["cursor"]["last_seq"] == 2
    assert tree_since["cursor"]["oldest_seq"] == 1
    assert tree_since["cursor"]["evicted_spans"] == 0
    assert tree_tail["traces"] == []
    assert tree_tail["cursor"]["last_seq"] == 2

    # /debug/numerics: the payload distinguishes "empty because taps are
    # off" from "empty because nothing recorded" — CHIASWARM_NUMERICS is
    # unset in the suite, so enabled=False and the ring is bounded+empty
    assert numerics_payload["enabled"] is False
    assert numerics_payload["records"] == []
    assert numerics_payload["ring"]["capacity"] >= 1
    assert "traced_probes" in numerics_payload
    # the measured hang-budget suggestion rides /healthz guard (ISSUE
    # 11 satellite): with no lane steps yet it reports measured=False
    # and the CURRENT prior knobs, never invented numbers
    suggestion = health["guard"]["suggested_hang_budget"]
    assert suggestion["measured"] in (False, True)
    assert "current" in suggestion

    # /healthz read-through view unchanged (PR-2/PR-3 keys intact)
    for key in ("jobs_failed", "jobs_retried", "results_dead_lettered",
                "breakers", "dead_letter_depth", "stepper"):
        assert key in health
    assert health["jobs_failed"] == 1

    # /metrics: resilience counters migrated onto the registry...
    assert "chiaswarm_jobs_failed_total 1" in body
    assert 'chiaswarm_jobs_total{outcome="error"} 1' in body
    assert 'chiaswarm_jobs_total{outcome="ok"} 1' in body
    # ...stepper-lane families (lanes are default-ON since ISSUE 7)...
    assert "chiaswarm_stepper_steps_executed_total" in body
    assert "chiaswarm_stepper_enabled 1" in body
    # ...the adaptive-width control-loop families (ISSUE 7): resize
    # actions by direction, the arrival-rate demand gauge, and the
    # per-workload admission breadth — all present from scrape one
    # (values are process-cumulative, so assert the series, not 0)
    assert "# TYPE chiaswarm_stepper_lane_resizes_total counter" in body
    assert 'chiaswarm_stepper_lane_resizes_total{direction="grow"}' in body
    assert ('chiaswarm_stepper_lane_resizes_total{direction="shrink"}'
            in body)
    assert "# TYPE chiaswarm_stepper_arrival_rate gauge" in body
    assert ("# TYPE chiaswarm_stepper_lane_admissions_total counter"
            in body)
    for workload in ("txt2img", "img2img", "inpaint", "controlnet"):
        assert (f'chiaswarm_stepper_lane_admissions_total'
                f'{{workload="{workload}"}}' in body), workload
    # ...lease/checkpoint/resume families (ISSUE 6) exist from scrape
    # one, even before any fleet event — dashboards need the zeroes...
    assert "chiaswarm_lease_heartbeats_total 0" in body
    assert "chiaswarm_leases_lost_total 0" in body
    assert "chiaswarm_checkpoints_written_total 0" in body
    assert "chiaswarm_checkpoints_corrupt_total 0" in body
    assert "chiaswarm_checkpoint_depth 0" in body
    assert "chiaswarm_inflight_jobs 0" in body
    assert "chiaswarm_stepper_rows_resumed_total 0" in body
    assert "# TYPE chiaswarm_stepper_resume_step histogram" in body
    # ...HBM residency families (ISSUE 8, serving/residency.py): every
    # label vocabulary pre-seeded to zero from scrape one...
    assert "# TYPE chiaswarm_residency_resident_bytes gauge" in body
    assert "chiaswarm_residency_budget_bytes" in body
    assert "chiaswarm_residency_peak_bytes" in body
    assert "chiaswarm_residency_bounces_total" in body
    from chiaswarm_tpu.obs.metrics import (
        RESIDENCY_EVICT_REASONS,
        RESIDENCY_LOAD_MODES,
        RESIDENCY_STATES,
    )

    for state in RESIDENCY_STATES:
        assert f'chiaswarm_residency_models{{state="{state}"}}' in body
    for reason in RESIDENCY_EVICT_REASONS:
        assert (f'chiaswarm_residency_evictions_total{{reason="{reason}"}}'
                in body)
    for mode in RESIDENCY_LOAD_MODES:
        assert (f'chiaswarm_residency_loads_total{{mode="{mode}"}}'
                in body)
    assert "# TYPE chiaswarm_residency_load_seconds histogram" in body
    # ...overload-control families (ISSUE 9, node/overload.py): the
    # shed/backpressure counters live on the worker registry DISTINCT
    # from the failure counters, pre-seeded from scrape one...
    assert "chiaswarm_jobs_shed_total 0" in body
    assert "chiaswarm_polls_backpressured_total 0" in body
    assert "chiaswarm_overload_state 0" in body
    assert "chiaswarm_overload_admission_cap 0" in body
    assert "chiaswarm_overload_backpressure_waits_total 0" in body
    assert ("# TYPE chiaswarm_overload_predicted_wait_seconds histogram"
            in body)
    for workload in ("txt2img", "img2img", "inpaint", "controlnet"):
        assert (f'chiaswarm_overload_shed_total{{workload="{workload}"}} 0'
                in body), workload
    assert "overload" in health and health["overload"]["state"] == "normal"
    # ...swarmguard families (ISSUE 10, serving/guard.py): hang/rung
    # counters pre-seeded across their vocabularies, the condemned-lane
    # and quarantine series at zero, the health/invalid families
    # declared — all from scrape one, before any gray failure...
    from chiaswarm_tpu.serving.guard import HANG_PHASES, HEAL_RUNGS

    for phase in HANG_PHASES:
        assert f'chiaswarm_guard_hangs_total{{phase="{phase}"}} 0' \
            in body, phase
    for rung in HEAL_RUNGS:
        assert f'chiaswarm_guard_heal_rung_total{{rung="{rung}"}} 0' \
            in body, rung
    assert "chiaswarm_guard_condemned_lanes_total 0" in body
    assert "chiaswarm_guard_quarantined_devices 0" in body
    assert "# TYPE chiaswarm_guard_invalid_outputs_total counter" in body
    assert "# TYPE chiaswarm_guard_device_health gauge" in body
    assert "chiaswarm_stepper_lanes_condemned_total 0" in body
    assert "chiaswarm_stepper_rows_invalid_total 0" in body
    # ...step-collapse families (ISSUE 12, swarmturbo): UNet evals by
    # mode, DeepCache-skipped steps, and the per-image full-eval
    # histogram — label vocabularies pre-seeded, series process-
    # cumulative (other suites may have stepped lanes already, so
    # assert presence, not zero, for the mode-labeled counter)...
    from chiaswarm_tpu.obs.metrics import STEPPER_UNET_EVAL_MODES

    assert "# TYPE chiaswarm_stepper_unet_evals_total counter" in body
    for mode in STEPPER_UNET_EVAL_MODES:
        assert (f'chiaswarm_stepper_unet_evals_total{{mode="{mode}"}}'
                in body), mode
    assert "# TYPE chiaswarm_stepper_steps_skipped_total counter" in body
    assert "chiaswarm_stepper_steps_skipped_total" in body
    assert ("# TYPE chiaswarm_stepper_unet_evals_per_image histogram"
            in body)
    assert "guard" in health and health["guard"]["enabled"] is True
    assert health["guard"]["restart_requested"] is False
    assert "chips_in_service" in health
    # ...compile-cache + hive families from the process registry...
    assert "chiaswarm_compile_cache_misses_total" in body
    assert "# TYPE chiaswarm_compiles_total counter" in body
    assert 'chiaswarm_hive_requests_total{endpoint="results",result="ok"}' \
        in body
    # ...the trace-ring eviction counter (ISSUE 13 satellite): present
    # at zero from scrape one so a scraper can alert on span loss...
    assert "chiaswarm_trace_spans_evicted_total 0" in body
    # ...swarmdurable families (ISSUE 14): the dead-letter replay
    # counter split by moment (live = hive healed mid-run, startup =
    # the PR-2 worker-restart path) and the hive-session outage gauge —
    # vocabularies pre-seeded from scrape one, and the healthy run
    # above replayed nothing...
    from chiaswarm_tpu.obs.metrics import DEAD_LETTER_REPLAY_WHEN

    for when in DEAD_LETTER_REPLAY_WHEN:
        assert (f'chiaswarm_dead_letter_replayed_total{{when="{when}"}} 0'
                in body), when
    assert "chiaswarm_hive_session_state 0" in body
    assert "chiaswarm_hive_outages_total 0" in body
    assert "chiaswarm_leases_assumed_lost_total 0" in body
    assert health["hive_session"]["state"] == "online"
    assert health["hive_epoch"] is None  # journal-less reference hive
    # ...swarmfed families (ISSUE 17): the per-shard half of the
    # session signal — one series per configured shard (a plain
    # hive_uri is shard 0 of 1), zeroed from scrape one so a
    # dashboard can tell "shard outage" from "series missing"...
    assert "# TYPE chiaswarm_hive_shard_session_state gauge" in body
    assert 'chiaswarm_hive_shard_session_state{shard="0"} 0' in body
    # ...phase latency histograms fed by the finished traces
    assert 'chiaswarm_job_phase_seconds_bucket{phase="upload",le="+Inf"}' \
        in body

    # /debug/traces: Perfetto-loadable chrome events with worker phases
    names = {e["name"] for e in chrome["traceEvents"]}
    assert {"job", "poll", "execute", "upload"} <= names
    assert {t["root"]["name"] for t in tree["traces"]} == {"job"}
    assert len(worker.traces) == 2


def test_federation_front_metric_families_preseeded():
    """swarmfed (ISSUE 17): the federation front's scrape body carries
    the per-shard depth/epoch/leased gauges zeroed for EVERY shard and
    each shard's steal/forward counters pre-seeded — all before any
    job, poll, or steal, so fleet dashboards see the full shard
    vocabulary from scrape one."""
    from chiaswarm_tpu.node.federation import FederatedHive

    fed = FederatedHive(n_shards=3, lease_s=30.0)
    body = render_all([fed.metrics]
                      + [shard.metrics for shard in fed.shards])

    assert "# TYPE chiaswarm_hive_shard_depth gauge" in body
    assert "# TYPE chiaswarm_hive_shard_epoch gauge" in body
    assert "# TYPE chiaswarm_hive_shard_leased gauge" in body
    for index in range(3):
        assert f'chiaswarm_hive_shard_depth{{shard="{index}"}} 0' \
            in body, index
        assert f'chiaswarm_hive_shard_epoch{{shard="{index}"}} 0' \
            in body, index
        assert f'chiaswarm_hive_shard_leased{{shard="{index}"}} 0' \
            in body, index
    # each shard pre-seeds its steal counter with the self-pair and
    # its forwarded-upload counter at zero
    assert "# TYPE chiaswarm_hive_steals_total counter" in body
    for index in range(3):
        assert (f'chiaswarm_hive_steals_total{{from="{index}",'
                f'to="{index}"}} 0' in body), index
    assert "chiaswarm_hive_shard_forwarded_uploads_total 0" in body


def test_planner_metric_families_preseeded_at_import():
    """swarmplan (ISSUE 19): importing the planner module pre-seeds
    every ``chiaswarm_planner_*`` family on the GLOBAL registry — the
    two fleet-size gauges at zero and the decisions counter carrying
    the full direction x reason label vocabulary — so a dashboard
    scraping /metrics sees the complete planner surface before the
    first planning tick ever runs."""
    import chiaswarm_tpu.node.planner  # noqa: F401  (import = pre-seed)
    from chiaswarm_tpu.obs.metrics import (
        PLANNER_DIRECTIONS,
        PLANNER_REASONS,
        REGISTRY,
    )

    body = render_all([REGISTRY])
    assert "# TYPE chiaswarm_planner_target_workers gauge" in body
    assert "# TYPE chiaswarm_planner_actual_workers gauge" in body
    assert "# TYPE chiaswarm_planner_decisions_total counter" in body
    assert "# TYPE chiaswarm_planner_placement_moves_total counter" \
        in body
    assert "# TYPE chiaswarm_planner_worker_hours_total counter" in body
    assert "chiaswarm_planner_target_workers 0" in body
    assert "chiaswarm_planner_actual_workers 0" in body
    # attached planners bind per-hive registries, so the global series
    # stay zeroed — and the whole label vocabulary is present
    for direction in PLANNER_DIRECTIONS:
        for reason in PLANNER_REASONS:
            assert (f'chiaswarm_planner_decisions_total{{'
                    f'direction="{direction}",reason="{reason}"}} 0'
                    in body), (direction, reason)
    assert "chiaswarm_planner_placement_moves_total 0" in body
    assert "chiaswarm_planner_worker_hours_total 0" in body


def test_fleet_endpoint_schema_from_heartbeat_scrape():
    """ISSUE 13 satellite: a heartbeating worker's metric snapshot
    lands in ``GET /api/fleet`` with the schema the item-5 autoscaler
    reads — per-worker demand/supply/state plus the hive aggregate."""
    import time as _time

    import aiohttp

    from chiaswarm_tpu.node.chaos import ChaoticExecutor
    from chiaswarm_tpu.node.minihive import MiniHive
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.node.settings import Settings
    from chiaswarm_tpu.node.worker import Worker

    class StubSlot:
        depth = 2
        data_width = 1

        def descriptor(self):
            return "stub"

    async def scenario():
        hive = MiniHive(lease_s=30.0, delay_s=0.01)
        uri = await hive.start()
        hive.submit({"id": "fleet-1", "model_name": "m/ok",
                     "prompt": "p", "workflow": "txt2img",
                     "content_type": "application/json"})
        worker = Worker(
            settings=Settings(
                hive_uri=uri, hive_token="t", worker_name="fleet-obs",
                install_signal_handlers=False, heartbeat_s=0.05,
                poll_busy_s=0.02, poll_idle_s=0.04,
                drain_timeout_s=5.0, result_drain_timeout_s=5.0),
            pool=[StubSlot()],
            registry=ModelRegistry(catalog=[], allow_random=True),
            executor=ChaoticExecutor())
        task = asyncio.create_task(worker.run())
        try:
            await hive.wait_for_results(1, timeout=30)
            deadline = _time.monotonic() + 30
            while "fleet-obs" not in hive.fleet and \
                    _time.monotonic() < deadline:
                await asyncio.sleep(0.02)
            async with aiohttp.ClientSession() as session:
                async with session.get(f"{hive.uri}/api/fleet") as resp:
                    assert resp.status == 200
                    snap = await resp.json()
        finally:
            worker.request_stop()
            await asyncio.wait_for(task, timeout=20)
            await hive.stop()
        return snap

    snap = asyncio.run(scenario())
    assert set(snap) == {"at_s", "workers", "aggregate"}
    entry = snap["workers"]["fleet-obs"]
    for key in ("queue_depth", "inflight_jobs", "jobs_done", "jobs_shed",
                "chips_in_service", "overload", "age_s", "live",
                "partitioned", "leased_jobs"):
        assert key in entry, key
    assert entry["live"] is True and entry["partitioned"] is False
    assert set(entry["overload"]) == {"state", "sheds_total",
                                      "service_ewma_s"}
    aggregate = snap["aggregate"]
    for key in ("workers_reporting", "workers_live", "chips_in_service",
                "arrival_rate_rows_s", "lane_occupancy_mean",
                "queue_depth", "inflight_jobs", "jobs_done", "jobs_shed",
                "workers_in_brownout", "observed_arrival_jobs_s",
                "pending_jobs", "leased_jobs", "completed_jobs",
                "abandoned_jobs"):
        assert key in aggregate, key
    assert aggregate["workers_reporting"] == 1
    assert aggregate["completed_jobs"] == 1
    json.dumps(snap)


# ---------------------------------------------------------------------------
# acceptance: end-to-end tiny txt2img, stepper off AND on
# ---------------------------------------------------------------------------


def _run_tiny_job_and_get_trace(stepper: bool, monkeypatch, seed: int):
    import sys

    sys.path.insert(0, "tests")
    from fake_hive import FakeHive

    import jax

    from chiaswarm_tpu.core.chip_pool import ChipPool
    from chiaswarm_tpu.core.mesh import MeshSpec
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.node.worker import Worker

    # lanes are default-on (ISSUE 7): the off leg must opt OUT explicitly
    monkeypatch.setenv("CHIASWARM_STEPPER", "1" if stepper else "0")
    registry = ModelRegistry(
        catalog=[{"name": "tiny", "family": "tiny", "parameters": {}}],
        allow_random=True)
    pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 1}),
                    devices=jax.devices()[:1])

    async def scenario():
        hive = FakeHive()
        uri_settings = None
        await hive.start()
        hive.jobs.append({
            "id": f"e2e-{'lane' if stepper else 'solo'}",
            "model_name": "tiny", "prompt": "an observable astronaut",
            "seed": seed, "num_inference_steps": 2, "guidance_scale": 7.5,
            "height": 64, "width": 64, "content_type": "image/png"})
        uri_settings = _endpoint_settings(hive.uri)
        worker = Worker(settings=uri_settings, registry=registry,
                        pool=pool)
        task = asyncio.create_task(worker.run())
        try:
            await hive.wait_for_results(1, timeout=300)
            for _ in range(100):
                if getattr(worker, "health_address", None):
                    break
                await asyncio.sleep(0.05)
            host, port = worker.health_address
            import aiohttp

            async with aiohttp.ClientSession() as session:
                async with session.get(
                        f"http://{host}:{port}/debug/traces") as resp:
                    chrome = await resp.json()
                async with session.get(
                        f"http://{host}:{port}/metrics") as resp:
                    metrics_body = await resp.text()
        finally:
            worker.request_stop()
            await asyncio.wait_for(task, timeout=30)
            await hive.stop()
        return hive.results, worker, chrome, metrics_body

    results, worker, chrome, metrics_body = asyncio.run(scenario())
    assert len(results) == 1
    assert results[0]["pipeline_config"].get("error") is None, results
    traces = worker.traces.traces()
    assert len(traces) == 1
    return traces[0], chrome, metrics_body


@pytest.mark.parametrize("stepper", [False, True],
                         ids=["stepper-off", "stepper-on"])
def test_e2e_tiny_txt2img_trace_spans(stepper, monkeypatch):
    """ISSUE 4 acceptance: the finished job's trace contains
    poll/execute/encode/step/decode/upload spans with positive, nested
    durations, on BOTH execution paths, and /debug/traces serves them
    as Perfetto-loadable JSON next to a /metrics scrape that shows the
    compile-cache counters the run populated."""
    trace, chrome, metrics_body = _run_tiny_job_and_get_trace(
        stepper, monkeypatch, seed=41 if stepper else 40)

    root = trace.root
    phases = [c.name for c in root.children]
    assert phases == ["poll", "execute", "upload"]
    execute = root.children[1]
    for name in ("encode", "step", "decode"):
        node = execute.find(name)
        assert node is not None, f"missing {name} span in {phases}"
        assert node.duration_s > 0
        # nested INSIDE the execute phase's interval
        assert node.t0 >= execute.t0 - 1e-9
        assert node.t1 <= execute.t1 + 1e-9
    for child in root.children:
        assert child.duration_s > 0
    assert root.find("upload.http") is not None  # nests under upload
    assert trace.meta["outcome"] == "ok"
    assert trace.meta["settled"] == "uploaded"
    if stepper:
        # the lane run stamps its lane-side timeline into the step span
        assert "lane" in execute.find("step").meta

    # Perfetto export of the same tree via the live endpoint
    names = {e["name"] for e in chrome["traceEvents"]}
    assert {"job", "poll", "execute", "encode", "step", "decode",
            "upload"} <= names
    for event in chrome["traceEvents"]:
        assert event["ph"] == "X" and event["dur"] >= 1

    # the run compiled real executables; the registry saw them
    assert 'chiaswarm_compile_cache_misses_total{cache="executables"' \
        in metrics_body
    if stepper:
        assert "chiaswarm_stepper_steps_executed_total 2" in metrics_body
        assert "chiaswarm_stepper_step_seconds_count" in metrics_body
        # the per-lane occupancy histogram sampled at each lane step
        # (ISSUE 5 obs tie-in) rides the same scrape, labeled by the
        # lane's (bounded) width — never by unbounded lane id
        assert 'chiaswarm_stepper_lane_occupancy_ratio_bucket{width="' \
            in metrics_body
        # lease/resume families (ISSUE 6): present at zero on a healthy
        # run — they only move when the fleet machinery redelivers
        assert "chiaswarm_stepper_rows_resumed_total 0" in metrics_body
        assert "chiaswarm_stepper_resumes_rejected_total 0" in metrics_body
        assert "# TYPE chiaswarm_stepper_resume_step histogram" \
            in metrics_body
        assert "chiaswarm_checkpoints_written_total" in metrics_body


def test_lane_occupancy_histogram_semantics():
    """The per-lane occupancy family (obs/metrics.py): ratio buckets in
    eighths, one series per lane-width label (bounded — lane IDs would
    leak a series per retired lane), registered on the process-global
    registry exactly once (get-or-create)."""
    from chiaswarm_tpu.obs.metrics import (
        OCCUPANCY_BUCKETS, lane_occupancy_histogram)

    reg = Registry()
    hist = lane_occupancy_histogram(reg)
    assert lane_occupancy_histogram(reg) is hist  # idempotent
    assert hist.buckets == OCCUPANCY_BUCKETS

    # a 4-wide lane stepping at 1, 2, 4, 4 active rows
    for active in (1, 2, 4, 4):
        hist.observe(active / 4, width="4")
    hist.observe(0.5, width="16")  # wider lane family: its own series
    assert hist.count(width="4") == 4 and hist.count(width="16") == 1
    assert hist.sum(width="4") == pytest.approx(2.75)

    body = reg.render()
    assert ('chiaswarm_stepper_lane_occupancy_ratio_bucket'
            '{width="4",le="0.25"} 1') in body
    assert ('chiaswarm_stepper_lane_occupancy_ratio_bucket'
            '{width="4",le="1"} 4') in body
    assert ('chiaswarm_stepper_lane_occupancy_ratio_count{width="16"} 1'
            ) in body

    # the real sampler feeds the process-global registry
    global_hist = lane_occupancy_histogram()
    from chiaswarm_tpu.obs import metrics as obs_metrics

    assert obs_metrics.REGISTRY.get(
        "chiaswarm_stepper_lane_occupancy_ratio") is global_hist


# ---------------------------------------------------------------------------
# swarmlens (ISSUE 11): numerics ring + histogram percentiles
# ---------------------------------------------------------------------------


def test_numerics_ring_bounded_eviction_keeps_newest():
    """The flight-recorder ring is bounded: the oldest records evict,
    seq numbers stay monotonic, and the eviction counter tells the
    operator the window was exceeded."""
    from chiaswarm_tpu.obs.numerics import NumericsRing

    ring = NumericsRing(capacity=4)
    for i in range(10):
        ring.record("p", step=i, l2=float(i))
    records = ring.snapshot()
    assert len(records) == 4
    assert [r["step"] for r in records] == [6, 7, 8, 9]
    assert [r["seq"] for r in records] == [6, 7, 8, 9]
    stats = ring.stats()
    assert stats["total"] == 10 and stats["evicted"] == 6
    assert stats["depth"] == 4 and stats["capacity"] == 4

    # prefix filter + limit serve the /debug/numerics query params
    ring.record("other.probe", step=99)
    assert [r["probe"] for r in ring.snapshot(probe_prefix="other")] == \
        ["other.probe"]
    assert len(ring.snapshot(limit=2)) == 2

    # drain is snapshot+clear (the bisect driver's per-run capture)
    drained = ring.drain()
    assert len(drained) == 4 and len(ring) == 0


def test_numerics_ring_records_are_json_and_dumpable(tmp_path):
    from chiaswarm_tpu.obs import numerics

    ring = numerics.NumericsRing(capacity=8)
    ring.record("a.b", step=1, shard=2, l2=1.5, mean=0.5, absmax=2.0,
                nonfinite=0, checksum=123, size=64, note="job-1")
    path = tmp_path / "run.jsonl"
    n = numerics.dump(str(path), ring.snapshot())
    assert n == 1
    loaded = numerics.load_dump(str(path))
    assert loaded[0]["probe"] == "a.b" and loaded[0]["note"] == "job-1"


def test_histogram_percentile_interpolation():
    """Bucket-interpolated quantiles: the primitive behind the BENCH
    step-seconds percentiles and the measured hang-budget suggestion."""
    from chiaswarm_tpu.obs.metrics import Histogram

    hist = Histogram("h", buckets=(1.0, 2.0, 4.0, 8.0))
    assert hist.percentile(0.5) is None  # empty series
    for v in (0.5, 1.5, 1.5, 3.0):
        hist.observe(v)
    # rank 2 of 4 lands in the (1, 2] bucket (2 obs): interpolated
    assert hist.percentile(0.5) == pytest.approx(1.5)
    assert hist.percentile(1.0) == pytest.approx(4.0)
    # overflow mass clamps to the last finite bound
    hist.observe(100.0)
    assert hist.percentile(0.99) == pytest.approx(8.0)
    pct = hist.percentiles((0.5, 0.99))
    assert set(pct) == {"p50", "p99"}

    labeled = Histogram("l", labelnames=("k",), buckets=(1.0, 2.0))
    labeled.observe(0.5, k="a")
    assert labeled.percentile(0.5, k="a") == pytest.approx(0.5)
    assert labeled.percentile(0.5, k="other") is None


def test_suggest_hang_budget_measured_vs_prior():
    """Below the sample floor the suggestion refuses to guess; above it
    the knobs derive from p50/p99 with documented clamps (ISSUE 11 —
    the PR-10 'priors, not measurements' carry-over closed)."""
    from chiaswarm_tpu.obs.metrics import Histogram
    from chiaswarm_tpu.serving.guard import suggest_hang_budget

    hist = Histogram("s", buckets=(0.01, 0.05, 0.1, 0.5, 1.0, 5.0))
    out = suggest_hang_budget(hist)
    assert out["measured"] is False and out["samples"] == 0
    assert out["current"]["factor"] == 20.0  # the documented prior

    for _ in range(60):
        hist.observe(0.04)
    for _ in range(4):
        hist.observe(0.4)  # a heavy tail: p99 lands past p50
    out = suggest_hang_budget(hist)
    assert out["measured"] is True and out["samples"] == 64
    s = out["suggested"]
    assert 4.0 <= s["factor"] <= 20.0
    assert s["floor_s"] >= 1.0
    assert s["ceil_s"] >= s["floor_s"]
    assert s["ceil_s"] <= out["current"]["ceil_s"]
    # measured floor tracks the tail, and sits far below the 30 s prior
    assert s["floor_s"] < out["current"]["floor_s"]
