"""swarmload (ISSUE 9, node/loadgen.py): the load harness units, the
tuning-sweep pins, and THE acceptance gate.

Layers:

- **Model units**: seeded determinism of users/curves/schedules, the
  workload mix, percentile/reconcile helpers, and the controller
  simulators the sweeps are built on.
- **Sweep pins**: the shipped LaneWidthController gains and the
  residency prefetch-ranking window must equal the default-seed sweep
  winners — a default and the harness can never silently disagree.
- **Load smoke** (the fast CI leg): a small seeded diurnal run over
  overload-controlled workers settles every job exactly once.
- **THE ISSUE-9 acceptance gate**: scripted 10x offered load, mixed
  workloads, one mid-run worker kill — zero job loss (every job
  completed, shed-redispatched, or abandoned-by-policy), sheds and
  backpressure observed, p99 of admitted jobs within deadline, and the
  capacity model populated.
- **Nightly soak** (slow tier): a bigger diurnal fleet soak seeded from
  the run id (chaos-soak.yml).
"""

from __future__ import annotations

import asyncio
import os
import time

import pytest

from chiaswarm_tpu.node import loadgen
from chiaswarm_tpu.node.loadgen import (
    DEFAULT_PROFILES,
    DiurnalCurve,
    KillPlan,
    LoadHive,
    RosterPlan,
    SyntheticExecutor,
    UserPopulation,
    build_scenario,
    generate_schedule,
    percentile,
    reconcile,
    run_load,
)
from chiaswarm_tpu.node.resilience import classify_result


@pytest.fixture(autouse=True)
def _tmp_root(tmp_path, monkeypatch):
    monkeypatch.setenv("SWARM_TPU_ROOT", str(tmp_path))
    return tmp_path


# ---------------------------------------------------------------------------
# model units
# ---------------------------------------------------------------------------


def test_percentile_nearest_rank():
    assert percentile([], 0.99) == 0.0
    assert percentile([5.0], 0.99) == 5.0
    values = list(range(1, 101))
    assert percentile(values, 0.50) == 50
    assert percentile(values, 0.99) == 99
    assert percentile(values, 1.0) == 100


def test_population_is_seeded_and_mix_tracks_weights():
    a = UserPopulation(n_users=3000, seed="pop1")
    b = UserPopulation(n_users=3000, seed="pop1")
    assert [u.profile.name for u in a.users] == \
        [u.profile.name for u in b.users]
    mix = a.mix()
    for profile in DEFAULT_PROFILES:
        assert abs(mix[profile.name] - profile.weight) < 0.05, mix
    # a different seed is a different population
    c = UserPopulation(n_users=3000, seed="pop2")
    assert [u.activity for u in a.users] != [u.activity for u in c.users]


def test_diurnal_curve_shape_and_spikes():
    curve = DiurnalCurve(amplitude=0.5, spikes=2, spike_mult=4.0,
                         seed="curve1")
    # trough at the start, peak mid-run (modulo spike windows)
    in_spike = [frac for frac in (i / 100 for i in range(101))
                if any(s <= frac < e for s, e in curve.spike_windows)]
    assert curve.multiplier(0.0) == pytest.approx(0.5)
    assert curve.multiplier(0.5) == pytest.approx(1.5)
    assert len(curve.spike_windows) == 2
    for frac in in_spike:
        base = 1.0 + 0.5 * __import__("math").sin(
            2.0 * __import__("math").pi * (frac - 0.25))
        assert curve.multiplier(frac) == pytest.approx(base * 4.0)
    # determinism
    again = DiurnalCurve(amplitude=0.5, spikes=2, spike_mult=4.0,
                         seed="curve1")
    assert again.spike_windows == curve.spike_windows


def test_schedule_is_deterministic_and_carries_deadlines():
    pop = UserPopulation(n_users=500, seed="s")
    curve = DiurnalCurve(seed="s")
    a = generate_schedule(pop, curve, duration_s=4.0, rate_jobs_s=30,
                          seed="s")
    b = generate_schedule(pop, curve, duration_s=4.0, rate_jobs_s=30,
                          seed="s")
    assert [(x.at_s, x.job["id"], x.workload) for x in a] == \
        [(y.at_s, y.job["id"], y.workload) for y in b]
    assert len(a) > 50
    by_name = {p.name: p for p in DEFAULT_PROFILES}
    for item in a:
        profile = by_name[item.workload]
        assert item.job["deadline_s"] == profile.deadline_s
        assert profile.steps[0] <= item.job["num_inference_steps"] \
            <= profile.steps[1]
        assert 0.0 <= item.at_s < 4.0
    # ids are unique (the zero-loss accounting key)
    ids = [x.job["id"] for x in a]
    assert len(ids) == len(set(ids))


def test_synthetic_executor_is_deterministic_per_attempt():
    async def run():
        ex_a = SyntheticExecutor(seed="e")
        ex_b = SyntheticExecutor(seed="e")
        job = {"id": "j1", "workflow": "img2img"}
        ra = await ex_a.do_work(dict(job), None, None)
        rb = await ex_b.do_work(dict(job), None, None)
        assert ra["pipeline_config"] == rb["pipeline_config"]
        assert ex_a._service(dict(job)) == ex_b._service(dict(job))
    asyncio.run(run())


def test_reconcile_flags_missing_and_double_settles():
    clock = [0.0]
    hive = LoadHive(lease_s=10.0, clock=lambda: clock[0])
    hive.submit_job({"id": "a"})
    hive.submit_job({"id": "b"})
    hive._take_jobs("w")
    hive._record_result({"id": "a", "artifacts": {},
                         "pipeline_config": {}}, "w")
    partial = reconcile(hive, ["a", "b"])
    assert partial["missing"] == ["b"] and not partial["zero_loss"]
    hive._record_result({"id": "b", "artifacts": {},
                         "pipeline_config": {}}, "w")
    full = reconcile(hive, ["a", "b"])
    assert full["zero_loss"] and full["completed"] == 2


# ---------------------------------------------------------------------------
# sweep pins: shipped defaults == default-seed sweep winners
# ---------------------------------------------------------------------------


def test_lane_gain_sweep_pins_shipped_defaults():
    """The ISSUE-9 satellite contract: LaneWidthController's default
    gains ARE the swarmload sweep winner (seed "swarmload"). If a
    future change re-tunes the simulator or the gains, both must move
    together — re-run the sweep and land its winner."""
    sweep = loadgen.sweep_lane_gains("swarmload")
    assert sweep["defaults_match_winner"], (
        f"shipped defaults {sweep['defaults']} != sweep winner "
        f"{sweep['winner']}")
    # the table is deterministic and fully ranked
    again = loadgen.sweep_lane_gains("swarmload")
    assert again["table"] == sweep["table"]
    costs = [row["cost"] for row in sweep["table"]]
    assert costs == sorted(costs)


def test_prefetch_window_sweep_pins_shipped_default():
    sweep = loadgen.sweep_prefetch_window("swarmload")
    assert sweep["defaults_match_winner"], sweep
    from chiaswarm_tpu.serving.residency import PREFETCH_RANK_WINDOW_S

    assert sweep["default_window_s"] == PREFETCH_RANK_WINDOW_S


def test_lane_simulator_grows_under_burst_and_idles_down():
    trace = [0] * 50 + [12] + [0] * 200   # one burst into an idle lane
    out = loadgen.simulate_lane_controller(grow_at=0.75, shrink_at=0.25,
                                           patience=6, trace=trace)
    assert out["resizes"] >= 2            # grew for the burst, shrank after
    assert 0.0 <= out["padding_waste"] <= 1.0
    assert out["cost"] > 0.0


# ---------------------------------------------------------------------------
# load smoke (the fast CI leg) + THE acceptance gate
# ---------------------------------------------------------------------------


def test_load_smoke_seeded_zero_loss():
    """Fast-tier smoke: a small seeded diurnal run (modest overload)
    through 2 overload-controlled workers settles every job exactly
    once and stamps a capacity model."""
    seed = "load-smoke"
    schedule = build_scenario(seed=seed, n_users=300, duration_s=2.0,
                              rate_jobs_s=25)
    assert len(schedule) > 20
    report = asyncio.run(run_load(schedule, n_workers=2, seed=seed,
                                  lease_s=3.0, settle_timeout_s=120))
    assert report["reconciliation"]["zero_loss"], report["reconciliation"]
    capacity = report["capacity"]
    assert capacity["chips"] == 2
    assert capacity["jobs_per_s_per_chip"] > 0
    assert set(capacity["workload_mix"]) <= {p.name
                                             for p in DEFAULT_PROFILES}
    assert report["hive"]["pending"] == 0
    # the measured suggested-deadline table (ISSUE 10 satellite) rides
    # every report: per-family p99 x margin over completed-ok jobs
    suggested = report["suggested_deadlines"]
    assert suggested["margin"] == loadgen.DEADLINE_MARGIN
    families = suggested["families"]
    assert families, suggested
    for entry in families.values():
        assert entry["suggested_s"] == pytest.approx(
            entry["p99_s"] * loadgen.DEADLINE_MARGIN, rel=1e-3)
        assert entry["n"] > 0
        # the conformance satellite (ISSUE 13): each family names its
        # dominant overshoot phase (None when nothing missed)
        assert "dominant_overshoot_phase" in entry
    # swarmsight (ISSUE 13): per-family deadline-budget attribution
    # folded from the flight records — the synthetic service model
    # books as "steps", so steps must dominate every family's share —
    # plus the /api/fleet aggregate snapshot the autoscaler reads
    from chiaswarm_tpu.obs.flight import ATTRIBUTION_PHASES

    attribution = report["budget_attribution"]["families"]
    assert attribution, report["budget_attribution"]
    for family, entry in attribution.items():
        assert set(entry["mean_s"]) == set(ATTRIBUTION_PHASES), family
        assert entry["n"] > 0
        assert entry["dominant_phase"] == "steps", entry
        assert abs(sum(entry["share"].values()) - 1.0) < 0.02
    fleet = report["fleet"]
    assert fleet["aggregate"]["workers_reporting"] == 2
    assert fleet["aggregate"]["chips_in_service"] == 2
    # every settled job left a COMPLETE flight record (ISSUE 13
    # satellite — the soak legs assert the same at scale)
    hive_stats = report["hive"]
    assert hive_stats["flights"]["records"] > 0


def test_load_churn_roster_join_leave():
    """ISSUE 14 satellite (ROADMAP item 5 residue): a scripted roster —
    one worker JOINS mid-run, one LEAVES by graceful drain — keeps
    zero-loss exactly-once settlement, records both churn events, and
    the fleet plane + capacity model see the elastic roster (the
    joined worker reports; the departed one drops out of the live
    aggregate), not just a static fleet."""
    seed = "load-churn"
    schedule = build_scenario(seed=seed, n_users=300, duration_s=2.5,
                              rate_jobs_s=25)
    hive = LoadHive(lease_s=3.0, delay_s=0.0, max_attempts=4,
                    max_jobs_per_poll=2)
    report = asyncio.run(run_load(
        schedule, n_workers=2, seed=seed, hive=hive,
        roster=RosterPlan(join_at=(0.25,), leave_at=(0.6,)),
        settle_timeout_s=120))
    assert report["reconciliation"]["zero_loss"], report["reconciliation"]
    events = report["roster"]
    assert [e["action"] for e in events] == ["join", "leave"]
    joined, departed = events[0]["worker"], events[1]["worker"]
    assert joined != departed
    assert events[0]["at_job"] <= events[1]["at_job"]
    assert events[1]["drained"] is True  # a leave is a DRAIN, not a kill
    # the joined worker actually served: it reports in the fleet
    # per-worker map and settled at least one job
    assert joined in report["fleet"]["workers"]
    settlers = {str(r.get("worker_name") or "") for r in hive.results}
    assert joined in settlers, sorted(settlers)
    # the departed worker served before its drain, and the drain is not
    # a kill: every job it held completed and uploaded (zero-loss above
    # already proves exactly-once; nothing is left pending or leased)
    assert departed in settlers, sorted(settlers)
    hive_stats = report["hive"]
    assert hive_stats["pending"] == 0 and not hive_stats["leased"]
    assert report["capacity"]["jobs_per_s_per_chip"] > 0


def test_overload_gate_10x_mixed_kill():
    """THE ISSUE-9 acceptance gate: scripted 10x offered load (peak
    rate ~10x the 3-worker fleet's measured capacity), the full mixed
    workload, one worker killed mid-run. Every job settles exactly once
    — completed, shed-redispatched, or abandoned-by-policy, zero lost —
    sheds and backpressure demonstrably engaged, brownout tripped, and
    the p99 end-to-end latency of ADMITTED jobs sits within each
    workload's deadline.

    Deflaked (ISSUE 12 satellite): the gate's bounds are RATIOS of the
    issued volume and the deadline clause scales by the run's MEASURED
    host-contention factor (loadgen's in-run sleep-overshoot probe) —
    absolute shed counts and raw wall clock flaked on contended CI
    hosts while asserting nothing the ratios don't. The zero-loss and
    exactly-once invariants are untouched."""
    seed = "overload-gate"
    # ~650 jobs over 3 s: mean service ~0.12 s x 3 single-slot workers
    # ≈ 22 jobs/s capacity vs ~200 jobs/s offered at the diurnal peak
    schedule = build_scenario(seed=seed, n_users=800, duration_s=3.0,
                              rate_jobs_s=160)
    assert len(schedule) > 400
    t0 = time.monotonic()
    report = asyncio.run(run_load(
        schedule, n_workers=3, seed=seed, lease_s=3.0,
        max_jobs_per_poll=4, kill=KillPlan(after_frac=0.5),
        settle_timeout_s=240))
    wall = time.monotonic() - t0
    issued_n = len(schedule)
    contention = report["contention"]["factor"]

    # 1. zero job loss, exactly once (the invariants stay absolute)
    rec = report["reconciliation"]
    assert rec["zero_loss"], rec
    assert rec["issued"] == issued_n

    # 2. the kill landed and the fleet absorbed it
    assert report["kill"] and report["kill"]["jobs"], report["kill"]
    assert report["hive"]["metrics"][
        "chiaswarm_hive_jobs_redelivered_total"]["values"][""] >= 0

    # 3. overload control engaged: sheds settled, backpressure waited,
    #    and at least one worker browned out. Ratio bounds: at 10x
    #    offered load the fleet MUST shed most of the volume whatever
    #    the host speed — a slower host sheds more, never fewer.
    outcomes = report["outcomes"]
    assert outcomes["shed"] > 0.05 * issued_n, outcomes
    assert outcomes["ok"] > 0.05 * issued_n, outcomes
    workers = report["workers"].values()
    assert sum(w["jobs_shed"] for w in workers) > 0.1 * issued_n
    assert sum(w["polls_backpressured"] for w in workers) > 0
    assert any(w["overload"]["sheds_total"] > 0 for w in workers)
    # shed jobs are capacity decisions, never failures
    assert all(w["jobs_failed"] == 0 for w in workers)

    # 4. THE latency clause, contention-adjusted: p99 of admitted jobs'
    #    latency/deadline ratios within the measured sleep-stretch
    #    factor (== 1.0 on an idle host, so the clause is unchanged
    #    there; a contended host loosens it by exactly what the host
    #    stole, not by an arbitrary fudge)
    assert report["admitted_deadline"][
        "p99_within_deadline_contention_adjusted"], (
        report["admitted_deadline"], report["contention"])

    # 5. the capacity model is populated
    capacity = report["capacity"]
    assert capacity["chips"] == 3
    assert capacity["jobs_per_s_per_chip"] > 0
    assert capacity["models_resident"] >= 1
    assert abs(sum(capacity["workload_mix"].values()) - 1.0) < 0.01
    # the run stays CI-sized relative to the host: shedding keeps the
    # backlog from serializing 10x load through 3 slots
    assert wall < 180 * contention, (wall, contention)


# ---------------------------------------------------------------------------
# per-model-family deadline tables (ISSUE 10 satellite, ROADMAP 5b)
# ---------------------------------------------------------------------------


def test_family_deadline_defaults_pinned_to_sweep():
    """The shipped DEFAULT_FAMILY_DEADLINES must equal the default-seed
    sweep derivation — pinned defaults == winner, the PR-9 convention
    (a default and the harness can never silently disagree)."""
    assert loadgen.DEFAULT_FAMILY_DEADLINES == \
        loadgen.sweep_deadline_table()
    # sanity of the derivation itself: deterministic per seed, scales
    # with the family cost factor, margin applied over the p99
    again = loadgen.sweep_deadline_table()
    assert again == loadgen.DEFAULT_FAMILY_DEADLINES
    table = loadgen.DEFAULT_FAMILY_DEADLINES
    assert table["tiny"] < table["sd15"] < table["sdxl"]
    # the few-step-distilled classes (ISSUE 12) price at their base
    # family's per-step cost x ~4/30 of the steps — always cheaper
    # than their full-step parent
    assert table["tiny"] < table["sdxl_turbo"] < table["sd15"]
    assert table["tiny"] < table["sd_turbo"] < table["sd15"]
    assert table["sd_turbo"] < table["sdxl_turbo"]


def test_model_family_heuristic():
    assert loadgen.model_family("stabilityai/sdxl-base") == "sdxl"
    assert loadgen.model_family("tiny") == "tiny"
    assert loadgen.model_family("swarm/sd15") == "sd15"
    assert loadgen.model_family(None) == "sd15"
    # few-step-distilled names outrank the "xl" hint (ISSUE 12), and
    # non-XL distillations price at the SD-class per-step cost
    assert loadgen.model_family("stabilityai/sdxl-turbo") == "sdxl_turbo"
    assert loadgen.model_family("latent-consistency/lcm-lora-sdxl") == \
        "sdxl_turbo"
    assert loadgen.model_family("stabilityai/sd-turbo") == "sd_turbo"
    assert loadgen.model_family("sd15-lcm") == "sd_turbo"


def test_fewstep_traffic_class_in_default_mix():
    """The txt2img_fewstep class (ISSUE 12): present in the default
    population mix, SHORT-deadline (the tightest in the mix), few-step
    (2–8), and scheduled jobs carry its deadline + step bounds."""
    by_name = {p.name: p for p in DEFAULT_PROFILES}
    fewstep = by_name["txt2img_fewstep"]
    assert fewstep.deadline_s == min(p.deadline_s
                                     for p in DEFAULT_PROFILES)
    assert fewstep.steps == (2, 8)
    pop = UserPopulation(n_users=2000, seed="fewstep")
    assert abs(pop.mix()["txt2img_fewstep"] - fewstep.weight) < 0.05
    schedule = generate_schedule(pop, DiurnalCurve(seed="fewstep"),
                                 duration_s=4.0, rate_jobs_s=40,
                                 seed="fewstep")
    fewstep_jobs = [s for s in schedule
                    if s.workload == "txt2img_fewstep"]
    assert fewstep_jobs, "mix produced no few-step arrivals"
    for item in fewstep_jobs:
        assert item.job["deadline_s"] == fewstep.deadline_s
        assert 2 <= item.job["num_inference_steps"] <= 8
        # the class IS the lcm-kind CFG-free path: real-pipeline runs
        # must exercise the fewstep lane eligibility, not a short dpm
        # job wearing the class name
        assert item.job["guidance_scale"] == 1.0
        assert item.job["parameters"]["scheduler_type"] == "LCMScheduler"


def test_worker_honors_family_deadline_override():
    """The settings-side half: ``family_deadline_s`` slots between a
    job's explicit deadline_s and the per-workflow table
    (node/worker.py::_job_deadline_s)."""
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.node.settings import Settings
    from chiaswarm_tpu.node.worker import Worker

    class StubSlot:
        depth = 2
        data_width = 1

        def descriptor(self):
            return "stub"

    worker = Worker(
        settings=Settings(hive_uri="http://h", hive_token="t",
                          worker_name="deadline-w",
                          install_signal_handlers=False,
                          job_deadline_s=600.0,
                          family_deadline_s={"tiny": 42.0}),
        pool=[StubSlot()],
        registry=ModelRegistry(catalog=[], allow_random=True))
    # family override engages for a catalog-resolvable model name
    assert worker._job_deadline_s({"model_name": "tiny"}) == 42.0
    # the job's explicit deadline always wins
    assert worker._job_deadline_s(
        {"model_name": "tiny", "deadline_s": 7.5}) == 7.5
    # a family not in the table falls through to the workflow default
    # (unknown names resolve to the sd15 family via get_family)
    assert worker._job_deadline_s(
        {"model_name": "no/such-family-model"}) == 600.0
    no_table = Worker(
        settings=Settings(hive_uri="http://h", hive_token="t",
                          worker_name="deadline-x",
                          install_signal_handlers=False,
                          job_deadline_s=123.0),
        pool=[StubSlot()],
        registry=ModelRegistry(catalog=[], allow_random=True))
    assert no_table._job_deadline_s({"model_name": "tiny"}) == 123.0


# ---------------------------------------------------------------------------
# nightly REAL-lane load soak (ISSUE 10 satellite, ROADMAP 5a):
# the harness's control-plane numbers meet the compute plane — real
# tiny-family lanes behind the same worker_factory seam
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_real_lane_load_soak_tiny_family(monkeypatch):
    """Swap the SyntheticExecutor for REAL tiny-family lanes via the
    worker_factory seam: a seeded diurnal stream of txt2img jobs runs
    through two workers with real pools/registries (lanes default-on),
    every job settles exactly once, and real frames come back."""
    import jax

    from chiaswarm_tpu.core.chip_pool import ChipPool
    from chiaswarm_tpu.core.mesh import MeshSpec
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.node.settings import Settings
    from chiaswarm_tpu.node.worker import Worker

    monkeypatch.setenv("CHIASWARM_STEPPER", "1")
    seed = os.environ.get("CHIASWARM_SOAK_SEED", "real-lane-default")
    jobs_scale = int(os.environ.get("CHIASWARM_SOAK_JOBS", "120"))
    # real compiles are the cost driver: a handful of jobs exercises
    # the whole path (poll -> format -> lane -> decode -> upload)
    profiles = (loadgen.WorkloadProfile("txt2img", 1.0, 60.0, (2, 4),
                                        0.5),)
    population = UserPopulation(n_users=50, profiles=profiles,
                                models=("tiny",),
                                seed=f"real:{seed}")
    curve = DiurnalCurve(seed=f"real:{seed}")
    schedule = generate_schedule(
        population, curve, duration_s=2.0,
        rate_jobs_s=max(3.0, jobs_scale / 30.0),
        seed=f"real:{seed}", id_prefix="real",
        content_type="image/png")
    assert schedule, "seeded schedule came out empty"

    def factory(uri: str, name: str) -> Worker:
        pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 1}),
                        devices=jax.devices()[:1])
        return Worker(
            settings=Settings(
                hive_uri=uri, hive_token="t", worker_name=name,
                job_deadline_s=600.0, heartbeat_s=0.1,
                poll_busy_s=0.02, poll_idle_s=0.05,
                poll_backoff_base_s=0.02, poll_backoff_cap_s=0.2,
                upload_retries=5, upload_retry_delay_s=0.02,
                drain_timeout_s=60.0, result_drain_timeout_s=30.0,
                install_signal_handlers=False),
            registry=ModelRegistry(
                catalog=[{"name": "tiny", "family": "tiny",
                          "parameters": {}}],
                allow_random=True),
            pool=pool)

    hive = LoadHive(lease_s=120.0, delay_s=0.0, max_attempts=4,
                    max_jobs_per_poll=1)
    report = asyncio.run(run_load(
        schedule, n_workers=2, worker_factory=factory, hive=hive,
        seed=f"real:{seed}", settle_timeout_s=900))
    rec = report["reconciliation"]
    assert rec["zero_loss"], rec
    assert report["outcomes"]["ok"] == len(schedule), report["outcomes"]
    assert report["capacity"]["jobs_per_s_per_chip"] > 0
    # the suggested-deadline table now reflects MEASURED tiny-family
    # latencies — the live refinement of the shipped sweep defaults
    assert "tiny" in report["suggested_deadlines"]["families"]
    # swarmsight (ISSUE 13 satellite): every settled REAL-lane soak job
    # has a complete flight record, and the real-pipeline digests carry
    # lane step spans the budget attribution books as steps
    assert hive.flights.verify(list(hive.completed)) == []
    attribution = report["budget_attribution"]["families"]
    assert attribution["tiny"]["mean_s"]["steps"] > 0, attribution


# ---------------------------------------------------------------------------
# nightly diurnal fleet soak (chaos-soak.yml; seed = run id)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_load_soak_diurnal_fleet_kill():
    """Nightly soak: one diurnal-curve fleet run at soak scale, seeded
    from the run id (CHIASWARM_SOAK_SEED) for exact replay, with a
    mid-run worker kill AND a scripted roster churn leg (ISSUE 14
    satellite): one worker joins mid-run, one drains and leaves. Gate:
    zero loss + admitted-deadline p99 under the elastic fleet."""
    seed = os.environ.get("CHIASWARM_SOAK_SEED", "load-soak-default")
    jobs_scale = int(os.environ.get("CHIASWARM_SOAK_JOBS", "120"))
    schedule = build_scenario(seed=f"load-soak:{seed}", n_users=2000,
                              duration_s=6.0,
                              rate_jobs_s=max(20, jobs_scale // 3))
    hive = LoadHive(lease_s=4.0, delay_s=0.0, max_attempts=4,
                    max_jobs_per_poll=4)
    report = asyncio.run(run_load(
        schedule, n_workers=3, seed=f"load-soak:{seed}", hive=hive,
        kill=KillPlan(after_frac=0.4),
        roster=RosterPlan(join_at=(0.3,), leave_at=(0.7,)),
        settle_timeout_s=600))
    assert report["reconciliation"]["zero_loss"], report["reconciliation"]
    # the churn leg actually churned: both events recorded, and the
    # kill victim was never the leave candidate (the plan skips it)
    assert [e["action"] for e in report["roster"]] == ["join", "leave"]
    if report["kill"]:
        assert report["roster"][1]["worker"] != report["kill"]["worker"]
    assert report["admitted_deadline"]["p99_within_deadline"], \
        report["admitted_deadline"]
    assert report["capacity"]["jobs_per_s_per_chip"] > 0
    # every settled envelope is a classified outcome the taxonomy knows
    hive_stats = report["hive"]
    assert hive_stats["pending"] == 0 and not hive_stats["leased"]
    # swarmsight (ISSUE 13 satellite): every SETTLED soak job left a
    # complete flight record (no orphan spans, no attempt gaps);
    # abandoned-by-policy jobs keep their unsettled records
    assert hive.flights.verify(list(hive.completed)) == []
