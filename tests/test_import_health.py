"""Import health: every chiaswarm_tpu module imports cleanly on CPU.

API-churn breakage (a symbol that does not exist on the pinned jax, an
import-time device query, a missing optional dep used unguarded) should
fail ONE named test per module — not poison the whole pytest collection
the way the seed's ``from jax import shard_map`` did. The static pass
(tests/test_lint.py) catches the known patterns; this test catches the
unknown ones by simply importing everything.

Runs under the suite's JAX_PLATFORMS=cpu conftest; modules must import
without an accelerator (R4 import-time-device-init is the static half of
the same invariant).
"""

from __future__ import annotations

import importlib
import pkgutil

import pytest

import chiaswarm_tpu


def _all_modules() -> list[str]:
    names = ["chiaswarm_tpu"]
    # a subpackage whose __init__ fails to import would otherwise be
    # silently SKIPPED by walk_packages (its submodules vanish from the
    # suite); record it so it still fails a named test below
    for info in pkgutil.walk_packages(chiaswarm_tpu.__path__,
                                      prefix="chiaswarm_tpu.",
                                      onerror=names.append):
        if info.name.rsplit(".", 1)[-1] == "__main__":
            continue  # CLI entry modules are exercised via subprocess tests
        names.append(info.name)
    return sorted(names)


_MODULES = _all_modules()


def test_module_walk_sees_the_whole_package():
    # a packaging regression that hides subpackages from pkgutil would
    # silently shrink this suite; pin a floor near the current count (88)
    assert len(_MODULES) >= 85, _MODULES


@pytest.mark.parametrize("name", _MODULES)
def test_imports_cleanly(name: str):
    importlib.import_module(name)
