"""Continuous step-level batching (serving/stepper.py): the numerical
equivalence gate plus the scheduling invariants.

Gate (ISSUE 3): a row denoised through a mixed-progress lane — spliced in
at a nonzero lane step, padded neighbors, per-row timesteps/sigmas,
DIFFERENT step counts and guidance scales sharing one program — must
match the solo per-job path for every sampler kind tier-1 serves
(dpmpp_2m, euler, euler_ancestral; DDIM/Heun/LMS map onto euler in this
framework, schedulers/sampling.py::SAMPLERS). Admission must never
compile (lane-program count bounded by buckets), deadlines apply per
row, and a failed lane bounces jobs to the per-job path instead of
losing them.

Runs on the hermetic CPU platform (tests/conftest.py).
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from chiaswarm_tpu.core.compile_cache import GLOBAL_CACHE
from chiaswarm_tpu.pipelines import (
    Components,
    DiffusionPipeline,
    GenerateRequest,
)
from chiaswarm_tpu.serving.stepper import (
    LaneDeadline,
    LaneReject,
    StepScheduler,
    aggregate_stats,
    stepper_enabled,
)


@pytest.fixture(scope="module")
def tiny_pipe():
    return DiffusionPipeline(Components.random("tiny", seed=0))


def _wait_steps(sched: StepScheduler, n: int, timeout: float = 120.0):
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        if sched.stats().get("steps_executed", 0) >= n:
            return
        time.sleep(0.005)
    raise AssertionError(
        f"scheduler never reached {n} steps: {sched.stats()}")


def _close(lane_img: np.ndarray, solo_img: np.ndarray) -> None:
    # different compiled batch shapes: agreement to uint8 quantization,
    # not bits (same tolerance as the burst-coalescing gate)
    diff = np.abs(lane_img.astype(int) - solo_img.astype(int))
    assert diff.max() <= 3 and (diff <= 1).mean() > 0.99, (
        diff.max(), (diff <= 1).mean())


# one representative per sampler KIND in the framework (the hive's other
# class names resolve onto these three, schedulers/sampling.py::SAMPLERS)
KINDS = [None,                                # -> dpmpp_2m (default)
         "DDIMScheduler",                     # -> euler family
         "EulerAncestralDiscreteScheduler"]   # -> euler_ancestral


@pytest.mark.parametrize("scheduler", KINDS)
def test_spliced_row_matches_solo(tiny_pipe, scheduler):
    """THE gate: job B splices into job A's running lane at a nonzero
    step, with a different step count AND guidance scale, and both jobs'
    images match their solo runs."""
    sched = StepScheduler()
    base = sched.stats().get("steps_executed", 0)
    fa = sched.submit_request(
        tiny_pipe, prompt="slow job", steps=16, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=21, scheduler=scheduler)
    _wait_steps(sched, base + 1)
    fb = sched.submit_request(
        tiny_pipe, prompt="late arrival", steps=3, guidance_scale=5.0,
        height=64, width=64, rows=1, seed=22, scheduler=scheduler)
    pending_b, info_b = fb.result(timeout=300)
    pending_a, info_a = fa.result(timeout=300)
    img_a, img_b = pending_a.wait(), pending_b.wait()
    # same lane, genuinely mid-flight: B joined after A had stepped
    assert info_b["lane"] == info_a["lane"]
    assert 1 <= info_b["admitted_at_step"] < 16

    solo_a, _ = tiny_pipe(GenerateRequest(
        prompt="slow job", steps=16, guidance_scale=7.5, height=64,
        width=64, seed=21, scheduler=scheduler))
    solo_b, _ = tiny_pipe(GenerateRequest(
        prompt="late arrival", steps=3, guidance_scale=5.0, height=64,
        width=64, seed=22, scheduler=scheduler))
    _close(img_a, solo_a)
    _close(img_b, solo_b)


def test_multi_row_job_matches_solo_batch(tiny_pipe):
    """num_images_per_prompt rows ride adjacent lane slots and match the
    solo batched run row-for-row (per-row fold_in keys)."""
    sched = StepScheduler()
    fut = sched.submit_request(
        tiny_pipe, prompt="pair", steps=4, guidance_scale=6.0,
        height=64, width=64, rows=2, seed=33)
    pending, _ = fut.result(timeout=300)
    imgs = pending.wait()
    solo, _ = tiny_pipe(GenerateRequest(
        prompt="pair", steps=4, guidance_scale=6.0, height=64, width=64,
        batch=2, seed=33))
    assert imgs.shape == solo.shape == (2, 64, 64, 3)
    _close(imgs, solo)


def test_admission_never_compiles(tiny_pipe, monkeypatch):
    """No recompile per admitted row: once a lane bucket is warm, jobs
    with new step counts / guidance values / seeds reuse the same four
    executables (the bounded-program acceptance criterion). Width is
    PINNED here so the adaptive controller cannot resize mid-test — a
    resize legitimately compiles the new lattice width once
    (test_adaptive_resize_compiles_only_new_lattice_widths covers
    that bound)."""
    monkeypatch.setenv("CHIASWARM_STEPPER_LANE_WIDTH", "4")
    sched = StepScheduler()
    sched.submit_request(tiny_pipe, prompt="warm", steps=5,
                         guidance_scale=7.5, height=64, width=64,
                         rows=1, seed=1).result(timeout=300)
    before = GLOBAL_CACHE.executables.stats["misses"]
    futs = [sched.submit_request(
        tiny_pipe, prompt=f"job {i}", steps=steps, guidance_scale=g,
        height=64, width=64, rows=1, seed=100 + i)
        for i, (steps, g) in enumerate([(4, 3.0), (7, 9.5), (9, 5.5)])]
    for fut in futs:
        fut.result(timeout=300)[0].wait()
    after = GLOBAL_CACHE.executables.stats["misses"]
    assert after == before, (before, after)


def test_row_deadline_expires_in_lane(tiny_pipe):
    """Per-row deadlines: an expired row retires with LaneDeadline while
    the lane keeps serving (the executor maps this to a structured
    timeout envelope, node/executor.py::_stepper_collect)."""
    sched = StepScheduler()
    fut = sched.submit_request(
        tiny_pipe, prompt="doomed", steps=8, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=5, deadline_s=0.0)
    with pytest.raises(LaneDeadline):
        fut.result(timeout=300)
    stats = sched.stats()
    assert stats.get("rows_expired", 0) >= 1
    # the lane survives: a follow-up job still completes
    ok = sched.submit_request(
        tiny_pipe, prompt="fine", steps=2, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=6)
    ok.result(timeout=300)[0].wait()


def test_lane_rejects_out_of_policy_jobs(tiny_pipe):
    sched = StepScheduler()
    with pytest.raises(LaneReject):  # no-CFG jobs run the solo program
        sched.submit_request(tiny_pipe, prompt="x", steps=4,
                             guidance_scale=1.0, height=64, width=64,
                             rows=1, seed=1)
    with pytest.raises(LaneReject):  # steps beyond the capacity lattice
        sched.submit_request(tiny_pipe, prompt="x", steps=4000,
                             guidance_scale=7.5, height=64, width=64,
                             rows=1, seed=1)
    with pytest.raises(LaneReject):  # wider than the lane
        sched.submit_request(tiny_pipe, prompt="x", steps=4,
                             guidance_scale=7.5, height=64, width=64,
                             rows=128, seed=1)


def test_injected_fault_bounces_rows_not_loses_them(tiny_pipe):
    """A lane fault (chaos seam) fails every resident row's future — the
    zero-loss contract is 'exception, never silence'."""
    sched = StepScheduler()
    boom = RuntimeError("RESOURCE_EXHAUSTED: injected mid-lane")
    sched.inject_fault(after_steps=sched.stats().get("steps_executed", 0),
                       exc=boom)
    fut = sched.submit_request(
        tiny_pipe, prompt="unlucky", steps=6, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=9)
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        fut.result(timeout=300)
    assert sched.stats().get("lanes_failed", 0) >= 1
    # the scheduler opens a FRESH lane afterwards and serves again
    ok = sched.submit_request(
        tiny_pipe, prompt="retry", steps=2, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=10)
    ok.result(timeout=300)[0].wait()


def test_oom_halves_width_even_after_lane_teardown(tiny_pipe):
    """The degradation ladder survives the teardown race: by the time a
    collector classifies the failure as OOM and calls note_oom(), the
    dead lane is already deregistered — the recorded failure hint must
    still let the halving find its key, and it must fire ONCE per
    incident no matter how many resident jobs report it."""
    sched = StepScheduler()
    sched.inject_fault(after_steps=sched.stats().get("steps_executed", 0),
                       exc=RuntimeError("RESOURCE_EXHAUSTED: oom"))
    fut = sched.submit_request(
        tiny_pipe, prompt="oomed", steps=4, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=40)
    with pytest.raises(RuntimeError):
        fut.result(timeout=300)
    for _ in range(3):  # every resident job's collector reports it
        sched.note_oom()
    assert sched._width_limits, "halving lost the dead lane's key"
    (limit,) = set(sched._width_limits.values())
    # halved exactly once from the width the dead lane actually ran at
    # (adaptive lanes open at initial_width, not the saturation anchor)
    assert limit == max(1, sched.initial_width(1, 64, 64) // 2)
    # the rebuilt lane honors the limit and still serves
    ok = sched.submit_request(
        tiny_pipe, prompt="after", steps=2, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=41)
    ok.result(timeout=300)[0].wait()


def test_drain_and_shutdown_retire_lanes(tiny_pipe):
    # contention probe (ISSUE 18 deflake, the PR-12/PR-17 pattern): on
    # an oversubscribed CI host the lane thread can hold the step loop
    # through a GIL-contended device sync, so the FIXED 5 s default
    # lane.join inside shutdown() can return with the thread still
    # live and lanes_live lands on a stale nonzero. Sample host
    # contention across the drain and widen the join deadline by the
    # measured factor; on a quiet host the factor is 1.0 and the
    # deadline is unchanged.
    from chiaswarm_tpu.node.loadgen import ContentionProbe

    probe = ContentionProbe().start()
    sched = StepScheduler()
    fut = sched.submit_request(
        tiny_pipe, prompt="drainee", steps=6, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=11)
    assert sched.drain(timeout_s=300.0)
    assert fut.done()
    fut.result()[0].wait()
    sched.shutdown(timeout_s=5.0 * probe.stop())
    assert sched.stats()["lanes_live"] == 0


def test_stats_and_aggregation(tiny_pipe):
    sched = StepScheduler()
    fut = sched.submit_request(
        tiny_pipe, prompt="counted", steps=4, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=12)
    fut.result(timeout=300)[0].wait()
    stats = sched.stats()
    assert stats["rows_admitted"] >= 1
    assert stats["steps_executed"] >= 4
    assert abs(stats["lane_occupancy"] + stats["padding_waste"] - 1.0) < 1e-6
    merged = aggregate_stats([sched, StepScheduler()])
    assert merged["rows_admitted"] == stats["rows_admitted"]
    assert 0.0 <= merged["lane_occupancy"] <= 1.0


# ---- executor wiring (node/executor.py) --------------------------------


@pytest.fixture()
def registry():
    from chiaswarm_tpu.node.registry import ModelRegistry

    return ModelRegistry(
        catalog=[{"name": "tiny", "family": "tiny", "parameters": {}}],
        allow_random=True,
    )


def _job(i: int, **over):
    job = {"id": f"s{i}", "model_name": "tiny", "prompt": f"prompt {i}",
           "seed": 200 + i, "num_inference_steps": 2,
           "height": 64, "width": 64, "content_type": "image/png"}
    job.update(over)
    return job


@pytest.fixture()
def single_chip_slot():
    import jax

    from chiaswarm_tpu.core.chip_pool import ChipPool
    from chiaswarm_tpu.core.mesh import MeshSpec

    pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 1}),
                    devices=jax.devices()[:1])
    return pool.slots[0]


def test_executor_routes_mixed_steps_onto_one_lane(
        monkeypatch, registry, single_chip_slot):
    """The relaxed admission key: jobs differing in steps AND guidance —
    which the burst path refuses to merge — share one lane program."""
    from chiaswarm_tpu.node.executor import synchronous_do_work_batch

    monkeypatch.setenv("CHIASWARM_STEPPER", "1")
    assert stepper_enabled()
    # s0/s3 share a step count on purpose: two DISTINCT jobs retiring at
    # the same boundary once bounced every row with "truth value of an
    # array is ambiguous" (dataclass field-eq on device arrays during
    # the membership check) — keep that shape covered
    jobs = [_job(0, num_inference_steps=2),
            _job(1, num_inference_steps=3, guidance_scale=5.0),
            _job(2, num_inference_steps=4),
            _job(3, num_inference_steps=2)]
    results = synchronous_do_work_batch(jobs, single_chip_slot, registry)
    by_id = {r["id"]: r for r in results}
    assert set(by_id) == {"s0", "s1", "s2", "s3"}
    lanes = set()
    for r in results:
        cfg = r["pipeline_config"]
        assert cfg.get("error") is None, cfg
        assert "stepper" in cfg, cfg
        assert cfg["seed"] in (200, 201, 202, 203)
        lanes.add(cfg["stepper"]["lane"])
    assert len(lanes) == 1, lanes
    stats = single_chip_slot._stepper.stats()
    assert stats["rows_completed"] >= 4


def test_executor_stepper_matches_solo_path(
        monkeypatch, registry, single_chip_slot):
    """End-to-end solo equivalence through the executor: the same job
    with lanes on and off produces the same image."""
    from chiaswarm_tpu.node.executor import synchronous_do_work

    monkeypatch.setenv("CHIASWARM_STEPPER", "1")
    lane_res = synchronous_do_work(_job(7, num_inference_steps=3),
                                   single_chip_slot, registry)
    assert "stepper" in lane_res["pipeline_config"]
    monkeypatch.setenv("CHIASWARM_STEPPER", "0")
    solo_res = synchronous_do_work(_job(7, num_inference_steps=3),
                                   single_chip_slot, registry)
    assert "stepper" not in solo_res["pipeline_config"]

    import base64
    import io

    from PIL import Image

    def img(res):
        return np.asarray(Image.open(io.BytesIO(base64.b64decode(
            res["artifacts"]["primary"]["blob"]))))

    _close(img(lane_res), img(solo_res))


def test_executor_falls_back_when_lane_faults(
        monkeypatch, registry, single_chip_slot):
    """Zero-loss through the executor: a faulted lane run falls back to
    the per-job path and the job still succeeds."""
    from chiaswarm_tpu.node.executor import synchronous_do_work
    from chiaswarm_tpu.serving.stepper import get_stepper

    monkeypatch.setenv("CHIASWARM_STEPPER", "1")
    stepper = get_stepper(single_chip_slot)
    stepper.inject_fault(
        after_steps=stepper.stats().get("steps_executed", 0),
        exc=RuntimeError("chaos: mid-lane crash"))
    result = synchronous_do_work(_job(9, num_inference_steps=3),
                                 single_chip_slot, registry)
    cfg = result["pipeline_config"]
    assert cfg.get("error") is None, cfg
    assert "stepper" not in cfg  # served by the fallback path
    assert "fatal_error" not in result


def test_executor_ineligible_jobs_keep_burst_path(
        monkeypatch, registry, single_chip_slot):
    """The lane-ineligible residue keeps its solo/burst programs:
    no-CFG jobs (the solo path compiles the no-CFG program) and upscale
    passes never enter lanes — while img2img, eligible since ISSUE 7,
    rides a lane and says so in its config stamp."""
    from chiaswarm_tpu.node.executor import synchronous_do_work

    monkeypatch.setenv("CHIASWARM_STEPPER", "1")
    rng = np.random.default_rng(3)
    init = rng.integers(0, 255, (64, 64, 3), dtype=np.uint8)
    r = synchronous_do_work(_job(11, image=init, strength=0.6),
                            single_chip_slot, registry)
    assert r["pipeline_config"]["mode"] == "img2img"
    assert "stepper" in r["pipeline_config"]  # lanes are the engine now
    r = synchronous_do_work(_job(12, guidance_scale=1.0),
                            single_chip_slot, registry)
    assert r["pipeline_config"].get("error") is None
    assert "stepper" not in r["pipeline_config"]


def test_executor_opt_out_restores_burst_routing(
        monkeypatch, registry, single_chip_slot):
    """CHIASWARM_STEPPER=0 restores the pre-lane routing end to end:
    even a perfectly eligible txt2img job runs its solo/burst program
    and carries no lane stamp (the ISSUE-7 opt-out acceptance gate)."""
    from chiaswarm_tpu.node.executor import synchronous_do_work

    monkeypatch.setenv("CHIASWARM_STEPPER", "0")
    r = synchronous_do_work(_job(13), single_chip_slot, registry)
    assert r["pipeline_config"].get("error") is None
    assert r["pipeline_config"]["mode"] == "txt2img"
    assert "stepper" not in r["pipeline_config"]


def test_burst_key_relaxes_only_with_stepper(monkeypatch):
    """Worker drain prefilter: steps/guidance/strength leave the burst
    key exactly when lanes are on (they ride per row) — since ISSUE 7
    for img2img and inpaint too, while the mode split itself stays."""
    from chiaswarm_tpu.node.worker import _burst_key

    monkeypatch.setenv("CHIASWARM_STEPPER", "0")
    assert _burst_key(_job(0)) != _burst_key(_job(1, num_inference_steps=9))
    monkeypatch.setenv("CHIASWARM_STEPPER", "1")
    assert _burst_key(_job(0)) == _burst_key(_job(1, num_inference_steps=9))
    assert _burst_key(_job(0)) == _burst_key(_job(2, guidance_scale=3.0))
    # image modes relax the per-row fields too (their lanes exist now:
    # strength is a per-row start index)...
    i1 = _burst_key(_job(3, start_image_uri="http://x/i.png",
                         num_inference_steps=2, strength=0.6))
    i2 = _burst_key(_job(4, start_image_uri="http://x/i.png",
                         num_inference_steps=9, strength=0.9))
    assert i1 is not None and i1 == i2
    # ...but never mix with txt2img or inpaint (the mode split holds)
    assert i1 != _burst_key(_job(0))
    assert i1 != _burst_key(_job(5, start_image_uri="http://x/i.png",
                                 mask_image_uri="http://x/m.png"))
    monkeypatch.setenv("CHIASWARM_STEPPER", "0")
    i3 = _burst_key(_job(6, start_image_uri="http://x/i.png",
                         num_inference_steps=2))
    i4 = _burst_key(_job(7, start_image_uri="http://x/i.png",
                         num_inference_steps=9))
    assert i3 != i4  # opt-out restores the strict image-mode keys


def test_worker_health_reports_stepper_counters(monkeypatch, registry,
                                                single_chip_slot):
    """/healthz: step-scheduler counters ride next to the resilience
    stats (lane occupancy, mid-flight admissions, steps executed)."""
    from chiaswarm_tpu.node.executor import synchronous_do_work
    from chiaswarm_tpu.node.settings import Settings
    from chiaswarm_tpu.node.worker import Worker

    monkeypatch.setenv("CHIASWARM_STEPPER", "1")
    synchronous_do_work(_job(20, num_inference_steps=2),
                        single_chip_slot, registry)
    worker = Worker(
        settings=Settings(hive_uri="http://unused", hive_token="t",
                          worker_name="stepper-health"),
        registry=registry, pool=[single_chip_slot], hive=object())
    health = worker.health()
    stepper = health["stepper"]
    assert stepper["enabled"] is True
    assert stepper["rows_completed"] >= 1
    assert stepper["steps_executed"] >= 2
    assert 0.0 <= stepper["lane_occupancy"] <= 1.0


# ---- step-boundary checkpoint / resume (ISSUE 6) -----------------------


def test_pack_unpack_roundtrip_is_bit_exact():
    """Resume state crosses two JSON serializations (spool file ->
    heartbeat -> redelivered job); the array packing must be exact —
    float bits and PRNG key words alike."""
    from chiaswarm_tpu.serving.stepper import pack_array, unpack_array

    rng = np.random.default_rng(7)
    latents = rng.standard_normal((2, 8, 8, 4)).astype(np.float32)
    keys = rng.integers(0, 2**32, size=(2, 2), dtype=np.uint32)
    for arr in (latents, keys):
        spec = pack_array(arr)
        back = unpack_array(spec)
        assert back.dtype == arr.dtype and back.shape == arr.shape
        assert np.array_equal(back, arr)
    # and through an actual JSON round trip
    import json

    back = unpack_array(json.loads(json.dumps(pack_array(latents))))
    assert np.array_equal(back, latents)


class _SpoolSlot:
    """Slot stub carrying only what lanes read: a checkpoint spool."""

    data_width = 1

    def __init__(self, spool):
        self._checkpoint_spool = spool


def test_lane_checkpoint_then_resume_matches_uninterrupted_run(
        tiny_pipe, tmp_path, monkeypatch):
    """The resume equivalence gate: a job restarted from a mid-run lane
    checkpoint (restored latents + keys + multistep history, spliced in
    at step k) finishes with images IDENTICAL to the uninterrupted lane
    run — and its lane info carries the nonzero resume step the
    acceptance criterion asserts on."""
    from chiaswarm_tpu.node.resilience import CheckpointSpool

    monkeypatch.setenv("CHIASWARM_STEPPER_CKPT_EVERY", "1")
    spool = CheckpointSpool(tmp_path / "ckpt")
    sched = StepScheduler(_SpoolSlot(spool))

    fut = sched.submit_request(
        tiny_pipe, prompt="resume me", steps=6, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=77, job_id="ck-1")
    pending, info = fut.result(timeout=300)
    imgs_fresh = pending.wait()
    assert info["resume_step"] == 0  # the uninterrupted run
    assert sched.stats().get("checkpoints_written", 0) >= 1

    # the spool holds the LAST pre-completion snapshot (step k >= 1);
    # hand it to a fresh scheduler as a redelivered job would arrive
    ckpt = spool.load("ck-1")
    assert ckpt is not None and ckpt["kind"] == "lane"
    assert 1 <= ckpt["step"] < 6

    sched2 = StepScheduler()
    fut2 = sched2.submit_request(
        tiny_pipe, prompt="resume me", steps=6, guidance_scale=7.5,
        height=64, width=64, rows=1,
        seed=0,  # deliberately different: resume must not re-derive keys
        job_id="ck-1", resume=ckpt)
    pending2, info2 = fut2.result(timeout=300)
    imgs_resumed = pending2.wait()
    assert info2["resume_step"] == ckpt["step"] >= 1
    assert sched2.stats().get("rows_resumed", 0) == 1
    # bit-identical: same executables, same restored state
    assert np.array_equal(imgs_resumed, imgs_fresh)


def test_resume_validation_rejects_mismatch_and_restarts_clean(
        tiny_pipe, tmp_path, monkeypatch):
    """A checkpoint that does not match the job (tampered steps) or is
    corrupt is rejected loudly: the job still completes — from step 0 —
    and the rejection is counted."""
    from chiaswarm_tpu.node.resilience import CheckpointSpool

    monkeypatch.setenv("CHIASWARM_STEPPER_CKPT_EVERY", "1")
    spool = CheckpointSpool(tmp_path / "ckpt2")
    sched = StepScheduler(_SpoolSlot(spool))
    fut = sched.submit_request(
        tiny_pipe, prompt="tamper", steps=5, guidance_scale=7.0,
        height=64, width=64, rows=1, seed=11, job_id="tp-1")
    imgs_solo = fut.result(timeout=300)[0].wait()

    ckpt = spool.load("tp-1")
    assert ckpt is not None
    tampered = dict(ckpt)
    tampered["steps"] = 9  # claims a different job

    sched2 = StepScheduler()
    fut2 = sched2.submit_request(
        tiny_pipe, prompt="tamper", steps=5, guidance_scale=7.0,
        height=64, width=64, rows=1, seed=11, job_id="tp-1",
        resume=tampered)
    pending2, info2 = fut2.result(timeout=300)
    assert info2["resume_step"] == 0  # restarted clean
    assert sched2.stats().get("resumes_rejected", 0) == 1
    assert np.array_equal(pending2.wait(), imgs_solo)

    # corrupt payloads reject the same way (never crash the submit)
    garbage = dict(ckpt)
    garbage["x"] = {"dtype": "float32", "shape": [1], "b64": "!!!"}
    fut3 = sched2.submit_request(
        tiny_pipe, prompt="tamper", steps=5, guidance_scale=7.0,
        height=64, width=64, rows=1, seed=11, resume=garbage)
    pending3, info3 = fut3.result(timeout=300)
    assert info3["resume_step"] == 0
    assert pending3.wait().shape == (1, 64, 64, 3)

    # a keys array with the right row count but the wrong tail shape
    # must reject at VALIDATION — inside lane admission it would take
    # every co-resident job down via the containment seam
    from chiaswarm_tpu.serving.stepper import pack_array
    bad_keys = dict(ckpt)
    bad_keys["keys"] = pack_array(np.zeros((1, 7), np.uint32))
    fut4 = sched2.submit_request(
        tiny_pipe, prompt="tamper", steps=5, guidance_scale=7.0,
        height=64, width=64, rows=1, seed=11, resume=bad_keys)
    pending4, info4 = fut4.result(timeout=300)
    assert info4["resume_step"] == 0
    assert sched2.stats().get("resumes_rejected", 0) == 3

    # latents stepped under a different guidance must not splice in and
    # finish under this job's guidance (wrong image delivered as a
    # success) — a mixed-up checkpoint restarts clean instead
    wrong_guidance = dict(ckpt)
    wrong_guidance["guidance"] = 3.0
    fut5 = sched2.submit_request(
        tiny_pipe, prompt="tamper", steps=5, guidance_scale=7.0,
        height=64, width=64, rows=1, seed=11, resume=wrong_guidance)
    pending5, info5 = fut5.result(timeout=300)
    assert info5["resume_step"] == 0
    assert sched2.stats().get("resumes_rejected", 0) == 4


def test_phase_checkpoint_resume_is_filtered_not_rejected(
        monkeypatch, registry, single_chip_slot):
    """A redelivered job whose dead worker ran it SOLO carries a
    phase-kind marker, not lane state: the lane path must filter it
    silently (fresh start at step 0) — a routine redelivery, not the
    tamper/corruption signal ``resumes_rejected`` counts."""
    from chiaswarm_tpu.node.executor import synchronous_do_work

    monkeypatch.setenv("CHIASWARM_STEPPER", "1")
    before = single_chip_slot._stepper.stats().get("resumes_rejected", 0) \
        if getattr(single_chip_slot, "_stepper", None) else 0
    result = synchronous_do_work(
        _job(30, num_inference_steps=2,
             resume={"version": 1, "kind": "phase", "phase": "denoised"}),
        single_chip_slot, registry)
    cfg = result["pipeline_config"]
    assert cfg.get("error") is None, cfg
    assert cfg["stepper"]["resume_step"] == 0
    stats = single_chip_slot._stepper.stats()
    assert stats.get("resumes_rejected", 0) == before  # NOT a rejection


def test_checkpoint_spool_hygiene(tmp_path):
    """ISSUE 6 satellite: per-worker namespacing, loud corrupt-file
    skip with a counter, GC on ack, wholesale clear at startup."""
    from chiaswarm_tpu.node.resilience import CheckpointSpool

    spool_a = CheckpointSpool(tmp_path / "checkpoints" / "worker-a")
    spool_b = CheckpointSpool(tmp_path / "checkpoints" / "worker-b")
    spool_a.save("j1", {"kind": "phase", "phase": "encoded"})
    spool_b.save("j1", {"kind": "phase", "phase": "denoised"})
    # namespaced: same job id, two workers, two files
    assert spool_a.load("j1")["phase"] == "encoded"
    assert spool_b.load("j1")["phase"] == "denoised"
    assert spool_a.depth() == spool_b.depth() == 1
    assert spool_a.written == 1

    # corrupt snapshot: skipped loudly, parked as .bad, counted
    path = spool_a.save("j2", {"kind": "lane", "step": 3})
    path.write_text("{truncated", encoding="utf-8")
    assert spool_a.load("j2") is None
    assert spool_a.corrupt_skipped == 1
    assert not path.exists()  # parked as .bad, not retried forever
    assert path.with_suffix(".json.bad").exists()

    # GC on ack removes exactly the acked job's file
    spool_a.save("j3", {"kind": "phase", "phase": "encoded"})
    spool_a.discard("j3")
    assert spool_a.load("j3") is None
    spool_a.discard("never-existed")  # idempotent

    # startup clear wipes leftovers (the hive's copies are authority),
    # including parked .bad corpses and orphaned mid-save .tmp files —
    # otherwise they accumulate forever across restarts
    spool_b.save("j4", {"kind": "phase", "phase": "encoded"})
    (spool_b.directory / "old.ckpt.json.tmp").write_text("{", "utf-8")
    assert spool_a.clear() >= 2            # j1 + the parked j2 .bad
    assert not list(spool_a.directory.glob("*.bad"))
    assert spool_b.clear() >= 2            # j4 + the orphaned .tmp
    assert spool_b.depth() == 0
    assert not list(spool_b.directory.glob("*.tmp"))


def test_checkpoint_spool_version_probe(tmp_path):
    """The heartbeat's has-it-changed probe must advance on EVERY save —
    including several within one filesystem-timestamp tick (coarse-mtime
    mounts), where an mtime-equality probe would report "unchanged" and
    leave a stale snapshot as the hive's resume authority."""
    from chiaswarm_tpu.node.resilience import CheckpointSpool

    spool = CheckpointSpool(tmp_path / "vers")
    assert spool.version("j1") is None  # absent
    spool.save("j1", {"kind": "lane", "step": 1})
    v1 = spool.version("j1")
    spool.save("j1", {"kind": "lane", "step": 2})  # same tick is fine
    v2 = spool.version("j1")
    assert v1 is not None and v2 is not None and v2 > v1
    spool.save("j2", {"kind": "phase", "phase": "encoded"})
    assert spool.version("j2") != spool.version("j1")
    spool.discard("j1")
    assert spool.version("j1") is None
    # a file this process never wrote (external checkpoint_dir) still
    # reads as present
    spool._path_for("ghost").write_text("{}", "utf-8")
    assert spool.version("ghost") == 0
    spool.clear()
    assert spool.version("j2") is None
    # distinct ids that sanitize identically ("job 1" vs "job_1") must
    # never collide onto one file — a collided checkpoint could resume
    # the OTHER job's latent trajectory
    spool.save("job 1", {"kind": "phase", "phase": "encoded"})
    spool.save("job_1", {"kind": "phase", "phase": "denoised"})
    assert spool.load("job 1")["phase"] == "encoded"
    assert spool.load("job_1")["phase"] == "denoised"
    assert spool.depth() == 2


def test_solo_path_records_phase_checkpoints(tmp_path):
    """The solo path's coarse markers (encoded -> denoised) ride the
    same spool through the executor's checkpoint scope; the file is
    GC'd on ack by the worker (covered in the spool hygiene test)."""
    from chiaswarm_tpu.node.resilience import (
        CheckpointSpool, checkpoint_scope, phase_checkpoint)

    spool = CheckpointSpool(tmp_path / "phases")
    phase_checkpoint("orphan")  # outside any scope: silent no-op
    assert spool.depth() == 0
    with checkpoint_scope(spool, "solo-1"):
        phase_checkpoint("encoded", model="tiny")
        assert spool.load("solo-1")["phase"] == "encoded"
        phase_checkpoint("denoised", model="tiny", generation_s=1.25)
    state = spool.load("solo-1")
    assert state["phase"] == "denoised"
    assert state["generation_s"] == 1.25
    # a None spool (stub slot, feature off) makes the scope a no-op
    with checkpoint_scope(None, "solo-2"):
        phase_checkpoint("encoded")
    assert spool.load("solo-2") is None


# ---------------------------------------------------------------------------
# ISSUE 7b: workload splice-equivalence gates (img2img / inpaint / ControlNet)
# ---------------------------------------------------------------------------


def _rng_image(seed: int, size: int = 64) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.integers(0, 255, (size, size, 3), dtype=np.uint8)


def _half_mask(size: int = 64) -> np.ndarray:
    mask = np.zeros((size, size), np.float32)
    mask[size // 2:] = 1.0
    return mask


def test_img2img_row_spliced_midflight_matches_solo(tiny_pipe):
    """ISSUE 7 gate: an img2img job (nonzero strength-derived start
    index) splices into a lane already mid-flight with a txt2img row,
    and BOTH match their solo runs — the per-row start index walks the
    identical truncated ladder."""
    init = _rng_image(70)
    sched = StepScheduler()
    base = sched.stats().get("steps_executed", 0)
    fa = sched.submit_request(
        tiny_pipe, prompt="resident txt2img", steps=16, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=71)
    _wait_steps(sched, base + 1)
    fb = sched.submit_request(
        tiny_pipe, prompt="late img2img", steps=6, guidance_scale=5.5,
        height=64, width=64, rows=1, seed=72,
        init_image=init, strength=0.5)
    pending_b, info_b = fb.result(timeout=300)
    pending_a, info_a = fa.result(timeout=300)
    img_a, img_b = pending_a.wait(), pending_b.wait()
    assert info_b["lane"] == info_a["lane"]  # one shared lane program
    assert 1 <= info_b["admitted_at_step"] < 16  # genuinely mid-flight
    sched.shutdown()

    solo_a, _ = tiny_pipe(GenerateRequest(
        prompt="resident txt2img", steps=16, guidance_scale=7.5,
        height=64, width=64, seed=71))
    solo_b, cfg_b = tiny_pipe(GenerateRequest(
        prompt="late img2img", steps=6, guidance_scale=5.5,
        height=64, width=64, seed=72, init_image=init, strength=0.5))
    assert cfg_b["mode"] == "img2img"
    assert cfg_b["denoise_steps"] < 6  # the truncated ladder engaged
    _close(img_a, solo_a)
    _close(img_b, solo_b)


def test_inpaint_row_spliced_midflight_matches_solo(tiny_pipe):
    """ISSUE 7 gate: an inpaint row (latent mask + clean source latents
    as lane row state, re-projected every step) admitted mid-flight
    matches its solo trajectory; the co-resident txt2img row is
    untouched by the inpaint math (per-row mask_on selection)."""
    init = _rng_image(75)
    mask = _half_mask()
    sched = StepScheduler()
    base = sched.stats().get("steps_executed", 0)
    fa = sched.submit_request(
        tiny_pipe, prompt="resident txt2img", steps=16, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=76)
    _wait_steps(sched, base + 1)
    fb = sched.submit_request(
        tiny_pipe, prompt="late inpaint", steps=5, guidance_scale=6.0,
        height=64, width=64, rows=1, seed=77,
        init_image=init, mask=mask)
    pending_b, info_b = fb.result(timeout=300)
    pending_a, info_a = fa.result(timeout=300)
    img_a, img_b = pending_a.wait(), pending_b.wait()
    assert info_b["lane"] == info_a["lane"]
    assert 1 <= info_b["admitted_at_step"] < 16
    sched.shutdown()

    solo_a, _ = tiny_pipe(GenerateRequest(
        prompt="resident txt2img", steps=16, guidance_scale=7.5,
        height=64, width=64, seed=76))
    solo_b, cfg_b = tiny_pipe(GenerateRequest(
        prompt="late inpaint", steps=5, guidance_scale=6.0,
        height=64, width=64, seed=77, init_image=init, mask=mask))
    assert cfg_b["mode"] == "inpaint"
    _close(img_a, solo_a)
    _close(img_b, solo_b)


def test_controlnet_rows_ride_bundle_keyed_lane_and_match_solo(tiny_pipe):
    """ISSUE 7 gate: ControlNet jobs ride a lane keyed by their bundle
    (per-row pre-embedded hints + conditioning scales), match the solo
    program, and never share a lane with plain txt2img rows."""
    from chiaswarm_tpu.pipelines.components import ControlNetBundle

    bundle = ControlNetBundle.random("tiny", seed=5)
    cond = _rng_image(80)
    sched = StepScheduler()
    fa = sched.submit_request(
        tiny_pipe, prompt="plain", steps=6, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=81)
    fb = sched.submit_request(
        tiny_pipe, prompt="controlled", steps=6, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=82,
        controlnet=bundle, control_image=cond, control_scale=0.8)
    pending_a, info_a = fa.result(timeout=300)
    pending_b, info_b = fb.result(timeout=300)
    img_a, img_b = pending_a.wait(), pending_b.wait()
    assert info_a["lane"] != info_b["lane"]  # bundle keys the lane
    sched.shutdown()

    solo_a, _ = tiny_pipe(GenerateRequest(
        prompt="plain", steps=6, guidance_scale=7.5, height=64, width=64,
        seed=81))
    solo_b, cfg_b = tiny_pipe(GenerateRequest(
        prompt="controlled", steps=6, guidance_scale=7.5, height=64,
        width=64, seed=82, controlnet=bundle, control_image=cond,
        control_scale=0.8))
    assert cfg_b.get("controlnet") is not None
    _close(img_a, solo_a)
    _close(img_b, solo_b)


def test_workload_admission_never_compiles_once_warm(tiny_pipe, monkeypatch):
    """The ISSUE-7 acceptance criterion for the new workloads: once the
    lane bucket (and the per-workload admission prep: init-latent
    encode, hint embed) is warm, admitting img2img / inpaint /
    ControlNet rows with new strengths, masks, scales and step counts
    compiles NOTHING — all per-row state, no per-job programs. Width is
    pinned so the adaptive controller cannot add lattice compiles."""
    from chiaswarm_tpu.pipelines.components import ControlNetBundle

    monkeypatch.setenv("CHIASWARM_STEPPER_LANE_WIDTH", "4")
    bundle = ControlNetBundle.random("tiny", seed=6)
    init, cond = _rng_image(85), _rng_image(86)
    sched = StepScheduler()
    # warm: one job per workload
    warm = [
        sched.submit_request(tiny_pipe, prompt="w1", steps=5,
                             guidance_scale=7.5, height=64, width=64,
                             rows=1, seed=1, init_image=init,
                             strength=0.6),
        sched.submit_request(tiny_pipe, prompt="w2", steps=5,
                             guidance_scale=7.5, height=64, width=64,
                             rows=1, seed=2, init_image=init,
                             mask=_half_mask()),
        sched.submit_request(tiny_pipe, prompt="w3", steps=5,
                             guidance_scale=7.5, height=64, width=64,
                             rows=1, seed=3, controlnet=bundle,
                             control_image=cond),
    ]
    for fut in warm:
        fut.result(timeout=300)[0].wait()
    before = GLOBAL_CACHE.executables.stats["misses"]
    checker = np.indices((64, 64)).sum(axis=0) % 2
    futs = [
        sched.submit_request(tiny_pipe, prompt="i2i", steps=7,
                             guidance_scale=4.0, height=64, width=64,
                             rows=1, seed=10, init_image=init,
                             strength=0.35),
        sched.submit_request(tiny_pipe, prompt="inp", steps=9,
                             guidance_scale=8.5, height=64, width=64,
                             rows=1, seed=11, init_image=init,
                             mask=checker.astype(np.float32)),
        sched.submit_request(tiny_pipe, prompt="ctl", steps=4,
                             guidance_scale=6.5, height=64, width=64,
                             rows=1, seed=12, controlnet=bundle,
                             control_image=_rng_image(87),
                             control_scale=0.3),
    ]
    for fut in futs:
        fut.result(timeout=300)[0].wait()
    after = GLOBAL_CACHE.executables.stats["misses"]
    sched.shutdown()
    assert after == before, (before, after)
    admitted = sched.stats()
    assert admitted.get("rows_admitted_img2img", 0) >= 2
    assert admitted.get("rows_admitted_inpaint", 0) >= 2
    assert admitted.get("rows_admitted_controlnet", 0) >= 2


def test_resume_rejects_workload_mismatch(tiny_pipe):
    """A checkpoint stepped down a different ladder suffix (txt2img from
    step 0) must not finish under an img2img job's identity — the
    workload/start fields are part of resume validation."""
    from chiaswarm_tpu.core.rng import key_for_seed
    from chiaswarm_tpu.serving.stepper import ResumeReject, pack_array

    lh, lw = tiny_pipe._latent_hw(64, 64)
    ch = tiny_pipe.c.family.vae.latent_channels
    template = np.asarray(key_for_seed(0))
    ck = {
        "kind": "lane", "step": 4, "steps": 6, "rows": 1,
        "height": 64, "width": 64, "guidance": 7.5,
        "workload": "txt2img", "start": 0,
        "x": pack_array(np.zeros((1, lh, lw, ch), np.float32)),
        "keys": pack_array(np.zeros((1,) + template.shape,
                                    template.dtype)),
        "old": pack_array(np.zeros((1, lh, lw, ch), np.float32)),
    }
    sched = StepScheduler()
    with pytest.raises(ResumeReject, match="workload mismatch"):
        sched._validate_resume(tiny_pipe, ck, steps=6, rows=1, height=64,
                               width=64, guidance=7.5, start=3,
                               workload="img2img")
    # the same payload IS valid for the txt2img identity it came from
    step, restored = sched._validate_resume(
        tiny_pipe, ck, steps=6, rows=1, height=64, width=64,
        guidance=7.5, start=0, workload="txt2img")
    assert step == 4 and set(restored) == {"x", "keys", "old"}


# ---------------------------------------------------------------------------
# ISSUE 7c: adaptive lane width — control-loop units + lane integration
# ---------------------------------------------------------------------------


class TestLaneWidthController:
    """Pure host-arithmetic units for the closed loop (no lanes, no jax):
    grow under burst, shrink under trickle, patience gating, OOM width
    limits, and the never-evict-residents floor."""

    def _ctl(self, **over):
        from chiaswarm_tpu.serving.stepper import LaneWidthController

        kw = dict(min_width=1, max_width=16, patience=3)
        kw.update(over)
        return LaneWidthController(**kw)

    def test_grow_under_burst_is_immediate(self):
        # pending rows that cannot fit resize NOW, onto the pow2 bucket
        ctl = self._ctl()
        assert ctl.decide(2, 2, 3, rate=1.0) == 8  # need 5 -> bucket 8

    def test_burst_growth_respects_max_width(self):
        ctl = self._ctl(max_width=4)
        assert ctl.decide(2, 2, 30, rate=5.0) == 4

    def test_grow_under_sustained_occupancy_needs_arrivals(self):
        ctl = self._ctl(alpha=1.0, grow_at=0.9, patience=2)
        assert ctl.decide(4, 4, 0, rate=2.0) == 4   # patience not met
        assert ctl.decide(4, 4, 0, rate=2.0) == 8   # sustained + flowing
        ctl2 = self._ctl(alpha=1.0, grow_at=0.9, patience=2)
        ctl2.decide(4, 4, 0, rate=0.0)
        # a full lane with NO arrivals holds: growing buys nothing
        assert ctl2.decide(4, 4, 0, rate=0.0) == 4

    def test_shrink_under_trickle_needs_patience(self):
        ctl = self._ctl(patience=3)
        assert ctl.decide(8, 1, 0, rate=0.0) == 8
        assert ctl.decide(8, 1, 0, rate=0.0) == 8
        assert ctl.decide(8, 1, 0, rate=0.0) == 4  # patience met: halve
        # and the counter re-arms after the resize
        assert ctl.decide(4, 1, 0, rate=0.0) == 4

    def test_never_shrinks_with_rows_pending(self):
        ctl = self._ctl(patience=1)
        for _ in range(8):
            assert ctl.decide(8, 1, 1, rate=0.1) == 8

    def test_oom_width_limit_clamps_the_next_decision(self):
        # note_oom's halved cap arrives as max_width: applied on the
        # very next boundary, patience or not
        ctl = self._ctl()
        assert ctl.decide(8, 1, 0, rate=0.0, max_width=4) == 4

    def test_width_never_drops_below_resident_rows(self):
        # an OOM cap below current occupancy must NOT evict residents:
        # the floor is the bucket holding every occupied row
        ctl = self._ctl()
        assert ctl.decide(8, 5, 0, rate=0.0, max_width=2) == 8


def test_adaptive_lane_grows_midflight_and_rows_stay_solo_exact(
        tiny_pipe, monkeypatch):
    """Lane integration for the closed loop: a lane opened narrow grows
    at a step boundary when a burst cannot fit — never mid-step — and
    the resident row's trajectory survives the resize (device state
    compaction) bit-compatibly with its solo run."""
    monkeypatch.delenv("CHIASWARM_STEPPER_LANE_WIDTH", raising=False)
    monkeypatch.setenv("CHIASWARM_STEPPER_MIN_WIDTH", "2")
    sched = StepScheduler()
    base = sched.stats().get("steps_executed", 0)
    fa = sched.submit_request(
        tiny_pipe, prompt="resident", steps=16, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=91)
    _wait_steps(sched, base + 1)
    late = [sched.submit_request(
        tiny_pipe, prompt=f"burst {i}", steps=4 + i, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=92 + i) for i in range(3)]
    results = [fut.result(timeout=300) for fut in late]
    imgs = [pending.wait() for pending, _ in results]
    pending_a, info_a = fa.result(timeout=300)
    img_a = pending_a.wait()
    stats = sched.stats()
    sched.shutdown()

    assert stats.get("lane_resizes", 0) >= 1, stats  # the loop closed
    # the burst retired from a GROWN lane (>= 4 rows; the long resident
    # may legitimately see the lane shrink again before it retires)
    assert max(info["lane_width"] for _, info in results) >= 4, results
    solo_a, _ = tiny_pipe(GenerateRequest(
        prompt="resident", steps=16, guidance_scale=7.5, height=64,
        width=64, seed=91))
    _close(img_a, solo_a)
    for i, img in enumerate(imgs):
        solo, _ = tiny_pipe(GenerateRequest(
            prompt=f"burst {i}", steps=4 + i, guidance_scale=7.5,
            height=64, width=64, seed=92 + i))
        _close(img, solo)


def test_adaptive_resize_compiles_only_new_lattice_widths(
        tiny_pipe, monkeypatch):
    """Resizes stay on the compile-cache lattice: the first pass through
    a traffic pattern compiles its widths once; an identical second
    pass (fresh scheduler, same widths) compiles NOTHING — growth is a
    cache hit, and admission itself never compiles either way."""
    monkeypatch.delenv("CHIASWARM_STEPPER_LANE_WIDTH", raising=False)
    monkeypatch.setenv("CHIASWARM_STEPPER_MIN_WIDTH", "2")

    def one_pass():
        sched = StepScheduler()
        base = sched.stats().get("steps_executed", 0)
        first = sched.submit_request(
            tiny_pipe, prompt="lead", steps=8, guidance_scale=7.5,
            height=64, width=64, rows=1, seed=95)
        _wait_steps(sched, base + 1)
        rest = [sched.submit_request(
            tiny_pipe, prompt=f"tail {i}", steps=5, guidance_scale=7.5,
            height=64, width=64, rows=1, seed=96 + i) for i in range(3)]
        for fut in [first] + rest:
            fut.result(timeout=300)[0].wait()
        resizes = sched.stats().get("lane_resizes", 0)
        sched.shutdown()
        return resizes

    assert one_pass() >= 1  # warm pass: the growth widths compile here
    before = GLOBAL_CACHE.executables.stats["misses"]
    one_pass()
    after = GLOBAL_CACHE.executables.stats["misses"]
    assert after == before, (before, after)


# ---- overload hooks (ISSUE 9): eviction retire + admission cap ---------


def test_eviction_retire_hook_frees_idle_lane_immediately(
        tiny_pipe, monkeypatch):
    """ISSUE 9 satellite: an idle lane asked to retire by the residency
    eviction hook frees its device state NOW — long before the idle
    grace (pinned to 10 minutes here so it provably wasn't the
    timeout), counted as lanes_evict_retired."""
    monkeypatch.setenv("CHIASWARM_STEPPER_IDLE_S", "600")
    from chiaswarm_tpu.serving.stepper import retire_lanes_for_owner

    sched = StepScheduler()
    fut = sched.submit_request(
        tiny_pipe, prompt="soon evicted", steps=3, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=41)
    fut.result(timeout=300)[0].wait()
    assert sched.stats()["lanes_live"] == 1  # idle but resident

    assert retire_lanes_for_owner(id(tiny_pipe.c)) >= 1
    end = time.monotonic() + 30
    while time.monotonic() < end and sched.stats()["lanes_live"]:
        time.sleep(0.02)
    stats = sched.stats()
    assert stats["lanes_live"] == 0, stats
    assert stats.get("lanes_evict_retired", 0) >= 1
    # rows were never harmed: nothing failed or expired
    assert stats.get("rows_failed", 0) == 0


def test_eviction_retire_waits_for_resident_rows(tiny_pipe, monkeypatch):
    """A BUSY lane asked to retire finishes its resident rows first
    (their params are still live on device), then retires at drain —
    the in-flight job completes normally."""
    monkeypatch.setenv("CHIASWARM_STEPPER_IDLE_S", "600")
    monkeypatch.setenv("CHIASWARM_STEPPER_STEP_DELAY_S", "0.05")
    from chiaswarm_tpu.serving.stepper import retire_lanes_for_owner

    sched = StepScheduler()
    base = sched.stats().get("steps_executed", 0)
    fut = sched.submit_request(
        tiny_pipe, prompt="evicted mid-flight", steps=10,
        guidance_scale=7.5, height=64, width=64, rows=1, seed=42)
    _wait_steps(sched, base + 2)
    assert retire_lanes_for_owner(id(tiny_pipe.c)) >= 1
    pending, _info = fut.result(timeout=300)
    assert pending.wait().shape[0] == 1      # the job completed
    end = time.monotonic() + 30
    while time.monotonic() < end and sched.stats()["lanes_live"]:
        time.sleep(0.02)
    stats = sched.stats()
    assert stats["lanes_live"] == 0, stats
    assert stats.get("rows_failed", 0) == 0
    assert stats.get("rows_completed", 0) >= 1


def test_admission_cap_throttles_rows_per_boundary(tiny_pipe, monkeypatch):
    """The brownout rung (node/overload.py via set_admission_cap): with
    cap=1, two jobs pending at the same boundary splice in one per
    boundary; the uncapped control admits both at once. The cap can
    never wedge a job wider than itself (first admit always allowed)."""
    monkeypatch.setenv("CHIASWARM_STEPPER_LANE_WIDTH", "4")
    monkeypatch.setenv("CHIASWARM_STEPPER_STEP_DELAY_S", "0.2")

    def run_pair(cap):
        sched = StepScheduler()
        if cap is not None:
            sched.set_admission_cap(cap)
            assert sched.admission_cap() == cap
        base = sched.stats().get("steps_executed", 0)
        lead = sched.submit_request(
            tiny_pipe, prompt="lead", steps=12, guidance_scale=7.5,
            height=64, width=64, rows=1, seed=51)
        _wait_steps(sched, base + 1)
        pair = [sched.submit_request(
            tiny_pipe, prompt=f"pending {i}", steps=3,
            guidance_scale=7.5, height=64, width=64, rows=1,
            seed=52 + i) for i in range(2)]
        infos = [fut.result(timeout=300)[1] for fut in pair]
        lead.result(timeout=300)[0].wait()
        sched.shutdown()
        return [info["admitted_at_step"] for info in infos]

    capped = run_pair(1)
    assert capped[0] != capped[1], capped      # one row per boundary
    uncapped = run_pair(None)
    assert uncapped[0] == uncapped[1], uncapped  # both splice together
