"""UperNet segmentation tests: HF torch fidelity + seg preprocessor wiring.

The reference's seg mode runs ``openmmlab/upernet-convnext-small``
through transformers (swarm/controlnet/input_processor.py:96-115); these
pin the native port (models/upernet.py) to HF's torch model on tiny
widths and cover the weight-gated preprocessor path with its ADE-palette
output.
"""

from __future__ import annotations

import numpy as np
import pytest

from chiaswarm_tpu.models.upernet import (
    UPERNET_TINY,
    UperNetDetector,
    UperNetSeg,
)


def _hf_tiny():
    torch = pytest.importorskip("torch")
    from transformers import ConvNextConfig, UperNetConfig
    from transformers import UperNetForSemanticSegmentation

    backbone = ConvNextConfig(
        depths=[1, 1, 1, 1], hidden_sizes=[8, 16, 24, 32],
        out_features=["stage1", "stage2", "stage3", "stage4"],
        drop_path_rate=0.0)
    cfg = UperNetConfig(
        backbone_config=backbone, hidden_size=16, pool_scales=[1, 2, 3, 6],
        num_labels=10, use_auxiliary_head=True, auxiliary_in_channels=24)
    torch.manual_seed(0)
    model = UperNetForSemanticSegmentation(cfg).eval()
    sd = model.state_dict()
    gen = torch.Generator().manual_seed(5)
    for key, value in sd.items():
        if value.dtype.is_floating_point and "running" not in key:
            sd[key] = torch.randn(value.shape, generator=gen) * 0.05
        elif key.endswith("running_var"):
            sd[key] = torch.rand(value.shape, generator=gen) + 0.5
        elif key.endswith("running_mean"):
            sd[key] = torch.randn(value.shape, generator=gen) * 0.1
    model.load_state_dict(sd)
    return torch, model


@pytest.mark.slow
def test_upernet_conversion_matches_torch():
    torch, hf = _hf_tiny()
    import jax.numpy as jnp

    from chiaswarm_tpu.convert.torch_to_flax import convert_upernet

    state = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    params = convert_upernet(state)
    x = np.random.RandomState(1).randn(1, 64, 64, 3).astype(np.float32)
    with torch.no_grad():
        tl = hf(torch.from_numpy(x.transpose(0, 3, 1, 2))).logits
        tseg = tl.argmax(dim=1).numpy().astype(np.uint8)
    fseg = np.asarray(UperNetSeg(UPERNET_TINY).apply(params,
                                                     jnp.asarray(x)))
    assert fseg.shape == tseg.shape
    # argmax maps must agree except where the top-2 logits are within
    # float tolerance of each other
    agree = (fseg == tseg).mean()
    assert agree > 0.99, agree


def test_detector_runs_and_colors_with_ade_palette():
    from chiaswarm_tpu.workloads.ade_palette import ADE20K_PALETTE

    det = UperNetDetector.random(seed=0)
    img = (np.random.RandomState(0).rand(50, 70, 3) * 255).astype(np.uint8)
    out = det(img)
    assert out.shape == (50, 70, 3) and out.dtype == np.uint8
    palette = {tuple(c) for c in ADE20K_PALETTE}
    colors = {tuple(c) for c in out.reshape(-1, 3)[::17]}
    assert colors <= palette


def test_ade_palette_matches_reference_table():
    from chiaswarm_tpu.workloads.ade_palette import ADE20K_PALETTE

    assert ADE20K_PALETTE.shape == (151, 3)
    assert tuple(ADE20K_PALETTE[0]) == (0, 0, 0)
    assert tuple(ADE20K_PALETTE[1]) == (120, 120, 120)
    assert tuple(ADE20K_PALETTE[4]) == (80, 50, 50)


def test_seg_preprocessor_uses_upernet_when_present(monkeypatch):
    from PIL import Image

    from chiaswarm_tpu.workloads import controlnet as wl

    monkeypatch.setattr(wl, "_SEG", [UperNetDetector.random(seed=1)])
    out = wl.preprocess_image(Image.new("RGB", (64, 48), (12, 160, 90)),
                              {"type": "seg", "preprocess": True})
    assert np.asarray(out).shape == (48, 64, 3)


def test_seg_preprocessor_falls_back(tmp_path, monkeypatch):
    from PIL import Image

    from chiaswarm_tpu.workloads import controlnet as wl

    monkeypatch.setenv("SDAAS_ROOT", str(tmp_path))
    monkeypatch.setattr(wl, "_SEG", [])
    out = wl.preprocess_image(Image.new("RGB", (64, 48), (12, 160, 90)),
                              {"type": "seg", "preprocess": True})
    assert np.asarray(out).shape == (48, 64, 3)
    assert wl._SEG == [None]
