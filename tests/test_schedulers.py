import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chiaswarm_tpu.schedulers import (
    SamplerConfig,
    add_noise,
    init_noise_scale,
    make_noise_schedule,
    make_sampling_schedule,
    resolve,
    sampler_step,
    scale_model_input,
    velocity_target,
)
from chiaswarm_tpu.schedulers.common import (
    ScheduleConfig,
    denoised_from_model_output,
    karras_sigmas,
    sigma_to_timestep,
)
from chiaswarm_tpu.schedulers.sampling import init_sampler_state


def test_beta_schedules():
    for sched_name in ("linear", "scaled_linear", "squaredcos_cap_v2"):
        cfg = ScheduleConfig(beta_schedule=sched_name)
        ns = make_noise_schedule(cfg)
        assert ns.betas.shape == (1000,)
        assert (np.asarray(ns.betas) > 0).all()
        acp = np.asarray(ns.alphas_cumprod)
        assert (np.diff(acp) < 0).all()  # strictly decreasing
        assert (np.diff(np.asarray(ns.sigmas)) > 0).all()  # sigma increasing in t


def test_karras_sigmas_descending():
    s = np.asarray(karras_sigmas(jnp.float32(0.03), jnp.float32(14.6), 30))
    assert s.shape == (30,)
    assert np.isclose(s[0], 14.6, rtol=1e-5)
    assert np.isclose(s[-1], 0.03, rtol=1e-5)
    assert (np.diff(s) < 0).all()


def test_sigma_timestep_roundtrip():
    ns = make_noise_schedule(ScheduleConfig())
    ts = sigma_to_timestep(ns, ns.sigmas[jnp.array([10, 500, 990])])
    assert np.allclose(np.asarray(ts), [10, 500, 990], atol=1e-3)


def test_add_noise_and_velocity_shapes():
    ns = make_noise_schedule(ScheduleConfig())
    x0 = jnp.ones((2, 4, 8, 8))
    noise = jnp.zeros_like(x0)
    t = jnp.array([0, 999])
    noised = add_noise(ns, x0, noise, t)
    # t=0: nearly clean; t=999: nearly zero signal
    assert np.asarray(noised)[0].mean() > 0.99
    assert abs(np.asarray(noised)[1].mean()) < 0.1
    v = velocity_target(ns, x0, noise, t)
    assert v.shape == x0.shape


def test_denoised_conversions_consistent():
    # x = x0 + sigma*eps ; epsilon- and v-param model outputs describing the
    # same state must give the same denoised estimate.
    rng = np.random.default_rng(0)
    x0 = jnp.asarray(rng.normal(size=(1, 4, 4, 4)), dtype=jnp.float32)
    eps = jnp.asarray(rng.normal(size=x0.shape), dtype=jnp.float32)
    sigma = jnp.float32(3.7)
    x = x0 + sigma * eps
    d_eps = denoised_from_model_output(eps, x, sigma, "epsilon")
    # v in VP coords: v = alpha*eps - sigma_vp*x0 with alpha=1/sqrt(1+s^2)
    alpha = 1.0 / jnp.sqrt(1 + sigma ** 2)
    v = alpha * eps - (sigma * alpha) * x0
    d_v = denoised_from_model_output(v, x, sigma, "v_prediction")
    assert np.allclose(np.asarray(d_eps), np.asarray(x0), atol=1e-5)
    assert np.allclose(np.asarray(d_v), np.asarray(x0), atol=1e-4)


@pytest.mark.parametrize("kind", ["euler", "ddim", "dpmpp_2m", "euler_ancestral"])
@pytest.mark.parametrize("karras", [True, False])
def test_sampler_recovers_x0_with_oracle_model(kind, karras):
    """With an oracle model (perfect epsilon prediction), every sampler must
    walk the noise ladder down to exactly x0."""
    cfg = SamplerConfig(kind=kind, use_karras_sigmas=karras)
    ns = make_noise_schedule(ScheduleConfig())
    sched = make_sampling_schedule(ns, 12, cfg)

    sigmas = np.asarray(sched.sigmas)
    assert sigmas[-1] == 0.0
    assert (np.diff(sigmas[:-1]) < 0).all()
    assert sched.timesteps.shape == (12,)
    ts = np.asarray(sched.timesteps)
    assert (ts >= 0).all() and (ts <= 999).all()

    rng = np.random.default_rng(1)
    x0 = jnp.asarray(rng.normal(size=(1, 4, 8, 8)), dtype=jnp.float32)
    noise = jnp.asarray(rng.normal(size=x0.shape), dtype=jnp.float32)
    x = noise * init_noise_scale(sched)

    state = init_sampler_state(x)
    zero_noise = jnp.zeros_like(x)
    for i in range(12):
        sigma = sched.sigmas[i]
        eps = (x - x0) / sigma  # oracle
        scaled = scale_model_input(sched, x, jnp.int32(i))
        assert np.isfinite(np.asarray(scaled)).all()
        x, state = sampler_step(cfg, sched, jnp.int32(i), x, eps, state,
                                noise=zero_noise)
    assert np.allclose(np.asarray(x), np.asarray(x0), atol=1e-4)


def test_sampler_step_is_scannable_and_jittable():
    cfg = SamplerConfig(kind="dpmpp_2m", use_karras_sigmas=True)
    ns = make_noise_schedule(ScheduleConfig())
    n_steps = 8
    sched = make_sampling_schedule(ns, n_steps, cfg)
    x0 = jnp.full((1, 4, 4, 4), 0.5, dtype=jnp.float32)

    @jax.jit
    def run(x_init):
        def body(carry, i):
            x, state = carry
            eps = (x - x0) / sched.sigmas[i]
            x, state = sampler_step(cfg, sched, i, x, eps, state)
            return (x, state), None

        state = init_sampler_state(x_init)
        (x, _), _ = jax.lax.scan(body, (x_init, state), jnp.arange(n_steps))
        return x

    key = jax.random.PRNGKey(0)
    x_init = jax.random.normal(key, x0.shape) * init_noise_scale(sched)
    out = run(x_init)
    assert np.allclose(np.asarray(out), 0.5, atol=1e-3)


def test_dpmpp_2m_beats_euler_on_curved_oracle():
    """Second-order multistep should track a curved denoiser trajectory more
    closely than first-order Euler at equal step count."""
    ns = make_noise_schedule(ScheduleConfig())

    def run(kind, n=6):
        cfg = SamplerConfig(kind=kind, use_karras_sigmas=True)
        sched = make_sampling_schedule(ns, n, cfg)
        x0 = jnp.full((1, 2, 2, 2), 1.0, dtype=jnp.float32)
        x = jnp.full(x0.shape, 0.0) + init_noise_scale(sched) * jnp.ones_like(x0)
        state = init_sampler_state(x)
        for i in range(n):
            sigma = sched.sigmas[i]
            # curved oracle: denoised estimate drifts with sigma
            denoised = x0 * (1.0 - 0.3 * sigma / (1.0 + sigma))
            eps = (x - denoised) / sigma
            x, state = sampler_step(cfg, sched, jnp.int32(i), x, eps, state)
        return np.abs(np.asarray(x) - 1.0).mean()

    assert run("dpmpp_2m") <= run("euler") + 1e-6


def test_resolve_scheduler_names():
    assert resolve("DPMSolverMultistepScheduler").kind == "dpmpp_2m"
    assert resolve("EulerDiscreteScheduler").kind == "euler"
    assert resolve("DDIMScheduler").kind == "ddim"
    assert resolve(None).kind == "dpmpp_2m"
    cfg = resolve("DDIMScheduler", prediction_type="v_prediction")
    assert cfg.prediction_type == "v_prediction"
    assert dataclasses.asdict(cfg)  # dataclass, hashable-able config


# ---------- golden trajectories vs the independent VP-coordinate oracle ----

class _GoldenHelper:
    """Run the framework's scan-compatible sampler loop with the oracle's
    mock model, in k-diffusion coordinates (fixtures are kd-space; see
    tests/make_scheduler_fixtures.py)."""

    @staticmethod
    def run(kind: str, n: int, use_karras: bool, x0: np.ndarray,
            noises: np.ndarray | None = None) -> np.ndarray:
        from tests.scheduler_oracle import mock_eps

        cfg = SamplerConfig(kind=kind, use_karras_sigmas=use_karras)
        ns = make_noise_schedule(ScheduleConfig())
        sched = make_sampling_schedule(ns, n, cfg)
        x = jnp.asarray(x0, jnp.float32)
        state = init_sampler_state(x)
        traj = []
        for i in range(n):
            inp = scale_model_input(sched, x, jnp.asarray(i))
            eps = mock_eps(np.asarray(inp, np.float64),
                           float(sched.timesteps[i]))
            nz = (jnp.asarray(noises[i], jnp.float32)
                  if noises is not None else jnp.zeros_like(x))
            x, state = sampler_step(cfg, sched, jnp.asarray(i), x,
                                    jnp.asarray(eps, jnp.float32), state,
                                    noise=nz, start_index=0)
            traj.append(np.asarray(x, np.float64))
        return np.stack(traj)


@pytest.fixture(scope="module")
def golden():
    import pathlib

    path = pathlib.Path(__file__).parent / "fixtures" / "scheduler_golden.npz"
    return np.load(path)


@pytest.mark.parametrize("n", [8, 20])
def test_golden_dpmpp_2m_karras(golden, n):
    """The reference's forced scheduler — DPMSolverMultistep + Karras
    (swarm/diffusion/diffusion_func.py:71-74). Ladder AND trajectory must
    match the VP-coordinate oracle step for step."""
    sig = golden[f"dpmpp_2m_{n}/sigmas"]
    cfg = SamplerConfig(kind="dpmpp_2m", use_karras_sigmas=True)
    ns = make_noise_schedule(ScheduleConfig())
    sched = make_sampling_schedule(ns, n, cfg)
    np.testing.assert_allclose(np.asarray(sched.sigmas), sig, rtol=2e-5,
                               atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(sched.timesteps), golden[f"dpmpp_2m_{n}/timesteps"],
        rtol=0, atol=2e-3)

    x0 = golden[f"init_unit_{n}"] * sig[0]
    ours = _GoldenHelper.run("dpmpp_2m", n, True, x0)
    ref = golden[f"dpmpp_2m_{n}/traj"]
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("n", [8, 20])
def test_golden_euler_karras(golden, n):
    sig = golden[f"euler_{n}/sigmas"]
    x0 = golden[f"init_unit_{n}"] * sig[0]
    ours = _GoldenHelper.run("euler", n, True, x0)
    np.testing.assert_allclose(ours, golden[f"euler_{n}/traj"],
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("n", [8, 20])
def test_golden_ddim_discrete_grid(golden, n):
    """Deterministic DDIM (VP coordinates, diffusers leading spacing) must
    equal our sigma-space euler/ddim step on the discrete grid — the
    change-of-variables identity the sampling module claims."""
    x0 = golden[f"init_unit_{n}"] * float(golden[f"ddim_{n}/sigma0"])
    ours = _GoldenHelper.run("ddim", n, False, x0)
    np.testing.assert_allclose(ours, golden[f"ddim_{n}/traj"],
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("n", [8, 20])
def test_golden_euler_ancestral(golden, n):
    sig = golden[f"euler_ancestral_{n}/sigmas"]
    x0 = golden[f"init_unit_{n}"] * sig[0]
    noises = golden[f"noises_{n}"]
    ours = _GoldenHelper.run("euler_ancestral", n, False, x0, noises=noises)
    np.testing.assert_allclose(ours, golden[f"euler_ancestral_{n}/traj"],
                               rtol=2e-4, atol=2e-4)
