"""Test-side inverse exporter: Flax param trees -> HF-diffusers torch state
dicts. Written independently of chiaswarm_tpu.convert (maps the *other*
direction) so a naming bug in the converter cannot cancel out in tests."""

from __future__ import annotations

import re

import numpy as np


def _flatten(tree, prefix=""):
    for key, value in tree.items():
        path = f"{prefix}/{key}" if prefix else key
        if isinstance(value, dict):
            yield from _flatten(value, path)
        else:
            yield path, np.asarray(value)


def _leaf(torch_key_base: str, leaf: str, value: np.ndarray,
          out: dict) -> None:
    if leaf == "kernel":
        if value.ndim == 4:
            out[f"{torch_key_base}.weight"] = value.transpose(3, 2, 0, 1)
        else:
            out[f"{torch_key_base}.weight"] = value.T
    elif leaf == "scale":
        out[f"{torch_key_base}.weight"] = value
    elif leaf == "embedding":
        out[f"{torch_key_base}.weight"] = value
    else:
        out[f"{torch_key_base}.{leaf}"] = value


def _attn_inner_to_torch(parts: list[str]) -> str:
    """['transformer_blocks_0', 'attn1', 'to_q'] -> torch suffix."""
    head = parts[0]
    m = re.fullmatch(r"transformer_blocks_(\d+)", head)
    if not m:
        return ".".join(parts)  # norm / proj_in / proj_out
    i = m.group(1)
    rest = parts[1:]
    if rest[0] == "ff":
        sub = "net.0.proj" if rest[1] == "proj_in" else "net.2"
        return f"transformer_blocks.{i}.ff.{sub}"
    if rest[0] in ("attn1", "attn2") and rest[1] == "to_out":
        return f"transformer_blocks.{i}.{rest[0]}.to_out.0"
    return f"transformer_blocks.{i}." + ".".join(rest)


def export_unet(flax_params: dict, n_levels: int) -> dict[str, np.ndarray]:
    out: dict[str, np.ndarray] = {}
    for path, value in _flatten(flax_params["params"]):
        parts = path.split("/")
        top, leaf = parts[0], parts[-1]
        mid = parts[1:-1]

        m = re.fullmatch(r"(down|up)_(\d+)_(resnets|attentions)_(\d+)", top)
        md = re.fullmatch(r"(down|up)_(\d+)_(downsample|upsample)", top)
        mm = re.fullmatch(r"mid_resnets_(\d+)", top)
        if m:
            side, level, kind, j = m.groups()
            idx = int(level) if side == "down" else n_levels - 1 - int(level)
            if kind == "resnets":
                base = f"{side}_blocks.{idx}.resnets.{j}.{mid[0]}"
            else:
                base = (f"{side}_blocks.{idx}.attentions.{j}."
                        + _attn_inner_to_torch(mid))
        elif md:
            side, level, kind = md.groups()
            idx = int(level) if side == "down" else n_levels - 1 - int(level)
            base = f"{side}_blocks.{idx}.{kind}rs.0.conv"  # downsamplers/upsamplers
        elif mm:
            base = f"mid_block.resnets.{mm.group(1)}.{mid[0]}"
        elif top == "mid_attention":
            base = "mid_block.attentions.0." + _attn_inner_to_torch(mid)
        elif top in ("time_embedding", "add_embedding"):
            base = f"{top}.{mid[0]}"
        else:  # conv_in / conv_norm_out / conv_out
            base = top
        _leaf(base, leaf, value, out)
    return out


def export_controlnet(bundle_params: dict,
                      n_levels: int) -> dict[str, np.ndarray]:
    """ControlNetBundle.params ({"net", "embed"}) -> diffusers
    ``ControlNetModel`` state-dict naming. The trunk reuses export_unet's
    reverse map (same module names as the UNet down+mid path); the
    controlnet-specific heads are the zero convs and the hint embedder."""
    out: dict[str, np.ndarray] = {}
    trunk: dict = {}
    for key, sub in bundle_params["net"]["params"].items():
        m = re.fullmatch(r"controlnet_down_blocks_(\d+)", key)
        if m:
            for leaf, value in sub.items():
                _leaf(f"controlnet_down_blocks.{m.group(1)}", leaf, value,
                      out)
        elif key == "controlnet_mid_block":
            for leaf, value in sub.items():
                _leaf("controlnet_mid_block", leaf, value, out)
        else:
            trunk[key] = sub
    out.update(export_unet({"params": trunk}, n_levels))
    for key, sub in bundle_params["embed"]["params"].items():
        m = re.fullmatch(r"blocks_(\d+)", key)
        base = (f"controlnet_cond_embedding.blocks.{m.group(1)}" if m
                else f"controlnet_cond_embedding.{key}")
        for leaf, value in sub.items():
            _leaf(base, leaf, value, out)
    return out


def export_vae(flax_params: dict, n_levels: int) -> dict[str, np.ndarray]:
    out: dict[str, np.ndarray] = {}
    for path, value in _flatten(flax_params["params"]):
        parts = path.split("/")
        side, leaf = parts[0], parts[-1]
        body = parts[1:-1]
        top = body[0] if body else ""

        if top == "quant_conv":
            base = "quant_conv"
        elif top == "post_quant_conv":
            base = "post_quant_conv"
        elif top == "mid":
            if body[1].startswith("resnets_"):
                j = body[1].split("_")[1]
                base = f"{side}.mid_block.resnets.{j}.{body[2]}"
            else:  # attentions_0
                base = f"{side}.mid_block.attentions.0.{body[2]}"
        else:
            m = re.fullmatch(r"(down|up)_(\d+)_resnets_(\d+)", top)
            md = re.fullmatch(r"(down|up)_(\d+)_(downsample|upsample)", top)
            if m:
                d, level, j = m.groups()
                idx = int(level) if d == "down" else n_levels - 1 - int(level)
                base = f"{side}.{d}_blocks.{idx}.resnets.{j}.{body[1]}"
            elif md:
                d, level, kind = md.groups()
                idx = int(level) if d == "down" else n_levels - 1 - int(level)
                base = f"{side}.{d}_blocks.{idx}.{kind}rs.0.conv"
            else:
                base = f"{side}.{top}"
        _leaf(base, leaf, value, out)
    return out


def export_text_encoder(flax_params: dict) -> dict[str, np.ndarray]:
    out: dict[str, np.ndarray] = {}
    for path, value in _flatten(flax_params["params"]):
        parts = path.split("/")
        top, leaf = parts[0], parts[-1]
        if top == "token_embedding":
            base = "text_model.embeddings.token_embedding"
        elif top == "position_embedding":
            base = "text_model.embeddings.position_embedding"
        elif top == "final_layer_norm":
            base = "text_model.final_layer_norm"
        elif top == "text_projection":
            base = "text_projection"
        else:
            m = re.fullmatch(r"layers_(\d+)", top)
            i = m.group(1)
            sub = parts[1]
            if sub == "self_attn":
                base = f"text_model.encoder.layers.{i}.self_attn.{parts[2]}"
            elif sub in ("fc1", "fc2"):
                base = f"text_model.encoder.layers.{i}.mlp.{sub}"
            else:
                base = f"text_model.encoder.layers.{i}.{sub}"
        _leaf(base, leaf, value, out)
    return out


def write_checkpoint(tmpdir, components) -> None:
    """Write an HF-style snapshot (safetensors) for a Components bundle."""
    from pathlib import Path

    from safetensors.numpy import save_file

    root = Path(tmpdir)
    n_unet = len(components.family.unet.block_out_channels)
    n_vae = len(components.family.vae.block_out_channels)

    def dump(subdir: str, state: dict) -> None:
        d = root / subdir
        d.mkdir(parents=True, exist_ok=True)
        save_file({k: np.ascontiguousarray(v) for k, v in state.items()},
                  str(d / "model.safetensors"))

    dump("unet", export_unet(components.params["unet"], n_unet))
    dump("vae", export_vae(components.params["vae"], n_vae))
    dump("text_encoder",
         export_text_encoder(components.params["text_encoder_0"]))
    if len(components.family.text_encoders) > 1:
        dump("text_encoder_2",
             export_text_encoder(components.params["text_encoder_1"]))
