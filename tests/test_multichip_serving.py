"""Multi-chip serving path: a workload on a >1-chip MeshSlot shards the
resident params (tp over 'model', dp over 'data') through the registry —
the production wiring of the dryrun's manual sharding (__graft_entry__).
Runs on the virtual 8-device CPU mesh (tests/conftest.py).
"""

import numpy as np
import pytest

from chiaswarm_tpu.core.chip_pool import ChipPool
from chiaswarm_tpu.core.mesh import MeshSpec
from chiaswarm_tpu.node.registry import ModelRegistry
from chiaswarm_tpu.workloads.diffusion import diffusion_callback


@pytest.mark.slow
def test_multichip_slot_shards_params_and_generates():
    import jax

    pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 4, "model": 2}))
    slot = pool.slots[0]
    assert slot.mesh.devices.size == 8

    registry = ModelRegistry(catalog=[], allow_random=True)
    artifacts, config = diffusion_callback(
        slot, "random/tiny", seed=5, registry=registry,
        prompt="a harbor", num_inference_steps=2, height=64, width=64,
        num_images_per_prompt=4)
    assert "primary" in artifacts
    assert config["mode"] == "txt2img"

    # the resident params must actually live on the slot mesh AND some
    # weight must be tensor-parallel partitioned (not merely replicated)
    pipe = registry.pipeline("random/tiny", mesh=slot.mesh)
    leaves = jax.tree.leaves(pipe.c.params)
    specs = {str(leaf.sharding.spec) for leaf in leaves
             if hasattr(leaf.sharding, "spec")}
    assert any("model" in s for s in specs), specs

    # single-chip mesh keys separately and stays unsharded
    single = registry.pipeline("random/tiny")
    assert single is not pipe


@pytest.mark.slow
def test_multichip_matches_single_chip_output():
    """Sharded serving must agree with single-chip up to partitioned-
    reduction rounding (XLA reorders float reductions across shards, so
    bit-exactness is not guaranteed — near-equality is)."""
    from chiaswarm_tpu.pipelines import GenerateRequest

    registry = ModelRegistry(catalog=[], allow_random=True)
    pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 4, "model": 2}))

    req = GenerateRequest(prompt="dunes", steps=2, height=64, width=64,
                          seed=9, guidance_scale=5.0)
    single_img, _ = registry.pipeline("random/tiny")(req)
    multi_img, _ = registry.pipeline("random/tiny",
                                     mesh=pool.slots[0].mesh)(req)
    diff = np.abs(single_img.astype(np.int32) - multi_img.astype(np.int32))
    assert (diff <= 2).mean() > 0.99, diff.max()


def test_seq_parallel_serving_matches_single_chip(monkeypatch):
    """latency_mode serving: params on a seq=4 mesh route the UNet's
    spatial self-attention through ring attention (ops/attention.py
    _try_ring via parallel/context.py::seq_parallel_wrap) and the
    pixels match the single-chip run."""
    from chiaswarm_tpu.parallel.context import capture_ring_calls
    from chiaswarm_tpu.pipelines import GenerateRequest

    monkeypatch.setenv("CHIASWARM_RING_MIN_TOKENS", "1")

    registry = ModelRegistry(catalog=[], allow_random=True)
    pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 2, "seq": 4}))

    req = GenerateRequest(prompt="a lighthouse", steps=2, height=64,
                          width=64, seed=21, guidance_scale=5.0)
    with capture_ring_calls() as rings:
        single_img, _ = registry.pipeline("random/tiny")(req)
        assert not rings  # single-chip never rings
        seq_img, _ = registry.pipeline("random/tiny",
                                       mesh=pool.slots[0].mesh)(req)
    assert rings, "seq-mesh pipeline never reached ring attention"
    diff = np.abs(single_img.astype(np.int32) - seq_img.astype(np.int32))
    assert (diff <= 2).mean() > 0.99, diff.max()


def test_caption_params_pin_to_slot_chip():
    """Per-slot caption serving: params land on the slot's lead chip, not
    the default device (registry.caption_pipeline mesh placement)."""
    import jax

    registry = ModelRegistry(catalog=[], allow_random=True)
    pool = ChipPool(n_slots=min(2, len(jax.devices())))
    slot = pool.slots[-1]
    pipe = registry.caption_pipeline("tinyblip", mesh=slot.mesh)
    lead = slot.mesh.devices.flatten()[0]
    devices = {next(iter(leaf.devices()))
               for leaf in jax.tree.leaves(pipe.c.params)}
    assert devices == {lead}, (devices, lead)
    # a different slot keys a separate resident entry
    other = registry.caption_pipeline("tinyblip", mesh=pool.slots[0].mesh)
    assert other is not pipe


def test_dp_sharding_reduces_per_device_flops():
    """Scaling-shape sanity (sharding-regression guard): the compiled
    dp=4-sharded UNet eval must cost each device a fraction of the
    unsharded program's FLOPs. Catches a silent batch-replication
    regression — if GSPMD stops partitioning the batch axis, per-device
    FLOPs jump back to the full count."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from chiaswarm_tpu.core.mesh import MeshSpec, build_mesh
    from chiaswarm_tpu.models.configs import FAMILIES
    from chiaswarm_tpu.models.unet import UNet

    fam = FAMILIES["tiny"]
    unet = UNet(fam.unet)
    batch, hw = 4, 8
    latent = jnp.zeros((batch, hw, hw, fam.unet.sample_channels))
    t = jnp.zeros((batch,))
    ctx = jnp.zeros((batch, 8, fam.unet.cross_attention_dim))
    params = jax.jit(unet.init)(jax.random.PRNGKey(0), latent, t, ctx)

    def flops(compiled) -> float:
        cost = compiled.cost_analysis()
        if isinstance(cost, list):  # older jax returns [dict]
            cost = cost[0]
        return float(cost.get("flops", 0.0))

    base = jax.jit(unet.apply).lower(params, latent, t, ctx).compile()

    mesh = build_mesh(MeshSpec({"data": 4}),
                      devices=jax.devices()[:4])
    row = NamedSharding(mesh, P("data"))
    sharded_in = (
        jax.device_put(latent, NamedSharding(mesh, P("data", None, None,
                                                     None))),
        jax.device_put(t, row),
        jax.device_put(ctx, NamedSharding(mesh, P("data", None, None))),
    )
    dp = jax.jit(unet.apply).lower(params, *sharded_in).compile()

    f_base, f_dp = flops(base), flops(dp)
    assert f_base > 0 and f_dp > 0
    # per-device cost must drop ~4x; allow generous slack for collective
    # and padding overhead (a replication regression would be ~1.0x)
    assert f_dp < 0.5 * f_base, (f_dp, f_base)


@pytest.mark.slow
def test_img2vid_tensor_parallel_matches_single_chip():
    """SVD-class img2vid under Megatron tp sharding (the video UNet's
    spatial blocks share the 2D UNet's module names, so the conv/attention
    partition rules apply unchanged): same clip as the replicated run."""
    from chiaswarm_tpu.parallel.sharding import shard_params
    from chiaswarm_tpu.pipelines.video import Img2VidPipeline, VideoComponents
    from chiaswarm_tpu.core.mesh import build_mesh

    rng = np.random.default_rng(5)
    image = rng.integers(0, 255, (64, 64, 3), dtype=np.uint8)

    c = VideoComponents.random("tiny_svd", seed=2)
    ref, _ = Img2VidPipeline(c)(image, num_frames=4, steps=2, seed=9,
                                height=64, width=64)

    mesh = build_mesh(MeshSpec({"data": 4, "model": 2}))
    c.params = shard_params(c.params, mesh)
    sharded, cfg = Img2VidPipeline(c)(image, num_frames=4, steps=2, seed=9,
                                      height=64, width=64)
    assert cfg["mode"] == "img2vid"
    diff = np.abs(ref.astype(np.int32) - sharded.astype(np.int32))
    assert (diff <= 2).mean() > 0.99, diff.max()
