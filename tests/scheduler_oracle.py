"""Independent numpy oracle for golden scheduler trajectories.

Purpose (SURVEY hard-part #4): the reference force-swaps every job onto
DPMSolverMultistep with Karras sigmas (swarm/diffusion/diffusion_func.py:
71-74), so our jittable sigma-space samplers (schedulers/sampling.py) must
match the diffusers semantics step for step. diffusers itself is NOT
installed in this zero-egress image, so the goldens cannot be literal
diffusers outputs; instead this module re-implements the diffusers
algorithms INDEPENDENTLY — in VP (variance-preserving) coordinates with
diffusers' own state bookkeeping (multistep model-output lists,
lower_order_final, leading/offset timestep spacing), following
DPMSolverMultistepScheduler / DDIMScheduler / EulerDiscreteScheduler /
EulerAncestralDiscreteScheduler and the DPM-Solver++ paper (Lu et al.
2022, Algorithm 2M) — while the framework's samplers work in k-diffusion
coordinates x_kd = x_vp / sqrt(alpha_bar). Agreement therefore checks the
algebraic change of variables AND the ladder construction, not shared code
paths. The fixtures generated from this oracle are committed
(tests/fixtures/scheduler_golden.npz, see make_scheduler_fixtures.py) so a
regression in either implementation turns the golden tests red.
"""

from __future__ import annotations

import numpy as np

T_TRAIN = 1000
BETA_START = 0.00085
BETA_END = 0.012


def train_tables() -> tuple[np.ndarray, np.ndarray]:
    """(alphas_cumprod, kd_sigmas) for SD's scaled_linear schedule."""
    betas = np.linspace(BETA_START ** 0.5, BETA_END ** 0.5, T_TRAIN,
                        dtype=np.float64) ** 2
    abar = np.cumprod(1.0 - betas)
    sigmas = np.sqrt((1.0 - abar) / abar)
    return abar, sigmas


def leading_timesteps(n: int, steps_offset: int = 1) -> np.ndarray:
    """diffusers timestep_spacing="leading": descending ints + offset."""
    step_ratio = T_TRAIN // n
    ts = (np.arange(n) * step_ratio).round()[::-1].astype(np.int64)
    return ts + steps_offset


def karras_ladder(sigma_min: float, sigma_max: float, n: int,
                  rho: float = 7.0) -> np.ndarray:
    ramp = np.linspace(0.0, 1.0, n)
    return (sigma_max ** (1 / rho)
            + ramp * (sigma_min ** (1 / rho) - sigma_max ** (1 / rho))) ** rho


def sigma_to_t(sigma: np.ndarray, kd_sigmas: np.ndarray) -> np.ndarray:
    """diffusers' _sigma_to_t: log-sigma interpolation onto train indices."""
    return np.interp(np.log(np.maximum(sigma, 1e-10)), np.log(kd_sigmas),
                     np.arange(len(kd_sigmas), dtype=np.float64))


def make_karras_schedule(n: int) -> tuple[np.ndarray, np.ndarray]:
    """(sigmas[n+1] with final 0, fractional timesteps[n]) the way
    DPMSolverMultistep/EulerDiscrete build them with use_karras_sigmas."""
    _, kd_sigmas = train_tables()
    ts = leading_timesteps(n)
    base = np.interp(ts.astype(np.float64)[::-1],
                     np.arange(T_TRAIN, dtype=np.float64), kd_sigmas)
    sig = karras_ladder(float(base[0]), float(base[-1]), n)
    timesteps = sigma_to_t(sig, kd_sigmas)
    return np.concatenate([sig, [0.0]]), timesteps


def _alpha_sigma_vp(sigma_kd: float) -> tuple[float, float]:
    """diffusers _sigma_to_alpha_sigma_t: VP-space (alpha_t, sigma_t)."""
    alpha = 1.0 / np.sqrt(1.0 + sigma_kd ** 2)
    return alpha, sigma_kd * alpha


class OracleDPMpp2M:
    """DPMSolverMultistepScheduler semantics: algorithm dpmsolver++,
    solver_order=2, use_karras_sigmas=True, lower_order_final=True,
    final_sigmas_type="zero", epsilon prediction — in VP coordinates."""

    def __init__(self, n: int):
        self.sigmas, self.timesteps = make_karras_schedule(n)
        self.n = n
        self.model_outputs: list[np.ndarray] = []
        self.step_index = 0

    def convert_to_x0(self, eps: np.ndarray, x_vp: np.ndarray,
                      sigma_kd: float) -> np.ndarray:
        alpha_t, sigma_t = _alpha_sigma_vp(sigma_kd)
        return (x_vp - sigma_t * eps) / alpha_t

    def step(self, eps: np.ndarray, x_vp: np.ndarray) -> np.ndarray:
        i = self.step_index
        s_kd, s_next_kd = self.sigmas[i], self.sigmas[i + 1]
        x0 = self.convert_to_x0(eps, x_vp, s_kd)
        self.model_outputs.append(x0)
        if len(self.model_outputs) > 2:
            self.model_outputs.pop(0)

        alpha_t, sigma_t = _alpha_sigma_vp(s_next_kd)
        alpha_s, sigma_s = _alpha_sigma_vp(s_kd)
        lam_t = np.log(alpha_t) - np.log(max(sigma_t, 1e-20))
        lam_s = np.log(alpha_s) - np.log(max(sigma_s, 1e-20))
        h = lam_t - lam_s

        use_first_order = (
            len(self.model_outputs) < 2
            or i == self.n - 1            # lower_order_final
            or s_next_kd == 0.0
        )
        if use_first_order:
            D = self.model_outputs[-1]
        else:
            s_prev_kd = self.sigmas[i - 1]
            alpha_p, sigma_p = _alpha_sigma_vp(s_prev_kd)
            lam_p = np.log(alpha_p) - np.log(max(sigma_p, 1e-20))
            h_0 = lam_s - lam_p
            r0 = h_0 / h
            m0, m1 = self.model_outputs[-1], self.model_outputs[-2]
            D = m0 + (0.5 / r0) * (m0 - m1)
        if s_next_kd == 0.0:
            x_next = D
        else:
            x_next = (sigma_t / sigma_s) * x_vp - alpha_t * np.expm1(-h) * D
        self.step_index += 1
        return x_next


class OracleDDIM:
    """DDIMScheduler semantics (eta=0, leading spacing, steps_offset=1,
    epsilon prediction) in VP coordinates on the discrete timestep grid."""

    def __init__(self, n: int):
        self.abar, self.kd_sigmas = train_tables()
        self.timesteps = leading_timesteps(n)  # descending ints
        self.n = n
        self.step_index = 0

    def step(self, eps: np.ndarray, x_vp: np.ndarray) -> np.ndarray:
        t = self.timesteps[self.step_index]
        prev_t = t - T_TRAIN // self.n
        a_t = self.abar[t]
        a_prev = self.abar[prev_t] if prev_t >= 0 else 1.0
        x0 = (x_vp - np.sqrt(1.0 - a_t) * eps) / np.sqrt(a_t)
        x_next = np.sqrt(a_prev) * x0 + np.sqrt(1.0 - a_prev) * eps
        self.step_index += 1
        return x_next


class OracleEuler:
    """EulerDiscreteScheduler semantics with use_karras_sigmas=True —
    k-diffusion coordinates (that is how diffusers implements it too; the
    independence here is the ladder + step recurrence, re-derived)."""

    def __init__(self, n: int):
        self.sigmas, self.timesteps = make_karras_schedule(n)
        self.step_index = 0

    def step(self, eps: np.ndarray, x_kd: np.ndarray) -> np.ndarray:
        i = self.step_index
        s, s_next = self.sigmas[i], self.sigmas[i + 1]
        x0 = x_kd - s * eps
        d = (x_kd - x0) / s
        x_next = x_kd + (s_next - s) * d
        self.step_index += 1
        return x_next


class OracleEulerAncestral:
    """EulerAncestralDiscreteScheduler semantics (no karras support in
    diffusers for this class): discrete interpolated sigmas, ancestral
    up/down split, caller-supplied per-step noise."""

    def __init__(self, n: int):
        _, kd_sigmas = train_tables()
        ts = leading_timesteps(n)
        sig = np.interp(ts.astype(np.float64),
                        np.arange(T_TRAIN, dtype=np.float64), kd_sigmas)
        self.sigmas = np.concatenate([sig, [0.0]])
        self.timesteps = ts.astype(np.float64)
        self.step_index = 0

    def step(self, eps: np.ndarray, x_kd: np.ndarray,
             noise: np.ndarray) -> np.ndarray:
        i = self.step_index
        s, s_next = self.sigmas[i], self.sigmas[i + 1]
        x0 = x_kd - s * eps
        if s_next == 0.0:
            x_next = x0
        else:
            var = s_next ** 2 * (s ** 2 - s_next ** 2) / s ** 2
            sigma_up = np.sqrt(max(var, 0.0))
            sigma_down = np.sqrt(max(s_next ** 2 - sigma_up ** 2, 0.0))
            d = (x_kd - x0) / s
            x_next = x_kd + (sigma_down - s) * d + noise * sigma_up
        self.step_index += 1
        return x_next


def mock_eps(x_model_input: np.ndarray, t: float) -> np.ndarray:
    """Deterministic stand-in model. Takes the *scaled* model input (which
    equals the VP-coordinate sample) and the conditioning timestep — the
    same two things the real UNet sees — so a timestep-mapping bug between
    implementations shows up as divergence."""
    return 0.9 * np.tanh(x_model_input) + 0.02 * np.cos(t / 100.0)
