"""Multi-chip tests on the virtual 8-device CPU mesh (tests/conftest.py):
ring attention == dense attention, tensor-parallel sharded pipeline ==
replicated pipeline. This is the "test multi-node without a cluster"
strategy from SURVEY.md §4."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from chiaswarm_tpu.core.compat import shard_map

from chiaswarm_tpu.core.mesh import MeshSpec, build_mesh
from chiaswarm_tpu.ops.attention import _xla_attention
from chiaswarm_tpu.parallel import (
    param_partition_specs,
    ring_attention,
    shard_params,
)
from chiaswarm_tpu.pipelines.components import Components
from chiaswarm_tpu.pipelines.diffusion import DiffusionPipeline, GenerateRequest


def test_ring_attention_matches_dense():
    mesh = build_mesh(MeshSpec({"seq": 8}))
    b, l, h, d = 2, 8 * 16, 2, 32
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (b, l, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, l, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, l, h, d), jnp.float32)

    spec = P(None, "seq", None, None)
    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="seq"),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    got = jax.jit(ring)(q, k, v)
    ref = _xla_attention(q, k, v, d ** -0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_attention_auto_routes_through_ring(monkeypatch):
    """ops.attention dispatch: under sequence_parallel on a seq>1 mesh,
    auto/ring route self-attention through the shard_map ring and match
    the dense path; cross-attention (S != L) stays local."""
    from chiaswarm_tpu.ops.attention import attention
    from chiaswarm_tpu.parallel import sequence_parallel

    monkeypatch.setenv("CHIASWARM_RING_MIN_TOKENS", "1")
    mesh = build_mesh(MeshSpec({"seq": 4}), devices=jax.devices()[:4])
    b, l, h, d = 2, 4 * 8, 2, 16
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(kq, (b, l, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, l, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, l, h, d), jnp.float32)
    ref = _xla_attention(q, k, v, d ** -0.5)

    with sequence_parallel(mesh):
        ringed = attention(q, k, v, impl="ring")
        auto = attention(q, k, v, impl="auto")
        # cross-attention: small KV must not take the ring
        cross = attention(q, k[:, :7], v[:, :7], impl="auto")
    np.testing.assert_allclose(np.asarray(ringed), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(auto), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    assert cross.shape == q.shape

    # outside the context, plain dispatch — and explicit ring demands it
    np.testing.assert_allclose(
        np.asarray(attention(q, k, v, impl="auto")), np.asarray(ref),
        rtol=2e-4, atol=2e-4)
    try:
        attention(q, k, v, impl="ring")
    except ValueError:
        pass
    else:
        raise AssertionError("impl='ring' without a seq mesh must raise")


def test_ring_composes_with_dp_and_tp(monkeypatch):
    """dp x seq x tp mesh: batch on 'data', heads on 'model', tokens on
    'seq' — one spec, no resharding beyond the ring."""
    from chiaswarm_tpu.ops.attention import attention
    from chiaswarm_tpu.parallel import sequence_parallel

    monkeypatch.setenv("CHIASWARM_RING_MIN_TOKENS", "1")
    mesh = build_mesh(MeshSpec({"data": 2, "seq": 2, "model": 2}))
    b, l, h, d = 2, 2 * 8, 2, 16
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(kq, (b, l, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, l, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, l, h, d), jnp.float32)
    with sequence_parallel(mesh):
        got = attention(q, k, v, impl="ring")
    ref = _xla_attention(q, k, v, d ** -0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_partition_specs_hit_attention_weights():
    c = Components.random("tiny", seed=0)
    specs = param_partition_specs(c.params)
    flat = jax.tree_util.tree_flatten_with_path(specs)[0]
    model_sharded = [
        "/".join(k.key for k in path if hasattr(k, "key"))
        for path, s in flat
        if any(ax == "model" for ax in s)
    ]
    assert any("to_q" in p for p in model_sharded)
    assert any("fc1" in p for p in model_sharded)
    assert any("proj_out" in p for p in model_sharded)
    # resnet conv pair is channel-sharded (conv1 column / conv2 row), with
    # the in-between norm2 + time projection sharded to match
    assert any("resnets" in p and "conv1" in p for p in model_sharded)
    assert any("resnets" in p and "conv2" in p for p in model_sharded)
    assert any("resnets" in p and "time_emb_proj" in p
               for p in model_sharded)
    assert any("resnets" in p and "norm2" in p for p in model_sharded)
    # norms over replicated activations stay replicated (norm1, attention
    # LayerNorms, conv_norm_out) — only the resnet-internal norm2 shards
    assert not any("norm" in p and "norm2" not in p for p in model_sharded)
    # conv2 bias must stay replicated: it is added AFTER the row-parallel
    # psum, adding it per-shard would count it tp times
    assert not any("conv2/bias" in p for p in model_sharded)
    # the VAE shares resnet block names under encoder/decoder but its
    # convs must stay replicated (tiny FLOPs share, channel counts don't
    # divide); only its mid-attention projections shard (deliberate,
    # covered by the module docstring's Megatron rules)
    assert not any(p.startswith("vae/") and "resnets" in p
                   for p in model_sharded)


@pytest.mark.slow
def test_tensor_parallel_pipeline_matches_replicated(mesh8):
    """Same request, params replicated vs sharded dp=4 x tp=2 — same pixels."""
    c = Components.random("tiny", seed=3)
    pipe = DiffusionPipeline(c)
    req = GenerateRequest(prompt="a pond", steps=3, height=64, width=64,
                          batch=1, seed=11, guidance_scale=5.0)
    ref_img, _ = pipe(req)

    c.params = shard_params(c.params, mesh8)
    sharded_img, cfg = pipe(req)
    np.testing.assert_allclose(
        sharded_img.astype(np.float32), ref_img.astype(np.float32),
        atol=3.0,  # uint8 space; fp reassociation across chips
    )
    assert cfg["mode"] == "txt2img"


def test_data_parallel_batch_sharding(mesh8):
    """Batch-sharded inputs run through jit with explicit out shardings."""
    mesh = mesh8

    def step(x):
        return jnp.tanh(x) * 2.0

    x = jnp.arange(4 * 8 * 8 * 3, dtype=jnp.float32).reshape(4, 8, 8, 3)
    sharding = NamedSharding(mesh, P("data", None, None, None))
    xs = jax.device_put(x, sharding)
    out = jax.jit(step, out_shardings=sharding)(xs)
    np.testing.assert_allclose(np.asarray(out), np.tanh(x) * 2.0, rtol=1e-6)
