"""Textual inversion: embedding merge, placeholder tokens, fatal mismatch.

Reference behavior covered: per-job ``load_textual_inversion`` with
incompatible inversions surfacing as fatal ValueError
(swarm/diffusion/diffusion_func.py:48-54, swarm/generator.py:34-41).
"""

import numpy as np
import pytest

from chiaswarm_tpu.convert.textual_inversion import apply_textual_inversion
from chiaswarm_tpu.models.tokenizer import HashTokenizer
from chiaswarm_tpu.pipelines import Components, DiffusionPipeline, GenerateRequest


def test_added_token_splitting():
    tok = HashTokenizer(vocab_size=100, max_length=16)
    base = tok.encode("a photo of sks dog")
    tok.add_token("sks", [200, 201])
    with_ti = tok.encode("a photo of sks dog")
    assert 200 in with_ti and 201 in with_ti
    assert with_ti != base
    # unrelated prompts are untouched
    assert tok.encode("a plain cat") == \
        HashTokenizer(vocab_size=100, max_length=16).encode("a plain cat")


@pytest.mark.slow
def test_apply_textual_inversion_changes_generation():
    c = Components.random("tiny", seed=0)
    hidden = c.params["text_encoder_0"]["params"][
        "token_embedding"]["embedding"].shape[1]
    pipe = DiffusionPipeline(c)
    req = GenerateRequest(prompt="a sks landscape", steps=2, height=64,
                          width=64, seed=3, guidance_scale=5.0)
    base_img, _ = pipe(req)

    c2 = Components.random("tiny", seed=0)
    rng = np.random.default_rng(1)
    added = apply_textual_inversion(
        c2, {"sks": rng.normal(size=(2, hidden)).astype(np.float32)})
    assert added == ["sks"]
    rows = c2.params["text_encoder_0"]["params"][
        "token_embedding"]["embedding"].shape[0]
    assert rows == c.params["text_encoder_0"]["params"][
        "token_embedding"]["embedding"].shape[0] + 2

    ti_img, _ = DiffusionPipeline(c2)(req)
    assert not np.array_equal(base_img, ti_img)   # concept steers output

    # prompts without the placeholder are unaffected by the merge
    neutral = GenerateRequest(prompt="plain hills", steps=2, height=64,
                              width=64, seed=3, guidance_scale=5.0)
    a, _ = pipe(neutral)
    b, _ = DiffusionPipeline(c2)(neutral)
    assert np.array_equal(a, b)


def test_incompatible_dimension_is_value_error():
    c = Components.random("tiny", seed=0)
    with pytest.raises(ValueError, match="incompatible"):
        apply_textual_inversion(
            c, {"sks": np.zeros((1, 9999), np.float32)})


def test_workload_missing_inversion_is_value_error():
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.workloads.diffusion import diffusion_callback

    registry = ModelRegistry(catalog=[], allow_random=True)
    with pytest.raises(ValueError, match="not.*available"):
        diffusion_callback(
            "slot0", "random/tiny", seed=1, registry=registry,
            prompt="x", num_inference_steps=1, height=64, width=64,
            textual_inversion="sd-concepts-library/nowhere")
