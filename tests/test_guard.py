"""swarmguard (ISSUE 10): gray-failure detection + the self-healing
ladder.

Four layers:

- **Units** (no jax): the watchdog monitor (arm/fire/disarm races),
  the DeviceGuard ladder (streaks, rung escalation order, recovery),
  hang-budget clamping, chaos-plan parsing, the output screens, and
  the failure-taxonomy membership of ``invalid_output``/``bad_asset``.
- **Lane-level** (real tiny lanes): a scripted wedge inside a step's
  armed window condemns the lane from the monitor thread; the rows'
  futures fail with LaneHung carrying the last step-boundary
  checkpoint, and resubmitting with it yields a BIT-IDENTICAL image to
  the uninterrupted run (the PR-6 resume-equivalence gate, reused). A
  scripted NaN injection retires exactly the poisoned row's job as
  ``invalid_output`` while its lane peer completes and matches solo.
- **Worker-level**: the executor heals a condemned lane transparently
  (the result carries ``stepper.resume_step >= 1``); the quarantine
  rung shrinks a 2-chip slot's mesh to the healthy chip (capacity
  re-advertised); the restart rung requests a graceful stop with the
  distinct supervisor exit code.
- **THE acceptance gate**: a 3-worker MiniHive fleet under mixed
  workloads with one scripted mid-lane wedge and one injected NaN row
  — every job settles exactly once (completed / redispatched
  ``invalid_output`` / resumed), the condemned lane's surviving rows
  resume at step >= 1, no garbage image uploads, and the health score
  + heal-rung transitions are visible on /metrics.

Everything is hermetic, scripted/seeded, on the CPU test mesh.
"""

from __future__ import annotations

import asyncio
import base64
import io
import time

import numpy as np
import pytest

from chiaswarm_tpu.node.resilience import (
    BREAKER_KINDS,
    NONFATAL_KINDS,
    REDISPATCH_KINDS,
    RETRYABLE_KINDS,
    BadAssetError,
    classify_exception,
    classify_result,
)
from chiaswarm_tpu.obs.metrics import Registry
from chiaswarm_tpu.serving import guard
from chiaswarm_tpu.serving.guard import (
    GUARD_RESTART_EXIT_CODE,
    DeviceGuard,
    InvalidOutput,
    LaneChaos,
    LaneHung,
    StepHung,
    Watchdog,
    hang_budget_s,
    screen_images,
    solo_hang_budget_s,
)


@pytest.fixture(autouse=True)
def _tmp_root(tmp_path, monkeypatch):
    monkeypatch.setenv("SWARM_TPU_ROOT", str(tmp_path))
    return tmp_path


@pytest.fixture(autouse=True)
def _fresh_chaos(monkeypatch):
    """Each test re-arms the one-shot chaos seams and starts with the
    chaos env unset (tests opt in explicitly)."""
    for name in (guard.ENV_CHAOS_WEDGE, guard.ENV_CHAOS_SLOW,
                 guard.ENV_CHAOS_NAN, guard.ENV_ENABLE,
                 guard.ENV_HANG_FACTOR, guard.ENV_HANG_FLOOR,
                 guard.ENV_HANG_CEIL):
        monkeypatch.delenv(name, raising=False)
    guard.reset_chaos()
    yield
    guard.reset_chaos()


# ---------------------------------------------------------------------------
# units
# ---------------------------------------------------------------------------


def test_watchdog_fires_then_disarm_reports_it():
    dog = Watchdog()
    fired = []
    ticket = dog.arm(0.05, lambda: fired.append(1), tag="t1")
    deadline = time.monotonic() + 5
    while not fired and time.monotonic() < deadline:
        time.sleep(0.01)
    assert fired == [1]
    assert dog.disarm(ticket) is True


def test_watchdog_disarm_before_deadline_never_fires():
    dog = Watchdog()
    fired = []
    ticket = dog.arm(5.0, lambda: fired.append(1), tag="t2")
    assert dog.disarm(ticket) is False
    time.sleep(0.05)
    assert not fired
    # disarming twice (or an unknown ticket) is harmless
    assert dog.disarm(ticket) is False


def test_hang_budget_clamps_and_cold_uses_ceiling(monkeypatch):
    monkeypatch.setenv(guard.ENV_HANG_FACTOR, "10")
    monkeypatch.setenv(guard.ENV_HANG_FLOOR, "2")
    monkeypatch.setenv(guard.ENV_HANG_CEIL, "50")
    assert hang_budget_s(0.0) == 50.0          # cold: first call compiles
    assert hang_budget_s(0.01) == 2.0          # floor
    assert hang_budget_s(1.0) == 10.0          # factor x ewma
    assert hang_budget_s(100.0) == 50.0        # ceiling
    # solo: never armed cold (no EWMA evidence / no steps)
    assert solo_hang_budget_s(0.0, 30) is None
    assert solo_hang_budget_s(0.5, 0) is None
    assert solo_hang_budget_s(0.5, 10) == 50.0  # clamped to ceiling


def test_device_guard_ladder_escalates_in_order_and_recovers():
    dg = DeviceGuard(cache_flush_after=3, quarantine_after=5,
                     restart_after=7, metrics_registry=Registry())
    dg.seed_devices(["3"])
    assert dg.health_scores() == {"3": 1.0}
    dg.note_hang(["3"])                    # streak 2 (hang weighs 2)
    assert dg.take_actions() == []
    dg.note_invalid_output(["3"], model="m")   # streak 3 -> cache_flush
    assert [a.rung for a in dg.take_actions()] == ["cache_flush"]
    dg.note_hang(["3"])                    # streak 5 -> quarantine
    actions = dg.take_actions()
    assert [a.rung for a in actions] == ["device_quarantine"]
    assert dg.quarantined == {"3"}
    dg.note_hang(["3"])                    # streak 7 -> restart
    assert [a.rung for a in dg.take_actions()] == ["restart"]
    assert dg.restart_requested is True
    assert dg.health_scores()["3"] == 0.0
    # each rung fires ONCE per sickness episode
    dg.note_hang(["3"])
    assert dg.take_actions() == []
    # recovery: OK events decay the streak; at zero the ladder re-arms
    for _ in range(20):
        dg.note_ok(["3"])
    assert dg.health_scores()["3"] == 1.0
    for _ in range(2):
        dg.note_hang(["3"])
    assert [a.rung for a in dg.take_actions()] == ["cache_flush"]


def test_device_guard_disabled_counts_but_never_acts():
    dg = DeviceGuard(enabled=False, cache_flush_after=1,
                     quarantine_after=2, restart_after=3,
                     metrics_registry=Registry())
    for _ in range(5):
        dg.note_hang(["0"])
    assert dg.take_actions() == []
    assert dg.snapshot()["hangs"] == 5


def test_chaos_plan_parses_and_one_shots(monkeypatch):
    monkeypatch.setenv(guard.ENV_CHAOS_WEDGE, "3:2.5")
    monkeypatch.setenv(guard.ENV_CHAOS_NAN, "4:1")
    monkeypatch.setenv(guard.ENV_CHAOS_SLOW, "3.0")
    plan = LaneChaos.from_env()
    assert plan.wedge_at(2) == 0.0
    assert plan.wedge_at(3) == 2.5
    assert plan.wedge_at(3) == 0.0          # one shot, process-wide
    # the NaN seam WANTS to fire at-or-after its step; the lane
    # consumes the one-shot only once the row is eligible
    assert plan.nan_wants(3) is None
    assert plan.nan_wants(4) == 1
    assert plan.nan_wants(5) == 1           # still pending
    assert guard.consume_chaos("nan") is True
    assert guard.consume_chaos("nan") is False
    assert plan.slow_extra_s(0.1) == pytest.approx(0.2)
    # malformed env values never raise — chaos defaults off
    monkeypatch.setenv(guard.ENV_CHAOS_WEDGE, "garbage")
    assert LaneChaos.from_env().wedge_step is None


def test_screen_images_catches_poison_and_passes_real_frames():
    rng = np.random.default_rng(7)
    screen_images(rng.integers(0, 255, (2, 8, 8, 3)).astype(np.uint8))
    with pytest.raises(InvalidOutput):
        screen_images(np.zeros((1, 8, 8, 3), np.uint8))   # black frame
    with pytest.raises(InvalidOutput):
        screen_images(np.full((1, 8, 8, 3), np.nan, np.float32))
    ok_and_black = np.concatenate(
        [rng.integers(1, 255, (1, 8, 8, 3)).astype(np.uint8),
         np.zeros((1, 8, 8, 3), np.uint8)])
    with pytest.raises(InvalidOutput):
        screen_images(ok_and_black)


def test_screen_images_disabled_by_env(monkeypatch):
    monkeypatch.setenv(guard.ENV_ENABLE, "0")
    screen_images(np.zeros((1, 8, 8, 3), np.uint8))  # no raise


def test_failure_taxonomy_membership():
    # invalid_output: redispatchable AND breaker fodder (a checkpoint
    # that keeps producing NaN is broken; a device that does is sick)
    assert "invalid_output" in REDISPATCH_KINDS
    assert "invalid_output" in BREAKER_KINDS
    assert "invalid_output" in NONFATAL_KINDS
    # bad_asset: non-fatal, but neither retried locally nor breaker
    # fodder nor hive-redispatched by kind
    assert "bad_asset" in NONFATAL_KINDS
    assert "bad_asset" not in RETRYABLE_KINDS
    assert "bad_asset" not in BREAKER_KINDS
    assert "bad_asset" not in REDISPATCH_KINDS
    assert classify_exception(InvalidOutput("x")) == "invalid_output"
    assert classify_exception(StepHung("x")) == "transient"
    assert classify_exception(BadAssetError("x")) == "bad_asset"
    # BadAssetError still satisfies legacy ValueError handling
    assert isinstance(BadAssetError("x"), ValueError)

    from chiaswarm_tpu.node.executor import error_result

    envelope = error_result({"id": "g1", "content_type":
                             "application/json"}, InvalidOutput("nan"),
                            kind="invalid_output")
    assert "fatal_error" not in envelope
    assert classify_result(envelope) == "invalid_output"


# ---------------------------------------------------------------------------
# lane-level: wedge -> condemn -> resume, NaN -> invalid_output
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_pipe():
    from chiaswarm_tpu.pipelines import Components, DiffusionPipeline

    return DiffusionPipeline(Components.random("tiny", seed=0))


def _wait_steps(sched, n, timeout=120.0):
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        if sched.stats().get("steps_executed", 0) >= n:
            return
        time.sleep(0.005)
    raise AssertionError(f"never reached {n} steps: {sched.stats()}")


def test_wedge_condemn_resume_bit_identical(tiny_pipe, monkeypatch):
    """THE lane-rebuild gate: a wedged step condemns the lane, the
    job's future fails with LaneHung + the last step-boundary
    checkpoint, and re-admission to a fresh lane resumes at step k —
    producing the BIT-IDENTICAL image of an uninterrupted lane run
    (the PR-6 resume-equivalence bar)."""
    from chiaswarm_tpu.serving.stepper import StepScheduler

    monkeypatch.setenv("CHIASWARM_STEPPER_CKPT_EVERY", "1")

    # uninterrupted reference (also warms the lane executables so the
    # wedged run's budget comes from a real step EWMA, not a compile)
    ref_sched = StepScheduler()
    ref_fut = ref_sched.submit_request(
        tiny_pipe, prompt="wedge me", steps=8, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=404)
    ref_pending, _ = ref_fut.result(timeout=300)
    ref_img = ref_pending.wait()
    ref_sched.shutdown()

    # wedged run: lane-local step 3 sleeps 3s with a sub-second budget
    monkeypatch.setenv(guard.ENV_HANG_FACTOR, "3")
    monkeypatch.setenv(guard.ENV_HANG_FLOOR, "0.2")
    monkeypatch.setenv(guard.ENV_CHAOS_WEDGE, "3:3.0")
    guard.reset_chaos()
    sched = StepScheduler()
    # feed the scheduler's step EWMA so the wedge's budget is tight
    # (a fresh scheduler would arm the first steps at the ceiling)
    sched.note_step_seconds(0.05)
    fut = sched.submit_request(
        tiny_pipe, prompt="wedge me", steps=8, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=404)
    with pytest.raises(LaneHung) as excinfo:
        fut.result(timeout=300)
    resume = excinfo.value.resume
    assert isinstance(resume, dict) and resume.get("kind") == "lane"
    assert 1 <= int(resume["step"]) < 8
    stats = sched.stats()
    assert stats.get("lanes_condemned") == 1
    assert stats.get("rows_hung", 0) >= 1

    # re-admission: fresh lane, resumed at the checkpointed step
    monkeypatch.delenv(guard.ENV_CHAOS_WEDGE)
    healed = sched.submit_request(
        tiny_pipe, prompt="wedge me", steps=8, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=404, resume=resume)
    pending, info = healed.result(timeout=300)
    img = pending.wait()
    assert info["resume_step"] == int(resume["step"])
    assert np.array_equal(img, ref_img)     # bit-identical
    sched.shutdown()


def test_nan_row_retires_alone_while_lane_peer_completes(
        tiny_pipe, monkeypatch):
    """A NaN-poisoned row retires with InvalidOutput at the next
    checkpoint boundary; the job sharing its lane keeps stepping and
    matches the solo run — the poison never takes peers down and never
    decodes."""
    from chiaswarm_tpu.pipelines import GenerateRequest
    from chiaswarm_tpu.serving.stepper import StepScheduler

    monkeypatch.setenv("CHIASWARM_STEPPER_CKPT_EVERY", "1")
    monkeypatch.setenv(guard.ENV_CHAOS_NAN, "2:0")
    guard.reset_chaos()
    sched = StepScheduler()
    doomed = sched.submit_request(
        tiny_pipe, prompt="poisoned", steps=8, guidance_scale=7.5,
        height=64, width=64, rows=1, seed=71)
    _wait_steps(sched, 1)
    survivor = sched.submit_request(
        tiny_pipe, prompt="survivor", steps=5, guidance_scale=6.0,
        height=64, width=64, rows=1, seed=72)
    with pytest.raises(InvalidOutput):
        doomed.result(timeout=300)
    pending, info = survivor.result(timeout=300)
    img = pending.wait()
    assert info["lane"] is not None
    stats = sched.stats()
    assert stats.get("rows_invalid") == 1
    assert stats.get("lanes_condemned", 0) == 0

    solo, _ = tiny_pipe(GenerateRequest(
        prompt="survivor", steps=5, guidance_scale=6.0, height=64,
        width=64, seed=72))
    diff = np.abs(img.astype(int) - solo.astype(int))
    assert diff.max() <= 3 and (diff <= 1).mean() > 0.99
    sched.shutdown()


@pytest.mark.slow
def test_executor_heals_condemned_lane_transparently(
        tiny_pipe, monkeypatch):
    """Worker-facing contract: a wedge mid-lane is invisible to the
    caller — synchronous_do_work returns a SUCCESS whose config stamps
    the resume step, and the slot's DeviceGuard heard the hang. (Slow
    tier: the same executor heal path runs inside the tier-1 fleet
    acceptance gate; this is the isolated, single-worker variant.)"""
    import jax

    from chiaswarm_tpu.core.chip_pool import ChipPool
    from chiaswarm_tpu.core.mesh import MeshSpec
    from chiaswarm_tpu.node.executor import synchronous_do_work
    from chiaswarm_tpu.node.registry import ModelRegistry

    monkeypatch.setenv("CHIASWARM_STEPPER", "1")
    monkeypatch.setenv("CHIASWARM_STEPPER_CKPT_EVERY", "1")
    registry = ModelRegistry(
        catalog=[{"name": "tiny", "family": "tiny", "parameters": {}}],
        allow_random=True)
    pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 1}),
                    devices=jax.devices()[:1])
    slot = pool.slots[0]
    slot._guard = DeviceGuard(metrics_registry=Registry())

    def job(i):
        return {"id": f"heal-{i}", "model_name": "tiny",
                "prompt": f"heal prompt {i}", "seed": 500 + i,
                "num_inference_steps": 8, "guidance_scale": 7.5,
                "height": 64, "width": 64, "content_type": "image/png"}

    # warm run: executables compiled, step EWMA fed
    warm = synchronous_do_work(job(0), slot, registry)
    assert warm["pipeline_config"].get("error") is None
    stepper = slot._stepper
    assert stepper.step_ewma() > 0.0
    # retire the warm lane so the wedged job opens a FRESH one whose
    # lane-local step counter starts at 1 (the chaos trigger is
    # lane-local); the executables stay cached, so step 1 of the new
    # lane dispatches without compiling and the tight budget is safe
    stepper.shutdown()

    monkeypatch.setenv(guard.ENV_HANG_FACTOR, "3")
    monkeypatch.setenv(guard.ENV_HANG_FLOOR, "0.2")
    monkeypatch.setenv(guard.ENV_CHAOS_WEDGE, "3:3.0")
    guard.reset_chaos()

    result = synchronous_do_work(job(1), slot, registry)
    config = result["pipeline_config"]
    assert config.get("error") is None, config
    info = config.get("stepper") or {}
    stats = stepper.stats()
    assert stats.get("lanes_condemned", 0) == 1, stats
    assert int(info.get("resume_step", 0)) >= 1, info
    assert slot._guard.snapshot()["hangs"] >= 1
    assert slot._guard.snapshot()["condemned_lanes"] >= 1
    stepper.shutdown()


# ---------------------------------------------------------------------------
# worker-level rungs: quarantine shrinks capacity, restart exit code
# ---------------------------------------------------------------------------


def _guard_worker(pool, **settings_over):
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.node.settings import Settings
    from chiaswarm_tpu.node.worker import Worker

    base = dict(hive_uri="http://hive", hive_token="t",
                worker_name="guard-w", install_signal_handlers=False)
    base.update(settings_over)
    return Worker(settings=Settings(**base), pool=pool,
                  registry=ModelRegistry(catalog=[], allow_random=True))


def test_quarantine_rung_shrinks_capacity_and_restart_rung_exits():
    """The two heavy rungs, end to end through the worker: escalating
    hangs on one chip of a 2-chip slot quarantine it — the slot mesh
    shrinks to the healthy chip and /healthz re-advertises the
    capacity — and further sickness requests the graceful restart with
    the distinct supervisor exit code."""
    import jax

    from chiaswarm_tpu.core.chip_pool import ChipPool
    from chiaswarm_tpu.core.mesh import MeshSpec

    pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 2}),
                    devices=jax.devices()[:2])
    worker = _guard_worker(pool, guard_cache_flush_after=2,
                           guard_quarantine_after=4,
                           guard_restart_after=6)
    slot = worker.pool.slots[0]
    assert slot.data_width == 2
    assert worker.health()["chips_in_service"] == 2
    sick = str(slot.mesh.devices.flatten()[0].id)

    worker.guard.note_hang([sick])                  # streak 2: flush
    worker.guard.note_hang([sick])                  # streak 4: quarantine
    worker._apply_heal_rungs()
    assert slot.data_width == 1
    assert sick not in {str(d.id) for d in slot.mesh.devices.flatten()}
    health = worker.health()
    assert health["chips_in_service"] == 1
    assert health["guard"]["quarantined"] == [sick]

    worker.guard.note_hang([sick])                  # streak 6: restart
    worker._apply_heal_rungs()
    assert worker._stop.is_set()
    assert worker.exit_code == GUARD_RESTART_EXIT_CODE
    # the /metrics mirror shows the rung transitions + health score
    body = worker.metrics.render()
    assert 'chiaswarm_guard_heal_rung_total{rung="device_quarantine"} 1' \
        in body
    assert 'chiaswarm_guard_heal_rung_total{rung="restart"} 1' in body
    assert f'chiaswarm_guard_device_health{{device="{sick}"}} 0' in body
    assert "chiaswarm_guard_quarantined_devices 1" in body


def test_single_chip_slot_declines_quarantine_and_escalates():
    """A 1-chip slot cannot shrink: the quarantine rung no-ops loudly
    and the next rung (restart) still fires — a sick only-chip heals by
    replacement, not amputation."""
    import jax

    from chiaswarm_tpu.core.chip_pool import ChipPool
    from chiaswarm_tpu.core.mesh import MeshSpec

    pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 1}),
                    devices=jax.devices()[:1])
    worker = _guard_worker(pool, guard_quarantine_after=2,
                           guard_restart_after=4)
    slot = worker.pool.slots[0]
    sick = str(slot.mesh.devices.flatten()[0].id)
    worker.guard.note_hang([sick])
    worker._apply_heal_rungs()
    assert slot.data_width == 1                     # unchanged
    worker.guard.note_hang([sick])
    worker._apply_heal_rungs()
    assert worker.exit_code == GUARD_RESTART_EXIT_CODE


def test_solo_watchdog_raises_stephung_and_notes_health(monkeypatch):
    """The solo denoise watchdog: the FIRST watched call on a slot runs
    under the generous ceiling (the solo program may be compiling —
    the code-review finding); later calls that outlive the tight
    steps-x-EWMA budget raise StepHung on return (classified transient
    -> the ladder re-runs them) and the device guard hears a solo-phase
    hang."""
    from chiaswarm_tpu.serving.guard import watch_solo

    class FakeStepper:
        @staticmethod
        def step_ewma():
            return 0.01

    class Slot:
        _stepper = FakeStepper()

    slot = Slot()
    slot._guard = DeviceGuard(metrics_registry=Registry())
    monkeypatch.setenv(guard.ENV_HANG_FACTOR, "1")
    monkeypatch.setenv(guard.ENV_HANG_FLOOR, "0.05")
    # first watched call of a program variant: ceiling budget — a slow
    # (compiling) call is NOT flagged, and the variant key is marked
    # warm for this cache-flush epoch afterwards
    with watch_solo(slot, steps=5, key=("m", 64, 64)):
        time.sleep(0.3)
    assert slot._guard.snapshot()["hangs"] == 0
    epoch, warm = getattr(slot, "_guard_solo_warm")
    assert epoch == guard.flush_epoch() and ("m", 64, 64) in warm
    # second call of the SAME variant: the tight budget applies
    with pytest.raises(StepHung):
        with watch_solo(slot, steps=5, key=("m", 64, 64)):
            time.sleep(0.5)
    snap = slot._guard.snapshot()
    assert snap["hangs"] == 1
    # a DIFFERENT variant (new model/shape = its own compile-cache
    # entry) re-colds to the ceiling — no flag on its slow first call
    with watch_solo(slot, steps=5, key=("other", 64, 64)):
        time.sleep(0.3)
    assert slot._guard.snapshot()["hangs"] == 1
    # a fast call of a warm variant is never flagged
    with watch_solo(slot, steps=5, key=("m", 64, 64)):
        pass
    assert slot._guard.snapshot()["hangs"] == 1
    # cold (no EWMA): never armed
    slot._stepper = type("S", (), {"step_ewma": staticmethod(
        lambda: 0.0)})()
    with watch_solo(slot, steps=5):
        time.sleep(0.1)


def test_screen_images_accepts_single_image_with_uniform_rows():
    """Regression (code review): an (H, W, C) array is ONE image, not a
    stack of H row-frames — a legitimate solid border/sky row must not
    read as a constant frame."""
    rng = np.random.default_rng(11)
    img = rng.integers(0, 255, (64, 64, 3)).astype(np.uint8)
    img[0, :, :] = 255          # solid top border row
    screen_images(img)          # no raise
    with pytest.raises(InvalidOutput):
        screen_images(np.full((64, 64, 3), 7, np.uint8))  # truly flat


def test_quarantine_amputates_at_most_one_chip_per_process():
    """Regression (code review): events are slot-granular, so every
    chip of a slot crosses the quarantine threshold together — the
    ladder must amputate ONE chip, not collapse the mesh chip by chip;
    continued sickness escalates to restart instead."""
    dg = DeviceGuard(cache_flush_after=2, quarantine_after=4,
                     restart_after=6, metrics_registry=Registry())
    devices = ["0", "1", "2", "3"]
    dg.note_hang(devices)                  # streak 2 -> one cache_flush
    assert [a.rung for a in dg.take_actions()] == ["cache_flush"]
    dg.note_hang(devices)                  # streak 4 -> ONE quarantine
    actions = dg.take_actions()
    assert [a.rung for a in actions] == ["device_quarantine"]
    assert len(dg.quarantined) == 1
    dg.note_hang(devices)                  # streak 6 -> restart (once)
    assert [a.rung for a in dg.take_actions()] == ["restart"]
    assert len(dg.quarantined) == 1        # still one amputation


# ---------------------------------------------------------------------------
# THE acceptance gate: 3-worker fleet, scripted wedge + NaN row
# ---------------------------------------------------------------------------


def _png_array(result) -> np.ndarray:
    from PIL import Image

    blob = result["artifacts"]["primary"]["blob"]
    raw = base64.b64decode(blob) if isinstance(blob, str) else blob
    return np.asarray(Image.open(io.BytesIO(raw)).convert("RGB"))


def test_fleet_gate_wedge_and_nan_settle_exactly_once(monkeypatch):
    """ISSUE 10 acceptance: 3 real-lane workers on one MiniHive, mixed
    workloads, one scripted mid-lane wedge (condemn -> resume) and one
    injected NaN row (invalid_output -> hive redispatch). Every job
    settles exactly once, the condemned lane's surviving rows resume at
    step >= 1, no uploaded image is poisoned, and the guard's health +
    rung families are live on /metrics."""
    import aiohttp
    import jax

    from chiaswarm_tpu.core.chip_pool import ChipPool
    from chiaswarm_tpu.core.mesh import MeshSpec
    from chiaswarm_tpu.node.loadgen import ContentionProbe
    from chiaswarm_tpu.node.minihive import MiniHive
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.node.settings import Settings
    from chiaswarm_tpu.node.worker import Worker

    monkeypatch.setenv("CHIASWARM_STEPPER", "1")
    monkeypatch.setenv("CHIASWARM_STEPPER_CKPT_EVERY", "1")
    monkeypatch.setenv("CHIASWARM_STEPPER_STEP_DELAY_S", "0.05")
    # pinned width: every lane program compiles in the warm-up phase,
    # so no phase-2 dispatch ever pays a (budget-blowing) resize
    # compile under the tight watchdog
    monkeypatch.setenv("CHIASWARM_STEPPER_LANE_WIDTH", "2")
    # factor 25 over the ~0.1-0.2 s honest post-warm-up step keeps
    # honest steps far under the budget, while the 15 s wedge (below)
    # sails far over it even when GIL contention inflates the EWMA;
    # the ceiling stays at its (generous) default so any cold compile
    # — e.g. on a worker the warm-up poll race starved — never condemns
    monkeypatch.setenv(guard.ENV_HANG_FACTOR, "25")
    monkeypatch.setenv(guard.ENV_HANG_FLOOR, "1.0")
    guard.reset_chaos()

    registry_catalog = [{"name": "tiny", "family": "tiny",
                         "parameters": {}}]

    def job(tag, i, workflow="txt2img", **over):
        payload = {"id": f"{tag}-{i}", "model_name": "tiny",
                   "workflow": workflow,
                   "prompt": f"{tag} prompt {i}", "seed": 700 + i,
                   "num_inference_steps": 8, "guidance_scale": 7.5,
                   "height": 64, "width": 64,
                   "content_type": "image/png"}
        payload.update(over)
        return payload

    async def scenario():
        hive = MiniHive(lease_s=120.0, delay_s=0.01, max_jobs_per_poll=1)
        uri = await hive.start()
        workers = []
        for tag in ("a", "b", "c"):
            pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 1}),
                            devices=jax.devices()[:1])
            workers.append(Worker(
                settings=Settings(
                    hive_uri=uri, hive_token="t",
                    worker_name=f"guardfleet-{tag}",
                    job_deadline_s=600.0, heartbeat_s=0.05,
                    poll_busy_s=0.02, poll_idle_s=0.05,
                    poll_backoff_base_s=0.02, poll_backoff_cap_s=0.1,
                    upload_retries=5, upload_retry_delay_s=0.02,
                    drain_timeout_s=30.0, result_drain_timeout_s=10.0,
                    install_signal_handlers=False,
                    health_bind_ephemeral=True),
                registry=ModelRegistry(catalog=registry_catalog,
                                       allow_random=True),
                pool=pool))
        tasks = [asyncio.create_task(w.run()) for w in workers]
        bodies = []
        # contention probe (ISSUE 17 deflake, the PR-12 pattern): on a
        # 1-core container the GIL-contended warm-up inflates each
        # scheduler's honest-step EWMA, and the hang budget (EWMA x
        # factor) inflates with it — a FIXED 15 s wedge can then land
        # UNDER the budget and never condemn. Sampling host contention
        # across the warm-up and scaling the wedge seconds by the
        # measured factor keeps the wedge/budget margin the test was
        # designed with; the settlement clauses below are untouched.
        probe = ContentionProbe().start()
        try:
            # PHASE 1 (warm-up, chaos unarmed, generous cold budgets):
            # the same job SHAPES the gate jobs use (steps 4 lands in
            # the same capacity bucket as 12) — every lane executable
            # compiles here, and each scheduler's step EWMA becomes an
            # honest post-compile number
            hive.submit(job("warm", 0, num_inference_steps=4))
            hive.submit(job("warm", 1, num_inference_steps=4))
            hive.submit(job("warm", 2, workflow="img2img",
                            num_inference_steps=4,
                            start_image_uri=f"{uri}/assets/image.png",
                            strength=0.8))
            await hive.wait_for_results(3, timeout=600)

            # PHASE 2: arm the wedge (15 s nominal, scaled by the
            # measured contention factor; fired 5 post-arm steps in —
            # its job has checkpoints by then) and the NaN poison
            # (row 0, 2 post-arm steps in), then release the gate
            # jobs: mixed workloads, two txt2img + one img2img
            wedge_s = 15.0 * probe.stop()
            monkeypatch.setenv(guard.ENV_CHAOS_WEDGE,
                               f"5:{wedge_s:.2f}")
            monkeypatch.setenv(guard.ENV_CHAOS_NAN, "2:0")
            guard.reset_chaos()
            hive.submit(job("gate", 0))
            hive.submit(job("gate", 1))
            hive.submit(job("gate", 2, workflow="img2img",
                            start_image_uri=f"{uri}/assets/image.png",
                            strength=0.8))
            await hive.wait_for_results(6, timeout=600)
            async with aiohttp.ClientSession() as session:
                for worker in workers:
                    for _ in range(100):
                        if getattr(worker, "health_address", None):
                            break
                        await asyncio.sleep(0.05)
                    host, port = worker.health_address
                    async with session.get(
                            f"http://{host}:{port}/metrics") as resp:
                        bodies.append(await resp.text())
        finally:
            for worker in workers:
                worker.request_stop()
            await asyncio.gather(*(asyncio.wait_for(t, timeout=60)
                                   for t in tasks),
                                 return_exceptions=True)
            for worker in workers:
                for slot in worker.pool:
                    stepper = getattr(slot, "_stepper", None)
                    if stepper is not None:
                        stepper.shutdown()
            await hive.stop()
        return hive, workers, bodies

    hive, workers, bodies = asyncio.run(scenario())

    # exactly-once settlement: completed / redispatched invalid_output
    uploaded = hive.uploaded_ids()
    assert sorted(uploaded) == ["gate-0", "gate-1", "gate-2",
                                "warm-0", "warm-1", "warm-2"]
    assert len(uploaded) == len(set(uploaded))
    assert hive.abandoned == []
    for result in hive.results:
        assert result["pipeline_config"].get("error") is None, result
        # no garbage image ever uploads: decode and screen every frame
        screen_images(_png_array(result), context="gate upload")

    # the NaN row traveled the redispatch path (invalid_output kind)
    redispatched = hive.metrics.get(
        "chiaswarm_hive_jobs_redispatched_total")
    assert redispatched.value(kind="invalid_output") >= 1

    # the condemned lane's rows resumed at step >= 1 somewhere
    resumed = [r for r in hive.results
               if int((r["pipeline_config"].get("stepper") or {})
                      .get("resume_step", 0)) >= 1]
    all_stats = [slot._stepper.stats()
                 for w in workers for slot in w.pool
                 if getattr(slot, "_stepper", None) is not None]
    assert sum(s.get("lanes_condemned", 0) for s in all_stats) >= 1
    assert resumed, [r["pipeline_config"].get("stepper")
                     for r in hive.results]
    assert sum(s.get("rows_invalid", 0) for s in all_stats) >= 1

    # the sick worker's health + rung transitions are on /metrics:
    # counters agree with the guard snapshots, and the families render
    snaps = [w.guard.snapshot() for w in workers]
    assert sum(s["hangs"] for s in snaps) >= 1
    assert sum(s["condemned_lanes"] for s in snaps) >= 1
    assert sum(s["invalid_outputs"] for s in snaps) >= 1
    merged = "\n".join(bodies)
    assert 'chiaswarm_guard_hangs_total{phase="lane"}' in merged
    assert "chiaswarm_guard_condemned_lanes_total" in merged
    assert 'chiaswarm_guard_heal_rung_total{rung="lane_rebuild"}' in merged
    assert 'chiaswarm_guard_invalid_outputs_total{model="tiny"}' in merged
    assert "chiaswarm_guard_device_health" in merged


# ---------------------------------------------------------------------------
# nightly seeded wedge/NaN soak (CI satellite; replay with
#   CHIASWARM_SOAK_SEED=<run id> pytest tests/test_guard.py --slow -k soak)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_guard_soak_seeded_wedge_nan(monkeypatch):
    """Seeded guard soak: a stream of lane jobs through one scheduler
    with a seeded wedge AND a seeded NaN injection — every job ends as
    exactly one of completed / LaneHung-healed / InvalidOutput, nothing
    hangs the suite, and the scheduler's books balance."""
    import os as _os

    from chiaswarm_tpu.pipelines import Components, DiffusionPipeline
    from chiaswarm_tpu.serving.stepper import StepScheduler

    seed = _os.environ.get("CHIASWARM_SOAK_SEED", "guard-soak")
    jobs = max(6, int(_os.environ.get("CHIASWARM_SOAK_JOBS", "120")) // 10)
    rng = np.random.default_rng(abs(hash(seed)) % (2 ** 32))
    wedge_step = int(rng.integers(2, 6))
    nan_step = int(rng.integers(2, 6))
    monkeypatch.setenv("CHIASWARM_STEPPER_CKPT_EVERY", "1")
    # pinned width: no adaptive-resize compiles can land under the
    # tight post-warm-up budget (a compile is not a gray failure)
    monkeypatch.setenv("CHIASWARM_STEPPER_LANE_WIDTH", "4")

    pipe = DiffusionPipeline(Components.random("tiny", seed=0))
    sched = StepScheduler()
    # warm-up under the default (generous) budget: the width-4 lane
    # executables compile here, and the step EWMA becomes honest
    warm = sched.submit_request(pipe, prompt="soak warm", steps=4,
                                guidance_scale=7.5, height=64, width=64,
                                rows=1, seed=999)
    warm.result(timeout=600)[0].wait()

    monkeypatch.setenv(guard.ENV_HANG_FACTOR, "20")
    monkeypatch.setenv(guard.ENV_HANG_FLOOR, "0.5")
    monkeypatch.setenv(guard.ENV_CHAOS_WEDGE, f"{wedge_step}:3.0")
    monkeypatch.setenv(guard.ENV_CHAOS_NAN, f"{nan_step}:0")
    guard.reset_chaos()
    args = {}
    futures = []
    for i in range(jobs):
        args[i] = dict(prompt=f"soak {i}",
                       steps=int(rng.integers(3, 9)),
                       guidance_scale=7.5, height=64, width=64, rows=1,
                       seed=1000 + i)
        futures.append((i, sched.submit_request(pipe, **args[i])))
        time.sleep(0.01)
    outcomes = {"ok": 0, "healed": 0, "invalid": 0, "lost": 0}

    def settle(i, fut, heal_budget=2):
        # the executor's heal policy, inlined: one re-admission (with
        # the condemnation checkpoint when one exists) per LaneHung
        try:
            pending, _info = fut.result(timeout=600)
            pending.wait()
            return "ok"
        except InvalidOutput:
            return "invalid"
        except LaneHung as exc:
            if heal_budget <= 0:
                return "lost"
            retry = sched.submit_request(
                pipe, resume=(exc.resume if isinstance(exc.resume, dict)
                              else None), **args[i])
            verdict = settle(i, retry, heal_budget - 1)
            return "healed" if verdict == "ok" else verdict

    for i, fut in futures:
        outcomes[settle(i, fut)] += 1
    assert sum(outcomes.values()) == jobs, outcomes
    assert outcomes["lost"] == 0, outcomes
    # every job settled as a real outcome; with all jobs co-resident
    # in one lane, the wedge can convert the whole population to
    # "healed" — completion is the invariant, not the plain-ok path
    assert outcomes["ok"] + outcomes["healed"] >= jobs - 1, outcomes
    assert outcomes["healed"] >= 1, outcomes
    stats = sched.stats()
    assert stats.get("lanes_condemned", 0) >= 1  # the wedge fired
    assert stats.get("rows_invalid", 0) == 1     # one-shot NaN
    sched.shutdown()
