"""Real-architecture parity without real weights (zero-egress proof).

VERDICT r2: module fidelity at tiny configs is necessary but not
sufficient — family config mismatches (per-block head layout, epsilon,
penultimate-layer choice, SDXL pooled slicing) only surface at the REAL
configs. This file closes what is closable offline:

- Text encoders: the EXACT published SD1.5 / SD2.1 / SDXL configs run
  through ``transformers``' own CLIPTextModel(WithProjection) — the very
  classes diffusers loads (swarm/diffusion/diffusion_func.py:41-46) —
  with random weights, exported, converted, and compared number-for-
  number against the native encoders. This is NON-circular: transformers
  is the independent reference implementation, and it exercises the
  penultimate-layer readout and the SDXL pooled/text-projection path at
  full size.
- UNet/VAE: full-real-config in-memory conversion round-trips (SD1.5,
  SDXL, x4-upscaler) — the converter must map every key at the real
  per-block layouts, not just the tiny test widths.

The remaining gap — numeric agreement of a REAL checkpoint's images vs
diffusers — needs weights this environment cannot fetch; see
tests/test_real_checkpoint.py for the integration marker that runs the
moment a snapshot is present.
"""

from __future__ import annotations

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp  # noqa: E402

from chiaswarm_tpu.convert.torch_to_flax import (  # noqa: E402
    convert_text_encoder,
    convert_unet,
    convert_vae,
)
from chiaswarm_tpu.models.clip import ClipTextEncoder  # noqa: E402
from chiaswarm_tpu.models.configs import (  # noqa: E402
    SD15,
    SD21,
    SDXL,
    UPSCALER_X4,
)

# the published text-encoder configs of the SD families, as shipped in the
# HF snapshots the reference serves (text_encoder/config.json)
_SD15_CLIP_L = dict(vocab_size=49408, hidden_size=768,
                    intermediate_size=3072, num_hidden_layers=12,
                    num_attention_heads=12, max_position_embeddings=77,
                    hidden_act="quick_gelu", projection_dim=768)
_SD21_CLIP_H = dict(vocab_size=49408, hidden_size=1024,
                    intermediate_size=4096, num_hidden_layers=23,
                    num_attention_heads=16, max_position_embeddings=77,
                    hidden_act="gelu", projection_dim=512)
_SDXL_BIGG = dict(vocab_size=49408, hidden_size=1280,
                  intermediate_size=5120, num_hidden_layers=32,
                  num_attention_heads=20, max_position_embeddings=77,
                  hidden_act="gelu", projection_dim=1280,
                  # the real config value: triggers transformers'
                  # argmax-of-ids EOS pooling branch
                  eos_token_id=2)


def _prompt_ids(batch: int = 2, seed: int = 0) -> np.ndarray:
    """CLIP-shaped input ids: BOS, tokens, ONE EOS (the 49407 vocab max),
    zero padding — the pooled readout must find the EOS position."""
    rng = np.random.default_rng(seed)
    ids = np.zeros((batch, 77), np.int64)
    for b in range(batch):
        n = 5 + 3 * b
        ids[b, 0] = 49406                       # BOS
        ids[b, 1:1 + n] = rng.integers(320, 40000, n)
        ids[b, 1 + n] = 49407                   # EOS
    return ids


def _torch_text_model(hf_cfg: dict, with_projection: bool, seed: int):
    torch.manual_seed(seed)
    cfg = transformers.CLIPTextConfig(**hf_cfg)
    cls = (transformers.CLIPTextModelWithProjection if with_projection
           else transformers.CLIPTextModel)
    return cls(cfg).eval()


def _flax_params(state_dict_model):
    state = {k: v.detach().numpy()
             for k, v in state_dict_model.state_dict().items()}
    return convert_text_encoder(state)


def test_sd15_text_encoder_full_config_parity():
    """SD1.5's ViT-L/14 tower at the real config: final-layer readout
    after final_layer_norm must match transformers exactly."""
    tm = _torch_text_model(_SD15_CLIP_L, with_projection=False, seed=0)
    enc = ClipTextEncoder(SD15.text_encoders[0])
    params = _flax_params(tm)
    ids = _prompt_ids(seed=1)
    with torch.no_grad():
        want = tm(torch.from_numpy(ids)).last_hidden_state.numpy()
    seq, _ = enc.apply(params, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(seq), want, atol=2e-4, rtol=2e-4)


@pytest.mark.slow
def test_sd21_text_encoder_full_config_parity():
    """SD2.1's OpenCLIP ViT-H tower: 23 layers, gelu — the family config
    the penultimate-trimmed checkpoint actually ships."""
    tm = _torch_text_model(_SD21_CLIP_H, with_projection=False, seed=1)
    enc = ClipTextEncoder(SD21.text_encoders[0])
    params = _flax_params(tm)
    ids = _prompt_ids(seed=2)
    with torch.no_grad():
        want = tm(torch.from_numpy(ids)).last_hidden_state.numpy()
    seq, _ = enc.apply(params, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(seq), want, atol=2e-4, rtol=2e-4)


def test_sdxl_encoder1_penultimate_readout_parity():
    """SDXL text_encoder 1: ViT-L with hidden_states[-2] readout and NO
    final layer norm (the diffusers SDXL prompt path)."""
    tm = _torch_text_model(_SD15_CLIP_L, with_projection=False, seed=2)
    enc = ClipTextEncoder(SDXL.text_encoders[0])
    params = _flax_params(tm)
    ids = _prompt_ids(seed=3)
    with torch.no_grad():
        out = tm(torch.from_numpy(ids), output_hidden_states=True)
    want = out.hidden_states[-2].numpy()
    seq, _ = enc.apply(params, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(seq), want, atol=2e-4, rtol=2e-4)


@pytest.mark.slow
def test_sdxl_encoder2_bigg_pooled_projection_parity():
    """SDXL text_encoder 2 (OpenCLIP bigG) at the FULL real config: the
    penultimate sequence readout AND the pooled text-projection output —
    the micro-conditioning input whose slicing VERDICT flagged — must
    both match transformers' CLIPTextModelWithProjection."""
    tm = _torch_text_model(_SDXL_BIGG, with_projection=True, seed=3)
    enc = ClipTextEncoder(SDXL.text_encoders[1])
    params = _flax_params(tm)
    ids = _prompt_ids(seed=4)
    with torch.no_grad():
        out = tm(torch.from_numpy(ids), output_hidden_states=True)
    want_seq = out.hidden_states[-2].numpy()
    want_pooled = out.text_embeds.numpy()
    seq, pooled = enc.apply(params, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(seq), want_seq,
                               atol=5e-4, rtol=5e-4)
    np.testing.assert_allclose(np.asarray(pooled), want_pooled,
                               atol=5e-4, rtol=5e-4)


# ---- T5 encoder vs transformers' own T5EncoderModel --------------------
# (DeepFloyd conditioning; ref swarm/diffusion/diffusion_func_if.py:16-27)


def _t5_ids_and_mask(batch: int = 2, length: int = 77, seed: int = 0):
    """T5-tokenizer-shaped inputs: tokens, ONE EOS (id 1), zero padding,
    and the padding attention mask the IF pipeline passes to the encoder."""
    rng = np.random.default_rng(seed)
    ids = np.zeros((batch, length), np.int64)
    mask = np.zeros((batch, length), np.int64)
    for b in range(batch):
        n = 6 + 5 * b
        ids[b, :n] = rng.integers(3, 32000, n)
        ids[b, n] = 1                            # </s>
        mask[b, :n + 1] = 1
    return ids, mask


@pytest.mark.slow
def test_t5_encoder_published_config_parity():
    """google/t5-v1_1-small — a real published config of the exact
    architecture family DeepFloyd's XXL encoder uses (gated-GELU, RMSNorm,
    shared relative bias, no attention scaling). The XXL width itself
    (4096d x 24, 4.7B params) does not fit host RAM, but width is a config
    number: every architecture branch XXL takes runs here, including the
    padding mask the IF serving path supplies."""
    from chiaswarm_tpu.convert.torch_to_flax import convert_t5
    from chiaswarm_tpu.models.t5 import T5Config, T5Encoder

    torch.manual_seed(7)
    tm = transformers.T5EncoderModel(transformers.T5Config(
        vocab_size=32128, d_model=512, d_kv=64, d_ff=1024,
        num_layers=8, num_heads=6, relative_attention_num_buckets=32,
        relative_attention_max_distance=128,
        feed_forward_proj="gated-gelu", tie_word_embeddings=False,
    )).eval()
    enc = T5Encoder(T5Config(
        d_model=512, d_kv=64, d_ff=1024, num_layers=8, num_heads=6,
        dtype="float32"))
    state = {k: v.detach().numpy() for k, v in tm.state_dict().items()}
    params = convert_t5(state)
    ids, mask = _t5_ids_and_mask(seed=11)
    with torch.no_grad():
        want = tm(torch.from_numpy(ids),
                  attention_mask=torch.from_numpy(mask)
                  ).last_hidden_state.numpy()
    got = enc.apply(params, jnp.asarray(ids), jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-3, rtol=1e-3)


def test_t5_relative_bucket_table_matches_transformers():
    """The bucket table at DeepFloyd-XXL's exact bucket parameters vs
    transformers' own _relative_position_bucket — the classic silent-
    mismatch site VERDICT r3 called out."""
    from transformers.models.t5.modeling_t5 import T5Attention

    from chiaswarm_tpu.models.t5 import relative_position_buckets

    for length in (8, 77, 512):
        got = relative_position_buckets(length, 32, 128)
        context = torch.arange(length)[:, None]
        memory = torch.arange(length)[None, :]
        want = T5Attention._relative_position_bucket(
            memory - context, bidirectional=True, num_buckets=32,
            max_distance=128).numpy()
        np.testing.assert_array_equal(got, want, err_msg=f"L={length}")


# ---- CLAP text tower vs transformers' own ClapTextModelWithProjection --
# (AudioLDM conditioning; ref swarm/audio/audioldm.py:12-24)


def _clap_ids(batch: int, length: int, vocab: int, seed: int) -> np.ndarray:
    """RoBERTa-shaped ids: <s> tokens </s> then <pad>=1 — the mask is
    derived from the pad id, so padding must be exercised."""
    rng = np.random.default_rng(seed)
    ids = np.full((batch, length), 1, np.int64)      # pad
    for b in range(batch):
        n = 4 + 3 * b
        ids[b, 0] = 0                                # <s>
        ids[b, 1:1 + n] = rng.integers(10, vocab - 10, n)
        ids[b, 1 + n] = 2                            # </s>
    return ids


def _clap_parity(hf_cfg: "transformers.ClapTextConfig", our_cfg, seed: int):
    from chiaswarm_tpu.convert.torch_to_flax import convert_clap_text
    from chiaswarm_tpu.models.clap import ClapTextEncoder

    torch.manual_seed(seed)
    tm = transformers.ClapTextModelWithProjection(hf_cfg).eval()
    state = {k: v.detach().numpy() for k, v in tm.state_dict().items()}
    params = convert_clap_text(state)
    ids = _clap_ids(2, 77, hf_cfg.vocab_size, seed)
    mask = (ids != 1).astype(np.int64)
    with torch.no_grad():
        out = tm(torch.from_numpy(ids),
                 attention_mask=torch.from_numpy(mask))
    seq, proj = ClapTextEncoder(our_cfg).apply(params, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(seq),
                               out.last_hidden_state.numpy(),
                               atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(proj), out.text_embeds.numpy(),
                               atol=2e-4, rtol=2e-4)


def test_clap_text_tower_tiny_parity():
    from chiaswarm_tpu.models.clap import ClapTextConfig

    hf = transformers.ClapTextConfig(
        vocab_size=500, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64, projection_dim=16,
        max_position_embeddings=130)
    ours = ClapTextConfig(vocab_size=500, hidden_size=32, num_layers=2,
                          num_heads=4, intermediate_size=64,
                          projection_dim=16, max_position_embeddings=130)
    _clap_parity(hf, ours, seed=3)


def test_clap_text_tower_real_config_parity():
    """transformers' ClapTextConfig DEFAULTS are the laion/clap-htsat
    config AudioLDM ships — the published 12x768 RoBERTa tower with the
    514-row offset position table and the two-layer ReLU projection."""
    from chiaswarm_tpu.models.clap import ClapTextConfig

    _clap_parity(transformers.ClapTextConfig(), ClapTextConfig(), seed=4)


# ---- CLIP vision tower vs transformers' CLIPVisionModelWithProjection --
# (SVD img2vid image conditioning + the safety checker's trunk)


def _vision_parity(hf_kw: dict, our_cfg, seed: int, tol: float):
    from chiaswarm_tpu.convert.torch_to_flax import convert_clip_vision
    from chiaswarm_tpu.models.clip import ClipVisionEncoder

    torch.manual_seed(seed)
    tm = transformers.CLIPVisionModelWithProjection(
        transformers.CLIPVisionConfig(**hf_kw)).eval()
    state = {k: v.detach().numpy() for k, v in tm.state_dict().items()}
    params = convert_clip_vision(state)
    rng = np.random.default_rng(seed)
    size = hf_kw["image_size"]
    pixels = rng.normal(size=(2, size, size, 3)).astype(np.float32)
    with torch.no_grad():
        want = tm(torch.from_numpy(
            pixels.transpose(0, 3, 1, 2))).image_embeds.numpy()
    got = ClipVisionEncoder(our_cfg).apply(params, jnp.asarray(pixels))
    np.testing.assert_allclose(np.asarray(got), want, atol=tol, rtol=tol)


def test_clip_vision_tiny_parity():
    from chiaswarm_tpu.models.clip import VisionConfig

    hf = dict(hidden_size=32, intermediate_size=64, num_hidden_layers=2,
              num_attention_heads=4, image_size=28, patch_size=14,
              projection_dim=16, hidden_act="quick_gelu")
    ours = VisionConfig(hidden_size=32, intermediate_size=64, num_layers=2,
                        num_heads=4, image_size=28, patch_size=14,
                        projection_dim=16)
    _vision_parity(hf, ours, seed=5, tol=2e-4)


@pytest.mark.slow
def test_clip_vision_vith_real_config_parity():
    """The laion ViT-H/14 image tower at the full published config — the
    image encoder SVD-class img2vid conditions on (and the shape class of
    the safety checker's ViT-L trunk)."""
    from chiaswarm_tpu.models.clip import VisionConfig

    hf = dict(hidden_size=1280, intermediate_size=5120,
              num_hidden_layers=32, num_attention_heads=16,
              image_size=224, patch_size=14, projection_dim=1024,
              hidden_act="gelu")
    ours = VisionConfig(hidden_size=1280, intermediate_size=5120,
                        num_layers=32, num_heads=16, image_size=224,
                        patch_size=14, projection_dim=1024,
                        hidden_act="gelu")
    _vision_parity(hf, ours, seed=6, tol=1e-3)


# ---- full-real-config UNet/VAE conversion round-trips ------------------


def _tree_leaves(tree, prefix=""):
    out = {}
    for key, value in tree.items():
        path = f"{prefix}/{key}" if prefix else key
        if isinstance(value, dict):
            out.update(_tree_leaves(value, path))
        else:
            out[path] = value
    return out


@pytest.mark.parametrize("family", [SD15, SDXL, UPSCALER_X4],
                         ids=lambda f: f.name)
@pytest.mark.slow
def test_full_config_unet_conversion_roundtrip(family):
    """The converter must map EVERY UNet key at the real per-block
    layouts (SDXL's [0,2,10] transformer depths, the x4-upscaler's
    class embedding + attention-free first level) — not just the tiny
    widths. In-memory: abstract bf16 host params -> torch-layout export
    -> converter -> identical tree."""
    from chiaswarm_tpu.pipelines.components import Components

    from tests.torch_export import export_unet

    src = Components.random_host(family, seed=0)
    exported = export_unet(src.params["unet"],
                           len(family.unet.block_out_channels))
    converted = convert_unet(exported, family.unet)

    want = _tree_leaves(src.params["unet"])
    got = _tree_leaves(converted)
    assert set(got) == set(want), (
        sorted(set(want) - set(got))[:5], sorted(set(got) - set(want))[:5])
    rng = np.random.default_rng(0)
    paths = sorted(want)
    for path in [paths[i] for i in
                 rng.choice(len(paths), size=24, replace=False)]:
        assert got[path].shape == want[path].shape, path
        np.testing.assert_array_equal(
            np.asarray(got[path], np.float32),
            np.asarray(want[path], np.float32), err_msg=path)


@pytest.mark.parametrize("family", [SD15, SDXL], ids=lambda f: f.name)
@pytest.mark.slow
def test_full_config_controlnet_conversion_roundtrip(family):
    """The ControlNet converter must map every key at the real trunk
    layouts (SD1.5's 4-level and SDXL's [0,2,10]-depth 3-level down
    path + the zero convs + the hint embedder) — the control branch of
    BASELINE config #4 (ref swarm/diffusion/diffusion_func.py:29-39)."""
    from chiaswarm_tpu.convert.torch_to_flax import convert_controlnet
    from chiaswarm_tpu.pipelines.components import ControlNetBundle

    from tests.torch_export import export_controlnet

    src = ControlNetBundle.random_host(family.name, seed=2)
    exported = export_controlnet(src.params,
                                 len(family.unet.block_out_channels))
    converted = convert_controlnet(exported, family.unet)

    want = _tree_leaves(src.params)
    got = _tree_leaves(converted)
    assert set(got) == set(want), (
        sorted(set(want) - set(got))[:5], sorted(set(got) - set(want))[:5])
    rng = np.random.default_rng(2)
    paths = sorted(want)
    for path in [paths[i] for i in
                 rng.choice(len(paths), size=24, replace=False)]:
        assert got[path].shape == want[path].shape, path
        np.testing.assert_array_equal(
            np.asarray(got[path], np.float32),
            np.asarray(want[path], np.float32), err_msg=path)


@pytest.mark.slow
def test_full_config_audioldm_unet_conversion_roundtrip():
    """The AudioLDM UNet at its real layout: cross-attention-free
    transformer blocks + the simple-projection class embedding (a Linear,
    not an Embed — the converter must transpose it) over the published
    (128, 256, 384, 640) mel-latent trunk (ref swarm/audio/
    audioldm.py:12-24)."""
    import jax

    import jax.numpy as jnp

    from chiaswarm_tpu.models.unet import UNet
    from chiaswarm_tpu.pipelines.audio import AUDIOLDM
    from chiaswarm_tpu.pipelines.components import materialize_host

    from tests.torch_export import export_unet

    unet = UNet(AUDIOLDM.unet)
    shapes = jax.eval_shape(
        unet.init, jax.random.PRNGKey(0),
        jnp.zeros((1, 8, 8, AUDIOLDM.unet.sample_channels)),
        jnp.zeros((1,)), None,
        class_labels=jnp.zeros((1, AUDIOLDM.unet.class_proj_dim)))
    src = materialize_host(shapes, np.random.default_rng(4), "bfloat16")
    exported = export_unet(src, len(AUDIOLDM.unet.block_out_channels))
    converted = convert_unet(exported, AUDIOLDM.unet)

    want = _tree_leaves(src["params"])
    got = _tree_leaves(converted["params"])
    assert set(got) == set(want), (
        sorted(set(want) - set(got))[:5], sorted(set(got) - set(want))[:5])
    for path in sorted(want):
        assert got[path].shape == want[path].shape, path
        # VALUES too: the square (512, 512) class-embedding Linear makes
        # a missing transpose shape-invisible — only equality catches it
        np.testing.assert_array_equal(
            np.asarray(got[path], np.float32),
            np.asarray(want[path], np.float32), err_msg=path)


@pytest.mark.parametrize("family", [SD15, UPSCALER_X4],
                         ids=lambda f: f.name)
@pytest.mark.slow
def test_full_config_vae_conversion_roundtrip(family):
    """Same for the VAE — including the x4-upscaler's 3-level f=4
    decoder, a layout no tiny family covered before."""
    from chiaswarm_tpu.pipelines.components import Components

    from tests.torch_export import export_vae

    src = Components.random_host(family, seed=1)
    exported = export_vae(src.params["vae"],
                          len(family.vae.block_out_channels))
    converted = convert_vae(exported, family.vae)

    want = _tree_leaves(src.params["vae"])
    got = _tree_leaves(converted)
    assert set(got) == set(want), (
        sorted(set(want) - set(got))[:5], sorted(set(got) - set(want))[:5])
    for path in sorted(want):
        assert got[path].shape == want[path].shape, path
