"""Real-architecture parity without real weights (zero-egress proof).

VERDICT r2: module fidelity at tiny configs is necessary but not
sufficient — family config mismatches (per-block head layout, epsilon,
penultimate-layer choice, SDXL pooled slicing) only surface at the REAL
configs. This file closes what is closable offline:

- Text encoders: the EXACT published SD1.5 / SD2.1 / SDXL configs run
  through ``transformers``' own CLIPTextModel(WithProjection) — the very
  classes diffusers loads (swarm/diffusion/diffusion_func.py:41-46) —
  with random weights, exported, converted, and compared number-for-
  number against the native encoders. This is NON-circular: transformers
  is the independent reference implementation, and it exercises the
  penultimate-layer readout and the SDXL pooled/text-projection path at
  full size.
- UNet/VAE: full-real-config in-memory conversion round-trips (SD1.5,
  SDXL, x4-upscaler) — the converter must map every key at the real
  per-block layouts, not just the tiny test widths.

The remaining gap — numeric agreement of a REAL checkpoint's images vs
diffusers — needs weights this environment cannot fetch; see
tests/test_real_checkpoint.py for the integration marker that runs the
moment a snapshot is present.
"""

from __future__ import annotations

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp  # noqa: E402

from chiaswarm_tpu.convert.torch_to_flax import (  # noqa: E402
    convert_text_encoder,
    convert_unet,
    convert_vae,
)
from chiaswarm_tpu.models.clip import ClipTextEncoder  # noqa: E402
from chiaswarm_tpu.models.configs import (  # noqa: E402
    SD15,
    SD21,
    SDXL,
    UPSCALER_X4,
)

# the published text-encoder configs of the SD families, as shipped in the
# HF snapshots the reference serves (text_encoder/config.json)
_SD15_CLIP_L = dict(vocab_size=49408, hidden_size=768,
                    intermediate_size=3072, num_hidden_layers=12,
                    num_attention_heads=12, max_position_embeddings=77,
                    hidden_act="quick_gelu", projection_dim=768)
_SD21_CLIP_H = dict(vocab_size=49408, hidden_size=1024,
                    intermediate_size=4096, num_hidden_layers=23,
                    num_attention_heads=16, max_position_embeddings=77,
                    hidden_act="gelu", projection_dim=512)
_SDXL_BIGG = dict(vocab_size=49408, hidden_size=1280,
                  intermediate_size=5120, num_hidden_layers=32,
                  num_attention_heads=20, max_position_embeddings=77,
                  hidden_act="gelu", projection_dim=1280,
                  # the real config value: triggers transformers'
                  # argmax-of-ids EOS pooling branch
                  eos_token_id=2)


def _prompt_ids(batch: int = 2, seed: int = 0) -> np.ndarray:
    """CLIP-shaped input ids: BOS, tokens, ONE EOS (the 49407 vocab max),
    zero padding — the pooled readout must find the EOS position."""
    rng = np.random.default_rng(seed)
    ids = np.zeros((batch, 77), np.int64)
    for b in range(batch):
        n = 5 + 3 * b
        ids[b, 0] = 49406                       # BOS
        ids[b, 1:1 + n] = rng.integers(320, 40000, n)
        ids[b, 1 + n] = 49407                   # EOS
    return ids


def _torch_text_model(hf_cfg: dict, with_projection: bool, seed: int):
    torch.manual_seed(seed)
    cfg = transformers.CLIPTextConfig(**hf_cfg)
    cls = (transformers.CLIPTextModelWithProjection if with_projection
           else transformers.CLIPTextModel)
    return cls(cfg).eval()


def _flax_params(state_dict_model):
    state = {k: v.detach().numpy()
             for k, v in state_dict_model.state_dict().items()}
    return convert_text_encoder(state)


def test_sd15_text_encoder_full_config_parity():
    """SD1.5's ViT-L/14 tower at the real config: final-layer readout
    after final_layer_norm must match transformers exactly."""
    tm = _torch_text_model(_SD15_CLIP_L, with_projection=False, seed=0)
    enc = ClipTextEncoder(SD15.text_encoders[0])
    params = _flax_params(tm)
    ids = _prompt_ids(seed=1)
    with torch.no_grad():
        want = tm(torch.from_numpy(ids)).last_hidden_state.numpy()
    seq, _ = enc.apply(params, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(seq), want, atol=2e-4, rtol=2e-4)


def test_sd21_text_encoder_full_config_parity():
    """SD2.1's OpenCLIP ViT-H tower: 23 layers, gelu — the family config
    the penultimate-trimmed checkpoint actually ships."""
    tm = _torch_text_model(_SD21_CLIP_H, with_projection=False, seed=1)
    enc = ClipTextEncoder(SD21.text_encoders[0])
    params = _flax_params(tm)
    ids = _prompt_ids(seed=2)
    with torch.no_grad():
        want = tm(torch.from_numpy(ids)).last_hidden_state.numpy()
    seq, _ = enc.apply(params, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(seq), want, atol=2e-4, rtol=2e-4)


def test_sdxl_encoder1_penultimate_readout_parity():
    """SDXL text_encoder 1: ViT-L with hidden_states[-2] readout and NO
    final layer norm (the diffusers SDXL prompt path)."""
    tm = _torch_text_model(_SD15_CLIP_L, with_projection=False, seed=2)
    enc = ClipTextEncoder(SDXL.text_encoders[0])
    params = _flax_params(tm)
    ids = _prompt_ids(seed=3)
    with torch.no_grad():
        out = tm(torch.from_numpy(ids), output_hidden_states=True)
    want = out.hidden_states[-2].numpy()
    seq, _ = enc.apply(params, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(seq), want, atol=2e-4, rtol=2e-4)


def test_sdxl_encoder2_bigg_pooled_projection_parity():
    """SDXL text_encoder 2 (OpenCLIP bigG) at the FULL real config: the
    penultimate sequence readout AND the pooled text-projection output —
    the micro-conditioning input whose slicing VERDICT flagged — must
    both match transformers' CLIPTextModelWithProjection."""
    tm = _torch_text_model(_SDXL_BIGG, with_projection=True, seed=3)
    enc = ClipTextEncoder(SDXL.text_encoders[1])
    params = _flax_params(tm)
    ids = _prompt_ids(seed=4)
    with torch.no_grad():
        out = tm(torch.from_numpy(ids), output_hidden_states=True)
    want_seq = out.hidden_states[-2].numpy()
    want_pooled = out.text_embeds.numpy()
    seq, pooled = enc.apply(params, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(seq), want_seq,
                               atol=5e-4, rtol=5e-4)
    np.testing.assert_allclose(np.asarray(pooled), want_pooled,
                               atol=5e-4, rtol=5e-4)


# ---- full-real-config UNet/VAE conversion round-trips ------------------


def _tree_leaves(tree, prefix=""):
    out = {}
    for key, value in tree.items():
        path = f"{prefix}/{key}" if prefix else key
        if isinstance(value, dict):
            out.update(_tree_leaves(value, path))
        else:
            out[path] = value
    return out


@pytest.mark.parametrize("family", [SD15, SDXL, UPSCALER_X4],
                         ids=lambda f: f.name)
def test_full_config_unet_conversion_roundtrip(family):
    """The converter must map EVERY UNet key at the real per-block
    layouts (SDXL's [0,2,10] transformer depths, the x4-upscaler's
    class embedding + attention-free first level) — not just the tiny
    widths. In-memory: abstract bf16 host params -> torch-layout export
    -> converter -> identical tree."""
    from chiaswarm_tpu.pipelines.components import Components

    from tests.torch_export import export_unet

    src = Components.random_host(family, seed=0)
    exported = export_unet(src.params["unet"],
                           len(family.unet.block_out_channels))
    converted = convert_unet(exported, family.unet)

    want = _tree_leaves(src.params["unet"])
    got = _tree_leaves(converted)
    assert set(got) == set(want), (
        sorted(set(want) - set(got))[:5], sorted(set(got) - set(want))[:5])
    rng = np.random.default_rng(0)
    paths = sorted(want)
    for path in [paths[i] for i in
                 rng.choice(len(paths), size=24, replace=False)]:
        assert got[path].shape == want[path].shape, path
        np.testing.assert_array_equal(
            np.asarray(got[path], np.float32),
            np.asarray(want[path], np.float32), err_msg=path)


@pytest.mark.parametrize("family", [SD15, UPSCALER_X4],
                         ids=lambda f: f.name)
def test_full_config_vae_conversion_roundtrip(family):
    """Same for the VAE — including the x4-upscaler's 3-level f=4
    decoder, a layout no tiny family covered before."""
    from chiaswarm_tpu.pipelines.components import Components

    from tests.torch_export import export_vae

    src = Components.random_host(family, seed=1)
    exported = export_vae(src.params["vae"],
                          len(family.vae.block_out_channels))
    converted = convert_vae(exported, family.vae)

    want = _tree_leaves(src.params["vae"])
    got = _tree_leaves(converted)
    assert set(got) == set(want), (
        sorted(set(want) - set(got))[:5], sorted(set(got) - set(want))[:5])
    for path in sorted(want):
        assert got[path].shape == want[path].shape, path
