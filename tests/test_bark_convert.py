"""Bark checkpoint conversion fidelity vs HF torch (tiny widths).

Pins every converted stage of the TTS stack (pipelines/tts.py) to the
torch reference the reference project shells out to
(swarm/audio/bark.py:15-21): causal GPT logits, non-causal fine-stage
logits per codebook, and the EnCodec quantizer+decoder waveform.
"""

from __future__ import annotations

import numpy as np
import pytest

torch = pytest.importorskip("torch")


def _tiny_bark():
    from transformers import BarkConfig, BarkModel
    from transformers.models.bark.configuration_bark import (
        BarkCoarseConfig,
        BarkFineConfig,
        BarkSemanticConfig,
    )
    from transformers.models.encodec.configuration_encodec import (
        EncodecConfig,
    )

    gpt_kw = dict(block_size=32, num_layers=2, num_heads=2, hidden_size=16,
                  dropout=0.0, bias=False)
    cfg = BarkConfig(
        semantic_config=BarkSemanticConfig(
            input_vocab_size=64, output_vocab_size=40, **gpt_kw).to_dict(),
        coarse_acoustics_config=BarkCoarseConfig(
            input_vocab_size=64, output_vocab_size=64, **gpt_kw).to_dict(),
        fine_acoustics_config=BarkFineConfig(
            input_vocab_size=24, output_vocab_size=24,
            n_codes_total=4, n_codes_given=1, **gpt_kw).to_dict(),
        codec_config=EncodecConfig(
            sampling_rate=16000, num_filters=4, upsampling_ratios=[4, 2],
            codebook_size=16, codebook_dim=8, hidden_size=8,
            num_lstm_layers=1, num_residual_layers=1,
            kernel_size=7, last_kernel_size=7, use_causal_conv=True,
            norm_type="weight_norm",
            target_bandwidths=[32.0]).to_dict(),
    )
    torch.manual_seed(0)
    # HF's _init_weights assumes LayerNorms have biases; bark's real
    # checkpoints use bias=False, which crashes it — patch for init
    from transformers.models.bark import modeling_bark as mb

    orig = mb.BarkPreTrainedModel._init_weights

    def safe_init(self, module):
        import torch.nn as nn

        if isinstance(module, nn.LayerNorm) and module.bias is None:
            module.weight.data.fill_(1.0)
            return
        orig(self, module)

    mb.BarkPreTrainedModel._init_weights = safe_init
    try:
        model = BarkModel(cfg).eval()
    finally:
        mb.BarkPreTrainedModel._init_weights = orig
    # give the weights non-degenerate values (safe_init leaves LN scale 1;
    # randomize linears/embeddings deterministically)
    sd = model.state_dict()
    gen = torch.Generator().manual_seed(7)
    for key, value in sd.items():
        if value.dtype.is_floating_point and value.ndim >= 2:
            sd[key] = torch.randn(value.shape, generator=gen) * 0.05
    model.load_state_dict(sd)
    return model


def _tts_family():
    from chiaswarm_tpu.models.codec import CodecConfig
    from chiaswarm_tpu.models.gpt import GPTConfig
    from chiaswarm_tpu.pipelines.tts import TTSFamily

    gpt_kw = dict(n_layer=2, n_head=2, n_embd=16, block_size=32)
    return TTSFamily(
        name="convert_test",
        semantic=GPTConfig(vocab_size=64, output_vocab_size=40, **gpt_kw),
        coarse=GPTConfig(vocab_size=64, output_vocab_size=64, **gpt_kw),
        fine=GPTConfig(vocab_size=24, output_vocab_size=24, **gpt_kw),
        codec=CodecConfig(n_codebooks=4, codebook_size=16, codebook_dim=8,
                          num_filters=4, upsampling_ratios=(4, 2),
                          num_lstm_layers=1, sampling_rate=16000),
        # scaled protocol constants consistent with the tiny vocabs
        text_encoding_offset=2,
        text_pad_token=60,
        semantic_infer_token=63,
        semantic_vocab=30,
        max_input_semantic_length=8,
        semantic_rate_hz=40.0,
        max_semantic_tokens=16,
        coarse_rate_hz=40.0,
        n_coarse=2,
        coarse_semantic_pad=62,
        coarse_infer_token=63,
        max_coarse_input_length=8,
        max_coarse_history=6,
        sliding_window_len=4,
        n_fine=4,
        fine_history_length=8,
        fine_input_length=16,
        codebook_size=16,
    )


@pytest.fixture(scope="module")
def converted():
    from chiaswarm_tpu.convert.torch_to_flax import convert_bark

    hf = _tiny_bark()
    state = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    fam = _tts_family()
    return hf, fam, convert_bark(state, fam)


def test_semantic_gpt_logits_match(converted):
    import jax.numpy as jnp

    from chiaswarm_tpu.models.gpt import GPT, init_caches

    hf, fam, params = converted
    ids = np.array([[3, 9, 21, 5, 17]], np.int64)
    with torch.no_grad():
        tl = hf.semantic(input_ids=torch.from_numpy(ids)).logits.numpy()
    gpt = GPT(fam.semantic)
    fl, _ = gpt.apply(params["semantic"], jnp.asarray(ids, jnp.int32),
                      init_caches(fam.semantic, 1), 0, jnp.int32(5))
    np.testing.assert_allclose(np.asarray(fl), tl, atol=1e-3, rtol=3e-3)


def test_coarse_gpt_logits_match(converted):
    import jax.numpy as jnp

    from chiaswarm_tpu.models.gpt import GPT, init_caches

    hf, fam, params = converted
    ids = np.array([[1, 40, 13, 46]], np.int64)
    with torch.no_grad():
        tl = hf.coarse_acoustics(
            input_ids=torch.from_numpy(ids)).logits.numpy()
    gpt = GPT(fam.coarse)
    fl, _ = gpt.apply(params["coarse"], jnp.asarray(ids, jnp.int32),
                      init_caches(fam.coarse, 1), 0, jnp.int32(4))
    np.testing.assert_allclose(np.asarray(fl), tl, atol=1e-3, rtol=3e-3)


def test_fine_logits_match_per_codebook(converted):
    import jax.numpy as jnp

    from chiaswarm_tpu.models.gpt import FineGPT

    hf, fam, params = converted
    rng = np.random.RandomState(0)
    codes = rng.randint(0, 17, size=(1, 8, 4)).astype(np.int64)
    fine = FineGPT(fam.fine, n_codes_total=4, n_codes_given=1)
    for ci in (1, 2, 3):
        with torch.no_grad():
            tl = hf.fine_acoustics(
                codebook_idx=ci,
                input_ids=torch.from_numpy(codes)).logits.numpy()
        fl = fine.apply(params["fine"], jnp.asarray(codes, jnp.int32), ci)
        np.testing.assert_allclose(np.asarray(fl), tl, atol=3e-4,
                                   rtol=3e-3, err_msg=f"codebook {ci}")


def test_encodec_decoder_waveform_matches(converted):
    import jax.numpy as jnp

    from chiaswarm_tpu.models.codec import CodecDecoder

    hf, fam, params = converted
    rng = np.random.RandomState(1)
    frames = 13
    codes = rng.randint(0, 16, size=(1, 4, frames)).astype(np.int64)
    with torch.no_grad():
        # (codebooks, batch, T) for quantizer.decode
        emb = hf.codec_model.quantizer.decode(
            torch.from_numpy(codes.transpose(1, 0, 2)))
        twav = hf.codec_model.decoder(emb).numpy()[:, 0]
    dec = CodecDecoder(fam.codec)
    fwav = np.asarray(dec.apply(params["codec"],
                                jnp.asarray(codes, jnp.int32)))
    assert fwav.shape == twav.shape
    np.testing.assert_allclose(fwav, twav, atol=1e-4, rtol=1e-3)


def test_tts_pipeline_runs_from_converted_checkpoint(tmp_path, converted):
    """End-to-end: save the torch state, load through
    TTSComponents.from_checkpoint, synthesize."""
    from chiaswarm_tpu.pipelines.tts import TTSComponents, TTSPipeline

    hf, fam, _ = converted
    torch.save(hf.state_dict(), str(tmp_path / "pytorch_model.bin"))
    c = TTSComponents.from_checkpoint(tmp_path, "bark-tiny", fam)
    wav, sr, config = TTSPipeline(c)("hi there", duration_s=0.2, seed=1)
    assert sr == 16000
    assert wav.shape[1] > 0 and np.isfinite(wav).all()
