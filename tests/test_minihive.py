"""Fleet-scale fault tolerance (ISSUE 6): the MiniHive lease protocol
and multi-worker chaos.

Three layers:

- **Protocol units** (fake clock, no workers): lease grant/extend/
  expiry, redelivery with the dead worker excluded, heartbeat checkpoint
  custody (stale senders rejected), exactly-once settling under double
  uploads, and redispatch on ``error_kind=model_unavailable``.
- **Fleet chaos** (real Workers + ChaoticExecutor, no pipelines): a
  partition outliving the lease makes the presumed-dead worker's late
  upload race the redelivered completion — exactly one is acked; a
  worker killed mid-job loses nothing.
- **The acceptance gate** (real lanes): 3 workers on one mini-hive, one
  killed mid-lane — every in-flight job completes exactly once, and the
  redelivered job provably resumes from checkpoint step >= 1 (asserted
  via its resume-step metric/span), not from step 0.

Everything is hermetic (loopback only) and scripted/seeded.
"""

from __future__ import annotations

import asyncio
import time

import pytest

from chiaswarm_tpu.node.chaos import ChaoticExecutor
from chiaswarm_tpu.node.executor import error_result
from chiaswarm_tpu.node.hivelog import HiveJournal
from chiaswarm_tpu.node.minihive import MiniHive, result_error_kind
from chiaswarm_tpu.node.registry import ModelRegistry
from chiaswarm_tpu.node.settings import Settings
from chiaswarm_tpu.node.worker import Worker


@pytest.fixture(autouse=True)
def _tmp_root(tmp_path, monkeypatch):
    monkeypatch.setenv("SWARM_TPU_ROOT", str(tmp_path))
    return tmp_path


@pytest.fixture(autouse=True)
def _restore_matmul_precision():
    import jax

    before = jax.config.jax_default_matmul_precision
    yield
    jax.config.update("jax_default_matmul_precision", before)


class StubSlot:
    def __init__(self, depth: int = 2, data_width: int = 1,
                 name: str = "stub"):
        self.depth = depth
        self.data_width = data_width
        self.name = name

    def descriptor(self):
        return self.name


def fleet_settings(uri: str, name: str, **over) -> Settings:
    base = dict(
        hive_uri=uri, hive_token="t", worker_name=name,
        job_deadline_s=0.5,
        transient_retries=2,
        retry_backoff_s=0.01, retry_backoff_cap_s=0.05,
        breaker_threshold=3, breaker_cooldown_s=3600.0,
        poll_busy_s=0.02, poll_idle_s=0.04,
        poll_backoff_base_s=0.02, poll_backoff_cap_s=0.1,
        upload_retries=3, upload_retry_delay_s=0.02,
        drain_timeout_s=5.0, result_drain_timeout_s=5.0,
        install_signal_handlers=False,
        heartbeat_s=0.1,
    )
    base.update(over)
    return Settings(**base)


def _job(job_id: str, chaos=None, model: str = "shared/tiny", **over):
    job = {"id": job_id, "model_name": model, "prompt": f"p {job_id}",
           "num_inference_steps": 2, "height": 64, "width": 64,
           "content_type": "application/json"}
    if chaos is not None:
        job["chaos"] = chaos
    job.update(over)
    return job


def _ok_result(job_id: str, worker: str = "") -> dict:
    result = {"id": job_id, "artifacts": {}, "nsfw": False,
              "pipeline_config": {"mode": "test"}}
    if worker:
        result["worker_name"] = worker
    return result


def _counter(hive: MiniHive, name: str) -> float:
    metric = hive.metrics.get(name)
    return 0.0 if metric is None else metric.value()


# ---------------------------------------------------------------------------
# protocol units (fake clock)
# ---------------------------------------------------------------------------


def test_lease_grant_extend_expire_redeliver_excludes_dead_worker():
    clock = [0.0]
    hive = MiniHive(lease_s=10.0, clock=lambda: clock[0])
    hive.submit(_job("j1"))

    [handed] = hive._take_jobs("wA")
    assert handed["id"] == "j1" and handed["attempt"] == 1
    assert "resume" not in handed  # nothing checkpointed yet
    assert hive.lease_holder("j1") == "wA"
    assert hive._take_jobs("wB") == []  # leased elsewhere

    clock[0] = 8.0
    hive._take_jobs("wA")  # a poll proves liveness: lease extends to 18
    clock[0] = 15.0
    assert hive.sweep() == []
    clock[0] = 19.0
    assert hive.sweep() == ["j1"]  # expired -> requeued

    # the dead worker is excluded; a live one gets attempt 2
    assert hive._take_jobs("wA") == []
    [redelivered] = hive._take_jobs("wB")
    assert redelivered["attempt"] == 2
    assert hive.lease_holder("j1") == "wB"

    # starvation valve: once EVERY known worker is excluded, exclusion
    # has nothing to route around and the job flows again
    clock[0] = 40.0
    assert hive.sweep() == ["j1"]
    assert hive.excluded["j1"] == {"wA", "wB"}
    [third] = hive._take_jobs("wA")
    assert third["attempt"] == 3

    assert _counter(hive, "chiaswarm_hive_leases_granted_total") == 3
    assert _counter(hive, "chiaswarm_hive_leases_expired_total") == 2
    assert _counter(hive, "chiaswarm_hive_jobs_redelivered_total") == 2


def test_max_attempts_abandons_instead_of_looping_forever():
    clock = [0.0]
    hive = MiniHive(lease_s=1.0, max_attempts=2, clock=lambda: clock[0])
    hive.submit(_job("j1"))
    for n, worker in enumerate(["wA", "wB"], start=1):
        [handed] = hive._take_jobs(worker)
        assert handed["attempt"] == n
        clock[0] += 2.0
        hive.sweep()
    assert hive.abandoned == ["j1"]
    assert hive._take_jobs("wC") == []  # parked, not redelivered
    assert _counter(hive, "chiaswarm_hive_jobs_abandoned_total") == 1


def test_heartbeat_extends_lease_and_owns_checkpoint_custody():
    """Heartbeats keep leases alive and carry resume checkpoints; a
    sender that lost its lease is told so, and its stale checkpoint must
    NOT shadow the new holder's progress."""

    async def scenario():
        import aiohttp

        clock = [0.0]
        hive = MiniHive(lease_s=1.0, clock=lambda: clock[0])
        await hive.start()
        try:
            hive.submit(_job("j1"))
            hive._take_jobs("wA")

            async with aiohttp.ClientSession() as session:
                async def beat(worker, ckpt):
                    async with session.post(
                            f"{hive.uri}/api/heartbeat",
                            json={"worker_name": worker,
                                  "jobs": [{"id": "j1",
                                            "checkpoint": ckpt}]}) as r:
                        return await r.json()

                # heartbeats past the original expiry keep the lease
                for _ in range(5):
                    clock[0] += 0.8
                    response = await beat("wA", {"kind": "lane", "step": 3})
                    assert response == {"status": "ok", "lost": []}
                assert hive.lease_holder("j1") == "wA"
                assert hive.checkpoints["j1"]["step"] == 3

                # silence past the lease: expiry + redelivery
                clock[0] += 1.5
                hive.sweep()
                [redelivered] = hive._take_jobs("wB")
                # the redelivered copy carries the dead worker's state
                assert redelivered["resume"] == {"kind": "lane", "step": 3}
                assert redelivered["attempt"] == 2

                # the resurrected worker's heartbeat: lease lost, stale
                # checkpoint rejected
                response = await beat("wA", {"kind": "lane", "step": 99})
                assert response["lost"] == ["j1"]
                assert hive.checkpoints["j1"]["step"] == 3
                assert _counter(
                    hive, "chiaswarm_hive_checkpoints_stale_total") == 1

                # a job that SETTLED is not "lost": an upload racing the
                # next beat must not read as phantom lease churn
                hive._record_result(_ok_result("j1", "wB"), "wB")
                response = await beat("wB", None)
                assert response == {"status": "ok", "lost": []}
        finally:
            await hive.stop()

    asyncio.run(scenario())


def test_exactly_once_under_double_upload():
    clock = [0.0]
    hive = MiniHive(lease_s=1.0, clock=lambda: clock[0])
    hive.submit(_job("j1"))
    hive._take_jobs("wA")
    clock[0] = 2.0
    hive.sweep()
    hive._take_jobs("wB")

    assert hive._record_result(_ok_result("j1", "wB"), "wB") == \
        {"status": "ok"}
    # the presumed-dead worker's late upload: acked, never counted
    assert hive._record_result(_ok_result("j1", "wA"), "wA") == \
        {"status": "duplicate"}
    assert hive.uploaded_ids() == ["j1"]
    assert [r["worker_name"] for r in hive.duplicate_results] == ["wA"]
    # the registry snapshot agrees with the lists (satellite 3 contract)
    assert _counter(hive, "chiaswarm_hive_results_completed_total") == 1
    assert _counter(hive, "chiaswarm_hive_results_duplicate_total") == 1
    assert hive.stats()["completed"] == 1

    # the inverse race: the LATE upload settles first, while the
    # redelivered copy is still queued — settling must withdraw it so
    # no worker burns a full re-execution on a finished job
    hive.submit(_job("j2"))
    hive._take_jobs("wA")
    clock[0] = 4.0
    assert hive.sweep() == ["j2"]          # requeued for redelivery
    assert hive._record_result(_ok_result("j2", "wA"), "wA") == \
        {"status": "ok"}                   # late upload wins anyway
    assert hive._take_jobs("wB") == []     # queued copy withdrawn
    assert hive.stats()["pending"] == 0
    assert sorted(hive.uploaded_ids()) == ["j1", "j2"]


def test_redispatch_on_model_unavailable_error_kind():
    """The resolved taxonomy tension, hive side: a model_unavailable
    envelope does not settle the job — it requeues with the refusing
    worker excluded; a worker that HAS the model then serves it."""
    clock = [0.0]
    hive = MiniHive(lease_s=30.0, clock=lambda: clock[0])
    hive.submit(_job("j1", model="only/on-wB"))
    hive._take_jobs("wA")
    assert hive._take_jobs("wB") == []  # wB is known, j1 is leased

    refusal = error_result(_job("j1"), "model 'only/on-wB' is not "
                           "available on this node",
                           kind="model_unavailable")
    assert result_error_kind(refusal) == "model_unavailable"
    ack = hive._record_result(refusal, "wA")
    assert ack == {"status": "requeued", "kind": "model_unavailable"}
    assert hive.uploaded_ids() == []  # NOT settled
    assert hive._take_jobs("wA") == []  # refuser excluded
    [handed] = hive._take_jobs("wB")
    assert handed["attempt"] == 2
    assert hive._record_result(_ok_result("j1", "wB"), "wB") == \
        {"status": "ok"}
    assert hive.uploaded_ids() == ["j1"]
    assert hive.metrics.get("chiaswarm_hive_jobs_redispatched_total") \
        .value(kind="model_unavailable") == 1

    # a FATAL envelope settles immediately: bad inputs follow the job,
    # redispatching them would just burn another node's time
    hive.submit(_job("j2"))
    hive._take_jobs("wA")
    fatal = error_result(_job("j2"), "bad inputs", kind="fatal",
                         fatal=True)
    assert hive._record_result(fatal, "wA") == {"status": "ok"}
    assert sorted(hive.uploaded_ids()) == ["j1", "j2"]

    # a LATE refusal — its lease already expired and sweep requeued the
    # job — must not settle the error (and must not strip the queued
    # copy): the refuser is excluded, the live copy owns the outcome
    late = MiniHive(lease_s=1.0, clock=lambda: clock[0])
    clock[0] = 100.0
    late.submit(_job("j4", model="only/on-wB"))
    late._take_jobs("wA")
    clock[0] = 102.0
    assert late.sweep() == ["j4"]          # expired -> requeued
    ack = late._record_result(
        error_result(_job("j4"), "nope", kind="model_unavailable"), "wA")
    assert ack == {"status": "requeued", "kind": "model_unavailable"}
    assert late.uploaded_ids() == []       # NOT settled
    [handed] = late._take_jobs("wB")       # still deliverable
    assert handed["id"] == "j4"

    # redispatch is bounded by max_attempts: the last refusal settles
    bounded = MiniHive(lease_s=30.0, max_attempts=2,
                       clock=lambda: clock[0])
    bounded.submit(_job("j3", model="nowhere"))
    bounded._take_jobs("wA")
    assert bounded._record_result(
        error_result(_job("j3"), "nope", kind="model_unavailable"),
        "wA")["status"] == "requeued"
    bounded._take_jobs("wB")
    assert bounded._record_result(
        error_result(_job("j3"), "nope", kind="model_unavailable"),
        "wB") == {"status": "ok"}  # attempts exhausted: settle the error
    assert bounded.uploaded_ids() == ["j3"]


@pytest.mark.parametrize("restart", [False, True],
                         ids=["static", "hive_restart"])
def test_stats_reconciliation_exactly_once_at_harness_scale(
        restart, tmp_path):
    """ISSUE 9 satellite: the ``GET /api/stats`` registry snapshot stays
    exactly-once-consistent at swarmload scale — thousands of settled
    jobs churned through 4 rotating workers on a fake clock, with
    duplicates, late uploads after redelivery, overload/model refusals,
    and lease-expiry abandonment injected throughout. The counters must
    reconcile with the settle lists to the job.

    The ``hive_restart`` variant (ISSUE 14 satellite) journals the run
    and crashes the hive mid-churn — the replacement is rebuilt purely
    by journal replay (counters included) and the SAME reconciliation
    must hold across the restart, to the job."""
    clock = [0.0]
    journal_dir = tmp_path / "recon-hive"
    hive = MiniHive(lease_s=5.0, max_attempts=3, max_jobs_per_poll=8,
                    clock=lambda: clock[0],
                    journal=(HiveJournal(journal_dir, fsync=False)
                             if restart else None))
    n = 3000
    for i in range(n):
        hive.submit(_job(f"scale-{i}"))
    workers = [f"w{k}" for k in range(4)]
    rng = __import__("random").Random("scale-recon")

    injected_dupes = 0
    late_uploads = 0
    salvaged = 0
    refusals = 0
    step = 0
    restarted = False

    def record(result, worker):
        # mirror the salvage bookkeeping: ANY settle landing on an
        # abandoned job (a straggler upload — incl. a lease that a
        # mid-batch clock jump expired before its upload was recorded)
        # must move it abandoned -> completed, counted once
        nonlocal salvaged
        was_abandoned = str(result.get("id")) in hive.abandoned
        ack = hive._record_result(result, worker)
        if was_abandoned and ack.get("status") == "ok":
            salvaged += 1
        return ack

    while True:
        clock[0] += 0.5
        if restart and not restarted and len(hive.completed) >= n // 2:
            # the mid-churn crash (ISSUE 14): the live hive object is
            # garbage from here — the replacement is rebuilt purely by
            # journal replay, counters included, and the reconciliation
            # below must hold across the epoch bump
            hive.journal = None  # SIGKILL: nothing else ever commits
            hive = MiniHive.recover(
                HiveJournal(journal_dir, fsync=False),
                lease_s=5.0, max_attempts=3, max_jobs_per_poll=8,
                clock=lambda: clock[0])
            restarted = True
        worker = workers[step % len(workers)]
        step += 1
        handed = hive._take_jobs(worker)
        if not handed and not hive.leases and not hive.pending_jobs:
            break
        for payload in handed:
            job_id = str(payload["id"])
            # every delivery carries a monotone queue-age stamp
            assert payload["queued_s"] >= 0.0
            roll = rng.random()
            if int(job_id.rsplit("-", 1)[1]) % 97 == 0:
                # a pathological cohort that NEVER uploads: every
                # delivery goes silent, so these jobs march through
                # redelivery to abandonment-by-policy and stay there
                clock[0] += hive.lease_s + 0.1
                hive.sweep()
                continue
            if roll < 0.04 and payload["attempt"] < hive.max_attempts:
                # an overload shed: requeued, shedder excluded
                ack = record(error_result(
                    _job(job_id), "shed", kind="overloaded"), worker)
                assert ack["status"] == "requeued"
                refusals += 1
            elif roll < 0.07:
                # worker goes silent on this one: its lease expires
                # (redelivery, or abandonment at max_attempts)...
                clock[0] += hive.lease_s + 0.1
                hive.sweep()
                if roll < 0.055:
                    # ...and then the straggler upload lands anyway:
                    # the first settle wins; if policy had already
                    # abandoned the job, the upload SALVAGES it (one
                    # job must never read as abandoned AND completed)
                    ack = record(_ok_result(job_id, worker), worker)
                    assert ack["status"] in ("ok", "duplicate")
                    late_uploads += 1
            else:
                ack = record(_ok_result(job_id, worker), worker)
                if ack["status"] == "ok" and rng.random() < 0.05:
                    # a racing double upload: acked, never counted
                    dup = record(_ok_result(job_id, "other"), "other")
                    assert dup == {"status": "duplicate"}
                    injected_dupes += 1
        if step > 50_000:  # safety valve: must never loop forever
            raise AssertionError("reconciliation churn did not converge")

    stats = hive.stats()
    issued = [f"scale-{i}" for i in range(n)]
    completed = set(hive.completed)
    abandoned = set(hive.abandoned)
    # exactly once: every job settled XOR abandoned, none twice, none
    # lost — at thousands of jobs with every race injected
    assert completed.isdisjoint(abandoned)
    assert completed | abandoned == set(issued)
    assert len(hive.abandoned) == len(abandoned)  # no double-abandon
    uploaded = hive.uploaded_ids()
    assert len(uploaded) == len(set(uploaded)) == len(completed)
    # the registry snapshot agrees with the lists TO THE JOB
    metrics = stats["metrics"]

    def counter(name: str, label: str = "") -> float:
        return metrics[name]["values"].get(label, 0)

    assert stats["completed"] == len(completed)
    assert set(stats["abandoned"]) == abandoned
    assert counter("chiaswarm_hive_results_completed_total") \
        == len(completed)
    assert counter("chiaswarm_hive_results_duplicate_total") \
        == len(hive.duplicate_results) >= injected_dupes
    # abandonments are monotone events; the LIST shrinks when a
    # straggler upload salvages one — counters reconcile exactly
    assert counter("chiaswarm_hive_jobs_salvaged_total") == salvaged
    assert counter("chiaswarm_hive_jobs_abandoned_total") \
        == len(abandoned) + salvaged
    assert counter("chiaswarm_hive_jobs_redispatched_total",
                   "overloaded") == refusals
    # grants = attempts actually handed out — nothing leaks
    assert counter("chiaswarm_hive_leases_granted_total") \
        == sum(hive.attempts.values())
    assert stats["pending"] == 0 and not stats["leased"]
    assert injected_dupes > 20 and late_uploads > 20 and refusals > 20
    assert salvaged > 0, "the salvage path never exercised"
    assert abandoned, "the abandonment path never exercised"
    if restart:
        # the crash actually happened, the replacement is a REPLAYED
        # hive (epoch bumped, recovery counted), and every assertion
        # above reconciled journal-rebuilt counters with live ones
        assert restarted, "the mid-run hive restart never triggered"
        assert hive.hive_epoch == 2
        assert counter("chiaswarm_hive_recoveries_total") == 1
        assert stats["journal"]["records_written"] > 0


# ---------------------------------------------------------------------------
# fleet chaos: real workers, scripted executors
# ---------------------------------------------------------------------------


def _fleet_worker(uri: str, name: str, executor=None, **over) -> Worker:
    return Worker(settings=fleet_settings(uri, name, **over),
                  pool=[StubSlot(name=name)],
                  registry=ModelRegistry(catalog=[], allow_random=True),
                  executor=executor or ChaoticExecutor())


def test_partitioned_worker_late_upload_races_redelivery_exactly_once():
    """Satellite 3, end to end with real workers: W1 takes the job, gets
    partitioned past its lease, finishes anyway, and keeps retrying the
    upload; the job redelivers to W2 which completes it; the partition
    heals and W1's stale upload lands — exactly one result is acked,
    zero jobs lost, counters agree with the registry snapshot."""

    async def scenario():
        hive = MiniHive(lease_s=0.5, delay_s=0.01, max_jobs_per_poll=1)
        uri = await hive.start()
        hive.submit(_job("race-1", chaos=["slow"]))

        workers = [
            _fleet_worker(uri, f"fleet-{tag}",
                          ChaoticExecutor(slow_s=0.4),
                          upload_retries=40, upload_retry_delay_s=0.05)
            for tag in ("a", "b")
        ]
        tasks = [asyncio.create_task(w.run()) for w in workers]
        try:
            # wait for the lease; partition the holder in the SAME loop
            # tick (no await in between) so it cannot sneak an upload in
            holder = None
            deadline = time.monotonic() + 30
            while holder is None and time.monotonic() < deadline:
                holder = hive.lease_holder("race-1")
                if holder is not None:
                    hive.partition(holder)
                    break
                await asyncio.sleep(0.01)
            assert holder is not None, "job never leased"

            # the redelivered copy must be completed by the OTHER worker
            await hive.wait_for_results(1, timeout=60)
            assert hive.completed["race-1"]["worker_name"] != holder

            # heal: the stale upload lands as an idempotent duplicate
            hive.heal(holder)
            deadline = time.monotonic() + 30
            while not hive.duplicate_results and \
                    time.monotonic() < deadline:
                await asyncio.sleep(0.02)
        finally:
            for worker in workers:
                worker.request_stop()
            await asyncio.gather(*(asyncio.wait_for(t, timeout=20)
                                   for t in tasks),
                                 return_exceptions=True)
            await hive.stop()

        assert hive.uploaded_ids() == ["race-1"]          # exactly once
        assert len(hive.duplicate_results) == 1           # stale, acked
        assert hive.duplicate_results[0]["worker_name"] == holder
        # counters == lists (the satellite's registry-agreement clause)
        snap = hive.stats()
        assert snap["completed"] == 1 and snap["duplicates"] == 1
        assert _counter(hive, "chiaswarm_hive_leases_expired_total") >= 1
        assert _counter(hive, "chiaswarm_hive_jobs_redelivered_total") >= 1

    asyncio.run(scenario())


def test_starvation_valve_redelivery_back_to_self_runs_once():
    """With every OTHER worker excluded, the valve can redeliver a job
    BACK to the worker still running it. The duplicate delivery must be
    dropped worker-side (a second local copy would orphan heartbeat
    coverage of whichever copy outlives the first settle and churn the
    lease forever): the job executes once and settles exactly once."""

    async def scenario():
        hive = MiniHive(lease_s=30.0, delay_s=0.01, max_jobs_per_poll=1)
        uri = await hive.start()
        hive.submit(_job("self-1", chaos=["slow"]))
        executor = ChaoticExecutor(slow_s=1.5)
        worker = _fleet_worker(uri, "fleet-self", executor)
        task = asyncio.create_task(worker.run())
        try:
            deadline = time.monotonic() + 30
            while hive.lease_holder("self-1") is None and \
                    time.monotonic() < deadline:
                await asyncio.sleep(0.01)
            assert hive.lease_holder("self-1") == "fleet-self"
            # preemption notice mid-run: the lease expires NOW, the only
            # live worker is the (excluded) holder, so the next poll
            # hands the job straight back to it
            hive.expire_worker("fleet-self")
            await hive.wait_for_results(1, timeout=60)
        finally:
            worker.request_stop()
            await asyncio.wait_for(task, timeout=20)
            await hive.stop()

        assert hive.uploaded_ids() == ["self-1"]          # exactly once
        assert executor.attempts.get("self-1", 0) == 1    # ONE local run
        assert _counter(hive, "chiaswarm_hive_jobs_redelivered_total") >= 1

    asyncio.run(scenario())


def test_heartbeat_reports_lost_leases_to_worker():
    """Worker side of lease loss: a heartbeat naming a job the hive no
    longer leases to this worker comes back in ``lost`` — counted in
    the worker's ``leases_lost`` stat ONCE per loss, not once per beat
    for as long as the local run continues (local work continues; the
    upload dedupes hive-side)."""

    async def scenario():
        hive = MiniHive(lease_s=30.0, delay_s=0.01)
        uri = await hive.start()
        worker = _fleet_worker(uri, "ghost-worker", heartbeat_s=0.05)
        # an in-flight job the hive never leased to us — the minimal
        # stand-in for "the lease moved on while we were partitioned"
        worker._inflight["ghost-1"] = 0.0
        task = asyncio.create_task(worker.run())
        try:
            deadline = time.monotonic() + 30
            while worker.stats.leases_lost < 1 and \
                    time.monotonic() < deadline:
                await asyncio.sleep(0.02)
            assert worker.stats.leases_lost == 1
            # the hive keeps reporting the loss every beat while the job
            # stays in flight — it must NOT be re-counted (a 60s local
            # run would otherwise inflate the metric by ~600x)
            beats_before = worker.stats.lease_heartbeats
            while worker.stats.lease_heartbeats < beats_before + 5 and \
                    time.monotonic() < deadline:
                await asyncio.sleep(0.02)
            assert worker.stats.leases_lost == 1
            # ...but a NEW loss of the same id (job settled locally, then
            # re-leased and lost again) counts as a fresh event. NB: the
            # heartbeat loop skips the POST (and the counter) while
            # nothing is in flight, so wait in wall time, not beats.
            worker._inflight.pop("ghost-1", None)
            await asyncio.sleep(0.3)  # several empty beats: state resets
            worker._inflight["ghost-1"] = 0.0
            while worker.stats.leases_lost < 2 and \
                    time.monotonic() < deadline:
                await asyncio.sleep(0.02)
        finally:
            worker._inflight.pop("ghost-1", None)
            worker.request_stop()
            await asyncio.wait_for(task, timeout=20)
            await hive.stop()
        assert worker.stats.lease_heartbeats >= 1
        assert worker.stats.leases_lost == 2

    asyncio.run(scenario())


def test_checkpoint_spool_attached_only_with_heartbeats():
    """With heartbeats off (the reference-hive default) nothing ever
    delivers a checkpoint anywhere — the spool must not be attached to
    slots, so lanes/solo jobs pay no snapshot cost for unread state."""
    registry = ModelRegistry(catalog=[], allow_random=True)
    off = Worker(settings=fleet_settings("http://h", "hb-off",
                                         heartbeat_s=0.0),
                 registry=registry, pool=[StubSlot()])
    assert all(getattr(s, "_checkpoint_spool", None) is None
               for s in off.pool)
    on = Worker(settings=fleet_settings("http://h", "hb-on",
                                        heartbeat_s=0.1),
                registry=registry, pool=[StubSlot()])
    assert all(getattr(s, "_checkpoint_spool", None) is on.checkpoints
               for s in on.pool)


def test_killed_worker_mid_job_loses_nothing():
    """A worker killed outright (task cancelled + partitioned, the
    in-process SIGKILL analog) mid-execution: its leases expire, every
    one of its jobs redelivers, and all jobs in the system settle
    exactly once on the survivors."""

    async def scenario():
        hive = MiniHive(lease_s=0.5, delay_s=0.01, max_jobs_per_poll=2)
        uri = await hive.start()
        jobs = [_job(f"k-{i}", chaos=["slow"]) for i in range(6)]
        for job in jobs:
            hive.submit(job)

        workers = [_fleet_worker(uri, f"kfleet-{tag}",
                                 ChaoticExecutor(slow_s=0.4))
                   for tag in ("a", "b", "c")]
        tasks = {w.settings.worker_name: asyncio.create_task(w.run())
                 for w in workers}
        victim = None
        victim_jobs: list[str] = []
        try:
            deadline = time.monotonic() + 30
            while victim is None and time.monotonic() < deadline:
                for worker in workers:
                    name = worker.settings.worker_name
                    leased = hive.leased_ids(name)
                    if leased:
                        # partition in the same loop tick as detection:
                        # nothing from the victim lands after this point
                        victim, victim_jobs = name, leased
                        hive.partition(name)
                        break
                if victim is None:
                    await asyncio.sleep(0.01)
            assert victim is not None, "no worker ever took a job"
            tasks[victim].cancel()     # and the process "dies"
            await asyncio.gather(tasks[victim], return_exceptions=True)

            await hive.wait_for_results(len(jobs), timeout=120)
        finally:
            for worker in workers:
                worker.request_stop()
            await asyncio.gather(*(asyncio.wait_for(t, timeout=20)
                                   for t in tasks.values()),
                                 return_exceptions=True)
            await hive.stop()

        uploaded = hive.uploaded_ids()
        assert sorted(uploaded) == sorted(j["id"] for j in jobs)
        assert len(uploaded) == len(set(uploaded))  # exactly once
        assert hive.abandoned == []
        # the victim's in-flight jobs went through redelivery
        assert victim_jobs
        redelivered = _counter(hive,
                               "chiaswarm_hive_jobs_redelivered_total")
        assert redelivered >= len(victim_jobs)
        for job_id in victim_jobs:
            assert hive.completed[job_id]["worker_name"] != victim

    asyncio.run(scenario())


# ---------------------------------------------------------------------------
# THE acceptance gate: kill mid-lane, resume from checkpoint step >= 1
# ---------------------------------------------------------------------------


def test_fleet_worker_kill_mid_lane_resumes_from_checkpoint(monkeypatch):
    """ISSUE 6 acceptance: 3 workers with real lanes on one mini-hive;
    the worker holding a checkpointed job is killed mid-lane. Every
    in-flight job completes exactly once, and the redelivered job
    provably resumes at checkpoint step >= 1 — asserted via the
    result's resume-step stamp (which also rides the job's step span)
    and the survivors' rows_resumed metric — not from step 0."""
    import jax

    from chiaswarm_tpu.core.chip_pool import ChipPool
    from chiaswarm_tpu.core.mesh import MeshSpec

    monkeypatch.setenv("CHIASWARM_STEPPER", "1")
    monkeypatch.setenv("CHIASWARM_STEPPER_CKPT_EVERY", "1")
    # stretch lane wall time so the kill deterministically lands
    # mid-lane (24 steps x 80 ms >> detection latency)
    monkeypatch.setenv("CHIASWARM_STEPPER_STEP_DELAY_S", "0.08")

    registry = ModelRegistry(
        catalog=[{"name": "tiny", "family": "tiny", "parameters": {}}],
        allow_random=True)

    def lane_job(i: int) -> dict:
        return {"id": f"lane-{i}", "model_name": "tiny",
                "prompt": f"fleet prompt {i}", "seed": 900 + i,
                "num_inference_steps": 24, "guidance_scale": 7.5,
                "height": 64, "width": 64, "content_type": "image/png"}

    async def scenario():
        # a GENEROUS lease: the three workers' first lane compiles are
        # GIL-heavy enough to starve the in-process heartbeat tasks for
        # seconds, and a sub-second lease would expire (and churn every
        # job through redelivery with no checkpoint yet) before step 1
        # even runs. The kill below revokes the victim's leases
        # explicitly via expire_worker — the preemption-notice path —
        # so redelivery is immediate AND deterministic.
        hive = MiniHive(lease_s=60.0, delay_s=0.01, max_jobs_per_poll=1)
        uri = await hive.start()
        for i in range(3):
            hive.submit(lane_job(i))

        workers = []
        for tag in ("a", "b", "c"):
            pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 1}),
                            devices=jax.devices()[:1])
            workers.append(Worker(
                settings=fleet_settings(uri, f"lanefleet-{tag}",
                                        job_deadline_s=600.0,
                                        heartbeat_s=0.05),
                registry=registry, pool=pool))
        tasks = {w.settings.worker_name: asyncio.create_task(w.run())
                 for w in workers}
        victim = victim_job = None
        try:
            # wait until some job's checkpoint (step >= 1) reached the
            # hive, then kill its lease holder mid-lane — partitioned in
            # the same loop tick as detection, so the victim cannot
            # finish-and-upload between the check and the kill
            deadline = time.monotonic() + 240
            while victim is None and time.monotonic() < deadline:
                for job_id, ckpt in list(hive.checkpoints.items()):
                    holder = hive.lease_holder(job_id)
                    if ckpt.get("kind") == "lane" and \
                            int(ckpt.get("step", 0)) >= 1 and \
                            holder is not None:
                        victim_job, victim = job_id, holder
                        hive.partition(holder)
                        break
                if victim is None:
                    await asyncio.sleep(0.02)
            assert victim is not None, \
                f"no lane checkpoint ever reached the hive: {hive.stats()}"
            tasks[victim].cancel()
            await asyncio.gather(tasks[victim], return_exceptions=True)
            # the preemption notice: revoke the dead worker's leases NOW
            # instead of waiting out lease_s — its checkpointed job
            # redelivers (with resume state) on this very sweep
            assert victim_job in hive.expire_worker(victim)

            await hive.wait_for_results(3, timeout=300)
        finally:
            for worker in workers:
                worker.request_stop()
            await asyncio.gather(*(asyncio.wait_for(t, timeout=60)
                                   for t in tasks.values()),
                                 return_exceptions=True)
            # the killed worker skipped graceful shutdown: retire its
            # lanes explicitly so no driver thread outlives the test
            for worker in workers:
                for slot in worker.pool:
                    stepper = getattr(slot, "_stepper", None)
                    if stepper is not None:
                        stepper.shutdown()
            await hive.stop()
        return hive, workers, victim, victim_job

    hive, workers, victim, victim_job = asyncio.run(scenario())

    # every in-flight job completed exactly once, with a real image
    uploaded = hive.uploaded_ids()
    assert sorted(uploaded) == ["lane-0", "lane-1", "lane-2"]
    assert len(uploaded) == len(set(uploaded))
    for result in hive.results:
        assert result["pipeline_config"].get("error") is None, result
        assert "fatal_error" not in result

    # the redelivered job resumed at checkpoint step >= 1, not step 0:
    # the lane stamps resume_step into the result config (and the same
    # dict rides the job's "step" span as meta)
    resumed = hive.completed[victim_job]
    assert resumed["worker_name"] != victim
    stepper_info = resumed["pipeline_config"].get("stepper") or {}
    assert int(stepper_info.get("resume_step", 0)) >= 1, stepper_info

    # and the survivors' metrics agree
    survivor_stats = [
        slot._stepper.stats()
        for worker in workers
        if worker.settings.worker_name != victim
        for slot in worker.pool
        if getattr(slot, "_stepper", None) is not None
    ]
    assert sum(s.get("rows_resumed", 0) for s in survivor_stats) >= 1
    assert _counter(hive, "chiaswarm_hive_checkpoints_stored_total") >= 1
    assert _counter(hive, "chiaswarm_hive_jobs_redelivered_total") >= 1

    # swarmsight (ISSUE 13): the SAME kill/resume run must leave ONE
    # stitched flight record for the victim job spanning both workers —
    # grant(1, victim) -> checkpoint markers -> redelivery ->
    # grant(2, survivor) -> exactly-once settle, attempt chain gapless
    # (tests/test_flight.py carries the full dedicated gate)
    assert hive.flights.verify(["lane-0", "lane-1", "lane-2"]) == []
    record = hive.flights.get(victim_job)
    events = [e["event"] for e in record["events"]]
    assert events.count("settled") == 1 and "checkpoint" in events
    grants = [e for e in record["events"] if e["event"] == "grant"]
    assert [g["attempt"] for g in grants][:2] == [1, 2]
    assert grants[0]["worker"] == victim
    assert record["settled"]["worker"] != victim
    digests = {a["attempt"]: a["digest"]
               for a in record["attempts"] if a["digest"]}
    assert float(digests[record["settled"]["attempt"]]
                 .get("resume_step") or 0) >= 1


def test_planner_drain_mid_lane_graceful_leave_resumes(monkeypatch):
    """swarmplan scale-down safety (ISSUE 19 satellite, mirroring the
    ISSUE 6 kill gate): the autoscaler retires a worker holding a
    mid-lane checkpointed job via the GRACEFUL path — ``request_stop``
    (finish in-flight, upload, exit) plus ``expire_worker`` lease
    preemption, never partition/cancel. The preempted job redelivers
    WITH its checkpoint to a survivor whose lane resumes at step >= 1,
    while the victim's own drain upload races it — exactly-once
    settlement absorbs whichever copy lands second."""
    import jax

    from chiaswarm_tpu.core.chip_pool import ChipPool
    from chiaswarm_tpu.core.mesh import MeshSpec

    monkeypatch.setenv("CHIASWARM_STEPPER", "1")
    monkeypatch.setenv("CHIASWARM_STEPPER_CKPT_EVERY", "1")
    # stretch lane wall time so the drain decision deterministically
    # lands mid-lane (24 steps x 80 ms >> poll/redeliver latency)
    monkeypatch.setenv("CHIASWARM_STEPPER_STEP_DELAY_S", "0.08")

    registry = ModelRegistry(
        catalog=[{"name": "tiny", "family": "tiny", "parameters": {}}],
        allow_random=True)

    def lane_job(i: int) -> dict:
        return {"id": f"drain-{i}", "model_name": "tiny",
                "prompt": f"drain prompt {i}", "seed": 700 + i,
                "num_inference_steps": 24, "guidance_scale": 7.5,
                "height": 64, "width": 64, "content_type": "image/png"}

    job_ids = [f"drain-{i}" for i in range(3)]

    async def scenario():
        hive = MiniHive(lease_s=60.0, delay_s=0.01, max_jobs_per_poll=1)
        uri = await hive.start()
        for i in range(3):
            hive.submit(lane_job(i))

        workers = []
        for tag in ("a", "b", "c"):
            pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 1}),
                            devices=jax.devices()[:1])
            workers.append(Worker(
                settings=fleet_settings(uri, f"drainfleet-{tag}",
                                        job_deadline_s=600.0,
                                        heartbeat_s=0.05),
                registry=registry, pool=pool))
        tasks = {w.settings.worker_name: asyncio.create_task(w.run())
                 for w in workers}
        victim = victim_job = None
        try:
            # wait until some job's lane checkpoint (step >= 1) reached
            # the hive, then drain its lease holder — the planner's
            # scale-down actuation, verbatim (loadgen._drain_auto)
            deadline = time.monotonic() + 240
            while victim is None and time.monotonic() < deadline:
                for job_id, ckpt in list(hive.checkpoints.items()):
                    holder = hive.lease_holder(job_id)
                    if ckpt.get("kind") == "lane" and \
                            int(ckpt.get("step", 0)) >= 1 and \
                            holder is not None:
                        victim_job, victim = job_id, holder
                        break
                if victim is None:
                    await asyncio.sleep(0.02)
            assert victim is not None, \
                f"no lane checkpoint ever reached the hive: {hive.stats()}"
            victim_worker = next(
                w for w in workers
                if w.settings.worker_name == victim)
            victim_worker.request_stop()  # graceful: NOT partitioned,
            # NOT cancelled — its in-flight lane finishes and uploads
            assert victim_job in hive.expire_worker(victim)

            await hive.wait_for_results(3, timeout=300)
        finally:
            for worker in workers:
                worker.request_stop()
            await asyncio.gather(*(asyncio.wait_for(t, timeout=60)
                                   for t in tasks.values()),
                                 return_exceptions=True)
            for worker in workers:
                for slot in worker.pool:
                    stepper = getattr(slot, "_stepper", None)
                    if stepper is not None:
                        stepper.shutdown()
            await hive.stop()
        return hive, workers, victim, victim_job

    hive, workers, victim, victim_job = asyncio.run(scenario())

    # every job settled exactly once — the victim's graceful upload and
    # the survivor's resumed completion raced, and the settle set
    # arbitrated; nothing was lost or abandoned by the scale-down
    uploaded = hive.uploaded_ids()
    assert sorted(set(uploaded)) == job_ids
    assert len(uploaded) == len(set(uploaded))
    assert hive.abandoned == []
    for result in hive.results:
        assert result["pipeline_config"].get("error") is None, result
        assert "fatal_error" not in result

    # the preemption actually moved the job: a second grant went to a
    # survivor (the victim stays excluded after expire_worker), and a
    # survivor lane admitted the row WITH resume state
    assert _counter(hive, "chiaswarm_hive_checkpoints_stored_total") >= 1
    assert _counter(hive, "chiaswarm_hive_jobs_redelivered_total") >= 1
    record = hive.flights.get(victim_job)
    grants = [e for e in record["events"] if e["event"] == "grant"]
    assert [g["attempt"] for g in grants][:2] == [1, 2]
    assert grants[0]["worker"] == victim
    assert grants[1]["worker"] != victim
    survivor_stats = [
        slot._stepper.stats()
        for worker in workers
        if worker.settings.worker_name != victim
        for slot in worker.pool
        if getattr(slot, "_stepper", None) is not None
    ]
    assert sum(s.get("rows_resumed", 0) for s in survivor_stats) >= 1

    # the flight book agrees end to end: gapless attempt chains, one
    # settle per job (whichever copy won), duplicates acked not counted
    assert hive.flights.verify(job_ids) == []
    events = [e["event"] for e in record["events"]]
    assert events.count("settled") == 1 and "checkpoint" in events


# ---------------------------------------------------------------------------
# nightly fleet soak (satellite 5): seeded kills at scale
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_fleet_soak_three_workers_kill_faults():
    """Nightly 3-worker soak: a seeded job mix (CHIASWARM_SOAK_SEED,
    nightly CI passes the run id for replay) over one mini-hive, with a
    seeded worker kill mid-run. Invariant: every issued job settles as
    exactly one acked result — redelivery absorbs the kill, duplicates
    are acked but never counted, nothing is abandoned."""
    import os
    import random

    seed = os.environ.get("CHIASWARM_SOAK_SEED", "fleet-soak-default")
    n_jobs = int(os.environ.get("CHIASWARM_SOAK_JOBS", "45"))
    rng = random.Random(f"fleet-soak:{seed}")

    outcome_scripts = (
        (["ok"], 5),
        (["slow"], 3),
        (["oom", "ok"], 2),
        (["fetch", "ok"], 2),
        (["crash"], 1),
        (["fatal"], 1),
        (["hang"], 1),
    )
    weighted = [s for s, w in outcome_scripts for _ in range(w)]
    jobs = [_job(f"soak-{i}", chaos=list(rng.choice(weighted)))
            for i in range(n_jobs)]
    kill_after = rng.randint(n_jobs // 6, n_jobs // 2)

    async def scenario():
        hive = MiniHive(lease_s=0.8, delay_s=0.01, max_jobs_per_poll=3)
        uri = await hive.start()
        for job in jobs:
            hive.submit(job)
        workers = [_fleet_worker(uri, f"soak-{tag}",
                                 ChaoticExecutor(hang_s=1.0, slow_s=0.1),
                                 job_deadline_s=0.3)
                   for tag in ("a", "b", "c")]
        tasks = {w.settings.worker_name: asyncio.create_task(w.run())
                 for w in workers}
        victim = None
        try:
            deadline = time.monotonic() + 300
            while time.monotonic() < deadline:
                if victim is None and len(hive.results) >= kill_after:
                    # seeded kill: whichever worker holds a lease when
                    # the threshold passes (deterministic given the
                    # scripts; assignment-agnostic assertions below)
                    for worker in workers:
                        name = worker.settings.worker_name
                        if hive.leased_ids(name):
                            victim = name
                            hive.partition(name)
                            tasks[name].cancel()
                            break
                if len(hive.results) >= n_jobs:
                    break
                await asyncio.sleep(0.05)
        finally:
            for worker in workers:
                worker.request_stop()
            await asyncio.gather(*(asyncio.wait_for(t, timeout=30)
                                   for t in tasks.values()),
                                 return_exceptions=True)
            await hive.stop()
        return hive, victim

    hive, victim = asyncio.run(scenario())
    uploaded = hive.uploaded_ids()
    issued = [j["id"] for j in jobs]
    assert len(uploaded) == len(set(uploaded)), "double-counted result"
    assert sorted(uploaded) == sorted(issued)
    assert hive.abandoned == []
    if victim is not None:
        assert _counter(hive,
                        "chiaswarm_hive_jobs_redelivered_total") >= 0
    # swarmsight (ISSUE 13 satellite): every settled soak job carries a
    # COMPLETE flight record — no orphan span digests, no attempt gaps
    assert hive.flights.verify(issued) == []


@pytest.mark.slow
def test_fleet_soak_mixed_workload_lanes_kill_resume(monkeypatch):
    """Nightly fleet soak for the ISSUE-7 workloads: txt2img, img2img
    and inpaint jobs ride lanes (default-on) across 3 workers; the
    worker holding a checkpointed IMAGE-workload job is killed mid-lane.
    Every job completes exactly once with its correct mode stamp, and
    the redelivered image-workload job resumes from checkpoint step >= 1
    on its own truncated ladder — the kill/resume coverage for the
    newly lane-eligible workloads."""
    import jax

    from chiaswarm_tpu.core.chip_pool import ChipPool
    from chiaswarm_tpu.core.mesh import MeshSpec

    monkeypatch.setenv("CHIASWARM_STEPPER_CKPT_EVERY", "1")
    monkeypatch.setenv("CHIASWARM_STEPPER_STEP_DELAY_S", "0.08")

    registry = ModelRegistry(
        catalog=[{"name": "tiny", "family": "tiny", "parameters": {}}],
        allow_random=True)

    def mixed_job(i: int, uri: str) -> dict:
        kind = ("txt2img", "img2img", "inpaint")[i % 3]
        job = {"id": f"mix-{i}", "model_name": "tiny",
               "prompt": f"soak prompt {i}", "seed": 950 + i,
               "num_inference_steps": 24, "guidance_scale": 7.5,
               "height": 64, "width": 64, "content_type": "image/png"}
        if kind != "txt2img":
            job["start_image_uri"] = f"{uri}/assets/image.png"
            job["strength"] = 0.6
        if kind == "inpaint":
            job["mask_image_uri"] = f"{uri}/assets/mask.png"
        return job

    async def scenario():
        hive = MiniHive(lease_s=60.0, delay_s=0.01, max_jobs_per_poll=1)
        uri = await hive.start()
        jobs = [mixed_job(i, uri) for i in range(6)]
        for job in jobs:
            hive.submit(job)

        workers = []
        for tag in ("a", "b", "c"):
            pool = ChipPool(n_slots=1, mesh_spec=MeshSpec({"data": 1}),
                            devices=jax.devices()[:1])
            workers.append(Worker(
                settings=fleet_settings(uri, f"mixfleet-{tag}",
                                        job_deadline_s=600.0,
                                        heartbeat_s=0.05),
                registry=registry, pool=pool))
        tasks = {w.settings.worker_name: asyncio.create_task(w.run())
                 for w in workers}
        victim = victim_job = None
        try:
            # wait for an IMAGE-workload lane checkpoint (img2img rows
            # only checkpoint past their start index), then kill its
            # holder mid-lane with the partition+expire preemption path
            deadline = time.monotonic() + 240
            while victim is None and time.monotonic() < deadline:
                for job_id, ckpt in list(hive.checkpoints.items()):
                    holder = hive.lease_holder(job_id)
                    if ckpt.get("kind") == "lane" and \
                            ckpt.get("workload") in ("img2img",
                                                     "inpaint") and \
                            int(ckpt.get("step", 0)) >= 1 and \
                            holder is not None:
                        victim_job, victim = job_id, holder
                        hive.partition(holder)
                        break
                if victim is None:
                    await asyncio.sleep(0.02)
            assert victim is not None, \
                f"no image-workload lane checkpoint: {hive.stats()}"
            tasks[victim].cancel()
            await asyncio.gather(tasks[victim], return_exceptions=True)
            assert victim_job in hive.expire_worker(victim)

            await hive.wait_for_results(6, timeout=500)
        finally:
            for worker in workers:
                worker.request_stop()
            await asyncio.gather(*(asyncio.wait_for(t, timeout=60)
                                   for t in tasks.values()),
                                 return_exceptions=True)
            for worker in workers:
                for slot in worker.pool:
                    stepper = getattr(slot, "_stepper", None)
                    if stepper is not None:
                        stepper.shutdown()
            await hive.stop()
        return hive, workers, victim, victim_job, jobs

    hive, workers, victim, victim_job, jobs = asyncio.run(scenario())

    uploaded = hive.uploaded_ids()
    assert sorted(uploaded) == sorted(j["id"] for j in jobs)
    assert len(uploaded) == len(set(uploaded))
    by_id = {j["id"]: j for j in jobs}
    for result in hive.results:
        assert result["pipeline_config"].get("error") is None, result
        assert "fatal_error" not in result
        job = by_id[result["id"]]
        want = ("inpaint" if "mask_image_uri" in job else
                "img2img" if "start_image_uri" in job else "txt2img")
        assert result["pipeline_config"]["mode"] == want, result["id"]

    # the redelivered image-workload job resumed mid-ladder, not from
    # its start index
    resumed = hive.completed[victim_job]
    assert resumed["worker_name"] != victim
    stepper_info = resumed["pipeline_config"].get("stepper") or {}
    assert int(stepper_info.get("resume_step", 0)) >= 1, stepper_info
    # the truncated img2img ladder is preserved through redelivery
    assert resumed["pipeline_config"]["denoise_steps"] <= 24

    survivor_stats = [
        slot._stepper.stats()
        for worker in workers
        if worker.settings.worker_name != victim
        for slot in worker.pool
        if getattr(slot, "_stepper", None) is not None
    ]
    assert sum(s.get("rows_resumed", 0) for s in survivor_stats) >= 1
    admitted_img = sum(s.get("rows_admitted_img2img", 0)
                       + s.get("rows_admitted_inpaint", 0)
                       for s in survivor_stats)
    assert admitted_img >= 1, survivor_stats
    # swarmsight (ISSUE 13 satellite): complete flight records for
    # every settled soak job, incl. the killed-and-resumed one
    assert hive.flights.verify([j["id"] for j in jobs]) == []
    flight = hive.flights.get(victim_job)
    assert flight["settled"]["worker"] != victim
    assert [e["event"] for e in flight["events"]].count("settled") == 1
