"""Tokenizer parity and dispatch.

The real-checkpoint load path hinges on tokenizer file *detection*:
AudioLDM snapshots ship a RoBERTa vocab.json+merges.txt — the same file
names CLIP uses for a disjoint algorithm (byte-level BPE vs ``</w>``
wordpiece BPE). The byte-level implementation is verified against
transformers' own ``RobertaTokenizer`` over a constructed vocab (offline
oracle, same method as the model-parity suite)."""

from __future__ import annotations

import json

import numpy as np
import pytest

from chiaswarm_tpu.models.tokenizer import (
    ByteLevelBpeTokenizer,
    ClipBpeTokenizer,
    HashTokenizer,
    _bytes_to_unicode,
    load_tokenizer,
)


def _write_byte_level_vocab(path):
    """A coherent mini byte-level BPE: full byte alphabet + a few merges,
    RoBERTa special-token layout."""
    byte_map = _bytes_to_unicode()
    alphabet = [byte_map[b] for b in range(256)]
    merges = [
        ("h", "e"), ("l", "l"), ("he", "ll"), ("hell", "o"),
        ("Ġ", "w"), ("o", "r"), ("Ġw", "or"), ("Ġwor", "ld"),
        ("l", "o"), ("Ġ", "lo"),
        # accented-word merges that CROSS the ASCII letter/symbol
        # boundary ("café" -> "caf" + "é" under an ASCII-only
        # pre-tokenizer): only the unicode \p{L} pattern keeps the word
        # one span so these can apply (ADVICE r4 #1)
        ("c", "a"), ("ca", "f"), ("caf", "Ã"), ("cafÃ", "©"),
    ]
    tokens = ["<s>", "<pad>", "</s>", "<unk>"] + alphabet + [
        a + b for a, b in merges]
    vocab = {t: i for i, t in enumerate(tokens)}
    with open(path / "vocab.json", "w", encoding="utf-8") as fh:
        json.dump(vocab, fh, ensure_ascii=False)
    with open(path / "merges.txt", "w", encoding="utf-8") as fh:
        fh.write("#version: 0.2\n")
        for a, b in merges:
            fh.write(f"{a} {b}\n")


@pytest.mark.parametrize("text", [
    "hello world", "Hello, world!!", "lo lo hello", "world  hello ", "",
    "café hello", "naïve café!", "東京 hello 123", "hello…café",
])
def test_byte_level_bpe_matches_roberta_tokenizer(tmp_path, text):
    transformers = pytest.importorskip("transformers")

    _write_byte_level_vocab(tmp_path)
    hf = transformers.RobertaTokenizer(
        str(tmp_path / "vocab.json"), str(tmp_path / "merges.txt"))
    want = hf(text, padding="max_length", truncation=True,
              max_length=16)["input_ids"]
    ours = ByteLevelBpeTokenizer.from_dir(tmp_path, max_length=16)
    assert ours.encode(text) == want


def test_load_tokenizer_dispatches_on_vocab_format(tmp_path):
    byte_dir = tmp_path / "roberta"
    byte_dir.mkdir()
    _write_byte_level_vocab(byte_dir)
    assert isinstance(load_tokenizer(byte_dir), ByteLevelBpeTokenizer)

    clip_dir = tmp_path / "clip"
    clip_dir.mkdir()
    vocab = {"<|startoftext|>": 0, "<|endoftext|>": 1, "hello</w>": 2,
             "h": 3, "e": 4}
    (clip_dir / "vocab.json").write_text(json.dumps(vocab))
    (clip_dir / "merges.txt").write_text("#version: 0.2\nh e\n")
    assert isinstance(load_tokenizer(clip_dir), ClipBpeTokenizer)


def test_hash_tokenizer_avoids_low_specials():
    """CLAP layout: bos=0 pad=1 eos=2 — hashed body ids must never land on
    a special (the attention mask is derived from exact pad-id equality)."""
    tok = HashTokenizer(1000, max_length=16, eos_id=2, bos_id=0, pad_id=1)
    ids = tok.encode("a b c d e f g h i j k three word prompt")
    assert ids[0] == 0 and 2 in ids
    body = ids[1:ids.index(2)]
    assert body and all(i >= 3 for i in body)
    # padding is pad_id, not eos
    short = tok.encode("hi")
    assert short[-1] == 1


def test_hash_tokenizer_t5_layout_no_bos():
    """T5: no BOS, eos=1, pad=0 — mask ids != 0 must keep the EOS."""
    tok = HashTokenizer(32128, max_length=8, eos_id=1, pad_id=0,
                        add_bos=False)
    ids = np.asarray(tok.encode("two words"))
    assert ids[0] not in (0, 1)          # body token first, no bos
    eos_pos = int(np.argmax(ids == 1))
    assert (ids[eos_pos + 1:] == 0).all()
    mask = ids != 0
    assert mask[:eos_pos + 1].all() and not mask[eos_pos + 1:].any()


def test_hash_tokenizer_clip_layout_unchanged():
    """Default (CLIP-style) layout keeps the historical id scheme: body in
    [0, vocab-2), bos=vocab-2, eos pads."""
    tok = HashTokenizer(1000, max_length=8)
    ids = tok.encode("hi there")
    assert ids[0] == 998 and ids[-1] == 999
    assert all(i < 998 for i in ids[1:3])
