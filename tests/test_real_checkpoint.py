"""Real-checkpoint integration proof — runs the moment weights exist.

This environment has zero egress (no HF hub), so the repository cannot
carry real SD weights or goldens produced from them. This marker closes
the loop the first time it runs somewhere with a snapshot:

    CHIASWARM_REAL_CHECKPOINT=/path/to/stable-diffusion-v1-5 \
        python -m pytest tests/test_real_checkpoint.py -v

where the path is an HF snapshot dir (unet/ vae/ text_encoder/
tokenizer/ scheduler/) as fetched by ``swarm-tpu init``. The test
converts the checkpoint with the production converter, renders a fixed-
seed txt2img, and:

1. asserts the pipeline produces a finite, non-degenerate image;
2. if ``<snapshot>/chiaswarm_golden.npy`` exists (a diffusers render of
   the same prompt/seed/steps/scheduler, saved as uint8 HWC), asserts
   image-level agreement at bf16 tolerance: PSNR >= 30 dB
   (VERDICT r2 "prove the converters on real checkpoints" contract;
   reference behavior: swarm/diffusion/diffusion_func.py:41-96).

To produce the golden with diffusers (on any machine with weights):

    import torch
    from diffusers import StableDiffusionPipeline, DDIMScheduler
    pipe = StableDiffusionPipeline.from_pretrained(SNAP, torch_dtype=torch.float32)
    pipe.scheduler = DDIMScheduler.from_config(pipe.scheduler.config)
    img = pipe(PROMPT, num_inference_steps=STEPS, guidance_scale=GUIDANCE,
               generator=torch.Generator().manual_seed(SEED)).images[0]
    numpy.save(SNAP + "/chiaswarm_golden.npy", numpy.asarray(img))

NOTE on seeds: diffusers draws the initial latent from torch's RNG while
this framework uses jax.random — the trajectories only align when the
golden machinery exports the initial noise too: save
``latents = torch.randn(...)`` (the tensor diffusers feeds the pipeline
via its ``latents=`` argument, BEFORE sigma scaling) next to the golden
as ``chiaswarm_golden_latent.npy`` in NHWC (1, H/8, W/8, 4). The test
feeds it through ``GenerateRequest.init_noise``; with a shared initial
noise and the deterministic DDIM sampler the two implementations walk
the same trajectory and PSNR measures converter fidelity.
"""

from __future__ import annotations

import os
from pathlib import Path

import numpy as np
import pytest

SNAPSHOT = os.environ.get("CHIASWARM_REAL_CHECKPOINT")

PROMPT = "a photograph of an astronaut riding a horse"
SEED = 42
STEPS = 20
GUIDANCE = 7.5
SIZE = 512

_needs_sd_snapshot = pytest.mark.skipif(
    not SNAPSHOT,
    reason="set CHIASWARM_REAL_CHECKPOINT=/path/to/sd-snapshot to run "
           "the real-weights integration proof (zero-egress CI skips)",
)


def _psnr(a: np.ndarray, b: np.ndarray) -> float:
    mse = float(np.mean((a.astype(np.float64) - b.astype(np.float64)) ** 2))
    if mse == 0:
        return float("inf")
    return 10.0 * np.log10(255.0 ** 2 / mse)


@_needs_sd_snapshot
def test_real_checkpoint_txt2img_end_to_end():
    from chiaswarm_tpu.pipelines.components import Components
    from chiaswarm_tpu.pipelines.diffusion import (
        DiffusionPipeline,
        GenerateRequest,
    )

    snap = Path(SNAPSHOT)
    assert (snap / "unet").is_dir(), f"not an SD snapshot: {snap}"

    components = Components.from_checkpoint(snap)
    pipe = DiffusionPipeline(components)

    init_noise = None
    latent_file = snap / "chiaswarm_golden_latent.npy"
    if latent_file.exists():
        init_noise = np.load(latent_file)

    req = GenerateRequest(prompt=PROMPT, steps=STEPS, height=SIZE,
                          width=SIZE, seed=SEED, guidance_scale=GUIDANCE,
                          scheduler="DDIMScheduler",
                          init_noise=init_noise)
    images, config = pipe(req)

    # 1. the converted checkpoint must render a real image
    assert images.shape == (1, SIZE, SIZE, 3)
    assert images.dtype == np.uint8
    assert np.isfinite(images.astype(np.float64)).all()
    spread = int(images.max()) - int(images.min())
    assert spread > 64, f"degenerate image (spread {spread})"
    assert config.get("error") is None

    # 2. image-level agreement with the diffusers golden when present
    golden_file = snap / "chiaswarm_golden.npy"
    if not golden_file.exists():
        pytest.skip("no chiaswarm_golden.npy next to the snapshot; "
                    "converted checkpoint rendered successfully "
                    "(PSNR check needs the diffusers golden — see module "
                    "docstring)")
    golden = np.load(golden_file)
    assert golden.shape == images.shape[1:]
    psnr = _psnr(images[0], golden)
    assert psnr >= 30.0, (
        f"converted checkpoint diverges from diffusers: PSNR {psnr:.1f} dB"
    )


# ---- video snapshots (VERDICT r4 #7) ----------------------------------

VIDEO_SNAPSHOT = os.environ.get("CHIASWARM_REAL_VIDEO_CHECKPOINT")


@pytest.mark.skipif(
    not VIDEO_SNAPSHOT,
    reason="set CHIASWARM_REAL_VIDEO_CHECKPOINT=/path/to/"
           "text-to-video-ms-1.7b (or an SVD img2vid snapshot) to run "
           "the real-video-weights proof")
def test_real_video_checkpoint_end_to_end():
    """The first host with a real video snapshot proves MOTION in one
    command: strict conversion (zero synthesized leaves — trained
    temporal weights load, pipelines/video.py::_strict_match) and a clip
    whose frames actually differ (a 2D-inflated or identity-filled model
    would render a near-static clip)."""
    from chiaswarm_tpu.pipelines.video import (
        Img2VidPipeline,
        VideoComponents,
        VideoPipeline,
        get_video_family,
    )

    snap = Path(VIDEO_SNAPSHOT)
    assert (snap / "unet").is_dir(), f"not a video snapshot: {snap}"
    family = get_video_family(snap.name)
    vc = VideoComponents.from_checkpoint(snap, snap.name, family)

    if family.image_conditioned:
        rng = np.random.default_rng(SEED)
        cond = rng.integers(0, 255, (576, 1024, 3), dtype=np.uint8)
        frames, config = Img2VidPipeline(vc)(
            cond, num_frames=14, steps=25, height=576, width=1024,
            seed=SEED)
    else:
        frames, config = VideoPipeline(vc)(
            PROMPT, num_frames=16, steps=25, height=256, width=256,
            seed=SEED)

    assert frames.dtype == np.uint8 and frames.ndim == 4
    assert np.isfinite(frames.astype(np.float64)).all()
    assert config.get("error") is None
    spread = int(frames.max()) - int(frames.min())
    assert spread > 64, f"degenerate clip (spread {spread})"
    # trained temporal weights must produce real motion: mean abs
    # frame-to-frame delta well above codec noise
    deltas = np.abs(np.diff(frames.astype(np.float64), axis=0))
    assert float(deltas.mean()) > 1.0, (
        f"near-static clip (mean frame delta {deltas.mean():.3f}) — "
        f"temporal weights did not load correctly")
