"""DPT depth-estimation tests: HF torch fidelity + preprocessor wiring.

The reference's depth mode runs the transformers depth-estimation
pipeline (swarm/controlnet/input_processor.py:87-93); these pin the
native DPT port (models/dpt.py) to HF's DPTForDepthEstimation on tiny
widths and cover the weight-gated depth/normal preprocessor path.
"""

from __future__ import annotations

import numpy as np
import pytest

from chiaswarm_tpu.models.dpt import DPT_TINY, DPTDetector


def _hf_tiny():
    torch = pytest.importorskip("torch")
    from transformers import DPTConfig as HFDPTConfig
    from transformers import DPTForDepthEstimation

    cfg = HFDPTConfig(
        hidden_size=32, intermediate_size=64, num_hidden_layers=4,
        num_attention_heads=4, image_size=32, patch_size=8,
        backbone_out_indices=[0, 1, 2, 3],
        neck_hidden_sizes=[16, 16, 24, 24], fusion_hidden_size=16,
        reassemble_factors=[4, 2, 1, 0.5], readout_type="project",
        is_hybrid=False, qkv_bias=True, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, add_projection=False,
        use_batch_norm_in_fusion_residual=False,
    )
    torch.manual_seed(0)
    model = DPTForDepthEstimation(cfg).eval()
    # non-degenerate weights (init leaves many zeros)
    sd = model.state_dict()
    gen = torch.Generator().manual_seed(3)
    for key, value in sd.items():
        if value.dtype.is_floating_point:
            sd[key] = torch.randn(value.shape, generator=gen) * 0.05
    model.load_state_dict(sd)
    return torch, model


def test_dpt_conversion_matches_torch():
    torch, hf = _hf_tiny()
    import jax.numpy as jnp

    from chiaswarm_tpu.convert.torch_to_flax import convert_dpt
    from chiaswarm_tpu.models.dpt import DPTDepth

    state = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    params = convert_dpt(state)
    x = np.random.RandomState(1).randn(1, 32, 32, 3).astype(np.float32)
    with torch.no_grad():
        td = hf(torch.from_numpy(x.transpose(0, 3, 1, 2))
                ).predicted_depth.numpy()
    fd = np.asarray(DPTDepth(DPT_TINY).apply(params, jnp.asarray(x)))
    assert fd.shape == td.shape
    np.testing.assert_allclose(fd, td, atol=2e-3, rtol=2e-3)


def test_detector_runs_and_normalizes():
    det = DPTDetector.random(seed=0)
    img = (np.random.RandomState(0).rand(45, 61, 3) * 255).astype(np.uint8)
    out = det(img)
    assert out.shape == (45, 61) and out.dtype == np.uint8
    d = det.depth(img)
    assert d.shape == (45, 61) and np.isfinite(d).all()


def test_depth_preprocessor_uses_dpt_when_present(monkeypatch):
    from PIL import Image

    from chiaswarm_tpu.workloads import controlnet as wl

    monkeypatch.setattr(wl, "_DPT", [DPTDetector.random(seed=1)])
    out = wl.preprocess_image(Image.new("RGB", (64, 48), (10, 200, 80)),
                              {"type": "depth", "preprocess": True})
    assert np.asarray(out).shape == (48, 64, 3)
    normal = wl.preprocess_image(Image.new("RGB", (64, 48), (10, 200, 80)),
                                 {"type": "normalbae", "preprocess": True})
    assert np.asarray(normal).shape == (48, 64, 3)


def test_depth_preprocessor_falls_back(tmp_path, monkeypatch):
    from PIL import Image

    from chiaswarm_tpu.workloads import controlnet as wl

    monkeypatch.setenv("SDAAS_ROOT", str(tmp_path))
    monkeypatch.setattr(wl, "_DPT", [])
    out = wl.preprocess_image(Image.new("RGB", (64, 48), (10, 200, 80)),
                              {"type": "depth", "preprocess": True})
    assert np.asarray(out).shape == (48, 64, 3)
    assert wl._DPT == [None]
