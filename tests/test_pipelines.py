import numpy as np
import pytest

from chiaswarm_tpu.core.compile_cache import GLOBAL_CACHE
from chiaswarm_tpu.pipelines import Components, DiffusionPipeline, GenerateRequest


@pytest.fixture(scope="module")
def tiny_pipeline():
    return DiffusionPipeline(Components.random("tiny", seed=0))


@pytest.fixture(scope="module")
def tiny_xl_pipeline():
    return DiffusionPipeline(Components.random("tiny_xl", seed=0))


def test_txt2img_basic(tiny_pipeline):
    req = GenerateRequest(prompt="a red fox", steps=4, height=64, width=64,
                          seed=11, guidance_scale=5.0)
    img, config = tiny_pipeline(req)
    assert img.shape == (1, 64, 64, 3)
    assert img.dtype == np.uint8
    assert config["mode"] == "txt2img"
    assert config["scheduler"] == "dpmpp_2m"
    assert config["steps"] == 4

    # determinism per seed
    img2, _ = tiny_pipeline(req)
    assert np.array_equal(img, img2)
    img3, _ = tiny_pipeline(GenerateRequest(
        prompt="a red fox", steps=4, height=64, width=64, seed=12,
        guidance_scale=5.0))
    assert not np.array_equal(img, img3)


def test_txt2img_guidance_no_recompile(tiny_pipeline):
    before = GLOBAL_CACHE.executables.stats["misses"]
    for g in (3.0, 9.5):
        tiny_pipeline(GenerateRequest(prompt="x", steps=4, height=64,
                                      width=64, seed=1, guidance_scale=g))
    after = GLOBAL_CACHE.executables.stats["misses"]
    assert after - before <= 1  # same executable for both guidance values


@pytest.mark.slow
def test_txt2img_batch_and_odd_size(tiny_pipeline):
    req = GenerateRequest(prompt="x", steps=2, height=70, width=60, batch=3,
                          seed=5)
    img, config = tiny_pipeline(req)
    assert img.shape == (3, 70, 60, 3)      # exact request honored on host
    assert config["batch"] == 4             # compiled at pow2 bucket
    assert config["compiled_size"] == [128, 64]  # snapped to lattice


@pytest.mark.slow
def test_init_noise_override_controls_trajectory(tiny_pipeline):
    """GenerateRequest.init_noise (the golden-parity hook,
    tests/test_real_checkpoint.py): a pinned standard-normal initial
    noise makes the render a function of the noise alone — same noise,
    same image across different seeds; different noise, different image;
    and the override beats the seed-drawn stream."""
    rng = np.random.default_rng(0)
    noise = rng.standard_normal((1, 32, 32, 4)).astype(np.float32)

    def run(seed, init_noise):
        req = GenerateRequest(prompt="a pinned render", steps=3, height=64,
                              width=64, seed=seed, guidance_scale=4.0,
                              scheduler="DDIMScheduler",
                              init_noise=init_noise)
        img, _ = tiny_pipeline(req)
        return img

    a = run(1, noise)
    b = run(2, noise)   # different seed, same noise: DDIM => same image
    assert np.array_equal(a, b)
    c = run(1, rng.standard_normal((1, 32, 32, 4)).astype(np.float32))
    assert not np.array_equal(a, c)
    d = run(1, None)    # seed-drawn stream differs from the override
    assert not np.array_equal(a, d)

    with pytest.raises(ValueError, match="init_noise shape"):
        run(1, rng.standard_normal((1, 5, 5, 4)).astype(np.float32))


def test_img2img_preserves_layout(tiny_pipeline):
    """Strength maps to a ladder START INDEX (the reference's semantics).

    The old pixel-distance monotonicity assertion (mean |out - init| at
    strength 0.2 vs 1.0) landed within noise on the tiny random-weight
    family (~78.7 vs ~78.0, ROADMAP) — the random VAE makes pixel
    distance to the init meaningless. Assert the STABLE contract
    instead: the executed ladder position (``denoise_steps`` in the
    config) is monotone in strength, strengths that quantize to the
    same start index produce bitwise-identical images, and different
    start indices produce different images."""
    rng = np.random.default_rng(0)
    init = rng.integers(0, 255, (64, 64, 3), dtype=np.uint8)
    req = GenerateRequest(prompt="x", steps=6, height=64, width=64, seed=3,
                          init_image=init, strength=0.4, guidance_scale=1.0)
    img, config = tiny_pipeline(req)
    assert config["mode"] == "img2img"
    assert img.shape == (1, 64, 64, 3)
    assert config["denoise_steps"] == 2  # round(6 * 0.4)

    def run(strength):
        return tiny_pipeline(GenerateRequest(
            prompt="x", steps=6, height=64, width=64, seed=3,
            init_image=init, strength=strength, guidance_scale=1.0))

    roundtrip, c_rt = run(0.05)
    low, c_low = run(0.5)
    high, c_high = run(1.0)
    # monotone: more strength -> more of the ladder actually executed
    assert (c_rt["denoise_steps"] < c_low["denoise_steps"]
            < c_high["denoise_steps"])
    assert c_high["denoise_steps"] == 6  # full regenerate
    # strengths quantizing to the SAME start index are the same program
    # with the same seed: bitwise-equal images (stable, luck-free)
    twin, c_twin = run(0.1)
    assert c_twin["denoise_steps"] == c_rt["denoise_steps"]
    assert np.array_equal(twin, roundtrip)
    # different start indices genuinely change the trajectory
    assert not np.array_equal(roundtrip, high)


def test_inpaint_keeps_known_region(tiny_pipeline):
    rng = np.random.default_rng(1)
    init = rng.integers(0, 255, (64, 64, 3), dtype=np.uint8)
    mask = np.zeros((64, 64), np.float32)
    mask[:, 32:] = 1.0  # regenerate the right half only
    req = GenerateRequest(prompt="x", steps=5, height=64, width=64, seed=9,
                          init_image=init, mask=mask, guidance_scale=1.0)
    img, config = tiny_pipeline(req)
    assert config["mode"] == "inpaint"

    # the kept region is re-projected from the KNOWN latents every step,
    # so with an all-keep mask the model's prediction is fully discarded:
    # the prompt must have NO effect on the output (luck-free property —
    # the tiny family's random VAE makes pixel-distance checks noise)
    keep_a, _ = tiny_pipeline(GenerateRequest(
        prompt="x", steps=5, height=64, width=64, seed=9, init_image=init,
        mask=np.zeros((64, 64), np.float32), guidance_scale=1.0))
    keep_b, _ = tiny_pipeline(GenerateRequest(
        prompt="a completely different prompt", steps=5, height=64,
        width=64, seed=9, init_image=init,
        mask=np.zeros((64, 64), np.float32), guidance_scale=1.0))
    assert np.array_equal(keep_a, keep_b)
    # ...while an all-regenerate mask must respond to the prompt
    regen_a, _ = tiny_pipeline(GenerateRequest(
        prompt="x", steps=5, height=64, width=64, seed=9, init_image=init,
        mask=np.ones((64, 64), np.float32), guidance_scale=1.0))
    regen_b, _ = tiny_pipeline(GenerateRequest(
        prompt="a completely different prompt", steps=5, height=64,
        width=64, seed=9, init_image=init,
        mask=np.ones((64, 64), np.float32), guidance_scale=1.0))
    assert not np.array_equal(regen_a, regen_b)


def test_sdxl_family_pipeline(tiny_xl_pipeline):
    req = GenerateRequest(prompt="a castle", steps=3, height=64, width=64,
                          seed=2, guidance_scale=6.0)
    img, config = tiny_xl_pipeline(req)
    assert img.shape == (1, 64, 64, 3)
    assert config["family"] == "tiny_xl"


@pytest.mark.slow
def test_scheduler_name_routing(tiny_pipeline):
    for name, kind in [("EulerDiscreteScheduler", "euler"),
                       ("DDIMScheduler", "ddim"),
                       ("EulerAncestralDiscreteScheduler", "euler_ancestral")]:
        img, config = tiny_pipeline(GenerateRequest(
            prompt="y", steps=3, height=64, width=64, seed=1, scheduler=name))
        assert config["scheduler"] == kind
        assert img.shape == (1, 64, 64, 3)


def test_components_param_bytes(tiny_pipeline):
    assert tiny_pipeline.c.param_bytes() > 10_000


@pytest.mark.slow
def test_sample_rows_are_batch_size_invariant():
    """Row b of a batched generation must equal the image generated at
    batch=1 with the same seed (per-sample noise keys fold the row index
    into the job seed) — the invariant that makes batch bucketing and any
    future job coalescing transparent to users."""
    from chiaswarm_tpu.pipelines import (
        Components,
        DiffusionPipeline,
        GenerateRequest,
    )

    pipe = DiffusionPipeline(Components.random("tiny", seed=0))
    solo, _ = pipe(GenerateRequest(prompt="a fish", steps=2, height=64,
                                   width=64, batch=1, seed=21,
                                   guidance_scale=5.0))
    batched, _ = pipe(GenerateRequest(prompt="a fish", steps=2, height=64,
                                      width=64, batch=3, seed=21,
                                      guidance_scale=5.0))
    # bitwise equality across DIFFERENT compiled programs is not
    # guaranteed (XLA reassociates float reductions per batch shape);
    # the noise streams are identical, so rows agree to quantization
    diff = np.abs(batched[0].astype(int) - solo[0].astype(int))
    assert diff.max() <= 3 and (diff <= 1).mean() > 0.99, (
        diff.max(), (diff <= 1).mean())
    # rows differ from each other (independent noise streams)
    assert not np.array_equal(batched[0], batched[1])
