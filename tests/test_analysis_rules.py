"""Per-rule fixture tests for swarmlint (chiaswarm_tpu/analysis).

One positive (must flag) and one negative (must stay silent) snippet per
rule, plus the baseline lifecycle: finding -> grandfathered -> fixed ->
stale entry errors under --strict.

Snippets are linted under a pipelines/ pseudo-path because R5/R6 scope
themselves to the top-level program layer.
"""

from __future__ import annotations

import json
import textwrap

import pytest

from chiaswarm_tpu.analysis import analyze_source, get_rule
from chiaswarm_tpu.analysis.runner import run

PIPE = "chiaswarm_tpu/pipelines/fixture.py"


def lint(src: str, path: str = PIPE, rule: str | None = None):
    rules = [get_rule(rule)] if rule else None
    return analyze_source(textwrap.dedent(src), path, rules)


def rules_hit(src: str, path: str = PIPE):
    return sorted({f.rule for f in lint(src, path)})


# ---------------------------------------------------------------- R1

def test_r1_flags_host_sync_inside_jitted_function():
    fs = lint("""
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return np.asarray(x) + 1
        """, rule="R1")
    assert [f.rule for f in fs] == ["host-sync-in-jit"]
    assert fs[0].symbol == "step"


def test_r1_flags_sync_reachable_through_local_call_graph():
    fs = lint("""
        import jax

        def _inner(c):
            return float(c.mean())

        def _body(c, _):
            return _inner(c), None

        def scan_all(xs):
            return jax.lax.scan(_body, xs, None, length=4)
        """, rule="R1")
    assert [f.symbol for f in fs] == ["_inner"]


def test_r1_tracks_float_of_locally_assigned_array():
    fs = lint("""
        import jax

        @jax.jit
        def step(x):
            loss = x.sum()
            return float(loss)
        """, rule="R1")
    assert len(fs) == 1 and "float" in fs[0].message
    # float() of a plain scalar parameter stays silent
    fs = lint("""
        import jax

        @jax.jit
        def step(x, scale):
            return x * float(scale)
        """, rule="R1")
    assert fs == []


def test_r1_ignores_host_sync_outside_jit_and_callbacks():
    fs = lint("""
        import jax
        import numpy as np

        def postprocess(x):
            # host side of the pipeline: syncs are the POINT here
            return np.asarray(jax.device_get(x)).item()

        @jax.jit
        def step(x):
            jax.debug.print("mean={m}", m=x.mean().item())
            return x
        """, rule="R1")
    assert fs == []


# ---------------------------------------------------------------- R2

def test_r2_flags_key_reused_after_split():
    fs = lint("""
        import jax

        def sample(seed):
            key = jax.random.PRNGKey(seed)
            k1, k2 = jax.random.split(key)
            return jax.random.normal(key, (3,))   # key already spent
        """, rule="R2")
    assert [f.rule for f in fs] == ["prng-key-reuse"]
    assert "'key'" in fs[0].message


def test_r2_flags_loop_invariant_key():
    fs = lint("""
        import jax

        def sample(key, n):
            out = []
            for _ in range(n):
                out.append(jax.random.uniform(key, (2,)))
            return out
        """, rule="R2")
    assert len(fs) == 1


def test_r2_flags_key_reuse_inside_comprehensions():
    fs = lint("""
        import jax

        def sample(key, n):
            return [jax.random.normal(key, (2,)) for _ in range(n)]
        """, rule="R2")
    assert len(fs) == 1
    # per-iteration keys from the comprehension's own target are fine
    fs = lint("""
        import jax

        def sample(keys):
            return [jax.random.normal(k, (2,)) for k in keys]
        """, rule="R2")
    assert fs == []
    # a comprehension target SHADOWING an outer key must neither consume
    # it nor flag the later legitimate draw
    fs = lint("""
        import jax

        def sample(key, n):
            rows = jax.random.split(jax.random.fold_in(key, 0), n)
            xs = [jax.random.normal(key, (2,)) for key in rows]
            return xs, jax.random.normal(key, (3,))
        """, rule="R2")
    assert fs == []


def test_r2_tracks_per_iteration_keys_from_split_loops():
    # two draws from the SAME per-iteration key: correlated — flag
    fs = lint("""
        import jax

        def sample(key, n):
            for k in jax.random.split(key, n):
                a = jax.random.normal(k, (2,))
                b = jax.random.normal(k, (2,))
        """, rule="R2")
    assert len(fs) == 1
    # one draw per iteration key is the canonical correct pattern
    fs = lint("""
        import jax

        def sample(key, n):
            return [jax.random.normal(k, (2,))
                    for k in jax.random.split(key, n)]
        """, rule="R2")
    assert fs == []


def test_r2_sees_match_statement_bodies():
    # rebinds across EXHAUSTIVE match arms must be honored: the second
    # draw below is fine on every path (no false positive)
    fs = lint("""
        import jax

        def sample(key, mode):
            key, xk = jax.random.split(key)
            x = jax.random.normal(xk, (2,))
            match mode:
                case "refresh":
                    key = jax.random.fold_in(key, 7)
                case _:
                    key, extra = jax.random.split(key)
            return jax.random.normal(key, (2,))
        """, rule="R2")
    assert fs == []
    # without a wildcard arm the no-match path still carries the spent
    # key, so the same draw IS potential reuse (consistent with if/else)
    fs = lint("""
        import jax

        def sample(key, mode):
            x = jax.random.normal(key, (2,))
            match mode:
                case "refresh":
                    key = jax.random.fold_in(key, 7)
            return jax.random.normal(key, (2,))
        """, rule="R2")
    assert len(fs) == 1
    # reuse INSIDE a match arm must be caught
    fs = lint("""
        import jax

        def sample(key, mode):
            match mode:
                case "double":
                    a = jax.random.normal(key, (2,))
                    b = jax.random.normal(key, (2,))
        """, rule="R2")
    assert len(fs) == 1


def test_r2_branch_rebinds_to_untracked_values_clear_consumption():
    # both arms rebind the name to something the rule cannot track: the
    # later draw must not be flagged off the stale pre-branch state
    fs = lint("""
        import jax

        def sample(seed, cond, make_key):
            key = jax.random.PRNGKey(seed)
            a = jax.random.normal(key, (2,))
            if cond:
                key = make_key(1)
            else:
                key = make_key(2)
            return a, jax.random.normal(key, (2,))
        """, rule="R2")
    assert fs == []


def test_r2_allows_split_rebind_and_fold_in():
    fs = lint("""
        import jax

        def sample(key, n):
            for i in range(n):
                key, sub = jax.random.split(key)
                x = jax.random.normal(sub, (3,))
            rows = [jax.random.fold_in(key, r) for r in range(4)]
            y = jax.random.normal(jax.random.fold_in(key, 99), (3,))
            return x, y, rows
        """, rule="R2")
    assert fs == []


# ---------------------------------------------------------------- R3

def test_r3_flags_direct_shard_map_import_even_guarded():
    fs = lint("""
        try:
            from jax import shard_map
        except ImportError:
            from jax.experimental.shard_map import shard_map
        """, rule="R3")
    assert len(fs) == 2  # both arms must route through core.compat
    assert all("core.compat" in f.message for f in fs)


def test_r3_flags_unguarded_experimental_and_pinned_attr_call():
    fs = lint("""
        import jax
        from jax.experimental import multihost_utils

        def n(axis):
            return jax.lax.axis_size(axis)
        """, rule="R3")
    assert sorted(f.line for f in fs) == [3, 6]


def test_r3_allows_guarded_experimental_allowlisted_pallas_and_compat_itself():
    fs = lint("""
        from jax.experimental import pallas as pl
        try:
            from jax.experimental import multihost_utils
        except ImportError:
            multihost_utils = None
        from chiaswarm_tpu.core.compat import shard_map, axis_size
        """, rule="R3")
    assert fs == []
    # compat.py itself may do whatever it needs
    fs = lint("from jax.experimental.shard_map import shard_map",
              path="chiaswarm_tpu/core/compat.py", rule="R3")
    assert fs == []


# ---------------------------------------------------------------- R4

def test_r4_flags_module_scope_and_default_arg_device_init():
    fs = lint("""
        import jax

        N_CHIPS = len(jax.devices())

        def run(n=jax.device_count()):
            return n
        """, rule="R4")
    assert sorted(f.line for f in fs) == [4, 6]


def test_r4_flags_module_scope_lambda_defaults():
    fs = lint("""
        import jax

        handler = lambda devs=jax.devices(): devs
        body_is_fine = lambda: jax.devices()
        """, rule="R4")
    assert [f.line for f in fs] == [4]
    # a lambda BODY inside a decorator/default expression runs at call
    # time, not import time — must not be flagged
    fs = lint("""
        import jax

        def f(make=lambda: jax.devices()):
            return make()
        """, rule="R4")
    assert fs == []


def test_r4_allows_device_queries_inside_functions():
    fs = lint("""
        import jax

        def chip_count():
            return len(jax.devices())

        class Pool:
            def __init__(self):
                self.devices = jax.local_devices()
        """, rule="R4")
    assert fs == []


# ---------------------------------------------------------------- R5

def test_r5_flags_raw_jit_in_program_layer_and_donated_params():
    fs = lint("""
        import jax
        from functools import partial

        class Pipeline:
            def __init__(self, c):
                self._fwd = jax.jit(lambda p, x: c.apply(p, x))

        @partial(jax.jit, donate_argnums=(0,))
        def denoise(params, latents):
            return latents
        """)
    r5 = [f for f in fs if f.rule == "jit-hygiene"]
    assert len(r5) == 3  # raw jit, raw decorator jit, donated params
    assert any("donates 'params'" in f.message for f in r5)


def test_r5_allows_toplevel_jit_and_init_jits_and_non_program_layer():
    fs = lint("""
        import jax
        from chiaswarm_tpu.core.compile_cache import toplevel_jit

        def build(c, k, x):
            params = jax.jit(c.unet.init)(k, x)          # one-shot init
            params2 = jax.jit(lambda kk: c.vae.init(kk, x))(k)
            fwd = toplevel_jit(lambda p, x: c.apply(p, x))
            return params, params2, fwd
        """, rule="R5")
    assert fs == []
    # outside pipelines/workloads raw jax.jit is fine (models, tests, ...)
    fs = lint("import jax\nf = jax.jit(lambda x: x)\n",
              path="chiaswarm_tpu/models/unet.py", rule="R5")
    assert fs == []


# ---------------------------------------------------------------- R6

def test_r6_flags_raw_request_shapes_reaching_compiled_code():
    fs = lint("""
        from chiaswarm_tpu.core.compile_cache import toplevel_jit

        def serve(req, params):
            fn = toplevel_jit(lambda p, h, w: p)
            return fn(params, req.height, req.width)
        """, rule="R6")
    assert [f.rule for f in fs] == ["recompile-hazard"]
    assert "height" in fs[0].message and "width" in fs[0].message


def test_r5_flags_curried_partial_jit_calls():
    fs = lint("""
        import jax
        from functools import partial

        class Pipeline:
            def __init__(self, c):
                self._f = partial(jax.jit, static_argnums=2)(c.apply)
        """, rule="R5")
    assert len(fs) == 1


def test_r6_sees_executables_bound_to_self_attributes():
    """The repo's dominant pattern: bind in __init__, call elsewhere."""
    fs = lint("""
        from chiaswarm_tpu.core.compile_cache import toplevel_jit

        class Pipeline:
            def __init__(self, c):
                self._run = toplevel_jit(lambda p, h, w: p)

            def generate(self, req, params):
                return self._run(params, req.height, req.width)
        """, rule="R6")
    assert [f.rule for f in fs] == ["recompile-hazard"]
    assert "generate" in fs[0].symbol


def test_r6_is_not_silenced_by_lookalike_method_names():
    fs = lint("""
        from chiaswarm_tpu.core.compile_cache import toplevel_jit

        def serve(req, params, store):
            store.snapshot()   # NOT a bucketing helper
            fn = toplevel_jit(lambda p, h: p)
            return fn(params, req.height)
        """, rule="R6")
    assert [f.rule for f in fs] == ["recompile-hazard"]


def test_r6_allows_bucketed_shapes_and_forwarding_functions():
    fs = lint("""
        from chiaswarm_tpu.core.compile_cache import (
            bucket_batch, bucket_image_size, toplevel_jit,
        )

        def serve(req, params):
            h, w = bucket_image_size(req.height, req.width)
            b = bucket_batch(req.batch)
            fn = toplevel_jit(lambda p, h, w, b: p)
            return fn(params, h, w, b)

        def enqueue(req, queue):
            # no compiled call here: forwarding the request is fine
            queue.put((req.height, req.width))
        """, rule="R6")
    assert fs == []


# ---------------------------------------------------------------- R7

def test_r7_flags_unpinned_mixed_dtype_scan_carry():
    fs = lint("""
        import jax
        import jax.numpy as jnp

        def body(x, _):
            x32 = x.astype(jnp.float32)
            return x32 * 2.0, None

        def run(x0):
            return jax.lax.scan(body, x0, None, length=4)
        """, rule="R7")
    assert [f.rule for f in fs] == ["scan-carry-dtype"]
    assert fs[0].symbol == "body"
    assert "carry" in fs[0].message


def test_r7_flags_fori_loop_body_and_keyword_binding():
    fs = lint("""
        import jax
        import jax.numpy as jnp

        def run(x0):
            def step(i, x):
                return x + jnp.float32(1.5)

            a = jax.lax.fori_loop(0, 4, step, x0)
            b = jax.lax.scan(f=lambda c, _: (c.astype(jnp.float32) + 1, None),
                             init=x0, xs=None, length=2)
            return a, b
        """, rule="R7")
    assert len(fs) == 2
    assert all(f.rule == "scan-carry-dtype" for f in fs)


def test_r7_constructor_return_is_a_promotion_not_a_pin():
    fs = lint("""
        import jax
        import jax.numpy as jnp

        def body(c, _):
            y = c.astype(jnp.float32)
            return jnp.float32(y), None

        def run(c0):
            return jax.lax.scan(body, c0, None, length=2)
        """, rule="R7")
    assert [f.rule for f in fs] == ["scan-carry-dtype"]


def test_r7_allows_pinned_carries_and_single_precision_bodies():
    fs = lint("""
        import jax
        import jax.numpy as jnp

        def pinned(x, _):
            x32 = x.astype(jnp.float32)
            x_next = (x32 * 2.0).astype(x.dtype)
            return x_next, None

        def helper_call(carry, t):
            # opaque helper result + untouched state: the repo's
            # sampler-shaped carry (pinning happens inside the helper)
            x, state = carry
            x2, state2 = step_helper(x, state, t)
            return (x2, state2), None

        def no_casts(x, _):
            return x * 2.0, None   # single-precision body: silent

        def int_casts(c, _):
            # integer casts (token ids, counters) are not a precision
            # hazard
            tok = jnp.argmax(c, axis=-1).astype(jnp.int32)
            return tok + 1, tok

        def run(x0, s0):
            jax.lax.scan(pinned, x0, None, length=2)
            jax.lax.scan(helper_call, (x0, s0), jnp.arange(2))
            jax.lax.scan(no_casts, x0, None, length=2)
            jax.lax.scan(int_casts, x0, None, length=2)
        """, rule="R7")
    assert fs == []


# ---------------------------------------------------------------- R8

def test_r8_flags_wallclock_subtraction_patterns():
    fs = lint("""
        import time

        def elapsed(work):
            t0 = time.time()
            work()
            return time.time() - t0
        """, rule="R8")
    assert [f.rule for f in fs] == ["wallclock-duration"]
    assert fs[0].symbol == "elapsed"
    assert "perf_counter" in fs[0].message


def test_r8_flags_assigned_stamp_and_module_scope_and_datetime():
    fs = lint("""
        import time
        from datetime import datetime

        _T0 = time.time()
        STARTUP_COST = time.time() - _T0

        def until_deadline(deadline):
            started = datetime.now()
            return deadline - started
        """, rule="R8")
    assert len(fs) == 2
    assert {f.symbol for f in fs} == {"<module>", "until_deadline"}


def test_r8_allows_monotonic_clocks_and_unsubtracted_stamps():
    fs = lint("""
        import time

        def timed(work):
            t0 = time.perf_counter()
            work()
            return time.perf_counter() - t0

        def paced(last):
            return time.monotonic() - last

        def stamped():
            # labeling a moment is fine; only differencing is the hazard
            return {"started_at_unix": time.time()}

        def local_scopes(t0):
            # a name assigned from time.time() in ANOTHER scope must not
            # poison this one's perf_counter arithmetic
            return time.perf_counter() - t0
        """, rule="R8")
    assert fs == []


# ---------------------------------------------------------------- baseline

BAD = """import jax

N = len(jax.devices())
"""


def _write(tmp_path, rel, content):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(content)
    return p


def test_baseline_lifecycle_add_suppress_fix_stale(tmp_path):
    mod = _write(tmp_path, "pkg/mod.py", BAD)
    bl = tmp_path / "baseline.json"

    # 1. new finding fails
    r = run([str(tmp_path)], baseline_path=str(bl), root=str(tmp_path))
    assert r.exit_code == 1 and len(r.new) == 1 and not r.stale

    # 2. grandfather it, rerun: suppressed, clean
    r = run([str(tmp_path)], baseline_path=str(bl), root=str(tmp_path),
            write_baseline=True)
    assert r.exit_code == 0
    doc = json.loads(bl.read_text())
    assert doc["schema"] == 1 and len(doc["findings"]) == 1
    assert doc["findings"][0]["rule"] == "import-time-device-init"
    r = run([str(tmp_path)], baseline_path=str(bl), root=str(tmp_path),
            strict=True)
    assert r.exit_code == 0 and len(r.suppressed) == 1

    # 3. a SECOND identical-identity finding is NOT covered (count=1)
    mod.write_text(BAD + "M = len(jax.devices())\n")
    r = run([str(tmp_path)], baseline_path=str(bl), root=str(tmp_path))
    assert r.exit_code == 1 and len(r.new) == 1 and len(r.suppressed) == 1

    # 4. fix the violation: the baseline entry is now stale —
    #    strict (CI) errors until it is deleted; non-strict only warns
    mod.write_text("import jax\n\ndef n():\n    return jax.devices()\n")
    r = run([str(tmp_path)], baseline_path=str(bl), root=str(tmp_path))
    assert r.exit_code == 0 and r.stale
    r = run([str(tmp_path)], baseline_path=str(bl), root=str(tmp_path),
            strict=True)
    assert r.exit_code == 1 and r.stale and "stale" in r.report

    # 5. shrink the baseline (the only sanctioned regeneration): clean
    r = run([str(tmp_path)], baseline_path=str(bl), root=str(tmp_path),
            write_baseline=True)
    assert json.loads(bl.read_text())["findings"] == []
    r = run([str(tmp_path)], baseline_path=str(bl), root=str(tmp_path),
            strict=True)
    assert r.exit_code == 0


def test_unparseable_file_is_reported_not_crashed(tmp_path):
    _write(tmp_path, "pkg/broken.py", "def f(:\n")
    r = run([str(tmp_path)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path))
    assert r.exit_code == 2 and r.errors
    # --write-baseline must refuse rather than write an incomplete file
    r = run([str(tmp_path)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), write_baseline=True)
    assert r.exit_code == 2 and "NOT written" in r.report
    assert not (tmp_path / "b.json").exists()


def test_baseline_entries_of_unparseable_files_are_not_stale(tmp_path):
    """A transient syntax error must not tell the user to delete still-
    valid baseline entries for that file."""
    mod = _write(tmp_path, "pkg/mod.py", BAD)
    bl = tmp_path / "baseline.json"
    r = run([str(tmp_path)], baseline_path=str(bl), root=str(tmp_path),
            write_baseline=True)
    assert r.exit_code == 0

    good = mod.read_text()
    mod.write_text("def f(:\n")  # mid-refactor breakage
    r = run([str(tmp_path)], baseline_path=str(bl), root=str(tmp_path),
            strict=True)
    assert r.exit_code == 2 and not r.stale, r.report

    mod.write_text(good)  # restored: entry still suppresses
    r = run([str(tmp_path)], baseline_path=str(bl), root=str(tmp_path),
            strict=True)
    assert r.exit_code == 0 and len(r.suppressed) == 1


def test_findings_are_deterministic_and_line_independent_keys():
    src = """
    import jax

    def sample(key):
        jax.random.normal(key, (2,))
        return jax.random.normal(key, (2,))
    """
    a = lint(src)
    b = lint("\n\n" + textwrap.dedent(src))  # shifted two lines down
    assert [f.baseline_key for f in a] == [f.baseline_key for f in b]
    assert a[0].line != b[0].line


def test_lambda_finding_keys_survive_line_shifts():
    src = """
    import jax
    f = jax.jit(lambda x: x.item())
    """
    a = lint(src, rule="R1")
    b = lint("\n# shifted\n" + textwrap.dedent(src), rule="R1")
    assert len(a) == 1
    assert [f.baseline_key for f in a] == [f.baseline_key for f in b]
    assert "<lambda#1>" in a[0].symbol


def test_overlapping_paths_and_bad_select_are_handled(tmp_path):
    _write(tmp_path, "pkg/mod.py", "x = 1\n")
    # a path fully covered by an earlier argument is not "empty"
    r = run([str(tmp_path), str(tmp_path / "pkg")],
            baseline_path=str(tmp_path / "b.json"), root=str(tmp_path))
    assert r.exit_code == 0, r.report
    # a typo'd rule selection is bad input (exit 2), not lint findings
    r = run([str(tmp_path)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), select=["R99"])
    assert r.exit_code == 2 and "unknown rule" in r.report


def test_nonexistent_path_fails_instead_of_linting_nothing(tmp_path):
    r = run([str(tmp_path / "no_such_dir")],
            baseline_path=str(tmp_path / "b.json"), root=str(tmp_path))
    assert r.exit_code == 2 and "does not exist" in r.report
    # a dir with no python files is equally suspicious
    (tmp_path / "empty").mkdir()
    r = run([str(tmp_path / "empty")],
            baseline_path=str(tmp_path / "b.json"), root=str(tmp_path))
    assert r.exit_code == 2 and "no Python files" in r.report


def test_multicount_entry_partial_fix_goes_stale(tmp_path):
    """count=2 entries must SHRINK when one of the two findings is fixed;
    leftover headroom would silently suppress a reintroduced violation."""
    mod = _write(tmp_path, "pkg/mod.py", BAD + "M = len(jax.devices())\n")
    bl = tmp_path / "baseline.json"
    r = run([str(tmp_path)], baseline_path=str(bl), root=str(tmp_path),
            write_baseline=True)
    assert json.loads(bl.read_text())["findings"][0]["count"] == 2

    mod.write_text(BAD)  # fix ONE of the two identical findings
    r = run([str(tmp_path)], baseline_path=str(bl), root=str(tmp_path),
            strict=True)
    assert r.exit_code == 1 and r.stale


def test_corrupt_baseline_is_bad_input_not_a_crash(tmp_path):
    _write(tmp_path, "pkg/mod.py", "x = 1\n")
    bl = tmp_path / "baseline.json"
    bl.write_text('{"schema": 99}')
    r = run([str(tmp_path)], baseline_path=str(bl), root=str(tmp_path))
    assert r.exit_code == 2 and "baseline" in r.report
    bl.write_text("{truncated")
    r = run([str(tmp_path)], baseline_path=str(bl), root=str(tmp_path),
            write_baseline=True)
    assert r.exit_code == 2


def test_partial_runs_do_not_corrupt_baseline(tmp_path):
    _write(tmp_path, "pkg/dev.py", BAD)  # R4 finding
    bl = tmp_path / "baseline.json"
    r = run([str(tmp_path / "pkg")], baseline_path=str(bl),
            root=str(tmp_path), write_baseline=True)
    assert r.exit_code == 0

    # --select of a DIFFERENT rule: the R4 entry is out of scope — not
    # stale, and a strict run stays green
    r = run([str(tmp_path / "pkg")], baseline_path=str(bl),
            root=str(tmp_path), select=["R2"], strict=True)
    assert r.exit_code == 0 and not r.stale

    # --write-baseline with --select is refused outright
    r = run([str(tmp_path / "pkg")], baseline_path=str(bl),
            root=str(tmp_path), select=["R2"], write_baseline=True)
    assert r.exit_code == 2 and "refusing" in r.report

    # path-subset write preserves entries for unvisited paths
    _write(tmp_path, "other/mod.py", BAD)
    r = run([str(tmp_path / "other")], baseline_path=str(bl),
            root=str(tmp_path), write_baseline=True)
    assert r.exit_code == 0
    doc = json.loads(bl.read_text())
    assert sorted(e["path"] for e in doc["findings"]) == [
        "other/mod.py", "pkg/dev.py"]


# ------------------------------------------------- swarmflow (R9/R10)

import os
import shutil
import subprocess
import sys

from chiaswarm_tpu.analysis import ProjectIndex, get_rule as _get_rule

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "swarmflow")


def _copy_fixture(tmp_path, name):
    dst = tmp_path / name
    shutil.copytree(os.path.join(FIXTURES, name), dst)
    return dst


def _index_of(*entries):
    """ProjectIndex over (relpath, source) pairs of dedented fixtures."""
    import ast as _ast

    return ProjectIndex.from_sources(
        [(rel, textwrap.dedent(src), _ast.parse(textwrap.dedent(src)))
         for rel, src in entries])


def test_r9_flags_cross_module_chain_that_r1_provably_misses(tmp_path):
    pkg = _copy_fixture(tmp_path, "syncpkg")
    r1 = run([str(pkg)], baseline_path=str(tmp_path / "b.json"),
             root=str(tmp_path), select=["R1"])
    assert r1.exit_code == 0 and r1.new == []  # per-file pass is blind
    r9 = run([str(pkg)], baseline_path=str(tmp_path / "b.json"),
             root=str(tmp_path), select=["R9"])
    assert r9.exit_code == 1 and len(r9.new) == 1
    f = r9.new[0]
    assert f.rule == "host-sync-reachability"
    assert f.path == "syncpkg/helpers.py" and f.symbol == "postprocess_mean"
    assert "'.item()'" in f.message and "syncpkg.program.step" in f.message
    # the full chain rides the finding: entry -> sink with paths + lines
    assert [hop[2] for hop in f.chain] == [
        "syncpkg.program.step", "syncpkg.helpers.postprocess_mean"]
    assert f.chain[0][0] == "syncpkg/program.py" and f.chain[0][1] > 0
    assert "chain:" in f.render()


def test_r9_cli_acceptance_chain_in_text_and_json(tmp_path):
    """The ISSUE acceptance command: --select R9 on the seeded fixture."""
    pkg = _copy_fixture(tmp_path, "syncpkg")
    proc = subprocess.run(
        [sys.executable, "-m", "chiaswarm_tpu.analysis", "--select", "R9",
         "--no-cache", str(pkg)],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "chain: syncpkg.program.step" in proc.stdout
    proc = subprocess.run(
        [sys.executable, "-m", "chiaswarm_tpu.analysis", "--select", "R9",
         "--no-cache", "--json", str(pkg)],
        capture_output=True, text=True, timeout=300)
    doc = json.loads(proc.stdout)
    assert len(doc) == 1 and len(doc[0]["chain"]) == 2
    assert doc[0]["chain"][0][2] == "syncpkg.program.step"


def test_r9_leaves_intra_module_chains_to_r1():
    src = """
        import jax

        def helper(x):
            return x.mean().item()

        @jax.jit
        def step(x):
            return helper(x)
        """
    assert lint(src, rule="R9") == []      # same module: R1's jurisdiction
    assert len(lint(src, rule="R1")) == 1  # and R1 does flag it


def test_r9_traced_wrapper_registration_roots_cross_module(tmp_path):
    """scan/vmap bodies and functions PASSED to jit (not decorated) are
    entry points too."""
    idx = _index_of(
        ("pkg/__init__.py", ""),
        ("pkg/a.py", """
            import jax
            from pkg.b import body

            def run(xs):
                return jax.lax.scan(body, xs, None, length=2)
            """),
        ("pkg/b.py", """
            def body(c, _):
                return c.sum().item(), None
            """),
    )
    fs = list(_get_rule("R9").check_project(idx))
    assert len(fs) == 1 and fs[0].path == "pkg/b.py"


def test_r10_drift_fixture_flags_all_three_classes(tmp_path):
    pkg = _copy_fixture(tmp_path, "driftpkg")
    r = run([str(pkg)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), select=["R10"])
    assert r.exit_code == 1
    msgs = sorted(f.message for f in r.new)
    assert len(msgs) == 3
    assert any("'batch'" in m and "no mesh" in m for m in msgs)
    assert any("in_specs arity 2" in m and "takes 3" in m for m in msgs)
    assert any("no caller binds" in m for m in msgs)
    # the clean consumers stay silent
    assert all(f.symbol not in ("clean_spec", "ring") for f in r.new)
    arity = next(f for f in r.new if "in_specs" in f.message)
    assert [hop[2] for hop in arity.chain] == [
        "driftpkg.specs.wrong_arity", "driftpkg.kernels.ring"]


def test_r10_is_silent_without_any_mesh():
    # nothing to drift from: a lone P("anything") defines no universe
    assert lint("""
        from jax.sharding import PartitionSpec as P

        def f():
            return P("anything", None)
        """, rule="R10") == []


def test_r10_consistent_axes_and_bound_params_stay_silent():
    idx = _index_of(
        ("pkg/__init__.py", ""),
        ("pkg/mesh.py", 'SEQ_AXIS = "seq"\n'),
        ("pkg/kern.py", """
            import jax

            def ring(q, k, v, *, axis_name):
                return jax.lax.ppermute(q, axis_name, [(0, 1)])
            """),
        ("pkg/use.py", """
            from functools import partial

            from jax.sharding import Mesh, PartitionSpec as P

            from pkg.kern import ring
            from pkg.mesh import SEQ_AXIS

            def build(devs, q, k, v):
                mesh = Mesh(devs, (SEQ_AXIS,))
                from chiaswarm_tpu.core.compat import shard_map
                spec = P(None, SEQ_AXIS, None, None)
                fn = shard_map(partial(ring, axis_name=SEQ_AXIS),
                               mesh=mesh, in_specs=(spec, spec, spec),
                               out_specs=spec)
                return fn(q, k, v)
            """),
    )
    assert list(_get_rule("R10").check_project(idx)) == []


def test_r10_flags_caller_binding_an_unknown_axis():
    idx = _index_of(
        ("pkg/__init__.py", ""),
        ("pkg/mesh.py", 'DATA_AXIS = "data"\n'),
        ("pkg/kern.py", """
            import jax

            def allreduce(x, *, axis_name):
                return jax.lax.psum(x, axis_name)
            """),
        ("pkg/use.py", """
            from pkg.kern import allreduce

            def agg(x):
                return allreduce(x, axis_name="rows")
            """),
    )
    fs = list(_get_rule("R10").check_project(idx))
    assert len(fs) == 1
    assert "'rows'" in fs[0].message and fs[0].path == "pkg/use.py"
    assert [hop[2] for hop in fs[0].chain] == [
        "pkg.use.agg", "pkg.kern.allreduce"]


# ------------------------------------------------- project index units


def test_project_symbol_resolution_follows_reexport_chains():
    idx = _index_of(
        ("pkg/__init__.py", "from pkg.shim import fn2\n"),
        ("pkg/impl.py", """
            AXIS = "data"

            def fn(x):
                return x
            """),
        ("pkg/shim.py", "from pkg.impl import fn as fn2, AXIS\n"),
    )
    assert idx.resolve_qual("pkg.shim.fn2") == ("func", ("pkg.impl", "fn"))
    assert idx.resolve_qual("pkg.fn2") == ("func", ("pkg.impl", "fn"))
    assert idx.resolve_qual("pkg.shim.AXIS") == ("const", "data")
    assert idx.resolve_axis({"ref": "pkg.shim.AXIS"}, "pkg.impl") == "data"
    assert idx.resolve_qual("pkg.impl.missing") is None
    assert idx.resolve_qual("nowhere.at.all") is None


def test_project_call_graph_edges_and_jit_roots():
    idx = _index_of(
        ("pkg/__init__.py", ""),
        ("pkg/a.py", """
            import jax
            from functools import partial

            from pkg import b
            from pkg.b import helper

            @jax.jit
            def root(x):
                return helper(x)

            def other(x):
                return b.helper(x) + partial(b.sibling, 1)(x)

            class C:
                def m(self):
                    return self.n()

                def n(self):
                    return 1
            """),
        ("pkg/b.py", """
            def helper(x):
                return x

            def sibling(k, x):
                return x
            """),
    )
    edges = idx.edges()
    assert ("pkg.b", "helper") in edges[("pkg.a", "root")]
    assert ("pkg.b", "helper") in edges[("pkg.a", "other")]
    assert ("pkg.b", "sibling") in edges[("pkg.a", "other")]
    assert ("pkg.a", "C.n") in edges[("pkg.a", "C.m")]
    assert set(idx.jit_entry_points()) == {("pkg.a", "root")}
    # relative imports resolve against the package
    idx2 = _index_of(
        ("pkg/__init__.py", ""),
        ("pkg/a.py", """
            from .b import helper

            def f(x):
                return helper(x)
            """),
        ("pkg/b.py", "def helper(x):\n    return x\n"),
    )
    assert ("pkg.b", "helper") in idx2.edges()[("pkg.a", "f")]


def test_project_import_graph_reverse_closure():
    idx = _index_of(
        ("pkg/__init__.py", ""),
        ("pkg/base.py", "X = 1\n"),
        ("pkg/mid.py", "from pkg.base import X\n"),
        ("pkg/top.py", "import pkg.mid\n"),
        ("pkg/island.py", "Y = 2\n"),
    )
    assert idx.reverse_closure({"pkg/base.py"}) == {
        "pkg/base.py", "pkg/mid.py", "pkg/top.py"}
    assert idx.reverse_closure({"pkg/top.py"}) == {"pkg/top.py"}
    assert idx.reverse_closure({"pkg/island.py"}) == {"pkg/island.py"}
    assert idx.module_deps("pkg/mid.py") == {"pkg/base.py"}


def test_project_cache_hits_and_invalidates_on_edit(tmp_path):
    a = _write(tmp_path, "pkg/a.py", "def f(x):\n    return x\n")
    b = _write(tmp_path, "pkg/b.py", "def g(x):\n    return x\n")
    cache = tmp_path / "cache.json"
    files = [(str(a), "pkg/a.py"), (str(b), "pkg/b.py")]
    ProjectIndex.build(files, cache_path=str(cache))
    assert cache.exists()

    # plant a marker in the cached summary of a.py: a cache HIT must
    # surface the marker, a content edit must rebuild and drop it
    doc = json.loads(cache.read_text())
    doc["files"]["pkg/a.py"]["summary"]["marker"] = True
    cache.write_text(json.dumps(doc))
    idx = ProjectIndex.build(files, cache_path=str(cache))
    assert idx.summaries["pkg/a.py"].get("marker") is True

    a.write_text("def f(x):\n    return x + 1\n")
    idx = ProjectIndex.build(files, cache_path=str(cache))
    assert "marker" not in idx.summaries["pkg/a.py"]
    # and the refreshed summary was persisted back
    doc = json.loads(cache.read_text())
    assert "marker" not in doc["files"]["pkg/a.py"]["summary"]

    # a corrupt cache is ignored, not fatal
    cache.write_text("{nope")
    idx = ProjectIndex.build(files, cache_path=str(cache))
    assert set(idx.summaries) == {"pkg/a.py", "pkg/b.py"}


def test_chain_keyed_baseline_survives_reroutes_and_goes_stale(tmp_path):
    """Baseline lifecycle for chain-carrying findings: the key excludes
    the chain, so rerouting an intermediate hop keeps the entry live;
    fixing the sink makes it stale."""
    pkg = _copy_fixture(tmp_path, "syncpkg")
    bl = tmp_path / "baseline.json"
    r = run([str(pkg)], baseline_path=str(bl), root=str(tmp_path))
    assert r.exit_code == 1 and [f.rule for f in r.new] == [
        "host-sync-reachability"]

    r = run([str(pkg)], baseline_path=str(bl), root=str(tmp_path),
            write_baseline=True)
    assert r.exit_code == 0
    doc = json.loads(bl.read_text())
    assert len(doc["findings"]) == 1
    assert set(doc["findings"][0]) == {  # identity only, no hops
        "rule", "path", "symbol", "message", "count"}

    # reroute: the jitted entry now reaches the sink through a NEW
    # intermediate function (different chain, same finding identity)
    (pkg / "program.py").write_text(textwrap.dedent("""
        import jax

        from syncpkg.helpers import postprocess_mean


        def indirection(x):
            return postprocess_mean(x)


        @jax.jit
        def step(x):
            return indirection(x) + 1.0
        """))
    r = run([str(pkg)], baseline_path=str(bl), root=str(tmp_path),
            strict=True)
    assert r.exit_code == 0 and len(r.suppressed) == 1 and not r.stale

    # fix the sink: entry goes stale, strict fails until deleted
    (pkg / "helpers.py").write_text(
        "def postprocess_mean(x):\n    return x.mean()\n")
    r = run([str(pkg)], baseline_path=str(bl), root=str(tmp_path),
            strict=True)
    assert r.exit_code == 1 and r.stale


def test_changed_only_lints_reverse_dependency_closure(tmp_path):
    """--changed-only: edited file + everything importing it, nothing
    else (the pre-existing finding in the untouched island must not
    resurface, and staleness scope stays narrow)."""
    def git(*args):
        subprocess.run(["git", "-C", str(tmp_path), *args], check=True,
                       capture_output=True)

    _write(tmp_path, "pkg/__init__.py", "")
    base = _write(tmp_path, "pkg/base.py", "def f():\n    return 1\n")
    _write(tmp_path, "pkg/top.py", "from pkg.base import f\n")
    _write(tmp_path, "pkg/island.py", BAD)  # pre-existing R4 finding
    git("init", "-q")
    git("-c", "user.email=t@t", "-c", "user.name=t", "commit", "-q",
        "--allow-empty", "-m", "x")
    git("add", ".")
    git("-c", "user.email=t@t", "-c", "user.name=t", "commit", "-q",
        "-m", "seed")
    git("update-ref", "refs/remotes/origin/main", "HEAD")

    # introduce a finding in base.py (working tree, uncommitted)
    base.write_text(BAD)
    r = run([str(tmp_path)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), changed_only=True)
    assert r.exit_code == 1
    assert [f.path for f in r.new] == ["pkg/base.py"]  # island NOT linted
    assert r.checked_files == 2 and r.total_files == 4  # base + top
    assert "changed-only" in r.report

    # a full run still sees both findings
    r = run([str(tmp_path)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path))
    assert sorted(f.path for f in r.new) == ["pkg/base.py",
                                             "pkg/island.py"]

    # --write-baseline from a partial run is refused
    r = run([str(tmp_path)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), changed_only=True, write_baseline=True)
    assert r.exit_code == 2 and "refusing" in r.report


def test_changed_only_without_git_is_bad_input(tmp_path):
    _write(tmp_path, "pkg/mod.py", "x = 1\n")
    r = run([str(tmp_path)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), changed_only=True)
    assert r.exit_code == 2 and "git" in r.report


def test_sarif_output_carries_chains_and_fingerprints(tmp_path):
    pkg = _copy_fixture(tmp_path, "syncpkg")
    out = tmp_path / "findings.sarif"
    proc = subprocess.run(
        [sys.executable, "-m", "chiaswarm_tpu.analysis", "--no-cache",
         "--sarif", str(out), str(pkg)],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    doc = json.loads(out.read_text())
    assert doc["version"] == "2.1.0"
    driver = doc["runs"][0]["tool"]["driver"]
    assert driver["name"] == "swarmlint"
    assert any(r["id"] == "host-sync-reachability"
               for r in driver["rules"])
    results = doc["runs"][0]["results"]
    assert len(results) == 1
    res = results[0]
    assert res["ruleId"] == "host-sync-reachability"
    assert res["partialFingerprints"]["swarmlintBaselineKey/v1"].startswith(
        "host-sync-reachability::")
    flow = res["codeFlows"][0]["threadFlows"][0]["locations"]
    assert len(flow) == 2
    assert flow[0]["location"]["message"]["text"] == "syncpkg.program.step"
    # columns/lines are 1-based per the SARIF spec
    assert res["locations"][0]["physicalLocation"]["region"][
        "startColumn"] >= 1


def test_r10_inline_lambda_callee_arity():
    idx = _index_of(
        ("pkg/__init__.py", ""),
        ("pkg/mesh.py", 'DATA_AXIS = "data"\n'),
        ("pkg/use.py", """
            from jax.sharding import PartitionSpec as P

            from pkg.mesh import DATA_AXIS

            def f(mesh, q, k):
                from chiaswarm_tpu.core.compat import shard_map
                spec = P(DATA_AXIS)
                fn = shard_map(lambda q, k, v: q, mesh=mesh,
                               in_specs=(spec, spec), out_specs=spec)
                return fn(q, k)

            def ok(mesh, q, k):
                from chiaswarm_tpu.core.compat import shard_map
                spec = P(DATA_AXIS)
                fn = shard_map(lambda a, b: a, mesh=mesh,
                               in_specs=(spec, spec), out_specs=spec)
                return fn(q, k)
            """),
    )
    fs = list(_get_rule("R10").check_project(idx))
    assert len(fs) == 1
    assert "lambda takes 3" in fs[0].message and fs[0].symbol == "f"


def test_r9_registration_site_is_a_chain_hop(tmp_path):
    """A traced body whose sync chain stays in ONE module but whose
    registration lives in ANOTHER must chain the registration site —
    that is the only cross-module evidence, and --changed-only's chain
    filter depends on it."""
    idx = _index_of(
        ("pkg/__init__.py", ""),
        ("pkg/a.py", """
            import jax
            from pkg.b import body

            def run(xs):
                return jax.lax.scan(body, xs, None, length=2)
            """),
        ("pkg/b.py", """
            def body(c, _):
                return c.sum().item(), None
            """),
    )
    fs = list(_get_rule("R9").check_project(idx))
    assert len(fs) == 1
    assert [hop[2] for hop in fs[0].chain] == ["pkg.a.run", "pkg.b.body"]
    assert fs[0].chain[0][0] == "pkg/a.py"


def test_changed_only_keeps_findings_rooted_in_the_changed_file(tmp_path):
    """Code-review regression: editing ONLY the registering file (the
    sink module is its dependency, outside the reverse closure) must
    still surface the R9 finding — via the chain's registration hop."""
    def git(*args):
        subprocess.run(["git", "-C", str(tmp_path), *args], check=True,
                       capture_output=True)

    _write(tmp_path, "pkg/__init__.py", "")
    a = _write(tmp_path, "pkg/a.py", "from pkg.b import body\n")
    _write(tmp_path, "pkg/b.py",
           "def body(c, _):\n    return c.sum().item(), None\n")
    git("init", "-q")
    git("add", ".")
    git("-c", "user.email=t@t", "-c", "user.name=t", "commit", "-q",
        "-m", "seed")
    git("update-ref", "refs/remotes/origin/main", "HEAD")

    a.write_text("import jax\nfrom pkg.b import body\n\n\n"
                 "def run(xs):\n"
                 "    return jax.lax.scan(body, xs, None, length=2)\n")
    r = run([str(tmp_path)], baseline_path=str(tmp_path / "bl.json"),
            root=str(tmp_path), changed_only=True)
    assert r.checked_files == 1  # only a.py re-linted per-file...
    assert [f.rule for f in r.new] == ["host-sync-reachability"]
    assert r.new[0].path == "pkg/b.py"  # ...but the chained finding lands
    assert r.new[0].chain[0][0] == "pkg/a.py"
    # and the fast path agrees with the full run
    full = run([str(tmp_path)], baseline_path=str(tmp_path / "bl.json"),
               root=str(tmp_path))
    assert [f.baseline_key for f in full.new] == [
        f.baseline_key for f in r.new]


def test_changed_only_fails_loudly_on_unparseable_changed_file(tmp_path):
    """Code-review regression: a syntax error in the CHANGED file must
    exit 2 from the fast path too — the import graph cannot see the file,
    but the raw changed set still reaches the per-file pass."""
    def git(*args):
        subprocess.run(["git", "-C", str(tmp_path), *args], check=True,
                       capture_output=True)

    a = _write(tmp_path, "pkg/a.py", "x = 1\n")
    _write(tmp_path, "pkg/b.py", "y = 2\n")
    git("init", "-q")
    git("add", ".")
    git("-c", "user.email=t@t", "-c", "user.name=t", "commit", "-q",
        "-m", "seed")
    git("update-ref", "refs/remotes/origin/main", "HEAD")

    a.write_text("def broken(:\n")
    r = run([str(tmp_path)], baseline_path=str(tmp_path / "bl.json"),
            root=str(tmp_path), changed_only=True)
    assert r.exit_code == 2 and any("pkg/a.py" in e for e in r.errors)


def test_subset_index_build_merges_into_cache_instead_of_evicting(tmp_path):
    """Code-review regression: building the index over a path subset
    must not truncate the whole-repo cache; deleted files DO get pruned
    at the next dirty write."""
    a = _write(tmp_path, "pkg/a.py", "x = 1\n")
    b = _write(tmp_path, "pkg/b.py", "y = 2\n")
    cache = tmp_path / "cache.json"
    both = [(str(a), "pkg/a.py"), (str(b), "pkg/b.py")]
    ProjectIndex.build(both, cache_path=str(cache))
    assert set(json.loads(cache.read_text())["files"]) == {
        "pkg/a.py", "pkg/b.py"}

    # subset run over a.py only (with an edit, so the cache is written):
    # b.py's warm entry survives
    a.write_text("x = 3\n")
    ProjectIndex.build([(str(a), "pkg/a.py")], cache_path=str(cache))
    assert set(json.loads(cache.read_text())["files"]) == {
        "pkg/a.py", "pkg/b.py"}

    # a fully-warm run does not rewrite the file at all
    before = cache.read_text()
    ProjectIndex.build(both, cache_path=str(cache))
    assert cache.read_text() == before

    # a deleted file's entry is pruned on the next dirty write
    b.unlink()
    a.write_text("x = 4\n")
    ProjectIndex.build([(str(a), "pkg/a.py")], cache_path=str(cache))
    assert set(json.loads(cache.read_text())["files"]) == {"pkg/a.py"}


# ----------------------------------- R9 dispatch tables + allow marker


def test_r9_reaches_through_module_level_dispatch_table():
    """ISSUE 11 satellite (the ROADMAP lint-extension candidate): a
    ``TABLE[key](...)`` call was an unresolvable edge — the call graph
    now conservatively reaches every table member, so a sync sink behind
    a workload dispatch dict is no longer invisible."""
    idx = _index_of(
        ("pkg/__init__.py", ""),
        ("pkg/a.py", """
            import jax
            from pkg.b import sink

            TABLE = {"img": sink}

            def dispatch(kind, x):
                return TABLE[kind](x)

            @jax.jit
            def step(x):
                return dispatch("img", x)
            """),
        ("pkg/b.py", """
            def sink(x):
                return x.mean().item()
            """),
    )
    rule = _get_rule("R9")
    findings = list(rule.check_project(idx))
    assert len(findings) == 1
    f = findings[0]
    assert f.path == "pkg/b.py"
    assert [hop[2] for hop in f.chain] == [
        "pkg.a.step", "pkg.a.dispatch", "pkg.b.sink"]


def test_r9_reaches_through_cross_module_table_reference():
    """The table may live in ANOTHER module than the caller —
    ``jobs.TABLE[k](...)`` resolves through the import alias to the
    owning module's table, whose values resolved in ITS namespace."""
    idx = _index_of(
        ("pkg/__init__.py", ""),
        ("pkg/jobs.py", """
            from pkg.sinks import drain

            CALLBACKS = {"audio": drain}
            """),
        ("pkg/exec.py", """
            import jax
            from pkg import jobs

            @jax.jit
            def step(x):
                return jobs.CALLBACKS["audio"](x)
            """),
        ("pkg/sinks.py", """
            def drain(x):
                return float(x.sum())
            """),
    )
    rule = _get_rule("R9")
    findings = list(rule.check_project(idx))
    assert len(findings) == 1
    assert findings[0].path == "pkg/sinks.py"
    assert [hop[2] for hop in findings[0].chain] == [
        "pkg.exec.step", "pkg.sinks.drain"]


def test_r9_local_dispatch_dict_expands_inline():
    idx = _index_of(
        ("pkg/__init__.py", ""),
        ("pkg/a.py", """
            import jax
            from pkg.b import sink

            def route(kind, x):
                handlers = {"img": sink}
                return handlers[kind](x)

            @jax.jit
            def step(x):
                return route("img", x)
            """),
        ("pkg/b.py", """
            def sink(x):
                return x.item()
            """),
    )
    findings = list(_get_rule("R9").check_project(idx))
    assert [f.path for f in findings] == ["pkg/b.py"]


def test_r9_table_of_non_callables_is_not_a_dispatch_table():
    """A dict of strings/numbers must NOT create call edges."""
    idx = _index_of(
        ("pkg/__init__.py", ""),
        ("pkg/a.py", """
            import jax
            from pkg.b import sink

            SIZES = {"img": 3, "vid": 4}

            @jax.jit
            def step(x):
                return x * SIZES["img"]
            """),
        ("pkg/b.py", """
            def sink(x):
                return x.item()
            """),
    )
    assert list(_get_rule("R9").check_project(idx)) == []


def test_allow_marker_sanctions_sync_site_for_r1_and_r9():
    """swarmlens taps (ISSUE 11): the ``swarmlens: allow-host-sync``
    marker — on the sync line or the comment line directly above —
    silences the shared sync_sites vocabulary, so sanctioned io_callback
    receiver bodies never become baseline noise. Both rules honor it
    (they share the extractor) and unmarked sites still flag."""
    marked_same_line = """
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            y = np.asarray(x)  # swarmlens: allow-host-sync
            return y
        """
    assert lint(marked_same_line, rule="R1") == []

    marked_above = """
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            # swarmlens: allow-host-sync
            y = np.asarray(x)
            return y
        """
    assert lint(marked_above, rule="R1") == []

    unmarked = """
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            y = np.asarray(x)
            return y
        """
    assert len(lint(unmarked, rule="R1")) == 1

    # R9 shares the extractor: a marked sink across modules stays silent
    def cross(sink_body: str):
        return _index_of(
            ("pkg/__init__.py", ""),
            ("pkg/a.py", """
                import jax
                from pkg.b import sink

                @jax.jit
                def step(x):
                    return sink(x)
                """),
            ("pkg/b.py", sink_body),
        )

    marked = cross("""
        def sink(x):
            return x.mean().item()  # swarmlens: allow-host-sync
        """)
    assert list(_get_rule("R9").check_project(marked)) == []
    unmarked_idx = cross("""
        def sink(x):
            return x.mean().item()
        """)
    assert len(list(_get_rule("R9").check_project(unmarked_idx))) == 1


# --------------------------------- swarmproof (R11/R12/R13, ISSUE 15)

from chiaswarm_tpu.analysis.shardflow import VMA

SHARDFLOW_FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures",
                                  "shardflow")


def _copy_shardflow(tmp_path, name):
    dst = tmp_path / name
    shutil.copytree(os.path.join(SHARDFLOW_FIXTURES, name), dst)
    return dst


def test_vma_lattice_combine_join_and_collective_transfer():
    """The abstract domain's algebra: combine (dataflow meet) is
    infectious on both sides, join (control merge) keeps only the
    definite intersection, collectives remove/introduce axes on both."""
    a = VMA(frozenset({"data", "seq"}), frozenset({"data"}))
    b = VMA(frozenset({"seq"}), frozenset({"seq"}))

    c = VMA.combine(a, b)
    assert c.may == {"data", "seq"} and c.must == {"data", "seq"}

    j = VMA.join(a, b)
    assert j.may == {"data", "seq"} and j.must == set()

    r = c.remove("seq")  # psum/all_gather over seq
    assert r.may == {"data"} and r.must == {"data"}

    i = VMA.empty().introduce("seq")  # axis_index("seq")
    assert i.may == i.must == {"seq"}

    top = VMA.top({"data", "seq"})
    assert top.may == {"data", "seq"} and top.must == set()
    assert VMA.combine() == VMA.empty()


def test_r11_flags_distilled_seq_parallel_fixture(tmp_path):
    """THE acceptance fixture: two-axis shard_map, replicated operand,
    complete product all-reduced over seq — R11 fires with the full
    entry→sink chain; the single-axis twin and the pure-seq-mesh twin
    stay green."""
    pkg = _copy_shardflow(tmp_path, "psumpkg")
    r = run([str(pkg)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), select=["R11"])
    assert r.exit_code == 1 and len(r.new) == 1
    f = r.new[0]
    assert f.rule == "replicated-psum"
    assert f.path == "psumpkg/kernels.py" and f.symbol == "kv_projection"
    assert "'seq'" in f.message and "axis size" in f.message
    # entry (the shard_map site) → kernel → the psum line itself
    assert [hop[2] for hop in f.chain] == [
        "psumpkg.program.bad_two_axis", "psumpkg.kernels.kv_projection",
        "psumpkg.kernels.kv_projection"]
    assert f.chain[0][0] == "psumpkg/program.py" and f.chain[0][1] > 0
    assert f.chain[-1] == ("psumpkg/kernels.py", f.line,
                          "psumpkg.kernels.kv_projection")
    assert "chain:" in f.render()


def test_r11_cli_acceptance_chain_in_text_json_and_sarif(tmp_path):
    """The ISSUE acceptance clause: the R11 chain renders in all three
    output formats (text, --json, --sarif codeFlows)."""
    pkg = _copy_shardflow(tmp_path, "psumpkg")
    base = [sys.executable, "-m", "chiaswarm_tpu.analysis", "--select",
            "R11", "--no-cache"]
    proc = subprocess.run(base + [str(pkg)], capture_output=True,
                          text=True, timeout=300)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "replicated-psum" in proc.stdout
    assert "chain: psumpkg.program.bad_two_axis" in proc.stdout

    proc = subprocess.run(base + ["--json", str(pkg)],
                          capture_output=True, text=True, timeout=300)
    doc = json.loads(proc.stdout)
    assert len(doc) == 1 and len(doc[0]["chain"]) == 3
    assert doc[0]["chain"][0][2] == "psumpkg.program.bad_two_axis"

    sarif = tmp_path / "out.sarif"
    proc = subprocess.run(base + ["--sarif", str(sarif), str(pkg)],
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 1
    res = json.loads(sarif.read_text())["runs"][0]["results"]
    assert len(res) == 1 and res[0]["ruleId"] == "replicated-psum"
    flow = res[0]["codeFlows"][0]["threadFlows"][0]["locations"]
    assert [h["location"]["message"]["text"] for h in flow] == [
        "psumpkg.program.bad_two_axis", "psumpkg.kernels.kv_projection",
        "psumpkg.kernels.kv_projection"]


def test_r12_flags_partial_sum_escape_clean_twin_silent(tmp_path):
    pkg = _copy_shardflow(tmp_path, "leakpkg")
    r = run([str(pkg)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), select=["R12"])
    assert r.exit_code == 1 and len(r.new) == 1
    f = r.new[0]
    assert f.rule == "unreduced-out-spec" and f.symbol == "bad_escape"
    assert "out_specs claims replication" in f.message
    # chain: the shard_map site, then the callee whose return leaks
    assert [hop[2] for hop in f.chain] == [
        "leakpkg.program.bad_escape", "leakpkg.program.partial_logits"]


def test_r13_cross_module_donation_drift(tmp_path):
    pkg = _copy_shardflow(tmp_path, "donpkg")
    r = run([str(pkg)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), select=["R13"])
    assert r.exit_code == 1 and len(r.new) == 1
    f = r.new[0]
    assert f.rule == "donation-drift"
    assert f.path == "donpkg/caller.py"
    assert f.symbol == "bad_read_after_donate"
    assert "'latents'" in f.message and "donpkg/wrappers.py" in f.message
    # chain: wrapper definition → donating call → the read-after-donate
    assert [hop[0] for hop in f.chain] == [
        "donpkg/wrappers.py", "donpkg/caller.py", "donpkg/caller.py"]
    assert f.chain[1][1] < f.chain[2][1]


def test_r10_two_mesh_instances_do_not_pool_axes(tmp_path):
    """The retired R10 imprecision (ISSUE 15 satellite): a seq-only mesh
    in one module must not sanction 'seq' specs on a data-only Mesh
    literal's shard_map in another — and the chain names the instance."""
    pkg = _copy_shardflow(tmp_path, "twomesh")
    r = run([str(pkg)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), select=["R10"])
    assert r.exit_code == 1 and len(r.new) == 1
    f = r.new[0]
    assert f.symbol == "shard_over_wrong_axis"
    assert "'seq'" in f.message and "binds only [data]" in f.message
    # chain hop 2 is the mesh instance definition
    assert f.chain[1][0] == "twomesh/dataside.py"
    assert "DATA_MESH" in f.chain[1][2]
    # the legitimate seq-mesh user and the bound-axis twin stay green
    assert all(x.symbol not in ("shard_over_seq", "shard_over_bound_axis")
               for x in r.new)


def test_r11_through_scan_body_closure():
    """The real trigger shape: the psum sits in a scan body closing over
    the shard_map callee's parameters (parallel/ring_attention.py's
    structure) — interpretation must descend through lax.scan into the
    closure with the caller's bindings visible."""
    idx = _index_of(
        ("pkg/__init__.py", ""),
        ("pkg/ring.py", """
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P
            from chiaswarm_tpu.core.compat import shard_map

            MESH = Mesh(np.array(jax.devices()).reshape(2, 4),
                        ("data", "seq"))

            def kernel(q, w, *, axis_name):
                def hop(carry, _):
                    kv = q @ w
                    return carry + jax.lax.psum(kv, axis_name), None
                out, _ = jax.lax.scan(hop, q * 0.0, None, length=4)
                return out

            def enter(q, w):
                from functools import partial
                fn = shard_map(partial(kernel, axis_name="seq"),
                               mesh=MESH,
                               in_specs=(P("data", None), P()),
                               out_specs=P("data", None))
                return fn(q, w)
            """),
    )
    fs = list(_get_rule("R11").check_project(idx))
    assert len(fs) == 1
    assert fs[0].symbol.endswith("hop")
    quals = [hop[2] for hop in fs[0].chain]
    assert quals[0] == "pkg.ring.enter"
    assert "pkg.ring.kernel" in quals


def test_r11_conditional_spec_contributes_may_only():
    """P(DATA if cond else None, SEQ): the value MAY vary over data, so
    a psum over data must stay silent (one-sided soundness) — while the
    psum over the definitely-replicated axis still fires."""
    idx = _index_of(
        ("pkg/__init__.py", ""),
        ("pkg/m.py", """
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P
            from chiaswarm_tpu.core.compat import shard_map

            MESH = Mesh(np.array(jax.devices()).reshape(2, 2, 2),
                        ("data", "seq", "model"))

            def k(x, b):
                return jax.lax.psum(x, "data")

            def enter(x, b, flag):
                fn = shard_map(
                    k, mesh=MESH,
                    in_specs=(P("data" if flag else None, "seq"), P()),
                    out_specs=P(None, "seq"))
                return fn(x, b)
            """),
    )
    assert list(_get_rule("R11").check_project(idx)) == []

    idx2 = _index_of(
        ("pkg/__init__.py", ""),
        ("pkg/m.py", """
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P
            from chiaswarm_tpu.core.compat import shard_map

            MESH = Mesh(np.array(jax.devices()).reshape(2, 2, 2),
                        ("data", "seq", "model"))

            def k(x, b):
                return jax.lax.psum(x, "model")

            def enter(x, b, flag):
                fn = shard_map(
                    k, mesh=MESH,
                    in_specs=(P("data" if flag else None, "seq"), P()),
                    out_specs=P(None, "seq"))
                return fn(x, b)
            """),
    )
    fs = list(_get_rule("R11").check_project(idx2))
    assert len(fs) == 1 and "'model'" in fs[0].message


def test_r12_all_gather_clears_the_varying_axis():
    """all_gather (like psum) makes the value invariant over the axis:
    an out_specs replication claim after it is honest."""
    idx = _index_of(
        ("pkg/__init__.py", ""),
        ("pkg/m.py", """
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P
            from chiaswarm_tpu.core.compat import shard_map

            MESH = Mesh(np.array(jax.devices()[:4]), ("seq",))

            def gathered(x):
                return jax.lax.all_gather(x, "seq")

            def enter(x):
                fn = shard_map(gathered, mesh=MESH,
                               in_specs=(P("seq"),), out_specs=P())
                return fn(x)
            """),
    )
    assert list(_get_rule("R12").check_project(idx)) == []


def test_r11_axis_index_introduces_varying():
    """axis_index(a) VARIES over a by construction — summing it over a
    is legitimate and must stay silent."""
    idx = _index_of(
        ("pkg/__init__.py", ""),
        ("pkg/m.py", """
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P
            from chiaswarm_tpu.core.compat import shard_map

            MESH = Mesh(np.array(jax.devices()[:4]), ("seq",))

            def k(x):
                shard = jax.lax.axis_index("seq")
                return jax.lax.psum(shard, "seq") + x

            def enter(x):
                fn = shard_map(k, mesh=MESH, in_specs=(P("seq"),),
                               out_specs=P("seq"))
                return fn(x)
            """),
    )
    assert list(_get_rule("R11").check_project(idx)) == []


def test_shardflow_baseline_lifecycle(tmp_path):
    """R11 findings ride the standard shrink-only baseline: finding →
    grandfathered → fixed → stale entry fails --strict. (The baseline is
    written by a full-rule run — --write-baseline refuses --select — so
    the fixture's module-scope jax.devices() R4 findings ride along and
    stay VALID across the R11 fix, proving staleness is per-entry.)"""
    pkg = _copy_shardflow(tmp_path, "psumpkg")
    bl = tmp_path / "baseline.json"
    r = run([str(pkg)], baseline_path=str(bl), root=str(tmp_path),
            select=["R11"])
    assert r.exit_code == 1 and len(r.new) == 1

    r = run([str(pkg)], baseline_path=str(bl), root=str(tmp_path),
            write_baseline=True)
    assert r.exit_code == 0
    doc = json.loads(bl.read_text())
    entries = [e for e in doc["findings"]
               if e["rule"] == "replicated-psum"]
    assert len(entries) == 1
    assert set(entries[0]) == {"rule", "path", "symbol", "message",
                               "count"}  # identity only, no chain hops

    r = run([str(pkg)], baseline_path=str(bl), root=str(tmp_path),
            select=["R11"], strict=True)
    assert r.exit_code == 0 and len(r.suppressed) == 1

    # fix: shard the operand over seq — the psum becomes a reduction
    prog = pkg / "program.py"
    fixed = prog.read_text().replace('in_specs=(P("data", None), P()),',
                                     'in_specs=(P("data", "seq"), P()),')
    assert fixed != prog.read_text()
    prog.write_text(fixed)
    r = run([str(pkg)], baseline_path=str(bl), root=str(tmp_path),
            select=["R11"], strict=True)
    assert r.exit_code == 1 and not r.new
    assert len(r.stale) == 1 and "replicated-psum" in r.stale[0]


def test_changed_only_mesh_definitions_expand_to_sharding_consumers(
        tmp_path):
    """ISSUE 15 small fix: editing a module that DEFINES mesh vocabulary
    must re-lint every sharding consumer even without an import edge
    (parallel/ring_attention.py reads its axis through a parameter and
    never imports core/mesh.py) — while non-sharding islands stay out of
    the fast path."""
    def git(*args):
        subprocess.run(["git", "-C", str(tmp_path), *args], check=True,
                       capture_output=True)

    _write(tmp_path, "pkg/__init__.py", "")
    meshdef = _write(tmp_path, "pkg/meshdef.py", textwrap.dedent("""
        import jax
        import numpy as np
        from jax.sharding import Mesh

        MESH = Mesh(np.array(jax.devices()[:2]), ("data",))
        """))
    _write(tmp_path, "pkg/ring.py", textwrap.dedent("""
        import jax

        def rotate(x, *, axis_name):
            return jax.lax.ppermute(x, axis_name, [(0, 1)])
        """))
    _write(tmp_path, "pkg/island.py", "z = 1\n")
    git("init", "-q")
    git("add", ".")
    git("-c", "user.email=t@t", "-c", "user.name=t", "commit", "-q",
        "-m", "seed")
    git("update-ref", "refs/remotes/origin/main", "HEAD")

    # edit ONLY the mesh-defining module
    meshdef.write_text(meshdef.read_text().replace(
        '("data",)', '("data", "seq")').replace("[:2]", "[:4]"))
    r = run([str(tmp_path)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), changed_only=True, select=["R10"])
    assert r.exit_code == 0, r.report
    # meshdef + the collective-bearing consumer; the island is skipped
    assert r.checked_files == 2 and r.total_files == 4

    # a non-mesh edit keeps the narrow closure
    _write(tmp_path, "pkg/island.py", "z = 2\n")
    git("add", ".")
    git("-c", "user.email=t@t", "-c", "user.name=t", "commit", "-q",
        "-m", "mesh")
    git("update-ref", "refs/remotes/origin/main", "HEAD")
    (tmp_path / "pkg/island.py").write_text("z = 3\n")
    r = run([str(tmp_path)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), changed_only=True, select=["R10"])
    assert r.checked_files == 1


# ------------------- swarmproof review-hardening regressions (5 fixes)


def _two_axis_header():
    return textwrap.dedent("""
        import jax
        import numpy as np
        from jax.sharding import Mesh, PartitionSpec as P
        from chiaswarm_tpu.core.compat import shard_map

        MESH = Mesh(np.array(jax.devices()).reshape(2, 4),
                    ("data", "seq"))
        """)


def test_r11_closure_memo_is_per_site_not_order_dependent():
    """Code-review regression: a scan-body closure's summary must not be
    memoized across shard_map sites — the closure reads the ENCLOSING
    activation's bindings, which differ per site. The clean site
    interpreting FIRST must not swallow the buggy site's finding."""
    def kernel(name):
        return textwrap.dedent(f"""
            def {name}(q, w):
                def hop(carry, _):
                    kv = q @ w
                    return carry + jax.lax.psum(kv, "seq"), None
                out, _ = jax.lax.scan(hop, q * 0.0, None, length=4)
                return out
            """)

    def enter(name, callee, spec):
        return textwrap.dedent(f"""
            def {name}(q, w):
                fn = shard_map({callee}, mesh=MESH,
                               in_specs=({spec}, P()),
                               out_specs=P("data", None))
                return fn(q, w)
            """)

    body = (_two_axis_header()
            + kernel("k_clean") + kernel("k_bad")
            # the CLEAN site (operand varies over seq) interprets first
            + enter("a_clean", "k_clean", 'P("data", "seq")')
            + enter("b_bad", "k_bad", 'P("data", None)'))
    idx = _index_of(("pkg/__init__.py", ""), ("pkg/m.py", body))
    fs = list(_get_rule("R11").check_project(idx))
    assert len(fs) == 1 and fs[0].chain[0][2] == "pkg.m.b_bad"

    # SAME kernel from both sites: the memo must still not leak the
    # clean activation's closure verdict into the bad one
    body2 = (_two_axis_header() + kernel("k")
             + enter("a_clean", "k", 'P("data", "seq")')
             + enter("b_bad", "k", 'P("data", None)'))
    idx2 = _index_of(("pkg/__init__.py", ""), ("pkg/m.py", body2))
    fs2 = list(_get_rule("R11").check_project(idx2))
    assert len(fs2) == 1 and fs2[0].chain[0][2] == "pkg.m.b_bad"


def test_r11_keyword_passed_positional_param_binds():
    """Code-review regression: helper(x=x) passing a varying value by
    keyword to a POSITIONAL parameter must bind it — not default the
    parameter to replicated and flag a sound psum."""
    idx = _index_of(
        ("pkg/__init__.py", ""),
        ("pkg/m.py", _two_axis_header() + textwrap.dedent("""
            def helper(x):
                return jax.lax.psum(x, "seq")

            def k(x, w):
                return helper(x=x)

            def enter(x, w):
                fn = shard_map(k, mesh=MESH,
                               in_specs=(P("data", "seq"), P()),
                               out_specs=P("data", None))
                return fn(x, w)
            """)),
    )
    assert list(_get_rule("R11").check_project(idx)) == []


def test_r11_branch_assignment_joins_instead_of_overwriting():
    """Code-review regression: `if flag: y = x` / `else: y = zeros`
    must JOIN (y MAY vary) — the else arm must not strong-kill the
    varying axis and produce a false-positive R11."""
    idx = _index_of(
        ("pkg/__init__.py", ""),
        ("pkg/m.py", _two_axis_header() + textwrap.dedent("""
            def k(x, w, flag):
                if flag:
                    y = x
                else:
                    y = x * 0.0 + 1.0
                    y = w
                return jax.lax.psum(y, "seq")

            def enter(x, w, flag):
                fn = shard_map(k, mesh=MESH,
                               in_specs=(P("data", "seq"), P(), P()),
                               out_specs=P("data", None))
                return fn(x, w, flag)
            """)),
    )
    assert list(_get_rule("R11").check_project(idx)) == []


def test_r13_mutually_exclusive_arms_do_not_chain():
    """Code-review regression: a donation in the if-arm must not chain
    to a read in the else-arm (they never both execute), while a read
    AFTER the conditional still flags."""
    header = """
        import jax

        step = jax.jit(lambda x: x + 1, donate_argnums=(0,))
        """
    exclusive = header + """
        def caller(buf, fast):
            if fast:
                out = step(buf)
            else:
                out = buf + 1.0
            return out
        """
    idx = _index_of(("pkg/__init__.py", ""), ("pkg/m.py", exclusive))
    assert list(_get_rule("R13").check_project(idx)) == []

    after = header + """
        def caller(buf, fast):
            if fast:
                out = step(buf)
            else:
                out = buf + 1.0
            return out + buf.mean()
        """
    idx2 = _index_of(("pkg/__init__.py", ""), ("pkg/m.py", after))
    fs = list(_get_rule("R13").check_project(idx2))
    assert len(fs) == 1 and fs[0].rule == "donation-drift"


def test_r11_pytree_prefix_spec_covers_every_callee_param():
    """Code-review regression: a single (pytree-prefix) in_specs applies
    to EVERY callee parameter — the 9th argument of a wide kernel must
    not silently bind replicated."""
    idx = _index_of(
        ("pkg/__init__.py", ""),
        ("pkg/m.py", _two_axis_header() + textwrap.dedent("""
            def k(a1, a2, a3, a4, a5, a6, a7, a8, a9):
                return jax.lax.psum(a9, "seq")

            def enter(args):
                fn = shard_map(k, mesh=MESH, in_specs=P("data", "seq"),
                               out_specs=P("data", "seq"))
                return fn(*args)
            """)),
    )
    assert list(_get_rule("R11").check_project(idx)) == []


def test_r12_tuple_axis_psum_reduces_every_named_axis():
    """Second-review regression: psum(x, ("data", "seq")) removes BOTH
    axes from the varying set — out_specs=P() after it is honest, and a
    psum over only ONE of two varying axes still leaks the other."""
    idx = _index_of(
        ("pkg/__init__.py", ""),
        ("pkg/m.py", _two_axis_header() + textwrap.dedent("""
            def k(x):
                return jax.lax.psum(x, ("data", "seq"))

            def enter(x):
                fn = shard_map(k, mesh=MESH,
                               in_specs=(P("data", "seq"),),
                               out_specs=P())
                return fn(x)
            """)),
    )
    assert list(_get_rule("R12").check_project(idx)) == []

    idx2 = _index_of(
        ("pkg/__init__.py", ""),
        ("pkg/m.py", _two_axis_header() + textwrap.dedent("""
            def k(x):
                return jax.lax.psum(x, ("seq",))

            def enter(x):
                fn = shard_map(k, mesh=MESH,
                               in_specs=(P("data", "seq"),),
                               out_specs=P())
                return fn(x)
            """)),
    )
    fs = list(_get_rule("R12").check_project(idx2))
    assert len(fs) == 1 and "'data'" in fs[0].message


def test_r11_keyword_invoked_scan_is_not_replicated():
    """Second-review regression: lax.scan called with keyword operands
    (f=, init=, xs=) must flow the carry's varying axes — not default
    the loop result to 'provably replicated' and flag a sound psum."""
    idx = _index_of(
        ("pkg/__init__.py", ""),
        ("pkg/m.py", _two_axis_header() + textwrap.dedent("""
            def k(x):
                def hop(carry, _):
                    return carry + 1.0, None
                out, _ = jax.lax.scan(f=hop, init=x, xs=None, length=4)
                return jax.lax.psum(out, "seq")

            def enter(x):
                fn = shard_map(k, mesh=MESH,
                               in_specs=(P("data", "seq"),),
                               out_specs=P("data", None))
                return fn(x)
            """)),
    )
    assert list(_get_rule("R11").check_project(idx)) == []


def test_r13_try_handler_reads_the_body_donation():
    """Second-review regression: a try body's donation IS live in its
    except handler (the body ran first) — must flag; sibling handlers
    are exclusive with each other — must not chain; a loop's else runs
    after the body — must flag."""
    header = textwrap.dedent("""
        import jax

        step = jax.jit(lambda x: x + 1, donate_argnums=(0,))
        """)
    handler_read = header + textwrap.dedent("""
        def caller(buf):
            try:
                out = step(buf)
            except Exception:
                return buf.mean()
            return out
        """)
    idx = _index_of(("pkg/__init__.py", ""), ("pkg/m.py", handler_read))
    fs = list(_get_rule("R13").check_project(idx))
    assert len(fs) == 1 and fs[0].rule == "donation-drift"

    sibling_handlers = header + textwrap.dedent("""
        def caller(buf, risky):
            try:
                out = risky(buf)
            except ValueError:
                out = step(buf)
            except TypeError:
                out = buf + 1.0
            return out
        """)
    idx2 = _index_of(("pkg/__init__.py", ""),
                     ("pkg/m.py", sibling_handlers))
    assert list(_get_rule("R13").check_project(idx2)) == []

    loop_else = header + textwrap.dedent("""
        def caller(buf, xs):
            for x in xs:
                out = step(buf)
            else:
                return buf.mean()
            return out
        """)
    idx3 = _index_of(("pkg/__init__.py", ""), ("pkg/m.py", loop_else))
    fs3 = list(_get_rule("R13").check_project(idx3))
    assert len(fs3) == 1


# --------------------------------------------- swarmrace (R14-R17)

RACEFLOW_FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures",
                                 "raceflow")


def _copy_raceflow(tmp_path, name):
    dst = tmp_path / name
    shutil.copytree(os.path.join(RACEFLOW_FIXTURES, name), dst)
    return dst


def test_r14_thread_publishes_inflight_jit_value(tmp_path):
    """PR-3's first container hazard: a worker thread appends a
    jit-produced value to a shared deque the event loop pops — R14 with
    the spawn-site -> publish chain; the block_until_ready twin is
    green."""
    pkg = _copy_raceflow(tmp_path, "handoffpkg")
    r = run([str(pkg)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), select=["R14"])
    assert r.exit_code == 1 and len(r.new) == 1, r.report
    f = r.new[0]
    assert f.rule == "cross-thread-device-handoff"
    assert f.path == "handoffpkg/lane.py"
    assert "'_out'" in f.message and "block_until_ready" in f.message
    # spawn site (the root) -> the thread body -> the publish itself
    assert [hop[2] for hop in f.chain] == [
        "handoffpkg.lane.Lane.__init__", "handoffpkg.lane.Lane._drive",
        "handoffpkg.lane.Lane._drive"]
    assert f.chain[-1] == ("handoffpkg/lane.py", f.line,
                           "handoffpkg.lane.Lane._drive")
    assert "chain:" in f.render()


def test_r14_executor_job_parks_result_in_shared_dict(tmp_path):
    """The second PR-3 hazard: run_in_executor job stores a jit result
    into a request-keyed dict an async poller pops; the .copy() twin is
    green."""
    pkg = _copy_raceflow(tmp_path, "futurepkg")
    r = run([str(pkg)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), select=["R14"])
    assert r.exit_code == 1 and len(r.new) == 1, r.report
    f = r.new[0]
    assert f.path == "futurepkg/pool.py" and "'_results'" in f.message
    assert [hop[2] for hop in f.chain] == [
        "futurepkg.pool.Pool.submit", "futurepkg.pool.Pool._job",
        "futurepkg.pool.Pool._job"]


def test_r15_fired_vs_condemn_mostly_locked(tmp_path):
    """PR-10's fired flag: Condition-guarded on the monitor path,
    written bare on the reset path (R15); the guarded twin is green."""
    pkg = _copy_raceflow(tmp_path, "firedpkg")
    r = run([str(pkg)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), select=["R15"])
    assert r.exit_code == 1 and len(r.new) == 1, r.report
    f = r.new[0]
    assert f.rule == "unguarded-shared-mutation"
    assert f.path == "firedpkg/watch.py"
    assert "'fired'" in f.message
    assert "firedpkg.watch.Watch._monitor" in f.message
    assert [hop[2] for hop in f.chain] == [
        "firedpkg.watch.Watch.__init__",
        "firedpkg.watch.Watch._reset_loop",
        "firedpkg.watch.Watch._reset_loop"]


def test_r16_abba_across_modules(tmp_path):
    """Two module locks taken in opposite order by two threads (the
    locks live in a module neither worker imports for spawning) — R16
    chains both sides; the same-order twin with its own lock pair is
    green."""
    pkg = _copy_raceflow(tmp_path, "abbapkg")
    r = run([str(pkg)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), select=["R16"])
    assert r.exit_code == 1 and len(r.new) == 1, r.report
    f = r.new[0]
    assert f.rule == "lock-order-inversion"
    assert f.path == "abbapkg/workers.py"
    assert "abbapkg.locks.A" in f.message and "abbapkg.locks.B" in f.message
    quals = [hop[2] for hop in f.chain]
    assert quals[0] == "abbapkg.workers.<module>"      # the spawn site
    assert quals[-1] == "abbapkg.workers.backward"     # the inverted edge
    assert "abbapkg.workers.forward" in quals


def test_r17_await_and_blocking_shapes(tmp_path):
    """Both R17 shapes in one package: threading lock held across an
    await, and time.sleep inside a coroutine; the asyncio.Lock twin is
    green."""
    pkg = _copy_raceflow(tmp_path, "blockpkg")
    r = run([str(pkg)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), select=["R17"])
    assert r.exit_code == 1 and len(r.new) == 2, r.report
    by_line = sorted(r.new, key=lambda f: f.line)
    assert all(f.path == "blockpkg/svc.py" for f in by_line)
    assert "'await' while holding threading lock" in by_line[0].message
    assert "blockpkg.svc.LOCK" in by_line[0].message
    assert "time.sleep" in by_line[1].message
    assert "event loop" in by_line[1].message


def test_r15_entry_held_credits_locked_helpers():
    """RacerD-style guard inference: a ``*_locked`` helper whose every
    call site holds the lock writes WITH the lock — no R15; the same
    shape with a genuinely bare writer on another root still fires."""
    guarded = """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []
                threading.Thread(target=self._worker).start()
                threading.Thread(target=self._other).start()

            def _worker(self):
                with self._lock:
                    self._push_locked(1)

            def _push_locked(self, x):
                self.items.append(x)

            def _other(self):
                with self._lock:
                    self.items.append(2)
        """
    idx = _index_of(("pkg/__init__.py", ""), ("pkg/box.py", guarded))
    assert list(_get_rule("R15").check_project(idx)) == []

    bare = guarded.replace("""
            def _other(self):
                with self._lock:
                    self.items.append(2)
        """, """
            def _other(self):
                self.items.append(2)
        """)
    idx2 = _index_of(("pkg/__init__.py", ""), ("pkg/box.py", bare))
    fs = list(_get_rule("R15").check_project(idx2))
    assert len(fs) == 1 and "'items'" in fs[0].message


def test_r17_executor_dispatched_blocking_helper_is_exempt():
    """A sync helper the coroutine hands to run_in_executor runs OFF
    the loop — no R17; the same helper called directly still fires.
    (The real-tree shape: node/worker.py dispatching
    obs/profiling.capture.)"""
    dispatched = """
        import asyncio
        import time

        def capture():
            time.sleep(1.0)

        async def runner():
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, capture)
        """
    idx = _index_of(("pkg/__init__.py", ""), ("pkg/m.py", dispatched))
    assert list(_get_rule("R17").check_project(idx)) == []

    direct = """
        import time

        def capture():
            time.sleep(1.0)

        async def runner():
            capture()
        """
    idx2 = _index_of(("pkg/__init__.py", ""), ("pkg/m.py", direct))
    fs = list(_get_rule("R17").check_project(idx2))
    assert len(fs) == 1 and "time.sleep" in fs[0].message


def test_r14_allow_marker_suppresses():
    """# swarmlens: allow-cross-thread-handoff on (or above) the publish
    line documents an intentional handoff and silences R14."""
    src = """
        import collections
        import threading

        import jax

        class Lane:
            def __init__(self):
                self._out = collections.deque()
                self._step = jax.jit(lambda x: x * 2)
                threading.Thread(target=self._drive).start()

            def _drive(self):
                y = self._step(1.0)
                # consumer re-synchronizes; see poll()
                # swarmlens: allow-cross-thread-handoff
                self._out.append(y)

            async def poll(self):
                return self._out.popleft()
        """
    idx = _index_of(("pkg/__init__.py", ""), ("pkg/lane.py", src))
    assert list(_get_rule("R14").check_project(idx)) == []


def test_raceflow_baseline_lifecycle(tmp_path):
    """R14 findings ride the shrink-only baseline: finding ->
    grandfathered -> fixed -> stale entry fails --strict."""
    pkg = _copy_raceflow(tmp_path, "handoffpkg")
    bl = tmp_path / "baseline.json"
    r = run([str(pkg)], baseline_path=str(bl), root=str(tmp_path),
            select=["R14"])
    assert r.exit_code == 1 and len(r.new) == 1

    r = run([str(pkg)], baseline_path=str(bl), root=str(tmp_path),
            write_baseline=True)
    assert r.exit_code == 0
    doc = json.loads(bl.read_text())
    entries = [e for e in doc["findings"]
               if e["rule"] == "cross-thread-device-handoff"]
    assert len(entries) == 1
    assert set(entries[0]) == {"rule", "path", "symbol", "message",
                               "count"}  # identity only, no chain hops

    r = run([str(pkg)], baseline_path=str(bl), root=str(tmp_path),
            select=["R14"], strict=True)
    assert r.exit_code == 0 and len(r.suppressed) == 1

    # fix: synchronize before publishing — the finding disappears and
    # its baseline entry goes stale
    lane = pkg / "lane.py"
    fixed = lane.read_text().replace(
        "y = self._step(1.0)",
        "y = jax.block_until_ready(self._step(1.0))")
    assert fixed != lane.read_text()
    lane.write_text(fixed)
    r = run([str(pkg)], baseline_path=str(bl), root=str(tmp_path),
            select=["R14"], strict=True)
    assert r.exit_code == 1 and not r.new
    assert len(r.stale) == 1 and "cross-thread-device-handoff" in r.stale[0]


def test_raceflow_cli_chain_in_text_json_and_sarif(tmp_path):
    """The acceptance clause: R14's root->site chain renders in all
    three output formats (text, --json, --sarif codeFlows)."""
    pkg = _copy_raceflow(tmp_path, "handoffpkg")
    base = [sys.executable, "-m", "chiaswarm_tpu.analysis", "--select",
            "R14", "--no-cache"]
    proc = subprocess.run(base + [str(pkg)], capture_output=True,
                          text=True, timeout=300)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "cross-thread-device-handoff" in proc.stdout
    assert "chain: handoffpkg.lane.Lane.__init__" in proc.stdout

    proc = subprocess.run(base + ["--json", str(pkg)],
                          capture_output=True, text=True, timeout=300)
    doc = json.loads(proc.stdout)
    assert len(doc) == 1 and len(doc[0]["chain"]) == 3
    assert doc[0]["chain"][0][2] == "handoffpkg.lane.Lane.__init__"

    sarif = tmp_path / "out.sarif"
    proc = subprocess.run(base + ["--sarif", str(sarif), str(pkg)],
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 1
    res = json.loads(sarif.read_text())["runs"][0]["results"]
    assert len(res) == 1
    assert res[0]["ruleId"] == "cross-thread-device-handoff"
    flow = res[0]["codeFlows"][0]["threadFlows"][0]["locations"]
    assert [h["location"]["message"]["text"] for h in flow] == [
        "handoffpkg.lane.Lane.__init__", "handoffpkg.lane.Lane._drive",
        "handoffpkg.lane.Lane._drive"]


def test_changed_only_conc_definitions_expand_to_conc_consumers(tmp_path):
    """ISSUE 16 satellite: editing a module that DEFINES an execution
    root or lock must re-lint every cross-root consumer even without an
    import edge — while conc-free islands stay out of the fast path."""
    def git(*args):
        subprocess.run(["git", "-C", str(tmp_path), *args], check=True,
                       capture_output=True)

    _write(tmp_path, "pkg/__init__.py", "")
    hub = _write(tmp_path, "pkg/hub.py", textwrap.dedent("""
        import threading

        LOCK = threading.Lock()

        def seed():
            pass

        threading.Thread(target=seed, daemon=True).start()
        """))
    _write(tmp_path, "pkg/user.py", textwrap.dedent("""
        import time

        def slow():
            time.sleep(0.1)
        """))
    _write(tmp_path, "pkg/island.py", "z = 1\n")
    git("init", "-q")
    git("add", ".")
    git("-c", "user.email=t@t", "-c", "user.name=t", "commit", "-q",
        "-m", "seed")
    git("update-ref", "refs/remotes/origin/main", "HEAD")

    # edit ONLY the spawn/lock-defining module
    hub.write_text(hub.read_text() + "\nEXTRA = 1\n")
    r = run([str(tmp_path)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), changed_only=True, select=["R17"])
    assert r.exit_code == 0, r.report
    # hub + the blocking-call consumer; the island is skipped
    assert r.checked_files == 2 and r.total_files == 4

    # a non-conc edit keeps the narrow closure
    git("add", ".")
    git("-c", "user.email=t@t", "-c", "user.name=t", "commit", "-q",
        "-m", "hub")
    git("update-ref", "refs/remotes/origin/main", "HEAD")
    (tmp_path / "pkg/island.py").write_text("z = 2\n")
    r = run([str(tmp_path)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), changed_only=True, select=["R17"])
    assert r.checked_files == 1


def test_r11_custom_vjp_bwd_explored_through_defvjp(tmp_path):
    """ISSUE 16 satellite: the bwd body of a custom_vjp primal has no
    visible call edge — shardflow follows the defvjp registration, so
    the data-only binding's replicated-residual psum fires (with the
    registration as a chain hop) while the seq-varying twin stays
    green."""
    pkg = _copy_shardflow(tmp_path, "vjppkg")
    r = run([str(pkg)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), select=["R11", "R12"])
    assert r.exit_code == 1 and len(r.new) == 1, r.report
    f = r.new[0]
    assert f.rule == "replicated-psum"
    assert f.path == "vjppkg/kernels.py"
    assert "'seq'" in f.message
    assert [hop[2] for hop in f.chain] == [
        "vjppkg.program.bad_replicated_grad", "vjppkg.kernels.matmul",
        "vjppkg.kernels.matmul.defvjp", "vjppkg.kernels.matmul_bwd",
        "vjppkg.kernels.matmul_bwd"]


def test_r17_native_build_allow_marker_is_load_bearing():
    """Burn-down regression: native/__init__.py runs subprocess.run
    under _LOCK deliberately (one-time cold-path compile, documented
    with an allow-marker). Stripping the marker must resurface R17
    through the entry-held chain load() -> _build() — proving the
    marker suppresses a live finding rather than decorating dead
    code."""
    src_path = os.path.join(os.path.dirname(__file__), "..",
                            "chiaswarm_tpu", "native", "__init__.py")
    with open(src_path) as fh:
        src = fh.read()
    assert "swarmlens: allow-blocking-under-lock" in src
    driver = """
        import threading

        from pkg.native import load

        threading.Thread(target=load, daemon=True).start()
        """
    idx = _index_of(("pkg/__init__.py", ""), ("pkg/native.py", src),
                    ("pkg/driver.py", driver))
    assert list(_get_rule("R17").check_project(idx)) == []

    stripped = "\n".join(
        line for line in src.splitlines()
        if "swarmlens: allow-blocking-under-lock" not in line) + "\n"
    idx2 = _index_of(("pkg/__init__.py", ""), ("pkg/native.py", stripped),
                     ("pkg/driver.py", driver))
    fs = list(_get_rule("R17").check_project(idx2))
    assert len(fs) == 1 and "subprocess.run" in fs[0].message
    assert "pkg.native._LOCK" in fs[0].message


# --------------------------------------------- swarmkey (R18-R21)

KEYFLOW_FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures",
                                "keyflow")


def _copy_keyflow(tmp_path, name):
    dst = tmp_path / name
    shutil.copytree(os.path.join(KEYFLOW_FIXTURES, name), dst)
    return dst


def test_r18_unkeyed_trace_input_both_faces(tmp_path):
    """The CHIASWARM_ATTENTION bug distilled: a trace-time env read the
    key never learns about, plus the flash-block shape (import-time read
    frozen into a module constant the traced body loads). The clean twin
    reads knobs the local builder's _TRACE_KNOBS folds — green."""
    pkg = _copy_keyflow(tmp_path, "unkeyedpkg")
    r = run([str(pkg)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), select=["R18"])
    assert r.exit_code == 1 and len(r.new) == 2, r.report
    const, direct = sorted(r.new, key=lambda f: f.line)
    assert const.rule == "unkeyed-trace-input"
    assert const.path == "unkeyedpkg/engine.py"
    assert "FIXTURE_BLOCK" in const.message and "_BLOCK" in const.message
    assert const.symbol == "<module>"
    assert const.chain[-1] == ("unkeyedpkg/engine.py", const.line,
                               "unkeyedpkg.engine._BLOCK")
    assert "FIXTURE_IMPL" in direct.message
    assert direct.symbol == "_impl"
    # traced root -> the helper -> the read itself
    assert [hop[2] for hop in direct.chain] == [
        "unkeyedpkg.engine._fwd", "unkeyedpkg.engine._impl",
        "unkeyedpkg.engine._impl"]
    assert "chain:" in direct.render()


def test_r19_env_read_inside_build_and_traced_scopes(tmp_path):
    """Both R19 scopes: a read inside a @jax.jit body and one inside a
    get_or_create factory — each executes once per slot; the
    read-at-dispatch twin is green."""
    pkg = _copy_keyflow(tmp_path, "frozenpkg")
    r = run([str(pkg)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), select=["R19"])
    assert r.exit_code == 1 and len(r.new) == 2, r.report
    jit_read, factory_read = sorted(r.new, key=lambda f: f.line)
    assert jit_read.rule == "frozen-env-reread"
    assert jit_read.path == "frozenpkg/engine.py"
    assert "FIXTURE_SCALE" in jit_read.message and jit_read.symbol == "step"
    assert "FIXTURE_MODE" in factory_read.message
    assert factory_read.symbol == "_build"
    # build-registration hop -> the frozen read
    assert [hop[2] for hop in factory_read.chain] == [
        "frozenpkg.engine.get", "frozenpkg.engine._build"]


def test_r20_unstable_component_only_on_persistent_surface(tmp_path):
    """id()/repr() in artifact_cache_key fire; the clean twin keeps
    id(self._c) in the IN-PROCESS static_cache_key — the two surfaces
    are judged differently."""
    pkg = _copy_keyflow(tmp_path, "unstablepkg")
    r = run([str(pkg)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), select=["R20"])
    assert r.exit_code == 1 and len(r.new) == 2, r.report
    assert all(f.rule == "unstable-key-component" for f in r.new)
    assert all(f.path == "unstablepkg/ship.py" for f in r.new)
    msgs = sorted(f.message for f in r.new)
    assert "id(model)" in msgs[0] and "repr(model.cfg)" in msgs[1]
    assert all("artifact_cache_key" in m for m in msgs)


def test_r21_shared_vocabulary_collides(tmp_path):
    """encode and decode building different programs under one
    (owner, tag, statics) triple collide; the per-program-tag twin is
    green."""
    pkg = _copy_keyflow(tmp_path, "collidepkg")
    r = run([str(pkg)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), select=["R21"])
    assert r.exit_code == 1 and len(r.new) == 1, r.report
    f = r.new[0]
    assert f.rule == "cache-tag-collision"
    assert f.path == "collidepkg/engine.py" and f.symbol == "Engine.decode"
    assert "'run'" in f.message and "Engine.encode" in f.message
    assert [hop[2] for hop in f.chain] == [
        "collidepkg.engine.Engine.encode",
        "collidepkg.engine.Engine.decode"]


def test_r6_interprocedural_face(tmp_path):
    """ISSUE 20 satellite: the raw-attr-through-parameter and the
    unbounded-container-display shapes, one call hop from the key site;
    the bucket-at-call-site twin is green."""
    pkg = _copy_keyflow(tmp_path, "cardpkg")
    r = run([str(pkg)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), select=["R6"])
    assert r.exit_code == 1 and len(r.new) == 2, r.report
    param, display = sorted(r.new, key=lambda f: f.line)
    assert param.rule == "recompile-hazard"
    assert param.path == "cardpkg/pipe.py" and param.symbol == "handle"
    assert ".height" in param.message and "'h'" in param.message
    # caller call site -> the key-site function -> the key site
    assert [hop[2] for hop in param.chain] == [
        "cardpkg.pipe.handle", "cardpkg.pipe._get_fn",
        "cardpkg.pipe._get_fn"]
    assert display.symbol == "_get_fn_sizes"
    assert "'sizes'" in display.message
    assert "non-hashable" in display.message


def test_keyflow_allow_markers_suppress(tmp_path):
    """Each keyflow rule has its own swarmlens marker; marking the
    finding line (or the comment line above) silences exactly it."""
    pkg = _copy_keyflow(tmp_path, "unkeyedpkg")
    eng = pkg / "engine.py"
    eng.write_text(eng.read_text().replace(
        'return os.environ.get("FIXTURE_IMPL", "einsum")',
        'return os.environ.get("FIXTURE_IMPL", "einsum")'
        '  # swarmlens: allow-unkeyed-trace-input'))
    r = run([str(pkg)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), select=["R18"])
    assert len(r.new) == 1 and "_BLOCK" in r.new[0].message, r.report

    pkg2 = _copy_keyflow(tmp_path, "frozenpkg")
    eng2 = pkg2 / "engine.py"
    eng2.write_text(eng2.read_text().replace(
        '    mode = os.environ.get("FIXTURE_MODE", "fast")',
        '    # swarmlens: allow-frozen-env-reread\n'
        '    mode = os.environ.get("FIXTURE_MODE", "fast")'))
    r = run([str(pkg2)], baseline_path=str(tmp_path / "b2.json"),
            root=str(tmp_path), select=["R19"])
    assert len(r.new) == 1 and "FIXTURE_SCALE" in r.new[0].message


def test_keyflow_baseline_lifecycle(tmp_path):
    """R18 findings ride the shrink-only baseline: finding ->
    grandfathered -> fixed -> stale entry fails --strict."""
    pkg = _copy_keyflow(tmp_path, "unkeyedpkg")
    bl = tmp_path / "baseline.json"
    r = run([str(pkg)], baseline_path=str(bl), root=str(tmp_path),
            select=["R18"])
    assert r.exit_code == 1 and len(r.new) == 2

    r = run([str(pkg)], baseline_path=str(bl), root=str(tmp_path),
            write_baseline=True)
    assert r.exit_code == 0
    doc = json.loads(bl.read_text())
    entries = [e for e in doc["findings"]
               if e["rule"] == "unkeyed-trace-input"]
    assert len(entries) == 2
    assert set(entries[0]) == {"rule", "path", "symbol", "message",
                               "count"}  # identity only, no chain hops

    r = run([str(pkg)], baseline_path=str(bl), root=str(tmp_path),
            select=["R18"], strict=True)
    assert r.exit_code == 0 and len(r.suppressed) == 2

    # fix: stop reading the unkeyed knob — the finding disappears and
    # its baseline entry goes stale
    eng = pkg / "engine.py"
    fixed = eng.read_text().replace(
        'os.environ.get("FIXTURE_IMPL", "einsum")', '"einsum"')
    assert fixed != eng.read_text()
    eng.write_text(fixed)
    r = run([str(pkg)], baseline_path=str(bl), root=str(tmp_path),
            select=["R18"], strict=True)
    assert r.exit_code == 1 and not r.new
    assert len(r.stale) == 1 and "unkeyed-trace-input" in r.stale[0]


def test_keyflow_cli_chain_in_text_json_and_sarif(tmp_path):
    """The acceptance clause: R18's entry->sink chain renders in all
    three output formats (text, --json, --sarif codeFlows)."""
    pkg = _copy_keyflow(tmp_path, "unkeyedpkg")
    base = [sys.executable, "-m", "chiaswarm_tpu.analysis", "--select",
            "R18", "--no-cache"]
    proc = subprocess.run(base + [str(pkg)], capture_output=True,
                          text=True, timeout=300)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "unkeyed-trace-input" in proc.stdout
    assert "chain: unkeyedpkg.engine._fwd" in proc.stdout

    proc = subprocess.run(base + ["--json", str(pkg)],
                          capture_output=True, text=True, timeout=300)
    doc = json.loads(proc.stdout)
    assert len(doc) == 2
    direct = [f for f in doc if f["symbol"] == "_impl"][0]
    assert len(direct["chain"]) == 3
    assert direct["chain"][0][2] == "unkeyedpkg.engine._fwd"

    sarif = tmp_path / "out.sarif"
    proc = subprocess.run(base + ["--sarif", str(sarif), str(pkg)],
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 1
    res = json.loads(sarif.read_text())["runs"][0]["results"]
    assert len(res) == 2
    assert {r_["ruleId"] for r_ in res} == {"unkeyed-trace-input"}
    flows = [r_ for r_ in res if r_["codeFlows"][0]["threadFlows"][0]
             ["locations"][-1]["location"]["message"]["text"]
             == "unkeyedpkg.engine._impl"]
    assert len(flows) == 1


def test_changed_only_key_definitions_expand_to_key_consumers(tmp_path):
    """ISSUE 20 satellite: editing the key-builder module (or any
    knob-defining module) must re-lint every compile-cached program
    site even without an import edge — while key-free islands stay out
    of the fast path."""
    def git(*args):
        subprocess.run(["git", "-C", str(tmp_path), *args], check=True,
                       capture_output=True)

    _write(tmp_path, "pkg/__init__.py", "")
    hub = _write(tmp_path, "pkg/keys.py", textwrap.dedent("""
        _TRACE_KNOBS = ("PKG_MODE",)

        def static_cache_key(owner, tag, static):
            return (owner, tag, tuple(sorted(static.items())))
        """))
    _write(tmp_path, "pkg/user.py", textwrap.dedent("""
        import os

        def impl():
            return os.environ.get("PKG_IMPL", "fast")
        """))
    _write(tmp_path, "pkg/island.py", "z = 1\n")
    git("init", "-q")
    git("add", ".")
    git("-c", "user.email=t@t", "-c", "user.name=t", "commit", "-q",
        "-m", "seed")
    git("update-ref", "refs/remotes/origin/main", "HEAD")

    # edit ONLY the key-defining module
    hub.write_text(hub.read_text() + "\nEXTRA = 1\n")
    r = run([str(tmp_path)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), changed_only=True, select=["R18"])
    assert r.exit_code == 0, r.report
    # the builder + the env-reading consumer; the island is skipped
    assert r.checked_files == 2 and r.total_files == 4

    # a key-free edit keeps the narrow closure
    git("add", ".")
    git("-c", "user.email=t@t", "-c", "user.name=t", "commit", "-q",
        "-m", "hub")
    git("update-ref", "refs/remotes/origin/main", "HEAD")
    (tmp_path / "pkg/island.py").write_text("z = 2\n")
    r = run([str(tmp_path)], baseline_path=str(tmp_path / "b.json"),
            root=str(tmp_path), changed_only=True, select=["R18"])
    assert r.checked_files == 1


def test_r18_attention_knob_fold_is_load_bearing():
    """Burn-down regression: the live CHIASWARM_ATTENTION finding is
    fixed by compile_cache._TRACE_ENV_KNOBS, not a marker — removing
    the knob from the tuple must resurface R18 through the real
    ops/attention.py chain."""
    ops_path = os.path.join(os.path.dirname(__file__), "..",
                            "chiaswarm_tpu", "ops", "attention.py")
    cc_path = os.path.join(os.path.dirname(__file__), "..",
                           "chiaswarm_tpu", "core", "compile_cache.py")
    with open(ops_path) as fh:
        ops_src = fh.read()
    with open(cc_path) as fh:
        cc_src = fh.read()
    assert '"CHIASWARM_ATTENTION",' in cc_src
    driver = """
        import jax

        from pkg.ops import attention

        step = jax.jit(lambda q, k, v: attention(q, k, v))
        """
    idx = _index_of(("pkg/__init__.py", ""), ("pkg/ops.py", ops_src),
                    ("pkg/cc.py", cc_src), ("pkg/driver.py", driver))
    fs = [f for f in _get_rule("R18").check_project(idx)
          if "CHIASWARM_ATTENTION" in f.message]
    assert fs == []

    stripped = cc_src.replace('    "CHIASWARM_ATTENTION",\n', "")
    assert stripped != cc_src
    idx2 = _index_of(("pkg/__init__.py", ""), ("pkg/ops.py", ops_src),
                     ("pkg/cc.py", stripped), ("pkg/driver.py", driver))
    fs = [f for f in _get_rule("R18").check_project(idx2)
          if "CHIASWARM_ATTENTION" in f.message]
    assert len(fs) == 1
    assert fs[0].symbol == "_env_impl"
