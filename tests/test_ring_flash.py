"""swarmkernel (ISSUE 18): the fused ring-flash kernel, hermetically.

On the virtual 8-device CPU mesh (tests/conftest.py) the Pallas kernel
runs in interpret mode, so these tests validate the in-kernel blockwise
recurrence itself — the same `_hop_kernel` the TPU path drives — against
BOTH oracles named by the acceptance criteria:

- the ppermute ring scan (parallel/ring_attention.py), the exactness
  oracle for the hop-by-hop combine; and
- the unsharded dense/flash path, the golden single-chip answer.

Tolerances are the repo's torch-parity bar (rtol/atol 2e-4,
tests/test_parallel.py). The activation-quantization seam
(CHIASWARM_ACTIVATIONS, convert/quantize.py) rides along: default-off
identity, per-tensor absmax bounds, cache-key folding, and the < 5%%
end-to-end forward-parity gate per diffusion family kind.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from chiaswarm_tpu.core.compat import shard_map, shard_map_unchecked
from chiaswarm_tpu.core.mesh import MeshSpec, build_mesh
from chiaswarm_tpu.ops.attention import _xla_attention
from chiaswarm_tpu.ops.ring_flash_attention import ring_flash_attention
from chiaswarm_tpu.parallel.ring_attention import ring_attention

RTOL = ATOL = 2e-4


def _qkv(seed: int, b: int, l: int, h: int, d: int):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (jax.random.normal(kq, (b, l, h, d), jnp.float32),
            jax.random.normal(kk, (b, l, h, d), jnp.float32),
            jax.random.normal(kv, (b, l, h, d), jnp.float32))


def _ring_flash_fn(mesh, spec, **kw):
    from functools import partial

    return shard_map_unchecked(
        partial(ring_flash_attention, axis_name="seq",
                mesh_axis_names=tuple(mesh.axis_names), **kw),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)


@pytest.mark.parametrize("sp", [4, 8])
def test_ring_flash_matches_ring_and_dense(sp):
    """The acceptance line: interpret-mode ring-flash == ppermute ring
    == dense attention on seq=4 AND seq=8 meshes, torch-parity bar."""
    mesh = build_mesh(MeshSpec({"seq": sp}), devices=jax.devices()[:sp])
    b, l, h, d = 2, 128, 2, 32
    q, k, v = _qkv(sp, b, l, h, d)
    spec = P(None, "seq", None, None)

    fused = jax.jit(_ring_flash_fn(mesh, spec))(q, k, v)
    ppermute = jax.jit(shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="seq"),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec))(q, k, v)
    dense = _xla_attention(q, k, v, d ** -0.5)

    np.testing.assert_allclose(np.asarray(fused), np.asarray(ppermute),
                               rtol=RTOL, atol=ATOL)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(dense),
                               rtol=RTOL, atol=ATOL)


def test_ring_flash_matches_unsharded_flash():
    """Against the OTHER oracle the issue names: the single-chip Pallas
    flash kernel in interpret mode — same blockwise recurrence, no
    ring; proves the hop combine is exactly the flash accumulator."""
    from chiaswarm_tpu.ops.flash_attention import flash_attention

    mesh = build_mesh(MeshSpec({"seq": 4}), devices=jax.devices()[:4])
    b, l, h, d = 2, 128, 2, 32
    q, k, v = _qkv(3, b, l, h, d)
    spec = P(None, "seq", None, None)
    fused = jax.jit(_ring_flash_fn(mesh, spec))(q, k, v)
    flash = flash_attention(q, k, v, interpret=True)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(flash),
                               rtol=RTOL, atol=ATOL)


def test_ring_flash_mixed_data_seq_mesh():
    """The divergence family's trigger shape (R11 / r06): a two-axis
    data=2 x seq=4 shard_map — batch sharded on data, tokens ringed."""
    mesh = build_mesh(MeshSpec({"data": 2, "seq": 4}))
    b, l, h, d = 2, 128, 2, 32
    q, k, v = _qkv(4, b, l, h, d)
    spec = P("data", "seq", None, None)
    fused = jax.jit(_ring_flash_fn(mesh, spec))(q, k, v)
    dense = _xla_attention(q, k, v, d ** -0.5)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(dense),
                               rtol=RTOL, atol=ATOL)


def test_ring_flash_inner_blocking():
    """Inner-blocked hop (block_q=block_kv=16 over a 32-token shard)
    must match the whole-shard default — the blocked path is what the
    TPU grid actually runs at SDXL sizes."""
    mesh = build_mesh(MeshSpec({"seq": 4}), devices=jax.devices()[:4])
    b, l, h, d = 2, 128, 2, 32
    q, k, v = _qkv(5, b, l, h, d)
    spec = P(None, "seq", None, None)
    blocked = jax.jit(_ring_flash_fn(mesh, spec, block_q=16,
                                     block_kv=16))(q, k, v)
    dense = _xla_attention(q, k, v, d ** -0.5)
    np.testing.assert_allclose(np.asarray(blocked), np.asarray(dense),
                               rtol=RTOL, atol=ATOL)


def test_dispatch_impl_ring_flash(monkeypatch):
    """ops.attention dispatch: impl='ring_flash' under sequence_parallel
    routes the fused kernel and matches dense; without a mesh the
    explicit impl= contract still raises."""
    from chiaswarm_tpu.ops.attention import attention
    from chiaswarm_tpu.parallel import sequence_parallel

    monkeypatch.setenv("CHIASWARM_RING_MIN_TOKENS", "1")
    mesh = build_mesh(MeshSpec({"seq": 4}), devices=jax.devices()[:4])
    b, l, h, d = 2, 64, 2, 16
    q, k, v = _qkv(6, b, l, h, d)
    ref = _xla_attention(q, k, v, d ** -0.5)
    with sequence_parallel(mesh):
        got = attention(q, k, v, impl="ring_flash")
        # cross-attention (tiny KV) stays local even for ring kinds
        cross = attention(q, k[:, :7], v[:, :7], impl="ring_flash")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=RTOL, atol=ATOL)
    assert cross.shape == q.shape
    with pytest.raises(ValueError, match="sequence-parallel mesh"):
        attention(q, k, v, impl="ring_flash")


def test_env_override_is_advisory(monkeypatch):
    """CHIASWARM_ATTENTION=ring_flash: on a seq mesh the auto pick is
    overridden to the fused kernel; OFF the mesh it must NOT crash (a
    fleet-wide env roll reaches workers with no seq axis) — those fall
    back to the local paths."""
    from chiaswarm_tpu.ops.attention import attention
    from chiaswarm_tpu.parallel import sequence_parallel

    monkeypatch.setenv("CHIASWARM_RING_MIN_TOKENS", "1")
    monkeypatch.setenv("CHIASWARM_ATTENTION", "ring_flash")
    mesh = build_mesh(MeshSpec({"seq": 4}), devices=jax.devices()[:4])
    b, l, h, d = 2, 64, 2, 16
    q, k, v = _qkv(7, b, l, h, d)
    ref = _xla_attention(q, k, v, d ** -0.5)
    with sequence_parallel(mesh):
        got = attention(q, k, v)  # auto, env-overridden
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=RTOL, atol=ATOL)
    # advisory off-mesh: falls back instead of raising
    local = attention(q, k, v)
    np.testing.assert_allclose(np.asarray(local), np.asarray(ref),
                               rtol=RTOL, atol=ATOL)


def test_ring_flash_taps_feed_bisect(monkeypatch):
    """The scan path's per-hop probes (ring_flash.hop_rowmax/rowsum/
    hop_acc + ring_flash.out) record under the same 'ring' numerics
    token as the ppermute ring — the stream divergence_bisect's
    seq_parallel_ring_flash config aligns against its fp twin."""
    from chiaswarm_tpu.obs import numerics

    monkeypatch.setenv("CHIASWARM_NUMERICS", "ring")
    mesh = build_mesh(MeshSpec({"seq": 4}), devices=jax.devices()[:4])
    b, l, h, d = 2, 64, 2, 16
    q, k, v = _qkv(8, b, l, h, d)
    spec = P(None, "seq", None, None)
    out = jax.jit(_ring_flash_fn(mesh, spec))(q, k, v)
    jax.block_until_ready(out)
    numerics.flush()
    records = numerics.RING.snapshot()
    probes = {r["probe"] for r in records}
    assert "ring_flash.out" in probes
    assert "ring_flash.hop_rowmax" in probes
    # per-hop x per-shard identity, the bisect's alignment key
    hops = [r for r in records if r["probe"] == "ring_flash.hop_rowsum"]
    assert {(r["step"], r["shard"]) for r in hops} >= {
        (hop, shard) for hop in range(4) for shard in range(4)}


# ---------------------------------------------------------------------------
# low-precision activations (CHIASWARM_ACTIVATIONS)


def test_activations_default_off_identity(monkeypatch):
    monkeypatch.delenv("CHIASWARM_ACTIVATIONS", raising=False)
    from chiaswarm_tpu.convert.quantize import (
        activations_enabled,
        fake_quant_activation,
    )

    assert not activations_enabled()
    x = jnp.arange(8.0).reshape(2, 4)
    assert fake_quant_activation(x, tag="t") is x


def test_activations_int8_absmax_bounds(monkeypatch):
    """Per-tensor dynamic absmax: every element lands within half a
    code of its fp value, and the absmax element round-trips exactly."""
    monkeypatch.setenv("CHIASWARM_ACTIVATIONS", "int8")
    from chiaswarm_tpu.convert.quantize import fake_quant_activation

    x = jax.random.normal(jax.random.PRNGKey(0), (4, 64), jnp.float32) * 3
    q = np.asarray(fake_quant_activation(x, tag="t"))
    scale = float(np.max(np.abs(np.asarray(x)))) / 127.0
    assert np.all(np.abs(np.asarray(x) - q) <= scale / 2 + 1e-8)
    i = np.unravel_index(np.argmax(np.abs(np.asarray(x))), x.shape)
    np.testing.assert_allclose(q[i], np.asarray(x)[i], rtol=1e-6)
    # integers are non-float: identity, never quantized
    ints = jnp.arange(5)
    assert fake_quant_activation(ints, tag="t") is ints


def test_activations_fp8_parity(monkeypatch):
    """fp8 (e4m3 via core/compat probe; degrades to int8 where the
    dtype/hardware is absent) keeps a unit-scale tensor within a few
    percent — the coarse-grid bound, not bit exactness."""
    monkeypatch.setenv("CHIASWARM_ACTIVATIONS", "fp8")
    from chiaswarm_tpu.convert.quantize import (
        activations_format,
        fake_quant_activation,
    )

    assert activations_format() in ("fp8", "int8")
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 64), jnp.float32)
    q = np.asarray(fake_quant_activation(x, tag="t"))
    rel = (np.linalg.norm(np.asarray(x) - q)
           / np.linalg.norm(np.asarray(x)))
    assert rel < 0.05, f"fp8 fake-quant rel err {rel:.4f}"


def test_activations_unknown_value_off(monkeypatch):
    monkeypatch.setenv("CHIASWARM_ACTIVATIONS", "int4")
    from chiaswarm_tpu.convert.quantize import activations_format

    assert activations_format() == "off"


def test_activation_cache_key_folds(monkeypatch):
    """The compile-cache discipline: the activations format folds into
    static_cache_key ONLY when enabled — default-off keys stay
    byte-identical to pre-ISSUE-18 keys (no fleet-wide recompile)."""
    from chiaswarm_tpu.core.compile_cache import static_cache_key

    monkeypatch.delenv("CHIASWARM_ACTIVATIONS", raising=False)
    monkeypatch.delenv("CHIASWARM_NUMERICS", raising=False)
    static = {"size": 64, "steps": 2}
    base = static_cache_key(1, "unet", static)
    assert not any("activations" in str(part) for part in base)
    monkeypatch.setenv("CHIASWARM_ACTIVATIONS", "int8")
    keyed = static_cache_key(1, "unet", static)
    assert keyed != base
    assert ("activations", "int8") in keyed
    # restore-off restores the historical key byte-identically
    monkeypatch.delenv("CHIASWARM_ACTIVATIONS", raising=False)
    assert static_cache_key(1, "unet", static) == base


def test_attention_int8_activations_parity(monkeypatch):
    """attention() with the quantized q/k/v seam engaged stays within
    the coarse bound vs the fp path on normal-scale inputs."""
    from chiaswarm_tpu.ops.attention import attention

    b, l, h, d = 2, 64, 2, 16
    q, k, v = _qkv(9, b, l, h, d)
    ref = np.asarray(attention(q, k, v, impl="xla"))
    monkeypatch.setenv("CHIASWARM_ACTIVATIONS", "int8")
    got = np.asarray(attention(q, k, v, impl="xla"))
    rel = np.linalg.norm(ref - got) / np.linalg.norm(ref)
    assert rel < 0.05, f"int8 activation attention rel err {rel:.4f}"


@pytest.mark.parametrize("family", [
    "tiny",
    pytest.param("tiny_xl", marks=pytest.mark.slow),
])
def test_int8_activation_forward_parity_per_family_kind(family,
                                                        monkeypatch):
    """The ISSUE-18 acceptance gate, mirroring the PR-8 weights gate
    (tests/test_residency.py): generated images through the REAL
    registry with CHIASWARM_ACTIVATIONS=int8 must stay within 5%%
    relative error of the fp path, per diffusion family kind."""
    monkeypatch.setenv("CHIASWARM_STEPPER", "0")
    from chiaswarm_tpu.node.registry import ModelRegistry
    from chiaswarm_tpu.pipelines.diffusion import GenerateRequest

    def registry():
        return ModelRegistry(
            catalog=[{"name": family, "family": family}],
            allow_random=True)

    req = GenerateRequest(prompt="parity", steps=2, guidance_scale=7.5,
                          height=64, width=64, batch=1, seed=11)
    monkeypatch.delenv("CHIASWARM_ACTIVATIONS", raising=False)
    img_fp, _ = registry().pipeline(family)(req)

    monkeypatch.setenv("CHIASWARM_ACTIVATIONS", "int8")
    img_q, _ = registry().pipeline(family)(req)

    assert img_q.shape == img_fp.shape
    diff = np.abs(img_fp.astype(np.float32) - img_q.astype(np.float32))
    rel = (np.linalg.norm(diff)
           / max(np.linalg.norm(img_fp.astype(np.float32)), 1e-9))
    assert diff.mean() < 4.0, f"mean abs uint8 diff {diff.mean():.2f}"
    assert rel < 0.05, f"relative error {rel:.4f}"
