"""Test-side torch references for the two published video-UNet layouts.

Independent torch implementations of diffusers' ``UNet3DConditionModel``
(ModelScope text-to-video — the snapshot the reference serves,
swarm/video/tx2vid.py:24-27) and ``UNetSpatioTemporalConditionModel``
(SVD img2vid), with the EXACT published state-dict naming. diffusers is
not installed in this environment, so these stand in for it on two fronts:

- numeric forward parity vs models/video_unet.py (converted weights must
  reproduce the torch forward number-for-number);
- full-published-config conversion coverage (state_dict() -> converter ->
  every Flax leaf present, nothing synthesized).

Written against the published module graphs, NOT against the Flax code —
a naming/semantics bug in the converter or the Flax modules cannot cancel
out here (same policy as tests/torch_export.py).
"""

from __future__ import annotations

import math

import torch
import torch.nn as nn
import torch.nn.functional as F


def _groups(channels: int) -> int:
    g = min(32, channels)
    while channels % g:
        g -= 1
    return g


def sinusoidal(t: torch.Tensor, dim: int) -> torch.Tensor:
    """diffusers get_timestep_embedding with flip_sin_to_cos=True,
    downscale_freq_shift=0: [cos | sin]."""
    half = dim // 2
    freqs = torch.exp(-math.log(10000.0) * torch.arange(half).float() / half)
    args = t.float()[:, None] * freqs[None]
    return torch.cat([torch.cos(args), torch.sin(args)], dim=-1)


class TimestepEmbedding(nn.Module):
    def __init__(self, in_dim: int, hidden: int, out_dim: int | None = None):
        super().__init__()
        self.linear_1 = nn.Linear(in_dim, hidden)
        self.linear_2 = nn.Linear(hidden, out_dim or hidden)

    def forward(self, x):
        return self.linear_2(F.silu(self.linear_1(x)))


class Attention(nn.Module):
    """diffusers Attention: biasless qkv, to_out = ModuleList([Linear,
    Dropout])."""

    def __init__(self, dim, heads, head_dim, cross_dim=None):
        super().__init__()
        inner = heads * head_dim
        self.heads, self.head_dim = heads, head_dim
        self.to_q = nn.Linear(dim, inner, bias=False)
        self.to_k = nn.Linear(cross_dim or dim, inner, bias=False)
        self.to_v = nn.Linear(cross_dim or dim, inner, bias=False)
        self.to_out = nn.ModuleList([nn.Linear(inner, dim), nn.Dropout(0.0)])

    def forward(self, x, context=None):
        context = x if context is None else context
        b, l, _ = x.shape
        s = context.shape[1]
        q = self.to_q(x).reshape(b, l, self.heads, self.head_dim)
        k = self.to_k(context).reshape(b, s, self.heads, self.head_dim)
        v = self.to_v(context).reshape(b, s, self.heads, self.head_dim)
        attn = torch.einsum("blhd,bshd->bhls", q, k) / math.sqrt(
            self.head_dim)
        attn = attn.softmax(dim=-1)
        out = torch.einsum("bhls,bshd->blhd", attn, v).reshape(b, l, -1)
        return self.to_out[1](self.to_out[0](out))


class GEGLU(nn.Module):
    def __init__(self, dim, inner):
        super().__init__()
        self.proj = nn.Linear(dim, inner * 2)

    def forward(self, x):
        x, gate = self.proj(x).chunk(2, dim=-1)
        return x * F.gelu(gate)


class FeedForward(nn.Module):
    def __init__(self, dim, out_dim=None):
        super().__init__()
        inner = dim * 4
        self.net = nn.ModuleList([GEGLU(dim, inner), nn.Dropout(0.0),
                                  nn.Linear(inner, out_dim or dim)])

    def forward(self, x):
        for layer in self.net:
            x = layer(x)
        return x


class BasicTransformerBlock(nn.Module):
    def __init__(self, dim, heads, head_dim, cross_dim=None,
                 double_self_attention=False):
        super().__init__()
        self.norm1 = nn.LayerNorm(dim)
        self.attn1 = Attention(dim, heads, head_dim)
        self.norm2 = nn.LayerNorm(dim)
        self.attn2 = Attention(
            dim, heads, head_dim,
            None if double_self_attention else cross_dim)
        self.norm3 = nn.LayerNorm(dim)
        self.ff = FeedForward(dim)
        self.double_self_attention = double_self_attention

    def forward(self, x, context=None):
        x = self.attn1(self.norm1(x)) + x
        ctx = None if self.double_self_attention else context
        x = self.attn2(self.norm2(x), ctx) + x
        return self.ff(self.norm3(x)) + x


class ResnetBlock2D(nn.Module):
    def __init__(self, in_ch, out_ch, temb_dim, eps=1e-5):
        super().__init__()
        self.norm1 = nn.GroupNorm(_groups(in_ch), in_ch, eps=eps)
        self.conv1 = nn.Conv2d(in_ch, out_ch, 3, padding=1)
        self.time_emb_proj = nn.Linear(temb_dim, out_ch)
        self.norm2 = nn.GroupNorm(_groups(out_ch), out_ch, eps=eps)
        self.conv2 = nn.Conv2d(out_ch, out_ch, 3, padding=1)
        self.conv_shortcut = (nn.Conv2d(in_ch, out_ch, 1)
                              if in_ch != out_ch else None)

    def forward(self, x, temb):
        h = self.conv1(F.silu(self.norm1(x)))
        h = h + self.time_emb_proj(F.silu(temb))[:, :, None, None]
        h = self.conv2(F.silu(self.norm2(h)))
        if self.conv_shortcut is not None:
            x = self.conv_shortcut(x)
        return x + h


class Downsample2D(nn.Module):
    def __init__(self, ch):
        super().__init__()
        self.conv = nn.Conv2d(ch, ch, 3, stride=2, padding=1)

    def forward(self, x):
        return self.conv(x)


class Upsample2D(nn.Module):
    def __init__(self, ch):
        super().__init__()
        self.conv = nn.Conv2d(ch, ch, 3, padding=1)

    def forward(self, x):
        return self.conv(F.interpolate(x, scale_factor=2.0, mode="nearest"))


class Transformer2DModel(nn.Module):
    """Spatial transformer with the conv-projection default the 3D UNet
    uses (use_linear_projection=False)."""

    def __init__(self, heads, head_dim, in_ch, cross_dim,
                 use_linear_projection=False, depth=1):
        super().__init__()
        inner = heads * head_dim
        self.use_linear_projection = use_linear_projection
        self.norm = nn.GroupNorm(_groups(in_ch), in_ch, eps=1e-6)
        if use_linear_projection:
            self.proj_in = nn.Linear(in_ch, inner)
            self.proj_out = nn.Linear(inner, in_ch)
        else:
            self.proj_in = nn.Conv2d(in_ch, inner, 1)
            self.proj_out = nn.Conv2d(inner, in_ch, 1)
        self.transformer_blocks = nn.ModuleList(
            [BasicTransformerBlock(inner, heads, head_dim, cross_dim)
             for _ in range(depth)])

    def forward(self, x, context):
        b, c, hh, ww = x.shape
        residual = x
        h = self.norm(x)
        if self.use_linear_projection:
            h = h.permute(0, 2, 3, 1).reshape(b, hh * ww, c)
            h = self.proj_in(h)
        else:
            h = self.proj_in(h)
            h = h.permute(0, 2, 3, 1).reshape(b, hh * ww, -1)
        for block in self.transformer_blocks:
            h = block(h, context)
        if self.use_linear_projection:
            h = self.proj_out(h)
            h = h.reshape(b, hh, ww, c).permute(0, 3, 1, 2)
        else:
            h = h.reshape(b, hh, ww, -1).permute(0, 3, 1, 2)
            h = self.proj_out(h)
        return h + residual


# ------------------------------------------------- ModelScope (UNet3D)


class TemporalConvLayer(nn.Module):
    """Four (GroupNorm, SiLU[, Dropout], Conv3d (3,1,1)) stages; conv4
    zero-initialized; residual add. Keys: conv1.{0,2}, conv2..4.{0,3}."""

    def __init__(self, dim):
        super().__init__()
        self.conv1 = nn.Sequential(
            nn.GroupNorm(_groups(dim), dim), nn.SiLU(),
            nn.Conv3d(dim, dim, (3, 1, 1), padding=(1, 0, 0)))
        for name in ("conv2", "conv3", "conv4"):
            setattr(self, name, nn.Sequential(
                nn.GroupNorm(_groups(dim), dim), nn.SiLU(), nn.Dropout(0.0),
                nn.Conv3d(dim, dim, (3, 1, 1), padding=(1, 0, 0))))
        nn.init.zeros_(self.conv4[-1].weight)
        nn.init.zeros_(self.conv4[-1].bias)

    def forward(self, x, num_frames):
        # x (B*F, C, H, W) -> (B, C, F, H, W)
        x = x.reshape(-1, num_frames, *x.shape[1:]).permute(0, 2, 1, 3, 4)
        identity = x
        x = self.conv4(self.conv3(self.conv2(self.conv1(x))))
        x = identity + x
        x = x.permute(0, 2, 1, 3, 4)                  # (B, F, C, H, W)
        return x.reshape(-1, *x.shape[2:])


class TransformerTemporalModel(nn.Module):
    """Frame-axis transformer, double self-attention (the diffusers
    default for this class)."""

    def __init__(self, heads, head_dim, in_ch):
        super().__init__()
        inner = heads * head_dim
        self.norm = nn.GroupNorm(_groups(in_ch), in_ch, eps=1e-6)
        self.proj_in = nn.Linear(in_ch, inner)
        self.transformer_blocks = nn.ModuleList(
            [BasicTransformerBlock(inner, heads, head_dim,
                                   double_self_attention=True)])
        self.proj_out = nn.Linear(inner, in_ch)

    def forward(self, x, num_frames):
        bf, c, hh, ww = x.shape
        b = bf // num_frames
        residual = x
        h = x.reshape(b, num_frames, c, hh, ww).permute(0, 2, 1, 3, 4)
        h = self.norm(h)
        h = h.permute(0, 3, 4, 2, 1).reshape(b * hh * ww, num_frames, c)
        h = self.proj_in(h)
        for block in self.transformer_blocks:
            h = block(h)
        h = self.proj_out(h)
        h = h.reshape(b, hh, ww, num_frames, c).permute(0, 4, 3, 1, 2)
        h = h.permute(0, 2, 1, 3, 4).reshape(bf, c, hh, ww)
        return h + residual


class _Block3D(nn.Module):
    """One down/up level of UNet3DConditionModel: resnets + temp_convs
    (+ attentions + temp_attentions when the level has attention)."""

    def __init__(self, chans, temb_dim, heads, head_dim, cross_dim,
                 depth, sampler=None):
        super().__init__()
        self.resnets = nn.ModuleList(
            [ResnetBlock2D(i, o, temb_dim) for i, o in chans])
        self.temp_convs = nn.ModuleList(
            [TemporalConvLayer(o) for _, o in chans])
        if depth > 0:
            self.attentions = nn.ModuleList(
                [Transformer2DModel(heads, head_dim, o, cross_dim,
                                    depth=depth) for _, o in chans])
            self.temp_attentions = nn.ModuleList(
                [TransformerTemporalModel(heads, head_dim, o)
                 for _, o in chans])
        else:
            self.attentions = self.temp_attentions = None
        if sampler == "down":
            self.downsamplers = nn.ModuleList([Downsample2D(chans[-1][1])])
        elif sampler == "up":
            self.upsamplers = nn.ModuleList([Upsample2D(chans[-1][1])])


class UNet3DRef(nn.Module):
    """diffusers UNet3DConditionModel at a chiaswarm UNetConfig."""

    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        chans = list(cfg.block_out_channels)
        temb_dim = chans[0] * 4
        self.conv_in = nn.Conv2d(cfg.sample_channels, chans[0], 3,
                                 padding=1)
        self.time_embedding = TimestepEmbedding(chans[0], temb_dim)
        head_dim0 = cfg.heads_for(chans[0], 0)[1]
        self.transformer_in = TransformerTemporalModel(8, head_dim0,
                                                       chans[0])
        down, in_ch = [], chans[0]
        for level, ch in enumerate(chans):
            heads, head_dim = cfg.heads_for(ch, level)
            pairs = []
            for _ in range(cfg.layers_per_block):
                pairs.append((in_ch, ch))
                in_ch = ch
            down.append(_Block3D(
                pairs, temb_dim, heads, head_dim, cfg.cross_attention_dim,
                cfg.transformer_depth[level],
                "down" if level < len(chans) - 1 else None))
        self.down_blocks = nn.ModuleList(down)

        mid_ch = chans[-1]
        mid_heads, mid_head_dim = cfg.heads_for(mid_ch, len(chans) - 1)
        mid_depth = max(cfg.transformer_depth) or 1

        class _Mid(nn.Module):
            def __init__(self):
                super().__init__()
                self.resnets = nn.ModuleList(
                    [ResnetBlock2D(mid_ch, mid_ch, temb_dim),
                     ResnetBlock2D(mid_ch, mid_ch, temb_dim)])
                self.temp_convs = nn.ModuleList(
                    [TemporalConvLayer(mid_ch), TemporalConvLayer(mid_ch)])
                self.attentions = nn.ModuleList(
                    [Transformer2DModel(mid_heads, mid_head_dim, mid_ch,
                                        cfg.cross_attention_dim,
                                        depth=mid_depth)])
                self.temp_attentions = nn.ModuleList(
                    [TransformerTemporalModel(mid_heads, mid_head_dim,
                                              mid_ch)])

        self.mid_block = _Mid()

        up = []
        skip_chs = []  # per-skip channel counts, mirroring the down path
        in_ch = chans[0]
        skip_chs.append(chans[0])
        for level, ch in enumerate(chans):
            for _ in range(cfg.layers_per_block):
                skip_chs.append(ch)
            if level < len(chans) - 1:
                skip_chs.append(ch)
        x_ch = chans[-1]
        for rev, ch in enumerate(reversed(chans)):
            level = len(chans) - 1 - rev
            heads, head_dim = cfg.heads_for(ch, level)
            pairs = []
            for _ in range(cfg.layers_per_block + 1):
                pairs.append((x_ch + skip_chs.pop(), ch))
                x_ch = ch
            up.append(_Block3D(
                pairs, temb_dim, heads, head_dim, cfg.cross_attention_dim,
                cfg.transformer_depth[level],
                "up" if level > 0 else None))
        self.up_blocks = nn.ModuleList(up)

        self.conv_norm_out = nn.GroupNorm(_groups(chans[0]), chans[0],
                                          eps=1e-5)
        self.conv_out = nn.Conv2d(chans[0], cfg.out_channels, 3, padding=1)

    def forward(self, sample, timesteps, context):
        # sample (B, C, F, H, W); context (B, S, D)
        b, _, f, _, _ = sample.shape
        temb = self.time_embedding(
            sinusoidal(timesteps, self.cfg.block_out_channels[0]))
        temb_f = temb.repeat_interleave(f, dim=0)
        ctx_f = context.repeat_interleave(f, dim=0)

        x = sample.permute(0, 2, 1, 3, 4).reshape(
            b * f, *sample.shape[1:2], *sample.shape[3:])
        x = self.conv_in(x)
        x = self.transformer_in(x, f)
        skips = [x]
        for block in self.down_blocks:
            for j, (resnet, tconv) in enumerate(
                    zip(block.resnets, block.temp_convs)):
                x = tconv(resnet(x, temb_f), f)
                if block.attentions is not None:
                    x = block.attentions[j](x, ctx_f)
                    x = block.temp_attentions[j](x, f)
                skips.append(x)
            if hasattr(block, "downsamplers"):
                x = block.downsamplers[0](x)
                skips.append(x)

        m = self.mid_block
        x = m.temp_convs[0](m.resnets[0](x, temb_f), f)
        x = m.attentions[0](x, ctx_f)
        x = m.temp_attentions[0](x, f)
        x = m.temp_convs[1](m.resnets[1](x, temb_f), f)

        for block in self.up_blocks:
            for j, (resnet, tconv) in enumerate(
                    zip(block.resnets, block.temp_convs)):
                x = torch.cat([x, skips.pop()], dim=1)
                x = tconv(resnet(x, temb_f), f)
                if block.attentions is not None:
                    x = block.attentions[j](x, ctx_f)
                    x = block.temp_attentions[j](x, f)
            if hasattr(block, "upsamplers"):
                x = block.upsamplers[0](x)

        x = self.conv_out(F.silu(self.conv_norm_out(x)))
        return x.reshape(b, f, *x.shape[1:]).permute(0, 2, 1, 3, 4)


# ------------------------------------------------------ SVD (spatio-temporal)


class _AlphaBlender(nn.Module):
    def __init__(self):
        super().__init__()
        self.mix_factor = nn.Parameter(torch.tensor([0.5]))


class TemporalResnetBlock(nn.Module):
    def __init__(self, dim, temb_dim, eps):
        super().__init__()
        self.norm1 = nn.GroupNorm(_groups(dim), dim, eps=eps)
        self.conv1 = nn.Conv3d(dim, dim, (3, 1, 1), padding=(1, 0, 0))
        if temb_dim is not None:
            self.time_emb_proj = nn.Linear(temb_dim, dim)
        self.norm2 = nn.GroupNorm(_groups(dim), dim, eps=eps)
        self.conv2 = nn.Conv3d(dim, dim, (3, 1, 1), padding=(1, 0, 0))

    def forward(self, x, temb_bf=None):
        # x (B, C, F, H, W); temb_bf (B, F, D)
        h = self.conv1(F.silu(self.norm1(x)))
        if temb_bf is not None:
            t = self.time_emb_proj(F.silu(temb_bf))      # (B, F, C)
            h = h + t.permute(0, 2, 1)[:, :, :, None, None]
        h = self.conv2(F.silu(self.norm2(h)))
        return x + h


class SpatioTemporalResBlock(nn.Module):
    def __init__(self, in_ch, out_ch, temb_dim, eps):
        super().__init__()
        self.spatial_res_block = ResnetBlock2D(in_ch, out_ch, temb_dim, eps)
        self.temporal_res_block = TemporalResnetBlock(out_ch, temb_dim, eps)
        self.time_mixer = _AlphaBlender()

    def forward(self, x, temb_f, num_frames):
        s = self.spatial_res_block(x, temb_f)
        bf, c, hh, ww = s.shape
        b = bf // num_frames
        s5 = s.reshape(b, num_frames, c, hh, ww).permute(0, 2, 1, 3, 4)
        temb_bf = temb_f.reshape(b, num_frames, -1)
        t5 = self.temporal_res_block(s5, temb_bf)
        # non-switched AlphaBlender — the SVD UNet direction
        # (switch_spatial_to_temporal_mix is a temporal-VAE-decoder-only
        # option in diffusers)
        a = torch.sigmoid(self.time_mixer.mix_factor)
        out = a * s5 + (1.0 - a) * t5
        return out.permute(0, 2, 1, 3, 4).reshape(bf, c, hh, ww)


class TemporalBasicTransformerBlock(nn.Module):
    def __init__(self, dim, heads, head_dim, cross_dim):
        super().__init__()
        self.norm_in = nn.LayerNorm(dim)
        self.ff_in = FeedForward(dim)
        self.norm1 = nn.LayerNorm(dim)
        self.attn1 = Attention(dim, heads, head_dim)
        self.norm2 = nn.LayerNorm(dim)
        self.attn2 = Attention(dim, heads, head_dim, cross_dim)
        self.norm3 = nn.LayerNorm(dim)
        self.ff = FeedForward(dim)

    def forward(self, x, num_frames, context):
        # x (B*F, S, C); context (B*S, S_ctx, D)
        bf, s, c = x.shape
        b = bf // num_frames
        h = x.reshape(b, num_frames, s, c).permute(0, 2, 1, 3)
        h = h.reshape(b * s, num_frames, c)
        residual = h
        h = self.ff_in(self.norm_in(h)) + residual
        h = self.attn1(self.norm1(h)) + h
        h = self.attn2(self.norm2(h), context) + h
        h = self.ff(self.norm3(h)) + h
        h = h.reshape(b, s, num_frames, c).permute(0, 2, 1, 3)
        return h.reshape(bf, s, c)


class TransformerSpatioTemporalModel(nn.Module):
    def __init__(self, heads, head_dim, in_ch, cross_dim, depth=1):
        super().__init__()
        inner = heads * head_dim
        self.in_ch = in_ch
        self.norm = nn.GroupNorm(_groups(in_ch), in_ch, eps=1e-6)
        self.proj_in = nn.Linear(in_ch, inner)
        self.transformer_blocks = nn.ModuleList(
            [BasicTransformerBlock(inner, heads, head_dim, cross_dim)
             for _ in range(depth)])
        self.temporal_transformer_blocks = nn.ModuleList(
            [TemporalBasicTransformerBlock(inner, heads, head_dim,
                                           cross_dim)
             for _ in range(depth)])
        self.time_pos_embed = TimestepEmbedding(in_ch, in_ch * 4, in_ch)
        self.time_mixer = _AlphaBlender()
        self.proj_out = nn.Linear(inner, in_ch)

    def forward(self, x, context, num_frames):
        # x (B*F, C, H, W); context (B*F, S_ctx, D)
        bf, c, hh, ww = x.shape
        b = bf // num_frames
        time_context = context.reshape(
            b, num_frames, -1, context.shape[-1])[:, 0]
        time_context = time_context[:, None].expand(
            b, hh * ww, -1, context.shape[-1])
        time_context = time_context.reshape(
            b * hh * ww, -1, context.shape[-1])

        residual = x
        h = self.norm(x).permute(0, 2, 3, 1).reshape(bf, hh * ww, c)
        h = self.proj_in(h)

        frame_ids = torch.arange(num_frames).repeat(b)
        femb = self.time_pos_embed(sinusoidal(frame_ids, self.in_ch))
        femb = femb[:, None]

        a = torch.sigmoid(self.time_mixer.mix_factor)
        for block, tblock in zip(self.transformer_blocks,
                                 self.temporal_transformer_blocks):
            s = block(h, context)
            t = tblock(s + femb, num_frames, time_context)
            h = a * s + (1.0 - a) * t
        h = self.proj_out(h)
        h = h.reshape(bf, hh, ww, c).permute(0, 3, 1, 2)
        return h + residual


class _BlockST(nn.Module):
    def __init__(self, chans, temb_dim, heads, head_dim, cross_dim,
                 depth, sampler=None):
        super().__init__()
        eps = 1e-6 if depth > 0 else 1e-5
        self.resnets = nn.ModuleList(
            [SpatioTemporalResBlock(i, o, temb_dim, eps) for i, o in chans])
        if depth > 0:
            self.attentions = nn.ModuleList(
                [TransformerSpatioTemporalModel(heads, head_dim, o,
                                                cross_dim, depth)
                 for _, o in chans])
        else:
            self.attentions = None
        if sampler == "down":
            self.downsamplers = nn.ModuleList([Downsample2D(chans[-1][1])])
        elif sampler == "up":
            self.upsamplers = nn.ModuleList([Upsample2D(chans[-1][1])])


class UNetSpatioTemporalRef(nn.Module):
    """diffusers UNetSpatioTemporalConditionModel at a UNetConfig."""

    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        chans = list(cfg.block_out_channels)
        temb_dim = chans[0] * 4
        self.conv_in = nn.Conv2d(cfg.sample_channels, chans[0], 3,
                                 padding=1)
        self.time_embedding = TimestepEmbedding(chans[0], temb_dim)
        self.add_embedding = TimestepEmbedding(
            3 * cfg.addition_embed_dim, temb_dim)

        down, in_ch = [], chans[0]
        for level, ch in enumerate(chans):
            heads, head_dim = cfg.heads_for(ch, level)
            pairs = []
            for _ in range(cfg.layers_per_block):
                pairs.append((in_ch, ch))
                in_ch = ch
            down.append(_BlockST(
                pairs, temb_dim, heads, head_dim, cfg.cross_attention_dim,
                cfg.transformer_depth[level],
                "down" if level < len(chans) - 1 else None))
        self.down_blocks = nn.ModuleList(down)

        mid_ch = chans[-1]
        mid_heads, mid_head_dim = cfg.heads_for(mid_ch, len(chans) - 1)
        mid_depth = max(cfg.transformer_depth) or 1

        class _Mid(nn.Module):
            def __init__(self):
                super().__init__()
                self.resnets = nn.ModuleList(
                    [SpatioTemporalResBlock(mid_ch, mid_ch, temb_dim, 1e-5),
                     SpatioTemporalResBlock(mid_ch, mid_ch, temb_dim,
                                            1e-5)])
                self.attentions = nn.ModuleList(
                    [TransformerSpatioTemporalModel(
                        mid_heads, mid_head_dim, mid_ch,
                        cfg.cross_attention_dim, mid_depth)])

        self.mid_block = _Mid()

        up = []
        skip_chs = [chans[0]]
        for level, ch in enumerate(chans):
            for _ in range(cfg.layers_per_block):
                skip_chs.append(ch)
            if level < len(chans) - 1:
                skip_chs.append(ch)
        x_ch = chans[-1]
        for rev, ch in enumerate(reversed(chans)):
            level = len(chans) - 1 - rev
            heads, head_dim = cfg.heads_for(ch, level)
            pairs = []
            for _ in range(cfg.layers_per_block + 1):
                pairs.append((x_ch + skip_chs.pop(), ch))
                x_ch = ch
            up.append(_BlockST(
                pairs, temb_dim, heads, head_dim, cfg.cross_attention_dim,
                cfg.transformer_depth[level],
                "up" if level > 0 else None))
        self.up_blocks = nn.ModuleList(up)

        self.conv_norm_out = nn.GroupNorm(_groups(chans[0]), chans[0],
                                          eps=1e-5)
        self.conv_out = nn.Conv2d(chans[0], cfg.out_channels, 3, padding=1)

    def forward(self, sample, timesteps, context, added_ids):
        # sample (B, F, C, H, W); context (B, S, D); added_ids (B, 3)
        b, f = sample.shape[:2]
        temb = self.time_embedding(
            sinusoidal(timesteps, self.cfg.block_out_channels[0]))
        ids_emb = sinusoidal(added_ids.flatten(),
                             self.cfg.addition_embed_dim).reshape(b, -1)
        temb = temb + self.add_embedding(ids_emb)
        temb_f = temb.repeat_interleave(f, dim=0)
        ctx_f = context.repeat_interleave(f, dim=0)

        x = sample.reshape(b * f, *sample.shape[2:])
        x = self.conv_in(x)
        skips = [x]
        for block in self.down_blocks:
            for j, resnet in enumerate(block.resnets):
                x = resnet(x, temb_f, f)
                if block.attentions is not None:
                    x = block.attentions[j](x, ctx_f, f)
                skips.append(x)
            if hasattr(block, "downsamplers"):
                x = block.downsamplers[0](x)
                skips.append(x)

        m = self.mid_block
        x = m.resnets[0](x, temb_f, f)
        x = m.attentions[0](x, ctx_f, f)
        x = m.resnets[1](x, temb_f, f)

        for block in self.up_blocks:
            for j, resnet in enumerate(block.resnets):
                x = torch.cat([x, skips.pop()], dim=1)
                x = resnet(x, temb_f, f)
                if block.attentions is not None:
                    x = block.attentions[j](x, ctx_f, f)
            if hasattr(block, "upsamplers"):
                x = block.upsamplers[0](x)

        x = self.conv_out(F.silu(self.conv_norm_out(x)))
        return x.reshape(b, f, *x.shape[1:])


# --------------------------------------- SVD temporal VAE decoder


class VaeResnetRef(nn.Module):
    """temb-free ResnetBlock2D (eps 1e-6), the VAE spatial resnet."""

    def __init__(self, in_ch, out_ch):
        super().__init__()
        self.norm1 = nn.GroupNorm(_groups(in_ch), in_ch, eps=1e-6)
        self.conv1 = nn.Conv2d(in_ch, out_ch, 3, padding=1)
        self.norm2 = nn.GroupNorm(_groups(out_ch), out_ch, eps=1e-6)
        self.conv2 = nn.Conv2d(out_ch, out_ch, 3, padding=1)
        self.conv_shortcut = (nn.Conv2d(in_ch, out_ch, 1)
                              if in_ch != out_ch else None)

    def forward(self, x):
        h = self.conv1(F.silu(self.norm1(x)))
        h = self.conv2(F.silu(self.norm2(h)))
        if self.conv_shortcut is not None:
            x = self.conv_shortcut(x)
        return x + h


class VaeSTBlockRef(nn.Module):
    """TemporalDecoder's SpatioTemporalResBlock: temb-free, spatial eps
    1e-6 / temporal 1e-5, merge_strategy='learned' WITH
    switch_spatial_to_temporal_mix -> out = (1-a)*spatial + a*temporal,
    mix_factor initialized at 0."""

    def __init__(self, in_ch, out_ch):
        super().__init__()
        self.spatial_res_block = VaeResnetRef(in_ch, out_ch)
        self.temporal_res_block = TemporalResnetBlock(out_ch, None, 1e-5)
        self.time_mixer = _AlphaBlender()

    def forward(self, x, num_frames):
        s = self.spatial_res_block(x)
        bf, c, hh, ww = s.shape
        b = bf // num_frames
        s5 = s.reshape(b, num_frames, c, hh, ww).permute(0, 2, 1, 3, 4)
        t5 = self.temporal_res_block(s5)
        a = torch.sigmoid(self.time_mixer.mix_factor)
        out = (1.0 - a) * s5 + a * t5
        return out.permute(0, 2, 1, 3, 4).reshape(bf, c, hh, ww)


class VaeMidAttentionRef(nn.Module):
    """diffusers Attention as the VAE mid uses it: group_norm, biased
    qkv, residual, one head at the full channel width."""

    def __init__(self, dim):
        super().__init__()
        self.group_norm = nn.GroupNorm(_groups(dim), dim, eps=1e-6)
        self.to_q = nn.Linear(dim, dim)
        self.to_k = nn.Linear(dim, dim)
        self.to_v = nn.Linear(dim, dim)
        self.to_out = nn.ModuleList([nn.Linear(dim, dim), nn.Dropout(0.0)])

    def forward(self, x):
        b, c, hh, ww = x.shape
        residual = x
        h = self.group_norm(x).permute(0, 2, 3, 1).reshape(b, hh * ww, c)
        q, k, v = self.to_q(h), self.to_k(h), self.to_v(h)
        attn = (q @ k.transpose(1, 2)) / math.sqrt(c)
        h = attn.softmax(dim=-1) @ v
        h = self.to_out[1](self.to_out[0](h))
        return h.reshape(b, hh, ww, c).permute(0, 3, 1, 2) + residual


class TemporalDecoderRef(nn.Module):
    """diffusers TemporalDecoder (the SVD snapshot's VAE decoder)."""

    def __init__(self, cfg):
        super().__init__()
        chans = list(cfg.block_out_channels)
        self.conv_in = nn.Conv2d(cfg.latent_channels, chans[-1], 3,
                                 padding=1)

        class _Mid(nn.Module):
            def __init__(self):
                super().__init__()
                self.resnets = nn.ModuleList(
                    [VaeSTBlockRef(chans[-1], chans[-1])
                     for _ in range(cfg.layers_per_block)])
                self.attentions = nn.ModuleList(
                    [VaeMidAttentionRef(chans[-1])])

        self.mid_block = _Mid()
        up = []
        x_ch = chans[-1]
        for i, ch in enumerate(reversed(chans)):
            resnets = nn.ModuleList(
                [VaeSTBlockRef(x_ch if j == 0 else ch, ch)
                 for j in range(cfg.layers_per_block + 1)])

            class _Block(nn.Module):
                pass

            block = _Block()
            block.resnets = resnets
            if i < len(chans) - 1:          # add_upsample on all but last
                block.upsamplers = nn.ModuleList([Upsample2D(ch)])
            up.append(block)
            x_ch = ch
        self.up_blocks = nn.ModuleList(up)
        self.conv_norm_out = nn.GroupNorm(_groups(chans[0]), chans[0],
                                          eps=1e-6)
        self.conv_out = nn.Conv2d(chans[0], cfg.in_channels, 3, padding=1)
        self.time_conv_out = nn.Conv3d(cfg.in_channels, cfg.in_channels,
                                       (3, 1, 1), padding=(1, 0, 0))

    def forward(self, z, num_frames):
        # z (B, F, C, H, W) unscaled latents -> (B, F, 3, H*8, W*8)
        b, f = z.shape[:2]
        x = self.conv_in(z.reshape(b * f, *z.shape[2:]))
        m = self.mid_block
        x = m.resnets[0](x, f)
        x = m.attentions[0](x)
        x = m.resnets[1](x, f)
        for block in self.up_blocks:
            for resnet in block.resnets:
                x = resnet(x, f)
            if hasattr(block, "upsamplers"):
                x = block.upsamplers[0](x)
        x = self.conv_out(F.silu(self.conv_norm_out(x)))
        c, hh, ww = x.shape[1:]
        x5 = x.reshape(b, f, c, hh, ww).permute(0, 2, 1, 3, 4)
        x5 = self.time_conv_out(x5)
        return x5.permute(0, 2, 1, 3, 4)


def randomize_(model: nn.Module, seed: int, scale: float = 0.15) -> None:
    """Replace every parameter (including the published zero inits and
    norm affines) with seeded random values so conversion parity is
    meaningful for all leaves."""
    gen = torch.Generator().manual_seed(seed)
    with torch.no_grad():
        for p in model.parameters():
            p.copy_(torch.randn(p.shape, generator=gen) * scale)
