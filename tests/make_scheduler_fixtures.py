"""Generate tests/fixtures/scheduler_golden.npz from the numpy oracle.

Run from the repo root:  python tests/make_scheduler_fixtures.py

Each fixture is a full per-step trajectory in k-diffusion coordinates
(x = x0 + sigma * eps), the framework's native space, converted from the
oracle's VP coordinates where applicable (x_kd = x_vp * sqrt(1 + sigma^2)).
See scheduler_oracle.py for why these are oracle- rather than
diffusers-generated.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

from scheduler_oracle import (
    OracleDDIM,
    OracleDPMpp2M,
    OracleEuler,
    OracleEulerAncestral,
    make_karras_schedule,
    mock_eps,
    train_tables,
)

SHAPE = (1, 4, 4, 4)
STEPS = (8, 20)


def vp_to_kd(x_vp: np.ndarray, sigma_kd: float) -> np.ndarray:
    return x_vp * np.sqrt(1.0 + sigma_kd ** 2)


def kd_to_vp(x_kd: np.ndarray, sigma_kd: float) -> np.ndarray:
    return x_kd / np.sqrt(1.0 + sigma_kd ** 2)


def run_dpmpp(n: int, x_kd0: np.ndarray) -> dict[str, np.ndarray]:
    o = OracleDPMpp2M(n)
    traj = []
    x_vp = kd_to_vp(x_kd0, float(o.sigmas[0]))
    for i in range(n):
        s = float(o.sigmas[i])
        eps = mock_eps(kd_to_vp(vp_to_kd(x_vp, s), s), float(o.timesteps[i]))
        x_vp = o.step(eps, x_vp)
        s_next = float(o.sigmas[i + 1])
        traj.append(vp_to_kd(x_vp, s_next) if s_next > 0 else x_vp)
    return {"sigmas": o.sigmas, "timesteps": o.timesteps,
            "traj": np.stack(traj)}


def run_ddim(n: int, x_kd0: np.ndarray) -> dict[str, np.ndarray]:
    o = OracleDDIM(n)
    abar, kd_sigmas = train_tables()
    sig0 = float(kd_sigmas[o.timesteps[0]])
    traj = []
    x_vp = kd_to_vp(x_kd0, sig0)
    for i in range(n):
        t = int(o.timesteps[i])
        eps = mock_eps(x_vp, float(t))
        x_vp = o.step(eps, x_vp)
        prev_t = t - 1000 // n
        s_next = float(kd_sigmas[prev_t]) if prev_t >= 0 else 0.0
        traj.append(vp_to_kd(x_vp, s_next) if s_next > 0 else x_vp)
    return {"timesteps": o.timesteps.astype(np.float64),
            "sigma0": np.float64(sig0), "traj": np.stack(traj)}


def run_euler(n: int, x_kd0: np.ndarray) -> dict[str, np.ndarray]:
    o = OracleEuler(n)
    traj = []
    x = x_kd0.copy()
    for i in range(n):
        s = float(o.sigmas[i])
        eps = mock_eps(x / np.sqrt(s ** 2 + 1.0), float(o.timesteps[i]))
        x = o.step(eps, x)
        traj.append(x.copy())
    return {"sigmas": o.sigmas, "timesteps": o.timesteps,
            "traj": np.stack(traj)}


def run_euler_ancestral(n: int, x_kd0: np.ndarray,
                        noises: np.ndarray) -> dict[str, np.ndarray]:
    o = OracleEulerAncestral(n)
    traj = []
    x = x_kd0.copy()
    for i in range(n):
        s = float(o.sigmas[i])
        eps = mock_eps(x / np.sqrt(s ** 2 + 1.0), float(o.timesteps[i]))
        x = o.step(eps, x, noises[i])
        traj.append(x.copy())
    return {"sigmas": o.sigmas, "timesteps": o.timesteps,
            "traj": np.stack(traj)}


def main() -> None:
    rng = np.random.default_rng(42)
    out: dict[str, np.ndarray] = {}
    for n in STEPS:
        sig, _ = make_karras_schedule(n)
        unit = rng.standard_normal(SHAPE)
        x0_karras = unit * sig[0]
        out[f"init_unit_{n}"] = unit
        noises = rng.standard_normal((n,) + SHAPE)
        out[f"noises_{n}"] = noises

        for key, res in (
            (f"dpmpp_2m_{n}", run_dpmpp(n, x0_karras)),
            (f"euler_{n}", run_euler(n, x0_karras)),
        ):
            for field, arr in res.items():
                out[f"{key}/{field}"] = arr

        # non-karras grids start at their own sigma0
        o_ea = OracleEulerAncestral(n)
        x0_ea = unit * o_ea.sigmas[0]
        for field, arr in run_euler_ancestral(n, x0_ea, noises).items():
            out[f"euler_ancestral_{n}/{field}"] = arr

        abar, kd_sigmas = train_tables()
        ddim = OracleDDIM(n)
        x0_ddim = unit * float(kd_sigmas[ddim.timesteps[0]])
        for field, arr in run_ddim(n, x0_ddim).items():
            out[f"ddim_{n}/{field}"] = arr

    dest = Path(__file__).parent / "fixtures" / "scheduler_golden.npz"
    dest.parent.mkdir(exist_ok=True)
    np.savez_compressed(dest, **out)
    print(f"wrote {dest} ({len(out)} arrays)")


if __name__ == "__main__":
    main()
