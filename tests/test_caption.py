"""Captioning (img2txt) tests: tiny hermetic pipeline + torch fidelity.

Covers the reference's swarm/captioning/caption_image.py behaviors —
conditional vs unconditional captioning and the VQA split (:21-26) — on the
native BLIP stack (models/blip.py, pipelines/caption.py), plus numerical
parity of the checkpoint converter against HF's torch BLIP on tiny widths.
"""

from __future__ import annotations

import numpy as np
import pytest

from chiaswarm_tpu.models.tokenizer import WordPieceTokenizer
from chiaswarm_tpu.pipelines.caption import (
    CaptionComponents,
    CaptionPipeline,
    _tiny_vocab,
)


@pytest.fixture(scope="module")
def tiny_pipe():
    return CaptionPipeline(CaptionComponents.random("blip_tiny", seed=0),
                           max_new_tokens=8)


def _img(seed=0, h=48, w=64):
    return (np.random.RandomState(seed).rand(h, w, 3) * 255).astype(np.uint8)


def test_caption_runs_and_is_deterministic(tiny_pipe):
    a = tiny_pipe(_img())
    b = tiny_pipe(_img())
    assert isinstance(a, str) and a
    assert a == b


def test_vqa_differs_from_caption(tiny_pipe):
    cap = tiny_pipe(_img())
    ans = tiny_pipe(_img(), "what color is the sky", vqa=True)
    assert isinstance(ans, str) and ans
    # question tower conditions the decode; with random weights the
    # trajectories should diverge
    assert ans != cap


def test_vqa_requires_question_tower():
    c = CaptionComponents.random("blip_tiny", seed=0, vqa=False)
    pipe = CaptionPipeline(c)
    with pytest.raises(ValueError, match="question tower"):
        pipe(_img(), "what is this", vqa=True)


def test_padded_prompt_bucket_matches_exact_decode():
    """A conditioned prefix padded to PROMPT_BUCKET (actual_len traced)
    must decode the same tokens as the exact-length prefill."""
    import jax.numpy as jnp

    from chiaswarm_tpu.models.blip import generate_text

    c = CaptionComponents.random("blip_tiny", seed=1, vqa=False)
    enc = jnp.asarray(
        np.random.RandomState(2).randn(1, 17, 32).astype(np.float32))
    prefix = [c.config.text.bos_token_id, 7, 11]
    exact = generate_text(c.decoder, c.params["decoder"],
                          jnp.asarray([prefix], jnp.int32), enc, None,
                          prompt_len=3, max_new=6)
    padded = prefix + [c.tokenizer.pad_id] * (17 - len(prefix))
    bucketed = generate_text(c.decoder, c.params["decoder"],
                             jnp.asarray([padded], jnp.int32), enc, None,
                             prompt_len=17, max_new=6,
                             actual_len=jnp.int32(3))
    assert np.array_equal(np.asarray(exact), np.asarray(bucketed))


def test_conditional_caption_prefixes_prompt():
    c = CaptionComponents.random("blip_tiny", seed=0, vqa=False)
    pipe = CaptionPipeline(c, max_new_tokens=6)
    out = pipe(_img(), "tok5 tok7")
    assert out.startswith("tok5 tok7")


def test_wordpiece_tokenizer_roundtrip():
    vocab = dict(_tiny_vocab())
    base = len(vocab)
    vocab.update({"hello": base, "wor": base + 1, "##ld": base + 2})
    tok = WordPieceTokenizer(vocab, max_length=16)
    ids = tok.encode("hello world")
    assert ids[0] == tok.cls_id and tok.sep_id in ids
    assert len(ids) == 16
    assert tok.decode(ids) == "hello world"
    # unknown word -> [UNK], never crashes
    assert tok._wordpiece("zzqq") == [tok.unk_id]


# ------------------------------------------------------ torch fidelity

def _hf_tiny():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    from transformers import BlipConfig as HFBlipConfig
    from transformers import BlipForConditionalGeneration

    cfg = HFBlipConfig.from_text_vision_configs(
        text_config=transformers.BlipTextConfig(
            vocab_size=1000, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            max_position_embeddings=64, encoder_hidden_size=32,
            is_decoder=True, bos_token_id=998, sep_token_id=999,
            eos_token_id=999, pad_token_id=0,
            attention_probs_dropout_prob=0.0, hidden_dropout_prob=0.0),
        vision_config=transformers.BlipVisionConfig(
            hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, image_size=32, patch_size=8,
            attention_dropout=0.0),
    )
    torch.manual_seed(0)
    model = BlipForConditionalGeneration(cfg).eval()
    return torch, model


def test_blip_conversion_matches_torch():
    torch, hf = _hf_tiny()
    import jax.numpy as jnp

    from chiaswarm_tpu.convert.torch_to_flax import (
        convert_blip_text,
        convert_blip_vision,
    )
    from chiaswarm_tpu.models.blip import (
        BLIP_TINY,
        BlipTextModel,
        BlipVisionEncoder,
    )

    state = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    vparams = convert_blip_vision(state)
    tparams = convert_blip_text(state, "text_decoder.")

    rng = np.random.RandomState(1)
    pixels = rng.randn(1, 32, 32, 3).astype(np.float32)
    with torch.no_grad():
        tv = hf.vision_model(
            torch.from_numpy(pixels.transpose(0, 3, 1, 2))
        ).last_hidden_state.numpy()
    fv = np.asarray(
        BlipVisionEncoder(BLIP_TINY.vision).apply(vparams,
                                                  jnp.asarray(pixels)))
    np.testing.assert_allclose(fv, tv, atol=2e-4, rtol=2e-3)

    ids = np.array([[998, 5, 17, 42]], np.int32)
    with torch.no_grad():
        tl = hf.text_decoder(
            input_ids=torch.from_numpy(ids.astype(np.int64)),
            encoder_hidden_states=torch.from_numpy(tv),
            is_decoder=True,
        ).logits.numpy()
    decoder = BlipTextModel(BLIP_TINY.text)
    cross_kvs = decoder.apply(tparams, jnp.asarray(tv), method="cross_kvs")
    fl, _ = decoder.apply(tparams, jnp.asarray(ids), causal=True,
                          cross_kvs=cross_kvs)
    np.testing.assert_allclose(np.asarray(fl), tl, atol=5e-4, rtol=2e-3)


@pytest.mark.slow
def test_blip_cached_decode_matches_full_forward():
    """The scan-decode KV ring must produce the same logits as a full
    causal forward at every position (prefill+step == one-shot)."""
    import jax.numpy as jnp

    from chiaswarm_tpu.models.blip import (
        BLIP_TINY,
        BlipTextModel,
        generate_text,
        init_text_caches,
    )

    c = CaptionComponents.random("blip_tiny", seed=3, vqa=False)
    decoder: BlipTextModel = c.decoder
    params = c.params["decoder"]
    enc = jnp.asarray(
        np.random.RandomState(0).randn(1, 17, 32).astype(np.float32))
    cross_kvs = decoder.apply(params, enc, method="cross_kvs")

    # greedy tokens from the cached scan path
    dec_in = jnp.asarray([[BLIP_TINY.text.bos_token_id]], jnp.int32)
    toks = np.asarray(generate_text(decoder, params, dec_in, enc, None,
                                    prompt_len=1, max_new=5))[0]

    # replay: full (uncached) causal forward over [bos] + toks must pick
    # the same argmax at each step
    seq = [BLIP_TINY.text.bos_token_id]
    for t in toks:
        logits, _ = decoder.apply(params, jnp.asarray([seq], jnp.int32),
                                  causal=True, cross_kvs=cross_kvs)
        nxt = int(np.argmax(np.asarray(logits)[0, -1]))
        assert nxt == int(t)
        if nxt == BLIP_TINY.text.sep_token_id:
            break
        seq.append(nxt)


def test_img2txt_end_to_end_dispatch():
    """img2txt routes through format_args -> executor -> caption_callback
    with a resident registry pipeline (swarm worker path equivalence)."""
    import json

    from chiaswarm_tpu.core.chip_pool import ChipPool
    from chiaswarm_tpu.node.executor import synchronous_do_work
    from chiaswarm_tpu.node.registry import ModelRegistry

    registry = ModelRegistry(catalog=[], allow_random=True)
    pool = ChipPool(n_slots=1)
    job = {"id": "cap-1", "workflow": "img2txt", "model_name": "tinyblip",
           "prompt": "", "image": _img()}
    result = synchronous_do_work(job, pool.slots[0], registry)
    cfg = result["pipeline_config"]
    assert "error" not in cfg, cfg
    blob = result["artifacts"]["primary"]
    assert cfg["caption"]
    payload = json.loads(__import__("base64").b64decode(blob["blob"]))
    assert payload["caption"] == cfg["caption"]
