"""NSFW safety checker: threshold head logic + unavailable-checker signal.

Reference behavior covered: diffusers-checker reliance with whole-result
OR-propagation (swarm/diffusion/diffusion_func.py:99-111,
swarm/generator.py:37,76).
"""

import numpy as np

from chiaswarm_tpu.workloads.safety import SafetyChecker, check_images


def _stub_checker(embed_rows: np.ndarray) -> SafetyChecker:
    """SafetyChecker with a fabricated embedding head (no CLIP weights)."""
    checker = SafetyChecker.__new__(SafetyChecker)
    checker.concept_embeds = np.asarray([[1.0, 0.0], [0.0, 1.0]], np.float32)
    checker.concept_thresholds = np.asarray([0.9, 0.9], np.float32)
    checker.special_embeds = np.asarray([[0.7071, 0.7071]], np.float32)
    checker.special_thresholds = np.asarray([0.94], np.float32)
    rows = iter(np.atleast_2d(embed_rows).astype(np.float32))
    checker._jit_embed = lambda pixel_values: np.stack(
        [next(rows) for _ in range(pixel_values.shape[0])])
    return checker


def _images(n):
    return np.zeros((n, 8, 8, 3), np.uint8)


def test_concept_hit_flags_image():
    checker = _stub_checker(np.asarray([[10.0, 0.0]]))  # cos vs concept0 = 1
    assert checker(_images(1)) == [True]


def test_orthogonal_embedding_is_clean():
    checker = _stub_checker(np.asarray([[1.0, -1.0]]))  # cos .707/-0.707 < .9
    assert checker(_images(1)) == [False]


def test_special_care_lowers_threshold():
    # cos vs concept0 ~0.894 (< 0.9), but special-care cos ~0.949
    # (> 0.94) lowers thresholds by 0.01 -> 0.89 -> flagged
    v = np.asarray([[0.9, 0.45]])
    checker = _stub_checker(v)
    assert checker(_images(1)) == [True]
    # same vector without the special-care hit stays clean
    checker2 = _stub_checker(v)
    checker2.special_thresholds = np.asarray([2.0], np.float32)  # never hits
    assert checker2(_images(1)) == [False]


def test_batch_flags_are_per_image():
    checker = _stub_checker(np.asarray([[10.0, 0.0], [1.0, -1.0]]))
    assert checker(_images(2)) == [True, False]


def test_unavailable_checker_is_explicit(tmp_path, monkeypatch):
    monkeypatch.setenv("SWARM_TPU_ROOT", str(tmp_path))
    nsfw, fields = check_images(_images(1), "some/model")
    assert nsfw is False
    assert fields["safety_checker"] == "unavailable"


def test_clip_preprocess_center_crops():
    from chiaswarm_tpu.workloads.safety import _MEAN, _STD, _clip_preprocess

    # wide frame: left half black, right half white; the center crop
    # must cover the middle (mixed), not squash the full width
    frame = np.zeros((100, 400, 3), np.uint8)
    frame[:, 200:] = 255
    out = _clip_preprocess(frame)
    assert out.shape == (224, 224, 3)
    restored = out * _STD + _MEAN
    assert restored[:, :100].mean() < 0.1   # left of crop: black
    assert restored[:, -100:].mean() > 0.9  # right of crop: white


def _tiny_vision_cfg():
    from chiaswarm_tpu.models.clip import VisionConfig

    return VisionConfig(hidden_size=16, intermediate_size=32, num_layers=2,
                        num_heads=2, image_size=28, patch_size=14,
                        projection_dim=8)


def _tiny_checker_state(cfg, threshold: float = 2.0):
    """Torch-layout checker state dict from a tiny flax init -> (state,
    flax params, vision module). ``threshold`` sets the concept head:
    2.0 never flags, -2.0 flags everything (cosines live in [-1, 1])."""
    import jax

    from chiaswarm_tpu.models.clip import ClipVisionEncoder

    vision = ClipVisionEncoder(cfg)
    params = vision.init(
        jax.random.PRNGKey(0),
        np.zeros((1, cfg.image_size, cfg.image_size, 3), np.float32))

    p = params["params"]
    rng = np.random.default_rng(0)
    d = cfg.projection_dim
    state = {
        "vision_model.vision_model.embeddings.class_embedding":
            np.asarray(p["class_embedding"]),
        "vision_model.vision_model.embeddings.patch_embedding.weight":
            np.asarray(p["patch_embedding"]["kernel"]).transpose(3, 2, 0, 1),
        "vision_model.vision_model.embeddings.position_embedding.weight":
            np.asarray(p["position_embedding"]["embedding"]),
        "vision_model.vision_model.pre_layrnorm.weight":
            np.asarray(p["pre_layrnorm"]["scale"]),
        "vision_model.vision_model.pre_layrnorm.bias":
            np.asarray(p["pre_layrnorm"]["bias"]),
        "vision_model.vision_model.post_layernorm.weight":
            np.asarray(p["post_layernorm"]["scale"]),
        "vision_model.vision_model.post_layernorm.bias":
            np.asarray(p["post_layernorm"]["bias"]),
        "visual_projection.weight":
            np.asarray(p["visual_projection"]["kernel"]).T,
        "concept_embeds": rng.normal(size=(3, d)).astype(np.float32),
        "concept_embeds_weights":
            np.full((3,), threshold, np.float32),
        "special_care_embeds": rng.normal(size=(1, d)).astype(np.float32),
        "special_care_embeds_weights": np.full((1,), 2.0, np.float32),
    }
    for i in range(cfg.num_layers):
        lp = p[f"layers_{i}"]
        pre = f"vision_model.vision_model.encoder.layers.{i}"
        for proj in ("q_proj", "k_proj", "v_proj", "out_proj"):
            state[f"{pre}.self_attn.{proj}.weight"] = \
                np.asarray(lp["self_attn"][proj]["kernel"]).T
            state[f"{pre}.self_attn.{proj}.bias"] = \
                np.asarray(lp["self_attn"][proj]["bias"])
        for ln in ("layer_norm1", "layer_norm2"):
            state[f"{pre}.{ln}.weight"] = np.asarray(lp[ln]["scale"])
            state[f"{pre}.{ln}.bias"] = np.asarray(lp[ln]["bias"])
        for fc in ("fc1", "fc2"):
            state[f"{pre}.mlp.{fc}.weight"] = np.asarray(lp[fc]["kernel"]).T
            state[f"{pre}.mlp.{fc}.bias"] = np.asarray(lp[fc]["bias"])
    return state, params, vision


def write_checker_fixture(target_dir, threshold: float = 2.0) -> None:
    """Materialize a tiny converted-format checker snapshot: safetensors
    weights + the config.json SafetyChecker reads its VisionConfig from."""
    import json

    from safetensors.numpy import save_file

    cfg = _tiny_vision_cfg()
    state, _, _ = _tiny_checker_state(cfg, threshold=threshold)
    target_dir.mkdir(parents=True, exist_ok=True)
    save_file(state, str(target_dir / "model.safetensors"))
    (target_dir / "config.json").write_text(json.dumps({
        "vision_config": {
            "hidden_size": cfg.hidden_size,
            "intermediate_size": cfg.intermediate_size,
            "num_hidden_layers": cfg.num_layers,
            "num_attention_heads": cfg.num_heads,
            "image_size": cfg.image_size,
            "patch_size": cfg.patch_size,
            "projection_dim": cfg.projection_dim,
        }}))


def test_convert_safety_checker_and_real_tower(tmp_path):
    """End-to-end real-code path: fabricate a tiny torch-layout checker
    state dict, convert it, run the native vision tower."""
    from chiaswarm_tpu.convert.torch_to_flax import convert_safety_checker

    cfg = _tiny_vision_cfg()
    state, params, vision = _tiny_checker_state(cfg)
    rng = np.random.default_rng(0)
    converted, buffers = convert_safety_checker(state)
    pixels = rng.normal(size=(2, 28, 28, 3)).astype(np.float32)
    want = vision.apply(params, pixels)
    got = vision.apply(converted, pixels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)

    # real SafetyChecker flow over the converted artifacts: with impossible
    # thresholds nothing flags; with the first concept aligned to an actual
    # embedding, that image flags
    checker = SafetyChecker.__new__(SafetyChecker)
    checker.concept_embeds = buffers["concept_embeds"]
    checker.concept_thresholds = buffers["concept_embeds_weights"]
    checker.special_embeds = buffers["special_care_embeds"]
    checker.special_thresholds = buffers["special_care_embeds_weights"]
    emb = np.asarray(got)

    def fake_vision(pixel_values):
        return emb[: pixel_values.shape[0]]

    checker._jit_embed = fake_vision
    assert checker(_images(2)) == [False, False]
    checker.concept_embeds = emb[:1]
    checker.concept_thresholds = np.asarray([0.99], np.float32)
    assert checker(_images(2))[0] is True


def test_checker_loads_tiny_fixture_from_disk(tmp_path, monkeypatch):
    """SafetyChecker reads its VisionConfig from the snapshot's own
    config.json — a tiny converted fixture loads and flags through the
    same path the production ViT-L checkpoint uses."""
    monkeypatch.setenv("SWARM_TPU_ROOT", str(tmp_path))
    from chiaswarm_tpu.node.registry import model_dir
    from chiaswarm_tpu.workloads import safety

    checker_dir = model_dir("CompVis/stable-diffusion-safety-checker")
    write_checker_fixture(checker_dir, threshold=-2.0)  # flags everything
    monkeypatch.setattr(safety, "_CACHE", {})
    nsfw, fields = check_images(_images(2), "some/model")
    assert nsfw is True
    assert fields["nsfw_flags"] == [True, True]

    # same fixture with never-hit thresholds: clean result, real path
    import shutil

    shutil.rmtree(checker_dir)
    write_checker_fixture(checker_dir, threshold=2.0)
    monkeypatch.setattr(safety, "_CACHE", {})
    nsfw, fields = check_images(_images(1), "some/model")
    assert nsfw is False
    assert fields["nsfw_flags"] == [False]
    assert "safety_checker" not in fields  # NOT the unavailable signal
