"""NSFW safety checker: threshold head logic + unavailable-checker signal.

Reference behavior covered: diffusers-checker reliance with whole-result
OR-propagation (swarm/diffusion/diffusion_func.py:99-111,
swarm/generator.py:37,76).
"""

import numpy as np

from chiaswarm_tpu.workloads.safety import SafetyChecker, check_images


def _stub_checker(embed_rows: np.ndarray) -> SafetyChecker:
    """SafetyChecker with a fabricated embedding head (no CLIP weights)."""
    checker = SafetyChecker.__new__(SafetyChecker)
    checker.concept_embeds = np.asarray([[1.0, 0.0], [0.0, 1.0]], np.float32)
    checker.concept_thresholds = np.asarray([0.9, 0.9], np.float32)
    checker.special_embeds = np.asarray([[0.7071, 0.7071]], np.float32)
    checker.special_thresholds = np.asarray([0.94], np.float32)
    rows = iter(np.atleast_2d(embed_rows).astype(np.float32))
    checker._jit_embed = lambda pixel_values: np.stack(
        [next(rows) for _ in range(pixel_values.shape[0])])
    return checker


def _images(n):
    return np.zeros((n, 8, 8, 3), np.uint8)


def test_concept_hit_flags_image():
    checker = _stub_checker(np.asarray([[10.0, 0.0]]))  # cos vs concept0 = 1
    assert checker(_images(1)) == [True]


def test_orthogonal_embedding_is_clean():
    checker = _stub_checker(np.asarray([[1.0, -1.0]]))  # cos .707/-0.707 < .9
    assert checker(_images(1)) == [False]


def test_special_care_lowers_threshold():
    # cos vs concept0 ~0.894 (< 0.9), but special-care cos ~0.949
    # (> 0.94) lowers thresholds by 0.01 -> 0.89 -> flagged
    v = np.asarray([[0.9, 0.45]])
    checker = _stub_checker(v)
    assert checker(_images(1)) == [True]
    # same vector without the special-care hit stays clean
    checker2 = _stub_checker(v)
    checker2.special_thresholds = np.asarray([2.0], np.float32)  # never hits
    assert checker2(_images(1)) == [False]


def test_batch_flags_are_per_image():
    checker = _stub_checker(np.asarray([[10.0, 0.0], [1.0, -1.0]]))
    assert checker(_images(2)) == [True, False]


def test_unavailable_checker_is_explicit(tmp_path, monkeypatch):
    monkeypatch.setenv("SWARM_TPU_ROOT", str(tmp_path))
    nsfw, fields = check_images(_images(1), "some/model")
    assert nsfw is False
    assert fields["safety_checker"] == "unavailable"
