"""Kernel tests: Pallas flash attention (interpret mode on CPU) vs the
einsum reference — the golden-value strategy SURVEY.md §4 calls for."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chiaswarm_tpu.ops.attention import _xla_attention, attention
from chiaswarm_tpu.ops.flash_attention import flash_attention


@pytest.mark.parametrize(
    "b,l,s,h,d",
    [
        (2, 64, 64, 4, 40),    # SD1.5-style self-attention head_dim 40
        (1, 100, 77, 2, 64),   # cross-attention: text KV of 77 tokens
        (1, 300, 300, 2, 80),  # non-multiple-of-block lengths
        (2, 128, 128, 1, 128), # exact lane-width head dim
    ],
)
def test_flash_matches_einsum(b, l, s, h, d):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (b, l, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, h, d), jnp.float32)
    scale = d ** -0.5
    ref = _xla_attention(q, k, v, scale)
    got = flash_attention(q, k, v, block_q=64, block_kv=64, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_flash_bf16_io():
    kq, kk = jax.random.split(jax.random.PRNGKey(1))
    q = jax.random.normal(kq, (1, 96, 2, 32), jnp.bfloat16)
    kvv = jax.random.normal(kk, (1, 96, 2, 32), jnp.bfloat16)
    out = flash_attention(q, kvv, kvv, block_q=32, block_kv=32,
                          interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = _xla_attention(q.astype(jnp.float32), kvv.astype(jnp.float32),
                         kvv.astype(jnp.float32), 32 ** -0.5)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), rtol=3e-2, atol=3e-2
    )


def test_attention_dispatch_explicit_flash():
    """impl="flash" forces the Pallas kernel even on CPU (interpret)."""
    q = jax.random.normal(jax.random.PRNGKey(2), (1, 64, 2, 16))
    out_flash = attention(q, q, q, impl="flash")
    out_xla = attention(q, q, q, impl="xla")
    np.testing.assert_allclose(np.asarray(out_flash), np.asarray(out_xla),
                               rtol=2e-4, atol=2e-4)


def test_flash_block_autopick_divisibility():
    """The auto block picker (ops/flash_attention.py::_pick_block):
    non-divisible lengths switch to the largest tuned-subdivision block
    that removes the masked padding (the SVD portrait's +4.2%); every
    power-of-two SD/SDXL shape keeps the tuned 2048/1024 blocks
    bit-for-bit; the r2 small-block cliff (256/512) is never selected;
    sub-threshold savings stay on the tuned block."""
    from chiaswarm_tpu.ops.flash_attention import _pick_block

    # tuned shapes unchanged (SDXL 1024px levels, SD 512px levels)
    assert _pick_block(16384, 2048) == 2048
    assert _pick_block(4096, 2048) == 2048
    assert _pick_block(4096, 1024) == 1024
    # SVD portrait levels tile exactly
    assert _pick_block(9216, 2048) == 1536
    assert _pick_block(9216, 1024) == 1024
    assert _pick_block(2304, 2048) == 768
    assert _pick_block(2304, 1024) == 768
    # 256-divisible lengths must NOT fall to the small-block cliff
    assert _pick_block(12544, 2048) == 1280
    # below-threshold saving keeps the tuned block (6% vs 4% padding)
    assert _pick_block(12544, 1024) == 1024
    # short sequences clamp to the 8-padded length as before
    assert _pick_block(77, 2048) == 80
    assert _pick_block(256, 2048) == 256
